"""Interpolation machinery tests (interpolation.cpp capability): spline
reproduction, spline importance sampling, Fourier recurrence, and the
curve shape's ribbon tessellation."""

import numpy as np
import jax.numpy as jnp

from tpu_pbrt.core.interpolation import (
    catmull_rom,
    find_interval,
    fourier,
    integrate_catmull_rom,
    sample_catmull_rom,
)


def test_find_interval():
    xs = jnp.asarray([0.0, 1.0, 2.0, 5.0, 9.0])
    x = jnp.asarray([-1.0, 0.0, 0.5, 1.0, 4.9, 9.0, 20.0])
    out = np.asarray(find_interval(xs, x))
    np.testing.assert_array_equal(out, [0, 0, 0, 1, 2, 3, 3])


def test_catmull_rom_interpolates_nodes_and_smooth():
    xs = np.linspace(0.0, 1.0, 9)
    fs = np.sin(2 * np.pi * xs) + 2.0
    out = np.asarray(catmull_rom(jnp.asarray(xs), jnp.asarray(fs), jnp.asarray(xs)))
    np.testing.assert_allclose(out, fs, atol=1e-5)
    # between nodes the spline tracks the smooth function closely
    xq = np.linspace(0.05, 0.95, 50)
    out = np.asarray(catmull_rom(jnp.asarray(xs), jnp.asarray(fs), jnp.asarray(xq)))
    np.testing.assert_allclose(out, np.sin(2 * np.pi * xq) + 2.0, atol=0.03)


def test_sample_catmull_rom_matches_density():
    """Samples drawn via SampleCatmullRom must be distributed like the
    spline: compare a histogram to the normalized function."""
    xs = np.linspace(0.0, 1.0, 17)
    fs = 0.2 + (xs - 0.3) ** 2  # positive, non-uniform
    cdf, total = integrate_catmull_rom(xs, fs)
    rng = np.random.default_rng(5)
    u = jnp.asarray(rng.uniform(size=200_000), jnp.float32)
    x, fval, pdf = sample_catmull_rom(xs, fs, cdf, u)
    x = np.asarray(x)
    assert (x >= 0).all() and (x <= 1).all()
    hist, edges = np.histogram(x, bins=16, range=(0, 1), density=True)
    centers = 0.5 * (edges[:-1] + edges[1:])
    expect = (0.2 + (centers - 0.3) ** 2) / total
    np.testing.assert_allclose(hist, expect, rtol=0.08)
    # importance-sampling identity: E[f(x)/pdf(x)] = integral of f = total
    est = np.mean((0.2 + (x - 0.3) ** 2) / np.maximum(np.asarray(pdf), 1e-9))
    np.testing.assert_allclose(est, total, rtol=0.05)


def test_fourier_matches_direct_sum():
    rng = np.random.default_rng(7)
    m = 12
    a = jnp.asarray(rng.normal(size=(64, m)), jnp.float32)
    phi = rng.uniform(0, 2 * np.pi, 64)
    out = np.asarray(fourier(a, jnp.asarray(np.cos(phi), jnp.float32), m))
    direct = np.sum(
        np.asarray(a) * np.cos(np.arange(m)[None, :] * phi[:, None]), axis=1
    )
    np.testing.assert_allclose(out, direct, atol=1e-3)


def test_curve_shape_tessellates_and_renders():
    from tests.test_render import render_scene, scene_header

    r = render_scene(
        scene_header("directlighting", spp=4, res=24)
        + '''
WorldBegin
LightSource "distant" "rgb L" [5 5 5] "point from" [0 0 -1] "point to" [0 0 0]
Material "matte" "rgb Kd" [0.8 0.8 0.8]
Shape "curve" "point P" [-1 0 0  -0.3 0.8 0  0.3 -0.8 0  1 0 0] "float width" [0.4]
WorldEnd
'''
    )
    img = r.image
    assert np.isfinite(img).all()
    assert img.mean() > 1e-3, "curve ribbon rendered black"
