"""Distribution-layer tests on the virtual 8-device CPU mesh (SURVEY.md §4:
how multi-node is tested without a cluster). Validates that the shard_map
tile scheduler + psum film merge produces the same image as the
single-device path — the distributed film merge is exact, not approximate,
because work items are partitioned (each sample is computed exactly once,
on exactly one device)."""

import jax
import numpy as np
import pytest

from tpu_pbrt.parallel.mesh import make_mesh
from tpu_pbrt.scenes import compile_api, make_cornell

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh from conftest"
)


def test_mesh_shape():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("tiles",)


def test_sharded_render_matches_single_device():
    api = make_cornell(res=24, spp=8, integrator="path", maxdepth=3)
    scene, integ = compile_api(api)
    r_single = integ.render(scene)

    api2 = make_cornell(res=24, spp=8, integrator="path", maxdepth=3)
    scene2, integ2 = compile_api(api2)
    r_mesh = integ2.render(scene2, mesh=make_mesh(8))

    assert r_mesh.image.shape == r_single.image.shape
    assert r_mesh.image.max() > 0
    # identical sample set, partitioned across devices -> identical film up
    # to float addition order
    assert np.allclose(r_mesh.image, r_single.image, rtol=1e-4, atol=1e-5)
    assert r_mesh.rays_traced == r_single.rays_traced


def test_sharded_render_four_devices():
    api = make_cornell(res=16, spp=4, integrator="directlighting", maxdepth=2)
    scene, integ = compile_api(api)
    r = integ.render(scene, mesh=make_mesh(4))
    assert r.image.max() > 0


class TestFaultInjection:
    """Worker-failure handling (SURVEY.md §2e): dropped chunk dispatches
    are re-dispatched; a state-poisoning failure rolls back to the last
    checkpoint. Both recoveries must be BIT-identical to the undisturbed
    render (chunks are idempotent pure functions of the work range).

    ISSUE 5 migrated the injections from the old per-integrator
    `_fault_hook` monkeypatch onto the first-class chaos registry
    (tpu_pbrt/chaos) — the same seam `python -m tpu_pbrt.chaos`
    exercises matrix-wide."""

    def _scene(self):
        api = make_cornell(res=16, spp=8, integrator="path", maxdepth=2)
        return compile_api(api)

    def test_redispatch_bit_identical(self):
        from tpu_pbrt.chaos import CHAOS

        scene, integ = self._scene()
        # small chunks so the render has several dispatches
        import os

        from tpu_pbrt import config

        os.environ["TPU_PBRT_CHUNK"] = str(16 * 16 * 2)
        os.environ["TPU_PBRT_RETRY_BACKOFF"] = "0.01"
        config.reload()
        try:
            ref = integ.render(scene)

            scene2, integ2 = self._scene()
            CHAOS.install("dispatch:fail@chunk=1&attempt=0")
            r = integ2.render(scene2)
            assert CHAOS.fired_total() == 1, "fault never fired"
            assert r.stats["recovery"]["redispatches"] == 1
        finally:
            CHAOS.clear()
            del os.environ["TPU_PBRT_CHUNK"]
            del os.environ["TPU_PBRT_RETRY_BACKOFF"]
        np.testing.assert_array_equal(np.asarray(r.image), np.asarray(ref.image))
        assert r.rays_traced == ref.rays_traced

    def test_poisoned_state_recovers_via_checkpoint(self, tmp_path):
        from tpu_pbrt.chaos import CHAOS

        import os

        from tpu_pbrt import config

        os.environ["TPU_PBRT_CHUNK"] = str(16 * 16 * 2)
        os.environ["TPU_PBRT_RETRY_BACKOFF"] = "0.01"
        config.reload()
        try:
            scene, integ = self._scene()
            ref = integ.render(scene)

            scene2, integ2 = self._scene()
            ck = str(tmp_path / "film.ckpt")
            CHAOS.install("dispatch:poison@chunk=3")
            r = integ2.render(scene2, checkpoint_path=ck, checkpoint_every=1)
            assert CHAOS.fired_total() == 1
            assert r.stats["recovery"]["rollbacks"] == 1
        finally:
            CHAOS.clear()
            del os.environ["TPU_PBRT_CHUNK"]
            del os.environ["TPU_PBRT_RETRY_BACKOFF"]
        np.testing.assert_allclose(
            np.asarray(r.image), np.asarray(ref.image), rtol=1e-6, atol=1e-7
        )
