"""Distribution-layer tests on the virtual 8-device CPU mesh (SURVEY.md §4:
how multi-node is tested without a cluster). Validates that the shard_map
tile scheduler + psum film merge produces the same image as the
single-device path — the distributed film merge is exact, not approximate,
because work items are partitioned (each sample is computed exactly once,
on exactly one device)."""

import jax
import numpy as np
import pytest

from tpu_pbrt.parallel.mesh import make_mesh
from tpu_pbrt.scenes import compile_api, make_cornell

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh from conftest"
)


def test_mesh_shape():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("tiles",)


def test_sharded_render_matches_single_device():
    api = make_cornell(res=24, spp=8, integrator="path", maxdepth=3)
    scene, integ = compile_api(api)
    r_single = integ.render(scene)

    api2 = make_cornell(res=24, spp=8, integrator="path", maxdepth=3)
    scene2, integ2 = compile_api(api2)
    r_mesh = integ2.render(scene2, mesh=make_mesh(8))

    assert r_mesh.image.shape == r_single.image.shape
    assert r_mesh.image.max() > 0
    # identical sample set, partitioned across devices -> identical film up
    # to float addition order
    assert np.allclose(r_mesh.image, r_single.image, rtol=1e-4, atol=1e-5)
    assert r_mesh.rays_traced == r_single.rays_traced


def test_sharded_render_four_devices():
    api = make_cornell(res=16, spp=4, integrator="directlighting", maxdepth=2)
    scene, integ = compile_api(api)
    r = integ.render(scene, mesh=make_mesh(4))
    assert r.image.max() > 0


class TestFaultInjection:
    """Worker-failure handling (SURVEY.md §2e): dropped chunk dispatches
    are re-dispatched; a state-poisoning failure rolls back to the last
    checkpoint. Both recoveries must be BIT-identical to the undisturbed
    render (chunks are idempotent pure functions of the work range)."""

    def _scene(self):
        api = make_cornell(res=16, spp=8, integrator="path", maxdepth=2)
        return compile_api(api)

    def test_redispatch_bit_identical(self):
        from tpu_pbrt.integrators.common import ChunkDispatchError

        scene, integ = self._scene()
        # small chunks so the render has several dispatches
        import os

        from tpu_pbrt import config

        os.environ["TPU_PBRT_CHUNK"] = str(16 * 16 * 2)
        config.reload()
        try:
            ref = integ.render(scene)

            scene2, integ2 = self._scene()
            failures = []

            def hook(c, attempt):
                if c == 1 and attempt == 0:
                    failures.append(c)
                    raise ChunkDispatchError("injected worker loss")

            integ2._fault_hook = hook
            r = integ2.render(scene2)
        finally:
            del os.environ["TPU_PBRT_CHUNK"]
        assert failures == [1], "fault hook never fired"
        np.testing.assert_array_equal(np.asarray(r.image), np.asarray(ref.image))
        assert r.rays_traced == ref.rays_traced

    def test_poisoned_state_recovers_via_checkpoint(self, tmp_path):
        from tpu_pbrt.integrators.common import ChunkDispatchError

        import os

        from tpu_pbrt import config

        os.environ["TPU_PBRT_CHUNK"] = str(16 * 16 * 2)
        config.reload()
        try:
            scene, integ = self._scene()
            ref = integ.render(scene)

            scene2, integ2 = self._scene()
            ck = str(tmp_path / "film.ckpt")
            fired = []

            def hook(c, attempt):
                if c == 3 and not fired:
                    fired.append(c)
                    raise ChunkDispatchError(
                        "injected mid-dispatch device loss", poisons_state=True
                    )

            integ2._fault_hook = hook
            r = integ2.render(scene2, checkpoint_path=ck, checkpoint_every=1)
        finally:
            del os.environ["TPU_PBRT_CHUNK"]
        assert fired == [3]
        np.testing.assert_allclose(
            np.asarray(r.image), np.asarray(ref.image), rtol=1e-6, atol=1e-7
        )
