"""Async pipelined dispatch (ISSUE 13): the in-flight chunk-slice
window over the render drain loop and the serve scheduler.

Oracles:

- BIT-IDENTITY ACROSS DEPTH: the window moves SYNC POINTS, never the
  dispatched programs or their order — so a depth-N render (N >= 2)
  must be bit-identical to depth-1 on every path: the single-device
  path pool drain, the serve multi-tenant interleaved drain, and the
  mesh renderer. At spp=1 there is no accumulation-order freedom at
  all.
- CHECKPOINT EQUIVALENCE MID-WINDOW: a cadence checkpoint that falls
  while slices are in flight is written from a device-side film
  snapshot, deferred to the slice's retirement — resuming from such a
  checkpoint (after a retry-budget exhaustion crash) must converge to
  the same bits as an undisturbed depth-1 render.
- RECOVERY WITH A NON-EMPTY WINDOW: a dispatch fault with slices in
  flight flushes the window and rides the existing ladder (rollback /
  plain re-dispatch) to a bit-identical film — the chaos-matrix
  `pipeline` row runs the same shape in CI.
- SCHEDULING: the serve dispatch record is depth- and prefetch-
  independent (the lookahead must never perturb the schedule), and
  step() samples its clock ONCE (the `now` race satellite: a job
  inside its backoff window must never be invisible to both the
  runnable set and the min-not_before wait).
"""

import os
import time

import numpy as np
import pytest

from tpu_pbrt import config
from tpu_pbrt.chaos import CHAOS
from tpu_pbrt.integrators.common import ChunkDispatchError, DispatchWindow
from tpu_pbrt.scene.api import Options, compile_string
from tpu_pbrt.scenes import cornell_box_text

SPP = 1  # one sample per pixel: bit-identity has no order freedom
TEXT = cornell_box_text(res=24, spp=SPP, integrator="path", maxdepth=3)
CHUNK = 96  # 24*24*1 = 576 work items -> 6 chunks


@pytest.fixture(autouse=True)
def _clear_chaos():
    CHAOS.clear()
    yield
    CHAOS.clear()


def _set(monkeypatch, depth, **extra):
    monkeypatch.setenv("TPU_PBRT_PIPELINE", str(depth))
    monkeypatch.setenv("TPU_PBRT_CHUNK", str(CHUNK))
    monkeypatch.setenv("TPU_PBRT_RETRY_BACKOFF", "0.01")
    for k, v in extra.items():
        monkeypatch.setenv(k, str(v))
    config.reload()


def _render(depth, monkeypatch, mesh=None, **render_kw):
    _set(monkeypatch, depth)
    scene, integ = compile_string(TEXT, Options(quiet=True))
    return integ.render(scene, mesh=mesh, **render_kw)


def _film(result):
    import jax

    st = jax.device_get(result.film_state)
    return [np.asarray(st.rgb), np.asarray(st.weight), np.asarray(st.splat)]


def _identical(a, b):
    return all(np.array_equal(x, y) for x, y in zip(_film(a), _film(b)))


# ---------------------------------------------------------------------------
# DispatchWindow unit behavior (pure host)
# ---------------------------------------------------------------------------


class TestDispatchWindow:
    def test_depth_clamped_and_retire_order(self):
        w = DispatchWindow(0)  # clamps to 1
        assert w.depth == 1
        w = DispatchWindow(2)
        w.push(0, np.int32(0))
        w.push(1, np.int32(1))
        assert w.full() and len(w) == 2
        assert w.retire_one() == 0
        assert not w.full() and len(w) == 1

    def test_deferred_runs_at_cursor_retirement(self):
        w = DispatchWindow(3)
        ran = []
        w.push(0, np.int32(0))
        w.defer(2, lambda: ran.append("cursor2"))  # needs chunk 1 retired
        w.push(1, np.int32(1))
        assert w.retire_one() == 0 and ran == []
        assert w.retire_one() == 1 and ran == ["cursor2"]

    def test_flush_discard_drops_deferred(self):
        w = DispatchWindow(2)
        ran = []
        w.push(0, np.int32(0))
        w.defer(1, lambda: ran.append("x"))
        w.flush(discard=True)
        assert len(w) == 0 and ran == []

    def test_flush_quiesce_runs_deferred(self):
        w = DispatchWindow(2)
        ran = []
        w.push(0, np.int32(0))
        w.defer(1, lambda: ran.append("x"))
        w.flush(discard=False)
        assert len(w) == 0 and ran == ["x"]

    def test_retire_wait_attributed(self):
        waits = []
        w = DispatchWindow(1, on_wait=waits.append)
        w.push(0, np.int32(0))
        w.retire_one()
        assert len(waits) == 1 and waits[0] >= 0.0


# ---------------------------------------------------------------------------
# depth-1 vs depth-N bit-identity
# ---------------------------------------------------------------------------


class TestDepthBitIdentity:
    def test_path_pool_chunk_render(self, monkeypatch):
        r1 = _render(1, monkeypatch)
        r3 = _render(3, monkeypatch)
        assert _identical(r1, r3), "depth-3 film differs from depth-1"
        assert r1.rays_traced == r3.rays_traced
        assert np.array_equal(
            np.asarray(r1.image), np.asarray(r3.image)
        )

    def test_depth_n_with_deferred_checkpoints(self, monkeypatch, tmp_path):
        """Cadence checkpoints landing mid-window (the film-snapshot +
        deferred-write path) must not perturb the film, and the final
        durable file must read back at the full cursor."""
        r1 = _render(1, monkeypatch)
        ck = str(tmp_path / "film.ckpt")
        r3 = _render(3, monkeypatch, checkpoint_path=ck, checkpoint_every=1)
        assert _identical(r1, r3)
        from tpu_pbrt.parallel.checkpoint import load_checkpoint

        state, cursor, rays, _ = load_checkpoint(ck)
        assert cursor == 6  # 576 / 96
        assert rays == r3.rays_traced
        assert np.array_equal(np.asarray(state.rgb), _film(r3)[0])

    def test_mesh_renderer(self, monkeypatch):
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs >= 4 virtual devices")
        from tpu_pbrt.parallel.mesh import make_mesh

        r1 = _render(1, monkeypatch, mesh=make_mesh(4))
        r3 = _render(3, monkeypatch, mesh=make_mesh(4))
        assert _identical(r1, r3), "mesh depth-3 film differs from depth-1"
        assert r1.rays_traced == r3.rays_traced

    def test_dispatch_ahead_phase_attribution(self, monkeypatch):
        """Depth >= 2 attributes overlapped dispatches to the new
        dispatch_ahead phase; depth 1 never does (there is nothing in
        flight to hide them under)."""
        monkeypatch.setenv("TPU_PBRT_METRICS", "1")
        r1 = _render(1, monkeypatch)
        r3 = _render(3, monkeypatch)
        ph1 = r1.stats.get("phase_seconds") or {}
        ph3 = r3.stats.get("phase_seconds") or {}
        assert "dispatch_ahead" not in ph1
        assert "dispatch_ahead" in ph3
        assert "device_wait" in ph3

    def test_strict_firewall_forces_depth_1(self, monkeypatch):
        from tpu_pbrt.parallel.mesh import resolve_pipeline_depth

        _set(monkeypatch, 4)
        assert resolve_pipeline_depth() == 4
        monkeypatch.setenv("TPU_PBRT_NONFINITE", "retry")
        config.reload()
        assert resolve_pipeline_depth() == 1


# ---------------------------------------------------------------------------
# host_overlap_fraction (pure + smoke)
# ---------------------------------------------------------------------------


class TestHostOverlapFraction:
    def test_pure_function(self):
        from tpu_pbrt.obs.metrics import (
            MetricsRegistry,
            host_overlap_fraction,
        )

        assert host_overlap_fraction({}) is None
        assert host_overlap_fraction(
            {"device_wait": 3.0, "dispatch": 1.0}
        ) == 0.75
        assert host_overlap_fraction(
            {"device_wait": 3.0}, wall_seconds=6.0
        ) == 0.5
        # clamped: attribution can overlap the wall measurement slightly
        assert host_overlap_fraction(
            {"device_wait": 9.0}, wall_seconds=6.0
        ) == 1.0
        assert host_overlap_fraction(
            registry=MetricsRegistry()
        ) is None

    @pytest.mark.slow
    def test_overlap_improves_with_depth(self, monkeypatch, tmp_path):
        """The acceptance smoke: with per-chunk checkpoint serialization
        as the host tax, depth 2 hides it under in-flight compute and
        device_wait swallows a larger fraction of wall than the
        synchronous depth-1 loop. Timing-dependent — kept out of
        tier-1; CI covers the structural half via phase attribution."""
        from tpu_pbrt.obs.metrics import host_overlap_fraction

        monkeypatch.setenv("TPU_PBRT_METRICS", "1")

        def overlap(depth, tag):
            r = _render(
                depth, monkeypatch,
                checkpoint_path=str(tmp_path / f"{tag}.ckpt"),
                checkpoint_every=1,
            )
            return host_overlap_fraction(
                r.stats.get("phase_seconds"), r.seconds
            )

        o1, o2 = overlap(1, "d1"), overlap(2, "d2")
        assert o1 is not None and o2 is not None
        assert o2 > o1, f"depth-2 overlap {o2} not above depth-1 {o1}"


# ---------------------------------------------------------------------------
# recovery + checkpoint-resume with slices in flight
# ---------------------------------------------------------------------------


class TestPipelinedRecovery:
    def test_clean_redispatch_mid_window(self, monkeypatch):
        """dispatch:fail with depth-3 slices in flight: the window is
        quiesced (not discarded) and the plain re-dispatch is exact."""
        ref = _render(1, monkeypatch)
        _set(monkeypatch, 3)
        CHAOS.install("dispatch:fail@chunk=2", seed=0)
        try:
            scene, integ = compile_string(TEXT, Options(quiet=True))
            r = integ.render(scene)
            rep = CHAOS.report()
        finally:
            CHAOS.clear()
        assert sum(e["fired"] for e in rep) == 1
        assert r.stats["recovery"]["redispatches"] == 1
        assert _identical(ref, r)

    def test_poison_rollback_mid_window(self, monkeypatch, tmp_path):
        """dispatch:poison with slices in flight: window discarded,
        rollback to a DEFERRED-written checkpoint, exact replay."""
        ref = _render(1, monkeypatch)
        _set(monkeypatch, 3)
        CHAOS.install("dispatch:poison@chunk=3", seed=0)
        try:
            scene, integ = compile_string(TEXT, Options(quiet=True))
            r = integ.render(
                scene, checkpoint_path=str(tmp_path / "f.ckpt"),
                checkpoint_every=1,
            )
        finally:
            CHAOS.clear()
        assert r.stats["recovery"]["rollbacks"] == 1
        assert _identical(ref, r)

    def test_checkpoint_resume_mid_window(self, monkeypatch, tmp_path):
        """Retry-budget exhaustion mid-render at depth 3 leaves a
        durable checkpoint written from a mid-window snapshot; the
        resume converges to the undisturbed depth-1 bits."""
        ref = _render(1, monkeypatch)
        ck = str(tmp_path / "f.ckpt")
        _set(monkeypatch, 3, TPU_PBRT_RETRY_MAX=1)
        CHAOS.install("dispatch:fail@chunk=4&times=99", seed=0)
        try:
            scene, integ = compile_string(TEXT, Options(quiet=True))
            with pytest.raises(RuntimeError, match="chunk 4"):
                integ.render(scene, checkpoint_path=ck, checkpoint_every=1)
        finally:
            CHAOS.clear()
        from tpu_pbrt.parallel.checkpoint import load_checkpoint

        _, cursor, _, _ = load_checkpoint(ck)
        assert cursor == 4  # every completed chunk survived the crash
        _set(monkeypatch, 3)
        scene, integ = compile_string(TEXT, Options(quiet=True))
        r = integ.render(scene, checkpoint_path=ck)
        assert _identical(ref, r)
        assert r.rays_traced == ref.rays_traced


# ---------------------------------------------------------------------------
# serve: multi-tenant drain, prefetch, and the step() clock satellite
# ---------------------------------------------------------------------------


def _drain_service(depth, prefetch, monkeypatch):
    from tpu_pbrt.serve import RenderService

    _set(monkeypatch, depth,
         TPU_PBRT_SERVE_PREFETCH="1" if prefetch else "0")
    svc = RenderService(chunk=CHUNK, seed=7)
    opts = Options(quiet=True)
    ja = svc.submit(text=TEXT, options=opts, tenant="alice")
    jb = svc.submit(text=TEXT, options=opts, tenant="bob")
    svc.drain()
    return svc, ja, jb


class TestServePipelined:
    def test_interleaved_multi_tenant_depth_identity(self, monkeypatch):
        solo = _render(1, monkeypatch)
        svc1, a1, b1 = _drain_service(1, True, monkeypatch)
        svc3, a3, b3 = _drain_service(3, True, monkeypatch)
        img_ref = np.asarray(solo.image, np.float32)
        for svc, ja, jb in ((svc1, a1, b1), (svc3, a3, b3)):
            for j in (ja, jb):
                img = np.asarray(svc.result(j).image, np.float32)
                assert np.array_equal(img, img_ref)
        # the dispatch record is depth-independent: the window moves
        # sync points, never the policy decisions
        assert svc1.schedule == svc3.schedule

    def test_prefetch_preactivates_next_job(self, monkeypatch):
        from tpu_pbrt.serve import RenderService

        _set(monkeypatch, 2)
        svc = RenderService(chunk=CHUNK, seed=7)
        opts = Options(quiet=True)
        svc.submit(text=TEXT, options=opts, tenant="alice")
        jb = svc.submit(text=TEXT, options=opts, tenant="bob")
        stepped = svc.step()
        assert stepped is not None
        other = jb if stepped != jb else "j1"
        # the next scheduled job was activated under the in-flight slice
        assert svc.jobs[other].state is not None
        svc.drain()

    def test_prefetch_off_schedule_identical(self, monkeypatch):
        svc_on, *_ = _drain_service(2, True, monkeypatch)
        svc_off, *_ = _drain_service(2, False, monkeypatch)
        assert svc_on.schedule == svc_off.schedule

    def test_prefetch_never_preempts(self, monkeypatch):
        from tpu_pbrt.serve import RenderService

        _set(monkeypatch, 2)
        svc = RenderService(chunk=CHUNK, seed=7, max_active=1)
        opts = Options(quiet=True)
        ja = svc.submit(text=TEXT, options=opts, tenant="alice")
        jb = svc.submit(text=TEXT, options=opts, tenant="bob")
        svc.step()
        # max_active=1: the lookahead must NOT have parked the running
        # job to make room for the next one
        assert svc.jobs[ja].preemptions == 0
        assert svc.jobs[jb].preemptions == 0
        assert (
            sum(1 for j in svc.jobs.values() if j.state is not None) <= 1
        )
        svc.drain()

    def test_step_now_race_backoff_window(self, monkeypatch):
        """Satellite: step() samples the decision clock ONCE. A job
        inside its backoff window at the sampled `now` must be counted
        by the min-not_before wait even if the clock passes not_before
        between two would-be samples — otherwise step() answers None
        with work still pending. The wall clock now routes through the
        injectable utils/clock.py seam (ISSUE 17), so the race is
        staged as an adversarial Clock whose every post-pick decision
        sample lands past the deadline."""
        from tpu_pbrt.serve import RenderService
        from tpu_pbrt.utils.clock import Clock

        _set(monkeypatch, 1)
        clock = Clock()
        svc = RenderService(chunk=CHUNK, seed=7, clock=clock)
        jid = svc.submit(text=TEXT, options=Options(quiet=True))
        real = time.time
        job = svc.jobs[jid]
        job.not_before = real() + 5.0  # inside a backoff window
        calls = {"n": 0}

        def fake():
            # first sample: the real clock (job excluded from runnable);
            # every later sample: past the backoff deadline — the exact
            # shape where double sampling loses the job entirely
            calls["n"] += 1
            return real() if calls["n"] == 1 else real() + 10.0

        monkeypatch.setattr(clock, "now", fake)
        # no need to wait out the window for real — the post-sleep
        # re-pick still has to see a fresh sample past the deadline
        monkeypatch.setattr(clock, "sleep", lambda s: None)
        assert svc.step() == jid
        job.not_before = 0.0  # let the drain below run at real speed
        svc.drain()

    def test_serve_deferred_checkpoint_resume(self, monkeypatch, tmp_path):
        """A job checkpointing every slice at depth 3 (deferred writes),
        preempted mid-render and resumed, still lands the solo bits."""
        from tpu_pbrt.serve import RenderService

        solo = _render(1, monkeypatch)
        _set(monkeypatch, 3)
        svc = RenderService(chunk=CHUNK, seed=7)
        jid = svc.submit(
            text=TEXT, options=Options(quiet=True),
            checkpoint_path=str(tmp_path / "job.ckpt"), checkpoint_every=1,
        )
        svc.step()
        svc.step()
        svc.preempt(jid)
        svc.resume(jid)
        svc.drain()
        img = np.asarray(svc.result(jid).image, np.float32)
        assert np.array_equal(img, np.asarray(solo.image, np.float32))
        assert svc.jobs[jid].preemptions == 1
