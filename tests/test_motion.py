"""Motion blur end-to-end (VERDICT r4 #8): shutter time sampled per
camera ray, two-keyframe vertex baking through the ActiveTransform
pair, cubic-in-time MXU feature tables (accel/mxu.py
tri_feature_weights_motion), and time-lerped hit vertices.

Analytic oracle: an emissive quad translating across a black background
under a full [0,1] shutter. Two closed forms:
- ENERGY: the image-integrated radiance equals the static quad's (time
  average of a translating emitter preserves total flux).
- PROFILE: a pixel the quad covers for a fraction f of the shutter
  reads f * L.
"""

import numpy as np

from tpu_pbrt.scenes import PbrtAPI, Options, compile_api, parse_string, pbrt_init


def _render(move_dx, spp=128, res=32):
    api = pbrt_init(Options(quiet=True))
    parse_string(
        f"""
Integrator "path" "integer maxdepth" [1]
Sampler "random" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}]
LookAt 0 0 -4  0 0 0  0 1 0
Camera "perspective" "float fov" [53] "float shutteropen" [0] "float shutterclose" [1]
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [4 4 4]
  ActiveTransform EndTime
  Translate {move_dx} 0 0
  ActiveTransform All
  Shape "trianglemesh" "integer indices" [0 2 1 0 3 2]
    "point P" [-1.5 -0.5 0  -0.5 -0.5 0  -0.5 0.5 0  -1.5 0.5 0]
AttributeEnd
WorldEnd
""",
        api,
        render=True,
    )
    return np.asarray(api.result.image)


def test_streak_energy_conserved():
    """Total image energy is independent of the travel distance."""
    static = _render(0.0)
    moving = _render(2.0)
    assert np.isfinite(moving).all()
    e_static = float(static.sum())
    e_moving = float(moving.sum())
    assert e_static > 0
    assert abs(e_moving - e_static) / e_static < 0.04, (e_moving, e_static)


def test_streak_profile_matches_closed_form():
    """The quad (width 1) travels dx=2 over the shutter: a point in the
    streak interior is covered for width/dx = 0.5 of the shutter ->
    reads 0.5 * L; a point in the static quad reads L."""
    static = _render(0.0)
    moving = _render(2.0)
    row = static.shape[0] // 2
    # static region brightness (center of the quad's original footprint)
    stat_val = float(static[row, 8:12, 0].mean())
    # streak interior: pixels between the quad's start and end positions
    mov_val = float(moving[row, 12:18, 0].mean())
    assert abs(stat_val - 4.0) / 4.0 < 0.06, stat_val
    assert abs(mov_val - 0.5 * 4.0) / (0.5 * 4.0) < 0.12, mov_val


def test_static_scene_unaffected():
    """A shutter with no moving geometry must render exactly as before
    (no tri_verts1 table, static 16-feature path)."""
    from tpu_pbrt.scenes import compile_api, make_cornell

    api = make_cornell(res=16, spp=4, integrator="path", maxdepth=2)
    scene, _ = compile_api(api)
    assert "tri_verts1" not in scene.dev
    assert scene.dev.get("bfeat") is None or scene.dev["bfeat"]["feat"].shape[0] == 16


def test_moving_mesh_stream_tracer():
    """A moving mesh big enough for the stream tracer (64-feature
    treelet pack): render finite and streaked."""
    api = pbrt_init(Options(quiet=True))
    import numpy as _np

    from tpu_pbrt.scenes import _displaced_sphere
    from tpu_pbrt.scene.paramset import ParamSet

    parse_string(
        """
Integrator "path" "integer maxdepth" [2]
Sampler "random" "integer pixelsamples" [4]
Film "image" "integer xresolution" [24] "integer yresolution" [24]
LookAt 0 0.5 -4  0 0 0  0 1 0
Camera "perspective" "float fov" [50] "float shutteropen" [0] "float shutterclose" [1]
WorldBegin
LightSource "point" "rgb I" [30 30 30] "point from" [0 3 -3]
Material "matte" "rgb Kd" [0.7 0.6 0.5]
ActiveTransform EndTime
Translate 1.2 0 0
ActiveTransform All
""",
        api,
        render=False,
    )
    V, F, N = _displaced_sphere(60, 120)
    ps = ParamSet()
    ps.add("integer indices", F.reshape(-1).tolist())
    ps.add("point P", V.reshape(-1).tolist())
    ps.add("normal N", N.reshape(-1).tolist())
    api.shape("trianglemesh", ps)
    scene, integ = compile_api(api)
    assert "tri_verts1" in scene.dev
    assert scene.dev["tstream"].n_features == 64
    res = integ.render(scene)
    img = np.asarray(res.image)
    assert np.isfinite(img).all()
    assert img.max() > 0.0
