"""Device texture evaluation tests (VERDICT r3 #6).

Oracles:
- a checkerboard whose two arms are EQUAL must render bit-comparably to
  the constant-folded scene (texture machinery is an identity),
- a checkerboard matte plane lit head-on shows the two albedos in the
  expected spatial pattern (CPU-oracle predicted from uv layout),
- an imagemap round-trips: a 2x2 image sampled at cell centers under
  "repeat" reproduces the texel values (bilinear at centers),
- mip pyramid: each level is the box average of the previous,
- noise: FBm is deterministic, bounded, and non-constant.
"""

import numpy as np
import jax.numpy as jnp

from tests.test_render import QUAD, render_scene, scene_header


PLANE = f'''
AttributeBegin
Material "matte" "texture Kd" "kdtex"
Shape "trianglemesh" {QUAD}
  "point P" [-4 -4 0   4 -4 0   4 4 0   -4 4 0]
  "float uv" [0 0  4 0  4 4  0 4]
AttributeEnd
'''


def _lit(body, spp=8, res=32):
    return render_scene(
        scene_header("directlighting", spp=spp, res=res)
        + '\nWorldBegin\n'
        + 'LightSource "distant" "rgb L" [3 3 3] "point from" [0 0 -1] "point to" [0 0 0]\n'
        + body
        + '\nWorldEnd\n'
    )


def test_equal_arm_checkerboard_matches_constant():
    tex = (
        'Texture "kdtex" "spectrum" "checkerboard" '
        '"rgb tex1" [0.4 0.5 0.6] "rgb tex2" [0.4 0.5 0.6]\n'
    )
    r_tex = _lit(tex + PLANE)
    const_plane = PLANE.replace(
        '"texture Kd" "kdtex"', '"rgb Kd" [0.4 0.5 0.6]'
    )
    r_const = _lit(const_plane)
    np.testing.assert_allclose(r_tex.image, r_const.image, rtol=1e-5, atol=1e-6)


def test_checkerboard_two_albedos_visible():
    tex = (
        'Texture "kdtex" "spectrum" "checkerboard" '
        '"rgb tex1" [0.9 0.9 0.9] "rgb tex2" [0.1 0.1 0.1]\n'
    )
    img = _lit(tex + PLANE, spp=16).image
    # the plane fills the view; uv in [0,4]^2 -> 16 alternating cells.
    # Both albedos must appear: bright pixels ~9x the dark ones.
    lum = img.mean(axis=-1)
    lo, hi = np.percentile(lum[lum > 1e-4], [10, 90])
    assert hi / max(lo, 1e-6) > 4.0, f"checker contrast missing: {lo} vs {hi}"


def test_imagemap_bilinear_roundtrip(tmp_path):
    from tpu_pbrt.utils.imageio import write_image

    img = np.zeros((2, 2, 3), np.float32)
    img[0, 0] = [1.0, 0.0, 0.0]
    img[0, 1] = [0.0, 1.0, 0.0]
    img[1, 0] = [0.0, 0.0, 1.0]
    img[1, 1] = [1.0, 1.0, 0.0]
    path = tmp_path / "t.pfm"
    write_image(str(path), img)

    from tpu_pbrt.core.texture_eval import build_texture_table

    node = (
        "imagemap",
        {
            "kind": "spectrum",
            "filename": str(path),
            "mapping": {"type": "uv", "su": 1.0, "sv": 1.0, "du": 0.0, "dv": 0.0},
            "trilerp": False,
            "max_aniso": 8.0,
            "wrap": "repeat",
            "scale": 1.0,
            "gamma": False,
        },
    )
    atlas, ev = build_texture_table([node])
    # texel centers: (0.25, 0.25) is texel (0,0) = row 0 col 0
    uv = jnp.asarray(
        [[0.25, 0.25], [0.75, 0.25], [0.25, 0.75], [0.75, 0.75]], jnp.float32
    )
    p = jnp.zeros((4, 3), jnp.float32)
    tid = jnp.zeros((4,), jnp.int32)
    out = np.asarray(ev(jnp.asarray(atlas), tid, uv, p))
    np.testing.assert_allclose(out[0], img[0, 0], atol=1e-5)
    np.testing.assert_allclose(out[1], img[0, 1], atol=1e-5)
    np.testing.assert_allclose(out[2], img[1, 0], atol=1e-5)
    np.testing.assert_allclose(out[3], img[1, 1], atol=1e-5)


def test_mip_pyramid_box_average():
    from tpu_pbrt.core.texture_eval import _build_pyramid

    rng = np.random.default_rng(0)
    img = rng.uniform(size=(8, 8, 3)).astype(np.float32)
    levels = _build_pyramid(img)
    assert [lv.shape[:2] for lv in levels] == [(8, 8), (4, 4), (2, 2), (1, 1)]
    np.testing.assert_allclose(levels[-1][0, 0], img.mean(axis=(0, 1)), rtol=1e-5)
    np.testing.assert_allclose(
        levels[1][0, 0], img[:2, :2].mean(axis=(0, 1)), rtol=1e-5
    )


def test_fbm_deterministic_bounded():
    from tpu_pbrt.core.texture_eval import fbm, noise3

    p = jnp.asarray(
        np.random.default_rng(1).uniform(-10, 10, (256, 3)), jnp.float32
    )
    n = np.asarray(noise3(p))
    assert np.all(np.abs(n) <= 1.5)
    assert n.std() > 0.05, "noise is (nearly) constant"
    f1 = np.asarray(fbm(p, 0.5, 6))
    f2 = np.asarray(fbm(p, 0.5, 6))
    np.testing.assert_array_equal(f1, f2)
    # lattice-point continuity: values at +eps and -eps agree
    q = jnp.asarray([[1.0, 2.0, 3.0]], jnp.float32)
    eps = 1e-3
    a = float(noise3(q - eps)[0])
    b = float(noise3(q + eps)[0])
    assert abs(a - b) < 0.05


def test_ewa_anisotropic_preserves_cross_axis_detail(tmp_path):
    """mipmap.h MIPMap::EWA semantics (VERDICT r4 #7): a footprint that
    is wide along u but narrow along v must average along u WITHOUT
    blurring across v. The isotropic trilinear path (scalar lod = max
    axis) picks the coarse level and destroys the stripes; the EWA
    filter keys the level off the MINOR axis and keeps them."""
    from tpu_pbrt.utils.imageio import write_image

    # horizontal stripes: value depends only on v (8-texel period rows)
    img = np.zeros((64, 64, 3), np.float32)
    img[(np.arange(64) // 8 % 2 == 0), :, :] = 1.0
    path = tmp_path / "stripes.pfm"
    write_image(str(path), img)

    from tpu_pbrt.core.texture_eval import build_texture_table

    node = (
        "imagemap",
        {
            "kind": "spectrum",
            "filename": str(path),
            "mapping": {"type": "uv", "su": 1.0, "sv": 1.0, "du": 0.0,
                        "dv": 0.0},
            "trilerp": False,
            "max_aniso": 8.0,
            "wrap": "repeat",
            "scale": 1.0,
            "gamma": False,
        },
    )
    atlas, ev = build_texture_table([node])
    a = jnp.asarray(atlas)
    # center of a white stripe (v around 0.0625 = row 4 of 64)
    uv = jnp.asarray([[0.5, 4.5 / 64.0]], jnp.float32)
    p = jnp.zeros((1, 3), jnp.float32)
    tid = jnp.zeros((1,), jnp.int32)

    # anisotropic footprint: wide along u, a texel along v
    duv4 = jnp.asarray([[0.25, 0.0, 0.0, 1.0 / 64.0]], jnp.float32)
    out_ewa = float(np.asarray(ev(a, tid, uv, p, duv4))[0, 0])
    # isotropic path at the same MAX width (the old behavior)
    out_iso = float(
        np.asarray(ev(a, tid, uv, p, jnp.full((1,), 0.25, jnp.float32)))[0, 0]
    )
    assert out_ewa > 0.85, f"EWA blurred across the minor axis: {out_ewa}"
    assert out_iso < 0.7, (
        f"isotropic reference unexpectedly sharp ({out_iso}) — "
        "the oracle no longer discriminates"
    )


def test_ewa_isotropic_footprint_matches_trilinear():
    """A circular footprint must reduce EWA to (approximately) the
    single-tap trilinear result — the taps collapse onto the same
    ellipse and the Gaussian weights normalize out."""
    from tpu_pbrt.core.texture_eval import build_texture_table

    rng = np.random.default_rng(7)
    # procedural checker node needs no file; use an imagemap-free
    # comparison via a synthetic imagemap written to tmp — instead
    # reuse fbm-free path: build a small random pfm in-memory
    import tempfile

    from tpu_pbrt.utils.imageio import write_image

    img = rng.uniform(size=(32, 32, 3)).astype(np.float32)
    with tempfile.NamedTemporaryFile(suffix=".pfm", delete=False) as f:
        path = f.name
    write_image(path, img)
    node = (
        "imagemap",
        {
            "kind": "spectrum",
            "filename": path,
            "mapping": {"type": "uv", "su": 1.0, "sv": 1.0, "du": 0.0,
                        "dv": 0.0},
            "trilerp": False,
            "max_aniso": 8.0,
            "wrap": "repeat",
            "scale": 1.0,
            "gamma": False,
        },
    )
    atlas, ev = build_texture_table([node])
    a = jnp.asarray(atlas)
    n = 16
    uv = jnp.asarray(rng.uniform(0.1, 0.9, (n, 2)), jnp.float32)
    p = jnp.zeros((n, 3), jnp.float32)
    tid = jnp.zeros((n,), jnp.int32)
    w = 0.1
    duv4 = jnp.tile(jnp.asarray([[w, 0.0, 0.0, w]], jnp.float32), (n, 1))
    out_ewa = np.asarray(ev(a, tid, uv, p, duv4))
    out_tri = np.asarray(ev(a, tid, uv, p, jnp.full((n,), w, jnp.float32)))
    # same level, taps spread across one footprint width: close, not exact
    assert np.max(np.abs(out_ewa - out_tri)) < 0.15
    import os

    os.unlink(path)
