"""tpu-serve (ISSUE 6): multi-tenant render service.

Oracles:

- BIT-IDENTITY UNDER MULTIPLEXING: chunks are idempotent pure functions
  of (scene, work range) and film accumulation is associative, so a
  job's film must be bit-identical to its solo run-to-completion render
  no matter how its slices interleave with other tenants', and across a
  preempt(emergency checkpoint)/resume cycle — at spp=1 every pixel
  holds one sample, so there is no accumulation-order freedom at all.
- RESIDENCY: a repeat submit of a warm scene pays 0 scene compiles and
  0 jit retraces (the PR 2 `_cache_size` audit applied to serving);
  cancel releases the pin; the LRU evicts by HBM footprint and never
  evicts pinned entries.
- POLICY: scheduling is deterministic given a seed (same submit
  sequence -> same schedule), weighted-fair across tenants, and strict
  across priority classes (with film-state preemption under
  max_active).
"""

import numpy as np
import pytest

from tpu_pbrt.scene.api import Options, compile_string
from tpu_pbrt.scenes import cornell_box_text
from tpu_pbrt.serve import (
    FairScheduler,
    RenderService,
    ResidencyCache,
    ShedError,
    SloPolicy,
    parse_slo_spec,
    preemption_victim,
    scene_hbm_bytes,
)

SPP = 1  # one sample per pixel: bit-identity has no order freedom
TEXT = cornell_box_text(res=32, spp=SPP, integrator="path", maxdepth=3)
CHUNK = 256  # 32*32*1 = 1024 work items -> 4 slices per job


@pytest.fixture(scope="module")
def solo_ref():
    """Solo run-to-completion reference (its own compile + integrator,
    rendered through the monolithic loop — the service must reproduce
    these bits through sliced, interleaved, preempted scheduling)."""
    scene, integ = compile_string(TEXT, Options(quiet=True))
    return np.asarray(integ.render(scene).image, np.float32)


# --------------------------------------------------------------------------
# queue policy (pure host units)
# --------------------------------------------------------------------------


class _J:
    def __init__(self, seq, tenant="t", priority=0):
        self.seq = seq
        self.tenant = tenant
        self.priority = priority


def test_scheduler_weighted_fair_and_deterministic():
    def run(seed):
        s = FairScheduler(seed=seed)
        s.set_weight("heavy", 2.0)
        s.set_weight("light", 1.0)
        jobs = [_J(1, "heavy"), _J(2, "light")]
        order = []
        for _ in range(30):
            j = s.pick(jobs)
            order.append(j.tenant)
            s.charge(j.tenant)
        return order

    a, b = run(7), run(7)
    assert a == b, "same seed must reproduce the schedule"
    # weight 2 tenant gets ~2x the slices under contention
    assert 18 <= a.count("heavy") <= 22, a.count("heavy")


def test_scheduler_reenter_drops_banked_credit():
    """A tenant that went idle while others kept dispatching must
    re-enter at the busy tenants' vtime floor, not replay its stale low
    vtime and monopolize the mesh."""
    s = FairScheduler(seed=0)
    s.tenant("a")
    s.tenant("b")
    for _ in range(100):
        s.charge("a")  # b idles while a spends 100 slices
    s.reenter("b", busy_tenants={"a"})
    assert s.tenant("b").vtime == s.tenant("a").vtime
    # and with nobody busy, re-entry is a no-op
    s.reenter("b", busy_tenants=set())
    assert s.tenant("b").vtime == s.tenant("a").vtime


def test_scheduler_priority_classes_beat_fairness():
    s = FairScheduler(seed=0)
    low, high = _J(1, "a", priority=0), _J(2, "b", priority=5)
    for _ in range(5):
        assert s.pick([low, high]) is high
        s.charge("b")


def test_preemption_victim_picks_lowest_outranked():
    a = _J(1, priority=0)
    b = _J(2, priority=2)
    cand = _J(3, priority=5)
    assert preemption_victim([a, b], cand) is a
    assert preemption_victim([b], _J(4, priority=2)) is None  # ties don't preempt


# --------------------------------------------------------------------------
# residency (host units over fake scenes)
# --------------------------------------------------------------------------


class _FakeFilm:
    full_resolution = (4, 4)


class _FakeScene:
    def __init__(self, kb):
        self.dev = {"a": np.zeros(kb * 256, np.float32)}  # kb KiB
        self.film = _FakeFilm()


def test_residency_lru_eviction_respects_pins():
    base = scene_hbm_bytes(_FakeScene(0))
    cache = ResidencyCache(max_bytes=2 * (base + 100 * 1024) + 1024)
    for key, kb in (("s1", 100), ("s2", 100), ("s3", 100)):
        cache.get_or_compile(key, lambda kb=kb: (_FakeScene(kb), object()))
    # LRU (s1) evicted to fit the budget
    assert cache.get("s1") is None
    assert cache.get("s2") is not None and cache.get("s3") is not None
    assert cache.evictions == 1 and cache.scene_compiles == 3
    # a pinned entry survives even when it is the LRU victim
    cache.pin("s2")
    _ = cache.get("s3")  # make s2 the coldest
    cache.get_or_compile("s4", lambda: (_FakeScene(100), object()))
    assert cache.get("s2") is not None, "pinned entry was evicted"
    # hits don't recompile
    n = cache.scene_compiles
    cache.get_or_compile("s4", lambda: (_FakeScene(100), object()))
    assert cache.scene_compiles == n and cache.hits == 1


# --------------------------------------------------------------------------
# the service (real renders, single device)
# --------------------------------------------------------------------------


def _service(**kw):
    kw.setdefault("chunk", CHUNK)
    kw.setdefault("seed", 0)
    return RenderService(**kw)


def test_interleaved_jobs_bit_identical_to_solo(solo_ref):
    svc = _service()
    j1 = svc.submit(text=TEXT, tenant="alice")
    j2 = svc.submit(text=TEXT, tenant="bob")
    assert svc.residency.stats()["scene_compiles"] == 1, (
        "two same-scene submits must share one resident compile"
    )
    svc.drain()
    # the fair scheduler actually interleaved the two jobs' slices
    owners = [jid for jid, _ in svc.schedule]
    first_done = {jid: owners[::-1].index(jid) for jid in (j1, j2)}
    assert owners.index(j2) < len(owners) - 1 - first_done[j1], (
        f"schedule never interleaved: {svc.schedule}"
    )
    for j in (j1, j2):
        img = np.asarray(svc.result(j).image, np.float32)
        assert np.isfinite(img).all()
        assert np.array_equal(img, solo_ref), (
            f"{j} differs from solo (max "
            f"{np.max(np.abs(img - solo_ref))})"
        )


def test_preempt_resume_bit_identical(solo_ref):
    svc = _service()
    j = svc.submit(text=TEXT)
    svc.step()
    svc.step()
    svc.preempt(j)  # emergency checkpoint + film state dropped
    job = svc.jobs[j]
    assert job.state is None and job.status == "paused"
    assert svc.step() is None, "paused job must not schedule"
    svc.resume(j)
    svc.drain()
    assert job.preemptions == 1
    img = np.asarray(svc.result(j).image, np.float32)
    assert np.array_equal(img, solo_ref)


def test_warm_resubmit_zero_scene_and_jit_recompiles(solo_ref):
    svc = _service()
    j1 = svc.submit(text=TEXT)
    svc.drain()
    ent = svc.residency.get(svc.jobs[j1].resident_key)
    jfn = ent.integrator._jit_cache[1]
    size = jfn._cache_size()
    j2 = svc.submit(text=TEXT)
    svc.drain()
    stats = svc.residency.stats()
    assert stats["scene_compiles"] == 1, stats
    jfn2 = ent.integrator._jit_cache[1]
    assert jfn2 is jfn, "warm resubmit rebuilt the chunk closure"
    assert jfn2._cache_size() == size, "warm resubmit retraced"
    assert np.array_equal(
        np.asarray(svc.result(j2).image, np.float32), solo_ref
    )


def test_cancel_releases_residency_and_spool():
    import os

    svc = _service(max_resident_bytes=1)  # budget nothing fits
    j = svc.submit(text=TEXT)
    key = svc.jobs[j].resident_key
    # pinned by the live job: over budget but NOT evictable
    assert svc.residency.get(key) is not None
    svc.step()
    ckpt = svc.jobs[j].checkpoint_path
    svc.preempt(j)
    assert os.path.exists(ckpt), "preempt must write the emergency checkpoint"
    svc.cancel(j)
    assert svc.jobs[j].status == "cancelled"
    # unpinned -> the over-budget eviction reclaims the scene, and the
    # spool checkpoint is gone
    assert svc.residency.get(key) is None
    assert not os.path.exists(ckpt)


def test_priority_preempts_film_residency(solo_ref):
    svc = _service(max_active=1)
    lo = svc.submit(text=TEXT, tenant="batch", priority=0)
    svc.step()
    svc.step()
    assert svc.jobs[lo].state is not None
    hi = svc.submit(text=TEXT, tenant="live", priority=5)
    jid = svc.step()
    assert jid == hi, "higher class must schedule immediately"
    assert svc.jobs[lo].state is None and svc.jobs[lo].preemptions == 1, (
        "low-priority job must be parked via emergency checkpoint"
    )
    svc.drain()
    for j in (lo, hi):
        assert np.array_equal(
            np.asarray(svc.result(j).image, np.float32), solo_ref
        )


def test_schedule_deterministic_across_services():
    def run():
        svc = _service(seed=3)
        svc.submit(text=TEXT, tenant="a")
        svc.submit(text=TEXT, tenant="b", weight=2.0)
        svc.drain()
        return list(svc.schedule)

    assert run() == run()


def test_preview_streams_partial_develop(tmp_path, solo_ref):
    svc = _service()
    out = tmp_path / "preview.pfm"
    j = svc.submit(text=TEXT, preview_every=1, preview_path=str(out))
    svc.step()
    assert out.exists(), "preview cadence wrote nothing"
    from tpu_pbrt.utils.imageio import read_image

    img = np.asarray(read_image(str(out)), np.float32)
    assert img.shape == solo_ref.shape
    assert np.isfinite(img).all()
    live = svc.preview(j)  # the on-demand primitive
    assert np.isfinite(np.asarray(live)).all()
    svc.drain()
    assert svc.jobs[j].previews >= 1


def test_unsliceable_integrator_rejected_at_submit():
    """SPPM/MLT own their render loops (no chunk-plan seam): the service
    must refuse at submit time with a clear error, not fail the first
    dispatch."""
    svc = _service()
    sppm_text = cornell_box_text(res=16, spp=1, integrator="sppm")
    with pytest.raises(ValueError, match="cannot be served"):
        svc.submit(text=sppm_text)


def test_step_failure_quarantines_job_not_service(solo_ref):
    """An unexpected per-job crash (here: a resume whose checkpoint was
    written for a DIFFERENT render configuration — the fingerprint
    guard) fails THE JOB; other tenants keep rendering and the failed
    job's residency pin is released."""
    from tpu_pbrt.parallel.checkpoint import save_checkpoint

    svc = _service()
    good = svc.submit(text=TEXT)
    bad = svc.submit(text=TEXT, tenant="other")
    film = svc.residency.get(svc.jobs[bad].resident_key).scene.film
    save_checkpoint(
        svc.jobs[bad].checkpoint_path, film.init_state(), 0, 0,
        fingerprint="some-other-render-config",
    )
    svc.drain()
    assert svc.jobs[bad].status == "failed"
    assert "fingerprint" in svc.jobs[bad].error or svc.jobs[bad].error
    assert np.array_equal(
        np.asarray(svc.result(good).image, np.float32), solo_ref
    )
    # the failed job no longer pins its scene
    assert svc.residency.get(svc.jobs[bad].resident_key).pins == 0


# --------------------------------------------------------------------------
# SLO load shedding + the metrics surface (ISSUE 10)
# --------------------------------------------------------------------------


def test_slo_depth_shed_is_deterministic_and_precompile(solo_ref):
    """An over-SLO burst sheds deterministically BEFORE compiling or
    queuing anything; once the class drains, admission opens again and
    the admitted work renders bit-identical to solo."""
    svc = _service(slo=SloPolicy(depth=parse_slo_spec("1", int)))
    j1 = svc.submit(text=TEXT, tenant="alice")
    compiles = svc.residency.stats()["scene_compiles"]
    reasons = []
    for _ in range(3):
        with pytest.raises(ShedError) as ei:
            svc.submit(text=TEXT, tenant="bob")
        reasons.append(ei.value.reason)
    assert svc.sheds == 3
    assert len(set(reasons)) == 1 and "depth" in reasons[0]
    # shedding never touched the compiler or the job table
    assert svc.residency.stats()["scene_compiles"] == compiles
    assert list(svc.jobs) == [j1]
    svc.drain()
    j2 = svc.submit(text=TEXT, tenant="bob")  # class drained: admitted
    svc.drain()
    assert np.array_equal(
        np.asarray(svc.result(j2).image, np.float32), solo_ref
    )


def test_slo_wait_shed_recovers_no_lockout():
    """Wait-SLO sheds while the class is congested, but the signal is a
    bounded window consulted only with queued work — once the queue
    drains, an idle class admits again (no permanent lockout from a
    past congestion spike)."""
    from collections import deque

    svc = _service(slo=SloPolicy(wait_s=parse_slo_spec("0.5", float)))
    j1 = svc.submit(text=TEXT, tenant="alice")  # depth 0: wait not consulted
    # simulate a congestion history: recent class-0 waits p90 over target
    svc._recent_waits[0] = deque([1.0] * 8, maxlen=32)
    with pytest.raises(ShedError, match="queue-wait p90"):
        svc.submit(text=TEXT, tenant="bob")
    assert svc.sheds == 1
    svc.drain()  # queue empties; the stale window must not lock the class
    j2 = svc.submit(text=TEXT, tenant="bob")
    svc.drain()
    assert svc.jobs[j1].status == "done" and svc.jobs[j2].status == "done"


def test_service_metrics_exposition_per_tenant(solo_ref):
    """The registry page lints clean and carries the per-tenant
    queue-wait/service-time histograms the acceptance names."""
    from tpu_pbrt.obs.metrics import METRICS, validate_exposition

    METRICS.reset()
    svc = _service()
    j1 = svc.submit(text=TEXT, tenant="alice")
    svc.submit(text=TEXT, tenant="bob")
    svc.drain()
    exp = svc.metrics_exposition()
    assert validate_exposition(exp) == []
    for needle in (
        "tpu_pbrt_serve_queue_wait_seconds_bucket",
        "tpu_pbrt_serve_slice_seconds_count",
        'tenant="alice"',
        'tenant="bob"',
        "tpu_pbrt_residency_hits_total",
        "tpu_pbrt_serve_queue_depth",
    ):
        assert needle in exp, f"exposition missing {needle}"
    # films unaffected by the instrumentation
    assert np.array_equal(
        np.asarray(svc.result(j1).image, np.float32), solo_ref
    )


def test_metrics_kill_switch_service_byte_identical(
    solo_ref, monkeypatch
):
    """TPU_PBRT_METRICS=0: the service renders the same bits, responds
    the same, and the exposition is empty (acceptance kill-switch
    criterion applied to serving)."""
    from tpu_pbrt import config
    from tpu_pbrt.obs.metrics import METRICS

    monkeypatch.setenv("TPU_PBRT_METRICS", "0")
    config.reload()
    METRICS.reset()
    svc = _service(slo=SloPolicy(depth=parse_slo_spec("1", int)))
    j = svc.submit(text=TEXT, tenant="alice")
    with pytest.raises(ShedError):
        svc.submit(text=TEXT, tenant="alice")  # depth shed still works
    svc.drain()
    assert svc.metrics_exposition() == ""
    assert METRICS.exposition() == ""
    assert np.array_equal(
        np.asarray(svc.result(j).image, np.float32), solo_ref
    )


def test_daemon_metrics_verb_and_shed_roundtrip():
    """JSONL round trip: an over-SLO submit answers {"shed": true}; the
    `metrics` verb returns a lint-clean Prometheus exposition carrying
    the shed counter and per-tenant histograms."""
    import io
    import json

    from tpu_pbrt.obs.metrics import METRICS, validate_exposition
    from tpu_pbrt.serve.__main__ import run_daemon

    METRICS.reset()
    svc = _service(slo=SloPolicy(depth=parse_slo_spec("1", int)))
    cmds = "\n".join(json.dumps(c) for c in [
        {"op": "submit", "text": TEXT, "tenant": "alice"},
        {"op": "submit", "text": TEXT, "tenant": "bob"},
        {"op": "metrics"},
        {"op": "shutdown", "drain": True},
    ]) + "\n"
    out = io.StringIO()
    assert run_daemon(svc, in_stream=io.StringIO(cmds), out=out) == 0
    lines = [json.loads(x) for x in out.getvalue().splitlines()]
    submits = [d for d in lines if d.get("op") == "submit"]
    assert submits[0]["ok"] is True
    assert submits[1] == {
        "ok": False, "op": "submit", "shed": True, "tenant": "bob",
        "priority": 0, "reason": submits[1]["reason"],
    }
    assert "depth" in submits[1]["reason"]
    met = [d for d in lines if d.get("op") == "metrics"]
    assert len(met) == 1 and met[0]["ok"]
    exp = met[0]["exposition"]
    assert validate_exposition(exp) == []
    assert "tpu_pbrt_serve_shed_total" in exp
    assert 'tenant="bob"' in exp
    # the admitted job still completed through the daemon loop
    done = [d for d in lines if d.get("event") == "done"]
    assert len(done) == 1


# --------------------------------------------------------------------------
# one CPU mesh: the acceptance scenario
# --------------------------------------------------------------------------


def test_concurrent_jobs_on_mesh_bit_identical_with_preempt():
    """ISSUE 6 acceptance: two concurrent submits on ONE CPU mesh, both
    bit-identical to their solo run-to-completion renders, including a
    preempt/resume cycle on one of them."""
    from tpu_pbrt.parallel.mesh import make_mesh

    mesh = make_mesh(2)
    scene, integ = compile_string(TEXT, Options(quiet=True))
    ref = np.asarray(integ.render(scene, mesh=mesh).image, np.float32)

    svc = _service(mesh=mesh)
    j1 = svc.submit(text=TEXT, tenant="alice")
    j2 = svc.submit(text=TEXT, tenant="bob")
    for _ in range(3):
        svc.step()
    svc.preempt(j2)
    svc.step()
    svc.resume(j2)
    svc.drain()
    for j in (j1, j2):
        img = np.asarray(svc.result(j).image, np.float32)
        assert np.isfinite(img).all()
        assert np.array_equal(img, ref), f"{j} differs from mesh solo"
    assert svc.jobs[j2].preemptions == 1
