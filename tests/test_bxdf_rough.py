"""Microfacet breadth tests: Beckmann distribution (microfacet.cpp
BeckmannDistribution) and rough-glass microfacet transmission
(reflection.cpp MicrofacetReflection/MicrofacetTransmission via
glass.cpp's rough path)."""

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core import bxdf


def _rng_dirs(n, seed=0, hemisphere=True):
    rng = np.random.default_rng(seed)
    d = rng.normal(size=(n, 3))
    d /= np.linalg.norm(d, axis=-1, keepdims=True)
    if hemisphere:
        d[:, 2] = np.abs(d[:, 2])
    return jnp.asarray(d, jnp.float32)


def test_beckmann_normalization():
    """int D(wh) cos(wh) dw = 1 over the hemisphere (the defining property
    of a microfacet NDF)."""
    n = 200_000
    wh = _rng_dirs(n, seed=1)
    for ax, ay in ((0.1, 0.1), (0.3, 0.3), (0.2, 0.5)):
        d = np.asarray(bxdf.beckmann_d(wh, jnp.float32(ax), jnp.float32(ay)))
        # uniform-hemisphere MC: E[D cos] * 2pi
        est = float(np.mean(d * np.asarray(wh[:, 2]))) * 2.0 * np.pi
        assert abs(est - 1.0) < 0.08, f"ax={ax} ay={ay}: {est}"


def test_beckmann_sample_matches_pdf():
    """E[g(wh)/pdf(wh)] over sampled wh must equal int g dw: checked for
    g = cos^2(theta) whose hemisphere integral is 2pi/3... under the NDF
    measure the cross-check is E[g] vs int g D cos (both MC)."""
    n = 200_000
    rng = np.random.default_rng(2)
    u1 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    u2 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    ax = ay = jnp.float32(0.25)
    wh = bxdf.beckmann_sample_wh(u1, u2, ax, ay)
    pdf = np.asarray(bxdf.beckmann_pdf(wh, ax, ay))
    assert (pdf > 0).all()
    g = np.asarray(wh[:, 2]) ** 2
    est_sampled = float(np.mean(g / pdf * pdf))  # sanity: finite weights
    assert np.isfinite(est_sampled)
    # importance estimate of int g D cos dw using the samples...
    est_a = float(np.mean(g))
    # ...vs uniform-hemisphere MC of the same integral (int g D cos / int D cos)
    whu = _rng_dirs(n, seed=3)
    d = np.asarray(bxdf.beckmann_d(whu, ax, ay))
    cz = np.asarray(whu[:, 2])
    est_b = float(np.sum(np.asarray(whu[:, 2]) ** 2 * d * cz) / np.sum(d * cz))
    assert abs(est_a - est_b) < 0.02, f"{est_a} vs {est_b}"


def _glass_mp(n, rough, eta=1.5):
    one = jnp.ones((n,), jnp.float32)
    one3 = jnp.ones((n, 3), jnp.float32)
    ax = bxdf.tr_roughness_to_alpha(jnp.full((n,), max(rough, 1e-3), jnp.float32))
    return bxdf.MatParams(
        mtype=jnp.full((n,), 4, jnp.int32),  # MAT_GLASS
        kd=one3 * 0,
        ks=one3 * 0,
        kr=one3,
        kt=one3,
        eta=one3 * eta,
        k=one3 * 0,
        ax=ax,
        ay=ax,
        sigma=one * 0,
        opacity=one3,
        rough_raw=jnp.full((n,), rough, jnp.float32),
    )


def test_smooth_glass_has_no_nonspecular_response():
    n = 64
    mp = _glass_mp(n, 0.0)
    wo = _rng_dirs(n, seed=4)
    wi = _rng_dirs(n, seed=5)
    f, pdf = bxdf.bsdf_eval(mp, wo, wi)
    assert float(jnp.max(jnp.abs(f))) == 0.0
    assert float(jnp.max(pdf)) == 0.0


def test_rough_glass_scatters_both_hemispheres():
    n = 50_000
    mp = _glass_mp(n, 0.02)  # remapped alpha ~0.19; huge alphas
    # legitimately reject ~half their samples (same-hemisphere checks)
    rng = np.random.default_rng(6)
    wo = jnp.broadcast_to(
        jnp.asarray(np.array([0.3, 0.0, 0.95]) / np.linalg.norm([0.3, 0, 0.95]), jnp.float32),
        (n, 3),
    )
    bs = bxdf.bsdf_sample(
        mp,
        wo,
        jnp.asarray(rng.uniform(size=n), jnp.float32),
        jnp.asarray(rng.uniform(size=n), jnp.float32),
        jnp.asarray(rng.uniform(size=n), jnp.float32),
    )
    ok = np.asarray(bs.pdf) > 0
    assert ok.mean() > 0.7
    trans = np.asarray(bs.is_transmission)[ok]
    spec = np.asarray(bs.is_specular)[ok]
    assert not spec.any(), "rough glass must not flag specular"
    assert 0.02 < trans.mean() < 0.98, "both lobes must be sampled"
    # sample/eval consistency: pdf>0 lanes have finite throughput weights
    w = np.asarray(bs.f)[ok] * np.abs(np.asarray(bs.wi[:, 2]))[ok, None] / np.asarray(bs.pdf)[ok, None]
    assert np.isfinite(w).all()
    assert (w >= 0).all()


def test_rough_glass_energy_conservation():
    """White rough glass (Kr=Kt=1): the single-scatter radiance estimator
    E[f |cos wi| / pdf] must approach the smooth-glass value
    F + (1-F)/eta^2 (radiance transport compresses transmitted radiance
    by 1/eta^2, exactly like SpecularTransmission's (etaI/etaT)^2), with
    only shadowing/masking losses below it."""
    n = 200_000
    mp = _glass_mp(n, 0.02)
    rng = np.random.default_rng(7)
    wo = jnp.broadcast_to(
        jnp.asarray(np.array([0.4, 0.1, 0.91]) / np.linalg.norm([0.4, 0.1, 0.91]), jnp.float32),
        (n, 3),
    )
    bs = bxdf.bsdf_sample(
        mp,
        wo,
        jnp.asarray(rng.uniform(size=n), jnp.float32),
        jnp.asarray(rng.uniform(size=n), jnp.float32),
        jnp.asarray(rng.uniform(size=n), jnp.float32),
    )
    pdf = np.asarray(bs.pdf)
    ok = pdf > 1e-9
    w = (
        np.asarray(bs.f)[ok]
        * np.abs(np.asarray(bs.wi[:, 2]))[ok, None]
        / pdf[ok, None]
    )
    # dead lanes (TIR on the transmission pick) carry zero — include them
    # as zeros in the mean, matching the estimator's expectation
    total = float(w.mean(axis=-1).sum() / n)
    ct = 0.91 / np.linalg.norm([0.4, 0.1, 0.91])
    F = float(np.asarray(bxdf.fresnel_dielectric(
        jnp.float32(ct), jnp.float32(1.0), jnp.float32(1.5))))
    expected = F + (1.0 - F) / 1.5**2
    assert 0.8 * expected < total <= 1.02 * expected, (
        f"energy estimate {total} vs analytic {expected}"
    )


def test_vndf_sampling_matches_distribution():
    """tr_sample_wh must draw from the visible-normal distribution
    D_vis = G1 D max(0, wo.wh)/cos(wo): regression for the sample11 sign
    bug that killed every u1 < 0.5 sample (horizon whs, tr_d = 0)."""
    n = 200_000
    rng = np.random.default_rng(11)
    wo = jnp.broadcast_to(
        jnp.asarray(np.array([0.3, 0, 0.95]) / np.linalg.norm([0.3, 0, 0.95]), jnp.float32),
        (n, 3),
    )
    u1 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    u2 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    for alpha in (0.1, 0.4):
        ax = jnp.full((n,), alpha, jnp.float32)
        wh = bxdf.tr_sample_wh(wo, u1, u2, ax, ax)
        d = np.asarray(bxdf.tr_d(wh, ax, ax))
        assert (d > 0).mean() > 0.999, "degenerate (horizon) whs sampled"
        est_a = float(np.mean(np.asarray(wh[:, 2]) ** 2))
        dirs = rng.normal(size=(n, 3))
        dirs /= np.linalg.norm(dirs, axis=-1, keepdims=True)
        dirs[:, 2] = np.abs(dirs[:, 2])
        whu = jnp.asarray(dirs, jnp.float32)
        dvis = np.asarray(
            bxdf.tr_d(whu, ax, ax)
            * bxdf.tr_g1(wo, ax, ax)
            * jnp.maximum(jnp.sum(wo * whu, -1), 0.0)
        )
        est_b = float((dirs[:, 2] ** 2 * dvis).sum() / dvis.sum())
        assert abs(est_a - est_b) < 0.01, f"alpha={alpha}: {est_a} vs {est_b}"
