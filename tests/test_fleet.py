"""tpu-fleet tests (ISSUE 20): consistent-hash affinity, edge
admission, double-delivery dedup, kill/drain failover through the
durable spool, router-restart adoption, and the multi-replica load
replay's byte-determinism. Jobs are protocheck's stub (scene,
integrator) pairs — instant, bit-deterministic, and exercising the
same submit path the fleet selftest drives with real renders."""

import numpy as np
import pytest

from tpu_pbrt.analysis.protocheck import _harness
from tpu_pbrt.fleet.router import (
    KNEE_REQ_S,
    FleetPolicy,
    FleetRouter,
    LocalReplica,
    fleet_size,
)
from tpu_pbrt.serve.service import DONE, ShedError
from tpu_pbrt.utils.clock import VirtualClock


def _stub(chunks=2, depth=1):
    h = _harness()
    return (h["StubScene"](), h["StubIntegrator"](chunks, depth))


def _rig(tmp_path, n=2, policy=None):
    clock = VirtualClock(start=0.0, tick=1e-6)
    reps = [
        LocalReplica(
            f"r{k}", clock=clock, spool_dir=str(tmp_path / f"r{k}"),
        )
        for k in range(n)
    ]
    router = FleetRouter(
        reps, clock=clock, policy=policy,
        spool_dir=str(tmp_path / "fleet"),
    )
    return clock, reps, router


# --------------------------------------------------------------------------
# Ring
# --------------------------------------------------------------------------


def test_ring_is_a_pure_function_of_the_replica_ids(tmp_path):
    _, _, a = _rig(tmp_path / "a", n=3)
    _, _, b = _rig(tmp_path / "b", n=3)
    keys = [f"scene{i}" for i in range(64)]
    assert [a.route_key(k) for k in keys] == [
        b.route_key(k) for k in keys
    ]


def test_replica_loss_moves_only_its_own_keys(tmp_path):
    _, reps, router = _rig(tmp_path, n=3)
    keys = [f"scene{i}" for i in range(64)]
    before = {k: router.route_key(k) for k in keys}
    assert len(set(before.values())) > 1  # the ring actually spreads
    reps[1].draining = True
    for k in keys:
        after = router.route_key(k)
        if before[k] == "r1":
            assert after != "r1"
        else:
            assert after == before[k]  # untouched keys keep affinity


def test_fleet_size_formula():
    assert fleet_size(0.0) == 1
    assert fleet_size(KNEE_REQ_S) == 1
    assert fleet_size(KNEE_REQ_S + 0.1) == 2
    assert fleet_size(10 * KNEE_REQ_S) == 10


# --------------------------------------------------------------------------
# Submit: affinity, dedup, edge admission
# --------------------------------------------------------------------------


def test_same_scene_routes_to_the_same_replica(tmp_path):
    _, reps, router = _rig(tmp_path)
    j1 = router.submit(
        compiled=_stub(), resident_key="sceneA", job_id="ja",
    )
    j2 = router.submit(
        compiled=_stub(), resident_key="sceneA", job_id="jb",
    )
    assert router.owner(j1) == router.owner(j2)
    router.drain_fleet()
    assert router.poll(j1)["status"] == DONE
    assert router.poll(j2)["status"] == DONE


def test_double_delivery_returns_existing_assignment(tmp_path):
    _, reps, router = _rig(tmp_path)
    router.submit(compiled=_stub(), resident_key="sceneA", job_id="ja")
    again = router.submit(
        compiled=_stub(), resident_key="sceneA", job_id="ja",
    )
    assert again == "ja"
    # exactly ONE instance exists across the whole fleet
    assert sum(len(r.service.jobs) for r in reps) == 1
    router.drain_fleet()
    # terminal ids stay refused inside the dedup window too
    assert router.poll("ja")["status"] == DONE
    assert (
        router.submit(
            compiled=_stub(), resident_key="sceneA", job_id="ja",
        )
        == "ja"
    )
    assert sum(len(r.service.jobs) for r in reps) == 1


def test_edge_sheds_over_knee_and_recovers_as_the_window_slides(tmp_path):
    clock, _, router = _rig(
        tmp_path, n=2,
        policy=FleetPolicy(knee_req_s=1.0, rate_window_s=1.0),
    )
    admitted, shed = 0, 0
    for i in range(4):  # capacity = 1 req/s x 2 replicas over 1 s
        try:
            router.submit(
                compiled=_stub(), resident_key=f"s{i}", job_id=f"e{i}",
            )
            admitted += 1
        except ShedError as e:
            assert "fleet-edge" in e.reason
            shed += 1
    assert (admitted, shed) == (2, 2)
    assert router.edge_sheds == 2
    clock.advance(1.5)  # the burst leaves the window
    router.submit(compiled=_stub(), resident_key="s9", job_id="e9")
    router.drain_fleet()


# --------------------------------------------------------------------------
# Failover
# --------------------------------------------------------------------------


def test_kill_failover_resumes_from_the_spool(tmp_path):
    _, reps, router = _rig(tmp_path)
    j = router.submit(
        compiled=_stub(chunks=4), resident_key="sceneK", job_id="jk",
        checkpoint_every=1,
    )
    victim = router.owner(j)
    survivor = "r1" if victim == "r0" else "r0"
    while router.poll(j)["chunks_done"] < 2:
        assert router.step() is not None
    at_kill = router.poll(j)["chunks_done"]
    assert router.kill_replica(victim) == [j]
    assert router.owner(j) == survivor
    router.drain_fleet()
    p = router.poll(j)
    assert p["status"] == DONE
    assert p["failovers"] == 1
    # resumed, not restarted: the survivor's instance began at the
    # durable cursor, and the terminal film is bit-identical to the
    # sequential reference schedule
    res = router.replicas[survivor].service.jobs[j].result
    ref = _harness()["reference_state"](4)
    assert np.array_equal(
        np.asarray(res.film_state.rgb), np.asarray(ref.rgb)
    )
    assert at_kill >= 2


def test_drain_failover_cancels_the_old_instance(tmp_path):
    _, reps, router = _rig(tmp_path)
    j = router.submit(
        compiled=_stub(chunks=4), resident_key="sceneD", job_id="jd",
        checkpoint_every=1,
    )
    old = router.owner(j)
    new = "r1" if old == "r0" else "r0"
    router.step()
    assert router.drain_replica(old) == [j]
    assert router.owner(j) == new
    # consume-the-spool dedup: the drained replica's instance is
    # terminal, so only ONE live instance exists fleet-wide
    assert router.replicas[old].status(j) == "cancelled"
    router.drain_fleet()
    assert router.poll(j)["status"] == DONE


# --------------------------------------------------------------------------
# Router restart
# --------------------------------------------------------------------------


def test_adopt_rebuilds_the_table_and_loses_no_job(tmp_path):
    clock, reps, router = _rig(tmp_path)
    j = router.submit(
        compiled=_stub(chunks=3), resident_key="sceneR", job_id="jr",
        checkpoint_every=1,
    )
    router.step()
    router2 = FleetRouter.adopt(
        reps, clock=clock, spool_dir=str(tmp_path / "fleet"),
    )
    assert "jr" in router2.jobs
    assert router2.owner("jr") == router.owner("jr")
    router2.drain_fleet()
    assert router2.poll("jr")["status"] == DONE


def test_adopted_jobs_cannot_fail_over_but_are_not_lost(tmp_path):
    clock, reps, router = _rig(tmp_path)
    router.submit(
        compiled=_stub(chunks=4), resident_key="sceneR", job_id="jr",
        checkpoint_every=1,
    )
    router.step()
    router2 = FleetRouter.adopt(
        reps, clock=clock, spool_dir=str(tmp_path / "fleet"),
    )
    with pytest.raises(RuntimeError, match="submit source"):
        router2._failover_job("jr", router2.owner("jr"))
    router2.drain_fleet()
    assert router2.poll("jr")["status"] == DONE


# --------------------------------------------------------------------------
# Multi-replica load replay
# --------------------------------------------------------------------------


def test_fleet_replay_is_byte_deterministic_and_spreads():
    from tpu_pbrt.load.replay import replay
    from tpu_pbrt.load.workload import SCENARIOS, generate

    wl = generate(SCENARIOS["editstorm"].spec, 7)
    a = replay(wl, replicas=2)
    b = replay(wl, replicas=2)
    assert a.log_text() == b.log_text()
    owners = {
        ln.rsplit("@", 1)[1] for ln in a.log if "-> ok@" in ln
    }
    assert owners == {"r0", "r1"}  # the editstorm key set splits
    assert a.failed == 0 and not a.unfinished
    assert a.completed == a.submitted
    assert not a.pin_leaks


def test_fleet_replay_single_replica_path_untouched():
    from tpu_pbrt.load.replay import replay
    from tpu_pbrt.load.workload import SCENARIOS, generate

    wl = generate(SCENARIOS["steady"].spec, 7)
    assert replay(wl).log_text() == replay(wl, replicas=1).log_text()


# --------------------------------------------------------------------------
# Daemon replica (process spawn + jax import: not tier-1)
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_daemon_replica_roundtrip(tmp_path):
    import time

    from tpu_pbrt.fleet.daemon import DaemonReplica
    from tpu_pbrt.scenes import cornell_box_text

    text = cornell_box_text(res=16, spp=1, integrator="path", maxdepth=2)
    rep = DaemonReplica("d0", spool_dir=str(tmp_path / "d0"), chunk=256)
    try:
        job = rep.submit(text=text, job_id="dj", trace_id="t:dj")
        deadline = time.monotonic() + 240
        while rep.status(job) not in ("done", "failed", None):
            assert time.monotonic() < deadline, "daemon job timed out"
            time.sleep(0.2)
        assert rep.status(job) == "done"
        ans = rep.drain()
        assert ans["ok"] and ans["draining"] and ans["quiescent"]
        assert rep.shutdown() == 0
    finally:
        if rep.proc.poll() is None:
            rep.proc.kill()
