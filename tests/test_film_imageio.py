"""Film/filter/imageio tests (pbrt src/tests/imageio.cpp counterpart +
Film semantics: filter-weighted accumulation, crop windows, splats,
associative merge)."""

import numpy as np
import jax.numpy as jnp
import pytest

from tpu_pbrt.core.film import Film, merge_film
from tpu_pbrt.core.filters import FilterSpec, make_filter
from tpu_pbrt.scene.paramset import ParamSet
from tpu_pbrt.utils import imageio


class TestFilters:
    def test_box(self):
        f = FilterSpec("box", 0.5, 0.5, 0, 0)
        assert float(f.evaluate(jnp.float32(0.2), jnp.float32(-0.3))) == 1.0
        assert float(f.evaluate(jnp.float32(0.6), jnp.float32(0.0))) == 0.0

    def test_triangle(self):
        f = FilterSpec("triangle", 2.0, 2.0, 0, 0)
        assert abs(float(f.evaluate(jnp.float32(0.0), jnp.float32(0.0))) - 4.0) < 1e-6
        assert float(f.evaluate(jnp.float32(2.1), jnp.float32(0.0))) == 0.0

    def test_gaussian_positive_inside(self):
        f = make_filter("gaussian", ParamSet())
        v = float(f.evaluate(jnp.float32(1.0), jnp.float32(1.0)))
        assert v > 0.0
        assert float(f.evaluate(jnp.float32(2.5), jnp.float32(0.0))) == 0.0

    def test_mitchell_partition(self):
        """Mitchell-Netravali sums to ~1 over integer offsets."""
        f = make_filter("mitchell", ParamSet())
        xs = jnp.arange(-2, 3, dtype=jnp.float32)[:, None] + 0.3
        ys = jnp.arange(-2, 3, dtype=jnp.float32)[None, :] - 0.1
        total = float(jnp.sum(f.evaluate(xs / 1.0, ys / 1.0) * 0 + f.evaluate(xs, ys)))
        assert abs(total - 1.0) < 0.05


class TestFilm:
    def test_box_filter_single_pixel(self):
        film = Film(resolution=(8, 8), filt=FilterSpec("box", 0.5, 0.5, 0, 0), filename="")
        st = film.init_state()
        p = jnp.asarray([[3.5, 4.5]])  # center of pixel (3,4)
        st = film.add_samples(st, p, jnp.asarray([[2.0, 4.0, 6.0]]))
        img = film.develop(st)
        assert np.allclose(img[4, 3], [2, 4, 6])
        assert img.sum() == pytest.approx(12.0)

    def test_filter_weight_normalisation(self):
        """Constant-radiance samples develop to the constant regardless of
        filter: sum(w*L)/sum(w) == L."""
        film = Film(resolution=(8, 8), filt=make_filter("gaussian", ParamSet()), filename="")
        st = film.init_state()
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.uniform(0, 8, (512, 2)).astype(np.float32))
        L = jnp.broadcast_to(jnp.asarray([1.5, 1.5, 1.5]), (512, 3))
        st = film.add_samples(st, p, L)
        img = film.develop(st)
        inner = img[2:6, 2:6]
        assert np.allclose(inner, 1.5, atol=1e-4)

    def test_merge_is_addition(self):
        film = Film(resolution=(4, 4), filename="")
        a = film.init_state()
        b = film.init_state()
        p = jnp.asarray([[1.5, 1.5]])
        a = film.add_samples(a, p, jnp.asarray([[1.0, 0.0, 0.0]]))
        b = film.add_samples(b, p, jnp.asarray([[0.0, 1.0, 0.0]]))
        m = merge_film(a, b)
        img = film.develop(m)
        assert np.allclose(img[1, 1], [0.5, 0.5, 0.0])  # averaged by weights

    def test_crop_window(self):
        film = Film(resolution=(8, 8), crop_window=(0.25, 0.75, 0.25, 0.75), filename="")
        x0, x1, y0, y1 = film.cropped_pixel_bounds
        assert (x0, x1, y0, y1) == (2, 6, 2, 6)
        st = film.init_state()
        # sample outside the crop: dropped
        st = film.add_samples(st, jnp.asarray([[0.5, 0.5], [3.5, 3.5]]), jnp.ones((2, 3)))
        img = film.develop(st)
        assert img.shape == (4, 4, 3)
        assert img[1, 1].sum() > 0
        assert float(np.asarray(st.weight)[0, 0]) == 0.0

    def test_splat(self):
        film = Film(resolution=(4, 4), filename="")
        st = film.init_state()
        st = film.add_splats(st, jnp.asarray([[2.2, 1.7]]), jnp.asarray([[3.0, 0.0, 0.0]]))
        img = film.develop(st, splat_scale=0.5)
        assert np.allclose(img[1, 2], [1.5, 0, 0])

    def test_nan_rejected(self):
        film = Film(resolution=(4, 4), filename="")
        st = film.init_state()
        st = film.add_samples(st, jnp.asarray([[1.5, 1.5]]), jnp.asarray([[np.nan, 1.0, 1.0]]))
        img = film.develop(st)
        assert np.isfinite(img).all()
        assert img[1, 1, 1] == 0.0  # whole sample dropped


class TestImageIO:
    @pytest.mark.parametrize("ext", ["exr", "pfm"])
    def test_float_roundtrip(self, tmp_path, ext):
        rng = np.random.default_rng(1)
        img = (rng.uniform(0, 4, (13, 17, 3)) ** 2).astype(np.float32)
        p = str(tmp_path / f"t.{ext}")
        imageio.write_image(p, img)
        back = imageio.read_image(p)
        tol = 2e-3 * img.max() if ext == "exr" else 1e-6  # half-float quantisation
        assert back.shape == img.shape
        assert np.abs(back - img).max() < tol

    def test_exr_float32_exact(self, tmp_path):
        rng = np.random.default_rng(2)
        img = rng.uniform(0, 100, (20, 31, 3)).astype(np.float32)
        p = str(tmp_path / "t32.exr")
        imageio.write_exr(p, img, half=False)
        back = imageio.read_image(p)
        assert np.array_equal(back, img)

    def test_png_roundtrip_8bit(self, tmp_path):
        rng = np.random.default_rng(3)
        img = rng.uniform(0, 1, (9, 11, 3)).astype(np.float32)
        p = str(tmp_path / "t.png")
        imageio.write_image(p, img)
        back = imageio.read_image(p)
        # 8-bit + sRGB roundtrip tolerance
        assert np.abs(back - img).max() < 0.01

    def test_tga_roundtrip(self, tmp_path):
        rng = np.random.default_rng(4)
        img = rng.uniform(0, 1, (6, 7, 3)).astype(np.float32)
        p = str(tmp_path / "t.tga")
        imageio.write_image(p, img)
        back = imageio.read_image(p)
        assert np.abs(back - img).max() < 0.01

    def test_gamma_correct_inverse(self):
        v = np.linspace(0, 1, 64)
        assert np.allclose(imageio.inverse_gamma_correct(imageio.gamma_correct(v)), v, atol=1e-6)


class TestAlignedAccumulation:
    def test_aligned_matches_scatter_path(self):
        # the aligned (scatter-free) fast path must reproduce the general
        # add_samples bit pattern for pixel-major whole-pixel chunks
        rng = np.random.default_rng(7)
        film = Film(resolution=(8, 4), filt=FilterSpec("box", 0.5, 0.5, 0, 0), filename="")
        spp = 4
        npc = film.aligned_chunk_pixels(8 * spp, spp)
        assert npc == 8
        state_a = film.init_state()
        state_b = film.init_state()
        for c in range(4):  # 4 chunks of 8 pixels x 4 spp tile the 32 px
            start_pix = c * 8
            k = np.arange(8 * spp)
            pix = start_pix + k // spp
            px = pix % 8
            py = pix // 8
            jit = rng.random((8 * spp, 2)).astype(np.float32)
            p_film = np.stack([px + jit[:, 0], py + jit[:, 1]], -1)
            L = rng.random((8 * spp, 3)).astype(np.float32)
            wt = rng.random(8 * spp).astype(np.float32)
            state_a = film.add_samples(state_a, jnp.asarray(p_film), jnp.asarray(L), jnp.asarray(wt))
            state_b = film.add_samples_aligned(
                state_b, jnp.int32(start_pix), spp, jnp.asarray(p_film), jnp.asarray(L), jnp.asarray(wt)
            )
        np.testing.assert_allclose(np.asarray(state_a.rgb), np.asarray(state_b.rgb), rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(state_a.weight), np.asarray(state_b.weight), rtol=1e-6, atol=1e-7)

    def test_aligned_gate_rejects_wide_filters_and_crops(self):
        wide = Film(resolution=(8, 4), filt=FilterSpec("gaussian", 2.0, 2.0, 2.0, 0), filename="")
        assert wide.aligned_chunk_pixels(32, 4) == 0
        crop = Film(resolution=(8, 4), filt=FilterSpec("box", 0.5, 0.5, 0, 0), filename="",
                    crop_window=(0.25, 0.75, 0.0, 1.0))
        assert crop.aligned_chunk_pixels(32, 4) == 0
        box = Film(resolution=(8, 4), filt=FilterSpec("box", 0.5, 0.5, 0, 0), filename="")
        assert box.aligned_chunk_pixels(30, 4) == 0  # not whole-pixel
        assert box.aligned_chunk_pixels(12, 4) == 0  # 3 px doesn't tile 32
