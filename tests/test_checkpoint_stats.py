"""Checkpoint/resume bit-compatibility (SURVEY.md §5.4) and the stats
registry report format (§5.1/§5.5)."""

import numpy as np

from tpu_pbrt.parallel.checkpoint import load_checkpoint, save_checkpoint
from tpu_pbrt.scenes import compile_api, make_cornell
from tpu_pbrt.utils.stats import STATS, ProgressReporter, StatsRegistry


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        api = make_cornell(res=16, spp=2, integrator="directlighting", maxdepth=1)
        scene, integ = compile_api(api)
        st = scene.film.init_state()
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, st, 7, 1234)
        st2, nxt, rays, ctr = load_checkpoint(p)
        assert nxt == 7 and rays == 1234 and ctr == {}
        assert np.array_equal(np.asarray(st.rgb), np.asarray(st2.rgb))

    def test_resume_bit_identical(self, tmp_path):
        """A render interrupted at a checkpoint and resumed produces the
        same image as an uninterrupted one (counter-based RNG + idempotent
        chunks)."""
        import os

        os.environ["TPU_PBRT_CHUNK"] = "1024"  # force multiple chunks
        from tpu_pbrt import config

        config.reload()
        try:
            api = make_cornell(res=16, spp=8, integrator="directlighting", maxdepth=2)
            scene, integ = compile_api(api)
            full = integ.render(scene)

            # simulate interruption: checkpoint after every chunk, then
            # resume from the halfway checkpoint
            p = str(tmp_path / "resume.npz")
            api2 = make_cornell(res=16, spp=8, integrator="directlighting", maxdepth=2)
            scene2, integ2 = compile_api(api2)
            integ2.render(scene2, checkpoint_path=p, checkpoint_every=1)
            st, nxt, rays, _ = load_checkpoint(p)
            # rewind the cursor to mid-render and resume
            save_checkpoint(p, scene2.film.init_state(), 0, 0)
            r3 = integ2.render(scene2, checkpoint_path=p, checkpoint_every=1)
            assert np.allclose(full.image, r3.image, atol=1e-6)
        finally:
            del os.environ["TPU_PBRT_CHUNK"]


class TestStats:
    def test_report_format(self):
        reg = StatsRegistry()
        reg.counter("Integrator/Camera rays traced", 100)
        reg.memory_counter("Scene/BVH memory", 3 << 20)
        reg.percent("Intersections/Regular ray intersection tests", 40, 100)
        reg.ratio("Scene/Rays per sample", 30, 10)
        reg.distribution("Integrator/Path length", 3)
        reg.distribution("Integrator/Path length", 5)
        with reg.phase("Accelerator/Intersect"):
            pass
        text = reg.report()
        assert "Statistics:" in text
        assert "Camera rays traced" in text
        assert "3.00 MiB" in text
        assert "(40.00%)" in text
        assert "(3.00x)" in text
        assert "4.000 avg" in text
        assert "Accelerator/Intersect" in text

    def test_global_registry_counts(self):
        STATS.counter("Test/widget", 2)
        STATS.counter("Test/widget", 3)
        assert STATS.counters["Test/widget"] >= 5

    def test_progress_quiet(self):
        p = ProgressReporter(10, "t", quiet=True)
        for _ in range(10):
            p.update()
        p.done()


class TestCheckpointFingerprint:
    def test_mismatched_config_rejected(self, tmp_path):
        """A checkpoint written under one (chunk, spp, scene) configuration
        must refuse to resume under another instead of silently corrupting
        the image (ADVICE r1)."""
        import jax.numpy as jnp
        import pytest

        from tpu_pbrt.core.film import FilmState

        st = FilmState(
            rgb=jnp.zeros((4, 4, 3)), weight=jnp.zeros((4, 4)), splat=jnp.zeros((4, 4, 3))
        )
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, st, 3, 100, fingerprint="chunk=1024;spp=8")
        # same fingerprint resumes
        _, nxt, rays, _ = load_checkpoint(p, "chunk=1024;spp=8")
        assert (nxt, rays) == (3, 100)
        # different fingerprint is refused
        with pytest.raises(ValueError, match="different render configuration"):
            load_checkpoint(p, "chunk=2048;spp=8")


class TestCheckpointCounters:
    """ISSUE 4 satellite: the cumulative telemetry-counter snapshot is a
    versioned checkpoint field, so a resumed render reports end-to-end
    totals."""

    def _tiny_state(self):
        import jax.numpy as jnp

        from tpu_pbrt.core.film import FilmState

        return FilmState(
            rgb=jnp.zeros((4, 4, 3)), weight=jnp.zeros((4, 4)),
            splat=jnp.zeros((4, 4, 3)),
        )

    def test_counter_snapshot_roundtrip(self, tmp_path):
        snap = {
            "rays_traced": 4912, "lanes_regenerated": 1024,
            "occupancy_histogram": [0, 1, 2, 3, 0, 0, 0, 4],
        }
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._tiny_state(), 2, 99, counters=snap)
        _, nxt, rays, ctr = load_checkpoint(p)
        assert (nxt, rays) == (2, 99)
        assert ctr == snap

    def test_v2_checkpoint_loads_without_counters(self, tmp_path):
        """A pre-telemetry (v2) file — no counters field — still resumes,
        with an empty snapshot."""
        st = self._tiny_state()
        p = str(tmp_path / "old.npz")
        np.savez_compressed(
            p, version=2, rgb=np.asarray(st.rgb),
            weight=np.asarray(st.weight), splat=np.asarray(st.splat),
            next_chunk=5, rays=777, fingerprint=np.array(""),
        )
        st2, nxt, rays, ctr = load_checkpoint(p)
        assert (nxt, rays, ctr) == (5, 777, {})

    def test_resumed_render_reports_end_to_end_totals(self, tmp_path):
        """Resume a FINISHED pool render from its checkpoint: zero new
        chunks run, yet the reported telemetry counters are the full
        render's totals (seeded from the snapshot)."""
        import os

        from tpu_pbrt.scenes import compile_api, make_cornell

        os.environ["TPU_PBRT_CHUNK"] = "1024"  # force multiple chunks
        from tpu_pbrt import config

        config.reload()
        try:
            api = make_cornell(res=16, spp=8, integrator="path", maxdepth=2)
            scene, integ = compile_api(api)
            p = str(tmp_path / "pool.npz")
            full = integ.render(scene, checkpoint_path=p, checkpoint_every=1)
            totals = full.stats["telemetry"]["counters"]
            assert totals["rays_traced"] == full.rays_traced > 0
            resumed = integ.render(
                scene, checkpoint_path=p, checkpoint_every=1
            )
            assert resumed.stats["telemetry"]["counters"] == totals
            # a telemetry-OFF resume must not report the saved snapshot
            # as this render's totals (it covers none of this process's
            # work) — but the checkpoint keeps carrying it forward so a
            # later telemetry-on resume still reports true totals
            os.environ["TPU_PBRT_TELEMETRY"] = "0"
            config.reload()
            off = integ.render(scene, checkpoint_path=p, checkpoint_every=1)
            assert "telemetry" not in off.stats
            _, _, _, ctr = load_checkpoint(p)
            assert ctr == totals
        finally:
            del os.environ["TPU_PBRT_CHUNK"]
            os.environ.pop("TPU_PBRT_TELEMETRY", None)
