"""BDPT cross-convergence tests (VERDICT r3 #4: bdpt mean ~= path mean
within noise on the cornell box — the upstream ecosystem's integrator
cross-check, mirroring pbrt's analytic-scenes strategy)."""

import numpy as np

from tpu_pbrt.scenes import compile_api, make_cornell


def _render(integrator, md, spp=64, res=20, only=None):
    api = make_cornell(res=res, spp=spp, integrator=integrator, maxdepth=md)
    scene, integ = compile_api(api)
    if only is not None:
        integ._only = only
    return np.asarray(integ.render(scene).image)


def test_bdpt_matches_path_direct():
    """maxdepth=1: bdpt's (0,2)+(1,2)+(2,1) strategies must reproduce
    direct lighting exactly (the MIS weights must partition each path
    family, not double count it)."""
    p = _render("path", 1)
    b = _render("bdpt", 1)
    rel = abs(b.mean() - p.mean()) / p.mean()
    assert rel < 0.05, f"bdpt {b.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"


def test_bdpt_matches_path_indirect():
    """maxdepth=3: full strategy matrix incl. s>=2 connections and
    light-tracing splats."""
    p = _render("path", 3)
    b = _render("bdpt", 3)
    rel = abs(b.mean() - p.mean()) / p.mean()
    assert rel < 0.05, f"bdpt {b.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"
    # per-channel agreement too (catches color-channel MIS asymmetries)
    pc, bc = p.mean(axis=(0, 1)), b.mean(axis=(0, 1))
    np.testing.assert_allclose(bc, pc, rtol=0.08)


def test_bdpt_light_tracing_splats_land():
    """The t=1 family renders through Film::AddSplat: restricted to the
    (2,1) strategy the image must be non-zero and concentrated where the
    directly lit geometry is."""
    img = _render("bdpt", 2, only={(2, 1)})
    assert img.mean() > 1e-3, "light-tracing splats produced a black image"
    assert np.isfinite(img).all()


def _render_env_scene(integrator, md=3, spp=96, res=16):
    """Envmap-lit scene with a glass blocker (VERDICT r4 #10's
    done-criterion shape): infinite-light subpaths must participate."""
    import os
    import tempfile

    import tpu_pbrt
    from tpu_pbrt.scenes import _crown_envmap_path

    env = _crown_envmap_path()
    scene = f"""
Integrator "{integrator}" "integer maxdepth" [{md}]
Sampler "zerotwosequence" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}]
LookAt 0 1 -4  0 0.5 0  0 1 0
Camera "perspective" "float fov" [45]
WorldBegin
LightSource "infinite" "string mapname" ["{env}"]
Material "matte" "rgb Kd" [0.6 0.55 0.5]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
  "point P" [-5 0 -5  5 0 -5  5 0 5  -5 0 5]
Material "glass" "float eta" [1.5]
AttributeBegin
  Translate 0 0.8 0
  Shape "sphere" "float radius" [0.6]
AttributeEnd
WorldEnd
"""
    with tempfile.NamedTemporaryFile("w", suffix=".pbrt", delete=False) as f:
        f.write(scene)
        path = f.name
    try:
        return np.asarray(tpu_pbrt.render_file(path).image)
    finally:
        os.unlink(path)


def test_bdpt_envmap_scene_matches_path():
    """Envmap-lit glass scene: bdpt (env via weight-1 escaped camera
    rays, all other strategies from surface bounces) must cross-converge
    with path — guards the env MIS contract documented in bdpt.py."""
    p = _render_env_scene("path")
    b = _render_env_scene("bdpt")
    assert np.isfinite(b).all()
    rel = abs(b.mean() - p.mean()) / p.mean()
    assert rel < 0.08, f"bdpt {b.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"


def _render_distant_scene(integrator, md=3, spp=96, res=16):
    import os
    import tempfile

    import tpu_pbrt

    scene = f"""
Integrator "{integrator}" "integer maxdepth" [{md}]
Sampler "zerotwosequence" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}]
LookAt 0 1 -4  0 0.5 0  0 1 0
Camera "perspective" "float fov" [45]
WorldBegin
LightSource "distant" "rgb L" [3 3 2.6] "point from" [2 5 -2] "point to" [0 0 0]
Material "matte" "rgb Kd" [0.6 0.55 0.5]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
  "point P" [-5 0 -5  5 0 -5  5 0 5  -5 0 5]
Material "plastic" "rgb Kd" [0.3 0.1 0.1] "rgb Ks" [0.4 0.4 0.4]
AttributeBegin
  Translate 0 0.8 0
  Shape "sphere" "float radius" [0.6]
AttributeEnd
WorldEnd
"""
    with tempfile.NamedTemporaryFile("w", suffix=".pbrt", delete=False) as f:
        f.write(scene)
        path = f.name
    try:
        return np.asarray(tpu_pbrt.render_file(path).image)
    finally:
        os.unlink(path)


def test_bdpt_distant_subpaths_match_path():
    """VERDICT r4 #10: distant lights source full light subpaths with
    pbrt's planar-beam (infinite-light) densities; all strategies must
    MIS-partition and cross-converge with path."""
    p = _render_distant_scene("path")
    b = _render_distant_scene("bdpt")
    assert np.isfinite(b).all()
    rel = abs(b.mean() - p.mean()) / p.mean()
    assert rel < 0.08, f"bdpt {b.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"
