"""BDPT cross-convergence tests (VERDICT r3 #4: bdpt mean ~= path mean
within noise on the cornell box — the upstream ecosystem's integrator
cross-check, mirroring pbrt's analytic-scenes strategy)."""

import numpy as np

from tpu_pbrt.scenes import compile_api, make_cornell


def _render(integrator, md, spp=64, res=20, only=None):
    api = make_cornell(res=res, spp=spp, integrator=integrator, maxdepth=md)
    scene, integ = compile_api(api)
    if only is not None:
        integ._only = only
    return np.asarray(integ.render(scene).image)


def test_bdpt_matches_path_direct():
    """maxdepth=1: bdpt's (0,2)+(1,2)+(2,1) strategies must reproduce
    direct lighting exactly (the MIS weights must partition each path
    family, not double count it)."""
    p = _render("path", 1)
    b = _render("bdpt", 1)
    rel = abs(b.mean() - p.mean()) / p.mean()
    assert rel < 0.05, f"bdpt {b.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"


def test_bdpt_matches_path_indirect():
    """maxdepth=3: full strategy matrix incl. s>=2 connections and
    light-tracing splats."""
    p = _render("path", 3)
    b = _render("bdpt", 3)
    rel = abs(b.mean() - p.mean()) / p.mean()
    assert rel < 0.05, f"bdpt {b.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"
    # per-channel agreement too (catches color-channel MIS asymmetries)
    pc, bc = p.mean(axis=(0, 1)), b.mean(axis=(0, 1))
    np.testing.assert_allclose(bc, pc, rtol=0.08)


def test_bdpt_light_tracing_splats_land():
    """The t=1 family renders through Film::AddSplat: restricted to the
    (2,1) strategy the image must be non-zero and concentrated where the
    directly lit geometry is."""
    img = _render("bdpt", 2, only={(2, 1)})
    assert img.mean() > 1e-3, "light-tracing splats produced a black image"
    assert np.isfinite(img).all()
