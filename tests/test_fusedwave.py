"""Fused Pallas wavefront kernel (accel/fusedwave.py, ISSUE 9): the
TPU_PBRT_FUSED=1 flush/expand programs must be BIT-identical to the jnp
stream tracer — same EDGE_EPS band, same argmin tiebreak, same
_finalize_hits contract — with the kernels running in Pallas interpret
mode on CPU (the sequential grid semantics the TPU also guarantees).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_pbrt import config
from tpu_pbrt.accel import build as bvh_build
from tpu_pbrt.accel.treelet import build_treelet_pack


def _random_tris(n, rng, scale=0.25):
    c = rng.uniform(-2, 2, (n, 1, 3))
    return (c + rng.uniform(-scale, scale, (n, 3, 3))).astype(np.float32)


def _random_rays(n, rng):
    o = rng.uniform(-4, 4, (n, 3)).astype(np.float32)
    d = rng.normal(size=(n, 3)).astype(np.float32)
    d /= np.linalg.norm(d, axis=-1, keepdims=True)
    return jnp.asarray(o), jnp.asarray(d)


def _clear_stream_caches():
    """The stream tracer's module-level jits cache by aval shape only;
    every TPU_PBRT_FUSED flip must drop them (same seam the render
    loop's jit-key guard and audit.forced_tracer use)."""
    from tpu_pbrt.accel.stream import clear_traverse_caches

    clear_traverse_caches()


def _set_fused(monkeypatch, on: bool, **env):
    monkeypatch.setenv("TPU_PBRT_FUSED", "1" if on else "0")
    for k, v in env.items():
        monkeypatch.setenv(k, str(v))
    config.reload()
    _clear_stream_caches()


def _pack(n_tris=6000, seed=31, leaf_tris=None):
    from tpu_pbrt.accel.stream import STREAM_LEAF_TRIS

    rng = np.random.default_rng(seed)
    tris = _random_tris(n_tris, rng)
    bvh = bvh_build.build_bvh(
        *bvh_build.triangle_bounds(tris), method="sah"
    )
    tris_perm = tris[bvh.prim_order]
    tp = build_treelet_pack(
        tris_perm, bvh, leaf_tris=leaf_tris or STREAM_LEAF_TRIS
    )
    return tp, jnp.asarray(tris_perm), rng


def _both_modes(monkeypatch, fn, **env):
    """Run fn() under TPU_PBRT_FUSED=0 then =1; return both results."""
    _set_fused(monkeypatch, False, **env)
    a = fn()
    _set_fused(monkeypatch, True, **env)
    b = fn()
    _clear_stream_caches()
    return a, b


def _assert_hits_identical(h0, h1):
    t0, t1 = np.asarray(h0.t), np.asarray(h1.t)
    np.testing.assert_array_equal(t0.view(np.int32), t1.view(np.int32))
    np.testing.assert_array_equal(np.asarray(h0.prim), np.asarray(h1.prim))
    np.testing.assert_array_equal(np.asarray(h0.b0), np.asarray(h1.b0))
    np.testing.assert_array_equal(np.asarray(h0.b1), np.asarray(h1.b1))


# ---------------------------------------------------------------------------
# interpret-mode bit-identity vs the jnp stream tracer
# ---------------------------------------------------------------------------


def test_fused_bit_identity_closest_and_any_hit(monkeypatch):
    tp, tv, rng = _pack()
    o, d = _random_rays(600, rng)

    def run():
        import tpu_pbrt.accel.stream as st

        h = st.stream_intersect(tp, tv, o, d, 1e30)
        p = st.stream_intersect_p(tp, o, d, 1e30)
        stats = st.stream_traverse_stats(tp, o, d, 1e30)
        return h, np.asarray(p), [int(x) for x in stats]

    (h0, p0, s0), (h1, p1, s1) = _both_modes(monkeypatch, run)
    assert np.isfinite(np.asarray(h0.t)).sum() > 50  # the test bites
    _assert_hits_identical(h0, h1)
    np.testing.assert_array_equal(p0, p1)
    assert s0 == s1  # (n_exp, n_tl, n_drop, iters) — incl. n_drop == 0
    assert s0[2] == 0


def test_fused_bit_identity_onehot_off(monkeypatch):
    """The fused EXPAND kernel's native-take child fetch (big-top-tree
    mode) must match the jnp gather path bit-for-bit."""
    tp, tv, rng = _pack(n_tris=4000, seed=5)
    o, d = _random_rays(400, rng)

    def run():
        import tpu_pbrt.accel.stream as st

        return st.stream_intersect(tp, tv, o, d, 1e30)

    h0, h1 = _both_modes(monkeypatch, run, TPU_PBRT_ONEHOT="0")
    _assert_hits_identical(h0, h1)


def test_fused_bit_identity_motion(monkeypatch):
    """Motion packs (64-row cubic-in-time features, rayF row 7 carrying
    the shutter time) ride the fused flush kernel too."""
    rng = np.random.default_rng(7)
    tris = _random_tris(2000, rng)
    tris1 = tris + rng.uniform(-0.05, 0.05, tris.shape).astype(np.float32)
    bm = np.minimum(tris.min(axis=1), tris1.min(axis=1))
    bM = np.maximum(tris.max(axis=1), tris1.max(axis=1))
    bvh = bvh_build.build_bvh(bm, bM, method="sah")
    tp = build_treelet_pack(
        tris[bvh.prim_order], bvh, leaf_tris=256,
        tri_verts1=tris1[bvh.prim_order],
    )
    assert tp.n_features == 64
    o, d = _random_rays(256, rng)
    tm = jnp.asarray(rng.uniform(0, 1, 256).astype(np.float32))
    tv0 = jnp.asarray(tris[bvh.prim_order])
    tv1 = jnp.asarray(tris1[bvh.prim_order])

    def run():
        import tpu_pbrt.accel.stream as st

        return st.stream_intersect(
            tp, tv0, o, d, 1e30, time=tm, tri_verts1=tv1
        )

    h0, h1 = _both_modes(monkeypatch, run)
    assert np.isfinite(np.asarray(h0.t)).sum() > 20
    _assert_hits_identical(h0, h1)


def test_fused_winner_tiebreak_lower_local_index(monkeypatch):
    """Two coincident triangles produce EXACTLY equal t: the winner must
    be the lower leaf-order index, in both tracer modes (the pinned
    argmin/merge tiebreak)."""
    tri = np.asarray(
        [[[0.0, -1, -1], [0, 1, -1], [0, 0, 1]]], np.float32
    )
    # several distinct triangles + an exact duplicate pair
    rng = np.random.default_rng(3)
    filler = _random_tris(40, rng) + np.asarray([8.0, 0, 0])
    tris = np.concatenate([tri, tri, filler]).astype(np.float32)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris))
    tris_perm = tris[bvh.prim_order]
    # one treelet holds everything (42 <= 64), so local index == leaf
    # order and the pinned tiebreak is exactly "lower leaf-order id"
    tp = build_treelet_pack(tris_perm, bvh, leaf_tris=64)
    assert tp.n_treelets == 1
    # the duplicates' leaf-order positions
    dup = sorted(int(np.where(bvh.prim_order == i)[0][0]) for i in (0, 1))
    o = jnp.asarray([[-5.0, 0, 0]])
    d = jnp.asarray([[1.0, 0, 0]])

    def run():
        import tpu_pbrt.accel.stream as st

        return st.stream_intersect(tp, jnp.asarray(tris_perm), o, d, 1e30)

    h0, h1 = _both_modes(monkeypatch, run)
    _assert_hits_identical(h0, h1)
    assert int(np.asarray(h0.prim)[0]) == dup[0]


def test_fused_empty_flush_and_dead_waves(monkeypatch):
    """Rays that (a) miss the whole scene and (b) are dead on arrival
    (t_max <= 0): the fused drain flush runs over an EMPTY leaf buffer
    (n_blocks == 0 -> zero kernel invocations) and must still agree."""
    tp, tv, rng = _pack(n_tris=1200, seed=11)
    R = 200
    o = jnp.full((R, 3), 50.0, jnp.float32)  # far outside the scene
    d = jnp.tile(jnp.asarray([1.0, 0.0, 0.0], jnp.float32), (R, 1))

    def run_miss():
        import tpu_pbrt.accel.stream as st

        return st.stream_intersect(tp, tv, o, d, 1e30)

    h0, h1 = _both_modes(monkeypatch, run_miss)
    assert (np.asarray(h0.prim) == -1).all()
    _assert_hits_identical(h0, h1)

    def run_dead():
        import tpu_pbrt.accel.stream as st

        return st.stream_intersect(tp, tv, o, d, -1.0)

    h0, h1 = _both_modes(monkeypatch, run_dead)
    assert (np.asarray(h0.prim) == -1).all()
    _assert_hits_identical(h0, h1)


def test_fused_burst_wave_small_slab(monkeypatch):
    """A small TPU_PBRT_SLAB forces the leaf buffer to cross the flush
    threshold repeatedly (multiple mid-wave flushes, the burst-wave
    shape): the fused path must stay bit-identical and drop nothing."""
    tp, tv, rng = _pack(n_tris=9000, seed=13, leaf_tris=128)
    o, d = _random_rays(4096, rng)

    def run():
        import tpu_pbrt.accel.stream as st

        h = st.stream_intersect(tp, tv, o, d, 1e30)
        stats = st.stream_traverse_stats(tp, o, d, 1e30)
        return h, [int(x) for x in stats]

    (h0, s0), (h1, s1) = _both_modes(
        monkeypatch, run, TPU_PBRT_SLAB="4096"
    )
    assert s0[3] > 3  # several expand/flush iterations actually ran
    assert s0 == s1 and s0[2] == 0
    _assert_hits_identical(h0, h1)


# ---------------------------------------------------------------------------
# integrator-level pin: pool_chunk renders bit-identical under FUSED=0/1
# ---------------------------------------------------------------------------


def test_fused_pool_chunk_bit_identity(monkeypatch):
    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    def run():
        api = make_killeroo_like(
            res=16, spp=2, integrator="path", maxdepth=3,
            n_theta=24, n_phi=48,
        )
        scene, integ = compile_api(api)
        film = scene.film
        out = integ.pool_chunk(
            scene.dev, film.init_state(), jnp.int32(0), jnp.int32(0),
            256, 64, film=film, cam=scene.camera,
        )
        fs, nrays = out[0], out[1]
        return (
            [np.asarray(x) for x in jax.tree_util.tree_leaves(fs)],
            int(nrays),
        )

    (f0, r0), (f1, r1) = _both_modes(monkeypatch, run)
    assert r0 == r1 and r0 > 0
    for a, b in zip(f0, f1):
        np.testing.assert_array_equal(a, b)


def test_fused_render_reports_tracer_mode(monkeypatch):
    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    _set_fused(monkeypatch, True)
    api = make_killeroo_like(
        res=12, spp=1, integrator="path", maxdepth=2,
        n_theta=24, n_phi=48,
    )
    scene, integ = compile_api(api)
    res = integ.render(scene)
    assert res.stats.get("tracer_mode") == "fused"
    _clear_stream_caches()


# ---------------------------------------------------------------------------
# gates, fallbacks, deprecation
# ---------------------------------------------------------------------------


def test_fused_gates_and_escape_hatches(monkeypatch):
    from tpu_pbrt.accel import stream as st

    # explicit on (CPU -> interpret), explicit off, VMEM ray cap,
    # and the global TPU_PBRT_PALLAS=0 escape hatch
    monkeypatch.setenv("TPU_PBRT_FUSED", "1")
    config.reload()
    assert st.tracer_mode(1 << 10) == "fused"
    assert st.tracer_mode(1 << 19) == "jnp"  # past FUSED_MAX_RAYS
    monkeypatch.setenv("TPU_PBRT_FUSED_MAX_RAYS", str(1 << 20))
    config.reload()
    assert st.tracer_mode(1 << 19) == "fused"
    monkeypatch.setenv("TPU_PBRT_PALLAS", "0")
    config.reload()
    assert st.tracer_mode(1 << 10) == "jnp"
    monkeypatch.delenv("TPU_PBRT_PALLAS")
    monkeypatch.setenv("TPU_PBRT_FUSED", "0")
    config.reload()
    assert st.tracer_mode(1 << 10) == "jnp"
    # unset = auto: off on the CPU backend the suite runs under
    monkeypatch.delenv("TPU_PBRT_FUSED")
    config.reload()
    assert st.tracer_mode(1 << 10) == "jnp"
    # geometry helper carries the attribution fields bench.py records
    geo = st.flush_geometry(1 << 16, 64)
    assert geo["blocks_per_flush"] > 0 and geo["tracer_mode"] == "jnp"


def test_prefetch_knob_deprecated_aliases_to_fused(monkeypatch):
    monkeypatch.setenv("TPU_PBRT_PREFETCH", "1")
    with pytest.warns(DeprecationWarning, match="TPU_PBRT_PREFETCH"):
        config.reload()
    assert config.cfg.fused is True
    # an explicit TPU_PBRT_FUSED wins over the alias
    monkeypatch.setenv("TPU_PBRT_FUSED", "0")
    with pytest.warns(DeprecationWarning):
        config.reload()
    assert config.cfg.fused is False


def test_budget_pins_fused_flush_hbm_3x_below_jnp():
    """ISSUE 9 acceptance: the committed static budgets must show the
    fused flush path at least 3x below the jnp flush path in HBM bytes
    per wave (the real margin is orders of magnitude — the jnp path's
    materialized phi/feature/matmul intermediates never exist)."""
    from tpu_pbrt.analysis.cost import load_budgets

    e = load_budgets()["entries"]
    assert "stream_intersect_fused" in e and "pool_chunk_fused" in e
    assert (
        e["stream_intersect"]["hbm_bytes"]
        >= 3 * e["stream_intersect_fused"]["hbm_bytes"]
    )
    assert (
        e["pool_chunk"]["hbm_bytes"]
        >= 3 * e["pool_chunk_fused"]["hbm_bytes"]
    )
