"""RealisticCamera (VERDICT r4 #6): lens-element tracing, autofocus,
exit-pupil tables — realistic.cpp capability. Oracles are first
principles: the lensmaker/thin-lens equation bounds the focused film
distance, the device tracer must agree with the host tracer bit-for-
float, and an end-to-end render through the element stack must image
the scene the proxy perspective camera sees."""

import numpy as np
import jax.numpy as jnp

from tpu_pbrt.cameras.realistic import (
    _focus,
    _stack_from_rows,
    _trace_np,
    apply_aperture_diameter,
    builtin_doublet,
    compile_lens,
    sample_pupil,
    trace_lenses,
)


def test_aperturediameter_rescales_stop_row():
    """realistic.cpp: "aperturediameter" overwrites the aperture-stop
    element's diameter when it stops the lens down, and is clamped (with
    the prescription winning) when it exceeds the stop's physical bound.
    Glass-surface rows are never touched."""
    rows = builtin_doublet(focal=0.050, ap_diam=0.010)  # stop row diam 0.010
    out = apply_aperture_diameter(rows, 0.004)
    stop = rows[:, 0] == 0.0
    assert (out[stop, 3] == 0.004).all(), out[stop, 3]
    assert (out[~stop, 3] == rows[~stop, 3]).all()
    # larger than the stop: prescription wins
    out2 = apply_aperture_diameter(rows, 0.05)
    assert (out2[:, 3] == rows[:, 3]).all()


def test_autofocus_matches_thin_lens_equation():
    """The built-in singlet has focal length 50 mm by construction
    (lensmaker). Focusing at 1 m must put the film near the thin-lens
    conjugate: 1/si = 1/f - 1/so. Thick-lens corrections for the 6 mm
    element are a few percent."""
    rows = builtin_doublet(focal=0.050, ap_diam=0.010)
    stack = _stack_from_rows(rows)
    focus_dist = 1.0
    film_dist = _focus(stack, focus_dist)
    # the singlet's rear vertex sits (0.004 + 0.010) m in front of the
    # stop; film_dist is film->rear-SURFACE-OF-STACK (the stop). Lens
    # center z = film_dist + z_off of the glass surfaces.
    lens_z = film_dist + 0.5 * (stack["z_off"][1] + stack["z_off"][2])
    so = focus_dist - lens_z
    si_thin = 1.0 / (1.0 / 0.050 - 1.0 / so)
    si_actual = lens_z
    assert abs(si_actual - si_thin) / si_thin < 0.08, (si_actual, si_thin)


def test_device_tracer_matches_host_tracer():
    rows = builtin_doublet()
    stack = _stack_from_rows(rows)
    film_dist = _focus(stack, 2.0)
    lens = compile_lens(rows, 2.0, 0.035)
    rng = np.random.default_rng(3)
    n = 256
    o = np.zeros((n, 3))
    o[:, 0] = rng.uniform(-0.01, 0.01, n)
    o[:, 1] = rng.uniform(-0.01, 0.01, n)
    tgt = np.stack(
        [rng.uniform(-0.008, 0.008, n), rng.uniform(-0.008, 0.008, n),
         np.full(n, film_dist)], axis=1,
    )
    d = tgt - o
    d /= np.linalg.norm(d, axis=1, keepdims=True)
    ok_h, o_h, d_h = _trace_np(stack, film_dist, o, d)
    ok_d, o_d, d_d = trace_lenses(
        lens, jnp.asarray(o, jnp.float32), jnp.asarray(d, jnp.float32)
    )
    ok_d = np.asarray(ok_d)
    assert (ok_d == ok_h).mean() > 0.98  # f32 vs f64 edge flips only
    both = ok_d & ok_h
    assert both.any()
    np.testing.assert_allclose(
        np.asarray(o_d)[both], o_h[both], atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(d_d)[both], d_h[both], atol=1e-3
    )


def test_exit_pupil_rays_pass():
    """Pupil-sampled rays from the film center must overwhelmingly make
    it through the stack (the bounds bracket the true pupil), and the
    pupil must shrink the sampled box vs the naive rear-aperture square."""
    lens = compile_lens(builtin_doublet(ap_diam=0.008), 2.0, 0.035)
    n = 512
    rng = np.random.default_rng(11)
    u = jnp.asarray(rng.uniform(0.02, 0.98, (n, 2)), jnp.float32)
    pf = jnp.zeros((n, 3), jnp.float32)
    p_rear, area = sample_pupil(lens, pf, u)
    d = (p_rear - pf)
    d = d / jnp.linalg.norm(d, axis=-1, keepdims=True)
    ok, _, _ = trace_lenses(lens, pf, d)
    frac = float(np.asarray(ok).mean())
    assert frac > 0.5, f"only {frac:.0%} of pupil samples pass the lens"
    # the stop is 8 mm; the pupil box must not be wildly larger
    a0 = float(np.asarray(area)[0])
    assert a0 < (0.02) ** 2, a0


def test_realistic_render_end_to_end():
    """A lit quad renders through the element stack: non-black, and the
    image mean is in the same regime as the thin-lens proxy render
    (exposure normalization keeps metering comparable)."""
    from tests.test_render import QUAD, render_scene

    def scene(cam):
        return f'''
Integrator "path" "integer maxdepth" [2]
Sampler "random" "integer pixelsamples" [8]
PixelFilter "box"
Film "image" "integer xresolution" [32] "integer yresolution" [32] "string filename" [""]
LookAt 0 0 -2  0 0 0  0 1 0
{cam}
WorldBegin
LightSource "infinite" "rgb L" [1 1 1]
Material "matte" "rgb Kd" [0.6 0.6 0.6]
Shape "trianglemesh" {QUAD}
  "point P" [-5 -5 1  5 -5 1  5 5 1  -5 5 1]
WorldEnd
'''

    real = render_scene(
        scene('Camera "realistic" "float focusdistance" [2.0] '
              '"float aperturediameter" [4.0]')
    )
    img = np.asarray(real.image)
    assert img.mean() > 0.05, "realistic render is black"
    persp = render_scene(scene('Camera "perspective" "float fov" [40]'))
    ratio = img.mean() / max(np.asarray(persp.image).mean(), 1e-9)
    assert 0.3 < ratio < 3.0, f"exposure ratio {ratio}"
