"""True MixMaterial (VERDICT r4 #7): stochastic one-sample resolution
of the scaled BSDF union (mixmat.cpp). Oracle: Lambertian f is LINEAR
in Kd, so mix(matte(kd1), matte(kd2), a) must converge to the SAME
image as matte(a*kd1 + (1-a)*kd2) — an exact cross-render identity, no
golden image needed."""

import numpy as np

from tests.test_render import QUAD, render_scene, scene_header

_PLANE = f'''
Shape "trianglemesh" {QUAD}
  "point P" [-20 -1 -20  20 -1 -20  20 -1 20  -20 -1 20]
'''


def _mix_scene(spp=64):
    return (
        scene_header("path", spp=spp, extra='"integer maxdepth" [2]')
        + '''
WorldBegin
LightSource "infinite" "rgb L" [1.0 1.0 1.0]
MakeNamedMaterial "red" "string type" ["matte"] "rgb Kd" [0.8 0.1 0.1]
MakeNamedMaterial "blue" "string type" ["matte"] "rgb Kd" [0.1 0.1 0.7]
Material "mix" "string namedmaterial1" ["red"]
  "string namedmaterial2" ["blue"] "rgb amount" [0.3 0.3 0.3]
'''
        + _PLANE
        + "WorldEnd\n"
    )


def _blend_scene(spp=64):
    # 0.3*red + 0.7*blue   (amount weights material1)
    kd = 0.3 * np.array([0.8, 0.1, 0.1]) + 0.7 * np.array([0.1, 0.1, 0.7])
    return (
        scene_header("path", spp=spp, extra='"integer maxdepth" [2]')
        + f'''
WorldBegin
LightSource "infinite" "rgb L" [1.0 1.0 1.0]
Material "matte" "rgb Kd" [{kd[0]} {kd[1]} {kd[2]}]
'''
        + _PLANE
        + "WorldEnd\n"
    )


def test_mix_matches_linear_blend_of_mattes():
    a = np.asarray(render_scene(_mix_scene()).image)
    b = np.asarray(render_scene(_blend_scene()).image)
    # the floor fills the lower image half; compare there (sky rows are
    # identical constants in both renders)
    fa, fb = a[20:, :], b[20:, :]
    assert abs(fa.mean() - fb.mean()) < 0.01, (fa.mean(), fb.mean())
    # per-pixel agreement within MC noise of the stochastic selection
    assert np.abs(fa - fb).mean() < 0.05


def test_mix_sub_materials_both_present():
    """amount=1 must reproduce material1 exactly; amount=0 material2 —
    the selection degenerates to deterministic (no noise penalty)."""
    def scene(amount):
        return (
            scene_header("path", spp=16, extra='"integer maxdepth" [2]')
            + f'''
WorldBegin
LightSource "infinite" "rgb L" [1.0 1.0 1.0]
MakeNamedMaterial "red" "string type" ["matte"] "rgb Kd" [0.8 0.1 0.1]
MakeNamedMaterial "blue" "string type" ["matte"] "rgb Kd" [0.1 0.1 0.7]
Material "mix" "string namedmaterial1" ["red"]
  "string namedmaterial2" ["blue"] "rgb amount" [{amount} {amount} {amount}]
'''
            + _PLANE
            + "WorldEnd\n"
        )

    img1 = np.asarray(render_scene(scene(1.0)).image)[20:, :]
    img0 = np.asarray(render_scene(scene(0.0)).image)[20:, :]
    # material1 = red-dominant, material2 = blue-dominant
    assert img1[..., 0].mean() > 2.0 * img1[..., 2].mean()
    assert img0[..., 2].mean() > 2.0 * img0[..., 0].mean()
