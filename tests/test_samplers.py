"""Sampler plugin tests (VERDICT r3 #7): the scene file's Sampler
directive must select a real stream structure, and the low-discrepancy
samplers must beat the random sampler at equal spp."""

import numpy as np
import jax.numpy as jnp

from tpu_pbrt.core.sampling import (
    PRIMES,
    normalize_sampler_name,
    radical_inverse_prime,
    sample_1d,
    sample_2d,
)


def test_radical_inverse_base3_values():
    n = jnp.asarray([0, 1, 2, 3, 4, 9], jnp.uint32)
    out = np.asarray(radical_inverse_prime(3, n))
    np.testing.assert_allclose(
        out, [0.0, 1 / 3, 2 / 3, 1 / 9, 1 / 9 + 1 / 3, 1 / 27], atol=1e-6
    )


def test_scrambled_radical_inverse_is_permutation():
    """The digit scramble must keep the first b^2 points distinct and
    stratified (a permutation of the base-b digit grid)."""
    for base in (3, 5):
        n = jnp.arange(base * base, dtype=jnp.uint32)
        out = np.asarray(radical_inverse_prime(base, n, scramble_seed=12345))
        # all distinct
        assert len(np.unique(np.round(out * base * base).astype(int))) == base * base
        # one point in each of the b^2 strata
        strata = np.floor(out * base * base).astype(int)
        assert sorted(strata) == list(range(base * base))


def _mean_rms(kind, spp, n_pix=256, dim=11):
    px = jnp.arange(n_pix, dtype=jnp.int32) % 16
    py = jnp.arange(n_pix, dtype=jnp.int32) // 16
    acc = np.zeros(n_pix)
    for s in range(spp):
        u = sample_1d(kind, spp, px, py, jnp.full((n_pix,), s, jnp.int32), dim)
        acc += np.asarray(u)
    return float(np.sqrt(np.mean((acc / spp - 0.5) ** 2)))


def test_ld_beats_random_1d():
    spp = 16
    r = _mean_rms("random", spp)
    for kind in ("02", "halton", "stratified"):
        ld = _mean_rms(kind, spp)
        assert ld < 0.5 * r, f"{kind}: rms {ld} not < half of random {r}"


def _prod_rms(kind, spp, n_pix=256, dim=5):
    """2D integration of f(u,v) = u*v (true mean 1/4) per pixel."""
    px = jnp.arange(n_pix, dtype=jnp.int32) % 16
    py = jnp.arange(n_pix, dtype=jnp.int32) // 16
    acc = np.zeros(n_pix)
    for s in range(spp):
        u, v = sample_2d(kind, spp, px, py, jnp.full((n_pix,), s, jnp.int32), dim)
        acc += np.asarray(u * v)
    return float(np.sqrt(np.mean((acc / spp - 0.25) ** 2)))


def test_ld_beats_random_2d():
    spp = 16
    r = _prod_rms("random", spp)
    # (0,2) is base-2 through and through: near-perfect at power-of-two
    # spp. Halton's odd-prime pairs only fully stratify at b^k samples,
    # so its margin at spp=16 is real but smaller (pbrt's Halton has the
    # same property).
    for kind, bound in (("02", 0.6), ("halton", 0.8)):
        ld = _prod_rms(kind, spp)
        assert ld < bound * r, f"{kind}: rms {ld} not < {bound}x random {r}"


def test_dimension_decorrelation():
    """Two different dimensions of the same sampler must not be linearly
    correlated across the sample index (the classic radical-inverse
    pitfall this dispatch's shuffling/scrambling exists to prevent)."""
    spp = 64
    px = jnp.zeros((1,), jnp.int32)
    py = jnp.zeros((1,), jnp.int32)
    for kind in ("02", "halton"):
        for d1, d2 in ((5, 21), (4, 8), (7, 23)):
            a = np.array(
                [
                    float(sample_1d(kind, spp, px, py, jnp.full((1,), s, jnp.int32), d1)[0])
                    for s in range(spp)
                ]
            )
            b = np.array(
                [
                    float(sample_1d(kind, spp, px, py, jnp.full((1,), s, jnp.int32), d2)[0])
                    for s in range(spp)
                ]
            )
            c = abs(np.corrcoef(a, b)[0, 1])
            assert c < 0.5, f"{kind} dims {d1},{d2} correlated: {c:.2f}"


def test_sampler_name_dispatch():
    assert normalize_sampler_name("sobol") == "sobol"
    assert normalize_sampler_name("zerotwosequence") == "02"
    assert normalize_sampler_name("maxmindist") == "02"  # loud substitute
    assert normalize_sampler_name("halton") == "halton"
    assert normalize_sampler_name("random") == "random"
    assert normalize_sampler_name("stratified") == "stratified"


def test_render_honors_sampler_name():
    """Same scene, different Sampler directives -> different images with
    ~equal means (the estimator is unbiased under every sampler), and the
    LD render is closer to a high-spp reference than the random one."""
    from tests.test_render import QUAD, render_scene

    def scene(sampler, spp):
        return f'''
Integrator "directlighting"
Sampler "{sampler}" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [16] "integer yresolution" [16] "string filename" [""]
LookAt 0 0 -3  0 0 0  0 1 0
Camera "perspective" "float fov" [60]
WorldBegin
AttributeBegin
AreaLightSource "diffuse" "rgb L" [8 8 8]
Shape "trianglemesh" {QUAD} "point P" [-0.4 0.95 -0.4  0.4 0.95 -0.4  0.4 0.95 0.4  -0.4 0.95 0.4]
AttributeEnd
Material "matte" "rgb Kd" [0.6 0.6 0.6]
Shape "trianglemesh" {QUAD} "point P" [-2 -1 2   2 -1 2   2 -1 -2  -2 -1 -2]
WorldEnd
'''

    ref = render_scene(scene("sobol", 128)).image
    img_r = render_scene(scene("random", 8)).image
    img_s = render_scene(scene("sobol", 8)).image
    assert not np.allclose(img_r, img_s), "sampler name ignored"
    mse_r = float(np.mean((img_r - ref) ** 2))
    mse_s = float(np.mean((img_s - ref) ** 2))
    assert mse_s < mse_r, f"sobol mse {mse_s} not below random {mse_r}"
    # unbiasedness: means agree within noise
    assert abs(img_r.mean() - img_s.mean()) / ref.mean() < 0.15
