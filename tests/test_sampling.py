"""Sampling-layer tests (pbrt src/tests/sampling.cpp counterpart):
distribution correctness of the warps, CDF sampling, stratification, the
stateless RNG, and MIS heuristics."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_pbrt.core import sampling as sm


def _u(n, salt):
    i = jnp.arange(n)
    return np.asarray(sm.uniform_float(i, salt))


class TestRNG:
    def test_uniformity(self):
        u = _u(100_000, 1)
        assert 0.0 <= u.min() and u.max() < 1.0
        # first three moments of U[0,1)
        assert abs(u.mean() - 0.5) < 3e-3
        assert abs((u**2).mean() - 1 / 3) < 3e-3
        hist, _ = np.histogram(u, bins=64, range=(0, 1))
        chi2 = ((hist - len(u) / 64) ** 2 / (len(u) / 64)).sum()
        assert chi2 < 64 * 2.0, f"chi2 {chi2}"

    def test_streams_uncorrelated(self):
        a = _u(50_000, 1)
        b = _u(50_000, 2)
        r = np.corrcoef(a, b)[0, 1]
        assert abs(r) < 0.02

    def test_deterministic(self):
        assert np.array_equal(_u(100, 7), _u(100, 7))


class TestWarps:
    def test_concentric_disk_in_unit_disk(self):
        n = 20_000
        u1, u2 = _u(n, 3), _u(n, 4)
        x, y = sm.concentric_sample_disk(jnp.asarray(u1), jnp.asarray(u2))
        r2 = np.asarray(x) ** 2 + np.asarray(y) ** 2
        assert r2.max() <= 1.0 + 1e-6
        # uniform density: mean radius^2 = 1/2
        assert abs(r2.mean() - 0.5) < 5e-3

    def test_cosine_hemisphere_mean_cos(self):
        n = 50_000
        d = np.asarray(sm.cosine_sample_hemisphere(jnp.asarray(_u(n, 5)), jnp.asarray(_u(n, 6))))
        assert (d[:, 2] >= 0).all()
        # E[cos theta] under p = cos/pi is 2/3
        assert abs(d[:, 2].mean() - 2 / 3) < 5e-3

    def test_uniform_sphere(self):
        n = 50_000
        d = np.asarray(sm.uniform_sample_sphere(jnp.asarray(_u(n, 8)), jnp.asarray(_u(n, 9))))
        assert np.allclose(np.linalg.norm(d, axis=-1), 1.0, atol=1e-5)
        assert np.abs(d.mean(axis=0)).max() < 0.02

    def test_uniform_triangle_barycentric(self):
        n = 50_000
        b0, b1 = sm.uniform_sample_triangle(jnp.asarray(_u(n, 10)), jnp.asarray(_u(n, 11)))
        b0, b1 = np.asarray(b0), np.asarray(b1)
        assert (b0 >= 0).all() and (b1 >= 0).all() and (b0 + b1 <= 1 + 1e-6).all()
        # uniform over the simplex: E[b0] = E[b1] = 1/3
        assert abs(b0.mean() - 1 / 3) < 5e-3
        assert abs(b1.mean() - 1 / 3) < 5e-3

    def test_cone_pdf_normalises(self):
        ct = 0.7
        n = 50_000
        d = np.asarray(sm.uniform_sample_cone(jnp.asarray(_u(n, 12)), jnp.asarray(_u(n, 13)), ct))
        assert (d[:, 2] >= ct - 1e-5).all()
        # solid angle of the cone = 2pi(1-ct); pdf = 1/that
        assert abs(float(sm.uniform_cone_pdf(jnp.float32(ct))) - 1 / (2 * np.pi * (1 - ct))) < 1e-6


class TestStratified:
    def test_stratified_1d_covers_strata(self):
        n_strata = 16
        s = jnp.arange(n_strata)
        vals = np.asarray(sm.stratified_1d(s, n_strata, 123, 7))
        cells = np.floor(vals * n_strata).astype(int)
        assert sorted(cells.tolist()) == list(range(n_strata)), cells

    def test_stratified_2d_covers_grid(self):
        sx = sy = 4
        s = jnp.arange(sx * sy)
        u, v = sm.stratified_2d(s, sx, sy, 55, 9)
        cx = np.floor(np.asarray(u) * sx).astype(int)
        cy = np.floor(np.asarray(v) * sy).astype(int)
        assert sorted((cy * sx + cx).tolist()) == list(range(sx * sy))

    def test_permutation_is_bijection(self):
        for n in (5, 8, 13, 100):
            p = np.asarray(sm.permutation_element(jnp.arange(n), n, jnp.uint32(17)))
            assert sorted(p.tolist()) == list(range(n)), (n, p)


class TestLowDiscrepancy:
    def test_radical_inverse_base2(self):
        got = np.asarray(sm.radical_inverse_base2(jnp.arange(8)))
        expect = [0, 0.5, 0.25, 0.75, 0.125, 0.625, 0.375, 0.875]
        assert np.allclose(got, expect, atol=1e-6)

    def test_sobol_2d_stratified(self):
        """(0,2)-sequence property: first 16 points stratify every 4x4
        elementary interval."""
        x, y = sm.sobol_2d(jnp.arange(16))
        cx = np.floor(np.asarray(x) * 4).astype(int)
        cy = np.floor(np.asarray(y) * 4).astype(int)
        assert sorted((cy * 4 + cx).tolist()) == list(range(16))


class TestDistribution1D:
    def test_discrete_pmf(self):
        d = sm.Distribution1D.build([1.0, 3.0, 0.0, 4.0])
        u = jnp.asarray(_u(100_000, 21))
        idx, pmf = d.sample_discrete(u)
        idx = np.asarray(idx)
        counts = np.bincount(idx, minlength=4) / len(idx)
        assert np.allclose(counts, [1 / 8, 3 / 8, 0, 4 / 8], atol=5e-3)
        assert np.allclose(np.asarray(pmf), counts[idx], atol=5e-3)

    def test_continuous_pdf_integrates(self):
        f = [0.2, 1.0, 2.0, 0.5, 0.3]
        d = sm.Distribution1D.build(f)
        u = jnp.asarray(_u(100_000, 22))
        x, pdf, _ = d.sample_continuous(u)
        x = np.asarray(x)
        # E[1/pdf] over samples = measure of domain = 1
        assert abs(np.mean(1.0 / np.asarray(pdf)) - 1.0) < 5e-3
        # histogram matches f (normalized)
        hist, _ = np.histogram(x, bins=5, range=(0, 1), density=True)
        fn = np.asarray(f) / np.mean(f)
        assert np.allclose(hist, fn, rtol=0.05)


class TestDistribution2D:
    def test_sample_matches_pdf(self):
        rng = np.random.default_rng(3)
        f = rng.uniform(0.1, 2.0, (8, 16))
        d = sm.Distribution2D.build(f)
        n = 200_000
        u1 = jnp.asarray(_u(n, 31))
        u2 = jnp.asarray(_u(n, 32))
        (u, v), pdf = d.sample_continuous(u1, u2)
        # cross-check pdf() against the sampling pdf
        pdf2 = d.pdf(u, v)
        assert np.allclose(np.asarray(pdf), np.asarray(pdf2), rtol=1e-4)
        # E[1/pdf] = domain measure = 1
        assert abs(np.mean(1.0 / np.asarray(pdf)) - 1.0) < 5e-3
        # cell frequencies proportional to f
        iu = np.clip((np.asarray(u) * 16).astype(int), 0, 15)
        iv = np.clip((np.asarray(v) * 8).astype(int), 0, 7)
        counts = np.zeros((8, 16))
        np.add.at(counts, (iv, iu), 1.0)
        counts /= counts.sum()
        expect = f / f.sum()
        assert np.abs(counts - expect).max() < 0.003


class TestMIS:
    def test_power_heuristic_partition(self):
        """w_f(pf,pg) + w_g(pg,pf) = 1 — the MIS weights partition unity."""
        pf = jnp.asarray(_u(1000, 41)) * 5
        pg = jnp.asarray(_u(1000, 42)) * 5
        wf = np.asarray(sm.power_heuristic(1, pf, 1, pg))
        wg = np.asarray(sm.power_heuristic(1, pg, 1, pf))
        assert np.allclose(wf + wg, 1.0, atol=1e-5)
