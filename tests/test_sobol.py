"""True Sobol' sampler tests (samplers/sobol.cpp capability, VERDICT r4
#7): generator-matrix validity, the global interval-to-index remap,
stratification, and the variance win over random sampling on cornell."""

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core.sampling import (
    N_SOBOL_DIMS,
    _SOBOL_V,
    _sobol_raw_bits,
    sobol_interval_to_index,
    sobol_sample,
)


def test_matrices_valid():
    """Every dimension's generator matrix must have nonsingular leading
    minors over GF(2) — the condition making it a base-2
    (0,1)-sequence (perfect 2^k stratification of every prefix)."""

    def leading_minors_nonsingular(cols, kmax=16):
        # row r of the k x k minor: bit (31 - r) of columns 0..k-1
        for k in range(1, kmax + 1):
            rows = []
            for r in range(k):
                bits = 0
                for c in range(k):
                    bits |= (((int(cols[c]) >> (31 - r)) & 1) << c)
                rows.append(bits)
            # gaussian elimination over GF(2)
            for col in range(k):
                piv = next(
                    (r for r in range(col, k) if (rows[r] >> col) & 1), None
                )
                if piv is None:
                    return False
                rows[col], rows[piv] = rows[piv], rows[col]
                for r in range(k):
                    if r != col and ((rows[r] >> col) & 1):
                        rows[r] ^= rows[col]
        return True

    for d in range(N_SOBOL_DIMS):
        assert leading_minors_nonsingular(_SOBOL_V[d]), f"dim {d}"


def test_remap_lands_in_pixel():
    """SobolIntervalToIndex: sample `frame` of pixel p maps to a global
    index whose dims 0/1 fall inside p (the defining property)."""
    m = 4
    res = 1 << m
    px, py = jnp.meshgrid(jnp.arange(res), jnp.arange(res), indexing="ij")
    px = px.reshape(-1).astype(jnp.int32)
    py = py.reshape(-1).astype(jnp.int32)
    scale = res * 2.3283064365386963e-10
    for frame in range(8):
        idx = sobol_interval_to_index(m, jnp.int32(frame), px, py)
        gx = (np.asarray(_sobol_raw_bits(idx, 0)).astype(np.uint32) * scale).astype(int)
        gy = (np.asarray(_sobol_raw_bits(idx, 1)).astype(np.uint32) * scale).astype(int)
        assert (gx == np.asarray(px)).all() and (gy == np.asarray(py)).all()
        # and distinct frames get distinct global indices
    i0 = sobol_interval_to_index(m, jnp.int32(0), px, py)
    i1 = sobol_interval_to_index(m, jnp.int32(1), px, py)
    assert (np.asarray(i0) != np.asarray(i1)).all()


def test_dimension_stratification():
    """First 2^k samples of every dimension hit every 1/2^k stratum
    exactly once (elementary-interval property), scrambled or not."""
    n = 1 << 10
    i = jnp.arange(n, dtype=jnp.int32)
    for dim in (0, 1, 2, 7, 23, 63):
        u = np.asarray(sobol_sample(i, dim))
        counts = np.bincount((u * n).astype(int), minlength=n)
        assert (counts == 1).all(), f"dim {dim} unscrambled"
        u2 = np.asarray(sobol_sample(i, dim, jnp.uint32(0xABCD + dim)))
        counts2 = np.bincount((u2 * n).astype(int), minlength=n)
        assert (counts2 == 1).all(), f"dim {dim} owen-scrambled"


def test_pair_01_is_02_sequence():
    """Dims (0,1) of the first 2^k samples form a (0,2)-sequence: every
    elementary box at total depth k holds exactly one point."""
    n = 1 << 8
    i = jnp.arange(n, dtype=jnp.int32)
    x = np.asarray(sobol_sample(i, 0))
    y = np.asarray(sobol_sample(i, 1))
    for kx in range(0, 9):
        ky = 8 - kx
        bx = (x * (1 << kx)).astype(int)
        by = (y * (1 << ky)).astype(int)
        cells = bx * (1 << ky) + by
        counts = np.bincount(cells, minlength=n)
        assert (counts == 1).all(), f"box split {kx}/{ky}"


def test_estimator_variance_beats_random():
    """VERDICT r4 #7 done-criterion (measured variance win at equal
    sample count): integrating a smooth 2D integrand with each pixel's
    spp draws from the REAL sample_2d path, the sobol sampler's
    per-pixel estimator variance must be far below random's. (A full
    render of the 16x16 cornell cannot show this: its MSE is dominated
    by silhouette pixels whose binary-visibility integrand defeats any
    stratification — all samplers tie there, measured.)"""
    from tpu_pbrt.core.sampling import sample_2d

    # decision dims are the padded per-pixel construction — no film-grid
    # context needed (the old module-global sobol ctx is gone, ADVICE r4)
    spp = 16
    n_pix = 1024
    pix = jnp.arange(n_pix, dtype=jnp.int32)
    px = pix % 64
    py = pix // 64
    # smooth integrand with known mean: E[sin(pi u) * v^2] = (2/pi)*(1/3)
    truth = (2.0 / np.pi) * (1.0 / 3.0)

    def pixel_means(kind):
        acc = jnp.zeros((n_pix,), jnp.float32)
        for s in range(spp):
            u, v = sample_2d(kind, spp, px, py,
                             jnp.full((n_pix,), s, jnp.int32), 5)
            acc = acc + jnp.sin(jnp.pi * u) * v * v
        return np.asarray(acc / spp)

    var_rand = float(((pixel_means("random") - truth) ** 2).mean())
    var_sob = float(((pixel_means("sobol") - truth) ** 2).mean())
    assert var_sob < 0.2 * var_rand, (
        f"sobol estimator variance {var_sob:.2e} not far below "
        f"random {var_rand:.2e}"
    )


def test_render_no_regression_vs_random():
    """Render-level guard: on the (edge-dominated) cornell box the sobol
    sampler must at least not LOSE to random."""
    from tpu_pbrt.scenes import compile_api, make_cornell

    def render(sampler, spp):
        api = make_cornell(res=16, spp=spp, integrator="path", maxdepth=2,
                           sampler=sampler)
        scene, integ = compile_api(api)
        return np.asarray(integ.render(scene).image)

    ref = render("random", 256)
    mse_rand = float(((render("random", 8) - ref) ** 2).mean())
    mse_sob = float(((render("sobol", 8) - ref) ** 2).mean())
    assert mse_sob < 1.25 * mse_rand, (
        f"sobol mse {mse_sob:.5f} regressed vs random {mse_rand:.5f}"
    )
