"""Test configuration: force an 8-device CPU mesh before any test imports.

This mirrors how the reference's distributed layer is tested without a
cluster (SURVEY.md §4): a virtual 8-device CPU platform exercises the
shard_map/psum code paths that run over ICI on real TPU hardware.

The override is unconditional and uses jax.config (not just the env var):
the harness's TPU plugin registers itself via sitecustomize at interpreter
startup and would otherwise claim the default backend. Unit tests must be
hardware-independent and deterministic. Set TPU_PBRT_TEST_PLATFORM=axon to
run the suite on real hardware instead.
"""

import os

_platform = os.environ.get("TPU_PBRT_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# The suite's wall time is ~all XLA:CPU LLVM optimization of big render
# programs (VERDICT r4 weak #3: 2066 s warm / >3500 s cold). Level 0
# compiles the same programs ~35x faster (measured: the mesh-SPPM module
# 728 s -> 21 s) and test renders are tiny, so runtime is noise. Set
# TPU_PBRT_TEST_XLA_OPT=default to run the optimized pipeline instead
# (e.g. when timing kernels on real hardware).
if (
    _platform == "cpu"
    and os.environ.get("TPU_PBRT_TEST_XLA_OPT", "0") == "0"
    and "xla_backend_optimization_level" not in _flags
):
    _flags += " --xla_backend_optimization_level=0"
os.environ["XLA_FLAGS"] = _flags
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

# Persistent XLA compilation cache: the suite's cost is almost entirely
# jit compiles of per-scene render programs (renders themselves are tiny).
# A warm cache turns the ~7-minute render/media files into seconds, which
# is what makes "always run the suite before committing" realistic
# (VERDICT r2 weak #6 / next-round #8).
import pathlib

_cache_dir = pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
_cache_dir.mkdir(exist_ok=True)
jax.config.update("jax_compilation_cache_dir", str(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _sync_tpu_pbrt_config():
    """TPU_PBRT_* knobs are snapshotted at import by tpu_pbrt.config;
    tests that mutate os.environ mid-test call config.reload() at the
    mutation point. This autouse resync at both test boundaries keeps a
    test's leftover env mutations (e.g. monkeypatch teardown, which
    restores os.environ but knows nothing of the snapshot) from
    poisoning the knobs later tests see."""
    from tpu_pbrt import config

    config.reload()
    yield
    config.reload()
