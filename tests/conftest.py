"""Test configuration: force an 8-device CPU mesh before jax imports.

This mirrors how the reference's distributed layer is tested without a
cluster (SURVEY.md §4): a virtual 8-device CPU platform exercises the
shard_map/psum code paths that run over ICI on real TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
