"""FourierBSDF tests: synthetic-table eval against the analytic
Lambertian it encodes, binary .bsdf round-trip, sampling consistency,
and an end-to-end scene."""

import os
import struct
import tempfile

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core import fourierbsdf as fb


def _lambert_table(n_mu=32, rho=0.7):
    """Table encoding f = rho/pi for reflection: stored a0 = f * |muI|
    on pairs with muI * muO < 0 (pbrt's muI = cos(-wi) convention)."""
    mu = np.linspace(-1.0, 1.0, n_mu).astype(np.float32)
    vals = np.zeros((n_mu, n_mu), np.float32)
    for o in range(n_mu):
        for i in range(n_mu):
            if mu[i] * mu[o] < 0:
                vals[o, i] = rho / np.pi * abs(mu[i])
    return fb.make_table(mu, vals), rho


def _dirs(n, seed, up=None):
    rng = np.random.default_rng(seed)
    d = rng.normal(size=(n, 3))
    d /= np.linalg.norm(d, axis=-1, keepdims=True)
    if up is not None:
        d[:, 2] = np.abs(d[:, 2]) * (1 if up else -1)
    return jnp.asarray(d, jnp.float32)


def test_lambertian_table_eval():
    tab, rho = _lambert_table()
    n = 20_000
    wo = _dirs(n, 1, up=True)
    wi = _dirs(n, 2, up=True)  # reflection: same hemisphere
    f, _ = fb.fourier_f_pdf(tab, wo, wi)
    # away from grazing, eval must reproduce rho/pi
    mask = (np.asarray(wi[:, 2]) > 0.2) & (np.asarray(wo[:, 2]) > 0.2)
    got = np.asarray(f[:, 0])[mask]
    np.testing.assert_allclose(got, rho / np.pi, rtol=0.03)
    # no transmission encoded: opposite hemisphere is (near) zero away
    # from the mu = 0 kink, where the Catmull-Rom support necessarily
    # straddles both signs
    wi_t = _dirs(n, 3, up=False)
    f_t, _ = fb.fourier_f_pdf(tab, wo, wi_t)
    mask_t = (np.asarray(wi_t[:, 2]) < -0.2) & (np.asarray(wo[:, 2]) > 0.2)
    assert float(np.abs(np.asarray(f_t[:, 0])[mask_t]).max()) < 0.02


def test_sampling_estimator_matches():
    tab, rho = _lambert_table()
    n = 300_000
    rng = np.random.default_rng(5)
    wo = jnp.broadcast_to(
        jnp.asarray([0.1, 0.2, 0.97], jnp.float32)
        / np.linalg.norm([0.1, 0.2, 0.97]),
        (n, 3),
    )
    u_l = jnp.asarray(rng.uniform(size=n), jnp.float32)
    u1 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    u2 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    wi = fb.fourier_sample_wi(wo, u_l, u1, u2)
    f, pdf = fb.fourier_f_pdf(tab, wo, wi)
    est = float(
        jnp.mean(
            jnp.where(
                pdf > 1e-8,
                f[:, 0] * jnp.abs(wi[:, 2]) / jnp.maximum(pdf, 1e-8),
                0.0,
            )
        )
    )
    # hemispherical albedo of the encoded Lambertian = rho
    assert abs(est - rho) < 0.03, est


def _write_bsdf(path, mu, vals, eta=1.0):
    """Write the SCATFUN v1 binary (reflection.cpp Read layout)."""
    n = len(mu)
    a = np.asarray(vals, np.float32).reshape(-1)
    m = (np.abs(a) > 0).astype(np.int32)
    offset = np.arange(n * n, dtype=np.int32)
    cdf = np.zeros((n, n), np.float32)
    with open(path, "wb") as f:
        f.write(b"SCATFUN\x01")
        f.write(struct.pack("<9i", 1, n, n * n, int(m.max()), 1, 1, 0, 0, 0))
        f.write(struct.pack("<f", eta))
        f.write(struct.pack("<4i", 0, 0, 0, 0))
        f.write(np.asarray(mu, np.float32).tobytes())
        f.write(cdf.tobytes())
        ol = np.stack([offset, m], axis=1).astype(np.int32)
        f.write(ol.tobytes())
        f.write(a.tobytes())


def test_binary_roundtrip():
    n_mu = 16
    mu = np.linspace(-1, 1, n_mu).astype(np.float32)
    rng = np.random.default_rng(7)
    vals = rng.random((n_mu, n_mu)).astype(np.float32)
    with tempfile.NamedTemporaryFile(suffix=".bsdf", delete=False) as f:
        path = f.name
    try:
        _write_bsdf(path, mu, vals, eta=1.33)
        tab = fb.read_bsdf_file(path)
        assert tab.n_channels == 1
        assert abs(tab.eta - 1.33) < 1e-6
        np.testing.assert_allclose(np.asarray(tab.mu), mu)
        np.testing.assert_allclose(np.asarray(tab.a), vals.reshape(-1))
    finally:
        os.unlink(path)


def test_fourier_scene_end_to_end():
    import tpu_pbrt

    tab, rho = _lambert_table(16)
    with tempfile.NamedTemporaryFile(suffix=".bsdf", delete=False) as f:
        bsdf_path = f.name
    n_mu = 16
    mu = np.linspace(-1, 1, n_mu).astype(np.float32)
    vals = np.zeros((n_mu, n_mu), np.float32)
    for o in range(n_mu):
        for i in range(n_mu):
            if mu[i] * mu[o] < 0:
                vals[o, i] = 0.6 / np.pi * abs(mu[i])
    _write_bsdf(bsdf_path, mu, vals)
    scene = f"""
Integrator "path" "integer maxdepth" [3]
Sampler "random" "integer pixelsamples" [4]
Film "image" "integer xresolution" [24] "integer yresolution" [24]
LookAt 0 2 5  0 0 0  0 1 0
Camera "perspective" "float fov" [45]
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [10 10 10]
  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
    "point P" [-1 3.9 -1  1 3.9 -1  1 3.9 1  -1 3.9 1]
AttributeEnd
Material "fourier" "string bsdffile" ["{bsdf_path}"]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
  "point P" [-3 0 -3  3 0 -3  3 0 3  -3 0 3]
WorldEnd
"""
    with tempfile.NamedTemporaryFile("w", suffix=".pbrt", delete=False) as f:
        f.write(scene)
        scene_path = f.name
    try:
        res = tpu_pbrt.render_file(scene_path)
        img = np.asarray(res.image)
        assert np.isfinite(img).all()
        assert img.max() > 0.0
    finally:
        os.unlink(scene_path)
        os.unlink(bsdf_path)
