"""End-to-end render tests with closed-form oracles.

Mirrors pbrt-v3's src/tests/analytic_scenes.cpp strategy (SURVEY.md §4):
build tiny scenes through the scene-description API in-process, render with
several integrator combinations, and assert the result matches analytic
radiance within noise tolerance — an oracle without golden images. Also
cross-checks integrators against each other (path vs directlighting on
direct-only scenes), the upstream ecosystem's convergence test.
"""

import numpy as np
import pytest

from tpu_pbrt.scene.api import Options, parse_string, pbrt_init


def render_scene(text, quiet=True):
    api = pbrt_init(Options(quiet=quiet))
    parse_string(text, api, render=True)
    return api.result


def scene_header(integrator, spp=16, res=32, extra=""):
    return f'''
Integrator "{integrator}" {extra}
Sampler "halton" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}] "string filename" [""]
LookAt 0 0 -3  0 0 0  0 1 0
Camera "perspective" "float fov" [60]
'''


QUAD = '"integer indices" [0 1 2 0 2 3]'


class TestFurnace:
    """Constant environment light, no geometry: every ray escapes and picks
    up exactly L (InfiniteAreaLight::Le with no occlusion)."""

    @pytest.mark.parametrize("integrator", ["path", "directlighting", "whitted"])
    def test_escape_radiance(self, integrator):
        r = render_scene(
            scene_header(integrator, spp=4)
            + '''
WorldBegin
LightSource "infinite" "rgb L" [0.4 0.5 0.6]
WorldEnd
'''
        )
        img = r.image
        assert np.allclose(img[..., 0], 0.4, atol=1e-3)
        assert np.allclose(img[..., 1], 0.5, atol=1e-3)
        assert np.allclose(img[..., 2], 0.6, atol=1e-3)

    def test_furnace_flat_plane_path(self):
        """Lambertian plane of albedo rho in a uniform furnace of radiance
        1: a flat plane sees only the environment (it cannot see itself), so
        its exitant radiance is exactly rho — the single-scatter white
        furnace identity, integrating f*cos over the hemisphere."""
        r = render_scene(
            scene_header("path", spp=128, res=16, extra='"integer maxdepth" [8]')
            + f'''
WorldBegin
LightSource "infinite" "rgb L" [1 1 1]
Material "matte" "rgb Kd" [0.5 0.5 0.5]
Shape "trianglemesh" {QUAD} "point P" [-9 -9 2  9 -9 2  9 9 2  -9 9 2]
WorldEnd
'''
        )
        img = r.image
        center = img[6:10, 6:10].mean()
        assert abs(center - 0.5) < 0.02, f"furnace radiance {center} != 0.5"


class TestAnalyticDirect:
    def test_area_light_seen_directly(self):
        """Camera ray hits the emissive quad -> pixel = Le exactly."""
        r = render_scene(
            scene_header("directlighting", spp=4)
            + f'''
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [3 2 1]
  # winding chosen so the geometric normal faces the camera (-z)
  Shape "trianglemesh" {QUAD} "point P" [-2 -2 0  -2 2 0  2 2 0  2 -2 0]
AttributeEnd
WorldEnd
'''
        )
        img = r.image
        c = img[16, 16]
        assert np.allclose(c, [3, 2, 1], rtol=1e-3), c

    def test_point_light_lambertian_analytic(self):
        """Point light I over a lambertian plane: L = (Kd/pi) * I cos/r^2,
        checked at the image center against the closed form."""
        I = np.array([10.0, 10.0, 10.0])
        kd = np.array([0.6, 0.4, 0.2])
        # plane z=2 facing camera at origin... camera at (0,0,-3) looking +z
        # light at (0, 0, 0): center hit point (0,0,2), r=2, cos=1
        r = render_scene(
            scene_header("directlighting", spp=16)
            + f'''
WorldBegin
LightSource "point" "rgb I" [10 10 10] "point from" [0 0 0]
Material "matte" "rgb Kd" [0.6 0.4 0.2]
Shape "trianglemesh" {QUAD} "point P" [-9 -9 2  9 -9 2  9 9 2  -9 9 2]
WorldEnd
'''
        )
        img = r.image
        expected = kd / np.pi * I * 1.0 / 4.0
        got = img[15:17, 15:17].mean(axis=(0, 1))
        assert np.allclose(got, expected, rtol=0.02), (got, expected)

    def test_distant_light_analytic(self):
        """Distant light L along -z onto a facing plane: Lo = Kd/pi * L."""
        r = render_scene(
            scene_header("directlighting", spp=4)
            + f'''
WorldBegin
LightSource "distant" "rgb L" [2 2 2] "point from" [0 0 -1] "point to" [0 0 0]
Material "matte" "rgb Kd" [0.5 0.5 0.5]
Shape "trianglemesh" {QUAD} "point P" [-9 -9 2  9 -9 2  9 9 2  -9 9 2]
WorldEnd
'''
        )
        img = r.image
        expected = 0.5 / np.pi * 2.0
        got = img[14:18, 14:18].mean()
        assert abs(got - expected) < 0.01 * expected + 1e-4, (got, expected)

    def test_shadow(self):
        """A small occluder near the light casts a shadow larger than its
        own silhouette: plane points beside the occluder (visible to the
        camera) are dark inside the umbra and lit outside it."""
        r = render_scene(
            scene_header("directlighting", spp=4)
            + f'''
WorldBegin
LightSource "point" "rgb I" [10 10 10] "point from" [0 0 0.5]
Material "matte" "rgb Kd" [0.5 0.5 0.5]
Shape "trianglemesh" {QUAD} "point P" [-9 -9 2  9 -9 2  9 9 2  -9 9 2]
Shape "trianglemesh" {QUAD} "point P" [-0.3 -0.3 1  0.3 -0.3 1  0.3 0.3 1  -0.3 0.3 1]
WorldEnd
'''
        )
        img = r.image
        # umbra on the plane reaches |x| = 0.3*(2-0.5)/(1-0.5) = 0.9;
        # the occluder hides only |x| < ~0.375 of the plane from the camera.
        # pixel col 19 -> plane x ~ 0.64 (shadowed, visible); col 28 -> ~2.2 (lit)
        assert img[16, 19].max() < 0.01, img[16, 19]
        assert img[16, 28].mean() > 0.03, img[16, 28]


class TestCrossIntegrator:
    def test_path_matches_direct_on_direct_only_scene(self):
        """On a scene with one bounce of transport (maxdepth=1), the path
        integrator and direct-lighting integrator estimate the same
        integral — the cross-convergence oracle from SURVEY.md §4."""
        scene_body = f'''
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [8 8 8]
  Translate 0 1.8 0
  Shape "trianglemesh" {QUAD} "point P" [-0.6 0 -0.6  0.6 0 -0.6  0.6 0 0.6  -0.6 0 0.6]
AttributeEnd
Material "matte" "rgb Kd" [0.7 0.6 0.5]
Shape "trianglemesh" {QUAD} "point P" [-2 -2 2  2 -2 2  2 2 2  -2 2 2]
Shape "trianglemesh" {QUAD} "point P" [-2 -2 -4  2 -2 -4  2 -2 2  -2 -2 2]
WorldEnd
'''
        r1 = render_scene(
            scene_header("directlighting", spp=128, res=24, extra='"integer maxdepth" [1]')
            + scene_body
        )
        r2 = render_scene(
            scene_header("path", spp=128, res=24, extra='"integer maxdepth" [1]') + scene_body
        )
        a, b = r1.image, r2.image
        mse = float(np.mean((a - b) ** 2))
        scale = float(np.mean(a**2)) + 1e-9
        assert mse / scale < 0.01, f"relative MSE {mse / scale}"


class TestSpecular:
    def test_mirror_reflects_light(self):
        """Mirror plane reflecting an area light: the reflected image of the
        light carries Le * Kr."""
        r = render_scene(
            scene_header("path", spp=32, extra='"integer maxdepth" [3]')
            + f'''
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [5 5 5]
  Shape "trianglemesh" {QUAD} "point P" [-2 -2 -3.05  2 -2 -3.05  2 2 -3.05  -2 2 -3.05]
AttributeEnd
Material "mirror" "rgb Kr" [0.8 0.8 0.8]
Shape "trianglemesh" {QUAD} "point P" [-2 -2 2  2 -2 2  2 2 2  -2 2 2]
WorldEnd
'''
        )
        img = r.image
        got = img[16, 16]
        assert np.allclose(got, 5 * 0.8, rtol=0.05), got


class TestImageLights:
    """Goniometric/projection lights: image-modulated point intensity
    (goniometric.cpp / projection.cpp capability)."""

    def _plane_scene(self, light, mapline=""):
        return (
            scene_header("directlighting", spp=4, res=24)
            + f'''
WorldBegin
{light}
Material "matte" "rgb Kd" [1 1 1]
Shape "trianglemesh" {QUAD} "point P" [-4 -4 1   4 -4 1   4 4 1  -4 4 1]
WorldEnd
'''
        )

    def test_gonio_constant_map_matches_point(self, tmp_path):
        import numpy as np
        from tpu_pbrt.utils.imageio import write_image

        m = str(tmp_path / "m.pfm")
        write_image(m, np.full((4, 8, 3), 1.0, np.float32))
        r_g = render_scene(self._plane_scene(
            f'LightSource "goniometric" "rgb I" [5 5 5] "string mapname" ["{m}"]'
        ))
        r_p = render_scene(self._plane_scene(
            'LightSource "point" "rgb I" [5 5 5]'
        ))
        np.testing.assert_allclose(r_g.image, r_p.image, rtol=1e-4, atol=1e-5)

    def test_projection_lights_only_inside_fov(self, tmp_path):
        import numpy as np
        from tpu_pbrt.utils.imageio import write_image

        m = str(tmp_path / "m.pfm")
        write_image(m, np.full((8, 8, 3), 1.0, np.float32))
        img = render_scene(self._plane_scene(
            f'LightSource "projection" "rgb I" [5 5 5] "float fov" [30] '
            f'"string mapname" ["{m}"]'
        )).image
        lum = img.mean(-1)
        assert lum.max() > 1e-3, "projection light contributed nothing"
        # the 30-degree frustum lights only the central patch of the plane
        assert lum[0, 0] == 0.0 and lum[-1, -1] == 0.0
        c = lum.shape[0] // 2
        assert lum[c, c] > 0.0
