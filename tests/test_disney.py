"""Disney BSDF tests (materials/disney.cpp capability): pdf
normalization over the sphere, sample/eval MC consistency, energy
bounds, lobe activation, and an end-to-end scene compile+render."""

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core import bxdf

MAT_DISNEY = None  # resolved lazily from the compiler enum


def _enum():
    global MAT_DISNEY
    if MAT_DISNEY is None:
        from tpu_pbrt.scene.compiler import MAT_DISNEY as v

        MAT_DISNEY = v
    return MAT_DISNEY


def _disney_mp(n, *, color=(0.6, 0.4, 0.3), rough=0.4, metallic=0.0,
               aniso=0.0, sheen=0.0, clearcoat=0.0, strans=0.0,
               thin=False, flat=0.0, dtrans=1.0, eta=1.5):
    one = jnp.ones((n,), jnp.float32)
    one3 = jnp.ones((n, 3), jnp.float32)
    dz = bxdf.DisneyParams(
        metallic=one * metallic,
        spectint=one * 0.0,
        aniso=one * aniso,
        sheen=one * sheen,
        sheentint=one * 0.5,
        clearcoat=one * clearcoat,
        ccgloss=one * 1.0,
        strans=one * strans,
        flat=one * flat,
        dtrans=one * dtrans,
        thin=jnp.full((n,), thin, bool),
        rough=one * rough,
    )
    return bxdf.MatParams(
        mtype=jnp.full((n,), _enum(), jnp.int32),
        kd=one3 * jnp.asarray(color, jnp.float32),
        ks=one3 * 0,
        kr=one3 * 0,
        kt=one3 * 0,
        eta=one3 * eta,
        k=one3 * 0,
        ax=one * 0.1,
        ay=one * 0.1,
        sigma=one * 0,
        opacity=one3,
        rough_raw=one * rough,
        dz=dz,
    )


def _sphere_dirs(n, seed):
    rng = np.random.default_rng(seed)
    d = rng.normal(size=(n, 3))
    d /= np.linalg.norm(d, axis=-1, keepdims=True)
    return jnp.asarray(d, jnp.float32)


PARAM_SETS = [
    dict(),  # plain diffuse-ish
    dict(metallic=0.9, rough=0.3),
    dict(clearcoat=1.0, rough=0.5),
    dict(sheen=1.0, rough=0.6),
    dict(aniso=0.8, rough=0.3, metallic=0.5),
    dict(strans=0.7, rough=0.25),
    dict(thin=True, flat=0.6, dtrans=0.8, rough=0.4),
]


def test_pdf_normalizes_over_sphere():
    """int pdf(wo, wi) dwi = 1 for every lobe mix (each component pdf is
    a normalized density and the mixture is a uniform average)."""
    n = 400_000
    wi = _sphere_dirs(n, 11)
    wo = jnp.broadcast_to(
        jnp.asarray([0.3, -0.2, 0.93], jnp.float32)
        / np.linalg.norm([0.3, -0.2, 0.93]),
        (n, 3),
    )
    for ps in PARAM_SETS:
        mp = _disney_mp(n, **ps)
        _, pdf = bxdf._disney_f_pdf(mp, wo, wi)
        est = float(jnp.mean(pdf)) * 4.0 * np.pi
        assert abs(est - 1.0) < 0.06, f"{ps}: int pdf = {est}"


def test_sample_eval_consistency():
    """The BSDF-sampling estimator E[f |cos| / pdf] must match a
    uniform-sphere MC of int f |cos| dwi, per channel."""
    n = 400_000
    rng = np.random.default_rng(3)
    wo = jnp.broadcast_to(
        jnp.asarray([0.2, 0.1, 0.97], jnp.float32)
        / np.linalg.norm([0.2, 0.1, 0.97]),
        (n, 3),
    )
    for ps in PARAM_SETS:
        mp = _disney_mp(n, **ps)
        u_l = jnp.asarray(rng.uniform(size=n), jnp.float32)
        u1 = jnp.asarray(rng.uniform(size=n), jnp.float32)
        u2 = jnp.asarray(rng.uniform(size=n), jnp.float32)
        wi_s, bad = bxdf._disney_sample_wi(mp, wo, u_l, u1, u2)
        f_s, pdf_s = bxdf._disney_f_pdf(mp, wo, wi_s)
        w = np.asarray(
            jnp.where(
                (pdf_s > 1e-9)[..., None] & ~bad[..., None],
                f_s * jnp.abs(wi_s[..., 2:3]) / jnp.maximum(pdf_s, 1e-9)[..., None],
                0.0,
            )
        )
        est_s = w.mean(axis=0)
        wi_u = _sphere_dirs(n, 17)
        f_u, _ = bxdf._disney_f_pdf(mp, wo, wi_u)
        est_u = np.asarray(f_u * jnp.abs(wi_u[..., 2:3])).mean(axis=0) * 4.0 * np.pi
        assert np.all(np.abs(est_s - est_u) < 0.04 + 0.1 * est_u), (
            f"{ps}: sampled {est_s} vs uniform {est_u}"
        )


def test_energy_bounded():
    """Total (reflected + transmitted) energy stays near-or-below 1 for
    a white base color (Disney is not strictly conserving but must not
    visibly amplify)."""
    n = 400_000
    wi = _sphere_dirs(n, 23)
    wo = jnp.broadcast_to(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (n, 3))
    for ps in PARAM_SETS:
        mp = _disney_mp(n, color=(1.0, 1.0, 1.0), **ps)
        f, _ = bxdf._disney_f_pdf(mp, wo, wi)
        est = float(jnp.mean(jnp.max(f, -1) * jnp.abs(wi[..., 2]))) * 4.0 * np.pi
        assert est < 1.35, f"{ps}: albedo {est}"


def test_metallic_kills_diffuse():
    n = 4096
    wo = jnp.broadcast_to(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (n, 3))
    wi = _sphere_dirs(n, 5)
    wi = wi.at[:, 2].set(jnp.abs(wi[:, 2]))
    f_m, _ = bxdf._disney_f_pdf(_disney_mp(n, metallic=1.0, rough=0.4), wo, wi)
    f_d, _ = bxdf._disney_f_pdf(_disney_mp(n, metallic=0.0, rough=0.4), wo, wi)
    # metallic=1 removes the diffuse floor: away from the specular peak
    # the metallic response must be far below the diffuse one
    off_peak = np.asarray(wi[:, 2]) < 0.7
    assert float(jnp.mean(jnp.where(off_peak, f_m[:, 0], 0.0))) < 0.25 * float(
        jnp.mean(jnp.where(off_peak, f_d[:, 0], 0.0))
    )


def test_spectrans_transmits():
    n = 100_000
    wo = jnp.broadcast_to(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (n, 3))
    wi = _sphere_dirs(n, 29)
    mp = _disney_mp(n, strans=0.9, rough=0.3)
    f, _ = bxdf._disney_f_pdf(mp, wo, wi)
    below = np.asarray(wi[:, 2]) < -0.05
    assert float(jnp.sum(jnp.where(below, f[:, 0], 0.0))) > 0.0


def test_disney_scene_end_to_end():
    import tpu_pbrt

    scene = """
Integrator "path" "integer maxdepth" [3]
Sampler "random" "integer pixelsamples" [4]
Film "image" "integer xresolution" [32] "integer yresolution" [32]
LookAt 0 2 5  0 1 0  0 1 0
Camera "perspective" "float fov" [45]
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [10 10 10]
  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
    "point P" [-1 3.9 -1  1 3.9 -1  1 3.9 1  -1 3.9 1]
AttributeEnd
Material "disney" "rgb color" [0.7 0.3 0.2] "float metallic" [0.4]
  "float roughness" [0.35] "float clearcoat" [0.8] "float sheen" [0.5]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
  "point P" [-3 0 -3  3 0 -3  3 0 3  -3 0 3]
WorldEnd
"""
    import tempfile, os

    with tempfile.NamedTemporaryFile("w", suffix=".pbrt", delete=False) as f:
        f.write(scene)
        path = f.name
    try:
        res = tpu_pbrt.render_file(path)
        img = np.asarray(res.image)
        assert np.isfinite(img).all()
        assert img.max() > 0.0
    finally:
        os.unlink(path)
