"""tpu-metrics (ISSUE 10): the host-side metrics registry.

Oracles:

- DETERMINISM: fixed-bucket histograms make snapshot/exposition a pure
  function of the observed values — two registries fed the same events
  expose identical bytes, and the bucket-derived p50/p90/p99 are exact
  arithmetic, pinned against hand-computed expectations.
- VALIDATION: the Prometheus text lint accepts the registry's own
  output and rejects the drift classes that break scrapers (missing
  TYPE, broken label escaping, non-monotone cumulative buckets).
- KILL SWITCH: TPU_PBRT_METRICS=0 leaves render stats and images
  byte-identical to a build without the registry, and records nothing.
- SLO: the shed decision is a pure function over (class, depth, p90) —
  a decision table, no service needed.
- SATELLITES: flight-recorder rotation cap, trace-span folding,
  bench_report schema gate over the committed captures.
"""

import json
import os

import numpy as np
import pytest

from tpu_pbrt import config
from tpu_pbrt.obs.metrics import (
    METRICS,
    MetricsRegistry,
    fold_trace,
    percentile_from_buckets,
    phase_summary,
    validate_exposition,
    validate_snapshot,
)
from tpu_pbrt.serve.queue import SloPolicy, parse_slo_spec


def _render_cornell(**kw):
    from tpu_pbrt.scenes import compile_api, make_cornell

    api = make_cornell(res=16, spp=4, integrator="path", maxdepth=3, **kw)
    scene, integ = compile_api(api)
    return scene, integ


# ---------------------------------------------------------------------------
# registry core: determinism + percentile math
# ---------------------------------------------------------------------------


class TestRegistry:
    def _fill(self, reg):
        h = reg.histogram("t_seconds", "latencies", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.06, 0.5, 2.0):
            h.observe(v, tenant="alice", job="j1")
        h.observe(0.05, tenant='bo"b\\x', job="j2")
        c = reg.counter("events_total", "events")
        c.inc(3, kind="a")
        c.inc(kind="b")
        reg.gauge("depth", "queue depth").set(4, priority="0")
        return reg

    def test_snapshot_and_exposition_deterministic(self):
        a = self._fill(MetricsRegistry())
        b = self._fill(MetricsRegistry())
        assert a.exposition() == b.exposition()
        assert a.snapshot() == b.snapshot()
        # and insertion ORDER does not matter: label keys are canonical
        c = MetricsRegistry()
        h = c.histogram("t_seconds", "latencies", buckets=(0.01, 0.1, 1.0))
        h.observe(0.05, job="j2", tenant='bo"b\\x')  # kwargs reordered
        for v in (0.005, 0.05, 0.06, 0.5, 2.0):
            h.observe(v, job="j1", tenant="alice")
        cc = c.counter("events_total", "events")
        cc.inc(kind="b")
        cc.inc(3, kind="a")
        c.gauge("depth", "queue depth").set(4, priority="0")
        assert c.exposition() == a.exposition()

    def test_own_exposition_and_snapshot_validate(self):
        reg = self._fill(MetricsRegistry())
        assert validate_exposition(reg.exposition()) == []
        assert validate_snapshot(reg.snapshot()) == []

    def test_counter_rejects_decrement_and_kind_conflict(self):
        reg = MetricsRegistry()
        c = reg.counter("x_total")
        with pytest.raises(ValueError, match="decremented"):
            c.inc(-1)
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_histogram_rejects_edge_conflict(self):
        """Two sites re-registering one histogram with different edges
        must raise — silently sharing the first site's buckets would
        funnel the second site's scale into +Inf."""
        reg = MetricsRegistry()
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        assert reg.histogram("h_seconds", buckets=(0.1, 1.0)) is h
        with pytest.raises(ValueError, match="edges"):
            reg.histogram("h_seconds", buckets=(1.0, 2.0))

    def test_window_p90_nearest_rank(self):
        """The wait-SLO window percentile is nearest-rank: 2 outliers in
        a window of 20 must NOT decide the p90."""
        from tpu_pbrt.serve.service import _window_p90

        assert _window_p90([]) is None
        assert _window_p90([0.3]) == 0.3
        w = [0.1] * 18 + [10.0, 10.0]
        assert _window_p90(w) == 0.1  # rank ceil(18)=18 of 20
        assert _window_p90([0.1] * 17 + [10.0] * 3) == 10.0

    def test_percentiles_from_buckets_exact(self):
        # counts [1,1,1,1] over edges (1,2,4): hand-computed quantiles
        edges = (1.0, 2.0, 4.0)
        counts = [1, 1, 1, 1]
        assert percentile_from_buckets(edges, counts, 0.25) == 1.0
        assert percentile_from_buckets(edges, counts, 0.5) == 2.0
        assert percentile_from_buckets(edges, counts, 0.75) == 4.0
        # the +Inf bucket clamps to the last finite edge
        assert percentile_from_buckets(edges, counts, 0.99) == 4.0
        assert percentile_from_buckets(edges, [0, 0, 0, 0], 0.5) is None
        # interpolation inside a bucket: 10 values in (1, 2]
        assert percentile_from_buckets(
            edges, [0, 10, 0, 0], 0.5
        ) == pytest.approx(1.5)

    def test_histogram_percentile_label_match(self):
        reg = MetricsRegistry()
        h = reg.histogram("w", buckets=(1.0, 2.0))
        for _ in range(4):
            h.observe(0.5, priority="0", tenant="a")
        for _ in range(4):
            h.observe(1.5, priority="1", tenant="b")
        assert h.percentile(0.9, match={"priority": "0"}) <= 1.0
        assert h.percentile(0.9, match={"priority": "1"}) > 1.0
        # subset semantics: {} aggregates everything
        assert h.percentile(0.5, match={}) is not None

    def test_kill_switch_records_nothing(self, monkeypatch):
        monkeypatch.setenv("TPU_PBRT_METRICS", "0")
        config.reload()
        reg = self._fill(MetricsRegistry())
        assert reg.exposition() == ""
        assert reg.snapshot()["metrics"]["tpu_pbrt_events_total"][
            "series"
        ] == []


# ---------------------------------------------------------------------------
# exposition lint: the drift classes that break a scraper
# ---------------------------------------------------------------------------


class TestExpositionLint:
    def test_missing_type_line(self):
        assert validate_exposition("foo 1\n")

    def test_bad_label_escaping(self):
        text = (
            "# TYPE m counter\n"
            'm{a="unescaped"quote"} 1\n'
        )
        assert any("label" in e for e in validate_exposition(text))

    def test_escaped_labels_accepted(self):
        text = (
            "# TYPE m counter\n"
            'm{a="back\\\\slash \\"quote\\" \\nnl"} 1\n'
        )
        assert validate_exposition(text) == []

    def test_non_monotone_buckets(self):
        text = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\n'
            'h_bucket{le="2"} 3\n'
            'h_bucket{le="+Inf"} 5\n'
            "h_sum 7\n"
            "h_count 5\n"
        )
        assert any("monotone" in e for e in validate_exposition(text))

    def test_count_must_match_inf_bucket(self):
        text = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 2\n'
            'h_bucket{le="+Inf"} 2\n'
            "h_sum 1\n"
            "h_count 9\n"
        )
        assert any("_count" in e for e in validate_exposition(text))

    def test_missing_inf_bucket(self):
        text = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 2\n'
            "h_sum 1\n"
            "h_count 2\n"
        )
        assert any("+Inf" in e for e in validate_exposition(text))

    def test_snapshot_validator_rejects_drift(self):
        assert validate_snapshot({"schema": "nope"})
        doc = {
            "schema": "tpu-pbrt-metrics-v1",
            "metrics": {
                "m": {"type": "histogram", "help": "", "series": [{
                    "labels": {}, "buckets": ["1", "+Inf"],
                    "counts": [1], "sum": 1.0, "count": 1,
                }]},
            },
        }
        assert any("counts" in e for e in validate_snapshot(doc))


# ---------------------------------------------------------------------------
# SLO shed decision table (pure policy, no service)
# ---------------------------------------------------------------------------


class TestSloPolicy:
    def test_parse_spec(self):
        assert parse_slo_spec("8", int) == {None: 8}
        assert parse_slo_spec("0=4, 5=32", int) == {0: 4, 5: 32}
        assert parse_slo_spec("default=2,1=3", float) == {None: 2.0, 1: 3.0}
        assert parse_slo_spec("", int) == {}
        with pytest.raises(ValueError):
            parse_slo_spec("x=y", int)

    def test_decision_table(self):
        p = SloPolicy(
            depth=parse_slo_spec("default=2,5=10", int),
            wait_s=parse_slo_spec("0=0.5", float),
        )
        table = [
            # (priority, depth, wait_p90, admit?)
            (0, 0, None, True),
            (0, 1, None, True),
            (0, 2, None, False),  # at the default depth target
            (5, 9, None, True),  # class-5 override
            (5, 10, None, False),
            (0, 0, 0.4, True),
            (0, 0, 0.6, False),  # wait breach
            (3, 0, 99.0, True),  # class 3 has no wait target
            (0, 99, None, False),
        ]
        for prio, depth, p90, want in table:
            ok, reason = p.admit(prio, depth, p90)
            assert ok is want, (prio, depth, p90, reason)
            assert ok == (reason == "")

    def test_disabled_policy_admits_everything(self):
        p = SloPolicy()
        assert not p.enabled()
        assert p.admit(0, 10_000, 1e9) == (True, "")

    def test_deterministic_burst(self):
        """The same burst against the same policy sheds the same
        requests — admission is a pure function, twice."""
        def run():
            p = SloPolicy(depth={None: 3})
            out = []
            depth = 0
            for _ in range(6):
                ok, _ = p.admit(0, depth)
                out.append(ok)
                depth += 1 if ok else 0
            return out

        assert run() == run() == [True, True, True, False, False, False]


# ---------------------------------------------------------------------------
# trace-span folding (the offline half of phase attribution)
# ---------------------------------------------------------------------------


class TestFoldTrace:
    def _doc(self, tracer):
        ev = []
        for i, dur_us in enumerate((2e6, 3e6, 4e6)):
            ev.append({
                "name": "render/chunk_dispatch", "ph": "X", "ts": i * 1e6,
                "dur": dur_us, "pid": 0, "tid": 0,
                "args": {"chunk": i, "tracer": tracer},
            })
        ev.append({
            "name": "render/develop", "ph": "X", "ts": 9e6, "dur": 1e5,
            "pid": 0, "tid": 0, "args": {},
        })
        ev.append({"name": "unrelated", "ph": "i", "ts": 0, "pid": 0,
                   "tid": 0, "s": "p"})
        return {"traceEvents": ev}

    def test_fold_labels_by_tracer(self):
        reg = MetricsRegistry()
        assert fold_trace(self._doc("fused"), reg) == 4
        assert fold_trace(self._doc("jnp"), reg) == 4
        summ = phase_summary(reg)
        assert set(summ) == {"dispatch", "deposit_develop"}
        assert summ["dispatch"]["count"] == 6
        h = reg.histogram("render_phase_seconds")
        fused = h.aggregate(match={"phase": "dispatch", "tracer": "fused"})
        jnp_ = h.aggregate(match={"phase": "dispatch", "tracer": "jnp"})
        assert fused["count"] == jnp_["count"] == 3
        assert fused["seconds"] == pytest.approx(9.0)

    def test_fold_from_file(self, tmp_path):
        p = tmp_path / "trace.json"
        p.write_text(json.dumps(self._doc("jnp")))
        reg = MetricsRegistry()
        assert fold_trace(str(p), reg) == 4


# ---------------------------------------------------------------------------
# render-loop phase attribution + the kill-switch bit-identity acceptance
# ---------------------------------------------------------------------------


class TestRenderPhases:
    def test_phase_attribution_and_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TPU_PBRT_METRICS", "1")
        config.reload()
        METRICS.reset()
        scene, integ = _render_cornell()
        r_on = integ.render(scene)
        ph = r_on.stats.get("phase_seconds")
        assert ph, "metrics-on render must report phase attribution"
        assert "dispatch_compile" in ph or "dispatch" in ph
        assert "deposit_develop" in ph
        summ = phase_summary()
        assert summ and all(v["count"] >= 1 for v in summ.values())
        # the registry's own exposition lints clean
        assert validate_exposition(METRICS.exposition()) == []
        # the inline attribution carries the tracer label (the ROADMAP
        # #1 fused-vs-jnp evidence channel; this cornell compiles to the
        # brute MXU path, whose plans label as the jnp tracer)
        h = METRICS.histogram("render_phase_seconds")
        assert h.aggregate(match={"tracer": "jnp"})

        monkeypatch.setenv("TPU_PBRT_METRICS", "0")
        config.reload()
        METRICS.reset()
        r_off = integ.render(scene)
        # acceptance: the kill switch pins bit-identical stats + image
        assert "phase_seconds" not in r_off.stats
        on_stats = dict(r_on.stats)
        on_stats.pop("phase_seconds")
        assert on_stats == r_off.stats
        assert np.array_equal(np.asarray(r_on.image), np.asarray(r_off.image))
        assert METRICS.exposition() == ""


# ---------------------------------------------------------------------------
# flight-recorder growth cap (satellite)
# ---------------------------------------------------------------------------


class TestFlightRotation:
    def test_rotates_once_past_cap(self, tmp_path, monkeypatch):
        from tpu_pbrt.obs.flight import FlightRecorder, validate_flight

        monkeypatch.setenv("TPU_PBRT_FLIGHT_MAX_MB", "0.0002")  # 200 bytes
        config.reload()
        p = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(p)
        for i in range(20):
            fr.heartbeat("render", chunk=i, payload="x" * 40)
        assert os.path.exists(p + ".1"), "no rotation happened"
        assert os.path.getsize(p) < 3 * 200, "live file grew past the cap"
        # both halves stay valid JSONL and no line was torn
        assert validate_flight(p) == []
        assert validate_flight(p + ".1") == []
        n = sum(
            len(open(f).read().splitlines()) for f in (p, p + ".1")
        )
        assert n >= 4  # older lines beyond one rotation are dropped

    def test_unbounded_by_default(self, tmp_path, monkeypatch):
        from tpu_pbrt.obs.flight import FlightRecorder

        monkeypatch.delenv("TPU_PBRT_FLIGHT_MAX_MB", raising=False)
        config.reload()
        p = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(p)
        for i in range(50):
            fr.heartbeat("render", chunk=i)
        assert not os.path.exists(p + ".1")
        assert len(open(p).read().splitlines()) == 50


# ---------------------------------------------------------------------------
# bench_report (satellite): trajectory table + schema gate
# ---------------------------------------------------------------------------


def _bench_report():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_report", os.path.join(root, "tools", "bench_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, root


class TestBenchReport:
    def test_committed_captures_pass_schema_gate(self, capsys):
        br, root = _bench_report()
        files = sorted(
            os.path.join(root, f) for f in os.listdir(root)
            if f.startswith("BENCH_r") and f.endswith(".json")
        )
        assert len(files) >= 5
        assert br.main(files) == 0
        table = capsys.readouterr().out
        assert "| r03 | 0.73 |" in table  # the live capture row
        assert "r05" in table

    def test_rows_carry_outage_and_trajectory_fields(self):
        br, root = _bench_report()
        rows = [
            br.load_capture(os.path.join(root, f"BENCH_r{i:02d}.json"))
            for i in (1, 3, 5)
        ]
        assert rows[0]["outage"] and rows[0]["mray_per_sec"] is None
        assert rows[1]["mray_per_sec"] == 0.73 and not rows[1]["outage"]
        assert rows[2]["outage"] is True
        for row in rows:
            for k in ("run", "roofline", "tracer", "flight_phase"):
                assert k in row

    def test_schema_drift_exits_nonzero(self, tmp_path, capsys):
        br, _ = _bench_report()
        bad = tmp_path / "BENCH_r99.json"
        bad.write_text(json.dumps({"n": 99, "cmd": "x", "rc": 0,
                                   "parsed": {"value": 1.0}}))
        assert br.main([str(bad)]) == 1
        assert "SCHEMA DRIFT" in capsys.readouterr().err

    def test_json_mode(self, capsys):
        br, root = _bench_report()
        assert br.main(
            [os.path.join(root, "BENCH_r03.json"), "--json"]
        ) == 0
        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["run"] == "r03"
