"""MLT cross-convergence tests: the PSSMLT estimator must reproduce the
path integrator's means (the normalization constant b and the Kelemen
splat weighting are exactly the things this verifies)."""

import numpy as np

from tpu_pbrt.scenes import compile_api, make_cornell


def _render(integrator, md, spp=64, res=16, **tweaks):
    api = make_cornell(res=res, spp=spp, integrator=integrator, maxdepth=md)
    scene, integ = compile_api(api)
    for k, v in tweaks.items():
        setattr(integ, k, v)
    return integ.render(scene)


def test_mlt_matches_path_direct():
    p = np.asarray(_render("path", 1, spp=64).image)
    r = _render(
        "mlt", 1, n_bootstrap=16384, n_chains=2048, mutations_per_pixel=400
    )
    m = np.asarray(r.image)
    rel = abs(m.mean() - p.mean()) / p.mean()
    assert rel < 0.08, f"mlt {m.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"
    assert np.isfinite(m).all()
    assert 0.0 < r.stats["acceptance"] < 1.0


def test_mlt_matches_path_indirect():
    p = np.asarray(_render("path", 3, spp=64).image)
    r = _render(
        "mlt", 3, n_bootstrap=16384, n_chains=2048, mutations_per_pixel=400
    )
    m = np.asarray(r.image)
    rel = abs(m.mean() - p.mean()) / p.mean()
    assert rel < 0.10, f"mlt {m.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"


def test_mlt_concentrates_on_bright_regions():
    """Metropolis mutation density follows luminance: the rendered image's
    bright/dark structure must correlate with the path render (pixelwise),
    not be uniform chain noise."""
    p = np.asarray(_render("path", 2, spp=64).image).mean(-1).ravel()
    m = np.asarray(
        _render(
            "mlt", 2, n_bootstrap=16384, n_chains=2048, mutations_per_pixel=400
        ).image
    ).mean(-1).ravel()
    c = np.corrcoef(p, m)[0, 1]
    assert c > 0.8, f"mlt image decorrelated from path ({c:.2f})"


def test_mlt_multi_device_matches_single():
    """Mesh MLT (chains sharded with global ids, splats psum-merged)
    must equal the single-device render up to f32 splat order."""
    import jax
    import pytest

    from tpu_pbrt.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    kw = dict(n_bootstrap=4096, n_chains=512, mutations_per_pixel=64)
    single = np.asarray(_render("mlt", 2, **kw).image)

    api = make_cornell(res=16, spp=64, integrator="mlt", maxdepth=2)
    scene, integ = compile_api(api)
    for k, v in kw.items():
        setattr(integ, k, v)
    multi = np.asarray(integ.render(scene, mesh=make_mesh(4)).image)

    assert np.isfinite(multi).all()
    assert abs(multi.mean() - single.mean()) / max(single.mean(), 1e-9) < 1e-3
    denom = np.maximum(np.abs(single), 1e-3)
    assert float((np.abs(multi - single) / denom).max()) < 1e-2
