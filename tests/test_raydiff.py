"""Ray differentials + trilinear mipmap selection (VERDICT r4 #4):
camera.cpp GenerateRayDifferential + interaction.cpp
ComputeDifferentials + mipmap.h Lookup. The oracle is pbrt's own
motivation: a fine checkerboard receding to the horizon aliases badly
at level 0 but converges to the 0.5 gray mean under proper filtering."""

import os
import tempfile

import numpy as np
import pytest


def _render_checker_floor(eval_mode, spp=4):
    """A high-frequency checker imagemap on a huge receding floor."""
    import tpu_pbrt
    from tpu_pbrt.utils.imageio import write_image

    # 64x64 checkerboard texture with 1-texel squares
    tex = ((np.indices((64, 64)).sum(axis=0) % 2) * 1.0).astype(np.float32)
    tex = np.repeat(tex[:, :, None], 3, axis=2)
    with tempfile.NamedTemporaryFile(suffix=".pfm", delete=False) as f:
        tex_path = f.name
    write_image(tex_path, tex)

    scene = f"""
Integrator "path" "integer maxdepth" [1]
Sampler "random" "integer pixelsamples" [{spp}]
Film "image" "integer xresolution" [48] "integer yresolution" [48]
LookAt 0 1 0  0 1 10  0 1 0
Camera "perspective" "float fov" [60]
WorldBegin
LightSource "distant" "rgb L" [3.14159 3.14159 3.14159] "point from" [0 1 0] "point to" [0 0 0]
Texture "chk" "color" "imagemap" "string filename" ["{tex_path}"]
  "float uscale" [100] "float vscale" [100]
Material "matte" "texture Kd" ["chk"]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
  "point P" [-200 0 0  200 0 0  200 0 400  -200 0 400]
  "float uv" [0 0  1 0  1 1  0 1]
WorldEnd
"""
    with tempfile.NamedTemporaryFile("w", suffix=".pbrt", delete=False) as f:
        f.write(scene)
        path = f.name
    old = os.environ.get("TPU_PBRT_MIPFILTER")
    try:
        if eval_mode == "level0":
            os.environ["TPU_PBRT_MIPFILTER"] = "0"
        else:
            os.environ.pop("TPU_PBRT_MIPFILTER", None)
        from tpu_pbrt import config

        config.reload()
        res = tpu_pbrt.render_file(path)
        return np.asarray(res.image)
    finally:
        if old is None:
            os.environ.pop("TPU_PBRT_MIPFILTER", None)
        else:
            os.environ["TPU_PBRT_MIPFILTER"] = old
        os.unlink(path)
        os.unlink(tex_path)


def test_distant_checker_filters_toward_mean():
    """Far rows of a receding fine checker must approach the checker
    mean (0.5 albedo) under trilinear mip filtering, while the level-0
    path stays noisy/aliased there. Albedo ~0.5 under a head-on distant
    light of radiance pi means pixel values near 0.5."""
    img_f = _render_checker_floor("filtered")
    img_0 = _render_checker_floor("level0")
    assert np.isfinite(img_f).all() and np.isfinite(img_0).all()

    # the rows just under the horizon (image center) see the distant
    # floor: footprint spans many checker cells -> filtered variance
    # collapses
    far_f = img_f[25:31, :, 0]
    far_0 = img_0[25:31, :, 0]
    var_f = float(far_f.var())
    var_0 = float(far_0.var())
    assert var_f < 0.35 * var_0, (
        f"filtered far-field variance {var_f:.5f} vs level0 {var_0:.5f}"
    )
    # and the filtered far field sits near the true mean
    assert abs(float(far_f.mean()) - 0.5) < 0.08, float(far_f.mean())


def test_filtering_monotone_with_distance():
    """Filtering must attack the far field much harder than the near
    field (the footprint grows with distance), and both bands must sit
    near the checker mean: the signature of correct LOD selection.
    (At this scene's uscale the near field's footprint already spans a
    few texels, so expecting level-0 sharpness there would be wrong —
    pbrt's UVMapping2D scales the differentials by uscale too.)"""
    img_f = _render_checker_floor("filtered")
    near_f = img_f[40:, :, 0]
    far_f = img_f[25:31, :, 0]
    assert near_f.std() > 3.0 * far_f.std()
    assert abs(float(near_f.mean()) - 0.5) < 0.15
    assert abs(float(far_f.mean()) - 0.5) < 0.08
