"""hbmcheck (ISSUE 18): static HBM residency, liveness & capacity
verification across the serve stack (analysis layer 7).

Five pieces under test: the memory model itself (film/job/worst-case
closed forms vs the HC-ALIAS symbolic buffer graph), the HC-* rule
families with synthetic positives AND negatives, the committed
hbm_budgets.json gate (regression -> --update-budgets -> clean round
trip), the --derive-hbm-caps inversion (the committed serve knob
defaults must be reproducible consequences of the model), and the
dynamic cross-check — the serve leak fixes this PR landed, asserted on
a REAL RenderService under a VirtualClock, plus the seeded
park-skips-film-release mutant flagged by PROTO-HBM through the real
`tools/explore.py --mutate` entry point.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from tpu_pbrt.analysis import hbmcheck as hc
from tpu_pbrt.analysis import protocheck as pc
from tpu_pbrt.integrators.common import live_film_carries

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"_hbmcheck_test_{name}", os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def explore():
    return _load_tool("explore")


# ---------------------------------------------------------------------------
# the memory model
# ---------------------------------------------------------------------------


class TestModel:
    def test_film_state_bytes_matches_live_layout(self):
        # rgb(3) + weight(1) + splat(3) f32 planes = 28 B/pixel
        assert hc.film_state_bytes(1, 1) == 28
        assert hc.film_state_bytes(512, 512) == 512 * 512 * 28
        assert hc.film_state_bytes(2, 2) == 112  # the protocheck stub film

    def test_live_film_carries_donation_collapse(self):
        # depth 1 donates in/out: ONE buffer; depth d>1 pins every
        # un-donated in-flight input carry + the newest output
        assert live_film_carries(1) == 1
        assert live_film_carries(0) == 1  # clamped
        assert live_film_carries(2) == 3
        assert live_film_carries(3) == 4

    def test_job_bytes_closed_form(self):
        fb = hc.film_state_bytes(*hc.REF_FILM)
        assert hc.job_hbm_bytes(fb, 1) == fb + hc.COUNTER_BYTES_PER_SLICE
        assert hc.job_hbm_bytes(fb, 2) == 3 * fb + 2 * hc.COUNTER_BYTES_PER_SLICE

    def test_serve_model_totals_add_up(self):
        m = hc.serve_model()
        assert m["total_bytes"] == (
            m["resident_bytes"] + m["jobs_bytes"]
            + m["prefetch_bytes"] + m["staging_bytes"]
        )
        assert m["jobs_bytes"] == m["max_active"] * m["job_bytes"]
        # the configured default budget is finite (the PR-18 knob)
        assert m["resident_bytes"] > 0


class TestHcCap:
    def test_clean_model_fits(self):
        assert hc.check_capacity(hc.serve_model()) == []

    def test_synthetic_over_cap_named(self):
        # a resident budget past the smallest platform's HBM must fail
        # naming the rule (the ISSUE-18 acceptance shape)
        m = hc.serve_model(resident_bytes=64 * hc.GiB)
        errs = hc.check_capacity(m)
        assert len(errs) == 1 and errs[0].startswith("HC-CAP:")

    def test_over_cap_config_exits_nonzero_via_cli(self):
        # the REAL entry point: the synthetic over-cap config must exit
        # non-zero and name HC-CAP
        import subprocess
        import sys

        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            TPU_PBRT_SERVE_RESIDENT_MB="65536",
        )
        r = subprocess.run(
            [sys.executable, "-m", "tpu_pbrt.analysis.hbmcheck"],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "HC-CAP" in r.stdout


class TestHcAcct:
    def test_reference_scene_within_tolerance(self):
        assert hc.acct_check() == []

    def test_lying_nbytes_detected(self):
        # an estimator trusting a bogus nbytes attribute must be caught
        # against the aval-exact shape x itemsize walk
        class _Lying:
            shape = (1024, 1024)
            dtype = np.float32
            nbytes = 64  # lies: exact is 4 MiB

        sc = hc.reference_scene()
        sc.dev["liar"] = _Lying()
        errs = hc.acct_check(sc)
        assert len(errs) == 1 and errs[0].startswith("HC-ACCT:")

    def test_exact_walk_is_shape_times_itemsize(self):
        sc = hc.reference_scene()
        want = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in (
                sc.dev["tri_verts9T"], sc.dev["tstream"]["slabs48"],
                sc.dev["tstream"]["child_idx"], sc.dev["tex_atlas_u8"],
                sc.dev["light_cdf"], sc.dev["mat_table"],
            )
        ) + hc.film_state_bytes(*hc.REF_FILM)
        assert hc.exact_scene_bytes(sc) == want


class TestHcAlias:
    def test_clean_graphs_reproduce_closed_form(self):
        assert hc.alias_audit() == []

    def test_depth1_donation_is_one_buffer(self):
        fb = hc.film_state_bytes(*hc.REF_FILM)
        bufs = hc.job_buffers(fb, 1)
        # carry_out and ckpt_snap both alias carry0: dedup counts once
        assert hc.dedup_bytes(bufs) == fb + hc.COUNTER_BYTES_PER_SLICE

    def test_donated_without_alias_edge_flagged(self):
        bufs = [
            hc.Buf("carry0", 100),
            hc.Buf("carry_out", 100, donated=True),  # missing alias_of
        ]
        errs = hc.check_alias(bufs)
        assert len(errs) == 1 and "double-count" in errs[0]
        assert errs[0].startswith("HC-ALIAS:")

    def test_unresolvable_alias_flagged(self):
        errs = hc.check_alias(
            [hc.Buf("snap", 100, alias_of="ghost")]
        )
        assert len(errs) == 1 and "unknown buffer" in errs[0]


# ---------------------------------------------------------------------------
# HC-LEAK static rule
# ---------------------------------------------------------------------------

_SVC = "tpu_pbrt/serve/service.py"
_RES = "tpu_pbrt/serve/residency.py"


def _rules(src, rel):
    return [v.rule for v in hc.hc_leak_source(src, rel)]


class TestHcLeak:
    def test_terminal_without_release_flagged(self):
        src = (
            "def fail(self, job):\n"
            "    job.status = FAILED\n"
            "    self.residency.unpin(job.resident_key)\n"
        )
        vs = hc.hc_leak_source(src, _SVC)
        assert [v.rule for v in vs] == ["HC-LEAK"]
        assert "releases no device buffers" in vs[0].message

    def test_terminal_with_release_helper_clean(self):
        src = (
            "def fail(self, job):\n"
            "    job.status = FAILED\n"
            "    self._release_device(job)\n"
            "    self.residency.unpin(job.resident_key)\n"
        )
        assert _rules(src, _SVC) == []

    def test_inline_release_requires_all_four_counter_lists(self):
        head = (
            "def fail(self, job):\n"
            "    job.status = CANCELLED\n"
            "    job.state = None\n"
            "    self.residency.unpin(job.resident_key)\n"
        )
        partial = head + (
            "    job.ray_counts.clear()\n"
            "    job.occ_counts.clear()\n"
        )
        full = partial + (
            "    job.ctr_counts.clear()\n"
            "    job.nf_counts.clear()\n"
        )
        assert _rules(partial, _SVC) == ["HC-LEAK"]
        assert _rules(full, _SVC) == []

    def test_terminal_without_unpin_flagged(self):
        src = (
            "def fin(self, job):\n"
            "    job.status = DONE\n"
            "    self._release_device(job)\n"
        )
        vs = hc.hc_leak_source(src, _SVC)
        assert [v.rule for v in vs] == ["HC-LEAK"]
        assert "pin" in vs[0].message

    def test_non_terminal_status_untouched(self):
        src = "def park(self, job):\n    job.status = PARKED\n"
        assert _rules(src, _SVC) == []

    def test_outside_serve_modules_unscoped(self):
        src = "def fail(self, job):\n    job.status = FAILED\n"
        assert _rules(src, "tpu_pbrt/film/image.py") == []

    def test_eviction_without_pin_check_flagged(self):
        bad = (
            "def evict(self):\n"
            "    for k in list(self._entries):\n"
            "        del self._entries[k]\n"
        )
        good = (
            "def evict(self):\n"
            "    for k, e in list(self._entries.items()):\n"
            "        if e.pins == 0:\n"
            "            del self._entries[k]\n"
        )
        vs = hc.hc_leak_source(bad, _RES)
        assert [v.rule for v in vs] == ["HC-LEAK"]
        assert "pin counts" in vs[0].message
        assert _rules(good, _RES) == []

    def test_pragma_suppression(self):
        src = (
            "def fail(self, job):  # jaxlint: disable=HC-LEAK\n"
            "    job.status = FAILED\n"
        )
        assert _rules(src, _SVC) == []

    def test_syntax_error_is_a_finding_not_a_crash(self):
        assert _rules("def broken(:\n", _SVC) == ["HC-PARSE"]

    def test_repo_tree_is_clean(self):
        assert hc.hc_leak_tree() == []


# ---------------------------------------------------------------------------
# budgets: regression -> refresh -> clean round trip
# ---------------------------------------------------------------------------


class TestBudgets:
    def test_committed_budgets_gate_clean(self):
        entries = hc.collect_entries()
        errs, _warns = hc.check_budgets(entries, hc.load_budgets())
        assert errs == []

    def test_missing_entry_is_an_error(self):
        errs, _ = hc.check_budgets(hc.collect_entries(), {"entries": {}})
        assert errs and all("no committed HBM budget" in e for e in errs)

    def test_regression_then_update_then_clean(self, tmp_path):
        p = tmp_path / "hbm_budgets.json"
        entries = hc.collect_entries()
        hc.save_budgets(entries, p, tolerance=0.1)
        # a 2x footprint regression must gate...
        grown = {
            k: dict(v, hbm_bytes=v["hbm_bytes"] * 2)
            for k, v in entries.items()
        }
        errs, _ = hc.check_budgets(grown, hc.load_budgets(p))
        assert errs and all("regressed" in e for e in errs)
        # ...an improvement only warns (ratchet hint)...
        shrunk = {
            k: dict(v, hbm_bytes=max(v["hbm_bytes"] // 2, 1))
            for k, v in entries.items()
        }
        errs, warns = hc.check_budgets(shrunk, hc.load_budgets(p))
        assert errs == [] and warns
        # ...and --update-budgets closes the loop, keeping tolerance
        hc.save_budgets(grown, p, tolerance=0.1)
        errs, warns = hc.check_budgets(grown, hc.load_budgets(p))
        assert errs == [] and warns == []
        assert json.loads(p.read_text())["tolerance"] == 0.1

    def test_stale_entry_warns(self, tmp_path):
        p = tmp_path / "hbm_budgets.json"
        entries = dict(hc.collect_entries())
        entries["serve.ghost"] = {"hbm_bytes": 1, "fingerprint": "x"}
        hc.save_budgets(entries, p)
        del entries["serve.ghost"]
        errs, warns = hc.check_budgets(entries, hc.load_budgets(p))
        assert errs == []
        assert any("serve.ghost" in w and "no live model term" in w
                   for w in warns)

    def test_run_hbmcheck_repo_gate_clean(self):
        errors, _warnings = hc.run_hbmcheck()
        assert errors == []


# ---------------------------------------------------------------------------
# --derive-hbm-caps: knob defaults are consequences of the model
# ---------------------------------------------------------------------------


class TestDeriveCaps:
    def test_derived_caps_admit_the_committed_defaults(self):
        from tpu_pbrt.config import cfg

        d = hc.derive_hbm_caps()
        assert hc.check_hbm_caps(d) == []
        c = d["configured"]
        assert c["serve_resident_mb"] == cfg.serve_resident_mb == 12288.0
        assert c["pipeline_depth"] == cfg.pipeline == 2
        worst = min(
            p["max_resident_mb_aligned"] for p in d["platforms"].values()
        )
        # the committed default IS the derive output's floor: the
        # largest 1024-aligned resident budget safe on every platform,
        # within one alignment quantum (the operator margin)
        assert worst - 1024 <= cfg.serve_resident_mb <= worst
        assert all(
            p["max_pipeline_depth"] >= cfg.pipeline
            for p in d["platforms"].values()
        )

    def test_caps_scale_with_hbm(self):
        d = hc.derive_hbm_caps()
        plats = d["platforms"]
        assert plats["v5e"]["max_active"] < plats["v4"]["max_active"]
        assert plats["v4"]["max_active"] < plats["v5p"]["max_active"]

    def test_overcommitted_knobs_flagged_by_name(self):
        d = hc.derive_hbm_caps()
        d["configured"]["serve_resident_mb"] = 1e9  # absurd
        d["configured"]["pipeline_depth"] = 10_000
        errs = hc.check_hbm_caps(d)
        assert len(errs) == 2
        assert all(e.startswith("HC-CAP:") for e in errs)

    def test_cli_reproduces_defaults(self):
        import subprocess
        import sys

        r = subprocess.run(
            [sys.executable, "-m", "tpu_pbrt.analysis.hbmcheck",
             "--derive-hbm-caps", "--format", "json"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert doc["configured"]["serve_resident_mb"] == 12288.0
        assert doc["configured"]["pipeline_depth"] == 2


# ---------------------------------------------------------------------------
# bench fields (satellite: the static HBM half of the bench line)
# ---------------------------------------------------------------------------


class TestBenchFields:
    def test_fields_present_and_sane(self):
        f = hc.bench_fields(512, 512)
        assert set(f) == {"static_hbm_per_job", "hbm_headroom"}
        assert f["static_hbm_per_job"] == hc.serve_model()["job_bytes"]
        assert 0.0 < f["hbm_headroom"] < 1.0

    def test_bench_whitelist_forwards_the_fields(self):
        # bench.py's subprocess whitelist must pass both keys through
        # (measured AND outage JSON lines ride the same helper)
        import bench

        src = open(os.path.join(REPO, "bench.py")).read()
        assert '"static_hbm_per_job"' in src
        assert '"hbm_headroom"' in src
        assert hasattr(bench, "static_wave_cost")


# ---------------------------------------------------------------------------
# the serve leak fixes (satellite 1) — real service, virtual clock
# ---------------------------------------------------------------------------


def _stub_service():
    """A real RenderService under a VirtualClock with protocheck's stub
    harness (2x2 film, 64 rays/chunk, no compile)."""
    model = pc.ProtocolModel(
        pc.Scenario(
            name="leakfix",
            jobs=(pc.JobSpec("j", n_chunks=4, checkpoint_every=2, depth=2),),
            allow=("submit", "step", "preempt", "cancel"),
        ),
        seed=0,
    )
    return model


def _device_refs(job):
    return (
        job.state, job.window,
        job.ray_counts, job.occ_counts, job.ctr_counts, job.nf_counts,
    )


class TestLeakFixes:
    def test_cancel_mid_render_releases_everything(self):
        m = _stub_service()
        try:
            m.apply(("submit", 0))
            m.apply(("step",))
            m.apply(("step",))
            job = m.svc.jobs["j"]
            assert job.ray_counts  # device counters accumulated
            m.svc.cancel("j")
            assert job.state is None and job.window is None
            assert job.plan is None  # jit closures no longer pin scene HBM
            assert not any(
                (job.ray_counts, job.occ_counts,
                 job.ctr_counts, job.nf_counts)
            )
            assert all(
                n == 0 for n in m.svc.residency.pin_counts().values()
            )
            assert m.violations == []
        finally:
            m.close()

    def test_finalize_clears_counters_and_plan_keeps_result(self):
        m = _stub_service()
        try:
            m.apply(("submit", 0))
            for _ in range(8):
                if m.svc.jobs["j"].status == "done":
                    break
                m.apply(("step",))
            job = m.svc.jobs["j"]
            assert job.status == "done"
            assert job.plan is None and job.state is None
            assert not job.ray_counts and job.window is None
            # intentional retention: the result film survives
            assert job.result is not None and job.result.film_state is not None
            # poll/progress still report totals without the plan
            assert m.svc.poll("j")["chunks_total"] == 4
            assert job.progress() == 1.0
            assert m.violations == []
        finally:
            m.close()

    def test_park_releases_film_carry(self):
        m = _stub_service()
        try:
            m.apply(("submit", 0))
            m.apply(("step",))
            m.apply(("preempt", "j"))
            job = m.svc.jobs["j"]
            assert job.status == "paused"
            assert job.state is None and job.window is None
            assert not job.ray_counts
            assert m.violations == []
        finally:
            m.close()

    def test_prefetched_then_cancelled_releases_activation(self):
        # the second ISSUE-18 suspect: a job activated by the prefetch
        # lookahead, then cancelled before its first dispatch, must not
        # strand the prefetched film state
        m = pc.ProtocolModel(
            pc.Scenario(
                name="leakfix-prefetch",
                jobs=(
                    pc.JobSpec("a", n_chunks=3, depth=2),
                    pc.JobSpec("b", n_chunks=3, depth=2),
                ),
                allow=("submit", "step", "cancel"),
            ),
            seed=0,
        )
        try:
            m.apply(("submit", 0))
            m.apply(("submit", 1))
            m.apply(("step",))  # dispatches one, prefetch-activates other
            pre = [
                j for j in m.svc.jobs.values()
                if j.status != "active" and j.state is not None
            ]
            for j in list(m.svc.jobs.values()):
                m.svc.cancel(j.job_id)
                assert j.state is None and j.window is None
                assert not j.ray_counts and j.plan is None
            held, _total = m._modeled_hbm()
            assert held == 0  # the PROTO-HBM drain baseline
            assert m.violations == []
            del pre
        finally:
            m.close()

    def test_retry_exhaustion_releases_on_failed(self):
        m = pc.ProtocolModel(
            pc.Scenario(
                name="leakfix-fail",
                jobs=(pc.JobSpec("j", n_chunks=2, depth=1),),
                fault="dispatch:fail@chunk=0&times=99",
                allow=("submit", "step", "advance"),
            ),
            seed=0,
        )
        try:
            m.apply(("submit", 0))
            for _ in range(64):
                job = m.svc.jobs["j"]
                if job.status == "failed":
                    break
                if m.apply(("step",)) == "idle":
                    m.apply(("advance",))
            job = m.svc.jobs["j"]
            assert job.status == "failed"
            assert job.state is None and job.window is None
            assert not any(
                (job.ray_counts, job.occ_counts,
                 job.ctr_counts, job.nf_counts)
            )
            assert job.plan is None
            held, _ = m._modeled_hbm()
            assert held == 0
        finally:
            m.close()


# ---------------------------------------------------------------------------
# the dynamic cross-check: PROTO-HBM + the seeded mutant via the CLI
# ---------------------------------------------------------------------------


class TestProtoHbm:
    def test_leak_mutant_detected_by_name_via_cli(self, explore, capsys):
        rc = explore.main(["--mutate", "park-skips-film-release"])
        out = capsys.readouterr().out
        assert rc == 1, out
        assert "PROTOCHECK VIOLATION PROTO-HBM" in out
        assert "film carry" in out

    def test_clean_tree_passes_the_leak_case(self):
        viol, _log = pc.run_mutation_case(
            "park-skips-film-release", mutate=False
        )
        assert viol == []

    def test_watermark_bounded_and_returns_to_baseline(self, explore):
        duo = next(s for s in pc.smoke_scenarios() if s.name == "duo-d2")
        _decisions, _log, viol = explore.canonical_drain(duo, seed=0)
        assert viol == []

    def test_static_worst_bounds_modeled_peak(self):
        m = _stub_service()
        try:
            m.apply(("submit", 0))
            m.apply(("step",))
            m.apply(("step",))
            assert 0 < m.hbm_peak <= m._static_worst_hbm()
        finally:
            m.close()
