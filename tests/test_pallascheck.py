"""pallascheck (ISSUE 11 tentpole): static VMEM budgets and
grid-semantics verification of the fused Pallas kernels — adversarial
synthetic kernels (an injected parallel-dim accumulator race, a missing
init seed, an out-of-bounds dynamic store, a VMEM-oversized block — each
caught), the cap derivation against the committed defaults, the
vmem_budgets.json gate workflow over a temp file, the repo-level mirror
of the CLI gate, and the mutation tests: deleting `_flush_kernel`'s
`@pl.when(b == 0)` seed or flipping its grid dim to "parallel" must exit
non-zero with a diagnostic naming the entry point."""

from functools import partial

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import tpu_pbrt.accel.fusedwave as fw
from tpu_pbrt.accel.stream import clear_traverse_caches
from tpu_pbrt.analysis import pallascheck as pc
from tpu_pbrt.config import cfg

# ---------------------------------------------------------------------------
# synthetic kernel fixtures
# ---------------------------------------------------------------------------


def _accum_call(x, *, seed: bool, semantics=("arbitrary",)):
    """A miniature flush-shaped accumulator: constant-index_map output
    revisited across a 4-step grid, optionally seeded on step 0."""

    def kern(x_ref, o_ref):
        b = pl.program_id(0)
        if seed:
            @pl.when(b == 0)
            def _():
                o_ref[...] = jnp.zeros_like(o_ref)

        cur = o_ref[...]
        o_ref[...] = cur + x_ref[...]

    return pl.pallas_call(
        kern,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=semantics,
        ),
        interpret=True,
    )(x)


def _kernels(fn, *args, entry="fixture"):
    jx = jax.make_jaxpr(fn)(*args)
    infos = pc.extract_kernels(jx, entry)
    assert infos, "fixture produced no pallas_call"
    findings = []
    for i in infos:
        findings.extend(pc.check_kernel(i))
    return infos, [f for f in findings if f.waived is None]


X = jnp.ones((4, 128), jnp.float32)


def test_parallel_dim_accumulator_race_flagged():
    """ISSUE 11 satellite: a revisited (constant index_map) output under
    a grid dim declared "parallel" is the megacore race pallascheck
    exists to catch."""
    _, findings = _kernels(
        lambda x: _accum_call(x, seed=True, semantics=("parallel",)), X
    )
    assert any(f.rule == "PC-RACE" for f in findings), findings


def test_sequential_accumulator_clean():
    _, findings = _kernels(
        lambda x: _accum_call(x, seed=True, semantics=("arbitrary",)), X
    )
    assert findings == [], findings


def test_missing_init_seed_flagged():
    """Reading the revisited accumulator with no grid-step-0 seed reads
    uninitialized VMEM on step 0."""
    _, findings = _kernels(lambda x: _accum_call(x, seed=False), X)
    assert any(f.rule == "PC-INIT" for f in findings), findings


def test_seed_survives_sequential_data_dependent_whens():
    """The stage-two megakernel shape: a step-0 seed followed by TWO
    sequential data-dependent @pl.when blocks each reading the
    accumulator must stay clean — the must-join over a cond must not
    clear init state the cond never touched (regression: branch-local
    alias ids leaking into the join)."""

    def call(x):
        def kern(x_ref, o_ref):
            b = pl.program_id(0)

            @pl.when(b == 0)
            def _():
                o_ref[...] = jnp.zeros_like(o_ref)

            @pl.when(x_ref[0, 0] > 0)
            def _():
                o_ref[...] = o_ref[...] + x_ref[...]

            @pl.when(x_ref[0, 1] > 0)
            def _():
                o_ref[...] = o_ref[...] * 2.0

        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
            interpret=True,
        )(x)

    _, findings = _kernels(call, X)
    assert findings == [], findings


def test_swap_old_value_before_seed_flagged():
    """A swap's RETURNED old value consumed before the step-0 seed is a
    read of uninitialized VMEM — but the seed itself (a swap whose old
    value is discarded) must stay clean."""

    def call(x):
        def kern(x_ref, o_ref):
            old = pl.swap(
                o_ref, (slice(None), slice(None)), x_ref[...]
            )
            o_ref[...] = old + x_ref[...]

        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
            interpret=True,
        )(x)

    _, findings = _kernels(call, X)
    assert any(f.rule == "PC-INIT" for f in findings), findings


def test_oob_dynamic_store_flagged_and_clamped_clean():
    def call(x, clamp: bool):
        def kern(x_ref, o_ref):
            def lane(i, c):
                j = jnp.clip(i * 3, 0, 127) if clamp else i * 3
                o_ref[0, j] = x_ref[0, i]
                return c

            jax.lax.fori_loop(0, 128, lane, 0)

        return pl.pallas_call(
            kern,
            grid=(1,),
            in_specs=[pl.BlockSpec((1, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((1, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
            interpret=True,
        )(x)

    # i in [0, 127] -> 3*i reaches 381, provably outside the block
    _, findings = _kernels(lambda x: call(x, clamp=False), X[:1])
    oob = [f for f in findings if f.rule == "PC-OOB"]
    assert oob and "dim 1" in oob[0].detail, findings
    _, findings = _kernels(lambda x: call(x, clamp=True), X[:1])
    assert not any(f.rule == "PC-OOB" for f in findings), findings


def test_vmem_oversized_block_flagged():
    """A single block bigger than platform VMEM with headroom must fail
    the capacity check even with no committed budget involved."""
    big = jnp.zeros((2, 8, 1 << 19), jnp.float32)  # 16 MB blocks

    def call(x):
        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        return pl.pallas_call(
            kern,
            grid=(2,),
            in_specs=[pl.BlockSpec((1, 8, 1 << 19), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, 8, 1 << 19), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((2, 8, 1 << 19), jnp.float32),
            interpret=True,
        )(x)

    infos, _ = _kernels(call, big)
    errors = pc.check_capacity({i.key: i for i in infos})
    assert errors and "PC-VMEM" in errors[0], errors


def test_double_buffer_charging():
    """Moving blocks are charged x2 (double-buffered), constant-index_map
    blocks once, scratch flat — the model the budget file commits."""

    def call(x):
        def kern(x_ref, c_ref, o_ref, scr):
            scr[...] = x_ref[...] + c_ref[...]
            o_ref[...] = scr[...]

        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[
                pl.BlockSpec((1, 128), lambda i: (i, 0)),  # moving
                pl.BlockSpec((1, 128), lambda i: (0, 0)),  # resident
            ],
            out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((4, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((1, 128), jnp.float32)],
            interpret=True,
        )(x, x[:1])

    infos, findings = _kernels(call, X)
    assert findings == [], findings
    (info,) = infos
    blk = 128 * 4
    assert info.vmem_bytes == 2 * blk + blk + 2 * blk + blk


# ---------------------------------------------------------------------------
# cap derivation (the hand-set caps as a checked consequence)
# ---------------------------------------------------------------------------


def test_derive_caps_reproduces_committed_defaults():
    """ISSUE 11 acceptance: --derive-caps reproduces the configured
    fused_max_rays=2^18 / fused_max_nodes=2^14 from the VMEM model (not
    from the constants), and the PC-CAPS check passes."""
    d = pc.derive_caps()
    for p in d["platforms"].values():
        assert p["max_rays"] >= cfg.fused_max_rays
        assert p["max_rays_pow2"] == cfg.fused_max_rays
        assert p["max_nodes"] >= cfg.fused_max_nodes
        assert p["max_nodes_pow2"] == cfg.fused_max_nodes
        # the docstring-era budget math survives as model coefficients:
        # 48 B/ray flush ((8,R) f32 table + two (R,) in + two (R,) out)
        assert p["flush_bytes_per_ray"] == 48
    assert pc.check_caps(d) == []


def test_caps_check_fails_when_cap_exceeds_model(monkeypatch):
    monkeypatch.setattr(cfg, "fused_max_rays", 1 << 22)
    errors = pc.check_caps()
    assert errors and "PC-CAPS" in errors[0] and "MAX_RAYS" in errors[0]


def test_wave_vmem_monotone():
    a = pc.wave_vmem(1 << 12, 256)
    b = pc.wave_vmem(1 << 13, 256)
    assert 0 < a < b


# ---------------------------------------------------------------------------
# the vmem_budgets.json gate workflow (temp file)
# ---------------------------------------------------------------------------


def _toy_entries(scale: int):
    def build():
        x = jnp.ones((4, 128 * scale), jnp.float32)

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        def call(v):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[
                    pl.BlockSpec((1, 128 * scale), lambda i: (i, 0))
                ],
                out_specs=pl.BlockSpec((1, 128 * scale), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(
                    (4, 128 * scale), jnp.float32
                ),
                interpret=True,
            )(v)

        return jax.make_jaxpr(call)(x)

    return {"toy": build}


def test_budget_gate_update_workflow(tmp_path):
    path = tmp_path / "vmem_budgets.json"
    errors, _ = pc.run_pallascheck(
        update=False, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors and "no committed VMEM budget" in errors[0]
    errors, _ = pc.run_pallascheck(
        update=True, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors == [], errors
    errors, _ = pc.run_pallascheck(
        update=False, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors == [], errors
    # synthetic regression: blocks 4x bigger -> gate fails
    errors, _ = pc.run_pallascheck(
        update=False, budgets_path=path, entries=_toy_entries(4)
    )
    assert errors and "regressed" in errors[0], errors
    # --update-budgets clears it
    pc.run_pallascheck(
        update=True, budgets_path=path, entries=_toy_entries(4)
    )
    errors, _ = pc.run_pallascheck(
        update=False, budgets_path=path, entries=_toy_entries(4)
    )
    assert errors == [], errors


def test_budget_improvement_is_ratchet_warning(tmp_path):
    path = tmp_path / "vmem_budgets.json"
    pc.run_pallascheck(update=True, budgets_path=path,
                       entries=_toy_entries(4))
    errors, warnings = pc.run_pallascheck(
        update=False, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors == []
    assert any("improved" in w for w in warnings)


# ---------------------------------------------------------------------------
# the repo gate (tier-1 mirror of the CLI acceptance criterion)
# ---------------------------------------------------------------------------


def test_repo_fused_entry_points_clean():
    """ISSUE 11 acceptance: pallascheck runs clean over every fused
    entry point against the committed vmem_budgets.json, including the
    PC-CAPS derivation."""
    errors, _ = pc.run_pallascheck()
    assert errors == [], "\n".join(errors)


def _refresh_fused_caches():
    fw.fused_flush_chunk.clear_cache()
    fw.fused_expand.clear_cache()
    clear_traverse_caches()


@pytest.fixture
def _clean_fused_caches():
    """The mutation tests re-trace the REAL entry points with a mutated
    kernel; the module-level jit caches key on avals only, so they must
    be dropped around the mutation or later tests inline the mutant."""
    _refresh_fused_caches()
    yield
    _refresh_fused_caches()


def _stream_entry():
    from tpu_pbrt.analysis import audit

    return {
        "stream_intersect_fused": lambda: audit.stream_traversal_jaxpr(
            fused=True
        ),
    }


def test_mutation_deleting_flush_seed_is_caught(
    monkeypatch, _clean_fused_caches
):
    """ISSUE 11 acceptance: deleting the @pl.when(b == 0) accumulator
    seed in _flush_kernel exits non-zero with a PC-INIT diagnostic
    naming the entry point."""
    monkeypatch.setattr(fw, "_seed_accumulators", lambda *refs: None)
    _refresh_fused_caches()
    errors, _ = pc.run_pallascheck(
        entries=_stream_entry(), check_caps_too=False
    )
    init = [e for e in errors if "PC-INIT" in e]
    assert init and "stream_intersect_fused" in init[0], errors


def test_mutation_parallel_flush_dim_is_caught(
    monkeypatch, _clean_fused_caches
):
    """... and flipping the flush grid dim to "parallel" exits non-zero
    with a PC-RACE diagnostic naming the entry point."""
    monkeypatch.setattr(fw, "FLUSH_DIM_SEMANTICS", ("parallel",))
    _refresh_fused_caches()
    errors, _ = pc.run_pallascheck(
        entries=_stream_entry(), check_caps_too=False
    )
    race = [e for e in errors if "PC-RACE" in e]
    assert race and "stream_intersect_fused" in race[0], errors


# ---------------------------------------------------------------------------
# CLI plumbing (ISSUE 11 satellite: uniform stage flags, no fail-fast)
# ---------------------------------------------------------------------------


def test_cli_reports_every_failing_stage(monkeypatch):
    """A crashed stage must not stop the suite: every later stage still
    runs and every failing stage is reported before the non-zero exit."""
    import tpu_pbrt.analysis.__main__ as amain

    calls = []

    def fake_cost(update=False):
        calls.append("cost")
        raise RuntimeError("cost stage exploded")

    def fake_shard():
        calls.append("shardcheck")
        return (["SC-UNREDUCED fixture"], [])

    def fake_pallas(update=False):
        calls.append("pallascheck")
        return (["PC-RACE fixture"], [])

    import tpu_pbrt.analysis.cost as cost_mod
    import tpu_pbrt.analysis.pallascheck as pc_mod
    import tpu_pbrt.analysis.shardcheck as shard_mod

    monkeypatch.setattr(cost_mod, "run_cost", fake_cost)
    monkeypatch.setattr(shard_mod, "run_shardcheck", fake_shard)
    monkeypatch.setattr(pc_mod, "run_pallascheck", fake_pallas)
    rc = amain.main(["--no-audit", "--format", "json"])
    assert rc == 1
    assert calls == ["cost", "shardcheck", "pallascheck"]


def test_bench_report_vmem_headroom_column(tmp_path):
    """ISSUE 11 satellite: a post-PR-11 capture's vmem_headroom reaches
    the trajectory table, and pre-PR-11 captures (no field) render as
    absent instead of failing the schema gate."""
    import importlib.util
    import json
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_report", os.path.join(root, "tools", "bench_report.py")
    )
    br = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(br)

    line = {
        "metric": "m", "value": 1.0, "unit": "Mray/s", "vs_baseline": 0.01,
        "vmem_headroom": 0.42,
    }
    new = tmp_path / "BENCH_r42.json"
    new.write_text(json.dumps({"n": 42, "cmd": "x", "rc": 0, "parsed": line}))
    row = br.load_capture(str(new))
    assert row["vmem_headroom"] == 0.42
    # committed pre-PR-11 capture: field absent, still loads
    old = br.load_capture(os.path.join(root, "BENCH_r03.json"))
    assert old["vmem_headroom"] is None
    assert ("vmem_headroom", "vmem_headroom") in br.COLUMNS


def test_cli_no_pallascheck_skips(monkeypatch):
    import tpu_pbrt.analysis.__main__ as amain
    import tpu_pbrt.analysis.pallascheck as pc_mod

    def boom(update=False):
        raise AssertionError("pallascheck ran despite --no-pallascheck")

    monkeypatch.setattr(pc_mod, "run_pallascheck", boom)
    rc = amain.main(
        ["--no-audit", "--no-cost", "--no-shardcheck", "--no-pallascheck"]
    )
    assert rc == 0
