"""jaxlint layer 2 (jaxpr/compile-time audit): the TPU hot-path
invariants asserted over the REAL render entry points (ISSUE 2
acceptance): no f64 in the path-integrator wave, film/pool donation
materialized as input->output aliasing in the executable, zero retraces
across two same-shape waves, and a clean smoke render under
jax.transfer_guard("disallow").

The golden-invariant matrix also covers volpath (homogeneous-medium
scene), bdpt and both SPPM passes — as of this PR all of them are clean,
so there are no xfail rows; a future violation fails loudly here and
must either be fixed or explicitly xfailed with a ROADMAP entry."""

import jax
import jax.numpy as jnp
import pytest

from tpu_pbrt.analysis import audit


# ---------------------------------------------------------------------------
# detector sanity: the checkers can actually see what they claim to
# ---------------------------------------------------------------------------


def test_find_f64_detects_wide_types():
    from jax.experimental import enable_x64

    with enable_x64():
        jx = jax.make_jaxpr(
            lambda x: x.astype(jnp.float64) * 2.0
        )(jnp.ones((4,), jnp.float32))
    assert audit.find_f64(jx), "f64 jaxpr not detected"


def test_find_f64_clean_on_f32():
    jx = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((4,), jnp.float32))
    assert audit.find_f64(jx) == []


def test_find_callbacks_detects_debug_print():
    def f(x):
        jax.debug.print("x={}", x)
        return x + 1

    jx = jax.make_jaxpr(f)(jnp.float32(1.0))
    assert audit.find_callbacks(jx), "debug callback not detected"


def test_callbacks_seen_inside_while_loop():
    def f(x):
        def body(c):
            jax.debug.print("c={}", c)
            return c - 1

        return jax.lax.while_loop(lambda c: c > 0, body, x)

    jx = jax.make_jaxpr(f)(jnp.int32(3))
    assert audit.find_callbacks(jx), "callback inside sub-jaxpr missed"


# ---------------------------------------------------------------------------
# golden jaxpr invariants over the real entry points
# ---------------------------------------------------------------------------


def _assert_clean(name, jx):
    f64 = audit.find_f64(jx)
    assert not f64, f"{name}: f64 leaked into the jaxpr: {f64[:5]}"
    cbs = audit.find_callbacks(jx)
    assert not cbs, f"{name}: callback primitives in the wave: {cbs}"


def test_path_wave_jaxpr_invariants():
    """ISSUE 2 acceptance: no f64 anywhere in the path-integrator wave."""
    _assert_clean("path.li", audit.integrator_li_jaxpr("path"))


def test_pool_drain_jaxpr_invariants():
    _assert_clean("pool_chunk", audit.pool_chunk_jaxpr())


def test_stream_traversal_jaxpr_invariants():
    _assert_clean("stream_intersect", audit.stream_traversal_jaxpr())


def test_film_deposit_jaxpr_invariants():
    _assert_clean("film.add_samples", audit.film_deposit_jaxpr())
    _assert_clean(
        "film.add_samples_pixel", audit.film_deposit_jaxpr(pixel_path=True)
    )


def test_mesh_step_jaxpr_invariants():
    _assert_clean("sharded_pool_renderer", audit.mesh_step_jaxpr())


def test_volpath_jaxpr_invariants():
    _assert_clean(
        "volpath.li", audit.integrator_li_jaxpr("volpath", "media")
    )


def test_bdpt_jaxpr_invariants():
    _assert_clean("bdpt.li", audit.integrator_li_jaxpr("bdpt", "cornell"))


def test_sppm_pass_jaxpr_invariants():
    cam, photon = audit.sppm_pass_jaxprs()
    _assert_clean("sppm camera pass", cam)
    _assert_clean("sppm photon pass", photon)


# ---------------------------------------------------------------------------
# compile-time invariants
# ---------------------------------------------------------------------------


def test_film_donation_materialized():
    """donate_argnums REQUESTS donation; the invariant is that the
    compiled executable actually aliases every film buffer input to an
    output (PR 1's donated-alias incident is the motivating example)."""
    assert audit.check_film_donation() == []


def test_zero_retraces_across_same_shape_waves():
    assert audit.check_recompile_guard() == []


def test_smoke_render_under_transfer_guard():
    assert audit.check_transfer_guard() == []


def test_donation_alias_counter_reads_hlo():
    txt = (
        "HloModule jit_f, is_scheduled=true, "
        "input_output_alias={ {0}: (0, {}, may-alias), "
        "{1}: (1, {}, may-alias) }, entry_computation_layout=..."
    )
    assert audit.donation_aliases(txt) == 2
    assert audit.donation_aliases("HloModule jit_f") == 0


def test_run_audit_aggregates_clean():
    """The CLI path: every audit passes on the shipped tree. Compile
    checks are exercised individually above; keep this to the pure-trace
    set so the aggregate stays cheap under pytest."""
    fails = audit.run_audit(include_compile=False)
    assert fails == [], "\n".join(fails)
