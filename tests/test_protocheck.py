"""protocheck (ISSUE 17): exhaustive interleaving & fault-schedule
verification of the serve/dispatch protocol (analysis layer 6).

Four pieces under test: the VirtualClock seam (utils/clock.py) that
makes a service run a pure function of a decision sequence, the SV-*
static rules over the protocol modules, the seeded mutation-regression
corpus (each historical bug re-introduced must be flagged BY NAME
through the real `tools/explore.py --mutate` entry point, and the
clean tree must pass the exact same decision sequences), and the
bounded explorer itself — clean-grid search, byte-identical replay
(PROTO-DET), and a virtual-time trace export that `tools/scope.py
--check` accepts.
"""

import importlib.util
import json
import os

import pytest

from tpu_pbrt.analysis import protocheck as pc
from tpu_pbrt.utils.clock import WALL, Clock, VirtualClock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    """Import a tools/ script (not a package) as a throwaway module."""
    spec = importlib.util.spec_from_file_location(
        f"_protocheck_test_{name}", os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def explore():
    return _load_tool("explore")


# ---------------------------------------------------------------------------
# the clock seam
# ---------------------------------------------------------------------------


class TestVirtualClock:
    def test_decision_sample_ticks_peek_does_not(self):
        vc = VirtualClock(start=10.0, tick=0.5)
        assert vc.peek() == 10.0
        assert vc.now() == 10.0  # returns current time, THEN ticks
        assert vc.peek() == 10.5  # the hidden-double-sample detector
        assert vc.now() == 10.5
        assert vc.samples == 2
        assert vc.monotonic() == vc.peek()  # one timeline, no epoch split

    def test_sleep_advances_instead_of_blocking(self):
        vc = VirtualClock()
        vc.sleep(2.0)
        assert vc.peek() == 2.0 and vc.sleeps == 1
        vc.sleep(-5.0)  # negative sleeps clamp like time.sleep rejects
        assert vc.peek() == 2.0

    def test_advance_to_never_goes_backward(self):
        vc = VirtualClock(start=3.0)
        vc.advance_to(1.0)
        assert vc.peek() == 3.0
        vc.advance_to(5.0)
        assert vc.peek() == 5.0
        vc.advance(0.25)
        assert vc.peek() == 5.25

    def test_wall_clock_is_the_default_interface(self):
        assert isinstance(WALL, Clock)
        a = WALL.now()
        assert WALL.peek() >= a  # real time, still ordered


class TestVirtualTimeTelemetry:
    """Satellite: the obs recorders under an injected VirtualClock must
    emit monotone nonnegative stamps and must not perturb the timeline
    (arming telemetry cannot change a virtual run's schedule)."""

    def test_trace_rebases_and_stays_monotone(self, tmp_path):
        from tpu_pbrt.obs.trace import TraceRecorder, validate_trace

        rec = TraceRecorder()
        rec.configure(str(tmp_path / "t.json"))
        vc = VirtualClock(start=100.0)
        rec.set_clock(vc)
        assert rec.clock_kind == "virtual"
        with rec.span("alpha"):
            vc.advance(0.25)
        vc.advance(1.0)
        rec.instant("mark")
        out = rec.export()
        doc = json.loads(open(out).read())
        assert doc["otherData"]["clock"] == "virtual"
        ts = [e["ts"] for e in doc["traceEvents"]]
        # rebase: starts at 0 despite the clock starting at 100 s; a
        # wall _t0 here would produce the negative stamps validate_trace
        # rejects
        assert ts[0] == 0.0 and ts == sorted(ts)
        assert validate_trace(doc) == []
        assert vc.samples == 0  # recording used monotonic(), not now()
        rec.set_clock(None)
        assert rec.clock_kind == "wall"

    def test_flight_heartbeats_monotone_under_virtual_time(self, tmp_path):
        from tpu_pbrt.obs.flight import FlightRecorder

        fr = FlightRecorder()
        fr.configure(str(tmp_path / "f.jsonl"))
        vc = VirtualClock(start=50.0)
        fr.set_clock(vc)
        fr.heartbeat("boot")
        vc.advance(0.5)
        fr.heartbeat("render", chunk=1)
        vc.advance(0.5)
        fr.heartbeat("render", chunk=2)
        lines = [json.loads(x) for x in open(tmp_path / "f.jsonl")]
        assert [x["t"] for x in lines] == sorted(x["t"] for x in lines)
        assert lines[0]["elapsed_s"] == 0.0  # rebased onto the clock
        assert lines[-1]["elapsed_s"] == 1.0
        assert vc.samples == 0  # peek() only: heartbeats never tick
        fr.set_clock(None)


# ---------------------------------------------------------------------------
# SV-* static rules
# ---------------------------------------------------------------------------


def _rules(src, rel):
    return [v.rule for v in pc.sv_lint_source(src, rel)]


class TestSvLint:
    def test_raw_wall_clock_in_scoped_module(self):
        src = "import time\n\ndef f(self):\n    return time.monotonic()\n"
        assert _rules(src, "tpu_pbrt/serve/service.py") == ["SV-CLOCK"]
        # the same call outside the protocol modules is fine
        assert _rules(src, "tpu_pbrt/film/image.py") == []

    def test_double_decision_sample_in_deadline_scope(self):
        src = (
            "def step(self):\n"
            "    now = self._now()\n"
            "    job = self._runnable(now)\n"
            "    later = self._now()\n"
            "    return job, later\n"
        )
        vs = pc.sv_lint_source(src, "tpu_pbrt/serve/service.py")
        assert [v.rule for v in vs] == ["SV-CLOCK"]
        assert "samples the decision clock 2 times" in vs[0].message

    def test_double_sample_outside_deadline_scope_allowed(self):
        # two samples bracketing a span is the TIMING idiom, legal when
        # the function never reasons about deadlines/runnability
        src = "def t(self):\n    a = self._now()\n    b = self._now()\n    return b - a\n"
        assert _rules(src, "tpu_pbrt/serve/service.py") == []

    def test_defer_requires_cursor_binding(self):
        bad = "def q(self, w, fn):\n    w.defer(fn)\n"
        good = "def q(self, w, fn):\n    w.defer(3, fn)\n"
        assert _rules(bad, "tpu_pbrt/serve/service.py") == ["SV-DEFER"]
        assert _rules(good, "tpu_pbrt/serve/service.py") == []

    def test_checkpoint_then_flush_must_discard(self):
        bad = (
            "def park(self, job):\n"
            "    save_checkpoint(job)\n"
            "    job.window.flush()\n"
        )
        good = bad.replace("flush()", "flush(discard=True)")
        vs = pc.sv_lint_source(bad, "tpu_pbrt/serve/service.py")
        assert [v.rule for v in vs] == ["SV-DEFER"]
        assert "superseded cursor" in vs[0].message
        assert _rules(good, "tpu_pbrt/serve/service.py") == []

    def test_vtime_written_outside_policy_api(self):
        assert _rules(
            "def cheat(ts):\n    ts.vtime = 0.0\n", "tpu_pbrt/serve/queue.py"
        ) == ["SV-VTIME"]
        assert _rules(
            "def cheat(ts):\n    ts.vtime += 1.0\n",
            "tpu_pbrt/serve/service.py",
        ) == ["SV-VTIME"]

    def test_pragma_suppression(self):
        src = (
            "import time\n\ndef f(self):\n"
            "    return time.monotonic()  # jaxlint: disable=SV-CLOCK\n"
        )
        assert _rules(src, "tpu_pbrt/serve/service.py") == []

    def test_syntax_error_is_a_finding_not_a_crash(self):
        assert _rules("def broken(:\n", "tpu_pbrt/serve/service.py") == [
            "SV-PARSE"
        ]

    def test_repo_tree_is_clean(self):
        assert pc.sv_lint_tree() == []


# ---------------------------------------------------------------------------
# mutation-regression corpus
# ---------------------------------------------------------------------------


class TestMutationCorpus:
    @pytest.mark.parametrize(
        "case", pc.MUTATION_CASES, ids=lambda c: c.name
    )
    def test_mutant_detected_by_name_via_cli(self, case, explore, capsys):
        """The REAL entry point: `tools/explore.py --mutate NAME` must
        exit non-zero and print the expected invariant."""
        rc = explore.main(["--mutate", case.name])
        out = capsys.readouterr().out
        assert rc == 1, out
        assert f"PROTOCHECK VIOLATION {case.expect}" in out
        assert case.historical in out

    @pytest.mark.parametrize(
        "case", pc.MUTATION_CASES, ids=lambda c: c.name
    )
    def test_clean_tree_passes_the_same_decisions(self, case):
        viol, log = pc.run_mutation_case(case.name, mutate=False)
        assert viol == []
        # and byte-identically so: the determinism contract
        viol2, log2 = pc.run_mutation_case(case.name, mutate=False)
        assert viol2 == [] and log2 == log

    def test_unknown_mutation_name_rejected(self):
        with pytest.raises(KeyError):
            pc.mutation_case("not-a-mutation")

    def test_corpus_covers_the_seeded_bugs(self):
        assert {c.expect for c in pc.MUTATION_CASES} == {
            "PROTO-WEDGE", "PROTO-VTIME", "PROTO-DEFER", "PROTO-HBM",
            "PROTO-ROUTE-DUP",
        }


# ---------------------------------------------------------------------------
# bounded explorer
# ---------------------------------------------------------------------------


class TestExplorer:
    def test_clean_grid_smoke(self, explore):
        # small budget: the full CI budget runs in tools/ci.sh; here we
        # only need every scenario to boot, explore, and stay clean
        assert explore.run_ci(seed=0, max_nodes=10, max_depth=4) == []

    def test_pruning_happens(self, explore):
        duo = next(s for s in pc.smoke_scenarios() if s.name == "duo-d2")
        ex = explore.Explorer(duo, seed=0, max_nodes=40, max_depth=7).run()
        assert ex.violations == []
        assert ex.pruned > 0  # commuting interleavings collapse

    def test_canonical_drain_replays_byte_identically(self, explore):
        duo = next(s for s in pc.smoke_scenarios() if s.name == "duo-d1")
        decisions, log1, viol = explore.canonical_drain(duo, seed=0)
        assert viol == []
        assert explore.replay_log(duo, decisions, seed=0) == log1

    def test_fault_scenario_drains_clean(self, explore):
        # a dispatch:fail placement must recover through the real
        # backoff ladder and still reconcile counters + film bits
        sc = next(
            s for s in pc.smoke_scenarios() if "dispatch:fail" in s.fault
        )
        _, _, viol = explore.canonical_drain(sc, seed=0)
        assert viol == []

    def test_trace_export_accepted_by_scope(self, explore, tmp_path):
        duo = next(s for s in pc.smoke_scenarios() if s.name == "duo-d2")
        out = explore.export_trace(duo, str(tmp_path / "trace.json"), seed=0)
        doc = json.loads(open(out).read())
        assert doc["otherData"]["clock"] == "virtual"
        scope = _load_tool("scope")
        assert scope.main([out, "--check"]) == 0
