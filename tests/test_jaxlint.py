"""jaxlint layer 1 (AST lint): rule firing, pragma scoping, traced-code
discovery, and the repo-wide gate (ISSUE 2 acceptance: zero errors with
<= 5 pragma suppressions across tpu_pbrt/)."""

import textwrap
from pathlib import Path

from tpu_pbrt.analysis.lint import PRAGMA_BUDGET, RULES, lint_file, lint_tree


def _lint_src(tmp_path: Path, src: str):
    root = tmp_path
    pkg = root / "tpu_pbrt"
    pkg.mkdir(exist_ok=True)
    f = pkg / "mod.py"
    f.write_text(textwrap.dedent(src))
    vs, pragmas = lint_file(f, root)
    return vs, pragmas


def _rules(vs):
    return sorted({v.rule for v in vs})


class TestRules:
    def test_host_sync_in_jitted_fn(self, tmp_path):
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax, numpy as np

            @jax.jit
            def f(x):
                v = x.item()
                w = np.asarray(x)
                return float(x) + v + w
            """,
        )
        assert _rules(vs) == ["JL-SYNC"]
        assert len(vs) == 3

    def test_float_on_tracer_attribute_flagged(self, tmp_path):
        """float()/bool() on a NamedTuple tracer field (hit.t, s.alive)
        is a host sync; on known-static bases (self.spp, cfg.slab,
        x.shape[0]) it is configuration and passes."""
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(self, hit, cfg):
                a = float(hit.t)
                b = bool(hit.valid)
                ok1 = float(self.rr_threshold)
                ok2 = float(cfg.headroom)
                ok3 = float(hit.t.shape[0])
                return a, b, ok1, ok2, ok3
            """,
        )
        assert [v.rule for v in vs] == ["JL-SYNC", "JL-SYNC"]
        assert {v.line for v in vs} == {6, 7}

    def test_callback_in_while_loop_body(self, tmp_path):
        """Traced-ness propagates into functions passed to lax HOFs."""
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax

            def run(x):
                def body(c):
                    jax.debug.print("c={}", c)
                    return c - 1
                return jax.lax.while_loop(lambda c: c > 0, body, x)
            """,
        )
        assert _rules(vs) == ["JL-CALLBACK"]

    def test_traced_propagates_through_helper_calls(self, tmp_path):
        """A helper only reachable FROM traced code is traced too."""
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax

            def helper(x):
                return x.item()

            @jax.jit
            def f(x):
                return helper(x)
            """,
        )
        assert _rules(vs) == ["JL-SYNC"]

    def test_host_code_not_flagged(self, tmp_path):
        """The same constructs OUTSIDE traced code are legitimate."""
        vs, _ = _lint_src(
            tmp_path,
            """
            import numpy as np

            def host_driver(result):
                a = np.asarray(result)
                a[0] = 1.0
                return float(a.sum())
            """,
        )
        assert vs == []

    def test_f64_and_dtypeless_ctor(self, tmp_path):
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax, jax.numpy as jnp, numpy as np

            @jax.jit
            def f(x):
                a = jnp.zeros((4,))
                b = x.astype(np.float64)
                return a + b
            """,
        )
        assert _rules(vs) == ["JL-DTYPE", "JL-F64"]

    def test_env_read_flagged_anywhere(self, tmp_path):
        vs, _ = _lint_src(
            tmp_path,
            """
            import os

            def knob():
                return os.environ.get("TPU_PBRT_X", "1")
            """,
        )
        assert _rules(vs) == ["JL-ENV"]

    def test_mutation_vs_local_container(self, tmp_path):
        """Captured-array stores are flagged; building a fresh local
        dict/list is not (textured_mat's kw pattern)."""
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x, buf):
                kw = {}
                kw["a"] = 1.0
                buf[0] = x
                return kw["a"]
            """,
        )
        assert [v.rule for v in vs] == ["JL-MUT"]
        assert "buf[0]" not in str(vs[0].message)

    def test_donate_rule_scoped_to_film_modules(self, tmp_path):
        root = tmp_path
        pkg = root / "tpu_pbrt" / "integrators"
        pkg.mkdir(parents=True)
        f = pkg / "common.py"
        f.write_text("import jax\njfn = jax.jit(lambda s: s)\n")
        vs, _ = lint_file(f, root)
        assert _rules(vs) == ["JL-DONATE"]
        # same code elsewhere is fine
        g = root / "tpu_pbrt" / "other.py"
        g.write_text("import jax\njfn = jax.jit(lambda s: s)\n")
        vs2, _ = lint_file(g, root)
        assert vs2 == []

    def test_donate_rule_sees_decorator_form(self, tmp_path):
        """@jax.jit (decorator syntax) must not bypass JL-DONATE; a
        zero-arg staging helper has nothing to donate and is exempt."""
        root = tmp_path
        pkg = root / "tpu_pbrt" / "integrators"
        pkg.mkdir(parents=True)
        f = pkg / "common.py"
        f.write_text(
            "import jax\n\n"
            "@jax.jit\n"
            "def chunk_fn(state):\n"
            "    return state\n\n"
            "@jax.jit\n"
            "def zero_arg_helper():\n"
            "    return 1\n"
        )
        vs, _ = lint_file(f, root)
        assert [v.rule for v in vs] == ["JL-DONATE"]
        assert vs[0].line == 4  # anchors at the def statement


class TestPragmas:
    def test_line_pragma_suppresses(self, tmp_path):
        vs, pragmas = _lint_src(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                return x.item()  # jaxlint: disable=JL-SYNC
            """,
        )
        assert vs == [] and pragmas == 1

    def test_def_line_pragma_covers_body(self, tmp_path):
        vs, pragmas = _lint_src(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):  # jaxlint: disable=JL-SYNC
                a = x.item()
                return float(x) + a
            """,
        )
        assert vs == [] and pragmas == 1

    def test_file_pragma(self, tmp_path):
        vs, pragmas = _lint_src(
            tmp_path,
            """
            # jaxlint: disable-file=JL-ENV
            import os
            A = os.environ.get("X")
            B = os.environ.get("Y")
            """,
        )
        assert vs == [] and pragmas == 1

    def test_pragma_in_docstring_is_not_a_pragma(self, tmp_path):
        vs, pragmas = _lint_src(
            tmp_path,
            '''
            """Docs: use `# jaxlint: disable=JL-SYNC` to suppress."""
            import os
            A = os.environ.get("X")
            ''',
        )
        assert _rules(vs) == ["JL-ENV"] and pragmas == 0

    def test_pragma_does_not_mute_other_rules(self, tmp_path):
        vs, _ = _lint_src(
            tmp_path,
            """
            import jax

            @jax.jit
            def f(x):
                return x.item()  # jaxlint: disable=JL-F64
            """,
        )
        assert _rules(vs) == ["JL-SYNC"]


class TestRepoGate:
    """The judged acceptance bar: the shipped tree lints clean."""

    def test_repo_lints_clean_with_pragma_budget(self):
        violations, pragmas = lint_tree()
        errors = [v for v in violations if v.severity == "error"]
        assert errors == [], "\n".join(str(v) for v in errors)
        assert pragmas <= PRAGMA_BUDGET, (
            f"{pragmas} pragma suppressions — the budget is "
            f"{PRAGMA_BUDGET}; fix the code instead of suppressing"
        )

    def test_parse_error_uses_dedicated_rule(self, tmp_path):
        pkg = tmp_path / "tpu_pbrt"
        pkg.mkdir()
        f = pkg / "broken.py"
        f.write_text("def f(:\n")
        vs, _ = lint_file(f, tmp_path)
        assert [v.rule for v in vs] == ["JL-PARSE"]

    def test_path_outside_repo_does_not_crash(self, tmp_path):
        f = tmp_path / "loose.py"
        f.write_text("import os\nA = os.environ.get('X')\n")
        vs, _ = lint_file(f, tmp_path / "elsewhere")
        assert [v.rule for v in vs] == ["JL-ENV"]

    def test_rule_registry_documented(self):
        # every rule id referenced by the README table exists
        readme = (
            Path(__file__).resolve().parents[1] / "README.md"
        ).read_text()
        for rule in RULES:
            assert rule in readme, f"{rule} missing from README"
