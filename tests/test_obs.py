"""tpu-trace telemetry subsystem (ISSUE 4): bit-identity of the render
under the telemetry kill switch, counter-block correctness, zero added
retraces/host-transfers (reusing the jaxpr-audit harness), trace-export
schema validation, flight-recorder format, and the live-vs-static
roofline cross-check."""

import json
import os

import numpy as np
import pytest

from tpu_pbrt import config
from tpu_pbrt.obs import counters as obs_counters
from tpu_pbrt.obs.flight import FlightRecorder, validate_flight
from tpu_pbrt.obs.rooflive import live_vs_static, load_static_budget
from tpu_pbrt.obs.trace import TraceRecorder, validate_trace


def _render_cornell(**kw):
    from tpu_pbrt.scenes import compile_api, make_cornell

    api = make_cornell(res=16, spp=4, integrator="path", maxdepth=3, **kw)
    scene, integ = compile_api(api)
    return integ.render(scene)


# ---------------------------------------------------------------------------
# config seam (ISSUE 4 satellite: knobs through the central config)
# ---------------------------------------------------------------------------


class TestConfigSeam:
    def test_telemetry_default_on_and_kill_switch(self, monkeypatch):
        monkeypatch.delenv("TPU_PBRT_TELEMETRY", raising=False)
        config.reload()
        assert config.cfg.telemetry is True
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        config.reload()
        assert config.cfg.telemetry is False
        assert obs_counters.enabled() is False
        assert obs_counters.maybe_zeros() is None

    def test_trace_and_flight_paths_reload(self, monkeypatch):
        monkeypatch.setenv("TPU_PBRT_TRACE_PATH", "/tmp/t.json")
        monkeypatch.setenv("TPU_PBRT_FLIGHT_PATH", "/tmp/f.jsonl")
        config.reload()
        assert config.cfg.trace_path == "/tmp/t.json"
        assert config.cfg.flight_path == "/tmp/f.jsonl"
        monkeypatch.delenv("TPU_PBRT_TRACE_PATH")
        monkeypatch.delenv("TPU_PBRT_FLIGHT_PATH")
        config.reload()
        assert config.cfg.trace_path is None
        assert config.cfg.flight_path is None


# ---------------------------------------------------------------------------
# bit-identity + counter correctness (the tentpole acceptance)
# ---------------------------------------------------------------------------


class TestBitIdentity:
    def test_film_identical_and_counters_consistent(self, monkeypatch):
        """Telemetry ON == telemetry OFF, bit for bit; the counter block
        reconciles exactly with the independent ray/wave accounting."""
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "1")
        config.reload()
        r_on = _render_cornell()
        tel = r_on.stats["telemetry"]
        ctr = tel["counters"]
        # rays counted by the telemetry block == the judged ray counter
        assert ctr["rays_traced"] == r_on.rays_traced > 0
        # every wave histogrammed exactly once
        assert sum(ctr["occupancy_histogram"]) == r_on.stats["n_waves"]
        # every work item (16*16 px * 4 spp) regenerated, terminated and
        # deposited exactly once on an un-truncated drain
        n_work = 16 * 16 * 4
        assert ctr["lanes_regenerated"] == n_work
        assert ctr["lanes_terminated"] == n_work
        assert ctr["film_deposits"] == n_work
        # single-device spread is degenerate but well-formed
        assert tel["wave_spread"]["per_device_waves"] == [
            r_on.stats["n_waves"]
        ]
        assert tel["wave_spread"]["rel_spread"] == 0.0

        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        config.reload()
        r_off = _render_cornell()
        assert "telemetry" not in r_off.stats
        assert np.array_equal(
            np.asarray(r_on.image), np.asarray(r_off.image)
        ), "telemetry changed the rendered image"

    def test_kill_switch_compiles_pre_telemetry_program(self, monkeypatch):
        """TPU_PBRT_TELEMETRY=0 is not a masked variant: the traced pool
        drain has the pre-telemetry output arity (film 3 + nrays + live +
        waves + truncated = 7 avals) and strictly fewer equations."""
        from tpu_pbrt.analysis import audit

        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "1")
        config.reload()
        jx_on = audit.pool_chunk_jaxpr()
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        config.reload()
        jx_off = audit.pool_chunk_jaxpr()
        assert len(jx_off.jaxpr.outvars) == 7
        # 7 counter leaves (6 scalars incl. nonfinite_deposits +
        # occupancy histogram)
        assert len(jx_on.jaxpr.outvars) == 14
        n_on = sum(len(j.eqns) for j in audit.iter_jaxprs(jx_on.jaxpr))
        n_off = sum(len(j.eqns) for j in audit.iter_jaxprs(jx_off.jaxpr))
        assert n_off < n_on


class TestNoAddedOverhead:
    """Acceptance: zero extra retraces and zero extra host transfers with
    telemetry on (default) — the jaxpr-audit harness re-run as the gate."""

    def test_zero_retraces_with_telemetry_on(self):
        from tpu_pbrt.analysis import audit

        assert config.cfg.telemetry is True
        assert audit.check_recompile_guard() == []

    def test_transfer_guard_clean_with_telemetry_on(self):
        from tpu_pbrt.analysis import audit

        assert config.cfg.telemetry is True
        assert audit.check_transfer_guard() == []


# ---------------------------------------------------------------------------
# counter host-side algebra
# ---------------------------------------------------------------------------


class TestCounterAlgebra:
    def test_merge_host_sums_and_pads(self):
        a = {"rays_traced": 10, "occupancy_histogram": [1, 2]}
        b = {"rays_traced": 5, "occupancy_histogram": [3, 4, 5],
             "film_deposits": 7}
        m = obs_counters.merge_host(a, b)
        assert m["rays_traced"] == 15
        assert m["occupancy_histogram"] == [4, 6, 5]
        assert m["film_deposits"] == 7
        assert obs_counters.merge_host({}, b) == b
        assert obs_counters.merge_host(a, {}) == a

    def test_spread_stats(self):
        s = obs_counters.spread_stats([10, 20, 10, 40])
        assert s["min"] == 10 and s["max"] == 40 and s["mean"] == 20.0
        assert s["rel_spread"] == pytest.approx(1.5)
        assert obs_counters.spread_stats([]) == {}


# ---------------------------------------------------------------------------
# trace recorder: schema validation of the export
# ---------------------------------------------------------------------------


class TestTraceExport:
    def _recorder(self, tmp_path):
        rec = TraceRecorder()
        rec.configure(str(tmp_path / "trace.json"))
        return rec

    def test_export_schema_valid(self, tmp_path):
        rec = self._recorder(tmp_path)
        with rec.span("bench/measure", chunk=3):
            with rec.span("render/chunk_dispatch"):
                pass
        rec.instant("checkpoint")
        rec.counter("occupancy", live=123)
        path = rec.export()
        assert validate_trace(path) == []
        doc = json.loads(open(path).read())
        names = [e["name"] for e in doc["traceEvents"]]
        assert "bench/measure" in names and "occupancy" in names
        # nested span closed after its parent opened: ts ordering holds
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert all(e["dur"] >= 0 for e in spans)

    def test_validator_rejects_malformed(self):
        assert validate_trace({"nope": []})
        assert validate_trace(
            {"traceEvents": [{"name": "x", "ph": "X", "ts": -1}]}
        )
        assert validate_trace(
            {"traceEvents": [{"name": "", "ph": "i", "ts": 0,
                              "pid": 0, "tid": 0}]}
        )
        # a complete span without dur is malformed
        assert validate_trace(
            {"traceEvents": [{"name": "x", "ph": "X", "ts": 0,
                              "pid": 0, "tid": 0}]}
        )

    def test_disabled_recorder_is_noop(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        config.reload()
        rec = self._recorder(tmp_path)
        with rec.span("x"):
            pass
        assert rec.maybe_export() is None
        assert not os.path.exists(str(tmp_path / "trace.json"))


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_heartbeats_and_validation(self, tmp_path):
        p = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(p)
        fr.heartbeat("probe", attempt=1, ok=False)
        fr.heartbeat("probe", attempt=2, ok=True)
        fr.heartbeat("measure", chunk=1)
        fr.counters({"rays_traced": 99}, phase="render_done")
        assert fr.last_phase == "render_done"
        assert fr.last_counters == {"rays_traced": 99}
        assert validate_flight(p, require_phases=["probe", "measure",
                                                  "render_done"]) == []
        errs = validate_flight(p, require_phases=["develop"])
        assert errs and "develop" in errs[0]
        lines = [json.loads(x) for x in open(p).read().splitlines()]
        assert lines[0]["phase"] == "probe"
        assert lines[-1]["counters"] == {"rays_traced": 99}

    def test_reserved_keys_win_over_caller_kwargs(self, tmp_path):
        """A phase kwarg named elapsed_s must not clobber the recorder's
        own monotonic baseline field."""
        p = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(p)
        fr.heartbeat("render", elapsed_s=9999.0, chunk=3)
        rec = json.loads(open(p).read().splitlines()[0])
        assert rec["elapsed_s"] < 9999.0
        assert rec["chunk"] == 3

    def test_configure_t0_rebases_elapsed(self, tmp_path):
        """bench hands its probe-phase start time over at the import
        handoff so one JSONL keeps a single monotonic elapsed_s
        baseline (the probe's import-free writer measured from the
        same epoch)."""
        import time

        p = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(p, t0=time.time() - 100.0)
        fr.heartbeat("measure")
        rec = json.loads(open(p).read().splitlines()[0])
        assert rec["elapsed_s"] >= 100.0

    def test_disabled_recorder_tracks_phase_without_writing(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        config.reload()
        p = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(p)
        fr.heartbeat("measure")
        # the outage JSON still reports last_phase; nothing hits disk
        assert fr.last_phase == "measure"
        assert not os.path.exists(p)

    def test_render_writes_phase_heartbeats(self, tmp_path, monkeypatch):
        """The render loop heartbeats its phases (the CI smoke asserts
        the same through main.py)."""
        from tpu_pbrt.obs.flight import FLIGHT

        p = str(tmp_path / "render_flight.jsonl")
        monkeypatch.setenv("TPU_PBRT_FLIGHT_PATH", p)
        config.reload()
        FLIGHT.configure(None)  # fall through to cfg.flight_path
        try:
            _render_cornell()
        finally:
            FLIGHT.configure(None)
        assert validate_flight(
            p, require_phases=["render", "render_done", "develop"]
        ) == []
        done = [
            json.loads(x) for x in open(p).read().splitlines()
            if json.loads(x)["phase"] == "render_done"
        ]
        assert done and done[-1]["counters"]["rays_traced"] > 0


# ---------------------------------------------------------------------------
# live-vs-static roofline cross-check
# ---------------------------------------------------------------------------


class TestRooflive:
    def test_ratio_null_on_unknown_platform(self):
        out = live_vs_static(
            waves=100, seconds=2.0, static_bytes_per_wave=1_000_000,
            device_kind="cpu",
        )
        assert out["live_bytes_per_sec"] == pytest.approx(5e7)
        assert out["live_vs_static_ratio"] is None

    def test_ratio_on_known_tpu(self):
        out = live_vs_static(
            waves=1000, seconds=1.0,
            static_bytes_per_wave=6_446_032_534,
            static_flops_per_wave=3_834_297_836,
            device_kind="TPU v5e", n_devices=8,
        )
        assert out["hbm_peak_bytes_per_sec"] == pytest.approx(8 * 819e9)
        assert out["live_vs_static_ratio"] == pytest.approx(
            6_446_032_534 * 1000 / (8 * 819e9), rel=1e-6
        )
        assert out["live_flops_per_sec"] == pytest.approx(3.834297836e12)

    def test_missing_inputs_degrade_to_nulls(self):
        out = live_vs_static(waves=None, seconds=None)
        assert out == {
            "live_bytes_per_sec": None, "live_flops_per_sec": None,
            "hbm_peak_bytes_per_sec": None, "live_vs_static_ratio": None,
        }

    def test_static_budget_fallback_reads_committed_file(self):
        entry = load_static_budget("pool_chunk")
        assert entry.get("hbm_bytes", 0) > 0
        assert load_static_budget("no_such_entry") == {}
