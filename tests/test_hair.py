"""Hair BSDF tests (hair.cpp capability) — the same oracles pbrt's own
src/tests/hair.cpp uses: white furnace (sigma_a = 0 conserves energy),
pdf normalization over the sphere, and sampling consistency."""

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core import bxdf
from tpu_pbrt.scene.compiler import MAT_HAIR


def _hair_mp(n, *, sigma_a=(0.0, 0.0, 0.0), beta_m=0.3, beta_n=0.3,
             alpha=0.0, eta=1.55, h=0.0):
    one = jnp.ones((n,), jnp.float32)
    one3 = jnp.ones((n, 3), jnp.float32)
    hz = bxdf.HairParams(
        sigma_a=one3 * jnp.asarray(sigma_a, jnp.float32),
        beta_m=one * beta_m,
        beta_n=one * beta_n,
        alpha=one * alpha,
        h=one * h,
    )
    return bxdf.MatParams(
        mtype=jnp.full((n,), MAT_HAIR, jnp.int32),
        kd=one3 * 0.5,
        ks=one3 * 0,
        kr=one3 * 0,
        kt=one3 * 0,
        eta=one3 * eta,
        k=one3 * 0,
        ax=one * 0.1,
        ay=one * 0.1,
        sigma=one * 0,
        opacity=one3,
        rough_raw=one * 0.3,
        hz=hz,
    )


def _sphere_dirs(n, seed):
    rng = np.random.default_rng(seed)
    d = rng.normal(size=(n, 3))
    d /= np.linalg.norm(d, axis=-1, keepdims=True)
    return jnp.asarray(d, jnp.float32)


def _wo(n, v=(0.3, 0.4, 0.87)):
    v = np.asarray(v, np.float64)
    v /= np.linalg.norm(v)
    return jnp.broadcast_to(jnp.asarray(v, jnp.float32), (n, 3))


def test_white_furnace():
    """sigma_a = 0: int f |cos| dwi = 1 for any roughness (pbrt
    src/tests/hair.cpp WhiteFurnace)."""
    n = 500_000
    wi = _sphere_dirs(n, 1)
    for bm, bn in ((0.2, 0.4), (0.4, 0.2), (0.6, 0.6), (0.9, 0.9)):
        for h in (-0.6, 0.0, 0.7):
            mp = _hair_mp(n, beta_m=bm, beta_n=bn, h=h)
            f, _ = bxdf._hair_f_pdf(mp, _wo(n), wi)
            est = float(
                jnp.mean(f[:, 0] * jnp.abs(wi[:, 2]))
            ) * 4.0 * np.pi
            assert abs(est - 1.0) < 0.05, f"bm={bm} bn={bn} h={h}: {est}"


def test_pdf_normalizes():
    n = 500_000
    wi = _sphere_dirs(n, 2)
    for bm, bn in ((0.3, 0.3), (0.8, 0.4)):
        mp = _hair_mp(n, sigma_a=(0.5, 1.0, 2.0), beta_m=bm, beta_n=bn,
                      h=0.3, alpha=2.0)
        _, pdf = bxdf._hair_f_pdf(mp, _wo(n), wi)
        est = float(jnp.mean(pdf)) * 4.0 * np.pi
        assert abs(est - 1.0) < 0.05, f"bm={bm} bn={bn}: int pdf = {est}"


def test_sample_eval_consistency():
    """E[f |cos| / pdf] over hair-sampled wi matches the uniform-sphere
    estimate of the same integral."""
    n = 500_000
    rng = np.random.default_rng(3)
    wo = _wo(n)
    mp = _hair_mp(n, sigma_a=(0.3, 0.6, 1.2), beta_m=0.4, beta_n=0.35,
                  h=0.25, alpha=2.0)
    u_l = jnp.asarray(rng.uniform(size=n), jnp.float32)
    u1 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    u2 = jnp.asarray(rng.uniform(size=n), jnp.float32)
    wi_s = bxdf._hair_sample_wi(mp, wo, u_l, u1, u2)
    f_s, pdf_s = bxdf._hair_f_pdf(mp, wo, wi_s)
    w = np.asarray(
        jnp.where(
            (pdf_s > 1e-8)[..., None],
            f_s * jnp.abs(wi_s[..., 2:3]) / jnp.maximum(pdf_s, 1e-8)[..., None],
            0.0,
        )
    )
    est_s = w.mean(axis=0)
    wi_u = _sphere_dirs(n, 5)
    f_u, _ = bxdf._hair_f_pdf(mp, wo, wi_u)
    est_u = np.asarray(f_u * jnp.abs(wi_u[..., 2:3])).mean(axis=0) * 4.0 * np.pi
    assert np.all(np.abs(est_s - est_u) < 0.05 + 0.12 * est_u), (
        f"sampled {est_s} vs uniform {est_u}"
    )


def test_absorption_darkens():
    n = 200_000
    wi = _sphere_dirs(n, 7)
    f_w, _ = bxdf._hair_f_pdf(_hair_mp(n), _wo(n), wi)
    f_d, _ = bxdf._hair_f_pdf(
        _hair_mp(n, sigma_a=(2.0, 2.0, 2.0)), _wo(n), wi
    )
    a_w = float(jnp.mean(f_w[:, 0] * jnp.abs(wi[:, 2]))) * 4 * np.pi
    a_d = float(jnp.mean(f_d[:, 0] * jnp.abs(wi[:, 2]))) * 4 * np.pi
    assert a_d < 0.6 * a_w


def test_hair_scene_end_to_end():
    """Curve geometry + hair material through the full pipeline."""
    import os
    import tempfile

    import tpu_pbrt

    scene = """
Integrator "path" "integer maxdepth" [3]
Sampler "random" "integer pixelsamples" [4]
Film "image" "integer xresolution" [32] "integer yresolution" [32]
LookAt 0 0.5 3  0 0.5 0  0 1 0
Camera "perspective" "float fov" [35]
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [15 15 15]
  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
    "point P" [-1 2.5 -1  1 2.5 -1  1 2.5 1  -1 2.5 1]
AttributeEnd
Material "hair" "float eumelanin" [1.3]
Shape "curve" "point P" [-0.5 0 0  -0.2 1.2 0  0.2 -0.2 0  0.5 1 0]
  "float width0" [0.2] "float width1" [0.1]
Material "matte" "rgb Kd" [0.6 0.6 0.6]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3]
  "point P" [-3 -0.5 -3  3 -0.5 -3  3 -0.5 3  -3 -0.5 3]
WorldEnd
"""
    with tempfile.NamedTemporaryFile("w", suffix=".pbrt", delete=False) as f:
        f.write(scene)
        path = f.name
    try:
        res = tpu_pbrt.render_file(path)
        img = np.asarray(res.image)
        assert np.isfinite(img).all()
        assert img.max() > 0.0
    finally:
        os.unlink(path)
