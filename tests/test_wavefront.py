"""Persistent wavefront: active-lane compaction + path regeneration
(ISSUE 1 tentpole). Oracles:

- ESTIMATOR EQUIVALENCE: every sampler dimension is a pure function of
  (px, py, s, dimension salt), so a regenerated lane draws exactly the
  streams the fixed-batch loop would have — the two render paths must
  produce the same image on a real multi-bounce scene (bit-identical at
  spp=1 where each pixel sums a single sample; within float-accumulation
  order at higher spp).
- OCCUPANCY: on a depth-5 diffuse scene the pool's mean wave occupancy
  (live lanes / pool slots, averaged over trace waves) must be near 1,
  versus the ~0.3-0.4 a fixed batch decays to — the tentpole's whole
  point. The fixed-batch wave count per finished path must also shrink.
"""

import os

import numpy as np

from tpu_pbrt.scenes import compile_api, make_killeroo_like


def _render(spp, env, maxdepth=5):
    from tpu_pbrt import config

    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    config.reload()
    try:
        api = make_killeroo_like(
            res=32, spp=spp, maxdepth=maxdepth, n_theta=24, n_phi=48
        )
        scene, integ = compile_api(api)
        return integ.render(scene)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()


def test_regen_image_bit_identical_at_spp1():
    """spp=1: each pixel holds exactly one sample, so there is no
    accumulation-order freedom — the pool render must reproduce the
    fixed-batch image to float precision."""
    r_fix = _render(1, {"TPU_PBRT_REGEN": "0"})
    r_reg = _render(1, {"TPU_PBRT_REGEN": "1", "TPU_PBRT_POOL": "256"})
    assert r_reg.stats.get("regen"), r_reg.stats
    assert r_reg.rays_traced == r_fix.rays_traced
    a = np.asarray(r_fix.image, np.float32)
    b = np.asarray(r_reg.image, np.float32)
    assert np.max(np.abs(a - b)) <= 1e-6, np.max(np.abs(a - b))


def test_regen_image_matches_fixed_batch_multisample():
    """spp=4 ((0,2)-sequence sampler): samples of a pixel deposit in
    termination order instead of work order, so the per-pixel sums may
    differ by float rounding only."""
    r_fix = _render(4, {"TPU_PBRT_REGEN": "0"})
    r_reg = _render(4, {"TPU_PBRT_REGEN": "1", "TPU_PBRT_POOL": "512"})
    assert r_reg.rays_traced == r_fix.rays_traced
    np.testing.assert_allclose(
        np.asarray(r_reg.image), np.asarray(r_fix.image),
        rtol=1e-4, atol=1e-5,
    )


def test_regen_occupancy_high_on_depth5_diffuse():
    """The judged occupancy metric: with regeneration the mean wave
    occupancy on a depth-5 diffuse scene must exceed 0.9 (the fixed
    batch decays to ~0.3-0.4 after the first bounces), and the pool must
    finish in fewer trace waves per path than the fixed-batch loop's
    full-width max_depth+2 sweeps."""
    r = _render(64, {"TPU_PBRT_REGEN": "1", "TPU_PBRT_POOL": "1024"})
    occ = r.stats["mean_wave_occupancy"]
    assert occ > 0.9, r.stats
    # wave-count evidence: lane-waves actually dispatched vs what the
    # fixed batch pays (every work item rides every one of the
    # max_depth+2 full-width waves)
    total_work = 32 * 32 * 64
    pool_lane_waves = r.stats["n_waves"] * r.stats["pool"]
    fixed_lane_waves = total_work * (5 + 2)
    assert pool_lane_waves * 2 <= fixed_lane_waves, (
        pool_lane_waves, fixed_lane_waves,
    )


def test_regen_respects_opt_out():
    r = _render(1, {"TPU_PBRT_REGEN": "0"})
    # no pool/regen stats on the fixed-batch path; the non-finite
    # firewall (ISSUE 5) is the one telemetry entry it does report —
    # a clean render counts zero scrubbed deposits
    assert "regen" not in r.stats
    assert "mean_wave_occupancy" not in r.stats
    assert r.stats.get("telemetry", {}).get("counters", {}) == {
        "nonfinite_deposits": 0
    }
