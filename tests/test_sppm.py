"""SPPM tests (VERDICT r3 #5): cross-convergence against path on the
cornell box, photon-permutation invariance of the sort-by-cell gather
(the determinism property that replaces pbrt's atomic linked-list grid),
and the no-photons-dropped capacity assertion."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_pbrt.scenes import compile_api, make_cornell


def _make(spp=8, res=16, md=3, photons=4096, radius=-1.0):
    api = make_cornell(
        res=res,
        spp=spp,
        integrator="sppm",
        maxdepth=md,
    )
    scene, integ = compile_api(api)
    integ.n_iterations = spp
    integ.photons_per_iter = photons
    integ.initial_radius = radius
    return scene, integ


def test_sppm_matches_path_direct():
    """maxdepth=1 SPPM is pure camera-pass direct lighting (photons only
    deposit at depth>0, which needs maxdepth>=2): must equal path md=1."""
    from tpu_pbrt.scenes import make_cornell as mk

    api = mk(res=16, spp=16, integrator="path", maxdepth=1)
    scene_p, integ_p = compile_api(api)
    p = np.asarray(integ_p.render(scene_p).image)

    scene, integ = _make(spp=16, md=1, photons=256)
    s = np.asarray(integ.render(scene).image)
    rel = abs(s.mean() - p.mean()) / p.mean()
    assert rel < 0.05, f"sppm {s.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"


def test_sppm_matches_path_indirect():
    """maxdepth=3: photon-estimated indirect + NEE direct must converge to
    the path estimate on the cornell box (the caustic-glass axis's
    diffuse-scene oracle)."""
    from tpu_pbrt.scenes import make_cornell as mk

    api = mk(res=16, spp=48, integrator="path", maxdepth=3)
    scene_p, integ_p = compile_api(api)
    p = np.asarray(integ_p.render(scene_p).image)

    scene, integ = _make(spp=16, md=3, photons=4096)
    r = integ.render(scene)
    s = np.asarray(r.image)
    rel = abs(s.mean() - p.mean()) / p.mean()
    # photon density estimation carries kernel bias at finite radius; the
    # tolerance reflects biased-but-consistent convergence
    assert rel < 0.15, f"sppm {s.mean():.4f} vs path {p.mean():.4f} ({rel:.1%})"
    assert np.isfinite(s).all()


def test_gather_photon_permutation_invariance():
    """Shuffling the photon deposit order must not change the gathered
    flux (up to f32 summation order): the determinism property of the
    sort-based grid (SURVEY.md §5.2)."""
    scene, integ = _make(spp=2, md=3, photons=2048)
    dev = scene.dev

    px = jnp.arange(64, dtype=jnp.int32) % 16
    py = jnp.arange(64, dtype=jnp.int32) // 16
    vps, _ = integ._camera_pass(dev, px, py, jnp.int32(0))
    dep_p, dep_d, dep_beta, dep_valid, _ = integ._photon_pass(dev, 2048, jnp.int32(0))

    verts = np.asarray(dev["tri_verts"]).reshape(-1, 3)
    lo = jnp.asarray(verts.min(0) - 0.1, jnp.float32)
    r2 = jnp.full((64,), 0.01, jnp.float32)
    cs = jnp.float32(0.25)
    args = dict(r2=r2, lo=lo, cs=cs, gres=(64, 64, 64))

    phi0, m0, drop0 = integ._gather(dev, vps, dep_p, dep_d, dep_beta, dep_valid, **args)

    rng = np.random.default_rng(3)
    perm = jnp.asarray(rng.permutation(dep_p.shape[0]))
    phi1, m1, drop1 = integ._gather(
        dev, vps, dep_p[perm], dep_d[perm], dep_beta[perm], dep_valid[perm], **args
    )
    assert int(drop0) == 0 and int(drop1) == 0
    np.testing.assert_allclose(np.asarray(m0), np.asarray(m1), rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(phi0), np.asarray(phi1), rtol=1e-4, atol=1e-6)


def test_sppm_radius_shrinks():
    """The progressive radius must strictly shrink for pixels that
    received photons (r2' = r2 * (N + gamma*M)/(N + M) < r2 for M>0)."""
    scene, integ = _make(spp=3, md=3, photons=4096, radius=0.5)
    r = integ.render(scene)
    # re-derive state is internal; the observable proxy: the render
    # completed, produced finite non-black output, and dropped nothing
    img = np.asarray(r.image)
    assert np.isfinite(img).all()
    assert img.mean() > 1e-4
    assert r.stats["photons_dropped"] == 0


def test_sppm_multi_device_matches_single():
    """VERDICT r4 #2: a mesh SPPM render (pixels + photons sharded,
    deposits all-gathered over ICI) must equal the single-device render
    up to f32 accumulation order — the sharded photon-id ranges union to
    EXACTLY the single-device photon set."""
    import jax

    from tpu_pbrt.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    scene, integ = _make(spp=4, res=16, md=3, photons=4096)
    single = np.asarray(integ.render(scene).image)

    scene2, integ2 = _make(spp=4, res=16, md=3, photons=4096)
    mesh = make_mesh(4)
    multi = np.asarray(integ2.render(scene2, mesh=mesh).image)

    assert np.isfinite(multi).all()
    # identical photon set + exhaustive gather: only f32 summation order
    # differs; the sort order inside runs can also permute, so allow a
    # small relative envelope rather than bit equality
    denom = np.maximum(np.abs(single), 1e-3)
    rel = np.abs(multi - single) / denom
    assert float(rel.max()) < 2e-2, f"max rel dev {rel.max():.3e}"
    assert abs(multi.mean() - single.mean()) / max(single.mean(), 1e-9) < 2e-3
