"""BSSRDF tables + sampling (VERDICT r4 #3, bssrdf.cpp capability).
Oracles are physical invariants: energy conservation of the diffusion
profile, monotone effective albedo, diffuse-albedo inversion round
trip, and CDF-inversion consistency — no golden data."""

import numpy as np
import jax.numpy as jnp

from tpu_pbrt.core.bssrdf import (
    BakedBSSRDF,
    N_RADII,
    bake_profile,
    effective_albedo_curve,
    fresnel_moment1,
    pdf_sr,
    sample_sr,
    sr_eval,
    subsurface_from_diffuse,
    sw_eval,
)


def test_fresnel_moments_limits():
    # eta -> 1: no Fresnel reflection, both moments vanish
    assert abs(fresnel_moment1(1.0)) < 5e-3
    # denser media reflect more at grazing: moment grows with eta
    assert fresnel_moment1(1.5) > fresnel_moment1(1.2) > 0.0


def test_profile_energy_conserved_and_monotone_in_albedo():
    rhos = [0.2, 0.5, 0.8, 0.95]
    rho_effs = []
    for rho in rhos:
        _, prof, cdf, rho_eff, r_max = bake_profile(
            sigma_s=rho, sigma_a=1.0 - rho, g=0.0, eta=1.33
        )
        assert 0.0 < rho_eff < 1.0, rho_eff  # scatters less than it receives
        assert np.all(prof >= 0.0)
        assert np.all(np.diff(cdf) >= -1e-12)
        assert r_max > 0.0
        rho_effs.append(rho_eff)
    assert np.all(np.diff(rho_effs) > 0), rho_effs
    # a nearly-white medium keeps a substantial fraction of its energy
    assert rho_effs[-1] > 0.35


def test_effective_albedo_curve_invertible():
    rho_s, rho_e = effective_albedo_curve(g=0.0, eta=1.33, n=12)
    assert np.all(np.diff(rho_e) >= 0.0)
    assert rho_e[0] < 0.05 and rho_e[-1] > 0.3


def test_subsurface_from_diffuse_round_trip():
    kd = np.array([0.2, 0.5, 0.7])
    mfp = np.array([1.0, 1.0, 1.0])
    sigma_s, sigma_a = subsurface_from_diffuse(kd, mfp, g=0.0, eta=1.33)
    for c in range(3):
        _, _, _, rho_eff, _ = bake_profile(
            float(sigma_s[c]), float(sigma_a[c]), 0.0, 1.33
        )
        assert abs(rho_eff - kd[c]) < 0.05, (c, rho_eff, kd[c])


def _bake_device_table(media, eta=1.33):
    rows = []
    for sig_s, sig_a in media:
        chans = [bake_profile(sig_s, sig_a, 0.0, eta) for _ in range(3)]
        rows.append(chans)
    M = len(rows)
    radii = np.zeros((M, 3, N_RADII), np.float32)
    prof = np.zeros((M, 3, N_RADII), np.float32)
    cdf = np.zeros((M, 3, N_RADII), np.float32)
    rho = np.zeros((M, 3), np.float32)
    rmax = np.zeros((M, 3), np.float32)
    for m, chans in enumerate(rows):
        for c, (ra, pr, cd, re, rm) in enumerate(chans):
            radii[m, c], prof[m, c], cdf[m, c] = ra, pr, cd
            rho[m, c], rmax[m, c] = re, rm
    return BakedBSSRDF(
        radii=jnp.asarray(radii), profile=jnp.asarray(prof),
        cdf=jnp.asarray(cdf), rho_eff=jnp.asarray(rho),
        r_max=jnp.asarray(rmax), eta=jnp.full((M,), eta, jnp.float32),
    )


def test_sample_sr_matches_density():
    """MC mean radius under CDF-inversion sampling == the quadrature
    mean of the density 2*pi*r*Sr/rho_eff."""
    tab = _bake_device_table([(0.8, 0.2)])
    n = 4096
    u = jnp.asarray((np.arange(n) + 0.5) / n, jnp.float32)
    mid = jnp.zeros((n,), jnp.int32)
    ch = jnp.zeros((n,), jnp.int32)
    r_s = np.asarray(sample_sr(tab, mid, ch, u))
    radii = np.asarray(tab.radii)[0, 0].astype(np.float64)
    prof = np.asarray(tab.profile)[0, 0].astype(np.float64)
    dens = 2.0 * np.pi * radii * prof
    mean_q = np.trapz(radii * dens, radii) / np.trapz(dens, radii)
    assert abs(r_s.mean() - mean_q) / mean_q < 0.05, (r_s.mean(), mean_q)


def test_pdf_sr_is_area_density_of_sampling():
    """pdf_sr must equal Sr/rho_eff (the area density whose r-marginal
    the sampler inverts): check against the table directly."""
    tab = _bake_device_table([(0.6, 0.4)])
    radii = np.asarray(tab.radii)[0, 0]
    test_r = jnp.asarray(radii[5:50:7], jnp.float32)
    k = test_r.shape[0]
    mid = jnp.zeros((k,), jnp.int32)
    ch = jnp.zeros((k,), jnp.int32)
    got = np.asarray(pdf_sr(tab, mid, ch, test_r))
    want = np.asarray(tab.profile)[0, 0][5:50:7] / float(
        np.asarray(tab.rho_eff)[0, 0]
    )
    np.testing.assert_allclose(got, want, rtol=1e-3)


def test_sr_eval_interpolates_table():
    tab = _bake_device_table([(0.8, 0.2)])
    radii = np.asarray(tab.radii)[0, 0]
    mid = jnp.zeros((3,), jnp.int32)
    r = jnp.asarray(radii[[3, 10, 30]], jnp.float32)
    out = np.asarray(sr_eval(tab, mid, r))
    want = np.asarray(tab.profile)[0, :, :][:, [3, 10, 30]].T
    np.testing.assert_allclose(out, want, rtol=1e-3)


def test_sw_normalization():
    """Integral of Sw * cos over the hemisphere equals the average
    Fresnel transmittance normalized by c: integral(Sw cos) =
    (1 - 2*fm1) / c = 1 by construction."""
    eta = jnp.float32(1.33)
    n = 20000
    u = (np.arange(n) + 0.5) / n
    cos_t = np.sqrt(u)  # cosine-distributed
    sw = np.asarray(sw_eval(eta, jnp.asarray(cos_t, jnp.float32)))
    # E_cosine[Sw] * pi = integral Sw cos dw
    integral = sw.mean() * np.pi
    assert abs(integral - 1.0) < 0.02, integral


def test_beam_diffusion_ss_exit_fresnel_convention():
    """BeamDiffusionSS must evaluate the exit Fresnel on the INSIDE-TO-
    OUTSIDE crossing — pbrt's FrDielectric(-cosThetaO, 1, eta), i.e. the
    eta -> 1 branch (ISSUE 2 satellite: the entering-side convention
    (+cos_o) was used, overestimating transmission toward the critical
    angle). Oracle: re-integrate the single-scatter profile with an
    explicit exiting-Fresnel term and require exact agreement, and
    require DISAGREEMENT with the entering-side convention."""
    import math

    from tpu_pbrt.core.bssrdf import _N_DEPTH, _fr_dielectric, beam_diffusion_ss

    sigma_s, sigma_a, g, eta = 0.8, 0.2, 0.3, 1.5
    r = np.geomspace(1e-3, 2.0, 24)

    def reference(exit_sign):
        sigma_t = sigma_a + sigma_s
        rho = sigma_s / sigma_t
        t_crit = r * math.sqrt(max(eta * eta - 1.0, 0.0))
        out = np.zeros_like(r)
        for i in range(_N_DEPTH):
            ti = t_crit - math.log(1.0 - (i + 0.5) / _N_DEPTH) / sigma_t
            d = np.sqrt(r * r + ti * ti)
            cos_o = ti / np.maximum(d, 1e-9)
            g2 = g * g
            denom = 1.0 + g2 + 2.0 * g * (-cos_o)
            phase = (1.0 - g2) / (4.0 * math.pi * np.maximum(denom, 1e-9) ** 1.5)
            fr_exit = 1.0 - _fr_dielectric(exit_sign * cos_o, eta)
            out += (
                rho * np.exp(-sigma_t * (d + t_crit))
                / np.maximum(d * d, 1e-12) * phase * fr_exit * cos_o
            ) / _N_DEPTH
        return np.maximum(out, 0.0)

    got = beam_diffusion_ss(sigma_s, sigma_a, g, eta, r)
    np.testing.assert_allclose(got, reference(-1.0), rtol=1e-12)
    # the two conventions genuinely differ for this medium — the oracle
    # has teeth
    assert np.max(np.abs(reference(-1.0) - reference(+1.0))) > 1e-6


def test_beam_diffusion_ss_exit_transmission_bounded_by_tir():
    """With the exiting convention, a chord angle below the critical
    cosine is fully internally reflected: contributions only flow where
    1 - Fr(-cos) > 0, so the profile stays finite, nonnegative and
    decreasing at large radius."""
    from tpu_pbrt.core.bssrdf import beam_diffusion_ss

    r = np.geomspace(1e-3, 5.0, 40)
    ss = beam_diffusion_ss(1.0, 0.1, 0.0, 1.5, r)
    assert np.all(np.isfinite(ss)) and np.all(ss >= 0.0)
    assert ss[-1] < ss[0]
