"""SpatialLightDistribution tests (lightdistrib.cpp capability,
VERDICT r2 weak #9): position-dependent light selection must prefer
nearby lights and leave the estimator unbiased (strategy choice changes
variance, never the mean)."""

import numpy as np
import jax.numpy as jnp

from tests.test_render import QUAD, render_scene


def _two_light_scene(strategy, spp=16):
    return f'''
Integrator "directlighting" "string lightsamplestrategy" ["{strategy}"]
Sampler "sobol" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [24] "integer yresolution" [24] "string filename" [""]
LookAt 0 0 -4  0 0 0  0 1 0
Camera "perspective" "float fov" [70]
WorldBegin
AttributeBegin
AreaLightSource "diffuse" "rgb L" [20 4 4]
Shape "trianglemesh" {QUAD} "point P" [-2.2 0.4 0  -1.8 0.4 0  -1.8 0.8 0  -2.2 0.8 0]
AttributeEnd
AttributeBegin
AreaLightSource "diffuse" "rgb L" [4 4 20]
Shape "trianglemesh" {QUAD} "point P" [1.8 0.4 0  2.2 0.4 0  2.2 0.8 0  1.8 0.8 0]
AttributeEnd
Material "matte" "rgb Kd" [0.7 0.7 0.7]
Shape "trianglemesh" {QUAD} "point P" [-3 -1 0.5   3 -1 0.5   3 -1 -3  -3 -1 -3]
WorldEnd
'''


def test_spatial_distribution_built_and_prefers_near_light():
    from tpu_pbrt.scene.api import Options, parse_string, pbrt_init

    api = pbrt_init(Options(quiet=True))
    parse_string(_two_light_scene("spatial", spp=2), api, render=True)
    scene = api.scene
    sd = scene.spatial_distr
    assert sd is not None
    L = sd.cdf.shape[-1]
    assert L == scene.n_lights == 4  # two quads = four triangle rows
    # a point right next to the left light mostly picks a left-light row
    p_left = jnp.asarray([[-2.0, 0.6, -0.2]], jnp.float32)
    p_right = jnp.asarray([[2.0, 0.6, -0.2]], jnp.float32)
    u = jnp.linspace(0.01, 0.99, 64)[:, None] * jnp.ones((1, 1))
    picks_l = np.asarray(
        sd.sample_discrete_at(u[:, 0], jnp.broadcast_to(p_left, (64, 3)))[0]
    )
    picks_r = np.asarray(
        sd.sample_discrete_at(u[:, 0], jnp.broadcast_to(p_right, (64, 3)))[0]
    )
    assert (picks_l <= 1).mean() > 0.8, "near-left point should pick left light"
    assert (picks_r >= 2).mean() > 0.8, "near-right point should pick right light"
    # pmf consistency: discrete_pdf_at matches the sampled pick pmfs
    idx, pmf = sd.sample_discrete_at(u[:, 0], jnp.broadcast_to(p_left, (64, 3)))
    pmf2 = sd.discrete_pdf_at(idx, jnp.broadcast_to(p_left, (64, 3)))
    np.testing.assert_allclose(np.asarray(pmf), np.asarray(pmf2), rtol=1e-5)


def test_spatial_strategy_unbiased():
    img_s = render_scene(_two_light_scene("spatial", spp=32)).image
    img_p = render_scene(_two_light_scene("power", spp=32)).image
    rel = abs(img_s.mean() - img_p.mean()) / max(img_p.mean(), 1e-9)
    assert rel < 0.06, f"spatial {img_s.mean():.5f} vs power {img_p.mean():.5f}"
    assert np.isfinite(img_s).all()
