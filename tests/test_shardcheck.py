"""shardcheck (ISSUE 3 tentpole): static replication analysis over
shard_map bodies — adversarial fixtures (a body returning an unreduced
per-device value MUST be flagged), the collective-in-varying-loop rule,
the SHARD_MAP_NOCHECK jax-version gate, and the repo-level mirror that
keeps the real mesh entry points verified (the check jax's own
check_rep/check_vma used to do before PR 1 had to turn it off)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import tpu_pbrt.parallel.mesh as mesh_mod
from tpu_pbrt.analysis import shardcheck
from tpu_pbrt.parallel.mesh import SHARD_MAP_NOCHECK, TILE_AXIS, shard_map


def _mesh(n=2):
    return Mesh(np.array(jax.devices()[:n]), (TILE_AXIS,))


def _scan(fn, *args, entry="fixture"):
    jx = jax.make_jaxpr(fn)(*args)
    return shardcheck.scan_closed_jaxpr(jx, entry)


# ---------------------------------------------------------------------------
# adversarial fixtures
# ---------------------------------------------------------------------------


def test_unreduced_output_flagged():
    """ISSUE 3 satellite: a shard_map body that returns a per-device
    partial value through a P() (replicated) out_spec must be flagged."""
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(TILE_AXIS),), out_specs=P(),
             **SHARD_MAP_NOCHECK)
    def bad(x):
        return jnp.sum(x)  # no psum: device 0's partial would win

    findings, n = _scan(bad, jnp.ones((8,), jnp.float32))
    assert n == 1
    assert any(f.rule == "SC-UNREDUCED" for f in findings)


def test_psum_reduced_output_clean():
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(TILE_AXIS),), out_specs=P(),
             **SHARD_MAP_NOCHECK)
    def good(x):
        return jax.lax.psum(jnp.sum(x), TILE_AXIS)

    findings, n = _scan(good, jnp.ones((8,), jnp.float32))
    assert n == 1 and findings == []


def test_all_gather_counts_as_replicating():
    """The sppm photon-exchange shape: all_gather over the axis makes
    every device hold the full set — replicated."""
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(TILE_AXIS),), out_specs=P(),
             **SHARD_MAP_NOCHECK)
    def good(x):
        return jnp.sum(jax.lax.all_gather(x, TILE_AXIS, tiled=True))

    findings, n = _scan(good, jnp.ones((8,), jnp.float32))
    assert n == 1 and findings == []


def test_axis_index_taints_output():
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(),), out_specs=P(),
             **SHARD_MAP_NOCHECK)
    def bad(x):
        return x + jax.lax.axis_index(TILE_AXIS)  # device-varying

    findings, n = _scan(bad, jnp.ones((8,), jnp.float32))
    assert any(f.rule == "SC-UNREDUCED" for f in findings)


def test_varying_sharded_out_spec_is_fine():
    """A P(axis)-sharded output is ALLOWED to vary — only claimed-
    replicated outputs are checked."""
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(TILE_AXIS),),
             out_specs=P(TILE_AXIS), **SHARD_MAP_NOCHECK)
    def fine(x):
        return x * 2.0

    findings, n = _scan(fine, jnp.ones((8,), jnp.float32))
    assert n == 1 and findings == []


def test_replication_flows_through_while_loop():
    """A fully replicated while loop stays replicated (no false
    positive on lockstep loops)."""
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(),), out_specs=P(),
             **SHARD_MAP_NOCHECK)
    def fine(x):
        def body(c):
            i, v = c
            return i + 1, v * 2.0

        return jax.lax.while_loop(lambda c: c[0] < 4, body, (0, x))[1]

    findings, n = _scan(fine, jnp.ones((8,), jnp.float32))
    assert n == 1 and findings == []


def test_collective_inside_varying_trip_loop_flagged():
    """Per-device trip counts + a collective in the body = mismatched
    collective counts across the mesh (deadlock on real hardware). The
    drain-loop contract (no collectives inside the drain) is exactly
    what this rule locks in."""
    m = _mesh()

    @partial(shard_map, mesh=m, in_specs=(P(TILE_AXIS),), out_specs=P(),
             **SHARD_MAP_NOCHECK)
    def bad(x):
        def body(c):
            i, v = c
            return i + 1.0, v + jax.lax.psum(v, TILE_AXIS)

        # bound depends on the device's shard -> per-device trip count
        _, v = jax.lax.while_loop(
            lambda c: c[0] < x[0], body, (jnp.float32(0.0), jnp.sum(x))
        )
        return jax.lax.psum(v, TILE_AXIS)

    findings, n = _scan(bad, jnp.ones((8,), jnp.float32))
    assert any(f.rule == "SC-LOOP-COLLECTIVE" for f in findings)


# ---------------------------------------------------------------------------
# SHARD_MAP_NOCHECK version gate (ISSUE 3 satellite)
# ---------------------------------------------------------------------------


def test_nocheck_gate_disables_on_old_jax(monkeypatch):
    monkeypatch.setattr(mesh_mod, "_jax_version", lambda: (0, 4, 37))
    kw = mesh_mod.resolve_shard_map_nocheck()
    assert kw and list(kw.values()) == [False]


def test_nocheck_gate_keeps_native_check_on_new_jax(monkeypatch):
    monkeypatch.setattr(mesh_mod, "_jax_version", lambda: (0, 7, 2))
    assert mesh_mod.resolve_shard_map_nocheck() == {}


def test_nocheck_gate_env_override(monkeypatch):
    from tpu_pbrt import config

    monkeypatch.setattr(mesh_mod, "_jax_version", lambda: (0, 4, 37))
    monkeypatch.setenv("TPU_PBRT_SHARD_NATIVE_CHECK", "1")
    config.reload()
    assert mesh_mod.resolve_shard_map_nocheck() == {}
    monkeypatch.setenv("TPU_PBRT_SHARD_NATIVE_CHECK", "0")
    config.reload()
    monkeypatch.setattr(mesh_mod, "_jax_version", lambda: (0, 9, 0))
    kw = mesh_mod.resolve_shard_map_nocheck()
    assert kw and list(kw.values()) == [False]


def test_current_jax_version_parses():
    v = mesh_mod._jax_version()
    assert len(v) == 3 and all(isinstance(p, int) for p in v)
    # the live SHARD_MAP_NOCHECK must agree with the resolver
    assert mesh_mod.SHARD_MAP_NOCHECK == mesh_mod.resolve_shard_map_nocheck()


# ---------------------------------------------------------------------------
# the repo gate (tier-1 mirror of the CLI acceptance criterion)
# ---------------------------------------------------------------------------


def test_repo_mesh_entry_points_clean():
    """The real mesh programs (pool + chunk renderers, sppm mesh
    iteration) all verify: every claimed-replicated output is reduced."""
    errors, warnings = shardcheck.run_shardcheck()
    assert errors == [], "\n".join(errors)


def test_deleting_film_psum_is_caught(monkeypatch):
    """ISSUE 3 acceptance: removing the psum from the mesh step makes
    the suite exit non-zero with an entry-point diagnostic."""

    def broken_pool_renderer(mesh, per_device_drain):
        @partial(
            mesh_mod.shard_map, mesh=mesh,
            in_specs=(P(), P(TILE_AXIS)), out_specs=(P(), P()),
            **SHARD_MAP_NOCHECK,
        )
        def step(dev, starts):
            contrib, aux = per_device_drain(dev, starts)
            # BUG under test: film psum deleted; aux still reduced
            aux = jax.tree.map(
                lambda x: jax.lax.psum(x, TILE_AXIS), aux
            )
            return contrib, aux

        return step

    monkeypatch.setattr(
        mesh_mod, "sharded_pool_renderer", broken_pool_renderer
    )
    errors, _ = shardcheck.run_shardcheck(
        {"sharded_pool_renderer": __import__(
            "tpu_pbrt.analysis.audit", fromlist=["mesh_step_jaxpr"]
        ).mesh_step_jaxpr}
    )
    assert errors and "SC-UNREDUCED" in errors[0], errors
