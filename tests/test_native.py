"""Native C++ BVH builder tests: the ctypes bridge must produce the SAME
tree as the pure-numpy reference implementation (both implement pbrt's
binned SAH with identical f64 math and stable tie-breaking), and must be
substantially faster."""

import time

import numpy as np
import pytest

from tpu_pbrt.accel.build import _build_recursive, triangle_bounds
from tpu_pbrt.accel.native import get_lib, native_build_sah


def _random_tris(n, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.uniform(-10, 10, (n, 1, 3))
    tri = base + rng.normal(0, 0.3, (n, 3, 3))
    return tri


needs_native = pytest.mark.skipif(
    get_lib() is None, reason="native library unavailable (no g++?)"
)


@needs_native
@pytest.mark.parametrize("n", [1, 2, 7, 100, 5000])
def test_native_matches_numpy(n):
    bmin, bmax = triangle_bounds(_random_tris(n))
    a = native_build_sah(bmin.astype(np.float64), bmax.astype(np.float64), 4)
    b = _build_recursive(bmin.astype(np.float64), bmax.astype(np.float64), 4, "sah")
    assert a.n_nodes == b.n_nodes
    np.testing.assert_array_equal(a.prim_order, b.prim_order)
    np.testing.assert_array_equal(a.n_prims, b.n_prims)
    np.testing.assert_array_equal(a.prim_offset, b.prim_offset)
    np.testing.assert_array_equal(a.second_child, b.second_child)
    np.testing.assert_array_equal(a.axis, b.axis)
    np.testing.assert_allclose(a.bounds_min, b.bounds_min, rtol=1e-6)
    np.testing.assert_allclose(a.bounds_max, b.bounds_max, rtol=1e-6)


@needs_native
def test_native_covers_all_prims():
    """Every primitive appears exactly once in leaf order, and leaf
    metadata tiles the order array."""
    n = 20000
    bmin, bmax = triangle_bounds(_random_tris(n, seed=3))
    a = native_build_sah(bmin.astype(np.float64), bmax.astype(np.float64), 4)
    assert sorted(a.prim_order.tolist()) == list(range(n))
    leaves = a.n_prims > 0
    assert a.n_prims[leaves].sum() == n
    assert (a.n_prims <= 4).all()


@needs_native
def test_native_speedup():
    n = 100_000
    bmin, bmax = triangle_bounds(_random_tris(n, seed=1))
    b64min, b64max = bmin.astype(np.float64), bmax.astype(np.float64)
    t0 = time.time()
    native_build_sah(b64min, b64max, 4)
    t_native = time.time() - t0
    t0 = time.time()
    _build_recursive(b64min, b64max, 4, "sah")
    t_numpy = time.time() - t0
    assert t_native < t_numpy / 5, f"native {t_native:.2f}s vs numpy {t_numpy:.2f}s"
