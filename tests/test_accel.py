"""Stage-1 geometry-kernel tests: BVH build + traversal vs brute-force
oracle, watertight intersection stress (modeled on pbrt src/tests/shapes.cpp
randomized triangle stress, SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_pbrt.accel import build as bvh_build
from tpu_pbrt.accel.traverse import (
    brute_force_intersect,
    bvh_as_device_dict,
    bvh_intersect,
    bvh_intersect_p,
    intersect_triangle,
)


def random_tris(n, rng, spread=10.0, size=1.0):
    base = rng.uniform(-spread, spread, (n, 1, 3))
    offs = rng.uniform(-size, size, (n, 3, 3))
    return (base + offs).astype(np.float32)


def random_rays(n, rng, spread=12.0):
    o = rng.uniform(-spread, spread, (n, 3)).astype(np.float32)
    d = rng.normal(size=(n, 3)).astype(np.float32)
    d /= np.linalg.norm(d, axis=1, keepdims=True)
    return o, d


@pytest.mark.parametrize("method", ["sah", "hlbvh", "middle", "equal"])
def test_bvh_matches_brute_force(method):
    rng = np.random.default_rng(7)
    tris = random_tris(300, rng)
    bmin, bmax = bvh_build.triangle_bounds(tris)
    bvh = bvh_build.build_bvh(bmin, bmax, method=method)
    tris_perm = jnp.asarray(tris[bvh.prim_order])
    dev = bvh_as_device_dict(bvh)

    o, d = random_rays(500, rng)
    o, d = jnp.asarray(o), jnp.asarray(d)
    hit_bvh = bvh_intersect(dev, tris_perm, o, d, 1e30)
    hit_bf = brute_force_intersect(tris_perm, o, d, 1e30, chunk=128)

    hit_mask_bvh = np.asarray(hit_bvh.prim >= 0)
    hit_mask_bf = np.asarray(hit_bf.prim >= 0)
    np.testing.assert_array_equal(hit_mask_bvh, hit_mask_bf)
    assert hit_mask_bf.sum() > 20, "test scene produced too few hits to be meaningful"
    np.testing.assert_allclose(
        np.asarray(hit_bvh.t)[hit_mask_bvh], np.asarray(hit_bf.t)[hit_mask_bf], rtol=1e-5, atol=1e-5
    )
    # where the nearest prim is unique, ids must agree
    same = np.asarray(hit_bvh.prim) == np.asarray(hit_bf.prim)
    assert same[hit_mask_bvh].mean() > 0.99


def test_intersect_p_consistent_with_closest_hit():
    rng = np.random.default_rng(11)
    tris = random_tris(200, rng)
    bmin, bmax = bvh_build.triangle_bounds(tris)
    bvh = bvh_build.build_bvh(bmin, bmax)
    tris_perm = jnp.asarray(tris[bvh.prim_order])
    dev = bvh_as_device_dict(bvh)
    o, d = random_rays(400, rng)
    o, d = jnp.asarray(o), jnp.asarray(d)
    closest = bvh_intersect(dev, tris_perm, o, d, 1e30)
    any_hit = bvh_intersect_p(dev, tris_perm, o, d, 1e30)
    np.testing.assert_array_equal(np.asarray(any_hit), np.asarray(closest.prim >= 0))


def test_t_max_respected():
    tri = jnp.asarray([[[0.0, -1, -1], [0, 1, -1], [0, 0, 1]]], dtype=jnp.float32)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(np.asarray(tri)))
    dev = bvh_as_device_dict(bvh)
    o = jnp.asarray([[-5.0, 0, 0]])
    d = jnp.asarray([[1.0, 0, 0]])
    assert int(bvh_intersect(dev, tri, o, d, 10.0).prim[0]) == 0
    assert int(bvh_intersect(dev, tri, o, d, 4.0).prim[0]) == -1
    assert not bool(bvh_intersect_p(dev, tri, o, d, 4.0)[0])


def test_watertight_shared_edge():
    """Rays aimed at the shared edge of a quad's two triangles must hit
    exactly one of them (the watertight guarantee)."""
    quad = np.array(
        [
            [[0, 0, 0], [1, 0, 0], [1, 1, 0]],
            [[0, 0, 0], [1, 1, 0], [0, 1, 0]],
        ],
        dtype=np.float32,
    )
    rng = np.random.default_rng(3)
    n = 256
    # points exactly on the diagonal x=y
    s = rng.uniform(0.05, 0.95, n).astype(np.float32)
    targets = np.stack([s, s, np.zeros_like(s)], axis=1)
    o = targets + np.array([0.3, -0.2, 2.5], dtype=np.float32)
    d = targets - o
    d /= np.linalg.norm(d, axis=1, keepdims=True)
    h0, *_ = intersect_triangle(jnp.asarray(o), jnp.asarray(d), *[jnp.asarray(quad[0, i]) for i in range(3)], 1e30)
    h1, *_ = intersect_triangle(jnp.asarray(o), jnp.asarray(d), *[jnp.asarray(quad[1, i]) for i in range(3)], 1e30)
    n_hits = np.asarray(h0).astype(int) + np.asarray(h1).astype(int)
    assert (n_hits >= 1).all(), "edge rays leaked through the shared edge"


def test_barycentrics_reconstruct_point():
    rng = np.random.default_rng(5)
    tris = random_tris(50, rng)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris))
    tris_perm = jnp.asarray(tris[bvh.prim_order])
    dev = bvh_as_device_dict(bvh)
    # aim rays at random triangle interiors so most rays hit
    o = rng.uniform(-15, 15, (200, 3)).astype(np.float32)
    picks = rng.integers(0, len(tris), 200)
    w = rng.dirichlet((1, 1, 1), 200).astype(np.float32)
    targets = np.einsum("nk,nkc->nc", w, tris[picks])
    d = targets - o
    d /= np.linalg.norm(d, axis=1, keepdims=True)
    o, d = jnp.asarray(o), jnp.asarray(d)
    hit = bvh_intersect(dev, tris_perm, o, d, 1e30)
    m = np.asarray(hit.prim >= 0)
    assert m.sum() > 5
    prim = np.asarray(hit.prim)[m]
    b0 = np.asarray(hit.b0)[m][:, None]
    b1 = np.asarray(hit.b1)[m][:, None]
    b2 = 1.0 - b0 - b1
    tv = np.asarray(tris_perm)[prim]
    p_bary = b0 * tv[:, 0] + b1 * tv[:, 1] + b2 * tv[:, 2]
    p_ray = np.asarray(o)[m] + np.asarray(hit.t)[m][:, None] * np.asarray(d)[m]
    np.testing.assert_allclose(p_bary, p_ray, atol=2e-3)


def test_single_and_degenerate_clusters():
    # all prims at the same centroid -> leaf fallback paths
    tri = np.tile(np.array([[[0, 0, 0], [1, 0, 0], [0, 1, 0]]], dtype=np.float32), (8, 1, 1))
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tri))
    dev = bvh_as_device_dict(bvh)
    o = jnp.asarray([[0.2, 0.2, 5.0]])
    d = jnp.asarray([[0.0, 0.0, -1.0]])
    hit = bvh_intersect(dev, jnp.asarray(tri[bvh.prim_order]), o, d, 1e30)
    assert int(hit.prim[0]) >= 0
    np.testing.assert_allclose(float(hit.t[0]), 5.0, rtol=1e-5)


def test_morton_codes_ordering():
    pts = np.array([[0, 0, 0], [1, 1, 1], [0.49, 0.49, 0.49], [0.51, 0.51, 0.51]], dtype=np.float64)
    codes = bvh_build.morton_codes(pts, np.zeros(3), np.ones(3))
    assert codes[0] < codes[2] < codes[3] < codes[1]


def test_big_morton_build_flat_layout():
    rng = np.random.default_rng(1)
    tris = random_tris(5000, rng)
    bmin, bmax = bvh_build.triangle_bounds(tris)
    bvh = bvh_build.build_bvh(bmin, bmax, method="hlbvh", max_leaf_prims=4)
    # interior nodes: left child adjacent, second child within bounds
    # (padded empty leaves also have n_prims==0 but inverted inf bounds)
    interior = (bvh.n_prims == 0) & (bvh.second_child > 0)
    ids = np.arange(bvh.n_nodes)
    assert (bvh.second_child[interior] > ids[interior]).all()
    assert (bvh.second_child[interior] < bvh.n_nodes).all()
    # all prims appear exactly once in leaf order
    np.testing.assert_array_equal(np.sort(bvh.prim_order), np.arange(5000))
    # parent bounds contain child bounds
    sc = bvh.second_child[interior]
    assert (bvh.bounds_min[interior] <= bvh.bounds_min[interior.nonzero()[0] + 1] + 1e-6).all()
    assert (bvh.bounds_min[interior] <= bvh.bounds_min[sc] + 1e-6).all()


def test_sah_prim_order_valid():
    rng = np.random.default_rng(2)
    tris = random_tris(777, rng)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris), method="sah")
    np.testing.assert_array_equal(np.sort(bvh.prim_order), np.arange(777))
    # leaves cover the full prim range without overlap
    leaves = bvh.n_prims > 0
    spans = sorted(zip(bvh.prim_offset[leaves], bvh.n_prims[leaves]))
    cursor = 0
    for off, cnt in spans:
        assert off == cursor
        cursor += cnt
    assert cursor == 777


def test_degenerate_cluster_exceeding_leaf_cap_still_all_hittable():
    """>MAX_LEAF_PRIMS distinct tris sharing one centroid must be force-split
    so the unrolled leaf loop can't silently drop primitives."""
    tris = np.array(
        [[[-s, -s, 0], [s, -s, 0], [0, 2 * s, 0]] for s in [0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8]],
        np.float32,
    )
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris))
    assert bvh.n_prims.max() <= bvh_build.MAX_LEAF_PRIMS
    dev = bvh_as_device_dict(bvh)
    tp = jnp.asarray(tris[bvh.prim_order])
    # point only inside the largest triangle
    h = bvh_intersect(dev, tp, jnp.asarray([[0.55, -0.55, 5]], jnp.float32), jnp.asarray([[0, 0, -1]], jnp.float32), 1e30)
    assert int(h.prim[0]) >= 0


def test_slab_nan_edge_on_ray_not_rejected():
    """Ray with d[axis]==0 and origin exactly on a node's slab plane: the
    0*inf NaN must be treated as inside-slab (pbrt's conservative ordering)."""
    tri = jnp.asarray([[[2, -1, -0.01], [2, 1, -0.01], [2, 0, 1]]], jnp.float32)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(np.asarray(tri)))
    dev = bvh_as_device_dict(bvh)
    h = bvh_intersect(dev, tri, jnp.asarray([[0, 0, 0.0]], jnp.float32), jnp.asarray([[1, 0, 0]], jnp.float32), 1e30)
    assert int(h.prim[0]) == 0
    np.testing.assert_allclose(float(h.t[0]), 2.0, rtol=1e-5)


# -------------------------------------------------------------------------
# MXU feature-matmul leaf tests + packet/treelet traversal (accel/mxu.py,
# accel/treelet.py, accel/packet.py)
# -------------------------------------------------------------------------

def _oracle_compare(hit, hit_bf, min_hits=20):
    m = np.asarray(hit.prim >= 0)
    mb = np.asarray(hit_bf.prim >= 0)
    np.testing.assert_array_equal(m, mb)
    assert mb.sum() > min_hits
    np.testing.assert_allclose(
        np.asarray(hit.t)[m], np.asarray(hit_bf.t)[m], rtol=1e-4, atol=1e-4
    )
    same = np.asarray(hit.prim) == np.asarray(hit_bf.prim)
    assert same[m].mean() > 0.99


def test_brute_feature_matches_oracle():
    from tpu_pbrt.accel.mxu import brute_feature_intersect, tri_feature_weights
    from tpu_pbrt.accel.traverse import brute_force_intersect

    rng = np.random.default_rng(21)
    tris = random_tris(200, rng)
    ctr = tris.mean(axis=(0, 1))
    feat = jnp.asarray(tri_feature_weights(tris, ctr))
    o, d = random_rays(600, rng)
    o, d = jnp.asarray(o), jnp.asarray(d)
    hf = brute_feature_intersect(feat, jnp.asarray(ctr), 200, o, d, 1e30)
    hb = brute_force_intersect(jnp.asarray(tris), o, d, 1e30, chunk=256)
    _oracle_compare(hf, hb)


def test_packet_matches_oracle():
    from tpu_pbrt.accel.packet import packet_intersect, packet_intersect_p
    from tpu_pbrt.accel.traverse import brute_force_intersect
    from tpu_pbrt.accel.treelet import build_treelet_pack

    rng = np.random.default_rng(23)
    tris = random_tris(3000, rng)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris), method="sah")
    tris_perm = tris[bvh.prim_order]
    tp = build_treelet_pack(tris_perm, bvh)
    assert tp.n_treelets > 8  # actually exercises the two-level walk
    o, d = random_rays(700, rng)
    o, d = jnp.asarray(o), jnp.asarray(d)
    hp = packet_intersect(tp, o, d, 1e30)
    hb = brute_force_intersect(jnp.asarray(tris_perm), o, d, 1e30, chunk=256)
    _oracle_compare(hp, hb)
    # any-hit predicate consistent with closest hit
    np.testing.assert_array_equal(
        np.asarray(packet_intersect_p(tp, o, d, 1e30)), np.asarray(hp.prim >= 0)
    )


def test_packet_t_max_respected():
    from tpu_pbrt.accel.packet import packet_intersect
    from tpu_pbrt.accel.treelet import build_treelet_pack

    tris = np.asarray([[[0.0, -1, -1], [0, 1, -1], [0, 0, 1]]], np.float32)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris))
    tp = build_treelet_pack(tris[bvh.prim_order], bvh)
    o = jnp.asarray([[-5.0, 0, 0]])
    d = jnp.asarray([[1.0, 0, 0]])
    assert int(packet_intersect(tp, o, d, 10.0).prim[0]) == 0
    assert int(packet_intersect(tp, o, d, 4.0).prim[0]) == -1


def test_treelet_cut_covers_all_prims():
    from tpu_pbrt.accel.treelet import cut_treelets

    rng = np.random.default_rng(29)
    tris = random_tris(2500, rng)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris), method="sah")
    off, cnt, bmin, bmax = cut_treelets(bvh)
    # treelet ranges tile [0, n) without gaps or overlap
    spans = sorted(zip(off.tolist(), cnt.tolist()))
    cursor = 0
    for o_, c_ in spans:
        assert o_ == cursor
        cursor += c_
    assert cursor == 2500


# -------------------------------------------------------------------------
# Stream (sort/compaction wavefront) traversal — accel/stream.py
# -------------------------------------------------------------------------

def test_stream_matches_oracle():
    from tpu_pbrt.accel.stream import (
        STREAM_LEAF_TRIS,
        stream_intersect,
        stream_intersect_p,
        stream_traverse_stats,
    )
    from tpu_pbrt.accel.traverse import brute_force_intersect
    from tpu_pbrt.accel.treelet import build_treelet_pack

    rng = np.random.default_rng(31)
    tris = random_tris(9000, rng)  # > 8 treelets at the 512-tri leaf default
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris), method="sah")
    tris_perm = tris[bvh.prim_order]
    tp = build_treelet_pack(tris_perm, bvh, leaf_tris=STREAM_LEAF_TRIS)
    assert tp.n_treelets > 8
    o, d = random_rays(700, rng)
    o, d = jnp.asarray(o), jnp.asarray(d)
    hs = stream_intersect(tp, jnp.asarray(tris_perm), o, d, 1e30)
    hb = brute_force_intersect(jnp.asarray(tris_perm), o, d, 1e30, chunk=256)
    _oracle_compare(hs, hb)
    np.testing.assert_array_equal(
        np.asarray(stream_intersect_p(tp, o, d, 1e30)), np.asarray(hs.prim >= 0)
    )
    # worklist capacity must never overflow (overflow = silent false misses)
    *_, n_drop, _ = stream_traverse_stats(tp, o, d, 1e30)
    assert int(n_drop) == 0


def test_stream_t_max_and_degenerate():
    from tpu_pbrt.accel.stream import STREAM_LEAF_TRIS, stream_intersect
    from tpu_pbrt.accel.treelet import build_treelet_pack

    tris = np.asarray([[[0.0, -1, -1], [0, 1, -1], [0, 0, 1]]], np.float32)
    bvh = bvh_build.build_bvh(*bvh_build.triangle_bounds(tris))
    tp = build_treelet_pack(tris[bvh.prim_order], bvh, leaf_tris=STREAM_LEAF_TRIS)
    o = jnp.asarray([[-5.0, 0, 0]])
    d = jnp.asarray([[1.0, 0, 0]])
    tv = jnp.asarray(tris[bvh.prim_order])
    assert int(stream_intersect(tp, tv, o, d, 10.0).prim[0]) == 0
    assert int(stream_intersect(tp, tv, o, d, 4.0).prim[0]) == -1
    # dead rays (t_max <= 0) must report misses
    assert int(stream_intersect(tp, tv, o, d, -1.0).prim[0]) == -1


def test_fused_flush_kernel_parity_interpret():
    """The fused wavefront flush kernel (accel/fusedwave.py) must agree
    with mxu.decode_outputs per block — run in interpreter mode so the
    TPU production path is covered by the CPU suite (a drift, e.g. a
    one-sided EDGE_EPS change, would otherwise ship silently and only
    surface as a corrupted render on hardware). Each block gets its own
    disjoint 128 rays, so the cross-block merge reduces to the per-block
    winners and the comparison is direct."""
    import jax

    from tpu_pbrt.accel.fusedwave import fused_flush_chunk
    from tpu_pbrt.accel.mxu import decode_outputs, ray_features, tri_feature_weights_raw

    rng = np.random.default_rng(41)
    B, L = 4, 64
    R = B * 128
    tris = rng.uniform(-1, 1, (B * L, 3, 3)).astype(np.float32)
    W = tri_feature_weights_raw(tris, np.zeros(3))
    featT = np.ascontiguousarray(
        W.reshape(B, L, 16, 4).transpose(0, 3, 1, 2).reshape(B, 4 * L, 16)
    )
    o = rng.uniform(-2, 2, (B, 128, 3)).astype(np.float32)
    d = rng.normal(size=(B, 128, 3)).astype(np.float32)
    d /= np.linalg.norm(d, axis=-1, keepdims=True)
    tb = jnp.full((B, 128), 1e30, jnp.float32)
    phi = jnp.swapaxes(ray_features(jnp.asarray(o), jnp.asarray(d)), 1, 2)
    feat_b = jnp.swapaxes(jnp.asarray(featT), 1, 2)  # (B, 16, 4L)

    out = jnp.einsum("cfb,cfk->cbk", phi, feat_b, precision=jax.lax.Precision.HIGHEST)
    t_ref, k_ref, _, _ = decode_outputs(out, L, tb)

    # kernel inputs: block b owns rays [128b, 128(b+1)), feature row b,
    # prim offset 1000*b, center 0 (matching the reference's phi build)
    rayF = jnp.concatenate(
        [
            jnp.asarray(o.reshape(R, 3).T),
            jnp.asarray(d.reshape(R, 3).T),
            jnp.full((1, R), 1e30, jnp.float32),
            jnp.zeros((1, R), jnp.float32),
        ]
    )
    rid_rows = jnp.arange(R, dtype=jnp.int32).reshape(B, 128)
    zero_bits = np.float32(0.0).view(np.int32)
    meta = jnp.stack(
        [
            jnp.arange(B, dtype=jnp.int32),
            1000 * jnp.arange(B, dtype=jnp.int32),
            jnp.full((B,), zero_bits, jnp.int32),
            jnp.full((B,), zero_bits, jnp.int32),
            jnp.full((B,), zero_bits, jnp.int32),
            jnp.ones((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32),
        ],
        axis=1,
    )
    t_row, prim = fused_flush_chunk(
        feat_b, meta, rid_rows, rayF,
        jnp.full((R,), jnp.inf, jnp.float32),
        jnp.full((R,), -1, jnp.int32),
        interpret=True,
    )
    t_pal = np.asarray(t_row).reshape(B, 128)
    p_pal = np.asarray(prim).reshape(B, 128)

    hit_ref = np.isfinite(np.asarray(t_ref))
    hit_pal = np.isfinite(t_pal)
    np.testing.assert_array_equal(hit_ref, hit_pal)
    assert hit_ref.sum() > 50
    np.testing.assert_array_equal(
        t_pal[hit_pal].view(np.int32),
        np.asarray(t_ref)[hit_ref].view(np.int32),
    )
    k_expect = 1000 * np.arange(B)[:, None] + np.asarray(k_ref)
    np.testing.assert_array_equal(p_pal[hit_pal], k_expect[hit_pal])


def test_capacity_overflow_detected_and_loud(monkeypatch):
    """VERDICT r4 #6, two halves: (a) starved worklists really do count
    drops in-kernel; (b) a render whose audit sees drops raises unless
    the escape hatch is set."""
    import pytest

    import tpu_pbrt.integrators.common as C
    from tpu_pbrt.accel.stream import stream_traverse_stats
    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    # (a) real drops: shrink the stack headroom far below a fat wave.
    # stream_traverse_stats reads the env at TRACE time — clear its jit
    # cache so earlier/later same-shape traces cannot leak sizes across
    # the env flip in either direction
    from tpu_pbrt import config

    stream_traverse_stats.clear_cache()
    monkeypatch.setenv("TPU_PBRT_HEADROOM", "0.0")
    monkeypatch.setenv("TPU_PBRT_SLAB", "4096")
    config.reload()
    api = make_killeroo_like(res=64, spp=2)
    scene, integ = compile_api(api)
    dev = scene.dev
    n = 1 << 18
    k = jnp.arange(n, dtype=jnp.int32)
    pf = jnp.stack(
        [(k % 64).astype(jnp.float32) + 0.5,
         ((k // 64) % 64).astype(jnp.float32) + 0.5], -1)
    from tpu_pbrt.cameras import generate_rays

    o, d, _ = generate_rays(scene.camera, pf, jnp.zeros_like(pf))
    *_, drops, _ = stream_traverse_stats(dev["tstream"], o, d, jnp.inf)
    assert int(drops) > 0, "starved worklists must register drops"

    # (b) the render-side audit fails loudly on any drop (patch the
    # audit seam so this leg does not depend on chunk-size heuristics)
    monkeypatch.delenv("TPU_PBRT_HEADROOM", raising=False)
    monkeypatch.delenv("TPU_PBRT_SLAB", raising=False)
    config.reload()
    import tpu_pbrt.accel.stream as stream_mod

    real_stats = stream_mod.stream_traverse_stats
    fake = lambda *a, **kw: (  # noqa: E731
        jnp.int32(1), jnp.int32(1), jnp.int32(7), jnp.int32(1))
    monkeypatch.setattr(stream_mod, "stream_traverse_stats", fake)
    api2 = make_killeroo_like(res=16, spp=1)
    scene2, integ2 = compile_api(api2)
    with pytest.raises(RuntimeError, match="dropped 7 traversal pairs"):
        integ2.render(scene2)
    monkeypatch.setenv("TPU_PBRT_ALLOW_DROPS", "1")
    config.reload()
    res = integ2.render(scene2)
    assert res.completed_fraction == 1.0
    monkeypatch.setattr(stream_mod, "stream_traverse_stats", real_stats)
    stream_traverse_stats.clear_cache()
