"""Participating-media tests: HG phase normalization/sampling consistency
(pbrt src/tests/hg.cpp counterpart) and analytic Beer-Lambert attenuation
through the volpath integrator."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_pbrt.core import media as md
from tpu_pbrt.core.sampling import uniform_float
from tests.test_render import render_scene


class TestHenyeyGreenstein:
    @pytest.mark.parametrize("g", [-0.6, -0.1, 0.0, 0.3, 0.9])
    def test_normalization(self, g):
        """Integral of p over the sphere = 1 (hg.cpp HenyeyGreenstein test)."""
        mu = np.linspace(-1, 1, 20001)
        p = np.asarray(md.hg_p(jnp.asarray(mu), g))
        integral = 2 * np.pi * np.trapezoid(p, mu)
        assert abs(integral - 1.0) < 1e-3, (g, integral)

    @pytest.mark.parametrize("g", [-0.5, 0.0, 0.7])
    def test_sampling_consistency(self, g):
        """Sampled directions reproduce the analytic mean cosine. pbrt's
        convention has wo pointing BACK along the incoming ray, so forward
        scattering is dot(wo,wi) = -1 and E[dot(wo,wi)] = -g."""
        n = 200_000
        i = jnp.arange(n)
        u1 = uniform_float(i, 101)
        u2 = uniform_float(i, 202)
        wo = jnp.broadcast_to(jnp.asarray([0.0, 0.0, 1.0]), (n, 3))
        wi, pdf = md.hg_sample(wo, jnp.full((n,), g, jnp.float32), u1, u2)
        wi = np.asarray(wi)
        assert np.allclose(np.linalg.norm(wi, axis=-1), 1.0, atol=1e-4)
        mu = wi[:, 2]  # dot(wo, wi)
        assert abs(mu.mean() - (-g)) < 5e-3, (g, mu.mean())
        # pdf returned must match hg_p at the sampled angle, and be a
        # correctly normalized density: E[1/(2 pi p)] = integral dmu = 2
        p2 = np.asarray(md.hg_p(jnp.asarray(mu), g))
        assert np.allclose(np.asarray(pdf), p2, rtol=1e-3, atol=1e-5)
        assert abs(float(np.mean(1.0 / (2 * np.pi * np.asarray(pdf)))) - 2.0) < 0.02


class TestVolPath:
    def test_beer_lambert_absorption(self):
        """Camera inside a purely absorbing homogeneous medium looking at an
        area light: pixel = Le * exp(-sigma_a * distance)."""
        sigma_a = 0.4
        dist = 3.0
        r = render_scene(
            f'''
Integrator "volpath" "integer maxdepth" [3]
Sampler "halton" "integer pixelsamples" [512]
PixelFilter "box"
Film "image" "integer xresolution" [16] "integer yresolution" [16] "string filename" [""]
LookAt 0 0 -3  0 0 0  0 1 0
MakeNamedMedium "fog" "string type" "homogeneous" "rgb sigma_a" [{sigma_a} {sigma_a} {sigma_a}] "rgb sigma_s" [0 0 0]
MediumInterface "" "fog"
Camera "perspective" "float fov" [50]
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [5 5 5]
  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-4 -4 0  -4 4 0  4 4 0  4 -4 0]
AttributeEnd
WorldEnd
'''
        )
        img = r.image
        expected = 5.0 * np.exp(-sigma_a * dist)
        got = float(img[7:9, 7:9].mean())
        assert abs(got - expected) / expected < 0.05, (got, expected)

    def test_no_medium_matches_path(self):
        """volpath on a medium-free scene must agree with path."""
        body = '''
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [8 8 8]
  Translate 0 1.8 0
  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-0.6 0 -0.6  0.6 0 -0.6  0.6 0 0.6  -0.6 0 0.6]
AttributeEnd
Material "matte" "rgb Kd" [0.7 0.6 0.5]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-2 -2 2  2 -2 2  2 2 2  -2 2 2]
WorldEnd
'''
        hdr = '''
Sampler "halton" "integer pixelsamples" [128]
PixelFilter "box"
Film "image" "integer xresolution" [20] "integer yresolution" [20] "string filename" [""]
LookAt 0 0 -3  0 0 0  0 1 0
Camera "perspective" "float fov" [60]
'''
        r1 = render_scene('Integrator "volpath" "integer maxdepth" [2]' + hdr + body)
        r2 = render_scene('Integrator "path" "integer maxdepth" [2]' + hdr + body)
        mse = float(np.mean((r1.image - r2.image) ** 2))
        scale = float(np.mean(r2.image**2)) + 1e-9
        assert mse / scale < 0.01, mse / scale

    def test_scattering_medium_brightens_shadow(self):
        """An isotropically scattering fog between light and a shadowed
        region adds in-scattered radiance where the direct path is blocked:
        single-scatter NEE from medium interactions must be nonzero."""
        r = render_scene(
            '''
Integrator "volpath" "integer maxdepth" [3]
Sampler "halton" "integer pixelsamples" [64]
PixelFilter "box"
Film "image" "integer xresolution" [16] "integer yresolution" [16] "string filename" [""]
LookAt 0 0 -3  0 0 0  0 1 0
MakeNamedMedium "fog" "string type" "homogeneous" "rgb sigma_a" [0.01 0.01 0.01] "rgb sigma_s" [0.4 0.4 0.4] "float g" [0.0]
MediumInterface "" "fog"
Camera "perspective" "float fov" [50]
WorldBegin
LightSource "point" "rgb I" [20 20 20] "point from" [0 2 0]
Material "matte" "rgb Kd" [0.1 0.1 0.1]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-9 -9 4  9 -9 4  9 9 4  -9 9 4]
WorldEnd
'''
        )
        img = r.image
        # fog glow: every pixel picks up in-scattered light
        assert float(img.min()) > 0.0
        assert float(img.mean()) > 0.01


class TestNullInterface:
    """ADVICE r1 (high): MAT_NONE container geometry must not occlude NEE
    shadow rays — pbrt VisibilityTester::Tr passes through null-BSDF
    surfaces accumulating per-segment transmittance."""

    CUBE = (
        'Shape "trianglemesh" "integer indices" '
        "[0 1 2 0 2 3  4 6 5 4 7 6  0 4 1 1 4 5  2 6 3 3 6 7  1 5 2 2 5 6  0 3 7 0 7 4] "
        '"point P" [-1 -1 -1  1 -1 -1  1 -1 1  -1 -1 1  -1 1 -1  1 1 -1  1 1 1  -1 1 1]'
    )

    def test_bounded_medium_not_black(self):
        """Scattering medium inside a null-material container, light
        outside: in-medium direct lighting must pass through the container
        walls (the cloud.pbrt topology)."""
        r = render_scene(
            f'''
Integrator "volpath" "integer maxdepth" [3]
Sampler "halton" "integer pixelsamples" [64]
PixelFilter "box"
Film "image" "integer xresolution" [16] "integer yresolution" [16] "string filename" [""]
LookAt 0 0 -4  0 0 0  0 1 0
Camera "perspective" "float fov" [40]
MakeNamedMedium "cloud" "string type" "homogeneous" "rgb sigma_a" [0.05 0.05 0.05] "rgb sigma_s" [0.8 0.8 0.8] "float g" [0.0]
WorldBegin
LightSource "point" "rgb I" [40 40 40] "point from" [0 3 0]
AttributeBegin
  Material "none"
  MediumInterface "cloud" ""
  {self.CUBE}
AttributeEnd
WorldEnd
'''
        )
        img = np.asarray(r.image)
        center = float(img[6:10, 6:10].mean())
        assert center > 0.005, f"in-medium NEE is black through the container: {center}"

    def test_null_quad_does_not_occlude_path(self):
        """path integrator: a null-material quad between an area light and
        a matte floor must neither block the light (NEE) nor silhouette the
        continuation rays."""
        body = '''
WorldBegin
AttributeBegin
  AreaLightSource "diffuse" "rgb L" [10 10 10]
  Translate 0 2 0
  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-0.8 0 -0.8  0.8 0 -0.8  0.8 0 0.8  -0.8 0 0.8]
AttributeEnd
{blocker}
Material "matte" "rgb Kd" [0.7 0.7 0.7]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-2 -1 -2  2 -1 -2  2 -1 2  -2 -1 2]
WorldEnd
'''
        hdr = '''
Integrator "path" "integer maxdepth" [3]
Sampler "halton" "integer pixelsamples" [128]
PixelFilter "box"
Film "image" "integer xresolution" [16] "integer yresolution" [16] "string filename" [""]
LookAt 0 0.4 -3.5  0 -0.4 0  0 1 0
Camera "perspective" "float fov" [45]
'''
        null_quad = (
            'AttributeBegin\n  Material "none"\n'
            '  Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] '
            '"point P" [-1.5 0.5 -1.5  1.5 0.5 -1.5  1.5 0.5 1.5  -1.5 0.5 1.5]\nAttributeEnd\n'
        )
        r_null = render_scene(hdr + body.format(blocker=null_quad))
        r_open = render_scene(hdr + body.format(blocker=""))
        m_null = float(np.asarray(r_null.image).mean())
        m_open = float(np.asarray(r_open.image).mean())
        assert m_open > 0.01
        assert abs(m_null - m_open) / m_open < 0.05, (m_null, m_open)


class TestVolumeFurnace:
    """VERDICT r4 #9: a closed-form in-scattering oracle. A camera at
    the center of a uniformly emitting sphere filled with a purely
    scattering medium must see EXACTLY the shell radiance L0 for any
    scattering coefficient and phase anisotropy (radiative transfer in
    a uniform isotropic field is the identity when sigma_a = 0) —
    exercising distance sampling, HG phase sampling, NEE-with-Tr, and
    multiple scattering at once."""

    @pytest.mark.parametrize("g", [0.0, 0.5])
    def test_scattering_furnace(self, g):
        L0 = 2.0
        sigma_s = 0.25  # tau = 1.25 to the shell: real multiple scatter
        r = render_scene(
            f'''
Integrator "volpath" "integer maxdepth" [12]
Sampler "halton" "integer pixelsamples" [256]
PixelFilter "box"
Film "image" "integer xresolution" [8] "integer yresolution" [8] "string filename" [""]
LookAt 0 0 0  0 0 1  0 1 0
MakeNamedMedium "fog" "string type" "homogeneous" "rgb sigma_a" [0 0 0] "rgb sigma_s" [{sigma_s} {sigma_s} {sigma_s}] "float g" [{g}]
MediumInterface "" "fog"
Camera "perspective" "float fov" [60]
WorldBegin
AttributeBegin
  # black-bodied pure emitter: a reflective shell would multiply the
  # furnace by 1/(1-rho)
  Material "matte" "rgb Kd" [0 0 0]
  AreaLightSource "diffuse" "rgb L" [{L0} {L0} {L0}] "bool twosided" ["true"]
  Shape "sphere" "float radius" [5]
AttributeEnd
WorldEnd
'''
        )
        img = np.asarray(r.image)
        got = float(img.mean())
        assert np.isfinite(img).all()
        # truncation at maxdepth loses a little energy; 8% envelope
        assert abs(got - L0) / L0 < 0.08, (got, L0, g)
