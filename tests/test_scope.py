"""tpu-scope (ISSUE 15): request tracing + timeline reconstruction,
the health watchdog, the bench regression gate, and the per-job flight
rotation cap.

The acceptance scenario lives in TestScopeReconstruction: a DEPTH-2
pipelined serve run with tracing and the flight recorder armed, a
preempt/resume cycle, and a chaos `dispatch:poison` landing mid-window
— `tools/scope.py --check` must rebuild every job's causal timeline
from the exported trace + per-job flight files and find it complete
(paired job/wait/slice spans, bound flow arrows, ok-retired coverage
of every chunk, flight heartbeats joined by trace id).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_pbrt import config
from tpu_pbrt.obs import health
from tpu_pbrt.obs.flight import FlightRecorder, job_flight_path
from tpu_pbrt.obs.metrics import MetricsRegistry
from tpu_pbrt.obs.trace import TRACE, TraceRecorder, validate_trace
from tpu_pbrt.scene.api import Options, compile_string
from tpu_pbrt.scenes import cornell_box_text
from tpu_pbrt.serve.service import RenderService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TEXT = cornell_box_text(res=32, spp=1, integrator="path", maxdepth=3)
CHUNK = 256  # 32*32*1 = 1024 work items -> 4 chunk-slices per job


def _ev(ph, name="n", ts=0.0, **extra):
    ev = {"name": name, "ph": ph, "ts": ts, "pid": 0, "tid": 0, "args": {}}
    if ph == "X":
        ev.setdefault("dur", 1.0)
    ev.update(extra)
    return ev


# --------------------------------------------------------------------------
# trace validator: async pairing, flow binding, overlap attribution
# --------------------------------------------------------------------------


class TestAsyncTraceValidator:
    def test_recorder_roundtrip_validates_clean(self, tmp_path):
        rec = TraceRecorder()
        rec.configure(str(tmp_path / "t.json"))
        tid = rec.trace_id("j1")
        assert tid == "t:j1"
        rec.async_begin("serve/job", id=tid, cat="job", job="j1")
        with rec.async_span("serve/queue_wait", id=f"{tid}/q1", cat="queue"):
            pass
        rec.flow_start("slice_flow", id=f"{tid}/c0")
        rec.flow_finish("slice_flow", id=f"{tid}/c0")
        rec.complete("serve/backoff", 1234.5, chunk=0)
        rec.async_end("serve/job", id=tid, cat="job", outcome="done")
        p = rec.export()
        assert validate_trace(p) == []
        doc = json.load(open(p))
        fin = [e for e in doc["traceEvents"] if e["ph"] == "f"]
        assert fin and fin[0]["bp"] == "e", (
            "flow finish must bind to the enclosing slice (bp=e)"
        )

    def test_unpaired_async_begin_rejected(self):
        doc = {"traceEvents": [
            _ev("b", "serve/job", id="t:j1", cat="job"),
        ]}
        errs = validate_trace(doc)
        assert errs and "never ended" in errs[0]

    def test_async_end_without_begin_rejected(self):
        doc = {"traceEvents": [
            _ev("e", "serve/job", id="t:j1", cat="job"),
        ]}
        errs = validate_trace(doc)
        assert errs and "without an open begin" in errs[0]

    def test_flow_finish_without_start_rejected(self):
        doc = {"traceEvents": [
            _ev("f", "slice_flow", id="t:j1/c0", cat="flow", bp="e"),
        ]}
        errs = validate_trace(doc)
        assert errs and "without a matching flow start" in errs[0]

    def test_unfinished_flow_rejected(self):
        doc = {"traceEvents": [
            _ev("s", "slice_flow", id="t:j1/c0", cat="flow"),
        ]}
        errs = validate_trace(doc)
        assert errs and "never finished" in errs[0]

    def test_async_event_requires_cat_and_id(self):
        errs = validate_trace({"traceEvents": [_ev("b", "x")]})
        assert any("without a cat" in e for e in errs)
        assert any("without an id" in e for e in errs)

    def test_overlapping_slices_without_ahead_rejected(self):
        """The satellite's exact gap: a depth-2 trace whose in-flight
        slice spans overlap but which carries no *_ahead
        dispatch-attribution span anywhere."""
        overlap = [
            _ev("b", "serve/slice_inflight", id="t:a/c0", cat="slice", ts=0),
            _ev("b", "serve/slice_inflight", id="t:a/c1", cat="slice", ts=5),
            _ev("e", "serve/slice_inflight", id="t:a/c0", cat="slice", ts=10),
            _ev("e", "serve/slice_inflight", id="t:a/c1", cat="slice", ts=15),
        ]
        errs = validate_trace({"traceEvents": overlap})
        assert errs and "_ahead" in errs[0]
        ok = overlap + [_ev("X", "serve/dispatch_ahead", ts=5, dur=2.0)]
        assert validate_trace({"traceEvents": ok}) == []

    def test_sequential_slices_need_no_ahead(self):
        """Depth-1 (non-overlapping) slices are fine without any
        lookahead attribution — the check keys on actual overlap."""
        doc = {"traceEvents": [
            _ev("b", "render/slice", id="t:a/c0", cat="slice", ts=0),
            _ev("e", "render/slice", id="t:a/c0", cat="slice", ts=10),
            _ev("b", "render/slice", id="t:a/c1", cat="slice", ts=10),
            _ev("e", "render/slice", id="t:a/c1", cat="slice", ts=20),
        ]}
        assert validate_trace(doc) == []


# --------------------------------------------------------------------------
# health watchdog conditions (pure units)
# --------------------------------------------------------------------------


class _FakeJob:
    def __init__(self, status="queued", attempt=0, job_id="j1"):
        self.status = status
        self.attempt = attempt
        self.job_id = job_id


class _FakeService:
    def __init__(self, jobs=(), steps=0, progress=0, sheds=0, seq=0):
        self.jobs = {j.job_id: j for j in jobs}
        self.health_steps = steps
        self.last_progress_step = progress
        self.sheds = sheds
        self._seq = seq


class TestHealthWatchdog:
    def _reg(self):
        return MetricsRegistry(force_enabled=True)

    def test_wedge_fires_on_stuck_runnable_work(self):
        svc = _FakeService([_FakeJob("queued")], steps=20, progress=2)
        rep = health.evaluate(svc, self._reg(),
                              health.Thresholds(wedge_steps=12))
        assert "wedge" in rep.firing()

    def test_wedge_silent_without_runnable_jobs(self):
        """A long idle gap with every job terminal/paused is not a
        wedge — there is nothing to make progress ON."""
        svc = _FakeService([_FakeJob("done")], steps=100, progress=0)
        rep = health.evaluate(svc, self._reg(),
                              health.Thresholds(wedge_steps=12))
        assert rep.ok

    def test_wedge_silent_under_threshold(self):
        svc = _FakeService([_FakeJob("queued")], steps=11, progress=0)
        rep = health.evaluate(svc, self._reg(),
                              health.Thresholds(wedge_steps=12))
        assert "wedge" not in rep.firing()

    def test_backoff_storm_fires_on_live_retry_streak(self):
        svc = _FakeService([_FakeJob("parked", attempt=3)], steps=1)
        rep = health.evaluate(svc, self._reg())
        assert "backoff_storm" in rep.firing()
        # attempt resets on success: the same job post-recovery is clean
        svc2 = _FakeService([_FakeJob("active", attempt=0)], steps=1)
        assert health.evaluate(svc2, self._reg()).ok

    def test_slo_burn_needs_fraction_and_floor(self):
        reg = self._reg()
        reg.counter("serve_shed_total", "sheds").inc(4, tenant="a")
        reg.counter("serve_submits_total", "admits").inc(2, tenant="a")
        rep = health.evaluate(None, reg)
        assert "slo_burn" in rep.firing()
        # 2 sheds of 4: over 50%? no — exactly 50% with floor unmet
        reg2 = self._reg()
        reg2.counter("serve_shed_total", "sheds").inc(2, tenant="a")
        reg2.counter("serve_submits_total", "admits").inc(2, tenant="a")
        assert health.evaluate(None, reg2).ok

    def test_slo_burn_falls_back_to_service_counts(self):
        """Registry armed but empty (metrics enabled after the fact):
        the service's own deterministic counts carry the signal."""
        svc = _FakeService(sheds=5, seq=1)
        rep = health.evaluate(svc, self._reg())
        assert "slo_burn" in rep.firing()

    def test_nonfinite_spike(self):
        reg = self._reg()
        reg.counter(
            "render_nonfinite_total", "scrubbed deposits"
        ).inc(7, tenant="a")
        rep = health.evaluate(None, reg)
        assert "nonfinite_spike" in rep.firing()
        cond = {c.name: c for c in rep.conditions}["nonfinite_spike"]
        assert cond.value == 7.0

    def test_snapshot_evaluation_matches_registry(self):
        reg = self._reg()
        reg.counter("serve_shed_total", "sheds").inc(4, tenant="a")
        reg.counter("serve_submits_total", "admits").inc(1, tenant="a")
        reg.counter(
            "render_nonfinite_total", "scrubbed deposits"
        ).inc(2, tenant="a")
        live = health.evaluate(None, reg)
        snap = health.evaluate_snapshot(reg.snapshot())
        assert live.firing() == snap.firing() == [
            "slo_burn", "nonfinite_spike",
        ]

    def test_report_shape(self):
        d = health.evaluate(None, self._reg()).to_dict()
        assert d["ok"] is True and d["firing"] == []
        assert sorted(c["name"] for c in d["conditions"]) == [
            "backoff_storm", "nonfinite_spike", "slo_burn", "wedge",
        ]


# --------------------------------------------------------------------------
# per-job flight rotation cap (satellite a)
# --------------------------------------------------------------------------


class TestJobFlightRotation:
    def test_job_heartbeat_rotates_at_cap(self, tmp_path, monkeypatch):
        """The TPU_PBRT_FLIGHT_MAX_MB cap must govern per-job files
        written through job_heartbeat — the pre-fix service re-armed
        `_path` per heartbeat and the cap applied only as a side effect
        of that swap."""
        monkeypatch.setenv("TPU_PBRT_FLIGHT_MAX_MB", "0.001")  # 1000 B
        config.reload()
        base = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(base)
        for i in range(40):  # ~100 B/line: several rotations
            fr.job_heartbeat("j1", "serve_slice", chunk=i, pad="x" * 60)
        per_job = job_flight_path(base, "j1")
        assert os.path.exists(per_job) and os.path.exists(per_job + ".1")
        assert os.path.getsize(per_job) < 2000
        assert os.path.getsize(per_job + ".1") < 2000
        assert not os.path.exists(base), (
            "job heartbeats must land in the per-job file only"
        )

    def test_job_heartbeat_disabled_writes_nothing(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        config.reload()
        base = str(tmp_path / "flight.jsonl")
        fr = FlightRecorder()
        fr.configure(base)
        fr.job_heartbeat("j1", "serve_slice", chunk=0)
        assert fr.last_phase == "serve_slice"
        assert not os.listdir(tmp_path)


# --------------------------------------------------------------------------
# bench regression gate (satellite + tentpole layer 3)
# --------------------------------------------------------------------------


class TestBenchGate:
    def test_selftest_and_named_regression(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--selftest"],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert r.returncode == 0, r.stdout + r.stderr

    def test_fresh_regression_exits_nonzero_naming_metric(self, tmp_path):
        base = json.load(open(os.path.join(REPO, "BENCH_r03.json")))["parsed"]
        slow = dict(base)
        slow["value"] = base["value"] * 0.5
        p = str(tmp_path / "fresh.json")
        json.dump(slow, open(p, "w"))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"), p],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert r.returncode == 1
        assert "value regressed" in r.stderr

    def test_outage_capture_exempt(self, tmp_path):
        p = str(tmp_path / "outage.json")
        json.dump({"value": 0.0, "error": "backend gone"}, open(p, "w"))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"), p],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert r.returncode == 0
        assert "OUTAGE" in r.stdout


# --------------------------------------------------------------------------
# the acceptance scenario: depth-2 + preempt/resume + chaos poison
# --------------------------------------------------------------------------


class TestScopeReconstruction:
    def _armed_run(self, tmp_path, monkeypatch):
        """Depth-2 pipelined serve drain with tracing + flight armed:
        two tenants, a preempt/resume cycle on j2, and a chaos
        `dispatch:poison` firing mid-window (rollback replay for the
        checkpointed job). Returns (trace path, flight base, job ids)."""
        from tpu_pbrt.chaos import CHAOS

        trace_p = str(tmp_path / "trace.json")
        flight_p = str(tmp_path / "flight.jsonl")
        monkeypatch.setenv("TPU_PBRT_TRACE_PATH", trace_p)
        monkeypatch.setenv("TPU_PBRT_FLIGHT_PATH", flight_p)
        monkeypatch.setenv("TPU_PBRT_PIPELINE", "2")
        monkeypatch.setenv("TPU_PBRT_RETRY_BACKOFF", "0.01")
        config.reload()
        TRACE.reset()
        svc = RenderService(chunk=CHUNK, seed=0)
        opts = Options(quiet=True)
        j1 = svc.submit(
            text=TEXT, tenant="alice",
            checkpoint_path=str(tmp_path / "j1.ckpt"), checkpoint_every=1,
        )
        j2 = svc.submit(text=TEXT, tenant="bob")
        CHAOS.install("dispatch:poison@chunk=2", seed=0)
        try:
            for _ in range(3):
                svc.step()
            svc.preempt(j2)
            for _ in range(2):
                svc.step()
            svc.resume(j2)
            svc.drain()
        finally:
            CHAOS.clear()
        for j in (j1, j2):
            assert svc.jobs[j].status == "done", svc.jobs[j].error
        assert TRACE.export() == trace_p
        TRACE.reset()
        return trace_p, flight_p, (j1, j2)

    def test_depth2_poisoned_run_reconstructs_gap_free(
        self, tmp_path, monkeypatch
    ):
        trace_p, flight_p, jobs = self._armed_run(tmp_path, monkeypatch)
        # the exported trace itself passes the async/flow validator
        assert validate_trace(trace_p) == []
        # and scope.py rebuilds one complete causal timeline per job
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "scope.py"),
             trace_p, "--flight", flight_p, "--check"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert r.returncode == 0, (
            f"scope --check found defects:\n{r.stdout}\n{r.stderr}"
        )
        assert "2 done" in r.stdout
        # single-job filter + human timeline render
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "scope.py"),
             trace_p, "--flight", flight_p, "--job", jobs[0]],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert r2.returncode == 0, r2.stdout + r2.stderr
        assert f"t:{jobs[0]}" in r2.stdout
        assert "retired ok" in r2.stdout
        # per-job flight lines carry the job's trace id (the join key)
        per_job = flight_p.replace("flight.jsonl", f"flight.{jobs[0]}.jsonl")
        lines = [
            json.loads(x)
            for x in open(per_job).read().splitlines() if x.strip()
        ]
        assert lines and all(
            ln["trace_id"] == f"t:{jobs[0]}" for ln in lines
        )
        phases = {ln["phase"] for ln in lines}
        assert {"serve_submit", "serve_done"} <= phases

    def test_scope_check_catches_a_severed_timeline(
        self, tmp_path, monkeypatch
    ):
        """Adversarial half: drop one slice's retire (async end) event
        from a valid export — scope --check must exit non-zero and name
        the job."""
        trace_p, flight_p, jobs = self._armed_run(tmp_path, monkeypatch)
        doc = json.load(open(trace_p))
        evs = doc["traceEvents"]
        cut = next(
            i for i, e in enumerate(evs)
            if e.get("ph") == "e" and e.get("cat") == "slice"
            and str(e.get("id", "")).startswith(f"t:{jobs[0]}/")
        )
        severed = [e for i, e in enumerate(evs) if i != cut]
        bad_p = str(tmp_path / "severed.json")
        json.dump({"traceEvents": severed}, open(bad_p, "w"))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "scope.py"),
             bad_p, "--check"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert r.returncode != 0
        assert jobs[0] in r.stderr or f"t:{jobs[0]}" in r.stderr

    def test_unarmed_run_emits_no_artifacts(self, tmp_path, monkeypatch):
        """With TPU_PBRT_TRACE_PATH unset the whole tpu-scope layer is
        a no-op: no events buffered, no flight files, byte-identical
        render stats path (the contract the ISSUE pins)."""
        monkeypatch.delenv("TPU_PBRT_TRACE_PATH", raising=False)
        monkeypatch.delenv("TPU_PBRT_FLIGHT_PATH", raising=False)
        config.reload()
        TRACE.reset()
        svc = RenderService(chunk=CHUNK, seed=0)
        j = svc.submit(text=TEXT, tenant="alice")
        svc.drain()
        assert svc.jobs[j].status == "done"
        assert TRACE._events == []
        assert TRACE.maybe_export() is None
        assert not [
            f for f in os.listdir(tmp_path) if "flight" in f or "trace" in f
        ]
