"""Chaos fault-injection subsystem (ISSUE 5): plan grammar + registry
determinism, each injection point firing exactly once, recovery
bit-identity, checkpoint v4 (checksum, fsync+rotate, corrupt-current ->
.prev fallback, v2/v3 compat), the non-finite film firewall
(scrub/count/raise/retry), retry backoff shape, and the bench probe's
chaos-hang + backoff satellite."""

import os

import numpy as np
import pytest

from tpu_pbrt import config
from tpu_pbrt.chaos import CHAOS, Fault, parse_plan


@pytest.fixture(autouse=True)
def _clear_chaos():
    """The registry is process-global state like the config snapshot —
    never let one test's plan leak into the next."""
    CHAOS.clear()
    yield
    CHAOS.clear()


def _render(res=12, spp=2, maxdepth=2, chunk=96, **render_kw):
    """Small multi-chunk pool render (res*res*spp=288 work items / 96 =
    3 chunks) shared by the recovery tests."""
    os.environ["TPU_PBRT_CHUNK"] = str(chunk)
    os.environ.setdefault("TPU_PBRT_RETRY_BACKOFF", "0.01")
    config.reload()
    try:
        from tpu_pbrt.scenes import compile_api, make_cornell

        api = make_cornell(
            res=res, spp=spp, integrator="path", maxdepth=maxdepth
        )
        scene, integ = compile_api(api)
        return integ.render(scene, **render_kw)
    finally:
        del os.environ["TPU_PBRT_CHUNK"]
        os.environ.pop("TPU_PBRT_RETRY_BACKOFF", None)
        config.reload()


# ---------------------------------------------------------------------------
# plan grammar
# ---------------------------------------------------------------------------


class TestPlanParsing:
    def test_full_grammar(self):
        plan = parse_plan(
            "dispatch:poison@chunk=3,ckpt:torn@write=2,"
            "nan:wave@5&chunk=1,probe:hang@attempt=1"
        )
        assert [(f.site, f.kind) for f in plan] == [
            ("dispatch", "poison"), ("ckpt", "torn"),
            ("nan", "wave"), ("probe", "hang"),
        ]
        assert plan[0].params == {"chunk": 3}
        # bare @value binds to the site's default key
        assert plan[2].params == {"wave": 5, "chunk": 1}
        assert plan[3].params == {"attempt": 1}

    def test_times_and_defaults(self):
        (f,) = parse_plan("dispatch:fail@chunk=2&times=99")
        assert f.times == 99 and f.params == {"chunk": 2}
        (g,) = parse_plan("mesh:lost")
        assert g.site == "mesh" and g.params == {} and g.times == 1

    def test_empty_plan(self):
        assert parse_plan("") == []
        assert parse_plan("  ,  ") == []

    @pytest.mark.parametrize(
        "bad",
        ["bogus:fail@chunk=1", "dispatch:explode", "nan:wave@x=y",
         "dispatch", "ckpt:torn@write=banana"],
    )
    def test_invalid_plans_fail_loudly(self, bad):
        """A typo'd plan must not silently inject nothing — that would
        certify recovery that was never exercised."""
        with pytest.raises(ValueError):
            parse_plan(bad)

    @pytest.mark.parametrize(
        "bad",
        ["dispatch:fail@chunck=3", "nan:wave@5&chnk=2", "ckpt:torn@chunk=1"],
    )
    def test_unknown_param_keys_fail_loudly(self, bad):
        """A typo'd KEY must not fall through to the seams' .get()
        defaults and fire the fault somewhere other than where the plan
        claimed."""
        with pytest.raises(ValueError, match="unknown param"):
            parse_plan(bad)

    def test_spec_roundtrip(self):
        for spec in ("dispatch:poison@chunk=3", "ckpt:torn@write=2"):
            (f,) = parse_plan(spec)
            assert parse_plan(f.spec())[0] == f


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_fires_exactly_once_and_exhausts(self):
        from tpu_pbrt.integrators.common import ChunkDispatchError

        CHAOS.install("dispatch:fail@chunk=1")
        with pytest.raises(ChunkDispatchError) as ei:
            CHAOS.dispatch(1, 0)
        assert not ei.value.poisons_state
        # exhausted: the re-dispatch of the same chunk runs clean
        CHAOS.dispatch(1, 1)
        CHAOS.dispatch(1, 0)
        assert CHAOS.report() == [
            {"fault": "dispatch:fail@chunk=1", "fired": 1, "times": 1}
        ]

    def test_attempt_matching(self):
        from tpu_pbrt.integrators.common import ChunkDispatchError

        CHAOS.install("dispatch:fail@chunk=0&attempt=1")
        CHAOS.dispatch(0, 0)  # wrong attempt: clean
        with pytest.raises(ChunkDispatchError):
            CHAOS.dispatch(0, 1)

    def test_poison_and_mesh_kinds(self):
        from tpu_pbrt.integrators.common import ChunkDispatchError

        CHAOS.install("dispatch:poison@chunk=2")
        with pytest.raises(ChunkDispatchError) as ei:
            CHAOS.dispatch(2, 0)
        assert ei.value.poisons_state
        CHAOS.install("mesh:lost@chunk=1")
        CHAOS.dispatch(1, 0, mesh=False)  # mesh faults need a mesh
        with pytest.raises(ChunkDispatchError) as ei:
            CHAOS.dispatch(1, 0, mesh=True)
        assert ei.value.poisons_state

    def test_registered_hook_is_called(self):
        """The promoted first-class form of the old test-only
        `integ._fault_hook` monkeypatch."""
        seen = []
        CHAOS.register_hook(lambda c, a: seen.append((c, a)))
        CHAOS.dispatch(4, 2)
        assert seen == [(4, 2)]
        CHAOS.clear()
        CHAOS.dispatch(4, 2)
        assert seen == [(4, 2)]

    def test_determinism_same_seed_same_bitflip(self):
        CHAOS.install("ckpt:bitflip@write=1", seed=7)
        a = CHAOS.bitflip_offset(10_000)
        CHAOS.install("ckpt:bitflip@write=1", seed=7)
        assert CHAOS.bitflip_offset(10_000) == a
        CHAOS.install("ckpt:bitflip@write=1", seed=8)
        assert CHAOS.bitflip_offset(10_000) != a

    def test_nan_wave_host_decision(self):
        CHAOS.install("nan:wave@3&chunk=2")
        assert CHAOS.has_nan() and CHAOS.trace_key() == (True,)
        assert CHAOS.nan_wave_for(0) == -1
        assert CHAOS.nan_wave_for(2) == 3
        # fired: the retry of chunk 2 is clean
        assert CHAOS.nan_wave_for(2) == -1
        CHAOS.clear()
        assert CHAOS.trace_key() == (False,)

    def test_probe_hang_parity_with_bench_parser(self):
        """The import-free parser in bench.py and the registry agree on
        the probe:hang grammar."""
        import bench

        CHAOS.install("probe:hang@attempt=2")
        assert not CHAOS.probe_hang(1) and CHAOS.probe_hang(2)
        os.environ["TPU_PBRT_FAULTS"] = "probe:hang@attempt=2,probe:hang@3"
        try:
            assert bench._probe_hang_attempts() == {2, 3}
        finally:
            del os.environ["TPU_PBRT_FAULTS"]


# ---------------------------------------------------------------------------
# retry backoff
# ---------------------------------------------------------------------------


class TestBackoff:
    def test_capped_exponential_with_deterministic_jitter(self, monkeypatch):
        from tpu_pbrt.integrators.common import redispatch_backoff

        monkeypatch.setenv("TPU_PBRT_RETRY_BACKOFF", "1.0")
        monkeypatch.setenv("TPU_PBRT_RETRY_BACKOFF_CAP", "8.0")
        config.reload()
        b = [redispatch_backoff(3, k) for k in range(1, 8)]
        # deterministic
        assert b == [redispatch_backoff(3, k) for k in range(1, 8)]
        # jitter keeps each sleep within [0.5, 1.0] * min(2^(k-1), cap)
        for k, v in enumerate(b, start=1):
            ceil = min(2.0 ** (k - 1), 8.0)
            assert 0.5 * ceil <= v <= ceil
        # capped: the tail stops growing past the cap
        assert max(b) <= 8.0
        # different chunks decorrelate
        assert redispatch_backoff(4, 1) != redispatch_backoff(3, 1)

    def test_zero_base_disables_sleeping(self, monkeypatch):
        from tpu_pbrt.integrators.common import redispatch_backoff

        monkeypatch.setenv("TPU_PBRT_RETRY_BACKOFF", "0")
        config.reload()
        assert redispatch_backoff(0, 5) == 0.0


# ---------------------------------------------------------------------------
# checkpoint v4
# ---------------------------------------------------------------------------


class TestCheckpointV4:
    def _state(self, fill=1.0):
        import jax.numpy as jnp

        from tpu_pbrt.core.film import FilmState

        return FilmState(
            rgb=jnp.full((4, 4, 3), fill), weight=jnp.full((4, 4), fill),
            splat=jnp.zeros((4, 4, 3)),
        )

    def test_v4_writes_checksum_and_rotates_prev(self, tmp_path):
        from tpu_pbrt.parallel.checkpoint import (
            _FORMAT_VERSION,
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._state(1.0), 1, 10, fingerprint="fp")
        with np.load(p) as z:
            assert int(z["version"]) == _FORMAT_VERSION == 4
            assert "checksum" in z
        assert not os.path.exists(p + ".prev")
        save_checkpoint(p, self._state(2.0), 2, 20, fingerprint="fp")
        # the previous good write is kept as the corruption fallback
        _, nxt, _, _ = load_checkpoint(p + ".prev", "fp")
        assert nxt == 1
        _, nxt, _, _ = load_checkpoint(p, "fp")
        assert nxt == 2

    def test_corrupt_current_falls_back_to_prev(self, tmp_path):
        from tpu_pbrt.parallel.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._state(1.0), 1, 10, fingerprint="fp")
        save_checkpoint(p, self._state(2.0), 2, 20, fingerprint="fp")
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
        st, nxt, rays, _ = load_checkpoint(p, "fp")
        assert (nxt, rays) == (1, 10)
        assert float(np.asarray(st.rgb)[0, 0, 0]) == 1.0

    def test_truncated_current_falls_back(self, tmp_path):
        from tpu_pbrt.parallel.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._state(1.0), 1, 10)
        save_checkpoint(p, self._state(2.0), 2, 20)
        with open(p, "rb") as f:
            data = f.read()
        with open(p, "wb") as f:
            f.write(data[: len(data) // 3])
        _, nxt, _, _ = load_checkpoint(p)
        assert nxt == 1

    def test_missing_current_falls_back_to_prev(self, tmp_path):
        """Only .prev on disk (a crash in a hardlink-less rotation, or a
        deleted current): checkpoint_exists sees it and load falls
        back — resume must not silently restart from chunk 0."""
        from tpu_pbrt.parallel.checkpoint import (
            checkpoint_exists,
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        assert not checkpoint_exists(p)
        save_checkpoint(p, self._state(1.0), 1, 10)
        save_checkpoint(p, self._state(2.0), 2, 20)
        os.remove(p)
        assert checkpoint_exists(p)
        _, nxt, _, _ = load_checkpoint(p)
        assert nxt == 1

    def test_rotation_never_unpublishes_current(self, tmp_path):
        """The .prev rotation hardlinks the old current in place: at
        every instant a complete file exists at `path` (a rename-based
        rotate has a crash window with NO current checkpoint)."""
        from tpu_pbrt.parallel.checkpoint import save_checkpoint

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._state(1.0), 1, 10)
        ino = os.stat(p).st_ino
        save_checkpoint(p, self._state(2.0), 2, 20)
        # .prev is the OLD current's inode: the rotation was a link, not
        # a rename that momentarily removed `path`
        assert os.stat(p + ".prev").st_ino == ino
        assert os.stat(p).st_ino != ino

    def test_corrupt_without_prev_raises(self, tmp_path):
        from tpu_pbrt.parallel.checkpoint import (
            CorruptCheckpointError,
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._state(), 1, 10)
        with open(p, "wb") as f:
            f.write(b"garbage")
        with pytest.raises(CorruptCheckpointError):
            load_checkpoint(p)

    def test_fingerprint_mismatch_never_falls_back(self, tmp_path):
        """Misconfiguration is not corruption: resuming under the wrong
        settings must refuse even though a .prev exists."""
        from tpu_pbrt.parallel.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, self._state(), 1, 10, fingerprint="a")
        save_checkpoint(p, self._state(), 2, 20, fingerprint="a")
        with pytest.raises(ValueError, match="different render configuration"):
            load_checkpoint(p, "b")

    def test_v2_and_v3_files_still_load(self, tmp_path):
        from tpu_pbrt.parallel.checkpoint import load_checkpoint

        st = self._state()
        for version, extra in ((2, {}), (
            3, {"counters": np.array('{"rays_traced": 9}')}
        )):
            p = str(tmp_path / f"v{version}.npz")
            np.savez_compressed(
                p, version=version, rgb=np.asarray(st.rgb),
                weight=np.asarray(st.weight), splat=np.asarray(st.splat),
                next_chunk=5, rays=77, fingerprint=np.array(""), **extra,
            )
            _, nxt, rays, ctr = load_checkpoint(p)
            assert (nxt, rays) == (5, 77)
            assert ctr == ({} if version == 2 else {"rays_traced": 9})

    def test_chaos_ckpt_faults(self, tmp_path):
        """torn/crash/bitflip injection through save_checkpoint leaves
        exactly the on-disk shapes load_checkpoint must survive."""
        from tpu_pbrt.parallel.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        CHAOS.install("ckpt:crash@write=2")
        save_checkpoint(p, self._state(1.0), 1, 10)
        save_checkpoint(p, self._state(2.0), 2, 20)  # crashes pre-rename
        _, nxt, _, _ = load_checkpoint(p)
        assert nxt == 1, "crash between tmp write and rename lost the old file"

        CHAOS.install("ckpt:torn@write=2")
        save_checkpoint(p, self._state(3.0), 3, 30)
        save_checkpoint(p, self._state(4.0), 4, 40)  # torn current
        _, nxt, _, _ = load_checkpoint(p)
        assert nxt == 3, "torn current did not fall back to .prev"

        CHAOS.install("ckpt:bitflip@write=2")
        save_checkpoint(p, self._state(5.0), 5, 50)
        save_checkpoint(p, self._state(6.0), 6, 60)  # flipped current
        _, nxt, _, _ = load_checkpoint(p)
        assert nxt == 5, "bit-flipped current did not fall back to .prev"


# ---------------------------------------------------------------------------
# recovery bit-identity (render-level)
# ---------------------------------------------------------------------------


class TestRecoveryBitIdentity:
    def test_nan_scrub_counts_and_stays_finite(self):
        """Acceptance: an injected NaN wave leaves the final image fully
        finite with nonfinite_deposits > 0 in telemetry."""
        ref = _render()
        assert ref.stats["telemetry"]["counters"]["nonfinite_deposits"] == 0
        CHAOS.install("nan:wave@1&chunk=1")
        r = _render()
        assert CHAOS.fired_total() == 1
        img = np.asarray(r.image)
        assert np.isfinite(img).all()
        assert r.stats["telemetry"]["counters"]["nonfinite_deposits"] > 0

    def test_nan_retry_mode_recovers_bit_identical(self, tmp_path, monkeypatch):
        ref = _render()
        monkeypatch.setenv("TPU_PBRT_NONFINITE", "retry")
        CHAOS.install("nan:wave@1&chunk=1")
        r = _render(
            checkpoint_path=str(tmp_path / "f.ckpt"), checkpoint_every=1
        )
        assert r.stats["recovery"]["nonfinite_retries"] == 1
        np.testing.assert_array_equal(
            np.asarray(r.image), np.asarray(ref.image)
        )
        assert r.stats["telemetry"]["counters"]["nonfinite_deposits"] == 0

    def test_nan_raise_mode_aborts(self, monkeypatch):
        from tpu_pbrt.integrators.common import NonFiniteRadianceError

        monkeypatch.setenv("TPU_PBRT_NONFINITE", "raise")
        CHAOS.install("nan:wave@1&chunk=1")
        with pytest.raises(NonFiniteRadianceError):
            _render()

    def test_nan_strict_modes_require_telemetry(self, monkeypatch):
        """raise/retry read the scrub count off the telemetry counters;
        with them killed the modes must refuse loudly up front, not
        silently degrade to scrub."""
        monkeypatch.setenv("TPU_PBRT_TELEMETRY", "0")
        monkeypatch.setenv("TPU_PBRT_NONFINITE", "raise")
        with pytest.raises(ValueError, match="TPU_PBRT_NONFINITE"):
            _render()

    def test_rollback_does_not_double_count_retry_extras(self, tmp_path):
        """A clean redispatch BEFORE a checkpointed rollback: the
        reloaded snapshot already bakes in that redispatch, and
        ctr_snapshot must add only the unbaked delta — not re-add the
        whole process total on every rollback."""
        CHAOS.install("dispatch:fail@chunk=0,dispatch:poison@chunk=2")
        r = _render(
            checkpoint_path=str(tmp_path / "f.ckpt"), checkpoint_every=1
        )
        assert r.stats["recovery"]["redispatches"] == 2
        assert r.stats["telemetry"]["counters"]["chunks_redispatched"] == 2

    def test_exhaustion_writes_emergency_checkpoint_then_resume(
        self, tmp_path, monkeypatch
    ):
        """Retry-budget exhaustion raises AFTER persisting completed
        work; a later resume finishes bit-identically."""
        from tpu_pbrt.parallel.checkpoint import load_checkpoint

        ref = _render()
        ck = str(tmp_path / "f.ckpt")
        monkeypatch.setenv("TPU_PBRT_RETRY_MAX", "2")
        CHAOS.install("dispatch:fail@chunk=2&times=99")
        with pytest.raises(RuntimeError, match="chunk 2 failed"):
            _render(checkpoint_path=ck, checkpoint_every=1)
        CHAOS.clear()
        _, cursor, _, _ = load_checkpoint(ck)
        assert cursor == 2, "emergency checkpoint lost completed chunks"
        monkeypatch.delenv("TPU_PBRT_RETRY_MAX")
        r = _render(checkpoint_path=ck, checkpoint_every=1)
        np.testing.assert_array_equal(
            np.asarray(r.image), np.asarray(ref.image)
        )

    def test_matrix_scenario_entry_point(self, tmp_path):
        """The `python -m tpu_pbrt.chaos` machinery itself (one cheap
        scenario end-to-end through its helpers); the full matrix runs
        in tools/ci.sh."""
        from tpu_pbrt.chaos import __main__ as matrix

        ok, detail = matrix.SCENARIOS["clean-redispatch"](str(tmp_path))
        assert ok, detail


# ---------------------------------------------------------------------------
# bench probe (satellite: backoff + chaos hang)
# ---------------------------------------------------------------------------


class TestBenchProbe:
    def test_probe_recovers_from_simulated_hang(self, tmp_path, monkeypatch):
        """probe:hang@attempt=1 makes attempt 1 time out like the
        BENCH_r04/r05 runtime hang; the capped-backoff retry then
        succeeds — with per-attempt accounting in the returned tuple."""
        import bench

        import time

        monkeypatch.setenv("TPU_PBRT_FAULTS", "probe:hang@attempt=1")
        monkeypatch.setattr(bench, "_FLIGHT_PATH", str(tmp_path / "f.jsonl"))
        # rebase the budget clock: bench.T_START is import-time and the
        # probe's budget guard would otherwise see a half-spent budget
        # deep into a long suite run
        monkeypatch.setattr(bench, "T_START", time.time())
        ok, detail, retries, wait_s = bench.probe_backend(
            timeout_s=3.0, max_attempts=2, backoff_base_s=0.05,
        )
        assert ok and retries == 1
        assert wait_s >= 3.0  # the hung attempt burned its full timeout
        import json

        lines = [
            json.loads(ln)
            for ln in open(tmp_path / "f.jsonl").read().splitlines()
        ]
        phases = [ln["phase"] for ln in lines]
        assert "probe_backoff" in phases
        assert any(ln.get("chaos_hang") for ln in lines)
        assert any(ln.get("ok") for ln in lines)
