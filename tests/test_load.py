"""tpu-load harness tests (ISSUE 19): schedule determinism, burst
shedding, p99 gates, capture-replay, health-watchdog gating."""

import os

import pytest

from tpu_pbrt.load.gates import (
    evaluate_gates,
    gate_determinism,
    gate_p99_wait,
    snapshot_wait_p99,
)
from tpu_pbrt.load.replay import replay, workload_from_flight
from tpu_pbrt.load.workload import SCENARIOS, generate


# --------------------------------------------------------------------------
# Schedule determinism
# --------------------------------------------------------------------------


def test_same_seed_schedule_byte_identity():
    spec = SCENARIOS["steady"].spec
    a = generate(spec, 123)
    b = generate(spec, 123)
    assert a.schedule_text() == b.schedule_text()
    assert a.requests == b.requests


def test_different_seed_diverges():
    spec = SCENARIOS["steady"].spec
    assert (
        generate(spec, 1).schedule_text()
        != generate(spec, 2).schedule_text()
    )


def test_same_seed_decision_log_byte_identity():
    wl = generate(SCENARIOS["steady"].spec, 5)
    a = replay(wl)
    b = replay(wl)
    g = gate_determinism(a, b)
    assert g.ok, g.detail
    assert a.log_text() == b.log_text()
    # the registry-derived gate inputs must agree too, not just the log
    assert snapshot_wait_p99(a.snapshot, 0) == snapshot_wait_p99(
        b.snapshot, 0
    )


# --------------------------------------------------------------------------
# Burst shedding
# --------------------------------------------------------------------------


def test_burst_scenario_sheds_deterministically():
    scn = SCENARIOS["burst"]
    wl = generate(scn.spec, 7)
    a = replay(wl)
    b = replay(wl)
    assert a.sheds > 0, "burst scenario must engage SLO shedding"
    assert a.sheds == b.sheds
    # the SAME submits are shed: the shed lines match byte for byte
    sheds_a = [ln for ln in a.log if "-> shed:" in ln]
    sheds_b = [ln for ln in b.log if "-> shed:" in ln]
    assert sheds_a == sheds_b and len(sheds_a) == a.sheds
    # shedding protected the admitted work: everything admitted finished
    assert a.completed == a.submitted
    assert not a.pin_leaks


# --------------------------------------------------------------------------
# p99 gate, positive and negative
# --------------------------------------------------------------------------


def test_p99_gate_positive_and_negative():
    wl = generate(SCENARIOS["steady"].spec, 7)
    res = replay(wl)
    p99 = snapshot_wait_p99(res.snapshot, 0)
    assert p99 is not None and p99 > 0
    assert gate_p99_wait(res, 0, target_s=10.0).ok
    # the same run must FAIL a target tighter than its observed p99
    neg = gate_p99_wait(res, 0, target_s=p99 / 2)
    assert not neg.ok
    # a class that never dispatched has no samples: the gate refuses to
    # pass on absence of evidence
    missing = gate_p99_wait(res, 99, target_s=10.0)
    assert not missing.ok and missing.value is None


# --------------------------------------------------------------------------
# Capture-replay
# --------------------------------------------------------------------------


def test_capture_replay_round_trip(tmp_path):
    flight = str(tmp_path / "flight.jsonl")
    wl = generate(SCENARIOS["editstorm"].spec, 11)
    first = replay(wl, flight_path=flight)
    rebuilt = workload_from_flight(flight)
    assert rebuilt.spec == wl.spec
    assert rebuilt.requests == wl.requests
    assert rebuilt.schedule_text() == wl.schedule_text()
    second = replay(rebuilt)
    assert second.log == first.log


def test_capture_replay_serve_fallback(tmp_path):
    """A flight log without harness lines (a real daemon's) still
    reconstructs arrivals from the per-job serve_* heartbeats."""
    flight = str(tmp_path / "flight.jsonl")
    wl = generate(SCENARIOS["steady"].spec, 3)
    first = replay(wl, flight_path=flight)
    os.remove(flight)  # drop the harness header + load_submit lines
    rebuilt = workload_from_flight(flight)
    assert len(rebuilt.requests) == first.submitted
    assert {r.scene for r in rebuilt.requests} == {
        r.scene for r in wl.requests
    }
    # chunk counts ride the serve_done heartbeat's chunks field
    orig = {r.scene: r.chunks for r in wl.requests}
    for r in rebuilt.requests:
        assert r.chunks == orig[r.scene]


def test_capture_replay_empty_log_raises(tmp_path):
    with pytest.raises(ValueError, match="nothing to reconstruct"):
        workload_from_flight(str(tmp_path / "nope.jsonl"))


# --------------------------------------------------------------------------
# Health gating
# --------------------------------------------------------------------------


def test_clean_scenarios_zero_health_false_positives():
    for name in ("steady", "burst", "heavy", "editstorm"):
        res = replay(generate(SCENARIOS[name].spec, 7))
        assert res.health_flags == [], (
            f"{name}: watchdog fired {res.health_flags} on clean traffic"
        )


def test_storm_scenarios_must_flag():
    res = replay(generate(SCENARIOS["retrystorm"].spec, 7))
    assert "backoff_storm" in res.health_flags
    assert res.failed == 0 and not res.unfinished  # retry_max recovers
    res = replay(generate(SCENARIOS["shedstorm"].spec, 7))
    assert "slo_burn" in res.health_flags


# --------------------------------------------------------------------------
# Scenario gates end to end
# --------------------------------------------------------------------------


def test_all_registered_scenarios_pass_their_gates():
    for name, scn in SCENARIOS.items():
        res = replay(generate(scn.spec, 7))
        gates = evaluate_gates(res, scn.gates)
        bad = [g for g in gates if not g.ok]
        assert not bad, f"{name}: {[(g.name, g.detail) for g in bad]}"


def test_residency_behavior_editstorm():
    """Edits recompile (new keys), resubmits hit warm — the residency
    counters distinguish them."""
    wl = generate(SCENARIOS["editstorm"].spec, 7)
    res = replay(wl)
    distinct_keys = len({r.scene for r in wl.requests})
    assert res.compiles == distinct_keys
    assert res.residency_hits == len(wl.requests) - distinct_keys
    assert res.residency_hits > 0
