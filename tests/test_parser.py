"""Stage-0 front-end tests: lexer, ParamSet, API state machine, PLY.

Modeled on pbrt-v3's src/tests/parser.cpp tokenizer tests plus API-level
checks of the directive state machine (SURVEY.md §4).
"""

import os

import numpy as np
import pytest

from tpu_pbrt.scene.lexer import Tokenizer
from tpu_pbrt.scene.paramset import ParamSet
from tpu_pbrt.scene.api import pbrt_init, parse_string, Options
from tpu_pbrt.scene import plyreader
from tpu_pbrt.utils.error import PbrtError


def toks(s):
    return [(t.kind, t.value) for t in Tokenizer(s)]


class TestLexer:
    def test_basic(self):
        assert toks('Shape "sphere" "float radius" [2.5]') == [
            ("ident", "Shape"),
            ("string", "sphere"),
            ("string", "float radius"),
            ("lbrack", "["),
            ("number", 2.5),
            ("rbrack", "]"),
        ]

    def test_comments_and_negatives(self):
        out = toks("# a comment\nTranslate -1 2e3 .5 # trailing\nRotate 90 0 0 1")
        assert out[0] == ("ident", "Translate")
        assert out[1:4] == [("number", -1.0), ("number", 2000.0), ("number", 0.5)]
        assert out[4] == ("ident", "Rotate")

    def test_string_escapes(self):
        assert toks(r'"a\"b" "c\nd"') == [("string", 'a"b'), ("string", "c\nd")]

    def test_line_tracking(self):
        t = Tokenizer("A\nB\n  C")
        lines = [tok.line for tok in t]
        assert lines == [1, 2, 3]


class TestParamSet:
    def test_typed_lookups(self):
        ps = ParamSet()
        ps.add("float radius", [2.5])
        ps.add("integer nsamples", [16])
        ps.add("bool flag", ["true"])
        ps.add("string name", ["hello"])
        ps.add("point3 P", [0, 0, 0, 1, 0, 0, 0, 1, 0])
        ps.add("rgb Kd", [0.5, 0.25, 0.125])
        assert ps.find_one_float("radius", 1.0) == 2.5
        assert ps.find_one_float("missing", 7.0) == 7.0
        assert ps.find_one_int("nsamples", 4) == 16
        assert ps.find_one_bool("flag", False) is True
        assert ps.find_one_string("name", "") == "hello"
        assert ps.find_point3("P").shape == (3, 3)
        np.testing.assert_allclose(ps.find_one_spectrum("Kd", 0.0), [0.5, 0.25, 0.125])

    def test_blackbody_and_xyz(self):
        ps = ParamSet()
        ps.add("blackbody L", [6500, 1.0])
        rgb = ps.find_one_spectrum("L", 0.0)
        assert rgb.shape == (3,)
        assert np.all(rgb > 0)
        # ~6500K is roughly white: channels within ~25% of each other
        assert rgb.max() / rgb.min() < 1.4

    def test_spectrum_pairs(self):
        ps = ParamSet()
        # flat SPD == equal-energy white; y integral normalization -> ~[1,1,1]
        ps.add("spectrum L", [400, 1.0, 500, 1.0, 600, 1.0, 700, 1.0])
        rgb = ps.find_one_spectrum("L", 0.0)
        assert abs(rgb.sum() / 3 - 1.0) < 0.2


SIMPLE_SCENE = """
LookAt 0 0 -5  0 0 0  0 1 0
Camera "perspective" "float fov" [45]
Film "image" "integer xresolution" [64] "integer yresolution" [48]
Sampler "halton" "integer pixelsamples" [8]
Integrator "path" "integer maxdepth" [3]
WorldBegin
  LightSource "point" "point3 from" [0 5 0] "rgb I" [10 10 10]
  AttributeBegin
    Translate 0 0 2
    Material "matte" "rgb Kd" [0.8 0.2 0.2]
    Shape "sphere" "float radius" [1]
  AttributeEnd
  AttributeBegin
    AreaLightSource "diffuse" "rgb L" [5 5 5]
    Shape "trianglemesh"
      "integer indices" [0 1 2]
      "point3 P" [-1 4 0  1 4 0  0 4 1]
  AttributeEnd
WorldEnd
"""


class TestAPI:
    def test_simple_scene_state(self):
        api = parse_string(SIMPLE_SCENE)
        ro = api.last_render_options
        assert ro.camera_name == "perspective"
        assert ro.camera_params.find_one_float("fov", 90) == 45
        assert ro.film_params.find_one_int("xresolution", 0) == 64
        assert ro.integrator_name == "path"
        assert len(ro.shapes) == 2
        assert len(ro.lights) == 1
        sphere = ro.shapes[0]
        assert sphere.type == "sphere"
        assert sphere.material.type == "matte"
        np.testing.assert_allclose(sphere.material.params["Kd"][1], [0.8, 0.2, 0.2])
        # CTM: camera LookAt must not leak into world block
        np.testing.assert_allclose(sphere.object_to_world[0].apply_point([0, 0, 0]), [0, 0, 2])
        tri = ro.shapes[1]
        assert tri.area_light is not None
        np.testing.assert_allclose(tri.area_light.find_one_spectrum("L", 0), [5, 5, 5])

    def test_attribute_stack_restores(self):
        api = parse_string(
            """
            WorldBegin
            Material "mirror"
            AttributeBegin
              Material "glass"
              Translate 1 0 0
            AttributeEnd
            Shape "sphere"
            WorldEnd
            """
        )
        s = api.last_render_options.shapes[0]
        assert s.material.type == "mirror"
        assert s.object_to_world[0].is_identity()

    def test_named_materials(self):
        api = parse_string(
            """
            WorldBegin
            MakeNamedMaterial "red" "string type" "matte" "rgb Kd" [1 0 0]
            Material "glass"
            NamedMaterial "red"
            Shape "sphere"
            WorldEnd
            """
        )
        s = api.last_render_options.shapes[0]
        assert s.material.type == "matte"
        np.testing.assert_allclose(s.material.params["Kd"][1], [1, 0, 0])

    def test_object_instancing(self):
        api = parse_string(
            """
            WorldBegin
            ObjectBegin "tree"
              Shape "sphere" "float radius" [0.5]
            ObjectEnd
            Translate 5 0 0
            ObjectInstance "tree"
            Translate 5 0 0
            ObjectInstance "tree"
            WorldEnd
            """
        )
        ro = api.last_render_options
        assert len(ro.instances["tree"]) == 1
        assert len(ro.instance_uses) == 2
        np.testing.assert_allclose(ro.instance_uses[1].instance_to_world[0].apply_point([0, 0, 0]), [10, 0, 0])

    def test_texture_registration(self):
        api = parse_string(
            """
            WorldBegin
            Texture "checks" "spectrum" "checkerboard"
               "float uscale" [8] "float vscale" [8]
               "rgb tex1" [.1 .1 .1] "rgb tex2" [.8 .8 .8]
            Material "matte" "texture Kd" "checks"
            Shape "sphere"
            WorldEnd
            """
        )
        s = api.last_render_options.shapes[0]
        kd = s.material.params["Kd"]
        assert kd[0] == "checkerboard"
        assert kd[1]["mapping"]["su"] == 8

    def test_world_state_enforced(self):
        api = pbrt_init()
        with pytest.raises(PbrtError):
            parse_string('Shape "sphere"', api)

    def test_unmatched_attribute_end(self):
        with pytest.raises(PbrtError):
            parse_string("WorldBegin\nAttributeEnd\nWorldEnd")

    def test_reverse_orientation(self):
        api = parse_string(
            """
            WorldBegin
            ReverseOrientation
            Shape "sphere"
            WorldEnd
            """
        )
        assert api.last_render_options.shapes[0].reverse_orientation is True

    def test_transform_directive_column_major(self):
        api = parse_string(
            """
            WorldBegin
            Transform [1 0 0 0  0 1 0 0  0 0 1 0  3 4 5 1]
            Shape "sphere"
            WorldEnd
            """
        )
        s = api.last_render_options.shapes[0]
        np.testing.assert_allclose(s.object_to_world[0].apply_point([0, 0, 0]), [3, 4, 5])

    def test_include(self, tmp_path):
        inc = tmp_path / "inner.pbrt"
        inc.write_text('Material "matte" "rgb Kd" [0 1 0]\nShape "sphere"\n')
        main = tmp_path / "main.pbrt"
        main.write_text(f'WorldBegin\nInclude "inner.pbrt"\nWorldEnd\n')
        from tpu_pbrt.scene.api import parse_file

        api = parse_file(str(main))
        assert len(api.last_render_options.shapes) == 1
        np.testing.assert_allclose(api.last_render_options.shapes[0].material.params["Kd"][1], [0, 1, 0])

    def test_medium_interface(self):
        api = parse_string(
            """
            MakeNamedMedium "fog" "string type" "homogeneous" "rgb sigma_s" [1 1 1]
            WorldBegin
            MediumInterface "fog" ""
            Shape "sphere"
            WorldEnd
            """
        )
        s = api.last_render_options.shapes[0]
        assert s.inside_medium == "fog"
        assert s.outside_medium == ""
        assert "fog" in api.last_render_options.named_media


class TestPLY:
    def test_roundtrip_binary(self, tmp_path):
        v = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [1, 1, 0]], dtype=np.float64)
        f = np.array([[0, 1, 2], [1, 3, 2]], dtype=np.int64)
        n = np.tile([0.0, 0.0, 1.0], (4, 1))
        p = str(tmp_path / "quad.ply")
        plyreader.write_ply(p, v, f, n)
        m = plyreader.read_ply(p)
        np.testing.assert_allclose(m["vertices"], v)
        np.testing.assert_array_equal(m["indices"], f)
        np.testing.assert_allclose(m["normals"], n)

    def test_ascii_with_quad(self, tmp_path):
        txt = """ply
format ascii 1.0
element vertex 4
property float x
property float y
property float z
element face 1
property list uchar int vertex_indices
end_header
0 0 0
1 0 0
1 1 0
0 1 0
4 0 1 2 3
"""
        p = tmp_path / "quad.ply"
        p.write_text(txt)
        m = plyreader.read_ply(str(p))
        assert m["vertices"].shape == (4, 3)
        # quad fan-triangulated into 2 tris
        np.testing.assert_array_equal(m["indices"], [[0, 1, 2], [0, 2, 3]])
