"""jaxcost (ISSUE 3 tentpole): the static roofline interpreter, its
anti-pattern detectors (adversarial fixtures), and the budget gate —
including the full update-budgets workflow over a temp file and the
repo-level mirror of the CLI gate against the COMMITTED budgets.json."""

import jax
import jax.numpy as jnp

from tpu_pbrt.analysis import cost


def _findings(fn, args, wave=64, entry="fixture"):
    jx = jax.make_jaxpr(fn)(*args)
    roll, findings = cost.analyze_jaxpr(jx, entry, wave)
    return roll, [f for f in findings if f.waived is None]


# ---------------------------------------------------------------------------
# detector sanity: adversarial fixtures (ISSUE 3 satellite)
# ---------------------------------------------------------------------------


def test_injected_f32_f64_f32_round_trip_flagged():
    """The satellite's named fixture: an f32 -> f64 -> f32 round trip in
    a wave-sized array must produce a JC-CHURN finding."""
    from jax.experimental import enable_x64

    def f(x):
        return x.astype(jnp.float64).astype(jnp.float32) * 2.0

    with enable_x64():
        jx = jax.make_jaxpr(f)(jnp.ones((128,), jnp.float32))
    _, findings = cost.analyze_jaxpr(jx, "fixture", 64)
    churn = [f for f in findings if f.rule == "JC-CHURN"]
    assert churn, "f32->f64->f32 round trip not flagged"
    assert "float32->float64->float32" in churn[0].detail


def test_round_trip_through_arithmetic_flagged():
    """The film.add_samples shape: convert, arithmetic against a
    literal, convert back."""

    def f(x):
        i = jnp.ceil(x).astype(jnp.int32)
        return (i + 3).astype(jnp.float32)

    _, findings = _findings(f, (jnp.ones((256,), jnp.float32),))
    assert any(f.rule == "JC-CHURN" for f in findings)


def test_small_round_trip_not_flagged():
    def f(x):
        return x.astype(jnp.int32).astype(jnp.float32)

    _, findings = _findings(
        f, (jnp.ones((cost.CHURN_MIN_ELEMS - 1,), jnp.float32),)
    )
    assert not any(f.rule == "JC-CHURN" for f in findings)


def test_oversized_broadcast_flagged():
    """The satellite's second named fixture: a non-scalar broadcast
    materializing BCAST_MIN_RATIO x its input above BCAST_MIN_BYTES."""

    def f(x):
        return jnp.broadcast_to(x[:, None], (512, 4096)) * 1.5

    _, findings = _findings(f, (jnp.ones((512,), jnp.float32),))
    assert any(f.rule == "JC-BCAST" for f in findings)


def test_scalar_broadcast_not_flagged():
    """Scalar broadcasts fuse for free — never an anti-pattern."""

    def f(x):
        return x + jnp.float32(2.0)

    _, findings = _findings(f, (jnp.ones((512, 4096), jnp.float32),))
    assert not any(f.rule == "JC-BCAST" for f in findings)


def test_large_transpose_flagged_and_small_ignored():
    def big(x):
        return x.T

    _, findings = _findings(big, (jnp.ones((4096, 64), jnp.float32),))
    assert any(f.rule == "JC-RELAYOUT" for f in findings)

    _, findings = _findings(big, (jnp.ones((16, 8), jnp.float32),))
    assert not any(f.rule == "JC-RELAYOUT" for f in findings)


def test_narrow_gather_flagged_unless_sorted():
    """Random narrow gathers past wave width are flagged; the SAME
    gather at sort-derived indices is the sanctioned pattern (the
    stream tracer's whole design) and must pass."""
    tab = jnp.ones((65536,), jnp.float32)
    idx = jnp.zeros((32768,), jnp.int32)

    def unsorted(t, i):
        return t[jnp.clip(i, 0, 65535)]

    _, findings = _findings(unsorted, (tab, idx))
    assert any(f.rule == "JC-GATHER" for f in findings)

    def sorted_(t, i):
        (i_s,) = jax.lax.sort([i], num_keys=1)
        return t[jnp.clip(i_s, 0, 65535)]

    _, findings = _findings(sorted_, (tab, idx))
    assert not any(f.rule == "JC-GATHER" for f in findings)


def test_padding_waste_flagged():
    def f(x):
        return x * 2.0  # (1M, 3): minor dim 3 pads to 128 on TPU tiles

    _, findings = _findings(f, (jnp.ones((1 << 20, 3), jnp.float32),))
    assert any(f.rule == "JC-PAD" for f in findings)


# ---------------------------------------------------------------------------
# rollup model sanity
# ---------------------------------------------------------------------------


def test_dot_flops_model():
    def f(a, b):
        return a @ b

    roll, _ = _findings(
        f,
        (jnp.ones((128, 64), jnp.float32), jnp.ones((64, 32), jnp.float32)),
    )
    assert roll.flops == 2 * 64 * 128 * 32


def test_scan_multiplies_by_trip_count():
    def body(c, _):
        return c + 1.0, None

    def once(x):
        return x + 1.0

    def scanned(x):
        c, _ = jax.lax.scan(body, x, None, length=10)
        return c

    r1, _ = _findings(once, (jnp.ones((256,), jnp.float32),))
    r10, _ = _findings(scanned, (jnp.ones((256,), jnp.float32),))
    assert r10.flops >= 10 * r1.flops


def test_while_body_charged_once():
    """A while body is one wave: the rollup must not multiply it."""

    def loop(x):
        return jax.lax.while_loop(
            lambda c: c[0] < 100, lambda c: (c[0] + 1, c[1] * 2.0), (0, x)
        )[1]

    def once(x):
        return x * 2.0

    r_loop, _ = _findings(loop, (jnp.ones((1024,), jnp.float32),))
    r_once, _ = _findings(once, (jnp.ones((1024,), jnp.float32),))
    assert r_loop.flops < 10 * r_once.flops
    assert r_loop.n_dynamic_loops == 1


def test_fingerprint_stable_and_change_sensitive():
    x = jnp.ones((64,), jnp.float32)
    r1, _ = _findings(lambda v: v * 2.0, (x,))
    r2, _ = _findings(lambda v: v * 2.0, (x,))
    r3, _ = _findings(lambda v: v * 2.0 + 1.0, (x,))
    assert r1.fingerprint == r2.fingerprint
    assert r1.fingerprint != r3.fingerprint


# ---------------------------------------------------------------------------
# budget gate: synthetic regression fails, --update-budgets clears it
# ---------------------------------------------------------------------------


def _toy_entries(scale: int):
    def build():
        x = jnp.ones((1024 * scale,), jnp.float32)
        return jax.make_jaxpr(lambda v: jnp.sum(v * 2.0 + 1.0))(x), 64

    return {"toy": build}


def test_budget_gate_regression_and_update(tmp_path):
    path = tmp_path / "budgets.json"
    # seed the budget from the baseline program
    errors, _, _, _ = cost.run_cost(
        update=True, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors == []
    # clean re-check against the committed file
    errors, warnings, _, _ = cost.run_cost(
        update=False, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors == [], errors
    # synthetic regression: the program got 4x bigger -> gate fails with
    # an entry-point diagnostic
    errors, _, _, _ = cost.run_cost(
        update=False, budgets_path=path, entries=_toy_entries(4)
    )
    assert errors and "toy" in errors[0] and "regressed" in errors[0]
    # --update-budgets clears it
    errors, _, _, _ = cost.run_cost(
        update=True, budgets_path=path, entries=_toy_entries(4)
    )
    assert errors == []
    errors, _, _, _ = cost.run_cost(
        update=False, budgets_path=path, entries=_toy_entries(4)
    )
    assert errors == []


def test_update_preserves_customized_tolerance(tmp_path):
    """--update-budgets refreshes the ROLLUPS only: a tolerance someone
    tightened in the committed file must survive the rewrite."""
    import json

    path = tmp_path / "budgets.json"
    cost.run_cost(update=True, budgets_path=path, entries=_toy_entries(1))
    data = json.loads(path.read_text())
    data["tolerance"] = 0.05
    path.write_text(json.dumps(data))
    cost.run_cost(update=True, budgets_path=path, entries=_toy_entries(2))
    assert json.loads(path.read_text())["tolerance"] == 0.05


def test_budget_gate_missing_entry_is_error(tmp_path):
    path = tmp_path / "budgets.json"
    errors, _, _, _ = cost.run_cost(
        update=False, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors and "no committed budget" in errors[0]


def test_budget_improvement_is_ratchet_warning(tmp_path):
    path = tmp_path / "budgets.json"
    cost.run_cost(update=True, budgets_path=path, entries=_toy_entries(4))
    errors, warnings, _, _ = cost.run_cost(
        update=False, budgets_path=path, entries=_toy_entries(1)
    )
    assert errors == []
    assert any("improved" in w for w in warnings)


def test_fingerprint_drift_is_warning_not_error(tmp_path):
    path = tmp_path / "budgets.json"
    cost.run_cost(update=True, budgets_path=path, entries=_toy_entries(1))

    def build():
        # same cost scale, different op mix -> fingerprint changes while
        # the metrics stay inside tolerance
        x = jnp.ones((1024,), jnp.float32)
        return jax.make_jaxpr(lambda v: jnp.sum((v - 1.0) * 2.0))(x), 64

    errors, warnings, _, _ = cost.run_cost(
        update=False, budgets_path=path, entries={"toy": build}
    )
    assert errors == []
    assert any("fingerprint changed" in w for w in warnings)


# ---------------------------------------------------------------------------
# the repo gate (tier-1 mirror of the CLI acceptance criterion)
# ---------------------------------------------------------------------------


def test_repo_entry_points_clean_against_committed_budgets():
    """ISSUE 3 acceptance: the shipped tree's entry points pass the
    committed budgets.json with zero cost errors and zero un-waived
    findings. A hot-path change that moves bytes/FLOPs past tolerance
    fails here (and in CI) even with no accelerator attached."""
    errors, warnings, rollups, findings = cost.run_cost(update=False)
    assert errors == [], "\n".join(errors)
    active = [f for f in findings if f.waived is None]
    assert active == [], "\n".join(str(f) for f in active)
    # every audited entry point must carry a budget row
    assert set(rollups) == set(cost.default_entry_points())


def test_bench_wave_rollup_shape():
    """The bench.py hook: a production-shaped pool wave traces without
    hardware and reports non-trivial static cost."""
    roll = cost.bench_wave_rollup(res=64, spp=4, chunk=1 << 12)
    assert roll.flops > 0 and roll.hbm_bytes > 0
    assert roll.n_dynamic_loops >= 1  # the drain loop is in the trace
