// Native binned-SAH BVH builder.
//
// Capability match for pbrt-v3 src/accelerators/bvh.cpp
// BVHAccel::recursiveBuild (12-bucket binned SAH, pbrt's leaf/split cost
// model, depth-first LinearBVHNode layout with the left child adjacent and
// the far child patched by offset) — the native-runtime counterpart of
// tpu_pbrt/accel/build.py::_build_recursive, which it matches node for
// node (same f64 internal math, same bucket assignment, same cost
// formula, same stable tie-breaking) so the Python fallback and this
// builder are interchangeable.
//
// Why native: scene compilation is host runtime, exactly the layer the
// reference implements in C++. The Python SAH loop visits every node in
// interpreter code (~25 s for a 128k-triangle scene); this builder is a
// tight memcpy-free loop over caller-allocated output arrays, ~50-100x
// faster, which is what makes crown-class (3.5M tris) SAH builds
// practical instead of falling back to the lower-quality Morton build.
//
// Build: g++ -O3 -shared -fPIC -o libbvh.so bvh_builder.cpp
// ABI: plain C, caller allocates (see build_sah_bvh).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {

constexpr int kBuckets = 12;
constexpr double kTraversalCost = 0.125;  // pbrt: 1/8 node vs intersect

struct V3 {
  double x, y, z;
};

inline V3 vmin(const V3 &a, const V3 &b) {
  return {std::min(a.x, b.x), std::min(a.y, b.y), std::min(a.z, b.z)};
}
inline V3 vmax(const V3 &a, const V3 &b) {
  return {std::max(a.x, b.x), std::max(a.y, b.y), std::max(a.z, b.z)};
}
inline double area(const V3 &mn, const V3 &mx) {
  double dx = std::max(mx.x - mn.x, 0.0);
  double dy = std::max(mx.y - mn.y, 0.0);
  double dz = std::max(mx.z - mn.z, 0.0);
  return 2.0 * (dx * dy + dx * dz + dy * dz);
}
inline double axis_of(const V3 &v, int dim) {
  return dim == 0 ? v.x : (dim == 1 ? v.y : v.z);
}

struct Builder {
  const double *bmin, *bmax;  // (n, 3) f64
  int64_t n;
  int max_leaf;

  float *out_min, *out_max;           // (cap, 3)
  int32_t *out_prim_off, *out_nprims; // (cap,)
  int32_t *out_second, *out_axis;     // (cap,)
  int64_t *out_order;                 // (n,)

  std::vector<V3> cen;
  std::vector<int64_t> idx;   // working permutation
  std::vector<int64_t> scratch;
  int64_t slot = 0;
  int64_t n_order = 0;

  V3 get(const double *arr, int64_t i) const {
    return {arr[3 * i], arr[3 * i + 1], arr[3 * i + 2]};
  }

  void emit_bounds(int64_t s, const V3 &mn, const V3 &mx) {
    out_min[3 * s] = (float)mn.x;
    out_min[3 * s + 1] = (float)mn.y;
    out_min[3 * s + 2] = (float)mn.z;
    out_max[3 * s] = (float)mx.x;
    out_max[3 * s + 1] = (float)mx.y;
    out_max[3 * s + 2] = (float)mx.z;
  }

  void make_leaf(int64_t my_slot, int64_t lo, int64_t hi) {
    out_prim_off[my_slot] = (int32_t)n_order;
    out_nprims[my_slot] = (int32_t)(hi - lo);
    for (int64_t i = lo; i < hi; ++i) out_order[n_order++] = idx[i];
  }

  struct Task {
    int64_t lo, hi, patch_parent;  // patch_parent < 0: no far-child patch
  };
  std::vector<Task> tasks;

  // builds the whole tree iteratively (explicit stack — unbalanced SAH
  // splits on multi-million-primitive scenes would overflow the C stack);
  // pushing right-then-left reproduces the recursive DFS layout: the left
  // child lands at parent+1, the right child's slot patches out_second.
  void build_all(int64_t lo0, int64_t hi0) {
    tasks.push_back({lo0, hi0, -1});
    while (!tasks.empty()) {
      Task t = tasks.back();
      tasks.pop_back();
      if (t.patch_parent >= 0) out_second[t.patch_parent] = (int32_t)slot;
      build_node(t.lo, t.hi);
    }
  }

  // emits ONE node for [lo, hi) and pushes child tasks
  void build_node(int64_t lo, int64_t hi) {
    int64_t my_slot = slot++;
    V3 nb_min = get(bmin, idx[lo]);
    V3 nb_max = get(bmax, idx[lo]);
    for (int64_t i = lo + 1; i < hi; ++i) {
      nb_min = vmin(nb_min, get(bmin, idx[i]));
      nb_max = vmax(nb_max, get(bmax, idx[i]));
    }
    emit_bounds(my_slot, nb_min, nb_max);
    int64_t count = hi - lo;
    if (count == 1) {
      make_leaf(my_slot, lo, hi);
      return;
    }
    V3 cb_min = cen[idx[lo]], cb_max = cen[idx[lo]];
    for (int64_t i = lo + 1; i < hi; ++i) {
      cb_min = vmin(cb_min, cen[idx[i]]);
      cb_max = vmax(cb_max, cen[idx[i]]);
    }
    double ext[3] = {cb_max.x - cb_min.x, cb_max.y - cb_min.y,
                     cb_max.z - cb_min.z};
    int dim = 0;
    if (ext[1] > ext[dim]) dim = 1;
    if (ext[2] > ext[dim]) dim = 2;

    auto split_at = [&](int64_t mid) {
      out_axis[my_slot] = dim;
      out_nprims[my_slot] = 0;
      tasks.push_back({lo + mid, hi, my_slot});  // right (far), patched
      tasks.push_back({lo, lo + mid, -1});       // left: next slot
    };

    if (ext[dim] <= 0.0) {
      if (count <= max_leaf) {
        make_leaf(my_slot, lo, hi);
      } else {
        split_at(count / 2);  // degenerate cluster: forced equal split
      }
      return;
    }
    if (count <= 2) {
      // tiny node: equal-count by centroid (argpartition equivalent)
      std::sort(idx.begin() + lo, idx.begin() + hi,
                [&](int64_t a, int64_t b) {
                  return axis_of(cen[a], dim) < axis_of(cen[b], dim);
                });
      split_at(count / 2);
      return;
    }

    // 12-bucket binned SAH (bvh.cpp "Allocate BucketInfo...")
    int64_t counts[kBuckets] = {0};
    V3 bk_min[kBuckets], bk_max[kBuckets];
    for (int b = 0; b < kBuckets; ++b) {
      bk_min[b] = {std::numeric_limits<double>::infinity(),
                   std::numeric_limits<double>::infinity(),
                   std::numeric_limits<double>::infinity()};
      bk_max[b] = {-std::numeric_limits<double>::infinity(),
                   -std::numeric_limits<double>::infinity(),
                   -std::numeric_limits<double>::infinity()};
    }
    auto bucket_of = [&](int64_t prim) {
      double t = (axis_of(cen[prim], dim) - axis_of(cb_min, dim)) / ext[dim];
      int b = (int)(kBuckets * t);
      return std::min(b, kBuckets - 1);
    };
    for (int64_t i = lo; i < hi; ++i) {
      int b = bucket_of(idx[i]);
      counts[b]++;
      bk_min[b] = vmin(bk_min[b], get(bmin, idx[i]));
      bk_max[b] = vmax(bk_max[b], get(bmax, idx[i]));
    }
    // prefix/suffix sweeps
    double cost[kBuckets - 1];
    int64_t cnt_f[kBuckets], cnt_b[kBuckets];
    V3 mn_f[kBuckets], mx_f[kBuckets], mn_b[kBuckets], mx_b[kBuckets];
    cnt_f[0] = counts[0];
    mn_f[0] = bk_min[0];
    mx_f[0] = bk_max[0];
    for (int b = 1; b < kBuckets; ++b) {
      cnt_f[b] = cnt_f[b - 1] + counts[b];
      mn_f[b] = vmin(mn_f[b - 1], bk_min[b]);
      mx_f[b] = vmax(mx_f[b - 1], bk_max[b]);
    }
    cnt_b[kBuckets - 1] = counts[kBuckets - 1];
    mn_b[kBuckets - 1] = bk_min[kBuckets - 1];
    mx_b[kBuckets - 1] = bk_max[kBuckets - 1];
    for (int b = kBuckets - 2; b >= 0; --b) {
      cnt_b[b] = cnt_b[b + 1] + counts[b];
      mn_b[b] = vmin(mn_b[b + 1], bk_min[b]);
      mx_b[b] = vmax(mx_b[b + 1], bk_max[b]);
    }
    double total_area = std::max(area(nb_min, nb_max), 1e-30);
    int best = -1;
    bool any_valid = false;
    double best_cost = std::numeric_limits<double>::infinity();
    for (int b = 0; b < kBuckets - 1; ++b) {
      bool valid = cnt_f[b] > 0 && cnt_b[b + 1] > 0;
      if (!valid) {
        cost[b] = std::numeric_limits<double>::infinity();
        continue;
      }
      any_valid = true;
      cost[b] = kTraversalCost + (cnt_f[b] * area(mn_f[b], mx_f[b]) +
                                  cnt_b[b + 1] * area(mn_b[b + 1], mx_b[b + 1])) /
                                     total_area;
      if (cost[b] < best_cost) {
        best_cost = cost[b];
        best = b;
      }
    }
    double leaf_cost = (double)count;
    if (count > max_leaf || best_cost < leaf_cost) {
      if (!any_valid) {
        std::sort(idx.begin() + lo, idx.begin() + hi,
                  [&](int64_t a, int64_t b) {
                    return axis_of(cen[a], dim) < axis_of(cen[b], dim);
                  });
        split_at(count / 2);
        return;
      }
      // stable partition: bucket <= best first, original order preserved
      // (matches numpy argsort(~left, kind='stable'))
      int64_t mid = 0;
      scratch.clear();
      int64_t w = lo;
      for (int64_t i = lo; i < hi; ++i) {
        if (bucket_of(idx[i]) <= best) {
          idx[w++] = idx[i];
          mid++;
        } else {
          scratch.push_back(idx[i]);
        }
      }
      std::memcpy(idx.data() + w, scratch.data(),
                  scratch.size() * sizeof(int64_t));
      split_at(mid);
    } else {
      make_leaf(my_slot, lo, hi);
    }
  }
};

}  // namespace

extern "C" {

// Returns the node count; -1 on error. Caller allocates out arrays at
// capacity 2n+1 (nodes) / n (order). Inputs are (n,3) float64 AABBs.
int64_t build_sah_bvh(const double *bmin, const double *bmax, int64_t n,
                      int32_t max_leaf, float *out_min, float *out_max,
                      int32_t *out_prim_off, int32_t *out_nprims,
                      int32_t *out_second, int32_t *out_axis,
                      int64_t *out_order) {
  if (n <= 0 || max_leaf <= 0) return -1;
  Builder b;
  b.bmin = bmin;
  b.bmax = bmax;
  b.n = n;
  b.max_leaf = max_leaf;
  b.out_min = out_min;
  b.out_max = out_max;
  b.out_prim_off = out_prim_off;
  b.out_nprims = out_nprims;
  b.out_second = out_second;
  b.out_axis = out_axis;
  b.out_order = out_order;
  b.cen.resize(n);
  b.idx.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    b.cen[i] = {0.5 * (bmin[3 * i] + bmax[3 * i]),
                0.5 * (bmin[3 * i + 1] + bmax[3 * i + 1]),
                0.5 * (bmin[3 * i + 2] + bmax[3 * i + 2])};
    b.idx[i] = i;
  }
  b.scratch.reserve(n);
  b.build_all(0, n);
  return b.slot;
}
}
