#!/usr/bin/env python
"""Benchmark driver: renders the killeroo-simple-class workload and prints
one JSON line {"metric", "value", "unit", "vs_baseline", ...}.

The workload mirrors BASELINE.json's killeroo-simple config (PathIntegrator,
matte trimesh, area light) with a procedural ~128k-triangle mesh standing in
for the PLY (pbrt-v3-scenes is not available in this environment).

Metrics (the judged pair, BASELINE.json `metric`):
- Mray/s: rays actually traced / steady-state wall time, counted in-kernel.
  A warmup pass excludes XLA compilation from the timing, matching how the
  reference's numbers would exclude its BVH build.
- mse: per-pixel MSE of an accelerator render vs the cached CPU reference
  image (tools/make_reference.py; refimg/). Target <= 1e-4.

Env knobs: BENCH_SPP/BENCH_RES (throughput run), MSE_RES/MSE_SPP/REF_SPP
(accuracy run), BENCH_SKIP_MSE=1 to skip the accuracy half.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def compute_mse(mse_res: int, mse_spp: int, ref_spp: int):
    """Accelerator render vs cached CPU reference -> per-pixel MSE, or None
    if the reference cache is missing (generate with tools/make_reference.py)."""
    import numpy as np

    from tools.make_reference import reference_path

    path = reference_path(mse_res, ref_spp)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        ref = np.asarray(z["image"], np.float32)

    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(res=mse_res, spp=mse_spp)
    scene, integ = compile_api(api)
    img = np.asarray(integ.render(scene).image, np.float32)
    return float(np.mean((img - ref) ** 2))


def main():
    spp = int(os.environ.get("BENCH_SPP", "64"))
    res = int(os.environ.get("BENCH_RES", "512"))

    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(res=res, spp=spp)
    scene, integ = compile_api(api)

    # warmup run with identical shapes so the timed run hits the jit cache
    integ.render(scene)
    result = integ.render(scene)

    mse = None
    if not os.environ.get("BENCH_SKIP_MSE"):
        try:
            mse = compute_mse(
                int(os.environ.get("MSE_RES", "128")),
                int(os.environ.get("MSE_SPP", "256")),
                int(os.environ.get("REF_SPP", "256")),
            )
        except Exception as e:  # noqa: BLE001 — MSE failure must not eat the perf number
            print(f"mse computation failed: {e}", file=sys.stderr)

    north_star = 100.0  # Mray/s on v5e-8 (BASELINE.json north_star)
    line = {
        "metric": "killeroo_like_path_mray_per_sec",
        "value": round(result.mray_per_sec, 3),
        "unit": "Mray/s",
        "vs_baseline": round(result.mray_per_sec / north_star, 4),
    }
    if mse is not None:
        line["mse_vs_cpu_ref"] = mse
        line["mse_target"] = 1e-4
    print(json.dumps(line))


if __name__ == "__main__":
    main()
