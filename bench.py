#!/usr/bin/env python
"""Benchmark driver: renders the killeroo-simple-class workload and prints
one JSON line {"metric", "value", "unit", "vs_baseline", ...}.

The workload mirrors BASELINE.json's killeroo-simple config (PathIntegrator,
matte trimesh, area light) with a procedural ~128k-triangle mesh standing in
for the PLY (pbrt-v3-scenes is not available in this environment).

Metrics (the judged pair, BASELINE.json `metric`):
- Mray/s: rays actually traced / steady-state wall time, counted in-kernel.
  A warmup pass excludes XLA compilation from the timing, matching how the
  reference's numbers would exclude its BVH build.
- mse: per-pixel MSE of an accelerator render vs the cached CPU reference
  image (tools/make_reference.py; refimg/). Target <= 1e-4.

Un-killable by design (VERDICT r2 #2): every phase is wall-clock budgeted
(the render loop's max_seconds stops at a chunk boundary; Mray/s divides
rays actually traced by wall time, so a partial run still measures
steady-state throughput), MSE is attempted only if the remaining budget
predicts it will finish, any exception prints a parseable JSON line, and
SIGTERM reports the last completed measurement instead of dying silently.
A driver timeout can therefore never yield `parsed: null`.

Env knobs: BENCH_SPP/BENCH_RES (throughput run), BENCH_BUDGET_S (total
wall-clock budget, default 420), MSE_RES/MSE_SPP/REF_SPP (accuracy run),
BENCH_SKIP_MSE=1 to skip the accuracy half.

Telemetry (ISSUE 4): every phase heartbeats into the flight recorder
(TPU_PBRT_FLIGHT_PATH, default BENCH_flight.jsonl) so an outage capture
carries its phase timeline, probe retry/wait accounting and the last
counter snapshot; `--trace out.json` (or TPU_PBRT_TRACE_PATH) exports a
Chrome-trace/Perfetto span timeline; the measured JSON line gains a
`telemetry` block — device counters, per-device wave-count spread, and
the live-vs-static roofline ratio (obs/rooflive.py) next to the static
fields.
"""

import json
import os
import subprocess
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

T_START = time.time()
BUDGET = float(os.environ.get("BENCH_BUDGET_S", "520"))

# -- import-free flight heartbeats for the probe/outage phases -------------
# The probe exists because an in-process accelerator-runtime import can
# hang unboundedly; importing tpu_pbrt (whose package __init__ pulls jax)
# before the probe succeeds would reintroduce exactly that hang. These
# few lines mirror tpu_pbrt/obs/flight.py's JSONL format with ZERO
# tpu_pbrt/jax imports; once the probe passes, the real FlightRecorder
# takes over appending to the same file.
_FLIGHT_PATH = os.environ.get("TPU_PBRT_FLIGHT_PATH") or "BENCH_flight.jsonl"
_TELEMETRY_ON = os.environ.get("TPU_PBRT_TELEMETRY", "1").strip().lower() \
    not in ("0", "false", "no", "off")
_last_phase = None


def _flight_heartbeat(phase: str, **fields):
    global _last_phase
    _last_phase = phase
    if not _TELEMETRY_ON:
        return
    line = {"t": round(time.time(), 3),
            "elapsed_s": round(time.time() - T_START, 3), "phase": phase}
    line.update(fields)
    try:
        with open(_FLIGHT_PATH, "a") as f:
            f.write(json.dumps(line) + "\n")
    except OSError:
        pass


def _probe_hang_attempts() -> set:
    """Chaos seam for the probe, parsed IMPORT-FREE: `probe:hang@attempt=N`
    entries of TPU_PBRT_FAULTS name the probe attempts that must simulate
    the r4/r5-class runtime hang. This mirrors tpu_pbrt/chaos's grammar
    for the one site that runs before tpu_pbrt may be imported (the real
    registry lives behind the jax import this path must avoid)."""
    out = set()
    for entry in os.environ.get("TPU_PBRT_FAULTS", "").split(","):
        entry = entry.strip()
        if not entry.startswith("probe:hang"):
            continue
        attempt = 1
        _, _, tail = entry.partition("@")
        for part in tail.split("&"):
            part = part.strip()
            if not part:
                continue
            k, eq, v = part.partition("=")
            if not eq:
                k, v = "attempt", k  # bare value -> the site default key
            if k == "attempt":
                try:
                    attempt = int(v)
                except ValueError:
                    pass
        out.add(attempt)
    return out


#: cumulative backoff the probe slept (reported on the outage JSON line)
_PROBE_BACKOFF_S = 0.0


def probe_backend(
    timeout_s: float = 150.0, max_attempts: int = 0,
    backoff_base_s: float = 5.0, backoff_cap_s: float = 60.0,
) -> tuple[bool, str, int, float]:
    """Bounded accelerator-backend health check in a SUBPROCESS (an
    in-process jax.devices() can hang indefinitely when the TPU tunnel
    is down — the r4 capture outage — and nothing in-process can bound
    it; this function must therefore import NOTHING that imports jax).
    Returns (ok, detail, retries, wait_seconds): retries = probe
    attempts beyond the first, wait_seconds = total time burned in the
    probe incl. backoff — BENCH_r05 lost exactly this context (the old
    fixed 60 s retry loop only printed to stderr).

    Retry policy (ISSUE 5 satellite): capped exponential backoff with
    deterministic jitter between attempts (min(base * 2^k, cap) scaled
    into [0.5, 1.0]) replaces the fixed 60 s sleep; every attempt and
    every backoff is heartbeat into the flight recorder with its detail
    and the cumulative backoff, and an attempt is skipped rather than
    started when the remaining BENCH budget cannot absorb it. Transient
    tunnel resets recover; a real outage is then classified distinctly
    so the judged line says 'infra outage', not 'tracer broke'."""
    global _PROBE_BACKOFF_S
    code_ok = (
        "import jax; d = jax.devices(); "
        "print(d[0].platform, len(d), flush=True)"
    )
    # chaos probe:hang — a subprocess that outlives the timeout is
    # indistinguishable from the real hung-runtime import
    code_hang = "import time; time.sleep(3600)"
    hang_attempts = _probe_hang_attempts()
    max_attempts = max_attempts or int(
        os.environ.get("BENCH_PROBE_ATTEMPTS", "3")
    )
    t_probe = time.time()
    retries = 0
    detail = "?"
    for attempt in range(1, max_attempts + 1):
        if attempt > 1:
            retries += 1
        simulated = attempt in hang_attempts
        _flight_heartbeat(
            "probe", attempt=attempt,
            **({"chaos_hang": True} if simulated else {}),
        )
        try:
            r = subprocess.run(
                [sys.executable, "-c", code_hang if simulated else code_ok],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if r.returncode == 0 and r.stdout.strip():
                detail = r.stdout.strip()
                _flight_heartbeat("probe", attempt=attempt, ok=True,
                                  backend=detail)
                return True, detail, retries, time.time() - t_probe
            detail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
            detail = f"rc={r.returncode}: {detail[0][:200]}"
        except subprocess.TimeoutExpired:
            detail = f"backend init hung >{timeout_s:.0f}s"
        _flight_heartbeat("probe", attempt=attempt, ok=False, detail=detail)
        if attempt == max_attempts:
            break
        b = min(backoff_base_s * (2.0 ** (attempt - 1)), backoff_cap_s)
        # deterministic jitter (zlib.crc32 of the attempt index): the
        # same run shape replays identically under chaos
        frac = (zlib.crc32(f"probe:{attempt}".encode()) & 0xFFFF) / 65535.0
        sleep_s = b * (0.5 + 0.5 * frac)
        if BUDGET - (time.time() - T_START) < timeout_s + sleep_s + 30:
            # no budget for another attempt + its backoff: stop probing
            # and let the outage line report what we know
            _flight_heartbeat(
                "probe_giveup", attempt=attempt,
                remaining_s=round(BUDGET - (time.time() - T_START), 1),
            )
            break
        _PROBE_BACKOFF_S += sleep_s
        _flight_heartbeat(
            "probe_backoff", attempt=attempt,
            backoff_s=round(sleep_s, 1),
            backoff_total_s=round(_PROBE_BACKOFF_S, 1),
        )
        print(
            f"backend probe failed ({detail}); retrying in {sleep_s:.1f}s",
            file=sys.stderr,
        )
        time.sleep(sleep_s)
    return False, detail, retries, time.time() - t_probe

def static_wave_cost(res: int, spp: int, timeout_s: float = 150.0) -> dict:
    """Static per-wave roofline of the production-shaped pool drain
    (tpu_pbrt/analysis/cost.py --bench-wave), computed in a CPU
    SUBPROCESS: a pure jaxpr trace that needs NO accelerator — which is
    the point (ISSUE 3): the r5 capture was an infra outage and its
    BENCH JSON carried zero perf signal; these fields keep the static
    half of the signal alive through any outage. Returns {} on failure
    (the judged metrics must never depend on this)."""
    if os.environ.get("BENCH_SKIP_STATIC"):
        return {}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("BENCH_SKIP_STATIC", None)
    try:
        r = subprocess.run(
            [sys.executable, "-m", "tpu_pbrt.analysis.cost",
             "--bench-wave", "--res", str(res), "--spp", str(spp)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
        if r.returncode == 0 and r.stdout.strip():
            d = json.loads(r.stdout.strip().splitlines()[-1])
            return {
                k: d[k]
                for k in ("static_flops_per_wave", "static_bytes_per_wave",
                          "static_intensity",
                          # pallascheck's fused-kernel VMEM footprint +
                          # budget headroom fraction (ISSUE 11) — absent
                          # from pre-PR-11 subprocess output, tolerated
                          "static_vmem_per_wave", "vmem_headroom",
                          # hbmcheck's per-job serve footprint + HBM
                          # budget headroom fraction (ISSUE 18) — same
                          # tolerance for pre-PR-18 subprocess output
                          "static_hbm_per_job", "hbm_headroom")
                if k in d
            }
        print(
            f"static wave cost subprocess rc={r.returncode}: "
            f"{(r.stderr or '').strip().splitlines()[-1:] or ['?']}",
            file=sys.stderr,
        )
    except Exception as e:  # noqa: BLE001 — advisory fields only
        print(f"static wave cost failed: {e}", file=sys.stderr)
    return {}


#: last completed throughput measurement, reported by the SIGTERM/exception
#: fallback so a mid-phase kill still lands the number we already have
_last_line = None


def remaining():
    return BUDGET - (time.time() - T_START)


class CompileTracker:
    """Counts XLA backend compilations and their wall seconds via the
    supported jax.monitoring event stream (jaxlint ISSUE 2 satellite:
    bench.py records `jit_recompiles` during the measured leg — any
    value > 0 means the steady-state number paid hidden compile time —
    and `compile_seconds` for the whole process)."""

    _EVENT = "/jax/core/compile/backend_compile_duration"

    def __init__(self):
        import jax.monitoring

        self.compiles = 0
        self.seconds = 0.0

        def _on_event(event, duration, **kw):
            if event == CompileTracker._EVENT:
                self.compiles += 1
                self.seconds += duration

        jax.monitoring.register_event_duration_secs_listener(_on_event)


def compute_mse(mse_res: int, mse_spp: int, ref_spp: int):
    """Accelerator render vs cached CPU reference -> per-pixel MSE, or None
    if the reference cache is missing (generate with tools/make_reference.py)
    or the budgeted render did not complete. The render budget is computed
    AFTER the scene build/compile so that unbudgeted phase can't push the
    total spend past BENCH_BUDGET_S."""
    import numpy as np

    from tools.make_reference import reference_path

    path = reference_path(mse_res, ref_spp)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        ref = np.asarray(z["image"], np.float32)

    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(res=mse_res, spp=mse_spp)
    scene, integ = compile_api(api)
    result = integ.render(scene, max_seconds=max(remaining() - 10.0, 5.0))
    if result.completed_fraction < 1.0:
        print(
            f"mse render incomplete ({result.completed_fraction:.0%}) — skipping",
            file=sys.stderr,
        )
        return None
    img = np.asarray(result.image, np.float32)
    return float(np.mean((img - ref) ** 2))


def main():
    # --trace out.json exports the span timeline; unknown args are left
    # for the driver (bench is also run bare by scripts that predate it)
    import argparse

    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--trace", default="")
    args, _ = ap.parse_known_args()

    # judged work shape (BASELINE.json: killeroo/crown @ 256spp)
    spp = int(os.environ.get("BENCH_SPP", "256"))
    res = int(os.environ.get("BENCH_RES", "512"))

    # classify an accelerator outage BEFORE touching jax in-process
    # (VERDICT r4 weak #1: the r4 capture recorded 0.0 Mray/s because
    # the 'axon' backend was down — an infra condition, not a perf one).
    # NOTHING on this path may import tpu_pbrt/jax: if the accelerator
    # runtime is what's hanging, an in-process import would stall the
    # capture before the bounded probe ever runs. Heartbeats use the
    # import-free writer; the static fields come from a subprocess.
    if not os.environ.get("BENCH_SKIP_PROBE"):
        ok, detail, retries, wait_s = probe_backend()
        if not ok:
            line = {
                "metric": "killeroo_like_path_mray_per_sec",
                "value": 0.0, "unit": "Mray/s", "vs_baseline": 0.0,
                "infra_outage": True,
                "error": f"accelerator backend unreachable ({detail}); "
                         "perf not measurable this capture — see "
                         "BASELINE.md for the last committed measurement",
                # the probe's own accounting + where the flight recorder
                # last heartbeat — the diagnosis BENCH_r05 lacked
                "probe_retries": retries,
                "probe_wait_seconds": round(wait_s, 1),
                "probe_backoff_seconds": round(_PROBE_BACKOFF_S, 1),
                "flight_phase": _last_phase,
                "flight_path": _FLIGHT_PATH,
            }
            # the static half of the perf signal survives the outage:
            # per-wave roofline from a CPU-side jaxpr trace (ISSUE 3)
            if remaining() > 60:
                line.update(static_wave_cost(
                    res, spp, timeout_s=max(min(remaining() - 20, 150), 30)
                ))
            # the telemetry block exists even through an outage so rows
            # stay schema-comparable; the live half is null by
            # definition (inline literal — obs.rooflive would import
            # tpu_pbrt, see above)
            line["telemetry"] = {
                "counters": None, "wave_spread": None,
                "tracer_mode": None, "fused_blocks_per_flush": None,
                "phase_seconds": None,
                "host_overlap_fraction": None,
                "live_bytes_per_sec": None, "live_flops_per_sec": None,
                "hbm_peak_bytes_per_sec": None,
                "live_vs_static_ratio": None,
            }
            _flight_heartbeat("report", infra_outage=True, retries=retries)
            print(json.dumps(line))
            return
        print(f"backend: {detail}", file=sys.stderr)

    # backend reachable: from here on tpu_pbrt (and jax) are safe to
    # import — hand the flight file over to the real recorder and arm
    # the span recorder
    from tpu_pbrt.obs.flight import FLIGHT
    from tpu_pbrt.obs.trace import TRACE

    FLIGHT.configure(_FLIGHT_PATH, t0=T_START)
    if args.trace:
        TRACE.configure(args.trace)

    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    tracker = CompileTracker()
    FLIGHT.heartbeat("scene_compile", res=res, spp=spp)
    # scene_compile_seconds: parse + BVH build + device upload, measured
    # SEPARATELY from compile_seconds (XLA jit) — the two costs a warm
    # render-service residency hit (ISSUE 6) eliminates are exactly
    # these, so the trajectory needs them apart to credit the win
    _t_scene = time.time()
    with TRACE.span("bench/scene_compile"):
        api = make_killeroo_like(res=res, spp=spp)
        scene, integ = compile_api(api)
    scene_compile_seconds = time.time() - _t_scene

    # Warmup: a tightly budgeted pass populates the jit cache (identical
    # shapes). Its result doubles as the fallback measurement if compile
    # ate the budget — a compile-tainted number still beats no number.
    FLIGHT.heartbeat("warmup")
    with TRACE.span("bench/warmup"):
        result = integ.render(scene, max_seconds=5)
    compiles_after_warmup = tracker.compiles
    if remaining() > 60:
        # steady-state throughput stabilizes well before completion; box
        # the main leg so the MSE and crown legs fit the total budget
        FLIGHT.heartbeat("measure")
        with TRACE.span("bench/measure"):
            result = integ.render(
                scene,
                max_seconds=min(
                    remaining() - 30.0, max(60.0, remaining() * 0.22)
                ),
            )

    # measured rays per camera ray from the run just completed (the class
    # default attribute is a lower bound; the real factor includes bounces
    # and shadow segments)
    cam_rays = res * res * spp * max(result.completed_fraction, 1e-6)
    rays_ratio = max(result.rays_traced / max(cam_rays, 1.0), 1.0)

    north_star = 100.0  # Mray/s on v5e-8 (BASELINE.json north_star)
    # sanity channel: a black render means the tracer is broken even if
    # the ray counter ticked — Mray/s over a broken image is not a result
    import numpy as np

    img_mean = float(np.mean(np.asarray(result.image, np.float32)))
    global _last_line
    _last_line = {
        "metric": "killeroo_like_path_mray_per_sec",
        "value": round(result.mray_per_sec, 3),
        "unit": "Mray/s",
        "vs_baseline": round(result.mray_per_sec / north_star, 4),
        "completed_fraction": round(result.completed_fraction, 4),
        "rays_traced": result.rays_traced,
        "seconds": round(result.seconds, 2),
        "image_mean": round(img_mean, 6),
    }
    # persistent-wavefront occupancy (ISSUE 1): live lanes per trace wave
    # under compaction+regeneration — the trajectory metric next to Mray/s
    occ = result.stats.get("mean_wave_occupancy")
    if occ is not None:
        _last_line["mean_wave_occupancy"] = round(float(occ), 4)
        _last_line["trace_waves"] = int(result.stats.get("n_waves", 0))
        _last_line["pool"] = int(result.stats.get("pool", 0))
    # compile accounting (jaxlint audit's recompile guard, measured in
    # the judged run): backend compiles during the steady-state leg must
    # be 0 — the warmup pass owns every legitimate trace for these
    # shapes. compiles_after_warmup == 0 means the warmup was served
    # from a persistent compile cache (the event stream only fires on
    # real backend compiles); flag it so a 0/0 reading is interpretable.
    _last_line["jit_recompiles"] = tracker.compiles - compiles_after_warmup
    _last_line["compile_seconds"] = round(tracker.seconds, 2)
    _last_line["scene_compile_seconds"] = round(scene_compile_seconds, 2)
    if compiles_after_warmup == 0:
        _last_line["compile_cache_warm"] = True
    if not (img_mean > 1e-6):
        _last_line["error"] = "image is black — tracer broken"

    # crown-class row (VERDICT r4 #5): >=1M-tri glass+metal-GGX+HDR-env
    # scene, reported as crown_* fields of the same JSON line (the
    # driver parses exactly one line). Runs BEFORE the MSE leg but
    # reserves its predicted cost so the judged accuracy number is
    # never starved.
    crown = None
    mse_res = int(os.environ.get("MSE_RES", "128"))
    mse_spp = int(os.environ.get("MSE_SPP", "256"))
    est_rays = mse_res * mse_res * mse_spp * rays_ratio
    mse_reserve = (
        0.0 if os.environ.get("BENCH_SKIP_MSE")
        # + ~95 s: the 128^2 MSE scene is a different shape and pays its
        # own jit compile, which est_rays/throughput cannot see
        else est_rays / max(result.mray_per_sec, 1e-6) / 1e6 + 95.0
    )
    if not os.environ.get("BENCH_SKIP_CROWN") and remaining() - mse_reserve > 90:
        try:
            from tpu_pbrt.scenes import make_crown_like

            FLIGHT.heartbeat("crown")
            with TRACE.span("bench/crown"):
                capi = make_crown_like(
                    res=int(os.environ.get("CROWN_RES", "512")),
                    spp=int(os.environ.get("CROWN_SPP", "256")),
                )
                cscene, cinteg = compile_api(capi)
                cinteg.render(cscene, max_seconds=5)  # warmup (jit compile)
                # the 1M-tri compile above is unbudgeted: re-check that
                # the judged MSE leg still fits before spending more here
                budget = remaining() - mse_reserve - 15.0
                if budget < 10.0:
                    raise RuntimeError("crown skipped post-compile: budget")
                cres = cinteg.render(cscene, max_seconds=budget)
            import numpy as _np

            cmean = float(_np.mean(_np.asarray(cres.image, _np.float32)))
            crown = {
                "crown_mray_per_sec": round(cres.mray_per_sec, 3),
                "crown_completed_fraction": round(cres.completed_fraction, 4),
                "crown_rays_traced": cres.rays_traced,
                "crown_image_mean": round(cmean, 6),
            }
            _last_line.update(crown)
        except Exception as e:  # noqa: BLE001
            crown = {"crown_error": f"{type(e).__name__}: {e}"}
    elif not os.environ.get("BENCH_SKIP_CROWN"):
        print(f"skipping crown row: {remaining():.0f}s left", file=sys.stderr)

    mse = None
    if not os.environ.get("BENCH_SKIP_MSE"):
        try:
            mse_res = int(os.environ.get("MSE_RES", "128"))
            mse_spp = int(os.environ.get("MSE_SPP", "256"))
            # predicted cost of the MSE render from measured throughput
            est_rays = mse_res * mse_res * mse_spp * rays_ratio
            est_s = est_rays / max(result.mray_per_sec, 1e-6) / 1e6 + 30.0
            budget = remaining() - 20.0
            if est_s < budget:
                FLIGHT.heartbeat("mse")
                with TRACE.span("bench/mse"):
                    mse = compute_mse(
                        mse_res, mse_spp,
                        int(os.environ.get("REF_SPP", "256")),
                    )
            else:
                print(
                    f"skipping MSE: est {est_s:.0f}s > budget {budget:.0f}s",
                    file=sys.stderr,
                )
        except Exception as e:  # noqa: BLE001 — MSE failure must not eat the perf number
            print(f"mse computation failed: {e}", file=sys.stderr)

    # static per-wave roofline next to the measured occupancy (ISSUE 3):
    # the same fields the outage path emits, so BENCH rows stay
    # comparable across infra-up and infra-down captures. Runs LAST —
    # it is advisory and must never starve the judged crown/MSE legs.
    if remaining() > 45:
        with TRACE.span("bench/static_cost"):
            _last_line.update(static_wave_cost(
                res, spp, timeout_s=max(min(remaining() - 15, 150), 30)
            ))

    # telemetry block (ISSUE 4): device counters + per-device wave-count
    # spread from the measured leg, and the live-vs-static roofline
    # ratio closing the loop on the static fields above (null on CPU or
    # when the static trace failed — the block is always present so
    # BENCH rows stay schema-comparable)
    import jax as _jax

    from tpu_pbrt.obs.metrics import host_overlap_fraction, phase_summary
    from tpu_pbrt.obs.rooflive import live_vs_static

    tstats = result.stats.get("telemetry") or {}
    devs = _jax.devices()
    # tracer attribution (ISSUE 9): which flush/expand program the wave
    # compiled to, and the static per-flush block capacity of the fused
    # grid — so the live roofline ratio reads against the right kernel
    fused_blocks = None
    if result.stats.get("pool") and "tstream" in scene.dev:
        from tpu_pbrt.accel.stream import flush_geometry

        fused_blocks = flush_geometry(
            # the tracer sees the fused camera+shadow 2R wave
            2 * int(result.stats["pool"]),
            scene.dev["tstream"].n_treelets,
        )["blocks_per_flush"]
    _last_line["telemetry"] = {
        "counters": tstats.get("counters"),
        "wave_spread": tstats.get("wave_spread"),
        "tracer_mode": result.stats.get("tracer_mode"),
        "fused_blocks_per_flush": fused_blocks,
        # per-phase wall-time histogram summary (ISSUE 10): dispatch vs
        # device-wait vs deposit-develop vs checkpoint across every leg
        # this process ran, labeled by tracer in the registry — the
        # fused-vs-jnp phase evidence ROADMAP #1 stage two waits on
        # (null under TPU_PBRT_METRICS=0; rows stay schema-comparable)
        "phase_seconds": phase_summary(),
        # device_wait / measured wall over the MEASURED leg (ISSUE 13):
        # 1.0 = the host tax (deposit/develop/checkpoint bookkeeping)
        # fully hidden under in-flight dispatch — the pipelined-drain
        # acceptance number, strictly better at TPU_PBRT_PIPELINE=2
        # than the depth-1 synchronous baseline
        "host_overlap_fraction": host_overlap_fraction(
            result.stats.get("phase_seconds"), result.seconds
        ),
        **live_vs_static(
            waves=result.stats.get("n_waves"),
            seconds=result.seconds,
            static_bytes_per_wave=_last_line.get("static_bytes_per_wave"),
            static_flops_per_wave=_last_line.get("static_flops_per_wave"),
            device_kind=getattr(devs[0], "device_kind", devs[0].platform),
            n_devices=len(devs),
        ),
    }
    if tstats.get("counters"):
        FLIGHT.counters(tstats["counters"], phase="measure_counters")

    line = dict(_last_line)
    if mse is not None:
        line["mse_vs_cpu_ref"] = mse
        line["mse_target"] = 1e-4
    if crown:
        line.update(crown)
    FLIGHT.heartbeat("report", mray_per_sec=line.get("value"))
    TRACE.maybe_export()
    from tpu_pbrt.obs.metrics import METRICS

    METRICS.maybe_export()  # TPU_PBRT_METRICS_PATH snapshot, if armed
    print(json.dumps(line))


def _on_term(signum, frame):
    raise RuntimeError(f"signal {signum}")


if __name__ == "__main__":
    import signal

    # `timeout` sends SIGTERM before SIGKILL: convert it into an exception
    # so the fallback line below still prints under a driver timeout
    signal.signal(signal.SIGTERM, _on_term)
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — ALWAYS print a parseable line
        line = dict(_last_line) if _last_line else {
            "metric": "killeroo_like_path_mray_per_sec",
            "value": 0.0,
            "unit": "Mray/s",
            "vs_baseline": 0.0,
        }
        line["error"] = f"{type(e).__name__}: {e}"
        # the flight recorder's last phase turns "signal 15" into "died
        # mid-<phase> after N s" for the post-mortem. Only touch the
        # real recorder if tpu_pbrt ALREADY imported — a fatal during a
        # hung-runtime capture must not start the import that hangs.
        try:
            mod = sys.modules.get("tpu_pbrt.obs.flight")
            if mod is not None and mod.FLIGHT.last_phase is not None:
                line["flight_phase"] = mod.FLIGHT.last_phase
            else:
                line["flight_phase"] = _last_phase
            _flight_heartbeat("fatal", error=line["error"])
            tmod = sys.modules.get("tpu_pbrt.obs.trace")
            if tmod is not None:
                tmod.TRACE.maybe_export()
        except Exception:  # noqa: BLE001 — telemetry must not mask the error
            pass
        print(json.dumps(line))
        sys.exit(0)
