#!/usr/bin/env python
"""Benchmark driver: renders the killeroo-simple-class workload and prints
one JSON line {"metric", "value", "unit", "vs_baseline"}.

The workload mirrors BASELINE.json's killeroo-simple config (PathIntegrator,
matte trimesh, area light) with a procedural ~128k-triangle mesh standing in
for the PLY (pbrt-v3-scenes is not available in this environment). Metric is
Mray/s (rays actually traced / steady-state wall time, counted in-kernel),
judged against the north-star 100 Mray/s target. A warmup pass excludes XLA
compilation from the timing, matching how the reference's numbers would
exclude its BVH build.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    spp = int(os.environ.get("BENCH_SPP", "64"))
    res = int(os.environ.get("BENCH_RES", "512"))

    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(res=res, spp=spp)
    scene, integ = compile_api(api)

    # warmup run with identical shapes so the timed run hits the jit cache
    integ.render(scene)
    result = integ.render(scene)
    north_star = 100.0  # Mray/s on v5e-8 (BASELINE.json north_star)
    print(
        json.dumps(
            {
                "metric": "killeroo_like_path_mray_per_sec",
                "value": round(result.mray_per_sec, 3),
                "unit": "Mray/s",
                "vs_baseline": round(result.mray_per_sec / north_star, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
