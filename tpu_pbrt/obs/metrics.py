"""tpu-metrics: process-wide HOST-side metrics registry (ISSUE 10).

PR 4 gave each render its own telemetry primitives — device counters
fetched once per drain, raw Perfetto spans, an append-only flight file.
A long-lived multi-tenant service needs the layer above: aggregation
ACROSS jobs (percentile queue wait, chunk service time), an exposition a
monitor can scrape, and the pressure signal ROADMAP #2's load shedding
decides against. This module is that layer:

- **Counter / Gauge / Histogram** with free-form labels. Histograms use
  FIXED bucket edges chosen at registration: snapshots are a pure
  function of the observed values (no reservoir sampling, no decay), so
  two services fed the same event sequence expose identical bytes — the
  same determinism contract the fair scheduler keeps.
- **p50/p90/p99 derived from bucket counts** (linear interpolation
  inside the covering bucket): cheap, deterministic, and good enough to
  steer load shedding — exact order statistics would need per-sample
  storage a render service must not pay.
- **Prometheus text exposition** (`exposition()`) plus a deterministic
  JSON `snapshot()`; both validated by `python -m tpu_pbrt.obs`
  (`validate_exposition` / `validate_snapshot`).
- **Span folding** (`fold_trace`): maps the PR 4 Chrome-trace span names
  onto the phase histogram with `tracer` labels, so one `--trace`
  capture yields the fused-vs-jnp phase breakdown ROADMAP #1 stage two
  needs without re-running anything.

Division of labor with PR 4: device-side truth stays with the traced
`WaveCounters` — this registry ingests host-visible events only, at the
existing drain/serve host boundaries. Nothing here imports jax, nothing
is called from traced code, so the audit/shardcheck/transfer-guard gates
and the compiled programs are untouched by construction.

Kill switch: `TPU_PBRT_METRICS=0`. Every record call is a no-op and no
snapshot/exposition is produced; render stats and serve responses are
byte-identical to a build without the registry (pinned by
tests/test_metrics.py).
"""

from __future__ import annotations

import math
import os
import re
from typing import Any, Dict, List, Optional, Tuple

#: exposition namespace — every metric name is prefixed with this
PREFIX = "tpu_pbrt_"

#: fixed latency edges (seconds): sub-ms host hops through multi-minute
#: chunk drains. Fixed at import so every snapshot is comparable.
TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    """Canonical (sorted) label tuple — the series key. Values are
    stringified here so snapshot/exposition need no further coercion."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_value(v: float) -> str:
    """Sample-value formatting: integers print as integers (counter
    increments are usually whole), floats round-trip via repr."""
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(edge: float) -> str:
    return "+Inf" if math.isinf(edge) else _fmt_value(edge)


def percentile_from_buckets(
    edges: Tuple[float, ...], counts: List[int], q: float
) -> Optional[float]:
    """The q-quantile implied by fixed-bucket counts: find the covering
    bucket by cumulative rank and interpolate linearly inside it.
    Deterministic (pure function of the counts); None on no data. The
    +Inf bucket cannot be interpolated — it clamps to the last finite
    edge (an under-estimate, which for SLO shedding is the conservative
    direction only if edges cover the targets; pick edges accordingly)."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c > 0 and cum + c >= target:
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i] if i < len(edges) else edges[-1]
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return edges[-1]


class _Metric:
    """Shared series storage: one dict keyed by the canonical label
    tuple. Subclasses define how values accumulate."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        self._reg = registry
        self.name = name
        self.help = help
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _enabled(self) -> bool:
        return self._reg.enabled

    def labelsets(self) -> List[Dict[str, str]]:
        return [dict(k) for k in sorted(self._series)]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if not self._enabled():
            return
        if value < 0:
            raise ValueError(f"counter {self.name} decremented by {value}")
        key = _label_key(labels)
        self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not self._enabled():
            return
        self._series[_label_key(labels)] = float(value)

    def value(self, **labels) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help, buckets=TIME_BUCKETS):
        super().__init__(registry, name, help)
        edges = tuple(float(b) for b in buckets)
        if list(edges) != sorted(set(edges)) or not edges:
            raise ValueError(f"histogram {name}: edges must be sorted unique")
        if math.isinf(edges[-1]):
            edges = edges[:-1]  # the +Inf bucket is implicit
        self.edges = edges
        # per-series exemplars: label key -> [(value, seq, fields), ...]
        self._exemplars: Dict[Tuple[Tuple[str, str], ...], list] = {}

    def observe(
        self, value: float, exemplar: Optional[Dict[str, Any]] = None,
        **labels,
    ) -> None:
        if not self._enabled():
            return
        key = _label_key(labels)
        ser = self._series.get(key)
        if ser is None:
            # [bucket counts (len(edges)+1, last = +Inf), sum, count]
            ser = self._series[key] = [[0] * (len(self.edges) + 1), 0.0, 0]
        v = float(value)
        i = len(self.edges)
        for j, edge in enumerate(self.edges):
            if v <= edge:
                i = j
                break
        ser[0][i] += 1
        ser[1] += v
        if exemplar is not None:
            # seq = pre-increment observation count: a deterministic
            # tiebreak that needs no extra state
            self._note_exemplar(key, v, ser[2], exemplar)
        ser[2] += 1

    def _note_exemplar(self, key, v: float, seq: int, fields):
        """Bounded, deterministic exemplar retention (tpu-scope): keep
        the top-K observations by value — the tail a debugger wants to
        join back to a trace — with the join ids (trace_id/span_id) the
        caller attached. Replacement is strictly-greater-than-the-min
        with ties keeping the EARLIEST observation, so the retained set
        is a pure function of the observation sequence (no reservoir
        sampling, no clock), matching the registry's determinism
        contract."""
        from tpu_pbrt.config import cfg

        k = cfg.metrics_exemplars
        if k <= 0:
            return
        ex = self._exemplars.setdefault(key, [])
        entry = (v, seq, dict(fields))
        if len(ex) < k:
            ex.append(entry)
            return
        mi = min(range(len(ex)), key=lambda i: (ex[i][0], -ex[i][1]))
        if v > ex[mi][0]:
            ex[mi] = entry

    def exemplars(self, **labels) -> List[Dict[str, Any]]:
        """Retained exemplars for one series, largest value first
        (deterministic order: value desc, then observation seq)."""
        ex = self._exemplars.get(_label_key(labels), [])
        return [
            {"value": v, **fields}
            for v, _, fields in sorted(ex, key=lambda e: (-e[0], e[1]))
        ]

    def _matching(self, match: Optional[Dict[str, Any]]):
        want = {str(k): str(v) for k, v in (match or {}).items()}
        for key, ser in sorted(self._series.items()):
            kd = dict(key)
            if all(kd.get(k) == v for k, v in want.items()):
                yield key, ser

    def percentile(
        self, q: float, match: Optional[Dict[str, Any]] = None
    ) -> Optional[float]:
        """q-quantile over every series whose labels match `match`
        (subset semantics; {} or None = all series aggregated)."""
        agg = [0] * (len(self.edges) + 1)
        for _, ser in self._matching(match):
            for i, c in enumerate(ser[0]):
                agg[i] += c
        return percentile_from_buckets(self.edges, agg, q)

    def aggregate(self, match: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Summed (sum, count, p50/p90/p99) over matching series —
        the bench/stats summary shape."""
        total_sum = 0.0
        total_n = 0
        agg = [0] * (len(self.edges) + 1)
        for _, ser in self._matching(match):
            for i, c in enumerate(ser[0]):
                agg[i] += c
            total_sum += ser[1]
            total_n += ser[2]
        if total_n == 0:
            return {}
        return {
            "seconds": round(total_sum, 6),
            "count": total_n,
            "p50": round(percentile_from_buckets(self.edges, agg, 0.50), 6),
            "p90": round(percentile_from_buckets(self.edges, agg, 0.90), 6),
            "p99": round(percentile_from_buckets(self.edges, agg, 0.99), 6),
        }


class MetricsRegistry:
    """Process-wide registry (the `METRICS` singleton). Registration is
    get-or-create keyed by name — instrumentation sites just call
    `METRICS.histogram(...)` inline and share series automatically; a
    kind conflict (counter re-registered as gauge) raises."""

    def __init__(self, force_enabled: bool = False):
        self._metrics: Dict[str, _Metric] = {}
        self._path: Optional[str] = None
        #: bypass the TPU_PBRT_METRICS kill switch — for OFFLINE use
        #: (trace replay, selftest) where the operator explicitly asked
        #: for an analysis: the switch guards live-render overhead and
        #: stats purity, neither of which an offline registry touches
        self._force = bool(force_enabled)

    @property
    def enabled(self) -> bool:
        if self._force:
            return True
        from tpu_pbrt.config import cfg

        return bool(cfg.metrics)

    # -- registration ------------------------------------------------------
    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        if not name.startswith(PREFIX):
            name = PREFIX + name
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(self, name, help, **kw)
        elif not isinstance(m, cls):
            raise ValueError(
                f"metric {name} already registered as {m.kind}, "
                f"not {cls.kind}"
            )
        elif "buckets" in kw:
            # a second registration site asking for DIFFERENT edges
            # would silently record into the first site's buckets (every
            # observation past the smaller scale lands in +Inf) — a
            # conflict must raise like the kind conflict above
            want = tuple(float(b) for b in kw["buckets"])
            if want and math.isinf(want[-1]):
                want = want[:-1]
            if want != m.edges:
                raise ValueError(
                    f"histogram {name} already registered with edges "
                    f"{m.edges}, not {want}"
                )
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets=TIME_BUCKETS
    ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def reset(self) -> None:
        """Drop every metric AND its registration (test seam)."""
        self._metrics.clear()

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Deterministic JSON-safe dict: metric names sorted, series
        sorted by label tuple, histogram percentiles precomputed."""
        out: Dict[str, Any] = {"schema": "tpu-pbrt-metrics-v1", "metrics": {}}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            series = []
            for key in sorted(m._series):
                ser = m._series[key]
                entry: Dict[str, Any] = {"labels": dict(key)}
                if m.kind == "histogram":
                    entry |= {
                        "buckets": [_fmt_le(e) for e in m.edges] + ["+Inf"],
                        "counts": list(ser[0]),
                        "sum": ser[1],
                        "count": ser[2],
                    }
                    for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                        entry[label] = percentile_from_buckets(
                            m.edges, ser[0], q
                        )
                    ex = m.exemplars(**dict(key))
                    if ex:
                        entry["exemplars"] = ex
                else:
                    entry["value"] = ser
                series.append(entry)
            out["metrics"][name] = {
                "type": m.kind, "help": m.help, "series": series,
            }
        return out

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if not m._series:
                # a registration with nothing recorded (e.g. the kill
                # switch was on) exposes nothing — not even headers, so
                # TPU_PBRT_METRICS=0 yields an empty page by contract
                continue
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for key in sorted(m._series):
                ser = m._series[key]
                base_labels = list(key)
                if m.kind == "histogram":
                    cum = 0
                    for i, edge in enumerate(list(m.edges) + [math.inf]):
                        cum += ser[0][i]
                        lab = _render_labels(
                            base_labels + [("le", _fmt_le(edge))]
                        )
                        lines.append(f"{name}_bucket{lab} {cum}")
                    lab = _render_labels(base_labels)
                    lines.append(f"{name}_sum{lab} {_fmt_value(ser[1])}")
                    lines.append(f"{name}_count{lab} {ser[2]}")
                else:
                    lab = _render_labels(base_labels)
                    lines.append(f"{name}{lab} {_fmt_value(ser)}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- snapshot file (--metrics-path) ------------------------------------
    def configure(self, path: Optional[str]) -> None:
        self._path = path or None

    @property
    def path(self) -> Optional[str]:
        from tpu_pbrt.config import cfg

        return self._path or cfg.metrics_path

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the exposition text atomically (tmp+rename, the
        checkpoint/trace pattern: a crash mid-write must leave the last
        valid snapshot, not a truncated one)."""
        path = path or self.path
        if not path:
            return None
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.exposition())
        os.replace(tmp, path)
        return path

    def maybe_export(self) -> Optional[str]:
        return self.export() if (self.enabled and self.path) else None


def _render_labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in sorted(pairs))
    return "{" + inner + "}"


#: the process-wide registry every instrumentation site records into
METRICS = MetricsRegistry()


# -- render-phase attribution (the ROADMAP #1 stage-two evidence) ----------

#: PR 4 span names -> phase labels; fold_trace and the inline render-loop
#: attribution write the SAME histogram, so a live capture and an offline
#: trace replay land in one comparable place
PHASE_HISTOGRAM = "render_phase_seconds"
SPAN_PHASES = {
    "render/chunk_dispatch": "dispatch",
    "render/chunk_dispatch+compile": "dispatch_compile",
    # a dispatch issued while older slices are still in flight (the
    # pipelined window, ISSUE 13): its host cost is hidden under device
    # compute, so it is attributed separately from a bare dispatch
    "render/chunk_dispatch_ahead": "dispatch_ahead",
    "render/chunk_retire": "device_wait",
    "render/wave_drain+film_merge": "device_wait",
    "render/develop": "deposit_develop",
    "render/write_image": "deposit_develop",
    "render/checkpoint": "checkpoint",
    "serve/slice": "dispatch",
    "serve/slice_ahead": "dispatch_ahead",
    "serve/slice_retire": "device_wait",
}


def phase_histogram(registry: MetricsRegistry = METRICS) -> Histogram:
    return registry.histogram(
        PHASE_HISTOGRAM,
        "wall seconds per render-loop phase (labels: phase, tracer)",
    )


def fold_trace(doc, registry: MetricsRegistry = METRICS) -> int:
    """Fold a Chrome-trace document (dict, or a path to one) into the
    phase histogram: every complete ('X') span whose name maps to a
    phase is observed with its tracer label. Returns the number of
    spans folded. This is the offline half of phase attribution — a
    `--trace` capture from a LIVE run replays into the exact histograms
    the inline instrumentation fills, labeled fused vs jnp."""
    import json

    if isinstance(doc, str):
        with open(doc) as f:
            doc = json.load(f)
    hist = phase_histogram(registry)
    n = 0
    for ev in doc.get("traceEvents", []):
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        phase = SPAN_PHASES.get(ev.get("name"))
        if phase is None:
            continue
        args = ev.get("args") or {}
        hist.observe(
            float(ev.get("dur", 0)) / 1e6,
            phase=phase,
            tracer=str(args.get("tracer", "unknown")),
        )
        n += 1
    return n


def phase_summary(
    registry: MetricsRegistry = METRICS,
) -> Optional[Dict[str, Any]]:
    """{phase: {seconds, count, p50, p90, p99}} over every tracer label —
    the bench-JSON `telemetry.phase_seconds` block and the render-stats
    summary. None when the registry is off or holds no phase data."""
    if not registry.enabled:
        return None
    m = registry._metrics.get(PREFIX + PHASE_HISTOGRAM)
    if m is None or not m._series:
        return None
    phases = sorted({dict(k).get("phase", "") for k in m._series})
    out = {}
    for ph in phases:
        agg = m.aggregate(match={"phase": ph})
        if agg:
            out[ph] = agg
    return out or None


def host_overlap_fraction(
    phases: Optional[Dict[str, float]] = None,
    wall_seconds: Optional[float] = None,
    registry: MetricsRegistry = METRICS,
) -> Optional[float]:
    """device_wait seconds / wall — the fraction of the drain's wall
    time the host spent blocked on device compute rather than doing its
    own work serially (ISSUE 13 / ROADMAP #2's acceptance metric). 1.0
    means every host-side second — deposit bookkeeping, develop,
    checkpoint serialization, scheduling — was hidden under in-flight
    dispatches; the gap to 1.0 is the host tax the pipeline window
    exists to hide.

    `phases` is a {phase: seconds} dict (a render's
    stats["phase_seconds"]); None aggregates the process-wide phase
    histogram instead. `wall_seconds` is the measured wall clock; None
    falls back to the sum of the attributed phases (a lower bound on
    wall, so the fallback fraction is an upper bound). Returns None
    when nothing was attributed."""
    if phases is None:
        summ = phase_summary(registry)
        if not summ:
            return None
        phases = {ph: agg["seconds"] for ph, agg in summ.items()}
    if not phases:
        return None
    wall = wall_seconds if wall_seconds else sum(phases.values())
    if not wall or wall <= 0:
        return None
    return round(min(float(phases.get("device_wait", 0.0)) / wall, 1.0), 4)


# -- validation (tests + `python -m tpu_pbrt.obs` + CI) --------------------


def validate_snapshot(doc: Any) -> List[str]:
    """Validate a registry snapshot() dict (or a path to its JSON)."""
    import json

    if isinstance(doc, str):
        try:
            with open(doc) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            return [f"unreadable snapshot: {e}"]
    errs: List[str] = []
    if not isinstance(doc, dict) or doc.get("schema") != "tpu-pbrt-metrics-v1":
        return ["snapshot must be an object with schema tpu-pbrt-metrics-v1"]
    metrics = doc.get("metrics")
    if not isinstance(metrics, dict):
        return ["snapshot.metrics must be an object"]
    for name, m in metrics.items():
        where = f"metrics[{name}]"
        if not _NAME_RE.match(str(name)):
            errs.append(f"{where}: bad metric name")
        if not isinstance(m, dict):
            errs.append(f"{where}: not an object")
            continue
        if m.get("type") not in ("counter", "gauge", "histogram"):
            errs.append(f"{where}: bad type {m.get('type')!r}")
            continue
        series = m.get("series", [])
        if not isinstance(series, list):
            errs.append(f"{where}: series is not an array")
            continue
        for i, ser in enumerate(series):
            sw = f"{where}.series[{i}]"
            if not isinstance(ser, dict):
                errs.append(f"{sw}: not an object")
                continue
            labels = ser.get("labels")
            if not isinstance(labels, dict):
                errs.append(f"{sw}: missing labels object")
                continue
            for k in labels:
                if not _LABEL_RE_OK(k):
                    errs.append(f"{sw}: bad label name {k!r}")
            if m["type"] == "histogram":
                counts = ser.get("counts")
                edges = ser.get("buckets")
                if not isinstance(counts, list) or not isinstance(edges, list):
                    errs.append(f"{sw}: histogram needs buckets+counts")
                    continue
                if len(counts) != len(edges):
                    errs.append(
                        f"{sw}: {len(counts)} counts for {len(edges)} buckets"
                    )
                if any((not isinstance(c, int)) or c < 0 for c in counts):
                    errs.append(f"{sw}: negative/non-int bucket count")
                if sum(c for c in counts if isinstance(c, int)) != ser.get(
                    "count"
                ):
                    errs.append(f"{sw}: count != sum of bucket counts")
                ex = ser.get("exemplars")
                if ex is not None:
                    if not isinstance(ex, list):
                        errs.append(f"{sw}: exemplars is not an array")
                    else:
                        for j, e in enumerate(ex):
                            if not isinstance(e, dict) or not isinstance(
                                e.get("value"), (int, float)
                            ):
                                errs.append(
                                    f"{sw}.exemplars[{j}]: missing "
                                    "numeric value"
                                )
            elif not isinstance(ser.get("value"), (int, float)):
                errs.append(f"{sw}: missing numeric value")
    return errs


def _LABEL_RE_OK(name: str) -> bool:
    return bool(_LABEL_NAME_RE.match(str(name)))


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r"(?:\{(.*)\})?"  # optional label body (parsed separately)
    r"\s+(\S+)\s*$"  # value
)
_LABEL_PAIR_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*(,|$)'
)


def _unescape_label(raw: str) -> str:
    """Single left-to-right pass — sequential str.replace would decode
    the '\\\\n' in a value like 'C:\\\\new' as backslash-then-newline
    instead of the literal backslash + 'n' the escaper wrote."""
    out = []
    i = 0
    while i < len(raw):
        ch = raw[i]
        if ch == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> Optional[Dict[str, str]]:
    """Parse a Prometheus label body, honoring escapes. None on syntax
    error (including an unescaped quote, which the naive split a lint
    must catch would mis-parse)."""
    out: Dict[str, str] = {}
    pos = 0
    while pos < len(body):
        m = _LABEL_PAIR_RE.match(body, pos)
        if m is None:
            return None
        out[m.group(1)] = _unescape_label(m.group(2))
        pos = m.end()
    return out


def validate_exposition(text: str) -> List[str]:
    """Lint a Prometheus text exposition: TYPE lines present and legal,
    sample/label syntax (incl. escaping), histogram bucket counts
    cumulative-monotone with a +Inf bucket equal to _count. Returns a
    list of problems; empty = a scraper will accept the page."""
    errs: List[str] = []
    types: Dict[str, str] = {}
    # histogram accounting: base name -> series key -> {le: value}
    hbuckets: Dict[str, Dict[Tuple, Dict[float, float]]] = {}
    hsums: Dict[str, Dict[Tuple, float]] = {}
    hcounts: Dict[str, Dict[Tuple, float]] = {}
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        where = f"line {ln}"
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errs.append(f"{where}: malformed TYPE line")
                continue
            _, _, name, kind = parts
            if kind not in ("counter", "gauge", "histogram", "summary",
                           "untyped"):
                errs.append(f"{where}: unknown type {kind!r}")
            if name in types:
                errs.append(f"{where}: duplicate TYPE for {name}")
            types[name] = kind
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = _SAMPLE_RE.match(line)
        if m is None:
            errs.append(f"{where}: unparseable sample")
            continue
        name, label_body, value_s = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(label_body) if label_body else {}
        if labels is None:
            errs.append(f"{where}: bad label syntax/escaping")
            continue
        try:
            value = float(value_s)
        except ValueError:
            errs.append(f"{where}: non-numeric value {value_s!r}")
            continue
        # resolve the declaring TYPE (histograms expose _bucket/_sum/_count)
        base = None
        if name in types:
            base = name
        else:
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in types:
                    base = name[: -len(suffix)]
                    break
        if base is None:
            errs.append(f"{where}: sample {name} has no preceding TYPE line")
            continue
        if types[base] == "histogram" and base != name:
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            if name.endswith("_bucket"):
                le_s = labels.get("le")
                if le_s is None:
                    errs.append(f"{where}: histogram bucket without le")
                    continue
                try:
                    le = math.inf if le_s == "+Inf" else float(le_s)
                except ValueError:
                    errs.append(f"{where}: non-numeric le {le_s!r}")
                    continue
                hbuckets.setdefault(base, {}).setdefault(key, {})[le] = value
            elif name.endswith("_sum"):
                hsums.setdefault(base, {})[key] = value
            elif name.endswith("_count"):
                hcounts.setdefault(base, {})[key] = value
        if value < 0 and types[base] == "counter":
            errs.append(f"{where}: negative counter sample")
    for base, series in hbuckets.items():
        for key, by_le in series.items():
            lab = dict(key)
            ledges = sorted(by_le)
            if not ledges or not math.isinf(ledges[-1]):
                errs.append(f"{base}{lab}: histogram missing +Inf bucket")
                continue
            vals = [by_le[e] for e in ledges]
            if any(b < a for a, b in zip(vals, vals[1:])):
                errs.append(
                    f"{base}{lab}: bucket counts not monotone "
                    f"non-decreasing: {vals}"
                )
            cnt = hcounts.get(base, {}).get(key)
            if cnt is None:
                errs.append(f"{base}{lab}: histogram missing _count")
            elif cnt != vals[-1]:
                errs.append(
                    f"{base}{lab}: _count {cnt} != +Inf bucket {vals[-1]}"
                )
            if hsums.get(base, {}).get(key) is None:
                errs.append(f"{base}{lab}: histogram missing _sum")
    return errs
