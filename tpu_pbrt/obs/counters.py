"""Device-side per-wave counter block for the persistent-wavefront drain.

The counters are pure `jnp` state carried through the `pool_chunk`
while_loop (and updated per wave inside `_bounce_wave`), psum-merged
across devices by the mesh drain, and fetched ONCE at the drain boundary
together with the ray/occupancy aux — never mid-loop, so the bounce loop
stays clean under `jax.transfer_guard("disallow")` and adds zero
retraces (the jaxpr-audit gates keep watching both).

Kill switch: `TPU_PBRT_TELEMETRY=0`. A disabled counter block is carried
as `None`, which is an EMPTY jax pytree — the loop carry contributes no
avals and the compiled program is the exact pre-telemetry one, not a
masked variant of it.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, NamedTuple, Optional

import jax
import jax.numpy as jnp

#: occupancy histogram resolution: bin k counts waves whose live-lane
#: fraction fell in [k/N, (k+1)/N) (a full wave lands in the last bin)
N_OCC_BINS = 8

#: host-dict field names, in WaveCounters field order
HOST_FIELDS = (
    "rays_traced",
    "lanes_regenerated",
    "lanes_terminated",
    "film_deposits",
    "lanes_compacted",
    "nonfinite_deposits",
    "occupancy_histogram",
)


class WaveCounters(NamedTuple):
    """Per-drain counter block; every field is an int32 device scalar
    except the occupancy histogram (int32 [N_OCC_BINS])."""

    #: rays traced (camera continuations + shadow + BSSRDF probe rays)
    rays: jnp.ndarray
    #: pool lanes refilled with fresh camera rays from the work counter
    regenerated: jnp.ndarray
    #: lanes whose path died this wave (miss / RR kill / maxdepth)
    terminated: jnp.ndarray
    #: film deposits (terminated lanes whose pending NEE also settled)
    deposits: jnp.ndarray
    #: live lanes relocated by the compaction sort (slot index changed)
    compacted: jnp.ndarray
    #: deposits whose radiance carried NaN/Inf and was scrubbed to zero
    #: by the film's non-finite firewall (ISSUE 5: one bad wave must not
    #: silently poison every later checkpoint — > 0 here is the signal)
    nonfinite: jnp.ndarray
    #: per-wave occupancy histogram (live lanes / pool width at trace time)
    occ_hist: jnp.ndarray


def enabled() -> bool:
    """The kill-switch gate — a STATIC Python decision at trace time."""
    from tpu_pbrt.config import cfg

    return bool(cfg.telemetry)


def zeros() -> WaveCounters:
    """Fresh counter block (call inside jit: the arrays are staged)."""
    z = jnp.int32(0)
    return WaveCounters(
        rays=z,
        regenerated=z,
        terminated=z,
        deposits=z,
        compacted=z,
        nonfinite=z,
        occ_hist=jnp.zeros((N_OCC_BINS,), jnp.int32),
    )


def maybe_zeros() -> Optional[WaveCounters]:
    """zeros() when telemetry is on, None (empty pytree) when killed."""
    return zeros() if enabled() else None


def bounce_update(
    ctr: Optional[WaveCounters], *, alive, rays_before, rays_after
) -> Optional[WaveCounters]:
    """One trace wave's worth of counting, from inside `_bounce_wave`:
    rays dispatched this wave and the occupancy-histogram bin of the
    wave's live-lane fraction. `alive` is the pre-trace live mask (the
    lanes that actually cost traversal), rays_before/after the per-lane
    ray accumulators around the wave."""
    if ctr is None:
        return None
    width = alive.shape[0]
    live = jnp.sum(alive, dtype=jnp.int32)
    wave_rays = jnp.sum(rays_after - rays_before, dtype=jnp.int32)
    bin_ix = jnp.clip(live * N_OCC_BINS // width, 0, N_OCC_BINS - 1)
    return ctr._replace(
        rays=ctr.rays + wave_rays,
        occ_hist=ctr.occ_hist.at[bin_ix].add(1),
    )


def pool_update(
    ctr: Optional[WaveCounters], *, regenerated, terminated, deposits,
    compacted, nonfinite=None,
) -> Optional[WaveCounters]:
    """The drain-loop structural counters, from the `pool_chunk` body:
    each argument is this wave's int32 count. nonfinite is the firewall's
    scrubbed-deposit count (None keeps the field untouched)."""
    if ctr is None:
        return None
    upd = ctr._replace(
        regenerated=ctr.regenerated + regenerated,
        terminated=ctr.terminated + terminated,
        deposits=ctr.deposits + deposits,
        compacted=ctr.compacted + compacted,
    )
    if nonfinite is not None:
        upd = upd._replace(nonfinite=ctr.nonfinite + nonfinite)
    return upd


# -- host side (the one fetch at the drain boundary) -----------------------


def to_host(ctrs: Iterable[WaveCounters]) -> Dict[str, Any]:
    """Fetch a list of per-chunk counter blocks with ONE device_get and
    sum them into the canonical host dict (ints + histogram list)."""
    ctrs = list(ctrs)
    if not ctrs:
        return {}
    host = jax.device_get(ctrs)
    out: Dict[str, Any] = {k: 0 for k in HOST_FIELDS}
    out["occupancy_histogram"] = [0] * N_OCC_BINS
    for c in host:
        out["rays_traced"] += int(c.rays)
        out["lanes_regenerated"] += int(c.regenerated)
        out["lanes_terminated"] += int(c.terminated)
        out["film_deposits"] += int(c.deposits)
        out["lanes_compacted"] += int(c.compacted)
        out["nonfinite_deposits"] += int(c.nonfinite)
        hist = [int(v) for v in c.occ_hist]
        out["occupancy_histogram"] = [
            a + b for a, b in zip(out["occupancy_histogram"], hist)
        ]
    return out


def merge_host(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Sum two host counter dicts (checkpoint-resume seeding: the saved
    cumulative snapshot + this process's drain)."""
    if not a:
        return dict(b)
    if not b:
        return dict(a)
    out: Dict[str, Any] = {}
    for k in set(a) | set(b):
        va, vb = a.get(k), b.get(k)
        if isinstance(va, list) or isinstance(vb, list):
            va = va or []
            vb = vb or []
            n = max(len(va), len(vb))
            va = va + [0] * (n - len(va))
            vb = vb + [0] * (n - len(vb))
            out[k] = [int(x) + int(y) for x, y in zip(va, vb)]
        else:
            out[k] = int(va or 0) + int(vb or 0)
    return out


def spread_stats(per_device_waves) -> Dict[str, Any]:
    """Per-device wave-count spread (the ROADMAP multi-chip metric): how
    unevenly the independent per-device drains ran. rel_spread =
    (max - min) / mean; 0 on a single device or a perfectly even mesh."""
    waves = [int(w) for w in per_device_waves]
    if not waves:
        return {}
    mean = sum(waves) / len(waves)
    return {
        "per_device_waves": waves,
        "min": min(waves),
        "max": max(waves),
        "mean": mean,
        "rel_spread": (max(waves) - min(waves)) / max(mean, 1e-9),
    }
