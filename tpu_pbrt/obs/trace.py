"""Host-side span recorder with Chrome-trace/Perfetto JSON export.

Records named spans around the render phases the HOST can see — jit
build + first (compiling) dispatch, per-chunk wave-batch dispatches, the
drain sync that covers device execution and the mesh film psum/merge,
checkpoint writes, develop — into the Chrome trace-event format
(`chrome://tracing` / https://ui.perfetto.dev load it directly).

Since the dispatch window (ISSUE 13) the host timeline is genuinely
concurrent — up to `TPU_PBRT_PIPELINE` chunk-slices in flight while the
host does other jobs' work — so flat complete ("X") spans alone cannot
express causality. tpu-scope (ISSUE 15) adds the three Chrome-trace
event families that can:

- **trace/span ids**: `trace_id(seed)` mints a deterministic per-request
  id (the render service keys it by job id); `span_id()` mints a
  process-monotonic span id. Both ride in event `args`, and the service
  stamps them on flight-file lines and histogram exemplars too, so one
  id joins every artifact a job touched.
- **async spans** ("b"/"e" phases, paired by (cat, id)): a span that
  OUTLIVES the host stack frame that opened it — a chunk-slice from
  dispatch enqueue to retire sync, a job from submit to done, a queue
  wait across many scheduler steps. Overlapping slices at depth N render
  as overlapping tracks instead of a lie.
- **flow events** ("s"/"f" phases, bound by id): the causal arrow from a
  dispatch enqueue to the retire sync that completed it, drawn by
  Perfetto across the in-flight gap.

The recorder is a process-global (`TRACE`) configured by `--trace` on
main.py / bench.py or `TPU_PBRT_TRACE_PATH`; unconfigured (or with
`TPU_PBRT_TELEMETRY=0`) every call is a cheap no-op. Timestamps are
microseconds from recorder start, as the trace-event spec expects.
"""

from __future__ import annotations

import json
import os
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

#: event phases we emit/accept: complete span, instant, counter,
#: metadata, async begin/end, flow start/finish
_PHASES = ("X", "i", "C", "M", "b", "e", "s", "f")
#: phases that pair/bind by id (async by (cat, id); flow by (cat, id))
_ASYNC = ("b", "e")
_FLOW = ("s", "f")


class TraceRecorder:
    def __init__(self):
        self._events: List[Dict[str, Any]] = []
        self._path: Optional[str] = None
        from tpu_pbrt.utils.clock import WALL

        self._clock = WALL
        self._t0 = self._clock.monotonic()
        self._next_span = 0

    # -- configuration -----------------------------------------------------
    def configure(self, path: Optional[str]):
        """Set (or clear) the export path; the --trace flag lands here."""
        self._path = path or None

    def set_clock(self, clock=None):
        """Inject a time source (utils/clock.py; None restores the wall
        clock) and REBASE the timestamp origin onto it. The rebase is
        the load-bearing part: a VirtualClock's timeline starts near 0,
        and subtracting a wall-clock `_t0` captured at import would
        produce negative `ts` — which validate_trace rightly rejects.
        Rebasing keeps every recorder the explorer arms emitting
        monotone nonnegative virtual-time stamps."""
        from tpu_pbrt.utils.clock import WALL

        self._clock = clock if clock is not None else WALL
        self._t0 = self._clock.monotonic()

    @property
    def clock_kind(self) -> str:
        """"wall" or the injected clock's class name (lowercased) — the
        export stamps this so tools/scope.py can tell a virtual-time
        explorer trace from a production one."""
        from tpu_pbrt.utils.clock import WALL

        if self._clock is WALL:
            return "wall"
        kind = type(self._clock).__name__.lower().removesuffix("clock")
        return kind or "wall"

    @property
    def path(self) -> Optional[str]:
        from tpu_pbrt.config import cfg

        return self._path or cfg.trace_path

    @property
    def enabled(self) -> bool:
        from tpu_pbrt.config import cfg

        return bool(cfg.telemetry and self.path)

    def reset(self):
        self._events = []
        self._t0 = self._clock.monotonic()
        self._next_span = 0

    # -- ids ---------------------------------------------------------------
    @staticmethod
    def trace_id(seed: str) -> str:
        """Deterministic request/trace id from a caller-owned seed (the
        service seeds with the job id): a pure string function, so the
        same submit sequence mints the same ids run after run — the
        determinism contract exemplars and test assertions need."""
        return f"t:{seed}"

    def span_id(self) -> str:
        """Process-monotonic span id ("s1", "s2", ...). Monotonic (not
        random): deterministic given the recorded event sequence, and
        reset() restarts the counter with the event buffer."""
        self._next_span += 1
        return f"s{self._next_span}"

    # -- recording ---------------------------------------------------------
    def _now_us(self) -> float:
        # monotonic(): a non-perturbing read — recording a span must
        # never advance a virtual timeline (arming the trace cannot
        # change the scheduling decisions it observes)
        return (self._clock.monotonic() - self._t0) * 1e6

    @contextmanager
    def span(self, name: str, **args):
        """Record a complete ("ph": "X") span around the with-body."""
        if not self.enabled:
            yield
            return
        ts = self._now_us()
        try:
            yield
        finally:
            self._events.append({
                "name": name, "ph": "X", "ts": ts,
                "dur": self._now_us() - ts,
                "pid": 0, "tid": 0, "args": args,
            })

    def complete(self, name: str, dur_us: float, ts_us: Optional[float] = None,
                 **args):
        """Emit a complete span with an EXPLICIT duration — for windows
        whose extent is known but not bracketed by a host stack frame
        (the re-dispatch backoff window: its length is computed the
        moment it opens)."""
        if not self.enabled:
            return
        self._events.append({
            "name": name, "ph": "X",
            "ts": self._now_us() if ts_us is None else ts_us,
            "dur": max(float(dur_us), 0.0),
            "pid": 0, "tid": 0, "args": args,
        })

    def instant(self, name: str, **args):
        if not self.enabled:
            return
        self._events.append({
            "name": name, "ph": "i", "ts": self._now_us(),
            "pid": 0, "tid": 0, "s": "p", "args": args,
        })

    def counter(self, name: str, **values):
        """A "C" counter event — Perfetto plots these as tracks."""
        if not self.enabled:
            return
        self._events.append({
            "name": name, "ph": "C", "ts": self._now_us(),
            "pid": 0, "tid": 0, "args": values,
        })

    # -- async spans + flow events (tpu-scope) -----------------------------
    def _id_event(self, ph: str, name: str, id: str, cat: str, extra=None,
                  **args):
        ev = {
            "name": name, "ph": ph, "ts": self._now_us(),
            "pid": 0, "tid": 0, "id": str(id), "cat": cat, "args": args,
        }
        if extra:
            ev |= extra
        self._events.append(ev)

    def async_begin(self, name: str, id: str, cat: str = "job", **args):
        """Open an async span: lives until the matching `async_end` with
        the same (cat, id) — across stack frames, scheduler steps, and
        other jobs' interleaved work."""
        if self.enabled:
            self._id_event("b", name, id, cat, **args)

    def async_end(self, name: str, id: str, cat: str = "job", **args):
        if self.enabled:
            self._id_event("e", name, id, cat, **args)

    @contextmanager
    def async_span(self, name: str, id: str, cat: str = "job", **args):
        """Async b/e pair around the with-body — for callers that DO
        have a bracketing frame but want the span on an id-keyed async
        track (overlap-safe) instead of the flat X timeline."""
        self.async_begin(name, id, cat, **args)
        try:
            yield
        finally:
            self.async_end(name, id, cat)

    def flow_start(self, name: str, id: str, cat: str = "flow", **args):
        """Open a causal arrow: the matching `flow_finish` with the same
        (cat, id) is the event this one CAUSED (dispatch enqueue ->
        retire sync)."""
        if self.enabled:
            self._id_event("s", name, id, cat, **args)

    def flow_finish(self, name: str, id: str, cat: str = "flow", **args):
        if self.enabled:
            # bp=e: bind to the enclosing slice, not the next one
            self._id_event("f", name, id, cat, extra={"bp": "e"}, **args)

    # -- export ------------------------------------------------------------
    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace JSON; returns the path written (None if
        no path is configured). Rewrites the whole file each call, so
        incremental exports (per render) are safe and the last one wins."""
        path = path or self.path
        if not path:
            return None
        doc = {
            "traceEvents": self._events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "tpu-pbrt obs.trace",
                "clock": self.clock_kind,
            },
        }
        # atomic tmp+rename (the checkpoint.py pattern): a crash mid-
        # export must leave the previous valid export intact, not a
        # truncated JSON — the failure path is where the trace matters
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def maybe_export(self) -> Optional[str]:
        """export() iff enabled — the render loop's exit hook."""
        return self.export() if self.enabled else None


#: the process-wide recorder every phase reports into
TRACE = TraceRecorder()


# -- schema validation (tests + `python -m tpu_pbrt.obs` + CI smoke) -------


def _intervals_overlap(iv: List[tuple]) -> bool:
    iv = sorted(iv)
    return any(b_start < a_end for (_, a_end), (b_start, _) in zip(iv, iv[1:]))


def validate_trace(doc) -> List[str]:
    """Validate a Chrome-trace document (dict, or a path to one).
    Returns a list of problems; empty means the file loads in Perfetto.

    Beyond per-event schema, this checks the tpu-scope causality
    invariants (ISSUE 15 satellite — the pre-scope validator accepted a
    depth-2 trace whose overlapping slices had no async structure and no
    dispatch_ahead attribution at all):

    - async "b"/"e" events pair up per (cat, id): every begin has a
      later end, no end without an open begin;
    - flow "f" events bind to an earlier "s" with the same (cat, id),
      and every started flow finishes;
    - overlapping in-flight slice spans (async cat "slice") imply
      pipelined dispatch — such a trace must also carry at least one
      `*_ahead` dispatch-attribution span, or the phase attribution the
      overlap fraction is computed from has a hole.
    """
    errs: List[str] = []
    if isinstance(doc, str):
        try:
            with open(doc) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            return [f"unreadable trace file: {e}"]
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["top level must be an object with a traceEvents array"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not an array"]
    async_open: Dict[tuple, List[float]] = {}  # (cat, id) -> begin ts stack
    flow_open: Dict[tuple, int] = {}  # (cat, id) -> started - finished
    slice_spans: Dict[tuple, List[float]] = {}  # open slice begins
    slice_iv: List[tuple] = []  # completed (begin_ts, end_ts) slice spans
    has_ahead = False
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: not an object")
            continue
        name = ev.get("name")
        if not isinstance(name, str) or not name:
            errs.append(f"{where}: missing/empty name")
            name = ""
        ph = ev.get("ph")
        if ph not in _PHASES:
            errs.append(f"{where}: unknown phase {ph!r}")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errs.append(f"{where}: bad ts {ts!r}")
            ts = 0.0
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errs.append(f"{where}: complete span with bad dur {dur!r}")
            if name.endswith("_ahead"):
                has_ahead = True
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                errs.append(f"{where}: missing integer {key}")
        if ph in _ASYNC or ph in _FLOW:
            cat, aid = ev.get("cat"), ev.get("id")
            if not isinstance(cat, str) or not cat:
                errs.append(f"{where}: {ph!r} event without a cat")
                cat = ""
            if not isinstance(aid, str) or not aid:
                errs.append(f"{where}: {ph!r} event without an id")
                continue
            k = (cat, aid)
            if ph == "b":
                async_open.setdefault(k, []).append(ts)
                if cat == "slice":
                    slice_spans.setdefault(k, []).append(ts)
            elif ph == "e":
                if not async_open.get(k):
                    errs.append(
                        f"{where}: async end {name!r} ({cat}:{aid}) "
                        "without an open begin"
                    )
                else:
                    t_b = async_open[k].pop()
                    if cat == "slice" and slice_spans.get(k):
                        slice_spans[k].pop()
                        slice_iv.append((t_b, ts))
            elif ph == "s":
                flow_open[k] = flow_open.get(k, 0) + 1
            elif ph == "f":
                if flow_open.get(k, 0) <= 0:
                    errs.append(
                        f"{where}: flow finish {name!r} ({cat}:{aid}) "
                        "without a matching flow start"
                    )
                else:
                    flow_open[k] -= 1
    for (cat, aid), stack in async_open.items():
        for _ in stack:
            errs.append(f"async span ({cat}:{aid}) begun but never ended")
    for (cat, aid), n in flow_open.items():
        if n > 0:
            errs.append(f"flow ({cat}:{aid}) started but never finished")
    if _intervals_overlap(slice_iv) and not has_ahead:
        errs.append(
            "overlapping in-flight slice spans (pipeline depth > 1) but "
            "no *_ahead dispatch-attribution span anywhere in the trace"
        )
    return errs
