"""Host-side span recorder with Chrome-trace/Perfetto JSON export.

Records named spans around the render phases the HOST can see — jit
build + first (compiling) dispatch, per-chunk wave-batch dispatches, the
drain sync that covers device execution and the mesh film psum/merge,
checkpoint writes, develop — into the Chrome trace-event format
(`chrome://tracing` / https://ui.perfetto.dev load it directly).

The recorder is a process-global (`TRACE`) configured by `--trace` on
main.py / bench.py or `TPU_PBRT_TRACE_PATH`; unconfigured (or with
`TPU_PBRT_TELEMETRY=0`) every call is a cheap no-op. Timestamps are
microseconds from recorder start, as the trace-event spec expects.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

#: event phases we emit/accept: complete span, instant, counter, metadata
_PHASES = ("X", "i", "C", "M")


class TraceRecorder:
    def __init__(self):
        self._events: List[Dict[str, Any]] = []
        self._path: Optional[str] = None
        self._t0 = time.perf_counter()

    # -- configuration -----------------------------------------------------
    def configure(self, path: Optional[str]):
        """Set (or clear) the export path; the --trace flag lands here."""
        self._path = path or None

    @property
    def path(self) -> Optional[str]:
        from tpu_pbrt.config import cfg

        return self._path or cfg.trace_path

    @property
    def enabled(self) -> bool:
        from tpu_pbrt.config import cfg

        return bool(cfg.telemetry and self.path)

    def reset(self):
        self._events = []
        self._t0 = time.perf_counter()

    # -- recording ---------------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextmanager
    def span(self, name: str, **args):
        """Record a complete ("ph": "X") span around the with-body."""
        if not self.enabled:
            yield
            return
        ts = self._now_us()
        try:
            yield
        finally:
            self._events.append({
                "name": name, "ph": "X", "ts": ts,
                "dur": self._now_us() - ts,
                "pid": 0, "tid": 0, "args": args,
            })

    def instant(self, name: str, **args):
        if not self.enabled:
            return
        self._events.append({
            "name": name, "ph": "i", "ts": self._now_us(),
            "pid": 0, "tid": 0, "s": "p", "args": args,
        })

    def counter(self, name: str, **values):
        """A "C" counter event — Perfetto plots these as tracks."""
        if not self.enabled:
            return
        self._events.append({
            "name": name, "ph": "C", "ts": self._now_us(),
            "pid": 0, "tid": 0, "args": values,
        })

    # -- export ------------------------------------------------------------
    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace JSON; returns the path written (None if
        no path is configured). Rewrites the whole file each call, so
        incremental exports (per render) are safe and the last one wins."""
        path = path or self.path
        if not path:
            return None
        doc = {
            "traceEvents": self._events,
            "displayTimeUnit": "ms",
            "otherData": {"tool": "tpu-pbrt obs.trace"},
        }
        # atomic tmp+rename (the checkpoint.py pattern): a crash mid-
        # export must leave the previous valid export intact, not a
        # truncated JSON — the failure path is where the trace matters
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def maybe_export(self) -> Optional[str]:
        """export() iff enabled — the render loop's exit hook."""
        return self.export() if self.enabled else None


#: the process-wide recorder every phase reports into
TRACE = TraceRecorder()


# -- schema validation (tests + `python -m tpu_pbrt.obs` + CI smoke) -------


def validate_trace(doc) -> List[str]:
    """Validate a Chrome-trace document (dict, or a path to one).
    Returns a list of problems; empty means the file loads in Perfetto."""
    errs: List[str] = []
    if isinstance(doc, str):
        try:
            with open(doc) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            return [f"unreadable trace file: {e}"]
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["top level must be an object with a traceEvents array"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not an array"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errs.append(f"{where}: missing/empty name")
        ph = ev.get("ph")
        if ph not in _PHASES:
            errs.append(f"{where}: unknown phase {ph!r}")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errs.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errs.append(f"{where}: complete span with bad dur {dur!r}")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                errs.append(f"{where}: missing integer {key}")
    return errs
