"""Append-only JSONL flight recorder.

BENCH_r05 recorded `0.0` with nothing but "backend unreachable" — no
record of which phase died, how long the probe waited, or what the last
completed work looked like. The flight recorder fixes that class of
capture: every phase writes heartbeat lines (`{"t", "elapsed_s",
"phase", ...fields}`) to an append-only JSONL file, each line flushed to
disk immediately, so whatever kills the process leaves the full
phase timeline plus the last counter snapshot behind.

Process-global `FLIGHT`, configured by `TPU_PBRT_FLIGHT_PATH` or
programmatically (bench.py defaults a path so outage captures always
carry a diagnosis). Unconfigured or with `TPU_PBRT_TELEMETRY=0` the
heartbeats still track `last_phase` in memory (bench's outage JSON
reports it either way) but write nothing.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


class FlightRecorder:
    def __init__(self):
        self._path: Optional[str] = None
        from tpu_pbrt.utils.clock import WALL

        self._clock = WALL
        self._t0 = self._clock.peek()
        self.last_phase: Optional[str] = None
        self.last_counters: Optional[Dict[str, Any]] = None

    def configure(self, path: Optional[str], t0: Optional[float] = None):
        """t0 rebases elapsed_s (epoch seconds): a caller that heartbeat
        with its own writer before this module could import (bench's
        import-free probe phase) hands its start time over so one JSONL
        file keeps a single monotonic elapsed_s baseline."""
        self._path = path or None
        if t0 is not None:
            self._t0 = t0

    def set_clock(self, clock=None):
        """Inject a time source (utils/clock.py; None restores the wall
        clock) and rebase the elapsed_s baseline onto it. Under a
        VirtualClock every heartbeat stamps virtual seconds — monotone
        nondecreasing along the decision sequence — instead of
        interleaving real time.time() into the lines of a simulated
        run. peek(): flight recording must never advance the timeline
        it is observing."""
        from tpu_pbrt.utils.clock import WALL

        self._clock = clock if clock is not None else WALL
        self._t0 = self._clock.peek()

    @property
    def path(self) -> Optional[str]:
        from tpu_pbrt.config import cfg

        return self._path or cfg.flight_path

    @property
    def enabled(self) -> bool:
        from tpu_pbrt.config import cfg

        return bool(cfg.telemetry and self.path)

    def _maybe_rotate(self, path: str):
        """Growth cap (`TPU_PBRT_FLIGHT_MAX_MB`): single-file rotation at
        the flush boundary — when the file has grown past the cap it is
        renamed to `<path>.1` (the previous rotation, if any, is
        replaced) and appending restarts on a fresh file. A long-lived
        serve daemon keeps at most 2x the cap on disk instead of an
        unbounded JSONL; the tail of the timeline is always the readable
        pair (`<path>.1` then `<path>`)."""
        from tpu_pbrt.config import cfg

        cap_mb = cfg.flight_max_mb
        if not cap_mb or cap_mb <= 0:
            return
        try:
            if os.path.getsize(path) >= cap_mb * 1e6:
                os.replace(path, path + ".1")
        except OSError:
            # missing file (nothing to rotate) or an unwritable dir —
            # the heartbeat's own open() will surface/swallow that
            pass

    def _write(self, path: str, phase: str, fields: Dict[str, Any]):
        """One JSONL line to `path`: wall clock, elapsed seconds, phase,
        fields. Opened/flushed/closed per line — crash-safe by
        construction — behind the same rotation cap whichever file it
        lands in."""
        now = self._clock.peek()
        line = {
            "t": round(now, 3),
            "elapsed_s": round(now - self._t0, 3),
            "phase": phase,
        }
        # reserved keys win: a caller kwarg must not clobber the
        # recorder's monotonic elapsed_s baseline (or t/phase)
        for k, v in fields.items():
            if k not in line:
                line[k] = v
        try:
            self._maybe_rotate(path)
            with open(path, "a") as f:
                f.write(json.dumps(line) + "\n")
        except OSError:
            # a full/readonly disk must never kill the render it's
            # supposed to be diagnosing
            pass

    def heartbeat(self, phase: str, **fields):
        """One JSONL line on the main flight file."""
        self.last_phase = phase
        if not self.enabled:
            return
        self._write(self.path, phase, fields)

    def job_heartbeat(self, job_id: str, phase: str, **fields):
        """One JSONL line on the per-job flight file
        (`flight.<job>.jsonl` next to the main path). First-class seam:
        the render service used to re-arm `_path` around every per-job
        heartbeat, which made the `TPU_PBRT_FLIGHT_MAX_MB` cap apply
        only as a side effect of the swap (and left any other per-job
        writer uncapped). Per-job files sit behind the same
        single-rotation cap as the main one, by construction."""
        self.last_phase = phase
        if not self.enabled:
            return
        path = job_flight_path(self.path, job_id)
        if path:
            self._write(path, phase, fields)

    def counters(self, snapshot: Dict[str, Any], phase: str = "counters"):
        """Record the latest device-counter snapshot (the drain-boundary
        fetch) so a post-mortem knows the last completed work."""
        self.last_counters = dict(snapshot)
        self.heartbeat(phase, counters=snapshot)


def job_flight_path(base: Optional[str], job_id: str) -> Optional[str]:
    """Per-job flight file next to `base` — `flight.jsonl` ->
    `flight.<job>.jsonl`. The render service re-arms the recorder with
    this per job slice it dispatches: a shared default path (bench's
    BENCH_flight.jsonl) would interleave heartbeat lines from every
    concurrent job into one undiagnosable stream."""
    if not base:
        return None
    # splitext (not a raw '.' split): it only splits the BASENAME, so a
    # dotted directory (/tmp/run.1/flight) can't be mangled into a
    # nonexistent path whose writes the recorder would silently drop
    root, ext = os.path.splitext(base)
    return f"{root}.{job_id}{ext}"


FLIGHT = FlightRecorder()


# -- validation (tests + `python -m tpu_pbrt.obs` + CI smoke) --------------


def validate_flight(path: str, require_phases=None) -> List[str]:
    """Validate a flight-recorder JSONL file: every line parses, carries
    t/elapsed_s/phase, and (optionally) each phase in `require_phases`
    has >= 1 heartbeat. Returns a list of problems."""
    errs: List[str] = []
    phases_seen = set()
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"unreadable flight file: {e}"]
    if not lines:
        errs.append("flight file is empty (no heartbeats recorded)")
    for i, raw in enumerate(lines):
        if not raw.strip():
            continue
        where = f"line {i + 1}"
        try:
            rec = json.loads(raw)
        except ValueError as e:
            errs.append(f"{where}: not JSON: {e}")
            continue
        if not isinstance(rec, dict):
            errs.append(f"{where}: not an object")
            continue
        if not isinstance(rec.get("phase"), str) or not rec.get("phase"):
            errs.append(f"{where}: missing phase")
        else:
            phases_seen.add(rec["phase"])
        for key in ("t", "elapsed_s"):
            if not isinstance(rec.get(key), (int, float)):
                errs.append(f"{where}: missing numeric {key}")
    for phase in require_phases or ():
        if phase not in phases_seen:
            errs.append(
                f"required phase {phase!r} has no heartbeat "
                f"(saw: {sorted(phases_seen)})"
            )
    return errs
