"""tpu-trace: runtime telemetry for the renderer (ISSUE 4).

Four pieces, one per module:

- `counters`  — a device-side per-wave counter block (pure jnp state)
  threaded through the persistent-wavefront drain loop and fetched
  exactly once at the drain boundary, so the bounce loop stays
  transfer-guard-clean and retrace-free;
- `trace`     — a host-side span recorder with Chrome-trace/Perfetto
  JSON export (`--trace` on main.py / bench.py);
- `flight`    — an append-only JSONL flight recorder (phase heartbeats +
  counter snapshots + backend probe state) so an infra-outage capture
  carries a diagnosis instead of a bare error string;
- `rooflive`  — live-vs-static roofline cross-check of measured wave
  rates against the committed static budgets (analysis/budgets.json);
- `metrics`   — process-wide host-side metrics registry (ISSUE 10):
  counters/gauges/fixed-bucket histograms with bucket-derived
  percentiles, Prometheus text exposition, render-phase attribution
  and the serve SLO load-shedding inputs (`TPU_PBRT_METRICS=0` kills).

All of it is default-on behind `TPU_PBRT_TELEMETRY` (=0 kills it and
compiles the exact pre-telemetry device program); `python -m
tpu_pbrt.obs` validates exported trace/flight files (the CI smoke
stage's gate).

Submodules are resolved LAZILY: `counters` imports jax at module level,
and an eager import here would drag jax into every `tpu_pbrt.obs.*`
consumer — including bench.py's outage path, which must stay bounded
when the accelerator runtime itself is what's hanging.
"""

import importlib

_SUBMODULES = ("counters", "flight", "metrics", "rooflive", "trace")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"tpu_pbrt.obs.{name}")
    raise AttributeError(f"module 'tpu_pbrt.obs' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
