"""`python -m tpu_pbrt.obs` — validate exported telemetry artifacts.

    python -m tpu_pbrt.obs trace.json \
        --flight flight.jsonl --require-phases render,develop \
        --metrics metrics.prom --metrics-snapshot metrics.json

Exit 0 iff every named artifact validates: the trace JSON loads in
Perfetto (schema check, no browser needed), the flight JSONL carries
>= 1 heartbeat for every required phase, a `--metrics` exposition file
passes the Prometheus text-format lint (type lines, label escaping,
monotone cumulative bucket counts), and a `--metrics-snapshot` JSON
matches the registry snapshot schema. This is the CI smoke stage's
gate (tools/ci.sh) and is importable from tests via
trace.validate_trace / flight.validate_flight /
metrics.validate_exposition / metrics.validate_snapshot.

Extras:
  --fold-metrics   fold the trace's phase spans into a metrics registry
                   and print the per-phase summary (the offline half of
                   ROADMAP #1's fused-vs-jnp phase attribution)
  --metrics-selftest  exercise the registry end to end (record -> lint
                   exposition -> percentile math) with no render; the
                   tools/ci.sh metrics stage.
  --health         evaluate the tpu-scope health watchdog (obs/health.py)
                   over a --metrics-snapshot file (the registry-derived
                   conditions: slo_burn, nonfinite_spike; wedge/storm
                   need a live service — use the daemon's `health` verb)
                   and exit non-zero if any condition fires.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_pbrt.obs.flight import validate_flight
from tpu_pbrt.obs.trace import validate_trace


def metrics_selftest() -> int:
    """Registry smoke with zero renders: known observations in, validated
    exposition + exact percentile expectations out. Runs import-free of
    jax (obs.metrics is pure host Python), so it is safe in any CI leg."""
    from tpu_pbrt.obs import metrics as m

    # force_enabled: the selftest validates the registry itself, so the
    # live-render kill switch must not turn it into a silent no-op
    reg = m.MetricsRegistry(force_enabled=True)
    fails = []
    h = reg.histogram("selftest_seconds", "selftest latencies")
    # 100 observations landing in known buckets: 1..100 ms
    for i in range(1, 101):
        h.observe(i / 1000.0, tenant="alice" if i % 2 else 'bo"b\\x')
    c = reg.counter("selftest_total", "selftest events")
    c.inc(3, kind="a")
    c.inc(2, kind="b")
    reg.gauge("selftest_depth", "selftest depth").set(4, klass="0")
    p50 = h.percentile(0.5)
    p99 = h.percentile(0.99)
    if not (0.025 <= p50 <= 0.1):
        fails.append(f"p50 {p50} outside the covering buckets")
    if not (0.05 <= p99 <= 0.25):
        fails.append(f"p99 {p99} outside the covering buckets")
    text = reg.exposition()
    errs = m.validate_exposition(text)
    fails += [f"exposition: {e}" for e in errs]
    errs = m.validate_snapshot(reg.snapshot())
    fails += [f"snapshot: {e}" for e in errs]
    # determinism: a second registry fed the same events exposes the
    # same bytes
    reg2 = m.MetricsRegistry(force_enabled=True)
    h2 = reg2.histogram("selftest_seconds", "selftest latencies")
    for i in range(1, 101):
        h2.observe(i / 1000.0, tenant="alice" if i % 2 else 'bo"b\\x')
    c2 = reg2.counter("selftest_total", "selftest events")
    c2.inc(3, kind="a")
    c2.inc(2, kind="b")
    reg2.gauge("selftest_depth", "selftest depth").set(4, klass="0")
    if reg2.exposition() != text:
        fails.append("same events produced a different exposition")
    for f in fails:
        print(f"FAIL metrics-selftest: {f}", file=sys.stderr)
    if not fails:
        print(f"metrics selftest OK ({len(text.splitlines())} lines)")
    return 1 if fails else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpu_pbrt.obs")
    ap.add_argument(
        "trace", nargs="?", help="Chrome-trace JSON file to validate"
    )
    ap.add_argument(
        "--flight", default="", help="flight-recorder JSONL file to validate"
    )
    ap.add_argument(
        "--require-phases", default="",
        help="comma-separated phases the flight file must each have "
             ">= 1 heartbeat for",
    )
    ap.add_argument(
        "--min-spans", type=int, default=1,
        help="minimum number of trace events required (default 1)",
    )
    ap.add_argument(
        "--metrics", default="",
        help="Prometheus text exposition file to lint",
    )
    ap.add_argument(
        "--metrics-snapshot", default="",
        help="metrics registry JSON snapshot file to validate",
    )
    ap.add_argument(
        "--fold-metrics", action="store_true",
        help="fold the trace's phase spans into a registry and print the "
             "per-phase time-attribution summary",
    )
    ap.add_argument(
        "--metrics-selftest", action="store_true",
        help="run the registry selftest (record/lint/percentiles) and exit",
    )
    ap.add_argument(
        "--health", action="store_true",
        help="evaluate the health watchdog over --metrics-snapshot "
             "(registry-derived conditions) and exit non-zero if firing",
    )
    args = ap.parse_args(argv)
    if args.metrics_selftest:
        return metrics_selftest()
    if args.fold_metrics and not args.trace:
        ap.error("--fold-metrics needs a trace file to fold")
    if args.health and not args.metrics_snapshot:
        ap.error("--health needs --metrics-snapshot to evaluate")
    if not any((args.trace, args.flight, args.metrics,
                args.metrics_snapshot)):
        ap.error(
            "nothing to validate: pass a trace file, --flight, --metrics "
            "and/or --metrics-snapshot"
        )

    problems = []
    if args.trace:
        errs = validate_trace(args.trace)
        problems += [f"trace: {e}" for e in errs]
        if not errs:
            with open(args.trace) as f:
                n = len(json.load(f)["traceEvents"])
            if n < args.min_spans:
                problems.append(
                    f"trace: only {n} events (need >= {args.min_spans})"
                )
            else:
                print(f"trace OK: {args.trace} ({n} events)")
        if not errs and args.fold_metrics:
            from tpu_pbrt.obs import metrics as m

            # force_enabled: an explicitly requested OFFLINE replay must
            # work even when the capture ran under TPU_PBRT_METRICS=0
            reg = m.MetricsRegistry(force_enabled=True)
            folded = m.fold_trace(args.trace, reg)
            print(f"folded {folded} phase spans from {args.trace}")
            print(json.dumps(m.phase_summary(reg), indent=2))
    if args.flight:
        phases = [p for p in args.require_phases.split(",") if p]
        errs = validate_flight(args.flight, require_phases=phases)
        problems += [f"flight: {e}" for e in errs]
        if not errs:
            print(f"flight OK: {args.flight} (phases: {phases or 'any'})")
    if args.metrics:
        from tpu_pbrt.obs.metrics import validate_exposition

        try:
            with open(args.metrics) as f:
                errs = validate_exposition(f.read())
        except OSError as e:
            errs = [f"unreadable exposition file: {e}"]
        problems += [f"metrics: {e}" for e in errs]
        if not errs:
            print(f"metrics OK: {args.metrics}")
    if args.metrics_snapshot:
        from tpu_pbrt.obs.metrics import validate_snapshot

        errs = validate_snapshot(args.metrics_snapshot)
        problems += [f"metrics-snapshot: {e}" for e in errs]
        if not errs:
            print(f"metrics snapshot OK: {args.metrics_snapshot}")
        if not errs and args.health:
            from tpu_pbrt.obs.health import evaluate_snapshot

            rep = evaluate_snapshot(args.metrics_snapshot)
            print(json.dumps(rep.to_dict(), indent=2))
            if not rep.ok:
                problems += [
                    f"health: condition firing: {name}"
                    for name in rep.firing()
                ]

    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
