"""`python -m tpu_pbrt.obs` — validate exported telemetry artifacts.

    python -m tpu_pbrt.obs trace.json \
        --flight flight.jsonl --require-phases render,develop

Exit 0 iff every named artifact validates: the trace JSON loads in
Perfetto (schema check, no browser needed) and the flight JSONL carries
>= 1 heartbeat for every required phase. This is the CI smoke stage's
gate (tools/ci.sh) and is importable from tests via
trace.validate_trace / flight.validate_flight.
"""

from __future__ import annotations

import argparse
import sys

from tpu_pbrt.obs.flight import validate_flight
from tpu_pbrt.obs.trace import validate_trace


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpu_pbrt.obs")
    ap.add_argument(
        "trace", nargs="?", help="Chrome-trace JSON file to validate"
    )
    ap.add_argument(
        "--flight", default="", help="flight-recorder JSONL file to validate"
    )
    ap.add_argument(
        "--require-phases", default="",
        help="comma-separated phases the flight file must each have "
             ">= 1 heartbeat for",
    )
    ap.add_argument(
        "--min-spans", type=int, default=1,
        help="minimum number of trace events required (default 1)",
    )
    args = ap.parse_args(argv)
    if not args.trace and not args.flight:
        ap.error("nothing to validate: pass a trace file and/or --flight")

    problems = []
    if args.trace:
        errs = validate_trace(args.trace)
        problems += [f"trace: {e}" for e in errs]
        if not errs:
            import json

            with open(args.trace) as f:
                n = len(json.load(f)["traceEvents"])
            if n < args.min_spans:
                problems.append(
                    f"trace: only {n} events (need >= {args.min_spans})"
                )
            else:
                print(f"trace OK: {args.trace} ({n} events)")
    if args.flight:
        phases = [p for p in args.require_phases.split(",") if p]
        errs = validate_flight(args.flight, require_phases=phases)
        problems += [f"flight: {e}" for e in errs]
        if not errs:
            print(f"flight OK: {args.flight} (phases: {phases or 'any'})")

    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
