"""Live-vs-static roofline cross-check.

PR 3's jaxcost computes STATIC per-wave costs (bytes/FLOPs of one pool
drain wave, committed in analysis/budgets.json and emitted into every
BENCH JSON as static_bytes_per_wave / static_flops_per_wave). This
module closes the loop with the LIVE side: a capture measures how many
waves ran and how long they took, so

    live_bytes_per_sec = static_bytes_per_wave * waves / seconds

is the HBM bandwidth the drain actually sustained under the static
model, and dividing by the platform's peak HBM bandwidth gives the
roofline fraction — the `live_vs_static_ratio` next to the static
fields in the bench JSON. Readings:

- ratio near 1: the drain is HBM-bound exactly as the static model says
  (further wins need fewer bytes/wave, not scheduling);
- ratio << 1: waves are NOT paying their modeled bytes — occupancy,
  launch latency, or host stalls dominate (scheduling problem);
- ratio > 1: the static model over-counts (fusion is eliminating
  modeled traffic) — refresh the model's assumptions.

The ratio is null when the platform's peak is unknown (CPU captures —
the static half still carries the signal, per the BENCH_r05 lesson).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

#: peak HBM bandwidth per chip, bytes/s (public TPU spec sheets; used
#: only to normalize the live-implied bandwidth into a roofline fraction)
PLATFORM_HBM_BYTES_PER_SEC = {
    "v2": 700e9,
    "v3": 900e9,
    "v4": 1228e9,
    "v5e": 819e9,
    "v5 lite": 819e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
    "trillium": 1640e9,
}


def platform_hbm_peak(device_kind: Optional[str]) -> Optional[float]:
    """Peak HBM bytes/s for a jax device_kind string (substring match,
    longest key wins so "v5 lite"/"v5e" beat "v5"); None when unknown."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    best = None
    for key, peak in PLATFORM_HBM_BYTES_PER_SEC.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, peak)
    return best[1] if best else None


def live_vs_static(
    *,
    waves: Optional[int],
    seconds: Optional[float],
    static_bytes_per_wave: Optional[int] = None,
    static_flops_per_wave: Optional[int] = None,
    device_kind: Optional[str] = None,
    n_devices: int = 1,
) -> Dict[str, Any]:
    """The bench-JSON telemetry fields. Never raises: missing inputs
    null out the dependent fields (an outage capture still gets a
    well-formed block)."""
    out: Dict[str, Any] = {
        "live_bytes_per_sec": None,
        "live_flops_per_sec": None,
        "hbm_peak_bytes_per_sec": None,
        "live_vs_static_ratio": None,
    }
    if not waves or not seconds or seconds <= 0:
        return out
    wave_rate = waves / seconds
    if static_bytes_per_wave:
        out["live_bytes_per_sec"] = static_bytes_per_wave * wave_rate
    if static_flops_per_wave:
        out["live_flops_per_sec"] = static_flops_per_wave * wave_rate
    peak = platform_hbm_peak(device_kind)
    if peak and out["live_bytes_per_sec"]:
        total_peak = peak * max(n_devices, 1)
        out["hbm_peak_bytes_per_sec"] = total_peak
        out["live_vs_static_ratio"] = round(
            out["live_bytes_per_sec"] / total_peak, 6
        )
    return out


def load_static_budget(
    entry: str = "pool_chunk", budgets_path: Optional[str] = None
) -> Dict[str, Any]:
    """The committed static budget for an entry point (fallback when a
    caller has no bench-shaped static trace at hand). Returns {} when
    the file or entry is missing — advisory, never fatal."""
    path = (
        Path(budgets_path)
        if budgets_path
        else Path(__file__).resolve().parent.parent / "analysis" / "budgets.json"
    )
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError):
        return {}
    return dict(doc.get("entries", {}).get(entry, {}))
