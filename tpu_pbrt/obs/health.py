"""tpu-scope health watchdog: a deterministic evaluator over the
metrics registry and the render service's own state (ISSUE 15).

The serve daemon had no health surface: a wedged drain (runnable jobs,
no progress), a backoff storm (one job burning its retry budget), an
SLO burn (sheds outpacing admissions), or a nonfinite-deposit spike were
all invisible until a client timed out. This module turns those four
failure shapes into named, thresholded conditions:

- **wedge** — the service has made K consecutive `step()` calls while
  runnable jobs exist and no chunk cursor advanced. K is
  `TPU_PBRT_HEALTH_WEDGE_STEPS` (default 12 — comfortably above the
  longest clean no-progress streak a backoff window produces in the
  chaos matrix, and far below any client timeout).
- **backoff_storm** — some job's CURRENT failure streak has reached
  `storm_attempts` consecutive re-dispatch attempts (job.attempt resets
  to 0 on success, so this flags live storms, not history).
- **slo_burn** — sheds / (sheds + admitted submits) exceeds
  `slo_burn_fraction` with at least `slo_burn_min_sheds` sheds: the
  admission policy is refusing a sustained fraction of the offered
  load, not just clipping one burst.
- **nonfinite_spike** — the `render_nonfinite_total` registry counter
  (folded in at the serve drain boundaries) exceeds `nonfinite_max`
  scrubbed deposits: the firewall is hiding real contamination.

Everything is a PURE function of (service state, registry counters,
thresholds) — no wall clock, no rates-over-time, no randomness — so the
chaos matrix can assert exactly which rows fire it and the 13 clean
rows provably do not. Exposed as the `health` verb on the JSONL daemon
and `--health` on `python -m tpu_pbrt.obs` (which evaluates the
registry-derived half from a metrics snapshot file, no service needed).
"""

from __future__ import annotations

# jaxlint: disable-file=JL-SYNC
# (pure host-side evaluator: jaxlint's by-name call graph marks
# `evaluate` traced because core/film.py calls `f.evaluate(...)` inside
# a jitted splat loop — a different, filter-kernel `evaluate`. The
# float()/bool() casts here act on service counters and dataclass
# fields; no tracer can ever reach this module.)

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpu_pbrt.obs.metrics import METRICS, PREFIX, MetricsRegistry


@dataclass
class Thresholds:
    """The watchdog's knobs — all deterministic counts/fractions."""

    #: consecutive no-progress step() calls (with runnable jobs) = wedge
    wedge_steps: Optional[int] = None  # None -> cfg.health_wedge_steps

    #: a job's current consecutive re-dispatch attempts = backoff storm
    storm_attempts: int = 3

    #: shed fraction of offered load (with a shed floor) = SLO burn
    slo_burn_fraction: float = 0.5
    slo_burn_min_sheds: int = 3

    #: scrubbed non-finite deposits tolerated before the spike fires
    nonfinite_max: int = 0

    def resolved_wedge_steps(self) -> int:
        if self.wedge_steps is not None:
            return int(self.wedge_steps)
        from tpu_pbrt.config import cfg

        return int(cfg.health_wedge_steps)


@dataclass
class Condition:
    name: str
    firing: bool
    detail: str
    value: Optional[float] = None
    threshold: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "firing": self.firing, "detail": self.detail,
        }
        if self.value is not None:
            out["value"] = self.value
        if self.threshold is not None:
            out["threshold"] = self.threshold
        return out


@dataclass
class HealthReport:
    conditions: List[Condition] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not any(c.firing for c in self.conditions)

    def firing(self) -> List[str]:
        return [c.name for c in self.conditions if c.firing]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "firing": self.firing(),
            "conditions": [c.to_dict() for c in self.conditions],
        }


def _counter_total(registry: MetricsRegistry, name: str) -> float:
    """Sum of a counter across every label series, 0.0 if unregistered."""
    m = registry._metrics.get(PREFIX + name)
    if m is None or m.kind != "counter":
        return 0.0
    return float(sum(m._series.values()))


def _burn_condition(sheds: float, admits: float, th: Thresholds) -> Condition:
    offered = sheds + admits
    frac = sheds / offered if offered > 0 else 0.0
    firing = sheds >= th.slo_burn_min_sheds and frac > th.slo_burn_fraction
    return Condition(
        "slo_burn", firing,
        f"{int(sheds)} shed of {int(offered)} offered "
        f"({frac:.0%}; fires over {th.slo_burn_fraction:.0%} "
        f"with >= {th.slo_burn_min_sheds} sheds)",
        value=round(frac, 4), threshold=th.slo_burn_fraction,
    )


def _nonfinite_condition(total: float, th: Thresholds) -> Condition:
    return Condition(
        "nonfinite_spike", total > th.nonfinite_max,
        f"{int(total)} non-finite deposit(s) scrubbed "
        f"(tolerated: {th.nonfinite_max})",
        value=total, threshold=float(th.nonfinite_max),
    )


def evaluate(
    service=None,
    registry: MetricsRegistry = METRICS,
    thresholds: Optional[Thresholds] = None,
) -> HealthReport:
    """Evaluate every condition against a live service and/or the
    registry. `service=None` evaluates the registry-derived half only
    (wedge/storm report not-applicable rather than guessing)."""
    th = thresholds or Thresholds()
    rep = HealthReport()

    # -- wedge + backoff storm: service-state conditions -------------------
    if service is not None:
        from tpu_pbrt.serve.service import _RUNNABLE

        runnable = [
            j for j in service.jobs.values() if j.status in _RUNNABLE
        ]
        k = th.resolved_wedge_steps()
        gap = service.health_steps - service.last_progress_step
        rep.conditions.append(Condition(
            "wedge", bool(runnable) and gap >= k,
            f"{gap} step(s) since the last cursor advance with "
            f"{len(runnable)} runnable job(s) (fires at {k})",
            value=float(gap), threshold=float(k),
        ))
        storming = [
            j for j in service.jobs.values()
            if j.attempt >= th.storm_attempts
        ]
        worst = max((j.attempt for j in storming), default=0)
        rep.conditions.append(Condition(
            "backoff_storm", bool(storming),
            (
                f"job(s) {sorted(j.job_id for j in storming)} at "
                f">= {th.storm_attempts} consecutive re-dispatch attempts"
                if storming
                else "no job in a live retry streak"
            ),
            value=float(worst), threshold=float(th.storm_attempts),
        ))
    else:
        rep.conditions.append(Condition(
            "wedge", False, "n/a (no service attached)"
        ))
        rep.conditions.append(Condition(
            "backoff_storm", False, "n/a (no service attached)"
        ))

    # -- SLO burn + nonfinite spike: registry-derived ----------------------
    if registry.enabled:
        sheds = _counter_total(registry, "serve_shed_total")
        admits = _counter_total(registry, "serve_submits_total")
        if service is not None and not sheds and not admits:
            # metrics armed after the fact (or reset): the service's own
            # deterministic counts carry the same signal
            sheds = float(service.sheds)
            admits = float(service._seq)
        rep.conditions.append(_burn_condition(sheds, admits, th))
        rep.conditions.append(_nonfinite_condition(
            _counter_total(registry, "render_nonfinite_total"), th
        ))
    elif service is not None:
        rep.conditions.append(_burn_condition(
            float(service.sheds), float(service._seq), th
        ))
        rep.conditions.append(Condition(
            "nonfinite_spike", False, "n/a (metrics registry disabled)"
        ))
    else:
        rep.conditions.append(Condition(
            "slo_burn", False, "n/a (no service or registry)"
        ))
        rep.conditions.append(Condition(
            "nonfinite_spike", False, "n/a (no service or registry)"
        ))
    return rep


def evaluate_snapshot(
    doc: Any, thresholds: Optional[Thresholds] = None
) -> HealthReport:
    """Evaluate the registry-derived conditions from a metrics
    `snapshot()` document (dict, or a path to its JSON) — the offline
    half `python -m tpu_pbrt.obs --health` exposes: no live service, so
    wedge/storm are not applicable."""
    import json

    if isinstance(doc, str):
        with open(doc) as f:
            doc = json.load(f)
    th = thresholds or Thresholds()
    rep = HealthReport()
    rep.conditions.append(Condition(
        "wedge", False, "n/a (snapshot evaluation has no service state)"
    ))
    rep.conditions.append(Condition(
        "backoff_storm", False,
        "n/a (snapshot evaluation has no service state)",
    ))

    def total(name: str) -> float:
        m = (doc.get("metrics") or {}).get(PREFIX + name) or {}
        return float(sum(
            s.get("value", 0) or 0 for s in m.get("series", [])
        ))

    rep.conditions.append(_burn_condition(
        total("serve_shed_total"), total("serve_submits_total"), th
    ))
    rep.conditions.append(
        _nonfinite_condition(total("render_nonfinite_total"), th)
    )
    return rep
