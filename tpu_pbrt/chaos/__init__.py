"""Deterministic, declarative fault injection (ISSUE 5 tentpole).

The reference fork's whole reason to exist is surviving failure: workers
die mid-tile and the master re-assigns their work without corrupting the
film merge (SURVEY.md §2e). This package turns that claim into a testable
contract: a fault PLAN — a comma-separated spec like

    dispatch:poison@chunk=3,ckpt:torn@write=2,nan:wave@5&chunk=1,probe:hang@attempt=1

— is parsed into seeded, reproducible injection points wired into the
render loop's existing failure seams:

========  =======================  ==========================================
site      kinds                    seam
========  =======================  ==========================================
dispatch  fail | poison            the chunk-dispatch try block in
                                   integrators/common.render (fail = clean
                                   loss, re-dispatch is exact; poison = the
                                   in-flight film accumulator is untrusted)
mesh      lost                     same seam, but only fires on a mesh
                                   render — simulates a single-device loss
                                   in the drain (state-poisoning)
ckpt      torn | crash | bitflip   parallel/checkpoint.save_checkpoint
                                   (torn final file, crash between tmp
                                   write and rename, seeded bit-flip)
nan       wave                     the pool wave's radiance output in
                                   PathIntegrator.pool_chunk (NaN lanes —
                                   exercises the non-finite film firewall)
probe     hang                     bench.py's backend probe (simulated
                                   runtime hang; parsed import-free there,
                                   see bench._probe_hang_attempts)
========  =======================  ==========================================

Grammar: ``site:kind[@param[&param...]]`` where each param is ``k=v`` or a
bare value that binds to the site's default key (``chunk`` for
dispatch/mesh, ``write`` for ckpt, ``wave`` for nan, ``attempt`` for
probe). The reserved param ``times=N`` caps how often a fault fires
(default 1 — every injection point fires exactly once unless asked
otherwise), which is what makes recovery testable: the re-dispatch of a
faulted chunk runs clean, so the recovered film must be BIT-identical to
an undisturbed render (idempotent chunks + counter-based RNG).

Activation: the process-global ``CHAOS`` registry, installed from
``TPU_PBRT_FAULTS`` at import (config snapshot contract — a later
``config.reload()`` does NOT re-install), ``--faults`` on main.py, or
``CHAOS.install(...)`` directly (tests, the matrix runner). An empty
registry costs one attribute read per seam.

``python -m tpu_pbrt.chaos`` runs the recovery matrix: every scenario
against the cropped cornell scene on CPU, asserting bit-identity against
the undisturbed render (see __main__.py).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tpu_pbrt.config import cfg

#: legal kinds per site (parse-time validation: a typo'd plan must fail
#: loudly, not silently inject nothing)
SITE_KINDS: Dict[str, frozenset] = {
    "dispatch": frozenset({"fail", "poison"}),
    "mesh": frozenset({"lost"}),
    "ckpt": frozenset({"torn", "crash", "bitflip"}),
    "nan": frozenset({"wave"}),
    "probe": frozenset({"hang"}),
}

#: the key a bare ``@value`` binds to, per site
DEFAULT_KEY: Dict[str, str] = {
    "dispatch": "chunk",
    "mesh": "chunk",
    "ckpt": "write",
    "nan": "wave",
    "probe": "attempt",
}

#: legal param keys per site (plus the reserved ``times``): a typo'd key
#: would otherwise fall through the seams' .get(key, default) matching
#: and fire the fault somewhere other than where the plan claimed
SITE_PARAMS: Dict[str, frozenset] = {
    "dispatch": frozenset({"chunk", "attempt"}),
    "mesh": frozenset({"chunk", "attempt"}),
    "ckpt": frozenset({"write"}),
    "nan": frozenset({"wave", "chunk"}),
    "probe": frozenset({"attempt"}),
}


@dataclass
class Fault:
    """One parsed plan entry. ``fired`` counts actual injections; a fault
    stops matching once ``fired >= times`` — recovery re-runs see a clean
    world."""

    site: str
    kind: str
    params: Dict[str, int] = field(default_factory=dict)
    times: int = 1
    fired: int = 0

    def exhausted(self) -> bool:
        return self.fired >= self.times

    def spec(self) -> str:
        ps = "&".join(f"{k}={v}" for k, v in sorted(self.params.items()))
        if self.times != 1:
            ps = (ps + "&" if ps else "") + f"times={self.times}"
        return f"{self.site}:{self.kind}" + (f"@{ps}" if ps else "")


def parse_plan(spec: str) -> List[Fault]:
    """Parse a fault-plan string into Fault entries. Raises ValueError on
    unknown sites/kinds/params — a chaos plan that silently injects
    nothing would certify recovery that was never exercised."""
    faults: List[Fault] = []
    for entry in str(spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        head, _, tail = entry.partition("@")
        site, sep, kind = head.partition(":")
        site = site.strip()
        kind = kind.strip()
        if not sep or site not in SITE_KINDS:
            raise ValueError(
                f"chaos plan: unknown site in {entry!r} "
                f"(sites: {sorted(SITE_KINDS)})"
            )
        if kind not in SITE_KINDS[site]:
            raise ValueError(
                f"chaos plan: unknown kind {kind!r} for site {site!r} "
                f"(kinds: {sorted(SITE_KINDS[site])})"
            )
        params: Dict[str, int] = {}
        times = 1
        if tail:
            for part in tail.split("&"):
                part = part.strip()
                if not part:
                    continue
                k, eq, v = part.partition("=")
                if not eq:
                    # bare value -> the site's default key
                    k, v = DEFAULT_KEY[site], k
                try:
                    iv = int(v)
                except ValueError as e:
                    raise ValueError(
                        f"chaos plan: non-integer value in {entry!r}: {part!r}"
                    ) from e
                if k == "times":
                    times = iv
                elif k not in SITE_PARAMS[site]:
                    raise ValueError(
                        f"chaos plan: unknown param {k!r} for site "
                        f"{site!r} in {entry!r} "
                        f"(params: {sorted(SITE_PARAMS[site])} + times)"
                    )
                else:
                    params[k] = iv
        faults.append(Fault(site=site, kind=kind, params=params, times=times))
    return faults


def protocol_fault_space(n_chunks: int = 2) -> List[str]:
    """The fault plans the serve-protocol explorer (analysis layer 6,
    tools/explore.py) crosses its decision sequences with — drawn from
    THIS grammar so every explored fault schedule is also a plan a user
    can hand to --faults / TPU_PBRT_FAULTS and replay outside the
    explorer. Host-side sites only: dispatch fail/poison exercise the
    recovery ladder's clean-retry and rollback/restart arms, ckpt
    torn/crash exercise the .prev fallback under the deferred-write
    protocol. ("" = the undisturbed schedule every faulted end state is
    compared against.) Each entry is parse_plan-validated here, at
    definition time."""
    specs = [""]
    for c in range(max(int(n_chunks), 1)):
        specs.append(f"dispatch:fail@chunk={c}")
        specs.append(f"dispatch:poison@chunk={c}")
    specs.append("ckpt:torn@write=1")
    specs.append("ckpt:crash@write=1")
    for s in specs:
        parse_plan(s)
    return specs


class ChaosRegistry:
    """Process-global injection-point registry. All decisions are host-
    side and deterministic: plan + seed fully determine which dispatch
    raises, which checkpoint write tears, which byte flips, and which
    pool wave goes NaN. The only traced component is the nan-wave index,
    passed INTO the jitted chunk as an int32 argument (-1 = clean), so a
    re-dispatch after the fault fired compiles nothing new and runs the
    exact clean program."""

    def __init__(self):
        self._plan: List[Fault] = []
        self._hooks: List[Callable[[int, int], None]] = []
        self._ckpt_writes = 0
        self.seed = 0

    # -- lifecycle ---------------------------------------------------------
    def install(self, plan, seed: int = 0) -> "ChaosRegistry":
        """Install a plan (spec string or Fault list), replacing any
        previous one and resetting all fired/write counters."""
        self._plan = (
            parse_plan(plan) if isinstance(plan, str) else list(plan)
        )
        self._ckpt_writes = 0
        self.seed = int(seed)
        return self

    def clear(self) -> None:
        """Remove the plan and any registered hooks (test teardown)."""
        self._plan = []
        self._hooks = []
        self._ckpt_writes = 0

    def active(self) -> bool:
        return bool(self._plan) or bool(self._hooks)

    def plan(self) -> List[Fault]:
        return list(self._plan)

    def report(self) -> List[Dict[str, Any]]:
        """Fired accounting per fault (the matrix's fires-exactly-once
        evidence)."""
        return [
            {"fault": f.spec(), "fired": f.fired, "times": f.times}
            for f in self._plan
        ]

    def fired_total(self) -> int:
        return sum(f.fired for f in self._plan)

    # -- test-callable hooks (the promoted _fault_hook seam) ---------------
    def register_hook(self, fn: Callable[[int, int], None]) -> None:
        """Register a callable hook(chunk, attempt) run at every chunk
        dispatch — the first-class replacement for the old test-only
        ``integ._fault_hook`` monkeypatch. Hooks may raise
        ChunkDispatchError to inject arbitrary failures."""
        self._hooks.append(fn)

    # -- seams -------------------------------------------------------------
    def dispatch(self, chunk: int, attempt: int, mesh: bool = False) -> None:
        """The chunk-dispatch seam: raises ChunkDispatchError when the
        plan (or a registered hook) says this (chunk, attempt) fails.
        ``attempt`` param in the plan matches exactly when present, any
        attempt otherwise."""
        for hook in list(self._hooks):
            hook(chunk, attempt)
        for f in self._plan:
            if f.site not in ("dispatch", "mesh") or f.exhausted():
                continue
            if f.site == "mesh" and not mesh:
                continue
            if f.params.get("chunk", 0) != chunk:
                continue
            if "attempt" in f.params and f.params["attempt"] != attempt:
                continue
            f.fired += 1
            from tpu_pbrt.integrators.common import ChunkDispatchError

            poisons = f.kind in ("poison", "lost")
            raise ChunkDispatchError(
                f"chaos: injected {f.site}:{f.kind} at chunk {chunk} "
                f"(attempt {attempt})",
                poisons_state=poisons,
            )

    def checkpoint_fault(self) -> Optional[str]:
        """The save_checkpoint seam: counts this write (1-based, process-
        wide since install) and returns the fault kind to apply — 'torn',
        'crash', 'bitflip' — or None for a clean write."""
        self._ckpt_writes += 1
        for f in self._plan:
            if f.site != "ckpt" or f.exhausted():
                continue
            if f.params.get("write", 1) == self._ckpt_writes:
                f.fired += 1
                return f.kind
        return None

    def bitflip_offset(self, size: int) -> int:
        """Seeded byte offset for ckpt:bitflip — same plan + seed flips
        the same byte (the determinism contract)."""
        return zlib.crc32(f"bitflip:{self.seed}".encode()) % max(size, 1)

    def has_nan(self) -> bool:
        """STATIC trace-time query: does the plan contain a nan site at
        all? When True the pool chunk closure takes the extra nan_wave
        argument (program shape changes — part of the jit-cache key via
        trace_key)."""
        return any(f.site == "nan" for f in self._plan)

    def nan_wave_for(self, chunk: int) -> int:
        """Host-side per-dispatch decision: the wave index to contaminate
        in this chunk's drain, or -1 for a clean dispatch. Marks the
        fault fired — the re-dispatch of the same chunk runs clean."""
        for f in self._plan:
            if f.site != "nan" or f.exhausted():
                continue
            if f.params.get("chunk", 0) != chunk:
                continue
            f.fired += 1
            return int(f.params.get("wave", 0))
        return -1

    def probe_hang(self, attempt: int) -> bool:
        """The bench probe seam (kept in API parity with bench.py's
        import-free parser, which is what production bench actually uses
        — this method serves tests of the shared grammar)."""
        for f in self._plan:
            if f.site != "probe" or f.kind != "hang" or f.exhausted():
                continue
            if f.params.get("attempt", 1) == attempt:
                f.fired += 1
                return True
        return False

    def trace_key(self) -> tuple:
        """The part of the registry that changes TRACED program shape —
        only the presence of a nan site (the injection argument exists or
        not). Host-only faults (dispatch/ckpt/probe) never force a
        recompile."""
        return (self.has_nan(),)


#: the process-global registry
CHAOS = ChaosRegistry()

# Env activation (TPU_PBRT_FAULTS), read once at import like every other
# config knob. Tests and the matrix runner use CHAOS.install() directly.
if cfg.faults:
    CHAOS.install(cfg.faults)
