"""`python -m tpu_pbrt.chaos` — the deterministic recovery matrix.

Renders the small cornell scene on CPU once undisturbed, then replays it
under every chaos scenario — poisoned dispatch, clean re-dispatch, torn /
crashed / bit-flipped checkpoint writes, corrupt-checkpoint resume, NaN
wave, retry-budget exhaustion, mesh device loss — asserting that each
recovery converges to a final film **bit-identical** to the undisturbed
render (chunks are idempotent pure functions of the work range and the
counter-based RNG is replay-exact, so recovery is EXACT, not
approximate). The one deliberate exception is `nan-wave-scrub`, which
validates the DEGRADE semantics instead: the firewall zeroes the
contaminated deposits, the final image stays fully finite, and
`nonfinite_deposits > 0` is reported in telemetry.

The matrix is also the health watchdog's truth table (ISSUE 15): the
`serve-wedge` and `serve-backoff-storm` rows inject serve drains the
watchdog MUST flag, and every other (clean) row asserts it stays
silent — a false-positive gate run after each pass.

The fleet rows (ISSUE 20) extend the ladder across replicas: `fleet-
replica-kill` kills a serve replica mid-job and asserts the job
resumes on the survivor from the durable spool bit-identically, and
`fleet-router-restart` restarts the ROUTER, adopts the same replicas
from their `stats` verbs, and drains every job to the same bits.

This is the SURVEY §2e fault-tolerance claim turned into a gate: it runs
in tools/ci.sh after the telemetry smoke stage, with no accelerator
required.

    python -m tpu_pbrt.chaos            # full matrix
    python -m tpu_pbrt.chaos --list     # scenario names
    python -m tpu_pbrt.chaos --only torn-ckpt-fallback,nan-wave-scrub
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

# matrix workload: small enough to compile fast at opt level 0, big
# enough for 8 chunks (the recovery ladder needs chunk structure)
RES = int(os.environ.get("CHAOS_RES", "20"))
SPP = int(os.environ.get("CHAOS_SPP", "4"))
MAXDEPTH = 3
N_CHUNKS = 8
CHUNK = RES * RES * SPP // N_CHUNKS

#: cached undisturbed renders (film arrays + ray count), keyed by mesh size
_REFS = {}


def _setup_env():
    """Process env for a standalone `python -m tpu_pbrt.chaos` run —
    BEFORE jax/tpu_pbrt import: CPU backend, virtual 8-device mesh, fast
    XLA pipeline (test renders are tiny; LLVM optimization is the cost),
    snappy deterministic retry backoff."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    if "xla_backend_optimization_level" not in flags:
        flags += " --xla_backend_optimization_level=0"
    os.environ["XLA_FLAGS"] = flags
    os.environ.setdefault("JAX_ENABLE_X64", "0")


@contextlib.contextmanager
def _env(**overrides):
    """Set TPU_PBRT_* knobs for one scenario and resync the config
    snapshot (the same seam tests/conftest.py uses — the matrix is test
    tooling, not production code)."""
    from tpu_pbrt import config

    old = {k: os.environ.get(k) for k in overrides}
    os.environ.update({k: str(v) for k, v in overrides.items()})
    config.reload()
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()


def _fresh():
    from tpu_pbrt.scenes import compile_api, make_cornell

    api = make_cornell(
        res=RES, spp=SPP, integrator="path", maxdepth=MAXDEPTH
    )
    return compile_api(api)


def _film(result):
    import jax
    import numpy as np

    st = jax.device_get(result.film_state)
    return [
        np.asarray(st.rgb), np.asarray(st.weight), np.asarray(st.splat)
    ]


def _identical(a, b) -> bool:
    import numpy as np

    return all(np.array_equal(x, y) for x, y in zip(a, b))


def _run(plan=None, seed=0, ckpt=None, ckpt_every=1, mesh_n=0, env=None):
    """One render under a chaos plan. Returns (result_or_exception,
    CHAOS fired report). The registry is always cleared afterwards."""
    from tpu_pbrt.chaos import CHAOS

    overrides = {
        "TPU_PBRT_CHUNK": CHUNK,
        "TPU_PBRT_RETRY_BACKOFF": os.environ.get(
            "TPU_PBRT_RETRY_BACKOFF", "0.01"
        ),
    }
    overrides.update(env or {})
    with _env(**overrides):
        if plan:
            CHAOS.install(plan, seed=seed)
        try:
            scene, integ = _fresh()
            kw = {}
            if ckpt:
                kw = dict(checkpoint_path=ckpt, checkpoint_every=ckpt_every)
            if mesh_n:
                from tpu_pbrt.parallel.mesh import make_mesh

                out = integ.render(scene, mesh=make_mesh(mesh_n), **kw)
            else:
                out = integ.render(scene, **kw)
        except Exception as e:  # noqa: BLE001 — scenario asserts on it
            out = e
        finally:
            rep = CHAOS.report()
            CHAOS.clear()
    return out, rep


def _reference(mesh_n=0):
    if mesh_n not in _REFS:
        r, _ = _run(mesh_n=mesh_n)
        if isinstance(r, Exception):
            raise r
        _REFS[mesh_n] = (_film(r), r.rays_traced)
    return _REFS[mesh_n]


def _check_recovered(r, rep, *, mesh_n=0, want_fired=None) -> tuple:
    """Shared postcondition: every fault fired the expected number of
    times and the final film is bit-identical to the undisturbed one."""
    if isinstance(r, Exception):
        return False, f"render raised {type(r).__name__}: {r}"
    fired = {e["fault"]: e["fired"] for e in rep}
    for spec, want in (want_fired or {}).items():
        got = next(
            (v for k, v in fired.items() if k.startswith(spec)), None
        )
        if got != want:
            return False, f"fault {spec} fired {got}, wanted {want}"
    ref_film, ref_rays = _reference(mesh_n)
    if not _identical(_film(r), ref_film):
        return False, "final film NOT bit-identical to undisturbed render"
    if r.rays_traced != ref_rays:
        return False, f"rays_traced {r.rays_traced} != {ref_rays}"
    return True, f"bit-identical; fired={fired}"


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def scen_clean_redispatch(tmp):
    """A chunk dispatch dies WITHOUT touching the film (worker loss
    before the dispatch ran): plain re-dispatch is exact."""
    r, rep = _run(plan="dispatch:fail@chunk=1")
    return _check_recovered(r, rep, want_fired={"dispatch:fail": 1})


def scen_poison_rollback(tmp):
    """A mid-dispatch loss poisons the film accumulator: roll back to
    the last durable checkpoint and replay."""
    r, rep = _run(
        plan="dispatch:poison@chunk=3",
        ckpt=os.path.join(tmp, "film.ckpt"),
    )
    ok, detail = _check_recovered(r, rep, want_fired={"dispatch:poison": 1})
    if ok and r.stats.get("recovery", {}).get("rollbacks") != 1:
        return False, "expected exactly 1 checkpoint rollback"
    return ok, detail


def scen_poison_restart(tmp):
    """Poisoning failure with NO checkpoint configured: the only safe
    recovery is a from-scratch restart — still exact."""
    r, rep = _run(plan="dispatch:poison@chunk=2")
    ok, detail = _check_recovered(r, rep, want_fired={"dispatch:poison": 1})
    if ok and r.stats.get("recovery", {}).get("restarts") != 1:
        return False, "expected exactly 1 restart"
    return ok, detail


def scen_torn_ckpt_fallback(tmp):
    """Checkpoint write 3 publishes a TORN file; the poisoning failure
    that follows must fall back to the rotated .prev and still recover
    exactly."""
    r, rep = _run(
        plan="ckpt:torn@write=3,dispatch:poison@chunk=3",
        ckpt=os.path.join(tmp, "film.ckpt"),
    )
    return _check_recovered(
        r, rep, want_fired={"ckpt:torn": 1, "dispatch:poison": 1}
    )


def scen_crash_ckpt_write(tmp):
    """Simulated crash between the tmp write and the rename: the write
    simply never happened; recovery uses the previous durable file."""
    r, rep = _run(
        plan="ckpt:crash@write=3,dispatch:poison@chunk=3",
        ckpt=os.path.join(tmp, "film.ckpt"),
    )
    return _check_recovered(
        r, rep, want_fired={"ckpt:crash": 1, "dispatch:poison": 1}
    )


def scen_bitflip_ckpt_fallback(tmp):
    """A bit-flipped checkpoint fails the v4 content checksum at load;
    rollback falls back to .prev."""
    r, rep = _run(
        plan="ckpt:bitflip@write=3,dispatch:poison@chunk=3",
        ckpt=os.path.join(tmp, "film.ckpt"),
    )
    return _check_recovered(
        r, rep, want_fired={"ckpt:bitflip": 1, "dispatch:poison": 1}
    )


def scen_nan_wave_retry(tmp):
    """A NaN wave under TPU_PBRT_NONFINITE=retry: the firewall detects
    the scrubbed deposits at the chunk boundary, the chunk is treated as
    poisoned and re-rendered clean — recovery is EXACT."""
    r, rep = _run(
        plan="nan:wave@1&chunk=1",
        ckpt=os.path.join(tmp, "film.ckpt"),
        env={"TPU_PBRT_NONFINITE": "retry"},
    )
    ok, detail = _check_recovered(r, rep, want_fired={"nan:wave": 1})
    if ok and r.stats.get("recovery", {}).get("nonfinite_retries") != 1:
        return False, "expected exactly 1 firewall retry"
    return ok, detail


def scen_nan_wave_scrub(tmp):
    """A NaN wave under the DEFAULT scrub mode: degrade, don't die — the
    final image is fully finite and the contamination is counted in
    nonfinite_deposits (the acceptance telemetry signal). Deliberately
    NOT bit-identical: the scrubbed samples deposited zero."""
    import numpy as np

    r, rep = _run(plan="nan:wave@1&chunk=1")
    if isinstance(r, Exception):
        return False, f"render raised {type(r).__name__}: {r}"
    fired = sum(e["fired"] for e in rep)
    if fired != 1:
        return False, f"nan fault fired {fired} times, wanted 1"
    img = np.asarray(r.image)
    if not np.isfinite(img).all():
        return False, "final image carries non-finite pixels"
    nf = (
        r.stats.get("telemetry", {})
        .get("counters", {})
        .get("nonfinite_deposits", 0)
    )
    if not nf > 0:
        return False, f"nonfinite_deposits = {nf}, wanted > 0"
    return True, f"image finite; nonfinite_deposits={nf}"


def _run_exhaustion(tmp):
    """Shared phase 1 for the exhaustion scenarios: chunk 5 fails every
    attempt, the retry budget (2) exhausts, and the loop writes an
    emergency checkpoint before raising."""
    ck = os.path.join(tmp, "film.ckpt")
    r, rep = _run(
        plan="dispatch:fail@chunk=5&times=99",
        ckpt=ck,
        env={"TPU_PBRT_RETRY_MAX": "2"},
    )
    if not isinstance(r, RuntimeError):
        return ck, f"expected RuntimeError, got {type(r).__name__}"
    from tpu_pbrt.parallel.checkpoint import load_checkpoint

    _, cursor, _, _ = load_checkpoint(ck)
    if cursor != 5:
        return ck, f"emergency checkpoint cursor {cursor}, wanted 5"
    return ck, None


def scen_exhaustion_emergency_resume(tmp):
    """Retry-budget exhaustion: the render dies loudly, but the
    emergency checkpoint preserves every completed chunk — a later
    resume finishes the job bit-identically."""
    ck, err = _run_exhaustion(tmp)
    if err:
        return False, err
    r2, rep2 = _run(ckpt=ck)  # no plan: the infra 'recovered'
    return _check_recovered(r2, rep2)


def scen_corrupt_resume(tmp):
    """Corrupt-checkpoint resume: the current checkpoint file is
    bit-flipped ON DISK after the crash; the resume must fall back to
    .prev and re-render the missing chunks exactly."""
    ck, err = _run_exhaustion(tmp)
    if err:
        return False, err
    size = os.path.getsize(ck)
    with open(ck, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    r2, rep2 = _run(ckpt=ck)
    return _check_recovered(r2, rep2)


def scen_mesh_device_loss(tmp):
    """Single-device loss in the mesh drain (simulated: the whole SPMD
    dispatch fails as state-poisoning — see parallel/mesh.py's failure
    model): rollback + re-dispatch on the virtual CPU mesh recovers
    bit-identically to the undisturbed MESH render."""
    import jax

    if len(jax.devices()) < 4:
        return True, "SKIP: needs >= 4 devices"
    r, rep = _run(
        plan="mesh:lost@chunk=1",
        ckpt=os.path.join(tmp, "film.ckpt"),
        mesh_n=4,
    )
    return _check_recovered(
        r, rep, mesh_n=4, want_fired={"mesh:lost": 1}
    )


def scen_fused_tracer(tmp):
    """Fused-wavefront tracer swap (ISSUE 9): the TPU_PBRT_FUSED=1
    program (Pallas flush/expand kernels, interpret mode on CPU) must
    render BIT-identical to the jnp path — through a mid-render
    dispatch failure, so the recovery ladder runs over the fused
    program too. Uses a killeroo-like scene: the matrix's cornell box
    compiles to the brute MXU path and would never touch the stream
    tracer the fused kernels live in."""
    import numpy as np

    from tpu_pbrt.chaos import CHAOS

    def render(fused, plan=None):
        with _env(TPU_PBRT_CHUNK=CHUNK, TPU_PBRT_FUSED=fused,
                  TPU_PBRT_RETRY_BACKOFF="0.01"):
            if plan:
                CHAOS.install(plan, seed=0)
            try:
                from tpu_pbrt.scenes import compile_api, make_killeroo_like

                api = make_killeroo_like(
                    res=16, spp=2, integrator="path", maxdepth=3,
                    n_theta=24, n_phi=48,
                )
                scene, integ = compile_api(api)
                out = integ.render(scene)
            finally:
                rep = CHAOS.report()
                CHAOS.clear()
        return out, rep

    ref, _ = render("0")
    r, rep = render("1", plan="dispatch:fail@chunk=1")
    fired = {e["fault"]: e["fired"] for e in rep}
    if sum(fired.values()) != 1:
        return False, f"dispatch fault fired {fired}, wanted 1"
    if r.stats.get("tracer_mode") != "fused":
        return False, f"tracer_mode={r.stats.get('tracer_mode')!r}, wanted 'fused'"
    if not _identical(_film(r), _film(ref)):
        return False, "fused film NOT bit-identical to jnp render"
    if r.rays_traced != ref.rays_traced:
        return False, f"rays {r.rays_traced} != {ref.rays_traced}"
    return True, f"fused == jnp bit-identical; fired={fired}"


def scen_pipeline(tmp):
    """Async pipelined dispatch (ISSUE 13): a poisoning dispatch loss
    with TPU_PBRT_PIPELINE=3 slices in flight — the window is flushed,
    the loop rolls back to the last durable checkpoint (whose cadence
    writes were DEFERRED under in-flight compute via the film
    snapshot) and the recovered film is bit-identical to the
    undisturbed render. Pins the tentpole's two contracts at once:
    depth-N == depth-1 bits, and the recovery ladder carrying over
    unchanged with a non-empty window."""
    r, rep = _run(
        plan="dispatch:poison@chunk=3",
        ckpt=os.path.join(tmp, "film.ckpt"),
        env={"TPU_PBRT_PIPELINE": "3"},
    )
    ok, detail = _check_recovered(r, rep, want_fired={"dispatch:poison": 1})
    if ok and r.stats.get("recovery", {}).get("rollbacks") != 1:
        return False, "expected exactly 1 checkpoint rollback"
    return ok, detail


def _serve_retry_storm(steps, env):
    """Shared rig for the watchdog rows: a serve job whose chunk-0
    dispatch fails EVERY attempt (times=99) with zero retry backoff and
    an unreachable retry budget — `steps` scheduler steps of pure
    no-progress retrying, then the health verdict. Returns (service,
    HealthReport) evaluated INSIDE the env overrides."""
    from tpu_pbrt.chaos import CHAOS
    from tpu_pbrt.obs.health import evaluate
    from tpu_pbrt.obs.metrics import METRICS

    overrides = {
        "TPU_PBRT_CHUNK": CHUNK,
        "TPU_PBRT_RETRY_BACKOFF": "0",
        "TPU_PBRT_RETRY_MAX": "999",
    }
    overrides.update(env or {})
    with _env(**overrides):
        from tpu_pbrt.serve.service import RenderService

        METRICS.reset()
        scene, integ = _fresh()
        service = RenderService(quiet=True)
        service.submit(compiled=(scene, integ), tenant="chaos")
        CHAOS.install("dispatch:fail@chunk=0&times=99", seed=0)
        try:
            for _ in range(steps):
                service.step()
            rep = evaluate(service)
        finally:
            CHAOS.clear()
            METRICS.reset()
    return service, rep


def scen_serve_wedge(tmp):
    """Health-watchdog row (ISSUE 15): a serve drain that retries the
    same chunk forever — runnable work, K+ step() calls, no cursor
    advance — MUST flag `wedge` (the failure mode that previously only
    surfaced as a client timeout)."""
    from tpu_pbrt.obs.health import Thresholds

    k = Thresholds().resolved_wedge_steps()
    service, rep = _serve_retry_storm(steps=k + 2, env=None)
    if service.last_progress_step != 0:
        return False, "rig broke: the wedged job made progress"
    if "wedge" not in rep.firing():
        return False, f"wedge NOT flagged after {k + 2} stuck steps: {rep.to_dict()}"
    return True, f"flagged {rep.firing()} after {k + 2} stuck steps"


def scen_serve_backoff_storm(tmp):
    """Health-watchdog row: the SAME retry streak caught EARLY — enough
    steps for the job's live attempt counter to cross the storm
    threshold, but well inside the wedge window. `backoff_storm` must
    flag; `wedge` must NOT (the two conditions separate a hot retry
    loop from a dead drain)."""
    from tpu_pbrt.obs.health import Thresholds

    th = Thresholds()
    steps = th.storm_attempts + 1
    if steps >= th.resolved_wedge_steps():
        return False, "rig broke: storm window not inside wedge window"
    service, rep = _serve_retry_storm(steps=steps, env=None)
    job = next(iter(service.jobs.values()))
    if job.attempt < th.storm_attempts:
        return False, f"rig broke: attempt {job.attempt} under threshold"
    if "backoff_storm" not in rep.firing():
        return False, f"backoff_storm NOT flagged: {rep.to_dict()}"
    if "wedge" in rep.firing():
        return False, f"wedge flagged {steps} steps in (threshold "  \
            f"{th.resolved_wedge_steps()}): {rep.to_dict()}"
    return True, f"flagged {rep.firing()} at attempt {job.attempt}"


def _fleet_rig(tmp):
    """Shared rig for the fleet rows: two real in-process replicas under
    one VirtualClock behind a FleetRouter, matrix chunking on both sides
    so the failover resume replays the exact chunk boundaries the
    undisturbed reference used."""
    from tpu_pbrt.fleet.router import FleetRouter, LocalReplica
    from tpu_pbrt.utils.clock import VirtualClock

    clock = VirtualClock(start=0.0, tick=1e-6)
    fleet = [
        LocalReplica(
            rid, clock=clock, chunk=CHUNK,
            spool_dir=os.path.join(tmp, rid),
        )
        for rid in ("r0", "r1")
    ]
    router = FleetRouter(
        fleet, clock=clock, spool_dir=os.path.join(tmp, "fleet"),
    )
    return clock, fleet, router


def scen_fleet_replica_kill(tmp):
    """Fleet failover row (ISSUE 20): a replica is KILLED mid-job past a
    durable checkpoint; the router fails the job over to the survivor,
    which resumes from the spool — the final film must be bit-identical
    to the undisturbed render (chunks are idempotent, the cursor is
    durable, and film accumulation from the cursor is sequential)."""
    from tpu_pbrt.obs.metrics import METRICS
    from tpu_pbrt.serve.service import DONE

    with _env(TPU_PBRT_CHUNK=CHUNK, TPU_PBRT_RETRY_BACKOFF="0.01"):
        METRICS.reset()
        _, _, router = _fleet_rig(tmp)
        try:
            scene, integ = _fresh()
            job = router.submit(
                compiled=(scene, integ), resident_key="chaos:cornell",
                checkpoint_every=1, tenant="chaos",
            )
            victim = router.owner(job)
            survivor = "r1" if victim == "r0" else "r0"
            for _ in range(4 * N_CHUNKS):
                if router.poll(job)["chunks_done"] >= 2:
                    break
                if router.step() is None:
                    return False, "no progress before the kill"
            else:
                return False, "never reached chunk 2 before the kill"
            at_kill = router.poll(job)["chunks_done"]
            moved = router.kill_replica(victim)
            if moved != [job]:
                return False, f"failover moved {moved}, wanted [{job!r}]"
            if router.owner(job) != survivor:
                return False, (
                    f"{job} on {router.owner(job)}, wanted {survivor}"
                )
            router.drain_fleet()
            p = router.poll(job)
            if p["status"] != DONE:
                return False, f"job ended {p['status']!r} after failover"
            r = router.result(job)
        finally:
            METRICS.reset()
    ref_film, _ = _reference()
    if not _identical(_film(r), ref_film):
        return False, (
            "failover film NOT bit-identical to undisturbed render"
        )
    return True, (
        f"bit-identical after kill({victim})->resume({survivor}) "
        f"at chunk {at_kill} ({p['failovers']} failover)"
    )


def scen_fleet_router_restart(tmp):
    """Fleet restart row (ISSUE 20): the ROUTER dies between decisions
    and a fresh one adopts the same replicas, rebuilding its routing
    table from each replica's `stats` verb — no job is lost, the drain
    completes every adopted job, and the films stay bit-identical."""
    from tpu_pbrt.fleet.router import FleetRouter
    from tpu_pbrt.obs.metrics import METRICS
    from tpu_pbrt.serve.service import DONE

    with _env(TPU_PBRT_CHUNK=CHUNK, TPU_PBRT_RETRY_BACKOFF="0.01"):
        METRICS.reset()
        clock, fleet, router = _fleet_rig(tmp)
        try:
            scene, integ = _fresh()
            jobs = [
                router.submit(
                    compiled=(scene, integ),
                    resident_key=f"chaos:cornell{i}",
                    checkpoint_every=1, tenant="chaos",
                )
                for i in range(2)
            ]
            for _ in range(3):  # some mid-flight progress, then "crash"
                router.step()
            router2 = FleetRouter.adopt(
                fleet, clock=clock,
                spool_dir=os.path.join(tmp, "fleet"),
            )
            lost = [j for j in jobs if j not in router2.jobs]
            if lost:
                return False, f"adopt lost job(s): {lost}"
            for j in jobs:
                if router2.owner(j) != router.owner(j):
                    return False, (
                        f"adopt re-homed {j}: {router.owner(j)} -> "
                        f"{router2.owner(j)}"
                    )
            router2.drain_fleet()
            polls = {j: router2.poll(j) for j in jobs}
            bad = {j: p["status"] for j, p in polls.items()
                   if p["status"] != DONE}
            if bad:
                return False, f"adopted job(s) did not finish: {bad}"
            films = [_film(router2.result(j)) for j in jobs]
        finally:
            METRICS.reset()
    ref_film, _ = _reference()
    for j, film in zip(jobs, films):
        if not _identical(film, ref_film):
            return False, f"{j}: film NOT bit-identical after restart"
    return True, (
        f"{len(jobs)} job(s) adopted across a router restart, "
        "all bit-identical"
    )


SCENARIOS = {
    "fused-tracer": scen_fused_tracer,
    "pipeline": scen_pipeline,
    "clean-redispatch": scen_clean_redispatch,
    "poison-rollback": scen_poison_rollback,
    "poison-restart": scen_poison_restart,
    "torn-ckpt-fallback": scen_torn_ckpt_fallback,
    "crash-ckpt-write": scen_crash_ckpt_write,
    "bitflip-ckpt-fallback": scen_bitflip_ckpt_fallback,
    "nan-wave-retry": scen_nan_wave_retry,
    "nan-wave-scrub": scen_nan_wave_scrub,
    "exhaustion-emergency-resume": scen_exhaustion_emergency_resume,
    "corrupt-resume": scen_corrupt_resume,
    "mesh-device-loss": scen_mesh_device_loss,
    "serve-wedge": scen_serve_wedge,
    "serve-backoff-storm": scen_serve_backoff_storm,
    "fleet-replica-kill": scen_fleet_replica_kill,
    "fleet-router-restart": scen_fleet_router_restart,
}

#: rows whose whole POINT is to trip the watchdog — every other row
#: must leave the registry-derived health conditions clean (the
#: watchdog's false-positive gate over the recovery matrix)
_WATCHDOG_ROWS = {"serve-wedge", "serve-backoff-storm"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpu_pbrt.chaos")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument(
        "--only", default="",
        help="comma-separated subset of scenario names to run",
    )
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {' '.join((fn.__doc__ or '').split())}")
        return 0

    _setup_env()
    import pathlib
    import tempfile

    import jax

    # warm persistent compile cache (shared with the test suite)
    cache = pathlib.Path(__file__).resolve().parents[2] / ".jax_cache"
    try:
        cache.mkdir(exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (OSError, AttributeError):
        pass

    only = {s for s in args.only.split(",") if s}
    unknown = only - set(SCENARIOS)
    if unknown:
        ap.error(f"unknown scenario(s): {sorted(unknown)}")
    failed = []
    ran = 0
    t_all = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        for name, fn in SCENARIOS.items():
            if only and name not in only:
                continue
            ran += 1
            sdir = os.path.join(tmp, name)
            os.makedirs(sdir, exist_ok=True)
            t0 = time.time()
            try:
                ok, detail = fn(sdir)
            except Exception as e:  # noqa: BLE001 — a broken scenario is a FAIL
                ok, detail = False, f"{type(e).__name__}: {e}"
            if ok and name not in _WATCHDOG_ROWS:
                # false-positive gate: a CLEAN recovery row must not
                # trip the registry-derived health conditions
                from tpu_pbrt.obs.health import evaluate

                hrep = evaluate(None)
                if not hrep.ok:
                    ok, detail = False, (
                        f"health watchdog fired on a clean row: "
                        f"{hrep.firing()}"
                    )
            dt = time.time() - t0
            print(
                f"chaos {name}: {'PASS' if ok else 'FAIL'} "
                f"({detail}) [{dt:.1f}s]",
                flush=True,
            )
            if not ok:
                failed.append(name)
    print(
        json.dumps(
            {
                "chaos_matrix": {
                    "scenarios": ran,
                    "passed": ran - len(failed),
                    "failed": failed,
                    "seconds": round(time.time() - t_all, 1),
                }
            }
        )
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
