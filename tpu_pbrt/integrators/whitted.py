"""WhittedIntegrator.

Capability match for pbrt-v3 src/integrators/whitted.{h,cpp}: classic
recursive ray tracing — direct lighting with *no* MIS (light sampling only,
every light, no area-light solid-angle weighting beyond the pdf) plus
specular reflection/transmission recursion. Implemented as the
DirectLightingIntegrator wavefront with the all-lights strategy, which is
the modern equivalent of WhittedIntegrator::Li's light loop.
"""

from __future__ import annotations

from tpu_pbrt.integrators.direct import DirectLightingIntegrator


class WhittedIntegrator(DirectLightingIntegrator):
    name = "whitted"

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.set_strategy("all")  # whitted always samples every light
