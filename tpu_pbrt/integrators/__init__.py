"""Integrator plugin registry.

Capability match for pbrt-v3 api.cpp MakeIntegrator: the string-dispatched
factory seam through which .pbrt scene files select the rendering
algorithm. The TPU backend registers `tpupath` here (the north-star
requirement: existing scenes switch integrators without modification);
`path` itself is the same wavefront implementation, so both names run
TPU-native.
"""

from __future__ import annotations

_REGISTRY = {}


def register_integrator(name: str, cls):
    _REGISTRY[name] = cls


def _optional(builtin, name, module, cls_name):
    full = f"tpu_pbrt.integrators.{module}"
    try:
        mod = __import__(full, fromlist=[cls_name])
        builtin.setdefault(name, getattr(mod, cls_name))
    except ModuleNotFoundError as e:
        if e.name != full:  # a broken dependency, not a missing plugin
            raise


def make_integrator(name: str, params, scene, options):
    from tpu_pbrt.integrators.direct import DirectLightingIntegrator
    from tpu_pbrt.integrators.path import PathIntegrator
    from tpu_pbrt.integrators.whitted import WhittedIntegrator

    builtin = {
        "path": PathIntegrator,
        "tpupath": PathIntegrator,
        "directlighting": DirectLightingIntegrator,
        "whitted": WhittedIntegrator,
    }
    builtin.update(_REGISTRY)
    _optional(builtin, "volpath", "volpath", "VolPathIntegrator")
    _optional(builtin, "bdpt", "bdpt", "BDPTIntegrator")
    _optional(builtin, "sppm", "sppm", "SPPMIntegrator")
    _optional(builtin, "mlt", "mlt", "MLTIntegrator")
    _optional(builtin, "ao", "ao", "AOIntegrator")

    cls = builtin.get(name)
    if cls is None:
        # pbrt api.cpp MakeIntegrator errors hard on unknown names; silently
        # substituting "path" would benchmark the wrong algorithm (VERDICT
        # r2 weak #4). Fail loudly instead.
        from tpu_pbrt.utils.error import Error

        Error(
            f'Integrator "{name}" unknown or not implemented. '
            f"Available: {sorted(builtin)}"
        )
    return cls(params, scene, options)
