"""MLTIntegrator — primary-sample-space Metropolis light transport.

Capability match for pbrt-v3 src/integrators/mlt.{h,cpp}: the MLTSampler
primary-sample vector with large-step/small-step mutations (mlt.cpp
MLTSampler::Accept/Reject, the exponential small-step kernel), the
bootstrap phase whose luminances build a Distribution1D and the b
normalization constant, parallel Markov chains, Kelemen-weighted
splat-only film accumulation, and the final b/mutationsPerPixel scaling.

TPU-first redesign:
- pbrt runs nChains sequential chains on worker threads; here EVERY lane
  of a (C,) batch is an independent chain — one jitted mutation step
  advances all chains at once, and the film splats of a whole step land
  in one scatter-add.
- the primary sample vector is an explicit (C, D) matrix; the path
  contribution function f(U) re-traces the unidirectional path estimator
  (path.py's NEE + forward-MIS scheme) with every random dimension read
  from U instead of the counter RNG — so MLT means match `path` means,
  which is the cross-convergence oracle.

Documented deviation: pbrt layers PSSMLT over the BDPT strategy space
(multiplexed MLT, one (s,t) strategy per chain depth); this
implementation mutates the unidirectional path space (Kelemen et al.'s
original PSSMLT). Equal-flight-time caustic performance is weaker; the
sampler/bootstrap/chain machinery — what mlt.cpp adds over bdpt.cpp — is
equivalent.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.cameras import generate_rays
from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.sampling import hash_u32, power_heuristic, uniform_float
from tpu_pbrt.core.vecmath import (
    dot,
    normalize,
    offset_ray_origin,
    to_local,
    to_world,
)
from tpu_pbrt.integrators.common import (
    RenderResult,
    WavefrontIntegrator,
    make_interaction,
    scene_intersect,
    scene_intersect_p,
)

#: dims consumed per bounce: light pick + light uv2 + bsdf lobe + bsdf uv2 + rr
_DIMS_PER_BOUNCE = 8  # [light pick/uv(3), bsdf(3), rr, mix]
_DIMS_CAMERA = 4  # film xy + lens uv


def _luminance(c):
    return 0.2126 * c[..., 0] + 0.7152 * c[..., 1] + 0.0722 * c[..., 2]


class MLTIntegrator(WavefrontIntegrator):
    name = "mlt"
    rays_per_camera_ray = 3.0

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        self.n_bootstrap = params.find_one_int("bootstrapsamples", 100000)
        self.n_chains = params.find_one_int("chains", 4096)
        self.mutations_per_pixel = params.find_one_int("mutationsperpixel", 100)
        self.sigma = params.find_one_float("sigma", 0.01)
        self.large_step_prob = params.find_one_float("largestepprobability", 0.3)
        self.n_dims = _DIMS_CAMERA + _DIMS_PER_BOUNCE * self.max_depth
        from tpu_pbrt.utils.error import Warning as _W

        if scene.has_null_materials:
            _W("mlt: null-interface materials are traversed as opaque")

    # ------------------------------------------------------------------
    # f(U): path contribution from an explicit primary-sample matrix
    # ------------------------------------------------------------------
    def _f(self, dev, U):
        """U: (C, D) in [0,1). Returns (p_film (C,2) raster, L (C,3))."""
        scene = self.scene
        film = scene.film
        x0, x1, y0, y1 = film.sample_bounds()
        w = x1 - x0
        h = y1 - y0
        p_film = jnp.stack(
            [x0 + U[:, 0] * w, y0 + U[:, 1] * h], axis=-1
        )
        o, d, wt = generate_rays(scene.camera, p_film, U[:, 2:4])
        C = U.shape[0]
        L = jnp.zeros((C, 3), jnp.float32)
        beta = wt[..., None] * jnp.ones((C, 3), jnp.float32)
        alive = jnp.ones((C,), bool)
        specular = jnp.ones((C,), bool)
        prev_pdf = jnp.zeros((C,), jnp.float32)
        prev_p = o
        # rolled depth loop: one bsdf/light-sampling instantiation for all
        # depths (XLA compile time is superlinear in module size; the
        # unrolled form dominated the MLT tests' wall time)
        def body(depth, carry):
            o, d, L, beta, alive, specular, prev_pdf, prev_p = carry
            t_max = jnp.where(alive, jnp.inf, -1.0)
            hit = scene_intersect(dev, o, d, t_max)
            it = make_interaction(dev, hit, o, d)
            it.valid = it.valid & alive
            miss = alive & (hit.prim < 0)
            if "envmap" in dev:
                le_env = ld.env_lookup(dev, d)
                pdf_env = ld.infinite_pdf(dev, self.light_distr, d, ref_p=prev_p)
                w_env = jnp.where(
                    specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_env)
                )
                L = L + jnp.where(miss[..., None], beta * le_env * w_env[..., None], 0.0)
            hit_light = jnp.where(it.valid, it.light, -1)
            le = ld.emitted_radiance(dev, hit_light, it.wo, it.ng)
            pdf_light = ld.emitted_pdf(
                dev, self.light_distr, prev_p, it.p, hit_light, it.ng
            )
            w_emit = jnp.where(
                specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_light)
            )
            L = L + beta * le * w_emit[..., None]
            alive = alive & (hit.prim >= 0)
            base = _DIMS_CAMERA + depth * _DIMS_PER_BOUNCE
            Ub = jax.lax.dynamic_slice(
                U, (jnp.int32(0), base), (C, _DIMS_PER_BOUNCE)
            )
            scatter_ok = alive & (depth < self.max_depth)
            # mix selection rides its own PSS dimension so f(U) stays
            # a deterministic function of U (detailed balance needs it)
            mp = self.mat_at(dev, it, u_mix=Ub[:, 7])
            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            # NEE light-sampling half (MIS vs BSDF pdf, as in path.py)
            ls = ld.sample_one_light(
                dev, self.light_distr, it.p, Ub[:, 0], Ub[:, 1], Ub[:, 2]
            )
            wi_l = to_local(ls.wi, it.ss, it.ts, it.ns)
            f_l, pdf_b = bxdf.bsdf_eval(mp, wo_l, wi_l)
            f_l = f_l * jnp.abs(dot(ls.wi, it.ns))[..., None]
            do_l = (
                it.valid
                & scatter_ok
                & (ls.pdf > 0.0)
                & (jnp.max(f_l, axis=-1) > 0.0)
                & (jnp.max(ls.li, axis=-1) > 0.0)
            )
            o_s = offset_ray_origin(it.p, it.ng, ls.wi)
            occluded = scene_intersect_p(
                dev, o_s, ls.wi, jnp.where(do_l, ls.dist * 0.999, -1.0)
            )
            w_l = jnp.where(
                ls.is_delta, 1.0, power_heuristic(1.0, ls.pdf, 1.0, pdf_b)
            )
            contrib = f_l * ls.li * (w_l / jnp.maximum(ls.pdf, 1e-20))[..., None]
            L = L + jnp.where((do_l & ~occluded)[..., None], beta * contrib, 0.0)
            # BSDF continuation
            bs = bxdf.bsdf_sample(mp, wo_l, Ub[:, 3], Ub[:, 4], Ub[:, 5])
            wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            cont = scatter_ok & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
            thr = bs.f * (jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None]
            beta = jnp.where(cont[..., None], beta * thr, beta)
            specular = bs.is_specular
            prev_pdf = jnp.where(bs.is_specular, 0.0, bs.pdf)
            prev_p = jnp.where(cont[..., None], it.p, prev_p)
            o = jnp.where(cont[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
            d = jnp.where(cont[..., None], wi_w, d)
            alive = cont
            # Russian roulette after depth 3 (path.cpp bounces > 3)
            do_rr = depth >= 3
            q = jnp.where(
                do_rr, jnp.maximum(0.05, 1.0 - jnp.max(beta, axis=-1)), 0.0
            )
            survive = Ub[:, 6] >= q
            beta = jnp.where(
                (alive & survive & do_rr)[..., None],
                beta / jnp.maximum(1.0 - q, 1e-6)[..., None],
                beta,
            )
            alive = alive & survive
            return o, d, L, beta, alive, specular, prev_pdf, prev_p

        carry = (o, d, L, beta, alive, specular, prev_pdf, prev_p)
        _, _, L, *_ = jax.lax.fori_loop(0, self.max_depth + 1, body, carry)
        return p_film, jnp.maximum(L, 0.0)

    # ------------------------------------------------------------------
    def render(self, scene=None, mesh=None, max_seconds: float = 0.0, **kw) -> RenderResult:
        scene = scene or self.scene
        dev = scene.dev
        film = scene.film
        x0, x1, y0, y1 = film.sample_bounds()
        w = x1 - x0
        h = y1 - y0
        npix = w * h
        D = self.n_dims
        C = self.n_chains
        total_mutations = npix * self.mutations_per_pixel
        n_steps = max(total_mutations // C, 1)

        # ---- bootstrap (mlt.cpp "Generate bootstrap samples") ----------
        nb = self.n_bootstrap
        bid = jnp.arange(nb, dtype=jnp.int32)

        @jax.jit
        def bootstrap_eval(salt):
            U = jnp.stack(
                [uniform_float(bid, bid * 7 + 3, salt, k) for k in range(D)], -1
            )
            _, L = self._f(dev, U)
            return _luminance(L), U

        y_boot, U_boot = bootstrap_eval(jnp.int32(0x8F2))
        y_np = np.asarray(y_boot, np.float64)
        b = float(y_np.mean())  # the normalization constant (E[y] estimate)
        if b <= 0.0:
            # black scene: nothing to mutate toward
            img = np.zeros((h, w, 3), np.float32)
            return RenderResult(
                image=img, film_state=None, seconds=0.0, rays_traced=nb,
                mray_per_sec=0.0, spp=self.mutations_per_pixel,
            )
        # chain seeds ~ y (Distribution1D over bootstrap luminances)
        p = y_np / y_np.sum()
        rng = np.random.default_rng(0x51F0)
        seeds = rng.choice(nb, size=C, p=p)
        U_cur = jnp.asarray(np.asarray(U_boot)[seeds])

        # ---- chains ----------------------------------------------------
        pL = self.large_step_prob
        sigma = self.sigma

        from functools import partial

        def chain_steps_body(U_cur, p_cur, L_cur, y_cur, splat_img, step0,
                             n_inner, cid0=0):
            n_local = U_cur.shape[0]

            def one(carry, step):
                U_cur, p_cur, L_cur, y_cur, splat = carry
                cid = cid0 + jnp.arange(n_local, dtype=jnp.int32)

                def u(salt):
                    return uniform_float(cid, step, jnp.int32(0x3D7), salt)

                large = u(0) < pL
                # small step: pbrt's exponential-scale symmetric kernel
                Un = jnp.stack([u(100 + k) for k in range(D)], -1)
                eps = jnp.stack([u(300 + k) for k in range(D)], -1)
                mag = sigma * jnp.exp(-jnp.log(1024.0) * eps)
                delta = jnp.where(Un < 0.5, mag, -mag)
                U_small = (U_cur + delta) % 1.0
                U_prop = jnp.where(large[:, None], Un, U_small)
                p_prop, L_prop = self._f(dev, U_prop)
                y_prop = _luminance(L_prop)
                a = jnp.minimum(1.0, y_prop / jnp.maximum(y_cur, 1e-20))
                # Kelemen weights (mlt.cpp "Compute acceptance probability")
                w_new = (a + large.astype(jnp.float32)) / (
                    y_prop / b + pL
                )
                w_old = (1.0 - a) / (y_cur / b + pL)

                def splat_to(splat, pf, val):
                    px = jnp.clip(pf[:, 0].astype(jnp.int32) - x0, 0, w - 1)
                    py = jnp.clip(pf[:, 1].astype(jnp.int32) - y0, 0, h - 1)
                    idx = py * w + px
                    ok = jnp.isfinite(val).all(-1) & (jnp.max(val, -1) >= 0.0)
                    return splat.at[jnp.where(ok, idx, npix)].add(
                        jnp.where(ok[:, None], val, 0.0), mode="drop"
                    )

                splat = splat_to(splat, p_prop, L_prop * w_new[:, None])
                splat = splat_to(splat, p_cur, L_cur * w_old[:, None])
                accept = u(700) < a
                U_cur = jnp.where(accept[:, None], U_prop, U_cur)
                p_cur = jnp.where(accept[:, None], p_prop, p_cur)
                L_cur = jnp.where(accept[:, None], L_prop, L_cur)
                y_cur = jnp.where(accept, y_prop, y_cur)
                return (U_cur, p_cur, L_cur, y_cur, splat), accept.mean()

            (U_cur, p_cur, L_cur, y_cur, splat_img), acc = jax.lax.scan(
                one,
                (U_cur, p_cur, L_cur, y_cur, splat_img),
                step0 + jnp.arange(n_inner, dtype=jnp.int32),
            )
            return U_cur, p_cur, L_cur, y_cur, splat_img, acc.mean()

        if mesh is not None and mesh.devices.size > 1:
            # chains shard over the mesh with GLOBAL chain ids (the shard
            # union is exactly the single-device chain set); each device
            # splats its chains into a full-image plane that psum-merges
            # over ICI at the end of every outer block
            from jax.sharding import NamedSharding, PartitionSpec as PS

            from tpu_pbrt.parallel.mesh import (
                SHARD_MAP_NOCHECK,
                TILE_AXIS,
                shard_map,
            )

            n_dev = int(mesh.devices.size)
            pad_c = (-C) % n_dev
            if pad_c:
                # seed pad rows from DISTINCT bootstrap states (wrap
                # around the chain set) — duplicating chain 0 would
                # over-represent one start state in the initial
                # distribution (small transient bias on short runs)
                wrap = jnp.arange(pad_c, dtype=jnp.int32) % C
                U_cur = jnp.concatenate([U_cur, U_cur[wrap]])
            C_tot = C + pad_c
            cpd = C_tot // n_dev
            U_cur = jax.device_put(
                U_cur, NamedSharding(mesh, PS(TILE_AXIS))
            )

            _specs = dict(
                mesh=mesh,
                in_specs=(
                    PS(),
                    (PS(TILE_AXIS), PS(TILE_AXIS), PS(TILE_AXIS),
                     PS(TILE_AXIS)),
                    PS(),
                    PS(),
                ),
                out_specs=(
                    (PS(TILE_AXIS), PS(TILE_AXIS), PS(TILE_AXIS),
                     PS(TILE_AXIS)),
                    PS(),
                    PS(),
                ),
                **SHARD_MAP_NOCHECK,
            )

            def make_steps_shard(n_inner_static):
                def steps_shard(dev_, carry, splat_in, step0):
                    u_, p_, l_, y_ = carry
                    didx = jax.lax.axis_index(TILE_AXIS)
                    u_, p_, l_, y_, delta, acc = chain_steps_body(
                        u_, p_, l_, y_, jnp.zeros_like(splat_in), step0,
                        n_inner_static, cid0=didx * cpd,
                    )
                    delta = jax.lax.psum(delta, TILE_AXIS)
                    acc = jax.lax.pmean(acc, TILE_AXIS)
                    return (u_, p_, l_, y_), splat_in + delta, acc

                return jax.jit(shard_map(steps_shard, **_specs))

            # one compiled step function per distinct n_inner (honoring
            # the argument exactly like the single-device static arg)
            _jit_steps_cache = {}

            def chain_steps(U_c, p_c, L_c, y_c, splat_img, step0, n_inner):
                fn = _jit_steps_cache.get(n_inner)
                if fn is None:
                    fn = make_steps_shard(n_inner)
                    _jit_steps_cache[n_inner] = fn
                carry, splat_img, acc = fn(
                    dev, (U_c, p_c, L_c, y_c), splat_img, step0
                )
                return (*carry, splat_img, acc)

            # padded chains are real chains (duplicated seeds) and their
            # mutations add energy: renormalize by the true chain count
            C = C_tot
        else:
            chain_steps = jax.jit(
                partial(chain_steps_body, cid0=0),
                static_argnames=("n_inner",),
            )

        p_cur, L_cur = jax.jit(self._f)(dev, U_cur)
        y_cur = _luminance(L_cur)
        splat = jnp.zeros((npix, 3), jnp.float32)

        from tpu_pbrt.utils.stats import STATS, ProgressReporter

        inner = 16
        n_outer = max(n_steps // inner, 1)
        progress = ProgressReporter(
            n_outer, "MLT", quiet=bool(getattr(self.options, "quiet", False))
        )
        t0 = time.time()
        done_steps = 0
        acc_rate = 0.0
        with STATS.phase("Integrator/MLT render"):
            for outer in range(n_outer):
                U_cur, p_cur, L_cur, y_cur, splat, acc_rate = chain_steps(
                    U_cur, p_cur, L_cur, y_cur, splat,
                    jnp.int32(outer * inner), inner,
                )
                done_steps += inner
                progress.update()
                if max_seconds > 0 and time.time() - t0 > max_seconds:
                    break
        progress.done()
        secs = time.time() - t0
        STATS.distribution("MLT/Acceptance rate", float(acc_rate))

        # final estimate: splat average scaled by b (film.cpp WriteImage's
        # splatScale = b / mutationsPerPixel, with the per-pixel mutation
        # count expressed through the splat normalization below)
        n_done = done_steps * C
        img = np.asarray(splat).reshape(h, w, 3) * (npix / max(n_done, 1))
        img = np.ascontiguousarray(img, np.float32)
        rays = (nb + n_done) * int(self.max_depth * 2)
        if film.filename:
            try:
                from tpu_pbrt.utils.imageio import write_image as _wi

                _wi(film.filename, img)
            except Exception as e:  # noqa: BLE001
                from tpu_pbrt.utils.error import Warning as _W

                _W(f"could not write image {film.filename}: {e}")
        return RenderResult(
            image=img,
            film_state=None,
            seconds=secs,
            rays_traced=rays,
            mray_per_sec=rays / max(secs, 1e-9) / 1e6,
            spp=self.mutations_per_pixel,
            completed_fraction=done_steps / max(n_steps, 1),
            stats={"b": b, "acceptance": float(acc_rate)},
        )
