"""PathIntegrator — the north-star wavefront bounce loop.

Capability match for pbrt-v3 src/integrators/path.{h,cpp} PathIntegrator::Li
(SURVEY.md §3.3): iterative bounce loop with emission on miss/first-hit,
NEE with MIS, BSDF importance sampling for the continuation, beta updates,
and Russian roulette after depth 3 with the eta^2 radiance correction.

TPU-first redesign (SURVEY.md §7): the per-ray recursion becomes a
wavefront — the whole ray batch advances one bounce per `lax.while_loop`
iteration under a live mask, with all control flow as masked selects. One
compiled bounce body serves every depth (compile time and program size are
constant in maxdepth — a Python-unrolled loop at production depth
overflowed the XLA program budget), and the loop exits as soon as every
lane is dead. The MIS bookkeeping uses the forward formulation (pbrt-v4
style): instead of EstimateDirect's extra BSDF-MIS shadow ray per bounce,
the continuation ray itself carries the BSDF pdf, and emitters hit by it
are weighted by power_heuristic(bsdf_pdf, light_pdf). Identical
expectation to the reference estimator, one ray cheaper per bounce.

Persistent wavefront (ISSUE 1 tentpole): the fixed-batch loop above leaves
most lanes dead after the first bounces (miss / RR) while every remaining
wave still pays full-width shading, NEE and sampling for them. The default
render path is therefore the Laine/Karras/Aila-style wavefront with
COMPACTION + REGENERATION (`pool_chunk`): a resident pool of path slots is
advanced one bounce per wave; terminated lanes scatter their L into the
film, are compacted to the pool tail with ONE packed-int32 single-key sort
(the stream tracer's fast sort path — no float keys), and are refilled
with fresh camera rays drained from a per-chunk work counter, so every
trace and shading wave runs near 100% occupancy. Because every sampler
dimension is a pure function of (px, py, s, dimension), a regenerated lane
reproduces exactly the sample stream the fixed-batch loop would have drawn
— the estimator (and the image, up to float accumulation order) is
identical. `TPU_PBRT_REGEN=0` falls back to the fixed-batch loop, which
also remains the path for scenes the pool does not support (null-interface
materials, multi-segment Tr, the halton sampler's scalar-salt dispatch).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.film import FilmState
from tpu_pbrt.core.sampling import power_heuristic, uniform_float
from tpu_pbrt.core.vecmath import dot, normalize, offset_ray_origin, to_local, to_world
from tpu_pbrt.integrators.common import (
    scene_intersect,
    scene_intersect_fused,
    scene_intersect_p,
    unoccluded_tr,
    DIM_BSDF_LOBE,
    DIM_BSDF_UV,
    DIM_LIGHT_PICK,
    DIM_LIGHT_UV,
    DIM_MIX,
    DIM_RR,
    DIM_TIME,
    DIMS_PER_BOUNCE,
    WavefrontIntegrator,
    make_interaction,
    texture_footprint,
)
from tpu_pbrt.scene.compiler import MAT_NONE

PASSTHROUGH_MARGIN = 4

#: compaction packs (free_flag << 30) | lane into one int32 sort key
_POOL_LANE_BITS = 30


class LaneSt(NamedTuple):
    """Per-lane path state — everything a path carries between bounces.
    Shared by the fixed-batch loop (all lanes in lockstep) and the
    persistent pool (lanes at mixed depths)."""

    o: jnp.ndarray
    d: jnp.ndarray
    L: jnp.ndarray
    beta: jnp.ndarray
    alive: jnp.ndarray
    depth: jnp.ndarray  # per-lane real (non-null) bounces taken; also the
    # lane's sampler-dimension salt base in pool mode
    prev_pdf: jnp.ndarray
    specular: jnp.ndarray
    eta_scale: jnp.ndarray
    prev_p: jnp.ndarray
    sh_o: jnp.ndarray  # pending shadow ray (fused mode)
    sh_d: jnp.ndarray
    sh_dist: jnp.ndarray  # < 0: no pending shadow
    ld_pend: jnp.ndarray  # beta-weighted NEE contribution awaiting
    # the pending shadow's visibility


def fresh_lanes(o, d) -> LaneSt:
    """Camera-ray lane state: the MIS state treats the camera 'bounce' as
    specular."""
    shape = o.shape[:-1]
    return LaneSt(
        o=o,
        d=d,
        L=jnp.zeros(shape + (3,), jnp.float32),
        beta=jnp.ones(shape + (3,), jnp.float32),
        alive=jnp.ones(shape, bool),
        depth=jnp.zeros(shape, jnp.int32),
        prev_pdf=jnp.zeros(shape, jnp.float32),
        specular=jnp.ones(shape, bool),
        eta_scale=jnp.ones(shape, jnp.float32),
        prev_p=o,
        sh_o=o,
        sh_d=d,
        sh_dist=jnp.full(shape, -1.0, jnp.float32),
        ld_pend=jnp.zeros(shape + (3,), jnp.float32),
    )


class PathIntegrator(WavefrontIntegrator):
    name = "path"

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        self.rr_threshold = params.find_one_float("rrthreshold", 1.0)
        # null-BSDF (interface/container) surfaces: pbrt spawns through them
        # without counting a bounce (path.cpp bounces--). The wavefront
        # equivalent is extra loop iterations + a per-lane real-bounce
        # counter; scenes without null materials pay nothing (ADVICE r1).
        self.margin = PASSTHROUGH_MARGIN if scene.has_null_materials else 0

    # -- regeneration support gate ----------------------------------------
    def _regen_enabled(self) -> bool:
        """Compaction+regeneration is ON by default for the path
        integrator wherever the pool's preconditions hold: the fused 2R
        wave layout (single-segment visibility, no null passthrough) and
        a sampler whose dimension salts work per-lane (halton's pair
        dispatch is a lax.switch on the salt and needs it scalar)."""
        from tpu_pbrt.config import cfg

        if not cfg.regen:
            return False
        if self.vis_segments != 1 or self.margin != 0:
            return False
        if self.skind == "halton":
            return False
        return True

    # -- one wavefront step ------------------------------------------------
    def _bounce_wave(
        self, dev, px, py, s, salt, ray_time, st: LaneSt, nrays,
        *, fused: bool, scalar_bounce=None, ctr=None,
    ):
        """Advance every lane one bounce: trace (fused continuation +
        pending-shadow 2R wave when `fused`), settle the previous bounce's
        NEE, add emission with forward MIS, sample NEE + the BSDF
        continuation, run the BSSRDF probe wave if compiled in, and apply
        Russian roulette.

        `salt` is the sampler-dimension base — the scalar loop iteration *
        DIMS_PER_BOUNCE in fixed-batch mode, the per-lane depth *
        DIMS_PER_BOUNCE in pool mode (identical values for any live lane,
        so both modes draw the same streams). `scalar_bounce` enables the
        lax.cond skip of the camera-footprint block when the whole wave
        shares one bounce index; pool mode (None) masks per-lane instead.
        `ctr` is the optional telemetry counter block (obs/counters.py):
        this wave's ray count and occupancy-histogram bin are folded in
        here, structural drain counters in the pool body. Returns
        (LaneSt, nrays + this wave's per-lane traced-ray counts, ctr).
        """
        shape = st.o.shape[:-1]
        nrays_in = nrays  # telemetry: the wave's ray delta (ctr below)
        o, d, L, beta, alive = st.o, st.d, st.L, st.beta, st.alive
        depth, prev_pdf, specular = st.depth, st.prev_pdf, st.specular
        eta_scale, prev_p = st.eta_scale, st.prev_p

        # dead lanes traverse with t_max < 0: the root slab test fails
        # immediately, so they cost one loop iteration, not a walk.
        # The trace below is where TPU_PBRT_FUSED lands: the stream
        # tracer compiles its flush/expand phases to the fused Pallas
        # wavefront kernels (accel/fusedwave.py) or the jnp path —
        # chosen at trace time from the 2R camera+shadow wave width
        # (TPU_PBRT_FUSED_MAX_RAYS gates VMEM residency), bit-identical
        # either way, keyed into the chunk closure's jit cache
        t_max = jnp.where(alive, jnp.inf, -1.0)
        if fused:
            R = o.shape[0]
            hit, sh_prim = scene_intersect_fused(
                dev,
                jnp.concatenate([o, st.sh_o]),
                jnp.concatenate([d, st.sh_d]),
                jnp.concatenate([t_max, st.sh_dist]),
                n_cam=R,
                # shadow rays inherit their camera sample's time
                time=None if ray_time is None
                else jnp.concatenate([ray_time, ray_time]),
            )
            # settle the previous bounce's NEE with its visibility
            vis_prev = (st.sh_dist > 0.0) & (sh_prim < 0)
            L = L + jnp.where(vis_prev[..., None], st.ld_pend, 0.0)
            nrays = nrays + (st.sh_dist > 0.0).astype(jnp.int32)
        else:
            hit = scene_intersect(dev, o, d, t_max, time=ray_time)
        nrays = nrays + alive.astype(jnp.int32)
        it = make_interaction(dev, hit, o, d)
        it.valid = it.valid & alive
        miss = alive & (hit.prim < 0)

        # camera-hit ray-differential footprint -> trilinear mip
        # selection (camera.cpp GenerateRayDifferential +
        # interaction.cpp ComputeDifferentials); bounce>0 vertices
        # shade at the finest level, as pbrt does for non-specular
        # continuations
        from tpu_pbrt.config import cfg

        if (self.tex_eval is not None and "tri_difT" in dev
                and cfg.mipfilter):
            from tpu_pbrt.cameras import ray_differentials

            def cam_footprint(args):
                o_, d_, prim_, p_, ng_, valid_ = args
                pf_c = jnp.stack(
                    [px.astype(jnp.float32) + 0.5,
                     py.astype(jnp.float32) + 0.5], axis=-1)
                dox, ddx, doy, ddy = ray_differentials(
                    self.scene.camera, pf_c)
                w0 = texture_footprint(
                    dev, prim_, p_, ng_, o_, d_, dox, ddx, doy, ddy
                )
                return jnp.where(valid_[..., None], w0, 0.0)

            args = (o, d, hit.prim, it.p, it.ng, it.valid)
            if scalar_bounce is not None:
                # bounce > 0 shades at the finest level (pbrt's behavior
                # for non-specular continuations) — skip the gather +
                # plane solves entirely on those iterations
                width = jax.lax.cond(
                    scalar_bounce == 0,
                    cam_footprint,
                    lambda a: jnp.zeros(
                        a[3].shape[:-1] + (4,), jnp.float32
                    ),
                    args,
                )
            else:
                # pool mode: lanes at mixed depths share the wave, so the
                # footprint is computed each wave and masked to the
                # camera-hit (depth 0) lanes
                width = jnp.where(
                    (depth == 0)[..., None], cam_footprint(args), 0.0
                )
        else:
            width = None

        # ---- emitted radiance with forward MIS ----------------------
        if "envmap" in dev:
            le_env = ld.env_lookup(dev, d)
            pdf_env = ld.infinite_pdf(dev, self.light_distr, d, ref_p=prev_p)
            w_env = jnp.where(
                specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_env)
            )
            L = L + jnp.where(miss[..., None], beta * le_env * w_env[..., None], 0.0)
        hit_light = jnp.where(it.valid, it.light, -1)
        le = ld.emitted_radiance(dev, hit_light, it.wo, it.ng)
        pdf_light = ld.emitted_pdf(dev, self.light_distr, prev_p, it.p, hit_light, it.ng)
        w_emit = jnp.where(specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_light))
        L = L + beta * le * w_emit[..., None]

        alive = alive & (hit.prim >= 0)
        # pbrt: the vertex at bounces == maxDepth emits but neither
        # samples lights nor continues
        can_scatter = depth < self.max_depth

        # ---- NEE: light-sampling half --------------------------------
        mp = self.mat_at(
            dev, it, width,
            u_mix=self.u1d(px, py, s, salt + DIM_MIX),
        )
        is_null = it.valid & (mp.mtype == MAT_NONE) if self.margin else None
        u_pick = self.u1d(px, py, s, salt + DIM_LIGHT_PICK)
        u1, u2 = self.u2d(px, py, s, salt + DIM_LIGHT_UV)
        ls = ld.sample_one_light(dev, self.light_distr, it.p, u_pick, u1, u2)
        wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
        wi_l = to_local(ls.wi, it.ss, it.ts, it.ns)
        f, bsdf_pdf = bxdf.bsdf_eval(mp, wo_l, wi_l)
        f = f * jnp.abs(dot(ls.wi, it.ns))[..., None]
        do_nee = (
            it.valid
            & can_scatter
            & (ls.pdf > 0.0)
            & (jnp.max(f, axis=-1) > 0.0)
            & (jnp.max(ls.li, axis=-1) > 0.0)
        )
        o_sh = offset_ray_origin(it.p, it.ng, ls.wi)
        sh_dist = jnp.where(do_nee, ls.dist, -1.0)  # fast-exit dead lanes
        w_l = jnp.where(ls.is_delta, 1.0, power_heuristic(1.0, ls.pdf, 1.0, bsdf_pdf))
        Ld = f * ls.li * (w_l / jnp.maximum(ls.pdf, 1e-20))[..., None]
        if fused:
            # queue the shadow ray; it rides the NEXT iteration's fused
            # wave (the 0.999 dist margin matches unoccluded_tr)
            sh_o_n = o_sh
            sh_d_n = ls.wi
            sh_dist_n = jnp.where(do_nee, sh_dist * 0.999, -1.0)
            ld_pend_n = jnp.where(do_nee[..., None], beta * Ld, 0.0)
        else:
            visible, _ = unoccluded_tr(
                dev, o_sh, ls.wi, sh_dist, None, px, py, s,
                salt + DIM_LIGHT_UV + 200, segments=self.vis_segments,
            )
            nrays = nrays + do_nee.astype(jnp.int32)
            L = L + jnp.where((do_nee & visible)[..., None], beta * Ld, 0.0)

        # ---- continuation: BSDF sample -------------------------------
        ul = self.u1d(px, py, s, salt + DIM_BSDF_LOBE)
        ub1, ub2 = self.u2d(px, py, s, salt + DIM_BSDF_UV)
        bs = bxdf.bsdf_sample(mp, wo_l, ul, ub1, ub2)
        wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
        cont = it.valid & can_scatter & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
        throughput = bs.f * (jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None]
        beta = jnp.where(cont[..., None], beta * throughput, beta)
        # eta^2 tracking for RR (path.cpp etaScale)
        eta2 = (mp.eta[..., 0]) ** 2
        going_in = dot(it.wo, it.ns) > 0.0
        scale = jnp.where(going_in, eta2, 1.0 / jnp.maximum(eta2, 1e-12))
        eta_scale = jnp.where(cont & bs.is_transmission, eta_scale * scale, eta_scale)

        prev_p = jnp.where(cont[..., None], it.p, prev_p)
        o = jnp.where(cont[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
        d = jnp.where(cont[..., None], wi_w, d)
        prev_pdf = jnp.where(cont, bs.pdf, prev_pdf)
        specular = jnp.where(cont, bs.is_specular, specular)
        depth = depth + cont.astype(jnp.int32)
        alive = cont

        # ---- BSSRDF probe wave (bssrdf.cpp Sample_S/Sample_Sp,
        # path.cpp's bssrdf block; compiled ONLY for scenes with
        # subsurface materials). A lane whose interface sample was
        # the specular TRANSMISSION re-emerges at an exit vertex
        # found by a fixed-K probe chord: axis/channel MIS picks a
        # radius from the baked diffusion CDF, the chord is
        # intersected K times collecting same-material hits with
        # reservoir selection, and the lane continues from the exit
        # with the Sw directional lobe (NEE + cosine continuation
        # inline below — the wavefront analog of pbrt's Sw-adapter
        # BSDF at pi). Entry Fresnel rides the interface sample;
        # f*cos/pdf of the specular transmission is 1, so beta here
        # gains exactly Sp * nFound / Pdf_Sp then Sw*pi. -----------
        if "bssrdf" in dev:
            from tpu_pbrt.core.bssrdf import (
                pdf_sr,
                sample_sr,
                sr_eval,
                sw_eval,
            )
            from tpu_pbrt.core.sampling import cosine_sample_hemisphere
            from tpu_pbrt.core.smalltab import small_take

            tabS = dev["bssrdf"]
            sub = jnp.maximum(mp.sub, 0)
            sss = cont & (mp.sub >= 0) & bs.is_transmission
            ua = self.u1d(px, py, s, salt + 12)
            uc = self.u1d(px, py, s, salt + 13)
            ur_ = self.u1d(px, py, s, salt + 14)
            uphi = self.u1d(px, py, s, salt + 15)
            # probe frame: ns axis w.p. 1/2, ss/ts each 1/4
            ax0 = (ua < 0.5)[..., None]
            ax1 = ((ua >= 0.5) & (ua < 0.75))[..., None]
            vz = jnp.where(ax0, it.ns, jnp.where(ax1, it.ss, it.ts))
            vx = jnp.where(ax0, it.ss, jnp.where(ax1, it.ts, it.ns))
            vy = jnp.where(ax0, it.ts, jnp.where(ax1, it.ns, it.ss))
            ch = jnp.clip((uc * 3.0).astype(jnp.int32), 0, 2)
            r_s = sample_sr(tabS, sub, ch, ur_)
            rmax_c = jnp.take_along_axis(
                tabS.r_max[sub], ch[..., None], axis=-1
            )[..., 0]
            l_ch = 2.0 * jnp.sqrt(jnp.maximum(rmax_c**2 - r_s**2, 0.0))
            phi_s = 2.0 * jnp.pi * uphi
            start = (
                it.p
                + r_s[..., None] * (
                    jnp.cos(phi_s)[..., None] * vx
                    + jnp.sin(phi_s)[..., None] * vy
                )
                + (0.5 * l_ch)[..., None] * vz
            )
            pdir = -vz
            ok_r = sss & (r_s < rmax_c) & (l_ch > 0.0)

            cur_o = start
            t_rem = jnp.where(ok_r, l_ch, -1.0)
            n_found = jnp.zeros(shape, jnp.int32)
            sel_p, sel_ng, sel_ns = it.p, it.ng, it.ns
            sel_ss, sel_ts = it.ss, it.ts
            for k in range(4):
                hitk = scene_intersect(
                    dev, cur_o, pdir, t_rem, time=ray_time
                )
                itk = make_interaction(dev, hitk, cur_o, pdir)
                nrays = nrays + (t_rem > 0.0).astype(jnp.int32)
                m_sub = small_take(
                    dev["mat"]["sub_id"], jnp.maximum(itk.mat, 0)
                )
                matchk = itk.valid & (m_sub == sub) & ok_r
                n_found = n_found + matchk.astype(jnp.int32)
                u_res = uniform_float(px, py, s, salt + 4000 + k)
                takek = matchk & (
                    u_res * n_found.astype(jnp.float32) < 1.0
                )
                tk = takek[..., None]
                sel_p = jnp.where(tk, itk.p, sel_p)
                sel_ng = jnp.where(tk, itk.ng, sel_ng)
                sel_ns = jnp.where(tk, itk.ns, sel_ns)
                sel_ss = jnp.where(tk, itk.ss, sel_ss)
                sel_ts = jnp.where(tk, itk.ts, sel_ts)
                adv = jnp.where(itk.valid, hitk.t + 1e-4, jnp.inf)
                cur_o = cur_o + adv[..., None] * pdir
                t_rem = jnp.where(itk.valid, t_rem - adv, -1.0)

            ok_exit = ok_r & (n_found > 0)
            dvec = sel_p - it.p
            dist_s = jnp.linalg.norm(dvec, axis=-1)
            sp = sr_eval(tabS, sub, dist_s)  # (R, 3)
            # Pdf_Sp: MIS over the 3 axes x 3 channels of projected
            # radii (bssrdf.cpp Pdf_Sp)
            dl = jnp.stack(
                [dot(dvec, it.ss), dot(dvec, it.ts), dot(dvec, it.ns)],
                axis=-1,
            )
            nl = jnp.stack(
                [dot(sel_ns, it.ss), dot(sel_ns, it.ts),
                 dot(sel_ns, it.ns)], axis=-1,
            )
            rproj = jnp.stack(
                [
                    jnp.sqrt(dl[..., 1] ** 2 + dl[..., 2] ** 2),
                    jnp.sqrt(dl[..., 2] ** 2 + dl[..., 0] ** 2),
                    jnp.sqrt(dl[..., 0] ** 2 + dl[..., 1] ** 2),
                ],
                axis=-1,
            )
            ax_prob = (0.25, 0.25, 0.5)
            pdf_tot = jnp.zeros(shape, jnp.float32)
            for a in range(3):
                for c in range(3):
                    pdf_tot = pdf_tot + pdf_sr(
                        tabS, sub, jnp.full_like(ch, c), rproj[..., a]
                    ) * jnp.abs(nl[..., a]) * (ax_prob[a] / 3.0)
            ok_exit = ok_exit & (pdf_tot > 0.0) & (
                jnp.max(sp, axis=-1) > 0.0
            )
            w_sss = sp * (
                n_found.astype(jnp.float32)
                / jnp.maximum(pdf_tot, 1e-20)
            )[..., None]
            beta = jnp.where(ok_exit[..., None], beta * w_sss, beta)

            # exit-vertex NEE with the Sw lobe (pbrt's Sw adapter); the
            # adapter's eta^2 radiance-mode factor (non-symmetric
            # scattering at the refractive exit) is applied once to beta
            # here so both the NEE term and the continuation carry it
            eta_sub = tabS.eta[sub]
            beta = jnp.where(
                ok_exit[..., None], beta * (eta_sub * eta_sub)[..., None],
                beta,
            )
            ls2 = ld.sample_one_light(
                dev, self.light_distr, sel_p,
                uniform_float(px, py, s, salt + 4100),
                uniform_float(px, py, s, salt + 4101),
                uniform_float(px, py, s, salt + 4102),
            )
            cos_l = dot(ls2.wi, sel_ns)
            f_sw_l = sw_eval(eta_sub, cos_l) * jnp.maximum(cos_l, 0.0)
            do2 = (
                ok_exit & can_scatter & (ls2.pdf > 0.0) & (cos_l > 1e-6)
                & (jnp.max(ls2.li, axis=-1) > 0.0)
            )
            occ2 = scene_intersect_p(
                dev, offset_ray_origin(sel_p, sel_ng, ls2.wi), ls2.wi,
                jnp.where(do2, ls2.dist * 0.999, -1.0),
            )
            nrays = nrays + do2.astype(jnp.int32)
            w_l2 = jnp.where(
                ls2.is_delta, 1.0,
                power_heuristic(1.0, ls2.pdf, 1.0, cos_l / jnp.pi),
            )
            L = L + jnp.where(
                (do2 & ~occ2)[..., None],
                beta * f_sw_l[..., None] * ls2.li
                * (w_l2 / jnp.maximum(ls2.pdf, 1e-20))[..., None],
                0.0,
            )

            # cosine continuation from the exit with Sw weighting:
            # beta *= Sw * cos / (cos/pi) = Sw * pi
            wloc = cosine_sample_hemisphere(
                uniform_float(px, py, s, salt + 4103),
                uniform_float(px, py, s, salt + 4104),
            )
            wi2 = normalize(
                wloc[..., 0:1] * sel_ss + wloc[..., 1:2] * sel_ts
                + wloc[..., 2:3] * sel_ns
            )
            cos2 = jnp.maximum(dot(wi2, sel_ns), 1e-6)
            beta = jnp.where(
                ok_exit[..., None],
                beta * (sw_eval(eta_sub, cos2) * jnp.pi)[..., None],
                beta,
            )
            o = jnp.where(
                ok_exit[..., None],
                offset_ray_origin(sel_p, sel_ng, wi2), o,
            )
            d = jnp.where(ok_exit[..., None], wi2, d)
            prev_p = jnp.where(ok_exit[..., None], sel_p, prev_p)
            prev_pdf = jnp.where(ok_exit, cos2 / jnp.pi, prev_pdf)
            specular = specular & ~ok_exit
            alive = jnp.where(sss, ok_exit, alive)

        # ---- null passthrough (uncounted bounce, path.cpp bounces--)
        if is_null is not None:
            alive = alive | is_null
            o = jnp.where(is_null[..., None], offset_ray_origin(it.p, it.ng, d), o)
            # d/beta/prev_pdf/specular/prev_p unchanged: the crossing is
            # not a scattering event; MIS still references the last real
            # vertex

        # ---- Russian roulette. pbrt path.cpp tests `bounces > 3` at
        # the END of iteration `bounces`; our per-lane `depth` counter
        # is post-increment here (depth == bounces + 1 for a lane that
        # continued every iteration), so `depth > 4` is the SAME
        # schedule — first possible kill after the 5th real bounce is
        # sampled. depth counts REAL bounces only: null crossings must
        # not advance RR (pbrt's bounces-- semantics). ----------------
        rr_on = depth > 4
        rr_beta = jnp.max(beta, axis=-1) * eta_scale
        q = jnp.maximum(0.05, 1.0 - rr_beta)
        u_rr = uniform_float(px, py, s, salt + DIM_RR)
        rr_cand = alive & rr_on & (rr_beta < self.rr_threshold)
        kill = rr_cand & (u_rr < q)
        survive_scale = jnp.where(rr_cand & ~kill, 1.0 / jnp.maximum(1.0 - q, 1e-6), 1.0)
        beta = beta * survive_scale[..., None]
        alive = alive & ~kill

        if fused:
            pend = (sh_o_n, sh_d_n, sh_dist_n, ld_pend_n)
        else:
            pend = (st.sh_o, st.sh_d, st.sh_dist, st.ld_pend)
        if ctr is not None:
            from tpu_pbrt.obs import counters as obs_counters

            ctr = obs_counters.bounce_update(
                ctr, alive=st.alive, rays_before=nrays_in, rays_after=nrays
            )
        return LaneSt(
            o, d, L, beta, alive, depth, prev_pdf, specular, eta_scale,
            prev_p, *pend,
        ), nrays, ctr

    # -- fixed-batch loop (TPU_PBRT_REGEN=0 fallback; non-fused scenes) ----
    def li(self, dev, o, d, px, py, s):
        shape = o.shape[:-1]
        # motion blur: one shutter time per camera sample, fixed along
        # the whole path (CameraSample::time); keyframes are the shutter
        # endpoints, so the normalized time IS the sample
        if "tri_verts1" in dev:
            ray_time = self.u1d(px, py, s, DIM_TIME)
        else:
            ray_time = None
        max_iters = self.max_depth + 1 + self.margin
        # Fused-wave mode (the stream tracer's costs are per-WAVE fixed +
        # per-pair): each iteration traces [continuation; previous bounce's
        # shadow ray] as ONE 2R batch, halving the wave count. The shadow
        # contribution lands one iteration late (pure pipelining — the
        # estimator is unchanged). Scenes with null-interface materials
        # need the multi-segment Tr walk and keep split waves.
        fused = self.vis_segments == 1 and self.margin == 0

        class St(NamedTuple):
            bounce: jnp.ndarray  # scalar: loop iteration (= sampler salt base)
            nrays: jnp.ndarray
            lane: LaneSt

        def cond(st: St):
            live = jnp.any(st.lane.alive)
            if fused:
                # one extra iteration may be needed to settle the last
                # pending shadow ray
                return (st.bounce < max_iters + 1) & (
                    live | jnp.any(st.lane.sh_dist > 0.0)
                )
            return (st.bounce < max_iters) & live

        def body(st: St):
            salt = st.bounce * DIMS_PER_BOUNCE
            lane, nrays, _ = self._bounce_wave(
                dev, px, py, s, salt, ray_time, st.lane, st.nrays,
                fused=fused, scalar_bounce=st.bounce,
            )
            return St(st.bounce + 1, nrays, lane)

        init = St(
            bounce=jnp.int32(0),
            nrays=jnp.zeros(shape, jnp.int32),
            lane=fresh_lanes(o, d),
        )
        out = jax.lax.while_loop(cond, body, init)
        return out.lane.L, out.nrays

    # -- persistent wavefront: compaction + regeneration -------------------
    def pool_chunk(self, dev, fs: FilmState, start_pix, start_s,
                   n_work: int, pool: int, film=None, cam=None,
                   nan_wave=None):
        """Drain work items [start, start + n_work) through a resident
        pool of `pool` path slots, one bounce per wave.

        Per wave: (1) COMPACT — one packed-int32 single-key sort
        ((free << 30) | lane, the stream tracer's radix fast path) moves
        active lanes to a contiguous prefix, free slots to the tail, and
        every pool array is permuted by the recovered lane index (a
        nearly-sorted gather: the key is two merged ascending runs);
        (2) REGENERATE — the free tail takes fresh camera rays from the
        chunk's work counter, so the trace/shade wave that follows runs
        near-full; (3) one `_bounce_wave`; (4) DEPOSIT — lanes that
        finished this wave (dead, no pending shadow) scatter their L into
        the film state and release their slot. A lane killed with a
        shadow ray still in flight stays resident one extra wave (the
        fused layout settles NEE one wave late) before depositing.

        Returns (film_state, rays_traced, live_lane_waves, n_waves,
        truncated, counters): mean wave occupancy = live_lane_waves /
        (n_waves * pool); truncated is 1 if the max_waves safety cutoff
        fired with work still outstanding (the caller warns loudly — a
        silently darker image must never pass as a completed render);
        counters is the telemetry WaveCounters block carried through the
        drain (None under TPU_PBRT_TELEMETRY=0 — an empty pytree leaf,
        so the killed program is the exact pre-telemetry one).

        nan_wave is the chaos-injection seam (tpu_pbrt/chaos `nan:wave`):
        a traced int32 scalar naming the wave whose active lanes get
        their radiance replaced with NaN (-1 = clean dispatch — the host
        passes -1 on every re-dispatch after the fault fired, so exact
        recovery needs no recompile). None (no nan site in the plan)
        compiles no injection code at all.
        """
        from tpu_pbrt.config import cfg
        from tpu_pbrt.obs import counters as obs_counters

        assert pool < (1 << _POOL_LANE_BITS)
        film = film if film is not None else self.scene.film
        cam = cam if cam is not None else self.scene.camera
        x0, x1, y0, y1 = film.sample_bounds()
        w = x1 - x0
        npix = w * (y1 - y0)
        spp = self.spp
        motion = "tri_verts1" in dev
        box_fast = film.pixel_deposit_ok()
        # Segmented deposit (ROADMAP "pool deposit path" carried item):
        # the in-loop film scatter ran full-pool-width per wave although
        # only the terminated lanes carry a deposit. One extra packed-i32
        # single-key sort (the compaction's fast path) moves this wave's
        # terminated lanes to a contiguous prefix and only a static
        # `seg`-wide window is gathered + scattered — ~pool/seg less
        # scatter traffic per wave; a rare wave where more than `seg`
        # lanes terminate at once falls back to the full-width scatter
        # (lax.cond in the body), so drain length and occupancy are
        # untouched. seg >= pool compiles the exact pre-segment program
        # (no sort, no cond).
        seg = int(cfg.deposit_seg)
        if seg == 0:
            seg = pool // 4 if pool >= 256 else pool
        if seg < 0 or seg > pool:
            seg = pool
        seg = max(seg, 1)
        # worst case: every refill round runs every lane to max_depth,
        # plus the shadow-settle wave — a static safety bound only
        max_waves = (n_work // pool + 2) * (self.max_depth + 2) + 8

        class PSt(NamedTuple):
            fs: FilmState
            lane: LaneSt
            px: jnp.ndarray
            py: jnp.ndarray
            s: jnp.ndarray
            wt: jnp.ndarray  # camera ray weight (realistic lens vignetting)
            time: jnp.ndarray  # per-lane shutter time (motion scenes)
            has_work: jnp.ndarray  # slot holds an undeposited work item
            cursor: jnp.ndarray  # work items consumed so far
            nrays: jnp.ndarray
            live: jnp.ndarray  # sum of live lanes over waves (occupancy)
            waves: jnp.ndarray
            ctr: Any  # WaveCounters | None (None = telemetry killed)

        def cond(ps: PSt):
            return ((ps.cursor < n_work) | jnp.any(ps.has_work)) & (
                ps.waves < max_waves
            )

        def body(ps: PSt):
            # ---- compaction: ONE packed-i32 single-key sort ----------
            lane_idx = jnp.arange(pool, dtype=jnp.int32)
            key = lane_idx | jnp.where(
                ps.has_work, 0, jnp.int32(1) << _POOL_LANE_BITS
            )
            (key_s,) = jax.lax.sort([key], num_keys=1)
            perm = key_s & ((1 << _POOL_LANE_BITS) - 1)

            def take(a):
                return jnp.take(a, perm, axis=0)

            lane = jax.tree.map(take, ps.lane)
            px, py, s = take(ps.px), take(ps.py), take(ps.s)
            wt, tl = take(ps.wt), take(ps.time)
            active = take(ps.has_work)
            n_live = jnp.sum(active, dtype=jnp.int32)

            # ---- regeneration from the work counter ------------------
            widx = ps.cursor + (lane_idx - n_live)
            can = (~active) & (widx < n_work)
            valid, pxn, pyn, sn, _, o_n, d_n, wt_n = self.work_to_rays(
                cam, spp, x0, y0, w, npix, start_pix, start_s,
                jnp.where(can, widx, 0),
            )
            can = can & valid
            fresh = fresh_lanes(o_n, d_n)
            lane = jax.tree.map(
                lambda new, old: jnp.where(
                    can.reshape((pool,) + (1,) * (new.ndim - 1)), new, old
                ),
                fresh, lane,
            )
            px = jnp.where(can, pxn, px)
            py = jnp.where(can, pyn, py)
            s = jnp.where(can, sn, s)
            wt = jnp.where(can, wt_n, wt)
            if motion:
                tl = jnp.where(can, self.u1d(pxn, pyn, sn, DIM_TIME), tl)
            # the counter also consumes work items whose pixel falls past
            # the frame (the final chunk's tail) — the fixed-batch loop
            # likewise masks them out
            consumed = jnp.clip(n_work - ps.cursor, 0, pool - n_live)
            has_work = active | can

            live = ps.live + jnp.sum(lane.alive, dtype=jnp.int32)
            alive_pre = lane.alive

            # ---- one bounce wave -------------------------------------
            salt = lane.depth * DIMS_PER_BOUNCE
            lane, nray_d, ctr = self._bounce_wave(
                dev, px, py, s, salt, tl if motion else None, lane,
                jnp.zeros((pool,), jnp.int32), fused=True,
                scalar_bounce=None, ctr=ps.ctr,
            )

            if nan_wave is not None:
                # chaos nan:wave injection — contaminate every resident
                # lane's radiance on the named wave. The NaNs ride the
                # lanes to their deposit wave (NaN + x = NaN), where the
                # film firewall scrubs and counts them
                poison = has_work & (ps.waves == nan_wave)
                lane = lane._replace(
                    L=jnp.where(
                        poison[..., None], jnp.float32(jnp.nan), lane.L
                    )
                )

            # ---- scatter-on-terminate film deposit -------------------
            done = has_work & ~lane.alive & ~(lane.sh_dist > 0.0)
            if ctr is not None:
                from tpu_pbrt.core.film import nonfinite_mask

                # structural drain counters (rays/occupancy were folded
                # in by _bounce_wave): all pure in-loop i32 reductions,
                # fetched once at the drain boundary with the rest of aux.
                # nonfinite counts the deposits the film firewall is
                # about to scrub — same predicate the deposit uses, so
                # the count and the scrub can never disagree
                ctr = obs_counters.pool_update(
                    ctr,
                    regenerated=jnp.sum(can, dtype=jnp.int32),
                    terminated=jnp.sum(
                        alive_pre & ~lane.alive, dtype=jnp.int32
                    ),
                    deposits=jnp.sum(done, dtype=jnp.int32),
                    compacted=jnp.sum(
                        active & (perm != lane_idx), dtype=jnp.int32
                    ),
                    nonfinite=jnp.sum(
                        done & nonfinite_mask(lane.L), dtype=jnp.int32
                    ),
                )
            if not box_fast:
                # general filter footprint: recompute the film jitter
                # (a pure function of the work item) and mask the
                # not-yet-terminated lanes out of the crop window
                fx, fy = self.film_jitter(px, py, s)
                p_film = jnp.stack(
                    [px.astype(jnp.float32) + fx,
                     py.astype(jnp.float32) + fy], axis=-1,
                )
            if seg < pool:
                # SEGMENTED deposit: one more packed-i32 single-key sort
                # (the compaction's fast path) moves this wave's
                # terminated lanes to a contiguous prefix — stable on
                # lane index, so the gathered batch deposits in exactly
                # the full-width scatter's relative order (bit-identity)
                # — and only a static `seg`-wide window is scattered.
                # The rare wave where MORE than `seg` lanes terminate at
                # once takes the full-width branch of the lax.cond
                # instead, so no lane ever waits for a window slot (a
                # deferred-deposit design measurably stalled
                # regeneration: occupancy 0.52 vs 0.96 on the depth-5
                # occupancy scene).
                dkey = lane_idx | jnp.where(
                    done, 0, jnp.int32(1) << _POOL_LANE_BITS
                )
                (dkey_s,) = jax.lax.sort([dkey], num_keys=1)
                dperm = (dkey_s & ((1 << _POOL_LANE_BITS) - 1))[:seg]
                dmask = jnp.take(done, dperm)

                if box_fast:

                    def _dep_seg(fs0):
                        return film.add_samples_pixel(
                            fs0, jnp.take(px, dperm), jnp.take(py, dperm),
                            jnp.take(lane.L, dperm, axis=0), dmask,
                            jnp.take(wt, dperm),
                        )

                    def _dep_full(fs0):
                        return film.add_samples_pixel(
                            fs0, px, py, lane.L, done, wt
                        )

                else:

                    def _dep_seg(fs0):
                        return film.add_samples(
                            fs0,
                            jnp.where(
                                dmask[..., None],
                                jnp.take(p_film, dperm, axis=0), -1e6,
                            ),
                            jnp.take(lane.L, dperm, axis=0),
                            jnp.take(wt, dperm),
                        )

                    def _dep_full(fs0):
                        return film.add_samples(
                            fs0,
                            jnp.where(done[..., None], p_film, -1e6),
                            lane.L, wt,
                        )

                fs = jax.lax.cond(
                    jnp.sum(done, dtype=jnp.int32) <= seg,
                    _dep_seg, _dep_full, ps.fs,
                )
            elif box_fast:
                # box(0.5): one masked own-pixel scatter, matching the
                # aligned path the fixed-batch single-device render uses
                fs = film.add_samples_pixel(ps.fs, px, py, lane.L, done, wt)
            else:
                fs = film.add_samples(
                    ps.fs, jnp.where(done[..., None], p_film, -1e6),
                    lane.L, wt,
                )
            return PSt(
                fs=fs, lane=lane, px=px, py=py, s=s, wt=wt, time=tl,
                has_work=has_work & ~done,
                cursor=ps.cursor + consumed,
                nrays=ps.nrays + jnp.sum(nray_d),
                live=live,
                waves=ps.waves + 1,
                ctr=ctr,
            )

        zero3 = jnp.zeros((pool, 3), jnp.float32)
        unit_d = jnp.broadcast_to(
            jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (pool, 3)
        )
        init = PSt(
            fs=fs,
            lane=fresh_lanes(zero3, unit_d)._replace(
                alive=jnp.zeros((pool,), bool)
            ),
            px=jnp.zeros((pool,), jnp.int32),
            py=jnp.zeros((pool,), jnp.int32),
            s=jnp.zeros((pool,), jnp.int32),
            wt=jnp.zeros((pool,), jnp.float32),
            time=jnp.zeros((pool,), jnp.float32),
            has_work=jnp.zeros((pool,), bool),
            cursor=jnp.int32(0),
            nrays=jnp.int32(0),
            live=jnp.int32(0),
            waves=jnp.int32(0),
            ctr=obs_counters.maybe_zeros(),
        )
        out = jax.lax.while_loop(cond, body, init)
        truncated = (
            (out.cursor < n_work) | jnp.any(out.has_work)
        ).astype(jnp.int32)
        return out.fs, out.nrays, out.live, out.waves, truncated, out.ctr
