"""PathIntegrator — the north-star wavefront bounce loop.

Capability match for pbrt-v3 src/integrators/path.{h,cpp} PathIntegrator::Li
(SURVEY.md §3.3): iterative bounce loop with emission on miss/first-hit,
NEE with MIS, BSDF importance sampling for the continuation, beta updates,
and Russian roulette after depth 3 with the eta^2 radiance correction.

TPU-first redesign (SURVEY.md §7): the per-ray recursion becomes a
wavefront — the whole ray batch advances one bounce per `lax.while_loop`
iteration under a live mask, with all control flow as masked selects. One
compiled bounce body serves every depth (compile time and program size are
constant in maxdepth — a Python-unrolled loop at production depth
overflowed the XLA program budget), and the loop exits as soon as every
lane is dead. The MIS bookkeeping uses the forward formulation (pbrt-v4
style): instead of EstimateDirect's extra BSDF-MIS shadow ray per bounce,
the continuation ray itself carries the BSDF pdf, and emitters hit by it
are weighted by power_heuristic(bsdf_pdf, light_pdf). Identical
expectation to the reference estimator, one ray cheaper per bounce.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.sampling import power_heuristic, uniform_float
from tpu_pbrt.core.vecmath import dot, normalize, offset_ray_origin, to_local, to_world
from tpu_pbrt.integrators.common import (
    scene_intersect,
    scene_intersect_fused,
    scene_intersect_p,
    unoccluded_tr,
    DIM_BSDF_LOBE,
    DIM_BSDF_UV,
    DIM_LIGHT_PICK,
    DIM_LIGHT_UV,
    DIM_MIX,
    DIM_RR,
    DIMS_PER_BOUNCE,
    WavefrontIntegrator,
    make_interaction,
    texture_footprint,
)
from tpu_pbrt.scene.compiler import MAT_NONE

PASSTHROUGH_MARGIN = 4


class PathIntegrator(WavefrontIntegrator):
    name = "path"

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        self.rr_threshold = params.find_one_float("rrthreshold", 1.0)
        # null-BSDF (interface/container) surfaces: pbrt spawns through them
        # without counting a bounce (path.cpp bounces--). The wavefront
        # equivalent is extra loop iterations + a per-lane real-bounce
        # counter; scenes without null materials pay nothing (ADVICE r1).
        self.margin = PASSTHROUGH_MARGIN if scene.has_null_materials else 0

    def li(self, dev, o, d, px, py, s):
        shape = o.shape[:-1]
        # motion blur: one shutter time per camera sample, fixed along
        # the whole path (CameraSample::time); keyframes are the shutter
        # endpoints, so the normalized time IS the sample
        if "tri_verts1" in dev:
            from tpu_pbrt.integrators.common import DIM_TIME

            ray_time = self.u1d(px, py, s, DIM_TIME)
        else:
            ray_time = None
        max_iters = self.max_depth + 1 + self.margin
        # Fused-wave mode (the stream tracer's costs are per-WAVE fixed +
        # per-pair): each iteration traces [continuation; previous bounce's
        # shadow ray] as ONE 2R batch, halving the wave count. The shadow
        # contribution lands one iteration late (pure pipelining — the
        # estimator is unchanged). Scenes with null-interface materials
        # need the multi-segment Tr walk and keep split waves.
        fused = self.vis_segments == 1 and self.margin == 0

        class St(NamedTuple):
            bounce: jnp.ndarray  # scalar: loop iteration (= sampler salt base)
            o: jnp.ndarray
            d: jnp.ndarray
            L: jnp.ndarray
            beta: jnp.ndarray
            alive: jnp.ndarray
            nrays: jnp.ndarray
            depth: jnp.ndarray  # per-lane real (non-null) bounces taken
            prev_pdf: jnp.ndarray
            specular: jnp.ndarray
            eta_scale: jnp.ndarray
            prev_p: jnp.ndarray
            sh_o: jnp.ndarray  # pending shadow ray (fused mode)
            sh_d: jnp.ndarray
            sh_dist: jnp.ndarray  # < 0: no pending shadow
            ld_pend: jnp.ndarray  # beta-weighted NEE contribution awaiting
            # the pending shadow's visibility

        def cond(st: St):
            live = jnp.any(st.alive)
            if fused:
                # one extra iteration may be needed to settle the last
                # pending shadow ray
                return (st.bounce < max_iters + 1) & (
                    live | jnp.any(st.sh_dist > 0.0)
                )
            return (st.bounce < max_iters) & live

        def body(st: St):
            bounce = st.bounce
            salt = bounce * DIMS_PER_BOUNCE
            o, d, L, beta, alive = st.o, st.d, st.L, st.beta, st.alive
            depth, prev_pdf, specular = st.depth, st.prev_pdf, st.specular
            eta_scale, prev_p, nrays = st.eta_scale, st.prev_p, st.nrays

            # dead lanes traverse with t_max < 0: the root slab test fails
            # immediately, so they cost one loop iteration, not a walk
            t_max = jnp.where(alive, jnp.inf, -1.0)
            if fused:
                R = o.shape[0]
                hit, sh_prim = scene_intersect_fused(
                    dev,
                    jnp.concatenate([o, st.sh_o]),
                    jnp.concatenate([d, st.sh_d]),
                    jnp.concatenate([t_max, st.sh_dist]),
                    n_cam=R,
                    # shadow rays inherit their camera sample's time
                    time=None if ray_time is None
                    else jnp.concatenate([ray_time, ray_time]),
                )
                # settle the previous bounce's NEE with its visibility
                vis_prev = (st.sh_dist > 0.0) & (sh_prim < 0)
                L = L + jnp.where(vis_prev[..., None], st.ld_pend, 0.0)
                nrays = nrays + (st.sh_dist > 0.0).astype(jnp.int32)
            else:
                hit = scene_intersect(dev, o, d, t_max, time=ray_time)
            nrays = nrays + alive.astype(jnp.int32)
            it = make_interaction(dev, hit, o, d)
            it.valid = it.valid & alive
            miss = alive & (hit.prim < 0)

            # camera-hit ray-differential footprint -> trilinear mip
            # selection (camera.cpp GenerateRayDifferential +
            # interaction.cpp ComputeDifferentials); bounce>0 vertices
            # shade at the finest level, as pbrt does for non-specular
            # continuations
            import os as _os

            if (self.tex_eval is not None and "tri_difT" in dev
                    and _os.environ.get("TPU_PBRT_MIPFILTER", "1") != "0"):
                from tpu_pbrt.cameras import ray_differentials

                def cam_footprint(args):
                    o_, d_, prim_, p_, ng_, valid_ = args
                    pf_c = jnp.stack(
                        [px.astype(jnp.float32) + 0.5,
                         py.astype(jnp.float32) + 0.5], axis=-1)
                    dox, ddx, doy, ddy = ray_differentials(
                        self.scene.camera, pf_c)
                    w0 = texture_footprint(
                        dev, prim_, p_, ng_, o_, d_, dox, ddx, doy, ddy
                    )
                    return jnp.where(valid_[..., None], w0, 0.0)

                # bounce > 0 shades at the finest level (pbrt's behavior
                # for non-specular continuations) — skip the gather +
                # plane solves entirely on those iterations
                width = jax.lax.cond(
                    bounce == 0,
                    cam_footprint,
                    lambda args: jnp.zeros(
                        args[3].shape[:-1] + (4,), jnp.float32
                    ),
                    (o, d, hit.prim, it.p, it.ng, it.valid),
                )
            else:
                width = None

            # ---- emitted radiance with forward MIS ----------------------
            if "envmap" in dev:
                le_env = ld.env_lookup(dev, d)
                pdf_env = ld.infinite_pdf(dev, self.light_distr, d, ref_p=prev_p)
                w_env = jnp.where(
                    specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_env)
                )
                L = L + jnp.where(miss[..., None], beta * le_env * w_env[..., None], 0.0)
            hit_light = jnp.where(it.valid, it.light, -1)
            le = ld.emitted_radiance(dev, hit_light, it.wo, it.ng)
            pdf_light = ld.emitted_pdf(dev, self.light_distr, prev_p, it.p, hit_light, it.ng)
            w_emit = jnp.where(specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_light))
            L = L + beta * le * w_emit[..., None]

            alive = alive & (hit.prim >= 0)
            # pbrt: the vertex at bounces == maxDepth emits but neither
            # samples lights nor continues
            can_scatter = depth < self.max_depth

            # ---- NEE: light-sampling half only --------------------------
            mp = self.mat_at(
                dev, it, width,
                u_mix=self.u1d(px, py, s, salt + DIM_MIX),
            )
            is_null = it.valid & (mp.mtype == MAT_NONE) if self.margin else None
            u_pick = self.u1d(px, py, s, salt + DIM_LIGHT_PICK)
            u1, u2 = self.u2d(px, py, s, salt + DIM_LIGHT_UV)
            ls = ld.sample_one_light(dev, self.light_distr, it.p, u_pick, u1, u2)
            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            wi_l = to_local(ls.wi, it.ss, it.ts, it.ns)
            f, bsdf_pdf = bxdf.bsdf_eval(mp, wo_l, wi_l)
            f = f * jnp.abs(dot(ls.wi, it.ns))[..., None]
            do_nee = (
                it.valid
                & can_scatter
                & (ls.pdf > 0.0)
                & (jnp.max(f, axis=-1) > 0.0)
                & (jnp.max(ls.li, axis=-1) > 0.0)
            )
            o_sh = offset_ray_origin(it.p, it.ng, ls.wi)
            sh_dist = jnp.where(do_nee, ls.dist, -1.0)  # fast-exit dead lanes
            w_l = jnp.where(ls.is_delta, 1.0, power_heuristic(1.0, ls.pdf, 1.0, bsdf_pdf))
            Ld = f * ls.li * (w_l / jnp.maximum(ls.pdf, 1e-20))[..., None]
            if fused:
                # queue the shadow ray; it rides the NEXT iteration's fused
                # wave (the 0.999 dist margin matches unoccluded_tr)
                sh_o_n = o_sh
                sh_d_n = ls.wi
                sh_dist_n = jnp.where(do_nee, sh_dist * 0.999, -1.0)
                ld_pend_n = jnp.where(do_nee[..., None], beta * Ld, 0.0)
            else:
                visible, _ = unoccluded_tr(
                    dev, o_sh, ls.wi, sh_dist, None, px, py, s,
                    salt + DIM_LIGHT_UV + 200, segments=self.vis_segments,
                )
                nrays = nrays + do_nee.astype(jnp.int32)
                L = L + jnp.where((do_nee & visible)[..., None], beta * Ld, 0.0)

            # ---- continuation: BSDF sample ------------------------------
            ul = self.u1d(px, py, s, salt + DIM_BSDF_LOBE)
            ub1, ub2 = self.u2d(px, py, s, salt + DIM_BSDF_UV)
            bs = bxdf.bsdf_sample(mp, wo_l, ul, ub1, ub2)
            wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            cont = it.valid & can_scatter & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
            throughput = bs.f * (jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None]
            beta = jnp.where(cont[..., None], beta * throughput, beta)
            # eta^2 tracking for RR (path.cpp etaScale)
            eta2 = (mp.eta[..., 0]) ** 2
            going_in = dot(it.wo, it.ns) > 0.0
            scale = jnp.where(going_in, eta2, 1.0 / jnp.maximum(eta2, 1e-12))
            eta_scale = jnp.where(cont & bs.is_transmission, eta_scale * scale, eta_scale)

            prev_p = jnp.where(cont[..., None], it.p, prev_p)
            o = jnp.where(cont[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
            d = jnp.where(cont[..., None], wi_w, d)
            prev_pdf = jnp.where(cont, bs.pdf, prev_pdf)
            specular = jnp.where(cont, bs.is_specular, specular)
            depth = depth + cont.astype(jnp.int32)
            alive = cont

            # ---- BSSRDF probe wave (bssrdf.cpp Sample_S/Sample_Sp,
            # path.cpp's bssrdf block; compiled ONLY for scenes with
            # subsurface materials). A lane whose interface sample was
            # the specular TRANSMISSION re-emerges at an exit vertex
            # found by a fixed-K probe chord: axis/channel MIS picks a
            # radius from the baked diffusion CDF, the chord is
            # intersected K times collecting same-material hits with
            # reservoir selection, and the lane continues from the exit
            # with the Sw directional lobe (NEE + cosine continuation
            # inline below — the wavefront analog of pbrt's Sw-adapter
            # BSDF at pi). Entry Fresnel rides the interface sample;
            # f*cos/pdf of the specular transmission is 1, so beta here
            # gains exactly Sp * nFound / Pdf_Sp then Sw*pi. -----------
            if "bssrdf" in dev:
                from tpu_pbrt.core.bssrdf import (
                    pdf_sr,
                    sample_sr,
                    sr_eval,
                    sw_eval,
                )
                from tpu_pbrt.core.sampling import cosine_sample_hemisphere
                from tpu_pbrt.core.smalltab import small_take

                tabS = dev["bssrdf"]
                sub = jnp.maximum(mp.sub, 0)
                sss = cont & (mp.sub >= 0) & bs.is_transmission
                ua = self.u1d(px, py, s, salt + 12)
                uc = self.u1d(px, py, s, salt + 13)
                ur_ = self.u1d(px, py, s, salt + 14)
                uphi = self.u1d(px, py, s, salt + 15)
                # probe frame: ns axis w.p. 1/2, ss/ts each 1/4
                ax0 = (ua < 0.5)[..., None]
                ax1 = ((ua >= 0.5) & (ua < 0.75))[..., None]
                vz = jnp.where(ax0, it.ns, jnp.where(ax1, it.ss, it.ts))
                vx = jnp.where(ax0, it.ss, jnp.where(ax1, it.ts, it.ns))
                vy = jnp.where(ax0, it.ts, jnp.where(ax1, it.ns, it.ss))
                ch = jnp.clip((uc * 3.0).astype(jnp.int32), 0, 2)
                r_s = sample_sr(tabS, sub, ch, ur_)
                rmax_c = jnp.take_along_axis(
                    tabS.r_max[sub], ch[..., None], axis=-1
                )[..., 0]
                l_ch = 2.0 * jnp.sqrt(jnp.maximum(rmax_c**2 - r_s**2, 0.0))
                phi_s = 2.0 * jnp.pi * uphi
                start = (
                    it.p
                    + r_s[..., None] * (
                        jnp.cos(phi_s)[..., None] * vx
                        + jnp.sin(phi_s)[..., None] * vy
                    )
                    + (0.5 * l_ch)[..., None] * vz
                )
                pdir = -vz
                ok_r = sss & (r_s < rmax_c) & (l_ch > 0.0)

                cur_o = start
                t_rem = jnp.where(ok_r, l_ch, -1.0)
                n_found = jnp.zeros(shape, jnp.int32)
                sel_p, sel_ng, sel_ns = it.p, it.ng, it.ns
                sel_ss, sel_ts = it.ss, it.ts
                for k in range(4):
                    hitk = scene_intersect(
                        dev, cur_o, pdir, t_rem, time=ray_time
                    )
                    itk = make_interaction(dev, hitk, cur_o, pdir)
                    nrays = nrays + (t_rem > 0.0).astype(jnp.int32)
                    m_sub = small_take(
                        dev["mat"]["sub_id"], jnp.maximum(itk.mat, 0)
                    )
                    matchk = itk.valid & (m_sub == sub) & ok_r
                    n_found = n_found + matchk.astype(jnp.int32)
                    u_res = uniform_float(px, py, s, salt + 4000 + k)
                    takek = matchk & (
                        u_res * n_found.astype(jnp.float32) < 1.0
                    )
                    tk = takek[..., None]
                    sel_p = jnp.where(tk, itk.p, sel_p)
                    sel_ng = jnp.where(tk, itk.ng, sel_ng)
                    sel_ns = jnp.where(tk, itk.ns, sel_ns)
                    sel_ss = jnp.where(tk, itk.ss, sel_ss)
                    sel_ts = jnp.where(tk, itk.ts, sel_ts)
                    adv = jnp.where(itk.valid, hitk.t + 1e-4, jnp.inf)
                    cur_o = cur_o + adv[..., None] * pdir
                    t_rem = jnp.where(itk.valid, t_rem - adv, -1.0)

                ok_exit = ok_r & (n_found > 0)
                dvec = sel_p - it.p
                dist_s = jnp.linalg.norm(dvec, axis=-1)
                sp = sr_eval(tabS, sub, dist_s)  # (R, 3)
                # Pdf_Sp: MIS over the 3 axes x 3 channels of projected
                # radii (bssrdf.cpp Pdf_Sp)
                dl = jnp.stack(
                    [dot(dvec, it.ss), dot(dvec, it.ts), dot(dvec, it.ns)],
                    axis=-1,
                )
                nl = jnp.stack(
                    [dot(sel_ns, it.ss), dot(sel_ns, it.ts),
                     dot(sel_ns, it.ns)], axis=-1,
                )
                rproj = jnp.stack(
                    [
                        jnp.sqrt(dl[..., 1] ** 2 + dl[..., 2] ** 2),
                        jnp.sqrt(dl[..., 2] ** 2 + dl[..., 0] ** 2),
                        jnp.sqrt(dl[..., 0] ** 2 + dl[..., 1] ** 2),
                    ],
                    axis=-1,
                )
                ax_prob = (0.25, 0.25, 0.5)
                pdf_tot = jnp.zeros(shape, jnp.float32)
                for a in range(3):
                    for c in range(3):
                        pdf_tot = pdf_tot + pdf_sr(
                            tabS, sub, jnp.full_like(ch, c), rproj[..., a]
                        ) * jnp.abs(nl[..., a]) * (ax_prob[a] / 3.0)
                ok_exit = ok_exit & (pdf_tot > 0.0) & (
                    jnp.max(sp, axis=-1) > 0.0
                )
                w_sss = sp * (
                    n_found.astype(jnp.float32)
                    / jnp.maximum(pdf_tot, 1e-20)
                )[..., None]
                beta = jnp.where(ok_exit[..., None], beta * w_sss, beta)

                # exit-vertex NEE with the Sw lobe (pbrt's Sw adapter)
                eta_sub = tabS.eta[sub]
                ls2 = ld.sample_one_light(
                    dev, self.light_distr, sel_p,
                    uniform_float(px, py, s, salt + 4100),
                    uniform_float(px, py, s, salt + 4101),
                    uniform_float(px, py, s, salt + 4102),
                )
                cos_l = dot(ls2.wi, sel_ns)
                f_sw_l = sw_eval(eta_sub, cos_l) * jnp.maximum(cos_l, 0.0)
                do2 = (
                    ok_exit & can_scatter & (ls2.pdf > 0.0) & (cos_l > 1e-6)
                    & (jnp.max(ls2.li, axis=-1) > 0.0)
                )
                occ2 = scene_intersect_p(
                    dev, offset_ray_origin(sel_p, sel_ng, ls2.wi), ls2.wi,
                    jnp.where(do2, ls2.dist * 0.999, -1.0),
                )
                nrays = nrays + do2.astype(jnp.int32)
                w_l2 = jnp.where(
                    ls2.is_delta, 1.0,
                    power_heuristic(1.0, ls2.pdf, 1.0, cos_l / jnp.pi),
                )
                L = L + jnp.where(
                    (do2 & ~occ2)[..., None],
                    beta * f_sw_l[..., None] * ls2.li
                    * (w_l2 / jnp.maximum(ls2.pdf, 1e-20))[..., None],
                    0.0,
                )

                # cosine continuation from the exit with Sw weighting:
                # beta *= Sw * cos / (cos/pi) = Sw * pi
                wloc = cosine_sample_hemisphere(
                    uniform_float(px, py, s, salt + 4103),
                    uniform_float(px, py, s, salt + 4104),
                )
                wi2 = normalize(
                    wloc[..., 0:1] * sel_ss + wloc[..., 1:2] * sel_ts
                    + wloc[..., 2:3] * sel_ns
                )
                cos2 = jnp.maximum(dot(wi2, sel_ns), 1e-6)
                beta = jnp.where(
                    ok_exit[..., None],
                    beta * (sw_eval(eta_sub, cos2) * jnp.pi)[..., None],
                    beta,
                )
                o = jnp.where(
                    ok_exit[..., None],
                    offset_ray_origin(sel_p, sel_ng, wi2), o,
                )
                d = jnp.where(ok_exit[..., None], wi2, d)
                prev_p = jnp.where(ok_exit[..., None], sel_p, prev_p)
                prev_pdf = jnp.where(ok_exit, cos2 / jnp.pi, prev_pdf)
                specular = specular & ~ok_exit
                alive = jnp.where(sss, ok_exit, alive)

            # ---- null passthrough (uncounted bounce, path.cpp bounces--)
            if is_null is not None:
                alive = alive | is_null
                o = jnp.where(is_null[..., None], offset_ray_origin(it.p, it.ng, d), o)
                # d/beta/prev_pdf/specular/prev_p unchanged: the crossing is
                # not a scattering event; MIS still references the last real
                # vertex

            # ---- Russian roulette. pbrt path.cpp tests `bounces > 3` at
            # the END of iteration `bounces`; our per-lane `depth` counter
            # is post-increment here (depth == bounces + 1 for a lane that
            # continued every iteration), so `depth > 4` is the SAME
            # schedule — first possible kill after the 5th real bounce is
            # sampled. depth counts REAL bounces only: null crossings must
            # not advance RR (pbrt's bounces-- semantics). ----------------
            rr_on = depth > 4
            rr_beta = jnp.max(beta, axis=-1) * eta_scale
            q = jnp.maximum(0.05, 1.0 - rr_beta)
            u_rr = uniform_float(px, py, s, salt + DIM_RR)
            rr_cand = alive & rr_on & (rr_beta < self.rr_threshold)
            kill = rr_cand & (u_rr < q)
            survive_scale = jnp.where(rr_cand & ~kill, 1.0 / jnp.maximum(1.0 - q, 1e-6), 1.0)
            beta = beta * survive_scale[..., None]
            alive = alive & ~kill

            if fused:
                pend = (sh_o_n, sh_d_n, sh_dist_n, ld_pend_n)
            else:
                pend = (st.sh_o, st.sh_d, st.sh_dist, st.ld_pend)
            return St(
                bounce + 1, o, d, L, beta, alive, nrays, depth,
                prev_pdf, specular, eta_scale, prev_p, *pend,
            )

        init = St(
            bounce=jnp.int32(0),
            o=o,
            d=d,
            L=jnp.zeros(shape + (3,), jnp.float32),
            beta=jnp.ones(shape + (3,), jnp.float32),
            alive=jnp.ones(shape, bool),
            nrays=jnp.zeros(shape, jnp.int32),
            depth=jnp.zeros(shape, jnp.int32),
            # MIS state: pdf of the BSDF sample that produced the current
            # ray; the camera "bounce" counts as specular
            prev_pdf=jnp.zeros(shape, jnp.float32),
            specular=jnp.ones(shape, bool),
            eta_scale=jnp.ones(shape, jnp.float32),
            prev_p=o,
            sh_o=o,
            sh_d=d,
            sh_dist=jnp.full(shape, -1.0, jnp.float32),
            ld_pend=jnp.zeros(shape + (3,), jnp.float32),
        )
        out = jax.lax.while_loop(cond, body, init)
        return out.L, out.nrays
