"""DirectLightingIntegrator.

Capability match for pbrt-v3 src/integrators/directlighting.{h,cpp}:
strategies UniformSampleAll / UniformSampleOne, maxdepth specular recursion
(Whitted-style mirror/glass continuation). The cornell-box config's
integrator (SURVEY.md §2c).
"""

from __future__ import annotations

import jax.numpy as jnp

from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.sampling import uniform_float
from tpu_pbrt.core.vecmath import dot, normalize, offset_ray_origin, to_world
from tpu_pbrt.integrators.common import (
    scene_intersect,
    scene_intersect_p,
    DIM_BSDF_LOBE,
    DIM_BSDF_UV,
    DIM_MIX,
    DIMS_PER_BOUNCE,
    WavefrontIntegrator,
    estimate_direct,
    make_interaction,
)
from tpu_pbrt.utils.error import Warning


class DirectLightingIntegrator(WavefrontIntegrator):
    name = "directlighting"

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        strategy = params.find_one_string("strategy", "all")
        if strategy not in ("all", "one"):
            Warning(f'Strategy "{strategy}" for direct lighting unknown. Using "all".')
            strategy = "all"
        self.set_strategy(strategy)

    def set_strategy(self, strategy: str):
        """Keeps strategy and the all-lights unroll count in sync."""
        self.strategy = strategy
        # "all" loops every light each shading point; cap the unroll
        if strategy == "all" and self.scene.n_lights > 16:
            Warning(
                f"UniformSampleAll over {self.scene.n_lights} lights would unroll "
                f"{self.scene.n_lights} NEE taps; falling back to one-light sampling."
            )
            self.strategy = "one"
        self.n_light_loop = self.scene.n_lights if self.strategy == "all" else 1

    def li(self, dev, o, d, px, py, s):
        L = jnp.zeros(o.shape[:-1] + (3,), jnp.float32)
        beta = jnp.ones_like(L)
        alive = jnp.ones(o.shape[:-1], bool)
        nrays = jnp.zeros(o.shape[:-1], jnp.int32)
        n_lights = dev["light"]["type"].shape[0]

        for depth in range(self.max_depth):
            hit = scene_intersect(dev, o, d, jnp.inf)
            nrays = nrays + alive.astype(jnp.int32)
            it = make_interaction(dev, hit, o, d)
            it.valid = it.valid & alive
            miss = alive & (hit.prim < 0)
            if "envmap" in dev:
                L = L + jnp.where(miss[..., None], beta * ld.env_lookup(dev, d), 0.0)
            # emitted at the hit (camera/specular paths see emitters directly)
            le = ld.emitted_radiance(dev, jnp.where(it.valid, it.light, -1), it.wo, it.ng)
            L = L + beta * le

            mp = self.mat_at(
                dev, it,
                u_mix=self.u1d(px, py, s, depth * 2000 + DIM_MIX),
            )
            if self.strategy == "all":
                for li_i in range(self.n_light_loop):
                    idx = jnp.full(o.shape[:-1], li_i, jnp.int32)
                    Ld = estimate_direct(
                        dev, self.light_distr, it, mp, px, py, s,
                        depth, light_idx=idx, salt_extra=li_i * 1000,
                        vis_segments=self.vis_segments,
                        sampler=(self.skind, self.spp),
                    )
                    L = L + jnp.where(it.valid[..., None], beta * Ld, 0.0)
                    nrays = nrays + 2 * it.valid.astype(jnp.int32)
            else:
                Ld = estimate_direct(
                    dev, self.light_distr, it, mp, px, py, s, depth,
                    vis_segments=self.vis_segments,
                    sampler=(self.skind, self.spp),
                )
                L = L + jnp.where(it.valid[..., None], beta * Ld, 0.0)
                nrays = nrays + 2 * it.valid.astype(jnp.int32)

            if depth + 1 >= self.max_depth:
                break
            # specular continuation only (directlighting.cpp SpecularReflect/
            # SpecularTransmit): non-specular paths stop here
            salt = depth * DIMS_PER_BOUNCE
            from tpu_pbrt.core.vecmath import to_local

            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            ul = self.u1d(px, py, s, salt + DIM_BSDF_LOBE + 77)
            u1, u2 = self.u2d(px, py, s, salt + DIM_BSDF_UV + 77)
            bs = bxdf.bsdf_sample(mp, wo_l, ul, u1, u2)
            cont = it.valid & bs.is_specular & (bs.pdf > 0.0)
            wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            beta = jnp.where(
                cont[..., None],
                beta * bs.f * (jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None],
                beta,
            )
            o = jnp.where(cont[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
            d = jnp.where(cont[..., None], wi_w, d)
            alive = cont
        return L, nrays
