"""AOIntegrator — ambient occlusion.

Capability match for pbrt-v3 src/integrators/ao.{h,cpp} (present in later
pbrt-v3; SURVEY.md §2c flags it "verify in fork"): cosine- or
uniform-weighted hemisphere visibility with a max distance. One occlusion
sample per camera sample (pixel samples average them, matching the
wavefront sampler model)."""

from __future__ import annotations

import jax.numpy as jnp

from tpu_pbrt.core.sampling import (
    UNIFORM_HEMISPHERE_PDF,
    cosine_hemisphere_pdf,
    cosine_sample_hemisphere,
    uniform_float,
    uniform_sample_hemisphere,
)
from tpu_pbrt.core.vecmath import dot, offset_ray_origin, to_world
from tpu_pbrt.integrators.common import (
    scene_intersect,
    scene_intersect_p,
    DIM_BSDF_UV,
    WavefrontIntegrator,
    make_interaction,
)


class AOIntegrator(WavefrontIntegrator):
    name = "ao"

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.cos_sample = params.find_one_bool("cossample", True)
        self.max_dist = params.find_one_float("maxdistance", float("inf"))

    def li(self, dev, o, d, px, py, s):
        hit = scene_intersect(dev, o, d, jnp.inf)
        it = make_interaction(dev, hit, o, d)
        nrays = jnp.ones(o.shape[:-1], jnp.int32)

        u1 = uniform_float(px, py, s, DIM_BSDF_UV)
        u2 = uniform_float(px, py, s, DIM_BSDF_UV + 100)
        if self.cos_sample:
            w_local = cosine_sample_hemisphere(u1, u2)
            pdf = cosine_hemisphere_pdf(w_local[..., 2])
        else:
            w_local = uniform_sample_hemisphere(u1, u2)
            pdf = jnp.full(u1.shape, UNIFORM_HEMISPHERE_PDF, jnp.float32)
        # flip into the hemisphere facing the viewer (ao.cpp: -w if
        # opposite n)
        wi = to_world(w_local, it.ss, it.ts, it.ns)
        flip = dot(wi, it.ns) * dot(it.wo, it.ns) < 0.0
        wi = jnp.where(flip[..., None], -wi, wi)
        o_sh = offset_ray_origin(it.p, it.ng, wi)
        occluded = scene_intersect_p(dev, o_sh, wi, self.max_dist)
        nrays = nrays + it.valid.astype(jnp.int32)
        cos_w = jnp.abs(dot(wi, it.ns))
        val = jnp.where(
            it.valid & ~occluded & (pdf > 0), cos_w / jnp.maximum(pdf, 1e-20) / jnp.pi, 0.0
        )
        L = jnp.broadcast_to(val[..., None], val.shape + (3,))
        return L, nrays
