"""Shared wavefront-integrator machinery.

Capability match for pbrt-v3 src/core/integrator.{h,cpp}:
- Integrator/SamplerIntegrator::Render — the tile loop. TPU-first redesign:
  instead of ParallelFor2D over 16x16 tiles with per-thread FilmTiles, the
  image x spp domain is a flat work index space, cut into fixed-size ray
  batches (<= MAX_RAYS_PER_DISPATCH). Each batch runs one jitted
  ray-gen -> Li -> film-scatter dispatch; film accumulation is associative
  so "tiles" merge by addition. Tiling across devices (shard_map over the
  work axis) is layered on in parallel/ (SURVEY.md §2f).
- UniformSampleOneLight / EstimateDirect (MIS NEE) — estimate_direct here.
- SurfaceInteraction construction (core/interaction.cpp): hit -> position,
  geometric/shading normals, uv, material/light ids.

Sampling convention: every random dimension is a pure function of
(pixel_x, pixel_y, sample_index, dimension_salt) via the counter-based RNG,
with the film dimension using a per-pixel-scrambled (0,2)-sequence — the
wavefront equivalent of pbrt's per-pixel sampler streams.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.traverse import (
    MAX_RAYS_PER_DISPATCH,
    Hit,
    bvh_intersect,
    bvh_intersect_p,
)
from tpu_pbrt.accel.wide import wide_intersect, wide_intersect_p
from tpu_pbrt.utils.clock import WALL


from tpu_pbrt.cameras import generate_rays
from tpu_pbrt.config import cfg
from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.film import FilmState
from tpu_pbrt.parallel.checkpoint import (
    checkpoint_exists,
    load_checkpoint,
    render_fingerprint,
    save_checkpoint,
)
from tpu_pbrt.core.sampling import (
    hash_u32,
    normalize_sampler_name,
    power_heuristic,
    sample_1d,
    sample_2d,
    sobol_2d,
    uniform_float,
)
from tpu_pbrt.core.vecmath import (
    coordinate_system,
    cross,
    dot,
    face_forward,
    normalize,
    offset_ray_origin,
    to_local,
    to_world,
)

def scene_intersect(dev, o, d, t_max, time=None) -> Hit:
    """Scene::Intersect — dispatches to the acceleration structure the
    scene compiler chose: the stream (sort/compaction wavefront) tracer
    (TPU-shaped default, coherence-independent), the all-triangles feature
    matmul for tiny scenes, or the packet/wide/binary walkers
    (TPU_PBRT_BVH=packet|wide|binary). time: per-ray shutter time in
    [0,1] for motion-blur scenes (dev carries tri_verts1)."""
    if "tstream" in dev:
        from tpu_pbrt.accel.stream import stream_intersect

        return stream_intersect(
            dev["tstream"], dev["tri_verts"], o, d, t_max,
            time=time, tri_verts1=dev.get("tri_verts1"),
            tv9T=dev.get("tri_verts9T"), tv9T1=dev.get("tri_verts1_9T"),
        )
    if "tpack" in dev:
        from tpu_pbrt.accel.packet import packet_intersect

        return packet_intersect(dev["tpack"], o, d, t_max)
    if "bfeat" in dev:
        from tpu_pbrt.accel.mxu import brute_feature_intersect

        bf = dev["bfeat"]
        n_tris = bf["feat"].shape[1] // 4
        hit = brute_feature_intersect(
            bf["feat"], bf["center"], n_tris, o, d, t_max, time=time
        )
        if "tri_verts1" in dev and time is not None:
            # shading must see the TIME-EVALUATED triangle, not the
            # shutter-start keyframe make_interaction would refetch
            prim = jnp.maximum(hit.prim, 0)
            tm = jnp.asarray(time, jnp.float32).reshape(-1, 1, 1)
            tv = (1.0 - tm) * dev["tri_verts"][prim] + tm * dev["tri_verts1"][prim]
            hit = hit._replace(tv=tv)
        return hit
    if "wbvh" in dev:
        return wide_intersect(dev["wbvh"], dev["tri_verts"], o, d, t_max)
    return bvh_intersect(dev["bvh"], dev["tri_verts"], o, d, t_max)


def scene_intersect_fused(dev, o, d, t_max, n_cam: int, time=None):
    """Fused camera+shadow closest-hit: full Hit for the first n_cam
    rays, bare prim ids for the tail (queued shadow rays only need
    prim >= 0; skipping their barycentric tri_verts refetch saves ~9
    gathered elements per shadow ray on the stream path)."""
    if "tstream" in dev:
        from tpu_pbrt.accel.stream import stream_intersect_split

        return stream_intersect_split(
            dev["tstream"], dev["tri_verts"], o, d, t_max, n_cam,
            time=time, tri_verts1=dev.get("tri_verts1"),
            tv9T=dev.get("tri_verts9T"), tv9T1=dev.get("tri_verts1_9T"),
        )
    hit = scene_intersect(dev, o, d, t_max, time=time)
    return jax.tree.map(lambda a: a[:n_cam], hit), hit.prim[n_cam:]


def scene_intersect_p(dev, o, d, t_max, time=None):
    """Scene::IntersectP — shadow-ray predicate."""
    if "tstream" in dev:
        from tpu_pbrt.accel.stream import stream_intersect_p

        return stream_intersect_p(dev["tstream"], o, d, t_max, time=time)
    if "tpack" in dev:
        from tpu_pbrt.accel.packet import packet_intersect_p

        return packet_intersect_p(dev["tpack"], o, d, t_max)
    if "bfeat" in dev:
        return scene_intersect(dev, o, d, t_max).prim >= 0
    if "wbvh" in dev:
        return wide_intersect_p(dev["wbvh"], dev["tri_verts"], o, d, t_max)
    return bvh_intersect_p(dev["bvh"], dev["tri_verts"], o, d, t_max)


def unoccluded_tr(dev, o, d, dist, cur_med, px, py, s, salt, segments=1):
    """VisibilityTester::Unoccluded/Tr (light.cpp): is the light sample
    visible, and with what transmittance?

    pbrt's Tr walk passes THROUGH null-BSDF surfaces (medium-interface
    container geometry), accumulating each sub-segment's medium
    transmittance and switching media at the crossing; only real-material
    hits occlude (ADVICE r1: MAT_NONE shapes must not block in-medium NEE).

    segments=1 is the cheap any-hit path for scenes with no null materials.
    cur_med None skips transmittance entirely (no media in flight).
    Returns (visible (R,), tr (R,3))."""
    from tpu_pbrt.core import media as md
    from tpu_pbrt.scene.compiler import MAT_NONE

    shape = o.shape[:-1]
    tr = jnp.ones(shape + (3,), jnp.float32)
    remaining = jnp.broadcast_to(jnp.asarray(dist, jnp.float32), shape) * 0.999
    mt = dev.get("media") if cur_med is not None else None

    if segments == 1:
        occluded = scene_intersect_p(dev, o, d, remaining)
        if mt is not None:
            med = jnp.where(~occluded, jnp.broadcast_to(cur_med, shape), -1)
            tr = md.medium_tr(mt, med, o, d, remaining, px, py, s, salt)
        return ~occluded, tr

    med = (
        jnp.broadcast_to(jnp.asarray(cur_med, jnp.int32), shape)
        if cur_med is not None
        else jnp.full(shape, -1, jnp.int32)
    )
    oo = o
    visible = jnp.zeros(shape, bool)
    active = jnp.ones(shape, bool)
    for k in range(segments):
        hit = scene_intersect(dev, oo, d, remaining)
        hit_any = active & (hit.prim >= 0)
        prim = jnp.maximum(hit.prim, 0)
        # tri_mat holds material-table indices; the null test is on the type
        is_null = hit_any & (dev["mat"]["type"][dev["tri_mat"][prim]] == MAT_NONE)
        seg_len = jnp.where(hit_any, hit.t, remaining)
        if mt is not None:
            tr_seg = md.medium_tr(
                mt, jnp.where(active, med, -1), oo, d, seg_len, px, py, s, salt + 7 * k
            )
            tr = jnp.where(active[..., None], tr * tr_seg, tr)
        visible = visible | (active & ~hit_any)
        # step past null interfaces, flipping the medium at the crossing
        step = is_null
        tv = dev["tri_verts"][prim]
        ng = normalize(cross(tv[..., 1, :] - tv[..., 0, :], tv[..., 2, :] - tv[..., 0, :]))
        going_in = dot(d, ng) < 0.0
        new_med = jnp.where(going_in, dev["tri_med_in"][prim], dev["tri_med_out"][prim])
        med = jnp.where(step, new_med, med)
        p_hit = oo + hit.t[..., None] * d
        oo = jnp.where(step[..., None], offset_ray_origin(p_hit, ng, d), oo)
        remaining = jnp.where(step, remaining - hit.t, remaining)
        active = step
    # lanes that ran out of segments while still inside null nesting count
    # as occluded (conservative; PASSTHROUGH_MARGIN bounds real scenes)
    return visible, tr


# dimension salts (one stream per logical sampler dimension; bounce-shifted)
DIM_FILM_X = 0
DIM_LENS = 2
DIM_TIME = 3
DIM_LIGHT_PICK = 4
DIM_LIGHT_UV = 5
DIM_BSDF_LOBE = 7
DIM_BSDF_UV = 8
DIM_RR = 10
DIM_MIX = 11
DIMS_PER_BOUNCE = 16


class ChunkDispatchError(RuntimeError):
    """A chunk dispatch failed (worker/device loss). poisons_state=True
    means the in-flight film accumulator cannot be trusted (mid-dispatch
    loss) and recovery must roll back to the last checkpoint; False means
    the dispatch never ran and a plain re-dispatch is exact."""

    def __init__(self, msg="chunk dispatch failed", poisons_state=False):
        super().__init__(msg)
        self.poisons_state = poisons_state


class NonFiniteWaveError(ChunkDispatchError):
    """The non-finite firewall found scrubbed deposits in a chunk under
    TPU_PBRT_NONFINITE=retry: the accumulated film holds ZEROED
    contributions where real radiance belonged, so the chunk counts as
    state-poisoning and recovery re-renders it exactly (rollback or
    restart + re-dispatch; the chaos nan injection fires once, so the
    re-run is clean and the final film bit-identical)."""

    def __init__(self, msg):
        super().__init__(msg, poisons_state=True)


class NonFiniteRadianceError(RuntimeError):
    """TPU_PBRT_NONFINITE=raise: a chunk deposited NaN/Inf radiance (the
    firewall scrubbed it, but strict mode treats any contamination as a
    hard error — debugging shaders/scenes where a silent zero would hide
    the bug)."""


def redispatch_backoff(chunk: int, attempt: int) -> float:
    """Seconds to sleep before re-dispatch `attempt` (1-based) of
    `chunk`: capped exponential backoff with DETERMINISTIC jitter —
    min(base * 2^(attempt-1), cap) scaled into [0.5, 1.0] by a hash of
    (chunk, attempt), so chaos-matrix recoveries are reproducible while
    real fleet retries still decorrelate across chunks. The tight
    no-backoff loop this replaces is exactly the BENCH_r04/r05 failure
    shape: a hung backend ate the whole capture budget in retries."""
    base = float(cfg.retry_backoff)
    cap = float(cfg.retry_backoff_cap)
    if base <= 0.0:
        return 0.0
    b = min(base * (2.0 ** max(attempt - 1, 0)), cap)
    frac = (zlib.crc32(f"{chunk}:{attempt}".encode()) & 0xFFFF) / 65535.0
    return b * (0.5 + 0.5 * frac)


def live_film_carries(depth: int) -> int:
    """Worst-case simultaneously-LIVE film accumulator buffers for one
    job dispatching through a depth-N window — the shared term of
    hbmcheck's static HBM model (HC-CAP/HC-ALIAS) and protocheck's
    PROTO-HBM dynamic watermark. Depth 1 compiles donation into the
    chunk closure: input and output alias, ONE buffer. Depth > 1
    compiles donation OUT (a deferred checkpoint snapshot may still
    read a superseded carry), so each of the ``depth`` in-flight slices
    pins its un-donated input carry, plus the newest output: depth + 1."""
    d = max(1, int(depth))
    return 1 if d == 1 else d + 1


class DispatchWindow:
    """Bounded in-flight window of dispatched chunk-slices (ISSUE 13 /
    ROADMAP #2 — the refactor every other speed item inherits).

    JAX dispatch is async: ``plan.dispatch`` returns device futures
    immediately. This class gives that asynchrony structure: keep up to
    ``depth`` slices launched ahead, and RETIRE the oldest (block on
    its per-chunk sync handle) only when the window is full — so all
    host-side work between dispatches (deposit bookkeeping, preview
    develop, checkpoint serialization, WFQ scheduling, metrics/flight/
    trace recording) runs UNDER the device compute of the slices still
    in flight. ``depth`` 1 reproduces the strictly synchronous
    dispatch/block/host-work loop — the A/B baseline the
    ``host_overlap_fraction`` acceptance compares against. Bit-identity
    across depths holds by construction: the window moves SYNC POINTS,
    never the dispatched programs or their order.

    Deferred actions (``defer``) run once their cursor's slice has
    retired — the checkpoint path snapshots the film accumulator
    device-side at enqueue time (``parallel/checkpoint.film_snapshot``;
    the live accumulator is donated into the next dispatch) and
    serializes the snapshot to disk under in-flight compute.

    Error contract: a device failure surfacing at a retire sync is
    re-raised as ``ChunkDispatchError(poisons_state=True)`` so the
    caller's recovery ladder handles it like a mid-dispatch loss; on
    ANY ChunkDispatchError the caller calls ``flush`` before the ladder
    — poisoning failures discard the window outright (the rollback/
    restart re-renders everything it covered), clean failures quiesce
    it (block on the survivors, run the deferred durable writes) so
    completed work is never lost to an unrelated chunk's retry streak.
    """

    __slots__ = (
        "depth", "slices", "deferred", "on_wait", "span_name", "clock",
    )

    def __init__(
        self, depth: int, on_wait=None, span_name: str = "", clock=None,
    ):
        self.depth = max(1, int(depth))
        #: [(chunk index, per-chunk device sync handle, trace span|None)]
        self.slices: list = []
        #: [(cursor, fn)] — fn() runs once chunk cursor-1 has retired
        self.deferred: list = []
        self.on_wait = on_wait  # dt -> None (device_wait attribution)
        self.span_name = span_name
        # injected time source (utils/clock.py) — only for device-wait
        # attribution, but under a VirtualClock even measurement must
        # not touch the wall (protocheck's determinism contract)
        if clock is None:
            from tpu_pbrt.utils.clock import WALL as clock  # noqa: N811
        self.clock = clock

    def __len__(self) -> int:
        return len(self.slices)

    def push(self, chunk: int, handle, span=None) -> None:
        """`span` (tpu-scope): the async-span descriptor the caller
        opened at dispatch enqueue — {"name", "id", "cat", optional
        "flow"/"flow_name", "trace_id", "span_id"} — which the window
        closes at the slice's retire sync (or its discard), so the
        in-flight lifetime renders as one causally-bound track however
        deep the pipeline runs."""
        self.slices.append((chunk, handle, span))

    @staticmethod
    def _close_span(span, ok: bool) -> None:
        if not span:
            return
        from tpu_pbrt.obs.trace import TRACE

        fid = span.get("flow")
        if fid:
            TRACE.flow_finish(
                span.get("flow_name", "slice_flow"), id=fid, ok=ok
            )
        TRACE.async_end(
            span["name"], id=span["id"], cat=span.get("cat", "slice"), ok=ok
        )

    def close_spans(self, ok: bool) -> None:
        """Close every in-flight slice's span WITHOUT retiring — for
        callers that sync the whole job another way (the serve park/
        finalize paths block on the film state, which transitively
        blocks on every in-flight slice) and then drop the window. The
        handles stay; later flush/drain sees the spans already closed."""
        for i, (chunk, handle, span) in enumerate(self.slices):
            self._close_span(span, ok)
            self.slices[i] = (chunk, handle, None)

    def defer(self, cursor: int, fn) -> None:
        self.deferred.append((cursor, fn))

    def full(self) -> bool:
        return len(self.slices) >= self.depth

    def retire_one(self) -> int:
        """Block on the OLDEST in-flight slice (the device_wait phase),
        then run every deferred action whose cursor has retired.
        Returns the retired chunk index."""
        chunk, handle, span = self.slices.pop(0)
        from tpu_pbrt.obs.trace import TRACE

        targs = {
            k: span[k]
            for k in ("trace_id", "span_id")
            if span and k in span
        }
        t0 = self.clock.monotonic()
        ok = False
        try:
            if self.span_name:
                with TRACE.span(self.span_name, chunk=chunk, **targs):
                    jax.block_until_ready(handle)
            else:
                jax.block_until_ready(handle)
            ok = True
        except jax.errors.JaxRuntimeError as e:
            raise ChunkDispatchError(
                f"in-flight slice {chunk} failed: {e}", poisons_state=True
            ) from e
        finally:
            if self.on_wait is not None:
                self.on_wait(self.clock.monotonic() - t0)
            self._close_span(span, ok)
        while self.deferred and self.deferred[0][0] <= chunk + 1:
            self.deferred.pop(0)[1]()
        return chunk

    def drain(self) -> None:
        """Retire everything in flight and run every deferred action."""
        while self.slices:
            self.retire_one()
        while self.deferred:
            self.deferred.pop(0)[1]()

    def flush(self, discard: bool = False) -> None:
        """Error-path teardown (see the class docstring). discard=True
        drops handles and deferred actions without touching the device;
        discard=False drains — and any latent async failure surfaces
        HERE, inside the caller's ladder, as a poisoning
        ChunkDispatchError with the window already cleared."""
        if discard:
            # close (not leak) the in-flight spans: the validator's
            # pairing invariant holds on error paths too, and the
            # timeline records WHICH slices the rollback threw away
            for _, _, span in self.slices:
                self._close_span(span, ok=False)
            self.slices.clear()
            self.deferred.clear()
            return
        try:
            self.drain()
        finally:
            for _, _, span in self.slices:
                self._close_span(span, ok=False)
            self.slices.clear()
            self.deferred.clear()


def _fixed_batch_nonfinite(p_film, L):
    """Non-finite-firewall count for the fixed-batch deposit paths: rows
    the film is about to scrub, restricted to valid work items (body()
    parks the final chunk's invalid tail at p_film = -1e6). Returns None
    when telemetry is killed so the compiled program stays the exact
    pre-telemetry one."""
    # direct import (not the module-attr spelling): keeps jaxlint's
    # by-name call graph from conflating this kill-switch gate with the
    # unrelated `.enabled` recorder properties
    from tpu_pbrt.obs.counters import enabled

    if not enabled():
        return None
    from tpu_pbrt.core.film import nonfinite_mask

    valid = p_film[..., 0] > -1e5
    return jnp.sum(nonfinite_mask(L) & valid, dtype=jnp.int32)


#: the stream tracer mode ("jnp" | "fused") the most recent chunk plan
#: compiled against — process-wide, because the stream module's jitted
#: entry points are process-wide (see the cache-drop note in
#: prepare_chunks)
_LAST_TRACER: list = []


@dataclass
class ChunkPlan:
    """The chunked decomposition of one render's work domain plus the
    (cached) jitted dispatch closure — everything needed to advance a
    render one idempotent chunk at a time.

    This is the submit/step seam the render service (tpu_pbrt/serve)
    schedules on: ``dispatch(state, c)`` runs chunk ``c`` against a film
    accumulator and returns the new state + accounting aux, and the
    (film state, chunk cursor, rays, counters) tuple a caller carries
    between dispatches is exactly the checkpoint-v4 payload — so any
    job can be parked mid-render (emergency checkpoint, PR 5's path)
    and resumed with no lost work. ``WavefrontIntegrator.render`` below
    is one scheduling policy over this plan (run to completion with the
    recovery ladder); the multi-tenant service loop is another."""

    integrator: Any
    scene: Any
    mesh: Any
    film: Any
    cam: Any
    chunk: int
    per_dev: int
    n_dev: int
    n_chunks: int
    spp: int
    total: int
    npix: int
    bounds: tuple  # film sample bounds (x0, x1, y0, y1)
    pool: int
    use_regen: bool
    chaos_nan: bool
    starts: list
    jfn: Any
    fingerprint: str
    #: which stream flush/expand program the plan's closure compiled to
    #: ("fused" = the Pallas wavefront kernels, "jnp" = the XLA path) —
    #: surfaced in RenderResult.stats / bench telemetry for roofline
    #: attribution, and part of the jit-closure cache identity
    tracer: str = "jnp"
    #: in-flight window depth the closure compiled for (ISSUE 13):
    #: depth 1 donates the film carry (the zero-copy in-place chain,
    #: byte-for-byte the pre-pipeline program); depth > 1 compiles
    #: WITHOUT donation so the carry pipelines as a true async enqueue
    #: and the previous accumulator stays readable for deferred
    #: checkpoint writes — see prepare_chunks for the full rationale
    pipeline_depth: int = 1

    def dispatch(self, state, c: int):
        """Dispatch chunk ``c`` against ``state``. At pipeline_depth 1
        the film accumulator is DONATED — callers must use the returned
        state and never touch the argument again; at depth > 1 the
        closure compiled without donation and ``state`` stays readable
        (the deferred-checkpoint contract). Returns (state, aux)."""
        st = self.starts[c]
        if self.mesh is None and self.chaos_nan:
            from tpu_pbrt.chaos import CHAOS

            nanw = jax.device_put(np.int32(CHAOS.nan_wave_for(c)))
            return self.jfn(state, self.scene.dev, st[0], st[1], nanw)
        if self.mesh is None:
            return self.jfn(state, self.scene.dev, st[0], st[1])
        return self.jfn(state, self.scene.dev, st)

    def aux_parts(self, aux):
        """Split a dispatch's aux into (nrays, occ, ctr, spread, nf):
        occ = (live, waves, truncated) on the regen path, ctr/spread
        the telemetry blocks (None when killed), nf the fixed-batch
        firewall scrub count. Mirrors render()'s inline unpacking for
        other schedulers (the render service)."""
        if self.use_regen:
            nrays = aux[0]
            occ = tuple(aux[1:4])
            ctr = aux[4] if len(aux) > 4 else None
            spread = aux[5] if len(aux) > 5 else None
            return nrays, occ, ctr, spread, None
        if isinstance(aux, tuple):
            return aux[0], None, None, None, aux[1]
        return aux, None, None, None, None

    def capacity_audit(self):
        """Pre-render stream-capacity audit (DEFAULT ON — an overflow
        must fail in seconds, not after the full render has been paid
        for): re-trace one camera-ray chunk through the stats variant of
        the stream tracer and FAIL loudly if any traversal pair was
        dropped to capacity (silent false misses otherwise). Audits the
        primary wave only — bounce waves produce FEWER simultaneous
        pairs (dead lanes cull at init), so the camera wave bounds the
        live worklist for a given chunk size. TPU_PBRT_AUDIT_DROPS=0
        opts out; the drop count is memoized per (scene, chunk) so
        repeat preparations (warm service resubmits) pay nothing."""
        dev = self.scene.dev
        if not cfg.audit_drops or "tstream" not in dev:
            return
        integ = self.integrator
        memo = getattr(integ, "_audit_memo", None)
        if memo is None:
            memo = integ._audit_memo = {}
        # CompiledScene is not hashable: key by identity, keep the strong
        # ref in the value so the id can never be recycled under the memo
        audit_key = (self.scene, self.chunk)
        memo_key = (id(self.scene), self.chunk)
        if memo_key in memo:
            drops = memo[memo_key][1]
        else:
            from tpu_pbrt.accel.stream import stream_traverse_stats
            from tpu_pbrt.obs.trace import TRACE

            x0, _, y0, _ = self.bounds
            w = self.bounds[1] - self.bounds[0]
            chunk, total, spp, cam = self.chunk, self.total, self.spp, self.cam
            cached_audit = getattr(integ, "_audit_jit", None)
            if (
                cached_audit is not None
                and cached_audit[0][0] is self.scene
                and cached_audit[0][1] == chunk
            ):
                audit_rays = cached_audit[1]
            else:

                @jax.jit
                def audit_rays():
                    # staged under jit: eager array creation would be an
                    # implicit transfer under the audit's transfer guard.
                    # Cached across render() calls (like the chunk
                    # closure) so repeat renders stay at 0 recompiles.
                    k = jnp.arange(min(chunk, total), dtype=jnp.int32)
                    pix = k // spp
                    p_film0 = jnp.stack(
                        [(x0 + pix % w).astype(jnp.float32) + 0.5,
                         (y0 + pix // w).astype(jnp.float32) + 0.5], axis=-1)
                    o0, d0, _ = generate_rays(
                        cam, p_film0, jnp.zeros_like(p_film0)
                    )
                    return o0, d0

                integ._audit_jit = (audit_key, audit_rays)

            with TRACE.span("render/capacity_audit"):
                o0, d0 = audit_rays()
                *_, drops, _ = stream_traverse_stats(
                    dev["tstream"], o0, d0,
                    jax.device_put(np.float32(np.inf)),
                )
                drops = int(jax.device_get(drops))
            memo[memo_key] = (self.scene, drops)
        if drops > 0:
            msg = (
                f"stream tracer dropped {drops} traversal pairs to "
                "capacity on the camera wave — the render may have false "
                "misses; lower TPU_PBRT_CHUNK or raise TPU_PBRT_HEADROOM"
            )
            if cfg.allow_drops:
                from tpu_pbrt.utils.error import Warning as _W

                _W(msg)
            else:
                raise RuntimeError(msg)


@dataclass
class RenderResult:
    image: np.ndarray
    film_state: Any
    seconds: float
    rays_traced: int
    mray_per_sec: float
    spp: int
    #: fraction of the work domain actually rendered (< 1.0 when a
    #: max_seconds budget stopped the loop early; the image is a partial,
    #: noisier render but Mray/s is still a valid steady-state measurement)
    completed_fraction: float = 1.0
    stats: Dict[str, Any] = field(default_factory=dict)


class Interaction:
    """SoA surface interaction for a ray batch."""

    __slots__ = ("p", "ng", "ns", "ss", "ts", "uv", "mat", "light", "wo", "valid")

    def __init__(self, p, ng, ns, ss, ts, uv, mat, light, wo, valid):
        self.p = p
        self.ng = ng
        self.ns = ns
        self.ss = ss  # shading tangent
        self.ts = ts  # shading bitangent
        self.uv = uv
        self.mat = mat
        self.light = light
        self.wo = wo
        self.valid = valid


def make_interaction(dev, hit: Hit, o, d) -> Interaction:
    """Hit records -> surface interaction (interaction.cpp SurfaceInteraction
    + triangle.cpp's normal/uv interpolation)."""
    prim = jnp.maximum(hit.prim, 0)
    # the tracer already fetched the hit vertices (Hit.tv) — re-gathering
    # tri_verts costs ~9 gathered elements/ray on TPU
    tv = hit.tv if hit.tv is not None else dev["tri_verts"][prim]
    if "tri_sh16" in dev:
        # one lane-major (16, T) take: normals, uvs, packed ids
        sh = jnp.take(dev["tri_sh16"], prim, axis=1)  # (16, R)
        shT = jnp.moveaxis(sh, 0, -1)  # (..., 16)
        tn = shT[..., 0:9].reshape(shT.shape[:-1] + (3, 3))
        tuv = shT[..., 9:15].reshape(shT.shape[:-1] + (3, 2))
        packed = sh[15].astype(jnp.int32)
        mat_id = packed // 4096
        light_id = packed % 4096 - 1
    else:
        tn = dev["tri_normals"][prim]
        tuv = dev["tri_uvs"][prim]
        mat_id = dev["tri_mat"][prim]
        light_id = dev["tri_light"][prim]
    b0 = hit.b0
    b1 = hit.b1
    b2 = 1.0 - b0 - b1
    p = b0[..., None] * tv[..., 0, :] + b1[..., None] * tv[..., 1, :] + b2[..., None] * tv[..., 2, :]
    e1 = tv[..., 1, :] - tv[..., 0, :]
    e2 = tv[..., 2, :] - tv[..., 0, :]
    ng = normalize(cross(e1, e2))
    ns = b0[..., None] * tn[..., 0, :] + b1[..., None] * tn[..., 1, :] + b2[..., None] * tn[..., 2, :]
    ns_len = jnp.linalg.norm(ns, axis=-1, keepdims=True)
    ns = jnp.where(ns_len > 1e-12, ns / jnp.maximum(ns_len, 1e-20), ng)
    # orient geometric normal to the shading normal's hemisphere
    ng = face_forward(ng, ns)
    uv = b0[..., None] * tuv[..., 0, :] + b1[..., None] * tuv[..., 1, :] + b2[..., None] * tuv[..., 2, :]
    if "tri_tanT" in dev:
        # uv-aligned shading tangent (triangle.cpp dpdu) — required by
        # the hair BSDF (x axis along the curve); built only when the
        # scene needs it, else the cheap arbitrary frame below
        tan = jnp.moveaxis(jnp.take(dev["tri_tanT"], prim, axis=1), 0, -1)
        tan = tan - ns * jnp.sum(tan * ns, axis=-1, keepdims=True)
        tl = jnp.linalg.norm(tan, axis=-1, keepdims=True)
        ss0, ts0 = coordinate_system(ns)
        ok = tl[..., 0] > 1e-8
        ss = jnp.where(ok[..., None], tan / jnp.maximum(tl, 1e-20), ss0)
        ts = jnp.where(ok[..., None], cross(ns, ss), ts0)
    else:
        ss, ts = coordinate_system(ns)
    return Interaction(
        p=p,
        ng=ng,
        ns=ns,
        ss=ss,
        ts=ts,
        uv=uv,
        mat=mat_id,
        light=light_id,
        wo=-d,
        valid=hit.prim >= 0,
    )


def texture_footprint(dev, it_prim, p_hit, ng, o, d, dox, ddx, doy, ddy):
    """SurfaceInteraction::ComputeDifferentials (interaction.cpp) -> the
    texture-space uv differentials for MIPMap::Lookup.

    Intersect the two pixel-offset rays with the tangent plane at the
    hit, take dpdx/dpdy, and solve the 2x2 least-squares for duv/dx and
    duv/dy against the triangle's uv-parameterization derivatives
    (dev["tri_difT"], built at compile). Returns (R, 4) stacked
    [dudx, dvdx, dudy, dvdy], 0 where undefined (level-0 fallback) —
    the full anisotropic footprint the EWA-class imagemap filter
    (texture_eval.py) needs; isotropic consumers take the row max."""
    prim = jnp.maximum(it_prim, 0)
    rows = jnp.take(dev["tri_difT"], prim, axis=1)  # (8, R)
    dpdu = jnp.moveaxis(rows[0:3], 0, -1)
    dpdv = jnp.moveaxis(rows[3:6], 0, -1)
    n = ng
    denom0 = dot(d, n)

    def plane_hit(do_, dd_):
        d_off = d + dd_
        o_off = o + do_
        den = dot(d_off, n)
        t = dot(p_hit - o_off, n) / jnp.where(jnp.abs(den) < 1e-9, 1.0, den)
        return o_off + t[..., None] * d_off - p_hit

    dpdx = plane_hit(dox, ddx)
    dpdy = plane_hit(doy, ddy)
    a00 = dot(dpdu, dpdu)
    a01 = dot(dpdu, dpdv)
    a11 = dot(dpdv, dpdv)
    det = a00 * a11 - a01 * a01
    ok = (jnp.abs(det) > 1e-18) & (jnp.abs(denom0) > 1e-9)
    inv = 1.0 / jnp.where(ok, det, 1.0)

    def solve(dp):
        b0 = dot(dp, dpdu)
        b1 = dot(dp, dpdv)
        du = (a11 * b0 - a01 * b1) * inv
        dv = (a00 * b1 - a01 * b0) * inv
        return du, dv

    dudx, dvdx = solve(dpdx)
    dudy, dvdy = solve(dpdy)
    duv = jnp.stack([dudx, dvdx, dudy, dvdy], axis=-1)
    good = (ok & jnp.all(jnp.isfinite(duv), axis=-1))[..., None]
    # clamp insane footprints (grazing angles): beyond half the texture
    # the coarsest level is right anyway
    return jnp.where(good, jnp.clip(duv, -0.5, 0.5), 0.0)


def textured_mat(
    dev, mid, uv, p, tex_eval, tex_used, width=None, u_mix=None
) -> "bxdf.MatParams":
    """Material::ComputeScatteringFunctions' texture evaluation step
    (material.cpp): gather the constant-folded parameter table, then
    overwrite each slot that carries a texture id with its compiled
    evaluator's value at (uv, p). tex_used is a STATIC set — untextured
    slots cost nothing at trace time. u_mix resolves mix-material lanes
    to one sub-material (bxdf.resolve_mix) before the gather."""
    mid = bxdf.resolve_mix(dev["mat"], mid, u_mix)
    mp = bxdf.gather_mat(dev["mat"], mid)
    if mp.hz is not None:
        # hair: across-width offset h = -1 + 2*v from the ribbon uv
        # (curve.cpp's flat-curve parameterization)
        h = jnp.clip(-1.0 + 2.0 * uv[..., 1], -0.9995, 0.9995)
        mp = mp._replace(hz=mp.hz._replace(h=h))
    if tex_eval is None or "tex_atlas" not in dev or not tex_used:
        return mp
    mt = dev["mat"]
    atlas = dev["tex_atlas"]

    def ev3(slot, field):
        tid = mt[slot][mid]
        v = tex_eval(atlas, tid, uv, p, width)
        return jnp.where((tid >= 0)[..., None], v, field)

    def ev1(slot, field):
        tid = mt[slot][mid]
        v = jnp.mean(tex_eval(atlas, tid, uv, p, width), axis=-1)
        return jnp.where(tid >= 0, v, field)

    kw = {}
    if "kd" in tex_used:
        kw["kd"] = ev3("kd_tex", mp.kd)
    if "ks" in tex_used:
        kw["ks"] = ev3("ks_tex", mp.ks)
    if "sigma" in tex_used:
        kw["sigma"] = ev1("sigma_tex", mp.sigma)
    if "opacity" in tex_used:
        kw["opacity"] = ev3("opacity_tex", mp.opacity)
    if "rough" in tex_used:
        # roughness feeds the GGX alphas through the remap, so the
        # override recomputes ax/ay (gather_mat's derivation)
        tid = mt["rough_tex"][mid]
        r = jnp.mean(tex_eval(atlas, tid, uv, p, width), axis=-1)
        remap = mt["remap"][mid]
        a_t = jnp.where(
            remap > 0, bxdf.tr_roughness_to_alpha(r), jnp.maximum(r, 1e-3)
        )
        kw["ax"] = jnp.where(tid >= 0, a_t, mp.ax)
        kw["ay"] = jnp.where(tid >= 0, a_t, mp.ay)
        # rough_raw gates the rough-glass lobes (_is_rough_glass): a
        # roughness texture on glass must activate them too
        kw["rough_raw"] = jnp.where(tid >= 0, r, mp.rough_raw)
    return mp._replace(**kw)


def estimate_direct(
    dev, light_distr, it: Interaction, mp, px, py, s, bounce,
    light_idx=None, salt_extra=0, vis_segments=1, sampler=("random", 1),
):
    """pbrt EstimateDirect with MIS, light-sampling half + BSDF-sampling
    half. Traces one shadow ray and (for the BSDF half) one MIS ray.

    light_idx None -> UniformSampleOneLight semantics (random light, pick
    pmf folded into the pdf). light_idx (R,) -> EstimateDirect against that
    specific light (UniformSampleAllLights loops this over every light).
    vis_segments > 1 makes the shadow walk pass through MAT_NONE container
    geometry (see unoccluded_tr). Returns (R,3) direct radiance."""
    salt = bounce * DIMS_PER_BOUNCE + salt_extra

    skind, spp = sampler
    # ---- light-sampling half -------------------------------------------
    u_pick = sample_1d(skind, spp, px, py, s, salt + DIM_LIGHT_PICK)
    u1, u2 = sample_2d(skind, spp, px, py, s, salt + DIM_LIGHT_UV)
    if light_idx is None:
        ls = ld.sample_one_light(dev, light_distr, it.p, u_pick, u1, u2)
    else:
        ls = ld.sample_light_rows(dev, light_idx, it.p, u1, u2)
    wi_l = to_local(ls.wi, it.ss, it.ts, it.ns)
    wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
    f, bsdf_pdf = bxdf.bsdf_eval(mp, wo_l, wi_l)
    f = f * jnp.abs(dot(ls.wi, it.ns))[..., None]
    do_light = it.valid & (ls.pdf > 0.0) & (jnp.max(f, axis=-1) > 0.0) & (
        jnp.max(ls.li, axis=-1) > 0.0
    )
    # shadow ray
    o_s = offset_ray_origin(it.p, it.ng, ls.wi)
    visible, _ = unoccluded_tr(
        dev, o_s, ls.wi, jnp.where(do_light, ls.dist, -1.0), None,
        px, py, s, salt + DIM_LIGHT_UV + 300, segments=vis_segments,
    )
    vis = do_light & visible
    w_light = jnp.where(ls.is_delta, 1.0, power_heuristic(1.0, ls.pdf, 1.0, bsdf_pdf))
    contrib_l = f * ls.li * (w_light / jnp.maximum(ls.pdf, 1e-20))[..., None]
    L = jnp.where(vis[..., None], contrib_l, 0.0)

    # ---- BSDF-sampling half (non-delta lights: area + infinite) ---------
    ul = sample_1d(skind, spp, px, py, s, salt + DIM_BSDF_LOBE + 200)
    ub1, ub2 = sample_2d(skind, spp, px, py, s, salt + DIM_BSDF_UV + 200)
    bs = bxdf.bsdf_sample(mp, wo_l, ul, ub1, ub2)
    wi_w = to_world(bs.wi, it.ss, it.ts, it.ns)
    f_b = bs.f * jnp.abs(dot(wi_w, it.ns))[..., None]
    do_b = (
        it.valid
        & ~bs.is_specular
        & (bs.pdf > 0.0)
        & (jnp.max(f_b, axis=-1) > 0.0)
    )
    o_b = offset_ray_origin(it.p, it.ng, wi_w)
    hit_b = scene_intersect(dev, o_b, wi_w, jnp.inf)
    hit_light = dev["tri_light"][jnp.maximum(hit_b.prim, 0)]
    hit_emissive = (hit_b.prim >= 0) & (hit_light >= 0)
    # emitted toward us?
    if light_idx is not None:
        # restricted to one light: only count hits on that light's triangle
        hit_emissive = hit_emissive & (hit_light == light_idx)
    it_b = make_interaction(dev, hit_b, o_b, wi_w)
    le_b = ld.emitted_radiance(dev, jnp.where(hit_emissive, hit_light, -1), -wi_w, it_b.ng)
    # pdf of light-sampling this direction (for MIS): pick pmf is included
    # in the one-light case and excluded in the restricted case, matching
    # the pdf convention of the light half above
    lpdf_area = ld.emitted_pdf(
        dev, None if light_idx is not None else light_distr, it.p, it_b.p, hit_light, it_b.ng
    )
    if light_idx is not None:
        n_l = dev["light"]["type"].shape[0]
        lpdf_area = lpdf_area * n_l  # undo the uniform pmf folded by emitted_pdf
    # escaped ray toward the env light
    if "envmap" in dev:
        from tpu_pbrt.scene.compiler import LIGHT_INFINITE

        is_env_row = (
            dev["light"]["type"][jnp.maximum(light_idx, 0)] == LIGHT_INFINITE
            if light_idx is not None
            else None
        )
        le_env = ld.env_lookup(dev, wi_w)
        lpdf_env = ld.infinite_pdf(
            dev, None if light_idx is not None else light_distr, wi_w, ref_p=it.p
        )
        if light_idx is not None:
            lpdf_env = lpdf_env * dev["light"]["type"].shape[0]
        miss = hit_b.prim < 0
        if light_idx is not None:
            miss = miss & is_env_row
        le_b = jnp.where(miss[..., None], le_env, le_b)
        lpdf = jnp.where(miss, lpdf_env, jnp.where(hit_emissive, lpdf_area, 0.0))
        got_light = miss | hit_emissive
    else:
        lpdf = jnp.where(hit_emissive, lpdf_area, 0.0)
        got_light = hit_emissive
    w_b = power_heuristic(1.0, bs.pdf, 1.0, lpdf)
    contrib_b = f_b * le_b * (w_b / jnp.maximum(bs.pdf, 1e-20))[..., None]
    L = L + jnp.where((do_b & got_light & (lpdf > 0.0))[..., None], contrib_b, 0.0)
    return L


class WavefrontIntegrator:
    """Base class: the chunked render loop (SamplerIntegrator::Render)."""

    #: extra rays traced per camera ray inside li() (for the Mray/s meter)
    rays_per_camera_ray: float = 1.0

    #: injected time source (utils/clock.py) for the redispatch backoff
    #: window. Class-level so existing constructors stay untouched; the
    #: load/protocheck harnesses set it to a VirtualClock per instance,
    #: turning the recovery ladder's backoff into a virtual-time advance
    #: instead of a wall sleep. WALL forwards to time.sleep, so unarmed
    #: renders behave byte-identically.
    clock = WALL

    def __init__(self, params, scene, options):
        self.params = params
        self.scene = scene
        self.options = options
        strategy = scene.light_distribution_name
        # "uniform" -> None; "power" -> Distribution1D; "spatial" -> the
        # dense per-voxel SpatialLightDistribution (multi-light scenes;
        # single-light scenes gain nothing and keep power)
        if strategy == "uniform":
            self.light_distr = None
        elif strategy == "spatial" and getattr(scene, "spatial_distr", None) is not None:
            self.light_distr = scene.spatial_distr
        else:
            self.light_distr = scene.light_distr
        # shadow rays must pass through MAT_NONE container geometry (pbrt
        # VisibilityTester); pay the multi-segment walk only when the scene
        # actually has null interfaces
        self.vis_segments = 4 if scene.has_null_materials else 1
        # compiled texture evaluator (None when everything constant-folded)
        self.tex_eval = getattr(scene, "tex_eval", None)
        self.tex_used = getattr(scene, "tex_used", frozenset())
        # sampler plugin dispatch (VERDICT r3 #7): the scene file's
        # Sampler directive selects the per-dimension stream structure
        self.skind = normalize_sampler_name(scene.sampler.name)
        self.spp = int(scene.sampler.spp)
        self._prepare_sampler()

    def _prepare_sampler(self):
        """Bind the sobol sampler's pixel-grid log2 for THIS scene onto
        the integrator (self._sobol_m — static per scene, threaded
        explicitly into every traced body; ADVICE r4 retired the old
        module-global context). Also downgrades to the (0,2) sampler
        when spp * 4^m would overflow the int32 global index (sobol.cpp
        uses 64-bit here)."""
        self._sobol_m = 0
        if self.skind != "sobol":
            return
        from tpu_pbrt.core.sampling import sobol_resolution_log2

        m = sobol_resolution_log2(self.scene.film.full_resolution)
        self._sobol_m = m
        if self.spp << (2 * m) >= (1 << 31):
            from tpu_pbrt.utils.error import Warning as _W

            _W(
                "sobol: spp * 4^ceil(log2(res)) exceeds the 32-bit global "
                "index range; SUBSTITUTING the (0,2)-sequence sampler"
            )
            self.skind = "02"

    def u1d(self, px, py, s, salt):
        return sample_1d(self.skind, self.spp, px, py, s, salt)

    def u2d(self, px, py, s, salt):
        return sample_2d(self.skind, self.spp, px, py, s, salt)

    def _regen_enabled(self) -> bool:
        """Whether this integrator opts into the persistent-wavefront
        compaction+regeneration render path (PathIntegrator overrides;
        everything else keeps the fixed-batch chunk loop)."""
        return False

    def film_jitter(self, px, py, s):
        """In-pixel film sample offset for sample s of pixel (px, py) —
        a pure function of the work item, so the pool renderer can
        recompute it at deposit time instead of carrying it."""
        if self.skind == "sobol":
            # true SobolSampler film dims: the global index remap
            # guarantees sample s of pixel p lands inside p; dims
            # 0/1 give the in-pixel offset (sobol.cpp)
            from tpu_pbrt.core.sampling import (
                _sobol_raw_bits,
                sobol_interval_to_index,
            )

            m_res = self._sobol_m
            gi = sobol_interval_to_index(m_res, s, px, py)
            sc = jnp.float32((1 << m_res) * 2.3283064365386963e-10)
            fx = jnp.clip(
                _sobol_raw_bits(gi, 0).astype(jnp.uint32).astype(jnp.float32)
                * sc - px.astype(jnp.float32), 0.0, 0.9999999)
            fy = jnp.clip(
                _sobol_raw_bits(gi, 1).astype(jnp.uint32).astype(jnp.float32)
                * sc - py.astype(jnp.float32), 0.0, 0.9999999)
            return fx, fy
        # film sample: per-pixel scrambled (0,2)-sequence
        sx_scr = hash_u32(px, py, 0x11)
        sy_scr = hash_u32(px, py, 0x22)
        return sobol_2d(s, sx_scr, sy_scr)

    def work_to_rays(self, cam, spp, x0, y0, w, npix, start_pix, start_s, k):
        """Flat work offsets k (R,) -> camera rays.

        The global work index (pix*spp + sample) can exceed int32 at
        production spp, so the range start is carried as (start_pix,
        start_s) and the arithmetic stays within int32. Shared by the
        fixed-batch chunk body and the pool renderer's regeneration step
        — both derive the SAME (px, py, s) and sampler streams for a
        given work item, which is what makes the two modes produce the
        same estimator."""
        s_tot = start_s + k
        pix = start_pix + s_tot // spp
        s = s_tot % spp
        valid = pix < npix
        px = x0 + pix % w
        py = y0 + pix // w
        fx, fy = self.film_jitter(px, py, s)
        p_film = jnp.stack(
            [px.astype(jnp.float32) + fx, py.astype(jnp.float32) + fy],
            axis=-1,
        )
        u_lens = jnp.stack(list(self.u2d(px, py, s, DIM_LENS)), axis=-1)
        o, d, wt = generate_rays(cam, p_film, u_lens)
        return valid, px, py, s, p_film, o, d, wt

    def mat_at(self, dev, it, width=None, u_mix=None) -> "bxdf.MatParams":
        """Textured material parameters at a surface interaction; width
        is the optional (R, 4) texture-space ray-differential footprint
        (camera hits) driving EWA/trilinear mip selection; u_mix the
        optional mix-material selection draw (bxdf.resolve_mix)."""
        return textured_mat(
            dev, it.mat, it.uv, it.p, self.tex_eval, self.tex_used, width,
            u_mix,
        )

    # -- subclass hook ----------------------------------------------------
    def li(self, dev, o, d, px, py, s):
        raise NotImplementedError

    # -- chunk-plan preparation (the submit/step seam) --------------------
    def prepare_chunks(
        self, scene=None, mesh=None, chunk: Optional[int] = None,
    ) -> ChunkPlan:
        """Build (or re-use, via the single-slot jit cache) the chunk
        decomposition + jitted dispatch closure for rendering ``scene``
        on ``mesh``. ``chunk`` overrides the platform-default chunk size
        — the render service passes its slice width here so one
        submit/step quantum stays small enough to preempt between.

        Pure preparation: nothing is dispatched. Repeat calls with the
        same (scene, mesh, chunk, knobs) return a plan sharing the SAME
        compiled closure — the 0-recompile contract the jaxpr audit and
        the service's warm-resubmit criterion both pin."""
        scene = scene or self.scene
        if mesh is None and getattr(self.options, "mesh_shape", None):
            from tpu_pbrt.parallel.mesh import resolve_mesh

            mesh = resolve_mesh(self.options.mesh_shape)
        film = scene.film
        cam = scene.camera
        dev = scene.dev
        x0, x1, y0, y1 = film.sample_bounds()
        w = x1 - x0
        h = y1 - y0
        npix = w * h
        spp = scene.sampler.spp
        total = npix * spp
        n_dev = 1 if mesh is None else mesh.devices.size

        # Default chunk: the stream tracer's sort/compaction steps amortize
        # over BIG waves, so TPU dispatches carry 1M camera rays (a path
        # chunk = ~maxdepth fused 2M-ray traversal waves at ~1s each,
        # comfortably under the tunnel's ~60-90 s dispatch watchdog; the
        # MAX_RAYS_PER_DISPATCH cap in accel/traverse.py applies to the
        # legacy unrolled walkers, not the stream worklist). The legacy
        # per-ray walkers
        # (TPU_PBRT_BVH=packet|wide|binary) are orders of magnitude slower
        # on divergent waves and keep the watchdog-safe 8k dispatches. CPU
        # (tests) prefers smaller programs to bound compile time.
        is_tpu = jax.devices()[0].platform != "cpu"
        if is_tpu:
            default_chunk = (1 << 20) if cfg.bvh == "stream" else (1 << 13)
        else:
            default_chunk = min(MAX_RAYS_PER_DISPATCH >> 1, 1 << 17)
        if chunk is None:
            chunk = int(cfg.chunk if cfg.chunk is not None else default_chunk)
        chunk = int(chunk)
        chunk = min(chunk, max(1024 * n_dev, total))
        chunk = max((chunk // n_dev) * n_dev, n_dev)
        per_dev = chunk // n_dev
        n_chunks = (total + chunk - 1) // chunk

        # Persistent wavefront (ISSUE 1): integrators that opt in drain
        # each chunk's work range through a resident pool of path slots
        # (compaction + camera-ray regeneration, PathIntegrator.pool_chunk)
        # instead of advancing one fixed batch to max_depth. The pool is
        # ~1/4 of the per-device work range so regeneration has material
        # to refill from; TPU_PBRT_POOL overrides, TPU_PBRT_REGEN=0
        # disables (A/B against the fixed-batch loop).
        use_regen = self._regen_enabled()
        pool = 0
        if use_regen:
            pool = int(cfg.pool)
            if pool <= 0:
                pool = max(per_dev // 4, min(per_dev, 4096))
            pool = min(pool, per_dev)

        def body(dev, start_pix, start_s, n_rays_in_body):
            """Film contribution of work items [start, start+n) — a pure
            function of the work range (idempotent: the checkpoint/re-
            dispatch unit, SURVEY.md §5.3/5.4)."""
            k = jnp.arange(n_rays_in_body, dtype=jnp.int32)
            valid, px, py, s, p_film, o, d, wt = self.work_to_rays(
                cam, spp, x0, y0, w, npix, start_pix, start_s, k
            )
            out = self.li(dev, o, d, px, py, s)
            if len(out) == 4:
                # splat-producing integrator (BDPT t=1 / MLT / SPPM):
                # (L, nrays, splat_xy (R,K,2), splat_val (R,K,3))
                L, nrays, sxy, sval = out
                sval = jnp.where(valid[..., None, None], sval, 0.0)
                splats = (sxy.reshape(-1, 2), sval.reshape(-1, 3))
            else:
                L, nrays = out
                splats = None
            nrays = jnp.sum(jnp.where(valid, nrays, 0))
            p_film = jnp.where(valid[..., None], p_film, -1e6)  # lands outside crop
            return p_film, L, wt, nrays, splats

        def split_start(g0):
            """Global work index (python int, unbounded) -> int32 pair."""
            return g0 // spp, g0 % spp

        # A fresh jax.jit closure recompiles on every render() call; cache
        # the jitted chunk function across calls (single slot, keyed on the
        # scene object identity + static loop parameters) so repeat renders
        # of the same scene — bench warmup, spp-chunked loops, resumed
        # checkpoints, warm service resubmits — hit the compile cache. The
        # cache holds a strong ref to the scene, keeping the keyed identity
        # stable.
        # the telemetry kill switch changes the traced program (counter
        # carry present/absent), so it is part of the closure identity —
        # a reload() between renders must not reuse the stale closure
        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.obs import counters as _obs_counters

        # chaos nan:wave injection threads a traced wave index into the
        # single-device pool drain (-1 = clean); its PRESENCE is static
        # program shape, so it is part of the closure identity
        chaos_nan = CHAOS.has_nan() and use_regen and mesh is None
        # the fused-wavefront switch (TPU_PBRT_FUSED / _PALLAS) selects
        # which flush/expand program _bounce_wave's tracer compiles to —
        # a config reload() flipping it between renders must retrace,
        # not reuse the stale closure (same contract as the telemetry
        # kill switch). The wave the tracer sees is the fused 2R
        # camera+shadow batch PER DEVICE: pool slots under regen, else
        # the per-device chunk slice (2*chunk would misattribute mesh
        # renders near the FUSED_MAX_RAYS boundary — and a mislabeled
        # key is a stale-closure hole, not just a wrong stat).
        from tpu_pbrt.accel.stream import tracer_mode as _tracer_mode

        tracer = _tracer_mode(2 * (pool if use_regen else per_dev))
        # in-flight window depth this plan compiles for (ISSUE 13).
        # Depth 1 donates the film carry — in-place accumulation, the
        # exact pre-pipeline program. Depth > 1 compiles WITHOUT
        # donation: re-donating a chained carry (the previous
        # dispatch's donation-aliased output) trips XLA:CPU's
        # synchronous donation path and the whole chunk executes INLINE
        # in the dispatch call (measured: dispatch ~58 ms..3.7 s,
        # block_until_ready ~0 — the overlap the window exists to
        # create silently erased), and an un-donated carry is also what
        # lets a deferred checkpoint write hold the previous
        # accumulator while newer slices are in flight. The price is
        # one extra film allocation per in-flight slice;
        # TPU_PBRT_PIPELINE=1 restores the zero-copy chain. Donation
        # changes the compiled program, so it is part of the closure
        # identity.
        from tpu_pbrt.parallel.mesh import resolve_pipeline_depth

        pipe_depth = resolve_pipeline_depth(mesh)
        donate = (0,) if pipe_depth == 1 else ()
        jit_key = (
            scene, mesh, chunk, spp, total, n_dev, pool, use_regen,
            _obs_counters.enabled(), CHAOS.trace_key(), tracer,
            bool(donate),
        )
        cached = getattr(self, "_jit_cache", None)
        if _LAST_TRACER and _LAST_TRACER[-1] != tracer:
            # the stream tracer's module-level jits cache by aval shape
            # alone AND are shared across integrator instances; a
            # tracer-mode flip (TPU_PBRT_FUSED reload) with unchanged
            # shapes would let any later trace — even a brand-new
            # integrator's — inline a STALE inner jaxpr labeled with
            # the new mode. Drop the inner caches at every flip.
            from tpu_pbrt.accel.stream import clear_traverse_caches

            clear_traverse_caches()
        _LAST_TRACER[:] = [tracer]
        if cached is not None and all(
            a is b if i < 2 else a == b for i, (a, b) in enumerate(zip(cached[0], jit_key))
        ):
            jfn = cached[1]
        else:
            if use_regen and mesh is None:
                if chaos_nan:

                    def chunk_fn(
                        state: FilmState, dev, start_pix, start_s, nanw
                    ):
                        fs2, nrays, live, waves, trunc, ctr = self.pool_chunk(
                            dev, state, start_pix, start_s, chunk, pool,
                            film=film, cam=cam, nan_wave=nanw,
                        )
                        return fs2, (nrays, live, waves, trunc, ctr)

                else:

                    def chunk_fn(state: FilmState, dev, start_pix, start_s):
                        fs2, nrays, live, waves, trunc, ctr = self.pool_chunk(
                            dev, state, start_pix, start_s, chunk, pool,
                            film=film, cam=cam,
                        )
                        # ctr is None under TPU_PBRT_TELEMETRY=0 — an
                        # empty pytree leaf, so the killed program is
                        # unchanged
                        return fs2, (nrays, live, waves, trunc, ctr)

                jfn = jax.jit(chunk_fn, donate_argnums=donate)
            elif use_regen:
                from tpu_pbrt.parallel.mesh import (
                    device_spread,
                    sharded_pool_renderer,
                )

                def per_device_fn(dev, start):
                    # each device drains ITS work slice [start, start +
                    # per_dev) with its own resident pool and work counter
                    # (see sharded_pool_renderer for the lockstep-freedom
                    # contract)
                    fs2, nrays, live, waves, trunc, ctr = self.pool_chunk(
                        dev, film.init_state(), start[0, 0], start[0, 1],
                        per_dev, pool, film=film, cam=cam,
                    )
                    # the one-hot wave vector rides the aux psum out as
                    # the per-device wave-count spread (ROADMAP multi-
                    # chip metric); None when telemetry is killed
                    spread = (
                        device_spread(waves, n_dev)
                        if ctr is not None else None
                    )
                    return fs2, (nrays, live, waves, trunc, ctr, spread)

                step = sharded_pool_renderer(mesh, per_device_fn)

                def chunk_fn(state: FilmState, dev, starts):
                    contrib, aux = step(dev, starts)
                    from tpu_pbrt.core.film import merge_film

                    return merge_film(state, contrib), aux

                jfn = jax.jit(chunk_fn, donate_argnums=donate)
            elif mesh is None:
                # pixel-major chunks that tile the frame exactly take the
                # film's scatter-free aligned accumulation path
                aligned = film.aligned_chunk_pixels(chunk, spp) > 0

                def chunk_fn(state: FilmState, dev, start_pix, start_s):
                    p_film, L, wt, nrays, splats = body(dev, start_pix, start_s, chunk)
                    nf = _fixed_batch_nonfinite(p_film, L)
                    if aligned:
                        state = film.add_samples_aligned(
                            state, start_pix, spp, p_film, L, wt
                        )
                    else:
                        state = film.add_samples(state, p_film, L, wt)
                    if splats is not None:
                        state = film.add_splats(state, *splats)
                    return state, (nrays if nf is None else (nrays, nf))

                jfn = jax.jit(chunk_fn, donate_argnums=donate)
            else:
                from tpu_pbrt.parallel.mesh import sharded_chunk_renderer

                def per_device_fn(dev, start):
                    # start: this device's (1, 2) shard of the (n_dev, 2) pairs
                    p_film, L, wt, nrays, splats = body(dev, start[0, 0], start[0, 1], per_dev)
                    nf = _fixed_batch_nonfinite(p_film, L)
                    contrib = film.add_samples(film.init_state(), p_film, L, wt)
                    if splats is not None:
                        contrib = film.add_splats(contrib, *splats)
                    return contrib, (nrays if nf is None else (nrays, nf))

                step = sharded_chunk_renderer(mesh, per_device_fn)

                def chunk_fn(state: FilmState, dev, starts):
                    contrib, aux = step(dev, starts)
                    from tpu_pbrt.core.film import merge_film

                    return merge_film(state, contrib), aux

                jfn = jax.jit(chunk_fn, donate_argnums=donate)
            self._jit_cache = (jit_key, jfn)

        # start cursors move host->device once per plan; the transfer is
        # EXPLICIT (device_put) so the whole loop runs clean under
        # jax.transfer_guard("disallow") — the jaxpr audit's smoke render
        if mesh is None:
            starts = [
                tuple(
                    jax.device_put(np.int32(v))
                    for v in split_start(c * chunk)
                )
                for c in range(n_chunks)
            ]
        else:
            starts = []
            for c in range(n_chunks):
                pairs = [split_start(c * chunk + i * per_dev) for i in range(n_dev)]
                starts.append(
                    jax.device_put(np.asarray(pairs, np.int32))
                )  # (n_dev, 2)

        fp = render_fingerprint(chunk=chunk, spp=spp, total=total, scene=scene)
        return ChunkPlan(
            integrator=self, scene=scene, mesh=mesh, film=film, cam=cam,
            chunk=chunk, per_dev=per_dev, n_dev=n_dev, n_chunks=n_chunks,
            spp=spp, total=total, npix=npix, bounds=(x0, x1, y0, y1),
            pool=pool, use_regen=use_regen, chaos_nan=chaos_nan,
            starts=starts, jfn=jfn, fingerprint=fp, tracer=tracer,
            pipeline_depth=pipe_depth,
        )

    # -- the loop ---------------------------------------------------------
    def render(
        self, scene=None, mesh=None, checkpoint_path=None, checkpoint_every=0,
        max_seconds: float = 0.0,
    ) -> RenderResult:
        """The SamplerIntegrator::Render loop. mesh=None runs single-device;
        a jax.sharding.Mesh runs the SPMD tile scheduler (parallel/mesh.py):
        work indices round-robined across devices, film merged by psum.

        max_seconds > 0 time-boxes the loop: after the budget elapses the
        loop stops at a chunk boundary and returns a partial render with
        completed_fraction < 1. NOTE the work domain is pixel-major, so a
        partial film is spatially truncated (trailing pixels unsampled) —
        only valid for throughput measurement or checkpointed resume, not
        for image comparison. The throughput meter stays valid — it
        divides rays actually traced by wall time. The stop can overshoot
        the budget by a few in-flight chunk durations (the sync lags the
        dispatch to keep the pipe full)."""
        plan = self.prepare_chunks(scene, mesh)
        scene, mesh, film = plan.scene, plan.mesh, plan.film
        spp, total = plan.spp, plan.total
        n_chunks, pool = plan.n_chunks, plan.pool
        use_regen = plan.use_regen

        # -- checkpoint/resume (SURVEY.md §5.4): film accumulation is
        # associative and chunks are idempotent, so a checkpoint is just
        # (film state, chunk cursor); the counter-based RNG makes resumed
        # renders bit-identical to uninterrupted ones.
        from tpu_pbrt.utils.stats import STATS, ProgressReporter

        self._prepare_sampler()
        ckpt_path = checkpoint_path or getattr(self.options, "checkpoint_path", None)
        checkpoint_every = checkpoint_every or getattr(self.options, "checkpoint_every", 0)
        first_chunk = 0
        prev_rays = 0
        prev_ctr: Dict[str, Any] = {}
        state = film.init_state()
        fp = plan.fingerprint
        if ckpt_path and checkpoint_exists(ckpt_path):
            state, first_chunk, prev_rays, prev_ctr = load_checkpoint(
                ckpt_path, fp
            )

        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.obs import counters as obs_counters
        from tpu_pbrt.obs.flight import FLIGHT
        from tpu_pbrt.obs.metrics import METRICS, phase_histogram
        from tpu_pbrt.obs.trace import TRACE

        # per-phase wall-time attribution (ISSUE 10 / ROADMAP #1 stage
        # two): dispatch vs device-wait vs deposit-develop vs checkpoint,
        # observed into the process-wide phase histogram with the plan's
        # tracer label — one live capture yields the fused-vs-jnp phase
        # breakdown. Host-side only: the timed regions already exist,
        # the clock reads cost nothing the TRACE spans don't, and with
        # TPU_PBRT_METRICS=0 nothing is recorded or reported at all.
        metrics_on = METRICS.enabled
        phase_s: Dict[str, float] = {}

        def _phase(name: str, dt: float) -> None:
            if not metrics_on:
                return
            phase_s[name] = phase_s.get(name, 0.0) + dt
            phase_histogram().observe(dt, phase=name, tracer=plan.tracer)

        # pre-render stream-capacity audit (fails loudly on a worklist
        # overflow — see ChunkPlan.capacity_audit)
        plan.capacity_audit()

        quiet = bool(getattr(self.options, "quiet", False))
        progress = ProgressReporter(n_chunks, "Rendering", quiet=quiet)
        ray_counts = []
        occ_counts = []  # regen mode: (live lane-waves, waves) per chunk
        ctr_counts = []  # telemetry: per-chunk WaveCounters (device side)
        spread_counts = []  # telemetry (mesh): per-device wave vectors
        nf_counts = []  # fixed-batch firewall: per-chunk scrub counts
        # host-side recovery accounting (ISSUE 5): flows into the obs
        # counter dict, the flight recorder and RenderResult.stats
        recovery = {
            "redispatches": 0,
            "rollbacks": 0,
            "restarts": 0,
            "nonfinite_retries": 0,
            "backoff_ms": 0,
        }
        # retry extras the INITIAL resume brought in from prior
        # processes: an in-process rollback later reloads a snapshot
        # this very loop wrote, so prev_ctr then already bakes in part
        # of `recovery` — ctr_snapshot must add only the unbaked delta
        # (prev_ctr[key] - prior_rec[key] is this process's baked share)
        # or every rollback would double-count the extras it replays
        prior_rec = {
            k: int(prev_ctr.get(k, 0))
            for k in ("chunks_redispatched", "retry_backoff_ms")
        }

        def ctr_snapshot(n_ctr=None, n_nf=None, rec=None):
            """Cumulative host counter dict (checkpoint payload / final
            stats): the saved snapshot + everything fetched so far. The
            device_get inside to_host is the telemetry's one explicit
            drain-boundary fetch (checkpoint writes are drain
            boundaries too). Folds in the fixed-batch firewall counts
            and the host-side retry/backoff accounting. n_ctr/n_nf/rec
            restrict the snapshot to a LIST PREFIX + a recovery-dict
            copy captured when a deferred (pipelined) checkpoint was
            enqueued — the written counters cover exactly the chunks
            the snapshot's cursor covers, not the slices dispatched
            ahead of it."""
            snap = obs_counters.merge_host(
                prev_ctr, obs_counters.to_host(ctr_counts[:n_ctr])
            )
            nf = nf_counts[:n_nf]
            if nf:
                snap = obs_counters.merge_host(
                    snap,
                    {
                        "nonfinite_deposits": sum(
                            int(v) for v in jax.device_get(nf)
                        )
                    },
                )
            rec = recovery if rec is None else rec
            extra = {}
            for key, cur in (
                ("chunks_redispatched", rec["redispatches"]),
                ("retry_backoff_ms", rec["backoff_ms"]),
            ):
                # clamp: a rollback that fell back to a PRIOR process's
                # .prev can hold smaller extras than the initial resume
                baked = max(0, int(snap.get(key, 0)) - prior_rec[key])
                if cur > baked:
                    extra[key] = cur - baked
            return obs_counters.merge_host(snap, extra)

        chunks_done = first_chunk
        FLIGHT.heartbeat(
            "render", chunks=n_chunks, resumed_at=first_chunk, spp=spp,
        )
        # heartbeat cadence: bounded line count on long renders, but
        # every chunk on short ones so the flight timeline has substance
        hb_every = max(1, n_chunks // 16)
        # -- recovery policy (ISSUE 5): capped exponential backoff with
        # deterministic jitter between re-dispatches, an attempt budget
        # AND a wall-clock deadline (the BENCH_r04/r05 hang shape: a
        # tight retry loop must not burn the whole capture), and a final
        # emergency checkpoint before giving up so completed work is
        # never lost.
        retry_max = int(cfg.retry_max)
        retry_deadline = float(cfg.retry_deadline)
        firewall_mode = cfg.nonfinite  # scrub | raise | retry
        if firewall_mode != "scrub" and not obs_counters.enabled():
            # the strict modes read the firewall's scrub COUNT, which
            # rides the telemetry counters — with them killed the check
            # would silently degrade to scrub mode, the exact silent
            # contamination raise/retry exist to prevent
            raise ValueError(
                f"TPU_PBRT_NONFINITE={firewall_mode} needs the telemetry "
                "counters (the firewall's scrub count), but "
                "TPU_PBRT_TELEMETRY=0 disabled them; re-enable telemetry "
                "or use the default scrub mode"
            )

        def chunk_nonfinite(aux):
            """The per-chunk firewall scrub count (device scalar), or
            None when telemetry is off (nothing to check)."""
            if use_regen:
                ctr = aux[4] if len(aux) > 4 else None
                return None if ctr is None else ctr.nonfinite
            return aux[1] if isinstance(aux, tuple) else None

        t0 = time.time()
        c = first_chunk
        attempt = 0
        retry_t0 = None  # wall clock of the current failure streak
        timed_out = False
        # -- in-flight dispatch window (ISSUE 13): keep `depth` chunk-
        # slices launched ahead and retire the oldest only when the
        # window is full, so every piece of host-side work below —
        # progress/heartbeats, deposit bookkeeping, deferred checkpoint
        # serialization — runs under the device compute of the slices
        # still in flight. Counters and device_get fetches still
        # reconcile only at the existing drain boundaries. The depth
        # comes from the PLAN (not re-resolved here): donation is
        # compiled into the closure, and the loop's hold-the-carry
        # checkpoint deferral is only legal against the depth the
        # closure was built for.
        from tpu_pbrt.parallel.checkpoint import begin_host_copy

        depth = plan.pipeline_depth
        window = DispatchWindow(
            depth,
            on_wait=lambda dt: _phase("device_wait", dt),
            span_name="render/chunk_retire",
        )
        # tpu-scope: one trace context for the whole render request —
        # every in-flight chunk-slice becomes an async span under it,
        # causally bound dispatch->retire by a flow event, so a depth-N
        # trace renders as N overlapping tracks instead of flat X spans
        # that pretend the loop is serial
        rloop_tid = TRACE.trace_id("render")

        def _write_checkpoint(st, cursor, n_ray, n_ctr, n_nf, rec=None):
            """One durable cadence write: chunks [0, cursor) of `st`,
            counters restricted to the captured list prefixes."""
            t_ph = time.perf_counter()
            with TRACE.span("render/checkpoint", chunk=cursor):
                save_checkpoint(
                    ckpt_path, st, cursor,
                    prev_rays + sum(
                        int(r)
                        for r in jax.device_get(ray_counts[:n_ray])
                    ),
                    fingerprint=fp,
                    counters=ctr_snapshot(n_ctr, n_nf, rec),
                )
            _phase("checkpoint", time.perf_counter() - t_ph)

        def _queue_checkpoint(cursor):
            """Cadence checkpoint at `cursor`. With slices in flight the
            durable write is deferred to the cursor's retirement — the
            npz compression + CRC + fsync then run under the compute of
            the newer slices. At depth > 1 the carry is never donated
            (plan.pipeline_depth compiled donation out), so the
            deferred write simply HOLDS the live accumulator reference
            and starts its device->host copy early. With an empty
            window (depth 1, or the first chunk) write immediately:
            the exact pre-pipeline path."""
            lens = (len(ray_counts), len(ctr_counts), len(nf_counts))
            if not len(window):
                _write_checkpoint(state, cursor, *lens)
                return
            snap = state
            begin_host_copy(snap)
            rec = dict(recovery)
            window.defer(
                cursor,
                lambda: _write_checkpoint(snap, cursor, *lens, rec=rec),
            )

        with STATS.phase("Integrator/Render loop"):
            while c < n_chunks or len(window):
                try:
                    if c < n_chunks:
                        # failure seam (SURVEY.md §2e worker-failure row):
                        # a dispatch that dies is re-run — chunks are
                        # idempotent pure functions of the work range, so
                        # re-dispatch is exact. If the failure could have
                        # poisoned the accumulated film (a mid-flight
                        # device loss), the checkpoint (if enabled) rolls
                        # the loop back to the last durable state instead.
                        # The CHAOS registry (tpu_pbrt/chaos) injects
                        # deterministic failures here — the promoted form
                        # of the old test-only `_fault_hook` monkeypatch.
                        CHAOS.dispatch(c, attempt, mesh=mesh is not None)
                        try:
                            # the first dispatch blocks the host on jit
                            # trace+compile; later ones are async enqueues
                            # — and one issued with older slices still in
                            # flight has its host cost hidden under their
                            # compute, so it is attributed separately
                            # (dispatch_ahead)
                            if c == first_chunk:
                                ph_name = "dispatch_compile"
                                span = "render/chunk_dispatch+compile"
                            elif len(window):
                                ph_name = "dispatch_ahead"
                                span = "render/chunk_dispatch_ahead"
                            else:
                                ph_name = "dispatch"
                                span = "render/chunk_dispatch"
                            t_ph = time.perf_counter()
                            with TRACE.span(
                                span, chunk=c, tracer=plan.tracer,
                            ):
                                state, aux = plan.dispatch(state, c)
                            _phase(ph_name, time.perf_counter() - t_ph)
                        except jax.errors.JaxRuntimeError as e:
                            # real device/runtime loss mid-dispatch: the
                            # donated film accumulator can no longer be
                            # trusted — route through the poisoning
                            # recovery (checkpoint rollback or restart),
                            # never reuse `state`
                            raise ChunkDispatchError(
                                f"device dispatch failed: {e}",
                                poisons_state=True,
                            ) from e
                        if firewall_mode != "scrub":
                            # strict firewall: check THIS chunk's scrub
                            # count (costs one per-chunk device sync —
                            # opt-in; resolve_pipeline_depth forces the
                            # window to depth 1 in these modes, exactly
                            # because of this sync). raise-mode aborts;
                            # retry-mode treats the chunk as poisoned
                            # (its deposits hold zeroed radiance) and
                            # re-renders it exactly.
                            nf_dev = chunk_nonfinite(aux)
                            nf_ct = (
                                0 if nf_dev is None
                                else int(jax.device_get(nf_dev))
                            )
                            if nf_ct:
                                if firewall_mode == "raise":
                                    raise NonFiniteRadianceError(
                                        f"chunk {c} deposited {nf_ct} "
                                        "non-finite radiance sample(s) "
                                        "(scrubbed to zero); "
                                        "TPU_PBRT_NONFINITE=raise treats "
                                        "this as fatal"
                                    )
                                recovery["nonfinite_retries"] += 1
                                raise NonFiniteWaveError(
                                    f"non-finite firewall: chunk {c} "
                                    f"scrubbed {nf_ct} deposit(s)"
                                )
                        attempt = 0
                        retry_t0 = None
                        c += 1
                        if use_regen:
                            nrays, lv, wv, trunc = aux[:4]
                            occ_counts.append((lv, wv, trunc))
                            if len(aux) > 4 and aux[4] is not None:
                                ctr_counts.append(aux[4])
                            if len(aux) > 5 and aux[5] is not None:
                                spread_counts.append(aux[5])
                        elif isinstance(aux, tuple):
                            nrays, nf_dep = aux
                            nf_counts.append(nf_dep)
                        else:
                            nrays = aux
                        ray_counts.append(nrays)
                        progress.update()
                        chunks_done = c
                        if c == first_chunk + 1 or c % hb_every == 0:
                            FLIGHT.heartbeat(
                                "render", chunk=c, of=n_chunks,
                                render_s=round(time.time() - t0, 3),
                            )
                        if (
                            ckpt_path and checkpoint_every
                            and c % checkpoint_every == 0
                        ):
                            _queue_checkpoint(c)
                        sid = f"{rloop_tid}/c{c - 1}"
                        TRACE.async_begin(
                            "render/slice", id=sid, cat="slice",
                            chunk=c - 1, trace_id=rloop_tid, span_id=sid,
                        )
                        TRACE.flow_start("slice_flow", id=sid)
                        window.push(c - 1, nrays, span={
                            "name": "render/slice", "id": sid,
                            "cat": "slice", "flow": sid,
                            "trace_id": rloop_tid, "span_id": sid,
                        })
                    # retire the oldest slice(s): only when the window is
                    # full (the host work above ran under their compute),
                    # plus the full drain once the work domain is
                    # exhausted. Each retire blocks on ONE per-chunk sync
                    # handle — the device keeps executing the newer
                    # in-flight slices through the wait.
                    while len(window) and (window.full() or c >= n_chunks):
                        window.retire_one()
                    if max_seconds > 0:
                        # time-boxed mode: the retire above paces the wall
                        # clock to completed work while the window keeps
                        # the pipe full. When the measured chunk rate says
                        # the remaining budget cannot absorb the in-flight
                        # window, drain eagerly — bounding overshoot to
                        # ~1 chunk duration even for very slow chunks.
                        done_n = max(len(ray_counts) - len(window), 1)
                        rate = (time.time() - t0) / done_n
                        if (
                            max_seconds - (time.time() - t0)
                            < (depth + 2) * rate
                        ):
                            window.drain()
                        if time.time() - t0 > max_seconds:
                            timed_out = True
                except ChunkDispatchError as e:
                    # flush the in-flight window BEFORE the ladder: a
                    # poisoning failure discards it outright (rollback/
                    # restart re-renders everything it covered); a clean
                    # failure quiesces it — blocking on the survivors
                    # surfaces any latent async loss here, and the
                    # deferred durable writes land before the retry
                    # streak can burn the attempt budget
                    try:
                        window.flush(discard=e.poisons_state)
                    except ChunkDispatchError as e2:
                        e = e2  # the flush itself found a poisoned device
                        window.flush(discard=True)
                    attempt += 1
                    recovery["redispatches"] += 1
                    STATS.counter("Distribution/Chunks re-dispatched", 1)
                    now = time.time()
                    if retry_t0 is None:
                        retry_t0 = now
                    deadline_hit = (
                        retry_deadline > 0
                        and now - retry_t0 > retry_deadline
                    )
                    if attempt > retry_max or deadline_hit:
                        # unrecoverable: write a final emergency
                        # checkpoint (unless this very failure poisoned
                        # the accumulator — then the last durable file
                        # already holds everything trustworthy) so
                        # completed work survives the crash
                        if ckpt_path and not e.poisons_state:
                            save_checkpoint(
                                ckpt_path, state, c,
                                prev_rays + sum(
                                    int(r)
                                    for r in jax.device_get(ray_counts)
                                ),
                                fingerprint=fp, counters=ctr_snapshot(),
                            )
                            FLIGHT.heartbeat(
                                "render_emergency_checkpoint", chunk=c,
                                attempt=attempt,
                            )
                        reason = (
                            f"retry deadline ({retry_deadline:.0f}s) exceeded"
                            if deadline_hit
                            else f"failed {attempt} times"
                        )
                        raise RuntimeError(f"chunk {c} {reason}") from e
                    if e.poisons_state and ckpt_path and checkpoint_exists(ckpt_path):
                        state, c, prev_rays, prev_ctr = load_checkpoint(
                            ckpt_path, fp
                        )
                        recovery["rollbacks"] += 1
                        ray_counts.clear()
                        occ_counts.clear()
                        ctr_counts.clear()
                        spread_counts.clear()
                        nf_counts.clear()
                    elif e.poisons_state:
                        # no durable state to roll back to: restart the render
                        state = film.init_state()
                        c = 0
                        prev_rays = 0
                        prev_ctr = {}
                        # the prior-process extras restarted with it
                        prior_rec = {k: 0 for k in prior_rec}
                        recovery["restarts"] += 1
                        ray_counts.clear()
                        occ_counts.clear()
                        ctr_counts.clear()
                        spread_counts.clear()
                        nf_counts.clear()
                    backoff_s = redispatch_backoff(c, attempt)
                    recovery["backoff_ms"] += int(backoff_s * 1000)
                    FLIGHT.heartbeat(
                        "render_redispatch", chunk=c, attempt=attempt,
                        poisoned=e.poisons_state,
                        backoff_s=round(backoff_s, 3),
                        backoff_total_ms=recovery["backoff_ms"],
                        error=str(e)[:200],
                    )
                    if backoff_s > 0:
                        # the backoff window's extent is known the
                        # moment it opens — record it as an explicit-
                        # duration span so the trace shows WHY the
                        # timeline has a hole
                        TRACE.complete(
                            "render/backoff", backoff_s * 1e6, chunk=c,
                            attempt=attempt, trace_id=rloop_tid,
                        )
                        self.clock.sleep(backoff_s)
                    continue
                if timed_out:
                    break
            # device execution of the queued wave batches (and, on a
            # mesh, the ICI film psum/merge) completes inside this sync
            t_ph = time.perf_counter()
            with TRACE.span("render/wave_drain+film_merge"):
                jax.block_until_ready(state)
            _phase("device_wait", time.perf_counter() - t_ph)
        secs = time.time() - t0
        progress.done()
        completed_fraction = chunks_done / max(n_chunks, 1)
        rays = prev_rays + int(sum(int(r) for r in jax.device_get(ray_counts)))
        STATS.counter("Integrator/Rays traced", rays)
        STATS.counter("Integrator/Camera rays traced", total)
        STATS.distribution("Integrator/Rays per camera ray", rays / max(total, 1))
        # the drain-boundary counter fetch (the telemetry's ONE
        # device_get for the whole render when no checkpoints fired)
        ctr_total = ctr_snapshot()
        if obs_counters.enabled() and ctr_total:
            FLIGHT.counters(ctr_total, phase="render_done")
        else:
            FLIGHT.heartbeat("render_done", rays=rays, seconds=round(secs, 3))
        if ckpt_path:
            t_ph = time.perf_counter()
            save_checkpoint(
                ckpt_path, state, chunks_done, rays, fingerprint=fp,
                counters=ctr_total,
            )
            _phase("checkpoint", time.perf_counter() - t_ph)
        # pbrt film.cpp WriteImage splatScale: splats (BDPT t=1, MLT, SPPM)
        # are deposited once per SAMPLE, so the developed image divides by
        # the number of samples actually taken — a time-boxed partial
        # render deposited only completed_fraction of them (the rgb plane
        # self-normalizes via its weight sum; the splat plane cannot)
        n_splat_samples = max(spp * completed_fraction, 1e-9)
        t_ph = time.perf_counter()
        with TRACE.span("render/develop"):
            img = film.develop(state, splat_scale=1.0 / n_splat_samples)
        FLIGHT.heartbeat("develop")
        if film.filename:
            with TRACE.span("render/write_image"):
                try:
                    film.write_image(state, splat_scale=1.0 / n_splat_samples)
                except Exception as e:  # noqa: BLE001
                    from tpu_pbrt.utils.error import Warning as _W

                    _W(f"could not write image {film.filename}: {e}")
        _phase("deposit_develop", time.perf_counter() - t_ph)
        stats: Dict[str, Any] = {}
        if "tstream" in scene.dev:
            # which flush/expand program the stream tracer compiled to
            # (jnp | fused) — bench.py copies this into its telemetry
            # block so live captures attribute the roofline ratio to
            # the right kernel
            stats["tracer_mode"] = plan.tracer
        if any(recovery.values()):
            # the render survived at least one failure — surface the
            # full retry/rollback/backoff accounting next to the image
            stats["recovery"] = dict(recovery)
        if use_regen and occ_counts:
            occ_host = jax.device_get(occ_counts)
            lv_t = sum(int(a) for a, _, _ in occ_host)
            wv_t = sum(int(b) for _, b, _ in occ_host)
            tr_t = sum(int(t) for _, _, t in occ_host)
            if tr_t:
                # the pool's max_waves safety cutoff fired with work still
                # outstanding — a silently darker image must never pass as
                # a completed render
                from tpu_pbrt.utils.error import Warning as _W

                _W(
                    f"persistent wavefront truncated {tr_t} chunk drain(s) "
                    "at the max_waves safety bound; the image is missing "
                    "samples (raise TPU_PBRT_POOL or report a bug)"
                )
                stats["truncated_chunks"] = tr_t
            stats |= {
                # fraction of pool slots holding a LIVE path at trace
                # time, averaged over every wave dispatched (the judged
                # occupancy metric: ~0.3-0.4 for the fixed-batch loop on
                # depth-5 diffuse scenes, near 1.0 with regeneration)
                "mean_wave_occupancy": lv_t / max(wv_t * pool, 1),
                "n_waves": wv_t,
                "pool": pool,
                "regen": True,
            }
            STATS.distribution(
                "Integrator/Wave occupancy", stats["mean_wave_occupancy"]
            )
        if obs_counters.enabled() and ctr_total:
            # the telemetry block: cumulative counters (checkpoint-
            # seeded, so resumed renders report end-to-end totals) and
            # the per-device wave-count spread (ROADMAP multi-chip
            # metric; degenerate single entry off-mesh). Gated on the
            # kill switch, NOT just on the snapshot: a telemetry-off
            # resume of a telemetry-on checkpoint has a non-empty saved
            # snapshot that covers none of THIS process's work — report
            # nothing rather than stale partials as end-to-end totals
            # (the checkpoint keeps carrying the snapshot forward so a
            # later telemetry-on resume still reports true totals)
            if spread_counts:
                spread_host = jax.device_get(spread_counts)
                per_dev = [
                    int(sum(v[i] for v in spread_host))
                    for i in range(len(spread_host[0]))
                ]
            elif use_regen and occ_counts:
                per_dev = [sum(int(b) for _, b, _ in occ_host)]
            else:
                per_dev = []
            stats["telemetry"] = {
                "counters": ctr_total,
                "wave_spread": obs_counters.spread_stats(per_dev),
            }
        if metrics_on and phase_s:
            # per-phase wall totals for THIS render (the cross-render
            # histogram with percentiles lives in the METRICS registry;
            # bench.py summarizes it via obs.metrics.phase_summary).
            # Present only with the registry on, so TPU_PBRT_METRICS=0
            # pins the exact pre-registry stats dict.
            stats["phase_seconds"] = {
                k: round(v, 6) for k, v in sorted(phase_s.items())
            }
        TRACE.maybe_export()
        return RenderResult(
            image=img,
            film_state=state,
            seconds=secs,
            rays_traced=rays,
            mray_per_sec=rays / max(secs, 1e-9) / 1e6,
            spp=spp,
            completed_fraction=completed_fraction,
            stats=stats,
        )
