"""BDPTIntegrator — bidirectional path tracing, wavefront-style.

Capability match for pbrt-v3 src/integrators/bdpt.{h,cpp}: camera and
light subpaths (GenerateCameraSubpath / GenerateLightSubpath), every
(s, t) connection strategy with s+t-2 <= maxdepth (ConnectBDPT), the
pdf-ratio MIS walk with junction overrides (MISWeight's ScopedAssignments
a1..a4), t=1 light-tracing splats through the camera (Film::AddSplat),
and the s=1 light-resampling strategy.

TPU-first redesign:
- pbrt's per-sample Vertex arrays become SoA arrays of shape (R, N) over
  the whole ray batch; subpaths extend one wave per depth slot.
- the (s, t) strategy double loop is STATIC Python (constant shapes);
  each strategy's contribution is dense masked math over all lanes.
- every strategy's connection visibility ray is buffered and traced in
  ONE (R x n_strategies) fused wave at the end — one big traversal
  instead of ~20 small ones (the stream tracer's costs are per-wave
  fixed + per-pair, so batching is the whole game).
- pdf_fwd/pdf_rev are stored area-measure exactly as in pbrt; the MIS
  junction overrides are computed per strategy with static vertex-slot
  reads.

Scope (checked loudly at construction):
- light subpaths start from every light type except INFINITE. DISTANT
  lights source subpaths with pbrt's infinite-light density treatment
  (bdpt.cpp "Correct subpath sampling densities for infinite area
  lights" + Vertex::PdfLight's planar beam density): the parallel beam
  reaches surfaces at the scene-disk density 1/(pi r^2) x |cos|, both
  for vertex-1 pdf_fwd and for the MIS junction's pt.pdf_rev —
  cross-converges with path within noise on a distant-lit scene.
  INFINITE lights remain excluded: escaped camera rays accumulate env
  radiance at MIS weight 1, which is unbiased exactly BECAUSE the env
  sources no other strategy (full env-subpath MIS is future work).
  SPPM uses BOTH as photon sources (no strategy MIS there).
- pinhole cameras for the t=1 splat strategies; with a lens the t=1
  family is skipped (losing only those strategies' variance reduction).
- no participating media (volpath covers medium scenes).
"""

from __future__ import annotations

import jax.numpy as jnp

from tpu_pbrt.cameras import camera_pdf_we, camera_sample_wi, camera_world_frame
from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.sampling import uniform_float
from tpu_pbrt.core.vecmath import (
    coordinate_system,
    dot,
    normalize,
    offset_ray_origin,
    to_local,
)
from tpu_pbrt.integrators.common import (
    DIMS_PER_BOUNCE,
    WavefrontIntegrator,
    make_interaction,
    scene_intersect,
    scene_intersect_p,
)

# sampler-dimension salt bases for the three BDPT sample streams
_SALT_CAM = 0
_SALT_LIGHT = 3001
_SALT_CONNECT = 6001


def _remap0(x):
    """MISWeight's remap0: pdf 0 (delta / unsampleable) counts as 1 so it
    cancels out of the ratio product."""
    return jnp.where(x == 0.0, 1.0, x)


def _convert_density(pdf_sa, p_from, p_to, n_to, to_is_surface):
    """Solid-angle pdf at p_from -> area pdf at p_to (vertex.h
    ConvertDensity): pdf * |cos(n_to, w)| / dist^2. to_is_surface False
    (camera/point endpoints) drops the cosine."""
    d = p_to - p_from
    d2 = jnp.maximum(jnp.sum(d * d, axis=-1), 1e-20)
    w = d / jnp.sqrt(d2)[..., None]
    cos_t = jnp.abs(dot(n_to, w)) if to_is_surface else 1.0
    return pdf_sa * cos_t / d2


class _Path:
    """SoA vertex storage for one subpath family, N static slots."""

    def __init__(self, R, N):
        self.p = jnp.zeros((R, N, 3), jnp.float32)
        self.ng = jnp.zeros((R, N, 3), jnp.float32)
        self.ns = jnp.zeros((R, N, 3), jnp.float32)
        self.beta = jnp.zeros((R, N, 3), jnp.float32)
        self.pdf_fwd = jnp.zeros((R, N), jnp.float32)
        self.pdf_rev = jnp.zeros((R, N), jnp.float32)
        self.mat = jnp.full((R, N), -1, jnp.int32)
        self.light = jnp.full((R, N), -1, jnp.int32)
        self.delta = jnp.zeros((R, N), bool)
        self.valid = jnp.zeros((R, N), bool)

    def set(self, i, **kw):
        for k, v in kw.items():
            setattr(self, k, getattr(self, k).at[:, i].set(v))


class BDPTIntegrator(WavefrontIntegrator):
    name = "bdpt"
    rays_per_camera_ray = 4.0

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        #: debug: restrict to a set of (s, t) strategies (tests/bisection)
        self._only = None
        from tpu_pbrt.utils.error import Warning as _W

        if scene.has_null_materials:
            _W("bdpt: null-interface materials are traversed as opaque")
        from tpu_pbrt.core.lights_dev import SpatialLightDistribution

        if isinstance(self.light_distr, SpatialLightDistribution):
            # BDPT's MIS walk evaluates pick pmfs at several path vertices;
            # the position-dependent strategy is not plumbed through it
            self.light_distr = scene.light_distr
        self._pinhole = float(scene.camera.lens_radius) == 0.0
        if not self._pinhole:
            _W("bdpt: lens camera — t=1 (light tracing) strategies skipped")
        import numpy as np

        from tpu_pbrt.scene.compiler import LIGHT_DISTANT, LIGHT_INFINITE

        lt_types = np.asarray(scene.dev["light"]["type"])
        if ((lt_types == LIGHT_DISTANT) | (lt_types == LIGHT_INFINITE)).any():
            if (lt_types == LIGHT_INFINITE).any():
                _W(
                    "bdpt: infinite lights contribute via escaped camera "
                    "rays and s=1 resampling only (env-subpath MIS is "
                    "future work); distant lights source full subpaths"
                )

    # ------------------------------------------------------------------
    def _walk(self, dev, path: _Path, o, d, beta, pdf_dir, alive, px, py,
              s, salt_base, n_steps, mode, origin_surface=None):
        """RandomWalk (bdpt.cpp:344): extend `path` writing slots
        [1, 1+n_steps). o/d leave the slot-0 vertex; pdf_dir is the
        solid-angle pdf of d from it. mode: 'radiance' (camera subpath)
        or 'importance' (light subpath, which carries pbrt's
        shading-normal correction). Returns (rays-traced, L_env): escaped
        radiance-mode rays pick up environment light with weight 1 —
        correct MIS because env is excluded from every other BDPT
        strategy (not a light-subpath source, masked out of s=1)."""
        nrays = jnp.zeros(alive.shape, jnp.int32)
        l_env = jnp.zeros(alive.shape + (3,), jnp.float32)
        prev_p = path.p[:, 0]
        prev_ns = path.ns[:, 0]
        # area-light origins are surface points (scatter-back density
        # conversion keeps the cosine); camera/point origins are not
        prev_surf = (
            jnp.zeros(alive.shape, bool) if origin_surface is None else origin_surface
        )
        for k in range(n_steps):
            i = 1 + k
            salt = salt_base + k * DIMS_PER_BOUNCE
            t_max = jnp.where(alive, jnp.inf, -1.0)
            hit = scene_intersect(dev, o, d, t_max)
            nrays = nrays + alive.astype(jnp.int32)
            it = make_interaction(dev, hit, o, d)
            found = alive & it.valid
            if mode == "radiance" and "envmap" in dev:
                miss = alive & (hit.prim < 0)
                l_env = l_env + jnp.where(
                    miss[..., None], beta * ld.env_lookup(dev, d), 0.0
                )
            pdf_area = _convert_density(pdf_dir, prev_p, it.p, it.ns, True)
            # mix materials resolve HERE (one draw per vertex) and the
            # RESOLVED sub-material id is what the vertex stores — every
            # later MIS/connection eval re-gathers the same leaf row, so
            # the whole (s,t) strategy family shades one consistent BSDF
            mid = bxdf.resolve_mix(
                dev["mat"], it.mat, uniform_float(px, py, s, salt + 11)
            )
            path.set(
                i,
                p=jnp.where(found[..., None], it.p, 0.0),
                ng=jnp.where(found[..., None], it.ng, 0.0),
                ns=jnp.where(found[..., None], it.ns, 0.0),
                beta=jnp.where(found[..., None], beta, 0.0),
                pdf_fwd=jnp.where(found, pdf_area, 0.0),
                mat=jnp.where(found, mid, -1),
                light=jnp.where(found, it.light, -1),
                valid=found,
            )
            if k == n_steps - 1:
                break  # the last slot never scatters
            mp = bxdf.gather_mat(dev["mat"], mid)
            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            bs = bxdf.bsdf_sample(
                mp, wo_l,
                uniform_float(px, py, s, salt + 7),
                uniform_float(px, py, s, salt + 8),
                uniform_float(px, py, s, salt + 9),
            )
            from tpu_pbrt.core.vecmath import to_world

            wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            cont = found & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
            corr = jnp.ones(alive.shape, jnp.float32)
            if mode == "importance":
                # pbrt CorrectShadingNormals: importance transport carries
                # the shading/geometric normal correction factor
                num = jnp.abs(dot(it.wo, it.ns)) * jnp.abs(dot(wi_w, it.ng))
                den = jnp.maximum(
                    jnp.abs(dot(it.wo, it.ng)) * jnp.abs(dot(wi_w, it.ns)), 1e-9
                )
                corr = num / den
            throughput = bs.f * (
                jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20)
            )[..., None]
            beta = jnp.where(cont[..., None], beta * throughput * corr[..., None], beta)
            # reverse pdf of the PREVIOUS vertex (scattering backwards)
            _, pdf_rev_sa = bxdf.bsdf_eval(
                mp, to_local(wi_w, it.ss, it.ts, it.ns), wo_l
            )
            pdf_rev_sa = jnp.where(bs.is_specular, 0.0, pdf_rev_sa)
            d_b = prev_p - it.p
            d2_b = jnp.maximum(jnp.sum(d_b * d_b, axis=-1), 1e-20)
            w_b = d_b / jnp.sqrt(d2_b)[..., None]
            cos_b = jnp.where(prev_surf, jnp.abs(dot(prev_ns, w_b)), 1.0)
            pdf_rev_prev = pdf_rev_sa * cos_b / d2_b
            path.pdf_rev = path.pdf_rev.at[:, i - 1].set(
                jnp.where(found, pdf_rev_prev, path.pdf_rev[:, i - 1])
            )
            path.delta = path.delta.at[:, i].set(found & bs.is_specular)
            prev_p = it.p
            prev_ns = it.ns
            prev_surf = jnp.ones(alive.shape, bool)
            o = jnp.where(cont[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
            d = jnp.where(cont[..., None], wi_w, d)
            pdf_dir = jnp.where(
                cont, jnp.where(bs.is_specular, 0.0, bs.pdf), pdf_dir
            )
            alive = cont
        return nrays, l_env

    # ------------------------------------------------------------------
    def _surface_pdf_sa(self, dev, path: _Path, i, wo_w, wi_w):
        """Solid-angle BSDF pdf at surface vertex slot i."""
        mp = bxdf.gather_mat(dev["mat"], jnp.maximum(path.mat[:, i], 0))
        ns = path.ns[:, i]
        ss, ts = coordinate_system(ns)
        _, pdf = bxdf.bsdf_eval(
            mp, to_local(wo_w, ss, ts, ns), to_local(wi_w, ss, ts, ns)
        )
        return pdf

    def _surface_f(self, dev, path: _Path, i, wo_w, wi_w):
        """BSDF value at surface vertex slot i."""
        mp = bxdf.gather_mat(dev["mat"], jnp.maximum(path.mat[:, i], 0))
        ns = path.ns[:, i]
        ss, ts = coordinate_system(ns)
        f, _ = bxdf.bsdf_eval(
            mp, to_local(wo_w, ss, ts, ns), to_local(wi_w, ss, ts, ns)
        )
        return f

    # ------------------------------------------------------------------
    def li(self, dev, o, d, px, py, s):
        R = o.shape[0]
        n_t = self.max_depth + 2  # camera vertices incl. the camera point
        n_s = self.max_depth + 1  # light vertices incl. the light point
        cam = self.scene.camera
        light_distr = self.light_distr

        # ---------------- camera subpath --------------------------------
        cpath = _Path(R, n_t)
        cpath.set(
            0,
            p=o,
            ng=d,
            ns=d,
            beta=jnp.ones((R, 3), jnp.float32),
            pdf_fwd=jnp.ones((R,), jnp.float32),
            valid=jnp.ones((R,), bool),
            # pbrt's camera vertex is NOT delta: the t=1 light-tracing
            # family samples the same paths, and its pdf must enter every
            # strategy's MIS denominator through this flag
        )
        _, cam_pdf_dir = camera_pdf_we(cam, d)
        nrays, l_env = self._walk(
            dev, cpath, o, d, jnp.ones((R, 3), jnp.float32), cam_pdf_dir,
            jnp.ones((R,), bool), px, py, s, _SALT_CAM, n_t - 1, "radiance",
        )

        # ---------------- light subpath ---------------------------------
        les = ld.sample_le(
            dev, light_distr,
            uniform_float(px, py, s, _SALT_LIGHT),
            uniform_float(px, py, s, _SALT_LIGHT + 1),
            uniform_float(px, py, s, _SALT_LIGHT + 2),
            uniform_float(px, py, s, _SALT_LIGHT + 3),
            uniform_float(px, py, s, _SALT_LIGHT + 4),
        )
        lpath = _Path(R, n_s)
        from tpu_pbrt.scene.compiler import LIGHT_INFINITE as _LINF

        lt_type = dev["light"]["type"][les.li_idx]
        # INFINITE is excluded from subpaths (the s=0 escaped-ray env
        # accumulation carries weight 1 — see module Scope note);
        # DISTANT subpaths are enabled: the ratio walk handles their
        # delta direction via the planar beam density below
        l_ok = (
            les.supported
            & (lt_type != _LINF)
            & (les.pdf_pos > 0.0)
            & (les.pdf_dir > 0.0)
        )
        lpath.set(
            0,
            p=les.p,
            ng=les.n,
            ns=les.n,
            beta=jnp.where(
                l_ok[..., None], les.le / (les.pmf * les.pdf_pos)[..., None], 0.0
            ),
            pdf_fwd=jnp.where(l_ok, les.pmf * les.pdf_pos, 0.0),
            light=les.li_idx,
            valid=l_ok,
        )
        cos0 = jnp.where(les.is_delta, 1.0, jnp.abs(dot(les.n, les.d)))
        beta_l1 = lpath.beta[:, 0] * (
            cos0 / jnp.maximum(les.pdf_dir, 1e-20)
        )[..., None]
        o_l = jnp.where(
            les.is_delta[..., None], les.p, offset_ray_origin(les.p, les.n, les.d)
        )
        nrays_l, _ = self._walk(
            dev, lpath, o_l, les.d, beta_l1, les.pdf_dir, l_ok,
            px, py, s, _SALT_LIGHT + 10, n_s - 1, "importance",
            origin_surface=~les.is_delta,
        )
        nrays = nrays + nrays_l
        # bdpt.cpp "Correct subpath sampling densities for infinite area
        # lights": a delta-direction (distant) light reaches vertex 1
        # as a PARALLEL beam — its area density is the planar disk pdf
        # 1/(pi r^2) x |cos|, not the 1/d^2-converted direction pdf the
        # generic walk wrote (which collapses over the huge disk offset)
        from tpu_pbrt.scene.compiler import LIGHT_DISTANT as _LDIST0

        is_dd0 = dev["light"]["type"][jnp.maximum(les.li_idx, 0)] == _LDIST0
        wr0 = dev["world_radius"]
        planar1 = (1.0 / (jnp.pi * wr0 * wr0)) * jnp.abs(
            dot(lpath.ng[:, 1], les.d)
        )
        lpath.pdf_fwd = lpath.pdf_fwd.at[:, 1].set(
            jnp.where(
                is_dd0 & lpath.valid[:, 1], planar1, lpath.pdf_fwd[:, 1]
            )
        )
        light0_is_delta = les.is_delta
        cam_p, _cam_fwd = camera_world_frame(cam)
        cam_pb = jnp.broadcast_to(cam_p, (R, 3))

        # ---------------- MIS -------------------------------------------
        def mis_weight(sidx, tidx, qs_override=None, pt_is_camera=False):
            """bdpt.cpp MISWeight for strategy (s=sidx, t=tidx).

            qs_override (s==1): (p, ns, li_idx, pdf_origin) of the
            resampled light vertex. pt_is_camera (t==1): the camera point
            stands in as the camera-side endpoint."""
            if sidx + tidx == 2:
                return jnp.ones((R,), jnp.float32)

            # endpoint data
            light0_delta = light0_is_delta
            if sidx > 0:
                if qs_override is not None:
                    qs_p, qs_ns, qs_li, _, light0_delta = qs_override
                    qs_delta = jnp.zeros((R,), bool)
                else:
                    qs_p = lpath.p[:, sidx - 1]
                    qs_ns = lpath.ns[:, sidx - 1]
                    qs_li = lpath.light[:, 0]
                    qs_delta = lpath.delta[:, sidx - 1]
            if pt_is_camera:
                pt_p = cam_pb
                pt_ns = jnp.zeros((R, 3), jnp.float32)
                pt_delta = jnp.zeros((R,), bool)
                pt_surface = False
            else:
                pt_p = cpath.p[:, tidx - 1]
                pt_ns = cpath.ns[:, tidx - 1]
                pt_delta = cpath.delta[:, tidx - 1]
                pt_surface = True

            # ---- junction overrides (ScopedAssignments a1..a4) ---------
            # a1: pt.pdf_rev — the light side generating pt
            if sidx > 0:
                wi_qp = normalize(pt_p - qs_p)
                if sidx == 1:
                    _, pdf_dir = ld.le_pdfs(
                        dev, jnp.maximum(qs_li, 0), qs_ns, wi_qp
                    )
                    pt_pdf_rev = _convert_density(
                        pdf_dir, qs_p, pt_p, pt_ns, pt_surface
                    )
                    # delta-direction (distant) lights: pbrt's
                    # Vertex::PdfLight treats them as INFINITE lights —
                    # the density of the parallel beam at pt is the
                    # PLANAR disk density 1/(pi r^2) (area measure, no
                    # 1/d^2 conversion), times |cos| on surfaces. A zero
                    # here poisons every camera-side ratio into 1 and
                    # collapses the MIS weight to 1/#strategies.
                    from tpu_pbrt.scene.compiler import LIGHT_DISTANT as _LD

                    is_dd = dev["light"]["type"][jnp.maximum(qs_li, 0)] == _LD
                    wr_ = dev["world_radius"]
                    planar = 1.0 / (jnp.pi * wr_ * wr_)
                    if pt_surface:
                        planar = planar * jnp.abs(dot(pt_ns, wi_qp))
                    pt_pdf_rev = jnp.where(is_dd, planar, pt_pdf_rev)
                else:
                    wo_qs = normalize(lpath.p[:, sidx - 2] - qs_p)
                    pdf_sa = self._surface_pdf_sa(dev, lpath, sidx - 1, wo_qs, wi_qp)
                    pt_pdf_rev = _convert_density(
                        pdf_sa, qs_p, pt_p, pt_ns, pt_surface
                    )
            else:
                # s == 0: pt IS on a light: PdfLightOrigin
                li0 = cpath.light[:, tidx - 1]
                pmf = ld.light_pick_pmf(dev, light_distr, li0)
                area = dev["light"]["area"][jnp.maximum(li0, 0)]
                pt_pdf_rev = jnp.where(li0 >= 0, pmf / jnp.maximum(area, 1e-20), 0.0)

            # a2: ptMinus.pdf_rev — pt scattering backward
            ptm_pdf_rev = None
            if tidx >= 2:
                ptm_p = cpath.p[:, tidx - 2]
                ptm_ns = cpath.ns[:, tidx - 2]
                wi_ptm = normalize(ptm_p - pt_p)
                if sidx > 0:
                    wo_pt = normalize(qs_p - pt_p)
                    pdf_sa = self._surface_pdf_sa(dev, cpath, tidx - 1, wo_pt, wi_ptm)
                    ptm_pdf_rev = _convert_density(pdf_sa, pt_p, ptm_p, ptm_ns, True)
                else:
                    # s == 0: emission direction pdf from the light at pt
                    li0 = cpath.light[:, tidx - 1]
                    _, pdf_dir = ld.le_pdfs(
                        dev, jnp.maximum(li0, 0), cpath.ng[:, tidx - 1], wi_ptm
                    )
                    ptm_pdf_rev = _convert_density(pdf_dir, pt_p, ptm_p, ptm_ns, True)

            # a3: qs.pdf_rev — the camera side generating qs
            qs_pdf_rev = None
            if sidx > 0:
                wi_pq = normalize(qs_p - pt_p)
                if pt_is_camera:
                    _, pdf_dir = camera_pdf_we(cam, wi_pq)
                    qs_pdf_rev = _convert_density(pdf_dir, pt_p, qs_p, qs_ns, True)
                else:
                    wo_pt = normalize(cpath.p[:, tidx - 2] - pt_p)
                    pdf_sa = self._surface_pdf_sa(dev, cpath, tidx - 1, wo_pt, wi_pq)
                    qs_pdf_rev = _convert_density(pdf_sa, pt_p, qs_p, qs_ns, True)

            # a4: qsMinus.pdf_rev — qs scattering backward
            qsm_pdf_rev = None
            if sidx >= 2:
                qsm_p = lpath.p[:, sidx - 2]
                qsm_ns = lpath.ns[:, sidx - 2]
                wo_qs = normalize(pt_p - qs_p)
                wi_qsm = normalize(qsm_p - qs_p)
                pdf_sa = self._surface_pdf_sa(dev, lpath, sidx - 1, wo_qs, wi_qsm)
                qsm_pdf_rev = _convert_density(pdf_sa, qs_p, qsm_p, qsm_ns, True)

            # ---- sumRi over both sides ---------------------------------
            sum_ri = jnp.zeros((R,), jnp.float32)
            ri = jnp.ones((R,), jnp.float32)
            for i in range(tidx - 1, 0, -1):
                rev = cpath.pdf_rev[:, i]
                if i == tidx - 1:
                    rev = pt_pdf_rev
                elif i == tidx - 2 and ptm_pdf_rev is not None:
                    rev = ptm_pdf_rev
                ri = ri * _remap0(rev) / _remap0(cpath.pdf_fwd[:, i])
                d_i = pt_delta if i == tidx - 1 else cpath.delta[:, i]
                d_im1 = cpath.delta[:, i - 1]  # slot 0 (camera): False
                sum_ri = sum_ri + jnp.where(~d_i & ~d_im1, ri, 0.0)
            ri = jnp.ones((R,), jnp.float32)
            for i in range(sidx - 1, -1, -1):
                rev = lpath.pdf_rev[:, i]
                fwd = lpath.pdf_fwd[:, i]
                if i == sidx - 1:
                    rev = qs_pdf_rev
                    if qs_override is not None:
                        fwd = qs_override[3]  # PdfLightOrigin of resample
                elif i == sidx - 2 and qsm_pdf_rev is not None:
                    rev = qsm_pdf_rev
                ri = ri * _remap0(rev) / _remap0(fwd)
                d_i = qs_delta if i == sidx - 1 else lpath.delta[:, i]
                d_im1 = light0_delta if i == 0 else lpath.delta[:, i - 1]
                sum_ri = sum_ri + jnp.where(~d_i & ~d_im1, ri, 0.0)
            return 1.0 / (1.0 + sum_ri)

        # ---------------- strategies ------------------------------------
        L = l_env
        vis_o, vis_d, vis_t, pend = [], [], [], []

        def _skip(sidx, tidx):
            return self._only is not None and (sidx, tidx) not in self._only

        # ---- s = 0: the camera path hits a light -----------------------
        for t in range(2, n_t + 1):
            if _skip(0, t):
                continue
            v = cpath.valid[:, t - 1]
            lid = cpath.light[:, t - 1]
            on_light = v & (lid >= 0)
            wo = normalize(cpath.p[:, t - 2] - cpath.p[:, t - 1])
            le = ld.emitted_radiance(
                dev, jnp.where(on_light, lid, -1), wo, cpath.ng[:, t - 1]
            )
            c = cpath.beta[:, t - 1] * le
            has = on_light & (jnp.max(c, axis=-1) > 0.0)
            w = jnp.where(has, mis_weight(0, t), 0.0)
            L = L + jnp.where(has[..., None], c * w[..., None], 0.0)

        # ---- t = 1: light-tracing splats through the camera ------------
        if self._pinhole:
            for st in range(2, n_s + 1):
                # st == 1 (light point itself to the lens) is skipped: it
                # reconstructs directly-visible lights, which the s=0/t>=2
                # strategies already cover with lower variance
                if _skip(st, 1):
                    continue
                v = lpath.valid[:, st - 1]
                qp = lpath.p[:, st - 1]
                qns = lpath.ns[:, st - 1]
                qng = lpath.ng[:, st - 1]
                wi, dist, pdf, we, raster, in_b = camera_sample_wi(cam, qp)
                wo_q = normalize(lpath.p[:, st - 2] - qp)
                f_val = self._surface_f(dev, lpath, st - 1, wo_q, wi)
                num = jnp.abs(dot(wo_q, qns)) * jnp.abs(dot(wi, qng))
                den = jnp.maximum(
                    jnp.abs(dot(wo_q, qng)) * jnp.abs(dot(wi, qns)), 1e-9
                )
                f_val = f_val * (num / den)[..., None]
                c = (
                    lpath.beta[:, st - 1]
                    * f_val
                    * (we / jnp.maximum(pdf, 1e-20) * jnp.abs(dot(wi, qns)))[..., None]
                )
                has = v & in_b & (pdf > 0.0) & (jnp.max(c, axis=-1) > 0.0)
                w = jnp.where(has, mis_weight(st, 1, pt_is_camera=True), 0.0)
                contrib = jnp.where(has[..., None], c * w[..., None], 0.0)
                vis_o.append(jnp.where(has[..., None], offset_ray_origin(qp, qng, wi), 0.0))
                vis_d.append(jnp.where(has[..., None], wi, jnp.ones_like(wi)))
                vis_t.append(jnp.where(has, dist * 0.999, -1.0))
                pend.append(("splat", contrib, raster))

        # ---- s = 1: light resampling (NEE-like) ------------------------
        for t in range(2, min(n_t, self.max_depth + 1) + 1):
            if _skip(1, t):
                continue
            v = cpath.valid[:, t - 1]
            ptp = cpath.p[:, t - 1]
            ls = ld.sample_one_light(
                dev, light_distr, ptp,
                uniform_float(px, py, s, _SALT_CONNECT + t * 4),
                uniform_float(px, py, s, _SALT_CONNECT + t * 4 + 1),
                uniform_float(px, py, s, _SALT_CONNECT + t * 4 + 2),
            )
            wo_pt = normalize(cpath.p[:, t - 2] - ptp)
            f_pt = self._surface_f(dev, cpath, t - 1, wo_pt, ls.wi)
            cos_pt = jnp.abs(dot(ls.wi, cpath.ns[:, t - 1]))
            c = (
                cpath.beta[:, t - 1]
                * f_pt
                * ls.li
                * (cos_pt / jnp.maximum(ls.pdf, 1e-20))[..., None]
            )
            lt = dev["light"]
            li_row = jnp.maximum(ls.li_idx, 0)
            from tpu_pbrt.scene.compiler import LIGHT_INFINITE

            not_env = lt["type"][li_row] != LIGHT_INFINITE
            has = v & not_env & (ls.pdf > 0.0) & (jnp.max(c, axis=-1) > 0.0)
            # the resampled light vertex for MIS: its position, surface
            # normal (area rows: the emitting triangle's), and its
            # PdfLightOrigin = pick pmf x area-measure position pdf
            sam_p = ptp + ls.wi * ls.dist[..., None]
            tri = lt["tri"][li_row]
            tv = dev["tri_verts"][jnp.maximum(tri, 0)]
            n_tri = ld.triangle_normal(tv)
            sam_ns = jnp.where(ls.is_delta[..., None], -ls.wi, n_tri)
            pmf = ld.light_pick_pmf(dev, light_distr, li_row)
            area = lt["area"][li_row]
            # delta lights: Pdf_Le's pdfPos is 0 (point.cpp:186) -> the
            # origin pdf remaps to 1 in the ratio walk
            pdf_origin = jnp.where(
                ls.is_delta, 0.0, pmf / jnp.maximum(area, 1e-20)
            )
            w = jnp.where(
                has,
                mis_weight(
                    1, t,
                    qs_override=(sam_p, sam_ns, li_row, pdf_origin, ls.is_delta),
                ),
                0.0,
            )
            contrib = jnp.where(has[..., None], c * w[..., None], 0.0)
            vis_o.append(
                jnp.where(has[..., None], offset_ray_origin(ptp, cpath.ng[:, t - 1], ls.wi), 0.0)
            )
            vis_d.append(jnp.where(has[..., None], ls.wi, jnp.ones_like(ls.wi)))
            vis_t.append(jnp.where(has, ls.dist * 0.999, -1.0))
            pend.append(("add", contrib, None))

        # ---- s >= 2, t >= 2: surface-surface connections ---------------
        for t in range(2, n_t + 1):
            for st in range(2, n_s + 1):
                if st + t - 2 > self.max_depth or _skip(st, t):
                    continue
                vc = cpath.valid[:, t - 1]
                vl = lpath.valid[:, st - 1]
                ptp = cpath.p[:, t - 1]
                qsp = lpath.p[:, st - 1]
                link = qsp - ptp
                d2 = jnp.maximum(jnp.sum(link * link, axis=-1), 1e-20)
                dist = jnp.sqrt(d2)
                wi = link / dist[..., None]
                wo_pt = normalize(cpath.p[:, t - 2] - ptp)
                wo_qs = normalize(lpath.p[:, st - 2] - qsp)
                f_pt = self._surface_f(dev, cpath, t - 1, wo_pt, wi)
                f_qs = self._surface_f(dev, lpath, st - 1, wo_qs, -wi)
                qns = lpath.ns[:, st - 1]
                qng = lpath.ng[:, st - 1]
                num = jnp.abs(dot(wo_qs, qns)) * jnp.abs(dot(-wi, qng))
                den = jnp.maximum(
                    jnp.abs(dot(wo_qs, qng)) * jnp.abs(dot(-wi, qns)), 1e-9
                )
                f_qs = f_qs * (num / den)[..., None]
                g = (
                    jnp.abs(dot(wi, cpath.ns[:, t - 1]))
                    * jnp.abs(dot(-wi, qns))
                    / d2
                )
                c = (
                    cpath.beta[:, t - 1] * f_pt * g[..., None]
                    * f_qs * lpath.beta[:, st - 1]
                )
                has = vc & vl & (jnp.max(c, axis=-1) > 0.0)
                w = jnp.where(has, mis_weight(st, t), 0.0)
                contrib = jnp.where(has[..., None], c * w[..., None], 0.0)
                vis_o.append(
                    jnp.where(has[..., None], offset_ray_origin(ptp, cpath.ng[:, t - 1], wi), 0.0)
                )
                vis_d.append(jnp.where(has[..., None], wi, jnp.ones_like(wi)))
                vis_t.append(jnp.where(has, dist * 0.998, -1.0))
                pend.append(("add", contrib, None))

        # ---- one fused visibility wave gates every connection ----------
        splat_xy, splat_val = [], []
        if pend:
            O = jnp.concatenate(vis_o)
            D = jnp.concatenate(vis_d)
            T = jnp.concatenate(vis_t)
            occ = scene_intersect_p(dev, O, D, jnp.where(T > 0, T, -1.0))
            for i, (kind, contrib, raster) in enumerate(pend):
                seg = slice(i * R, (i + 1) * R)
                visible = ~occ[seg] & (T[seg] > 0)
                nrays = nrays + (T[seg] > 0).astype(jnp.int32)
                cv = jnp.where(visible[..., None], contrib, 0.0)
                if kind == "add":
                    L = L + cv
                else:
                    splat_xy.append(raster)
                    splat_val.append(cv)
        if splat_xy:
            return (
                L, nrays,
                jnp.stack(splat_xy, axis=1),  # (R, K, 2)
                jnp.stack(splat_val, axis=1),  # (R, K, 3)
            )
        return L, nrays
