"""SPPMIntegrator — stochastic progressive photon mapping, TPU-native.

Capability match for pbrt-v3 src/integrators/sppm.{h,cpp}
SPPMIntegrator::Render: per-iteration camera pass storing per-pixel
visible points, photon pass from Light::Sample_Le random walks, per-pixel
radius/flux updates (the Knaus-Zwicker style progressive shrink with
gamma = 2/3), and the final estimate
L = Ld/N_iter + tau / (N_iter * photonsPerIteration * pi * r^2).

TPU-first redesign of the two racy structures (SURVEY.md §5.2, §7 stage 8):
- pbrt's uniform hash grid of std::atomic linked lists (sppm.cpp grid
  build) becomes SORT-BY-CELL + searchsorted runs: photon deposits are
  sorted by integer cell id, each visible point scans the (bounded) runs
  of the up-to-8 cells overlapped by its radius-r bounding box, and the
  distance test decides membership exactly as in the reference. No
  atomics anywhere; the result is deterministic up to f32 addition order
  within a run (tested by photon-permutation invariance).
- pbrt's AtomicFloat Phi[3] accumulation becomes a dense masked
  sum over the scanned run slots.
- cross-device photon exchange (the fork's "global ray sort + photon
  atomics" axis): pixels AND photons shard over the mesh; each device
  traces its pixel shard's visible points and a disjoint global-id
  range of photons, then jax.lax.all_gather over ICI replicates the
  deposits so every device gathers its own visible points against the
  FULL photon set. Per-pixel state stays sharded; only the deposit
  exchange and the global max-radius (pmax) cross devices. The shard
  union reproduces the single-device photon set exactly, so a mesh
  render equals the single-device one up to f32 accumulation order
  (tested on a 4-device CPU mesh).


Capacity note: every cell run is scanned to EXHAUSTION — a while_loop
walks each run in `scancap`-photon chunks, so nothing is ever dropped
(pbrt's linked lists are unbounded and so, effectively, is this; the
chunk size only trades loop iterations against per-chunk width). The
`photons_dropped` stat is kept for API stability and is always 0.
"""

from __future__ import annotations

from typing import NamedTuple

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.cameras import generate_rays
from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core.sampling import hash_u32, sobol_2d, uniform_float
from tpu_pbrt.core.vecmath import (
    dot,
    normalize,
    offset_ray_origin,
    to_local,
    to_world,
)
from tpu_pbrt.integrators.common import (
    DIM_LENS,
    DIM_MIX,
    DIMS_PER_BOUNCE,
    RenderResult,
    WavefrontIntegrator,
    estimate_direct,
    make_interaction,
    scene_intersect,
)

# sampler-dimension salt bases for the two SPPM streams
_SALT_CAM = 12001
_SALT_PHOTON = 24001

#: progressive radius shrink parameter (sppm.cpp gamma)
_GAMMA = 2.0 / 3.0


class _VisiblePoints(NamedTuple):
    """SoA per-pixel visible points for one iteration (sppm.h VisiblePoint)."""

    p: jnp.ndarray  # (P,3)
    wo: jnp.ndarray  # (P,3) world
    ns: jnp.ndarray  # (P,3) shading frame
    ss: jnp.ndarray
    ts: jnp.ndarray
    beta: jnp.ndarray  # (P,3)
    uv: jnp.ndarray  # (P,2) surface uv (texture evaluation at gather)
    mat: jnp.ndarray  # (P,) material id, -1 = no VP this iteration
    ld: jnp.ndarray  # (P,3) this iteration's direct/emitted radiance


class _SPPMState(NamedTuple):
    """Persistent per-pixel state across iterations (sppm.h SPPMPixel)."""

    r2: jnp.ndarray  # (P,) current search radius^2
    n: jnp.ndarray  # (P,) accumulated photon count (gamma-weighted)
    tau: jnp.ndarray  # (P,3) accumulated flux
    ld: jnp.ndarray  # (P,3) accumulated direct radiance
    dropped: jnp.ndarray  # () photons truncated by scan_cap (stat)


class SPPMIntegrator(WavefrontIntegrator):
    name = "sppm"
    rays_per_camera_ray = 3.0

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        self.n_iterations = params.find_one_int("numiterations", 64)
        self.photons_per_iter = params.find_one_int("photonsperiteration", -1)
        self.initial_radius = params.find_one_float("radius", 1.0)
        #: photons per gather chunk (see capacity note above — a width/
        #: iterations tradeoff, not a truncation bound)
        self.scan_cap = params.find_one_int("scancap", 32)
        from tpu_pbrt.utils.error import Warning as _W

        if scene.has_null_materials:
            _W("sppm: null-interface materials are traversed as opaque")

    # ------------------------------------------------------------------
    # camera pass: one VP per pixel (sppm.cpp "Generate SPPM visible points")
    # ------------------------------------------------------------------
    def _camera_pass(self, dev, px, py, it_idx):
        scene = self.scene
        cam = scene.camera
        shape = px.shape
        s = jnp.full(shape, it_idx, jnp.int32)
        sx_scr = hash_u32(px, py, 0x31)
        sy_scr = hash_u32(px, py, 0x42)
        fx, fy = sobol_2d(s, sx_scr, sy_scr)
        p_film = jnp.stack(
            [px.astype(jnp.float32) + fx, py.astype(jnp.float32) + fy], -1
        )
        u_lens = jnp.stack(
            [
                uniform_float(px, py, s, _SALT_CAM + DIM_LENS),
                uniform_float(px, py, s, _SALT_CAM + DIM_LENS + 1),
            ],
            -1,
        )
        o, d, wt = generate_rays(cam, p_film, u_lens)
        beta = jnp.broadcast_to(wt[..., None], shape + (3,)).astype(jnp.float32)

        ld_acc = jnp.zeros(shape + (3,), jnp.float32)
        vp_p = jnp.zeros(shape + (3,), jnp.float32)
        vp_wo = jnp.zeros(shape + (3,), jnp.float32)
        vp_ns = jnp.zeros(shape + (3,), jnp.float32)
        vp_ss = jnp.zeros(shape + (3,), jnp.float32)
        vp_ts = jnp.zeros(shape + (3,), jnp.float32)
        vp_beta = jnp.zeros(shape + (3,), jnp.float32)
        vp_uv = jnp.zeros(shape + (2,), jnp.float32)
        vp_mat = jnp.full(shape, -1, jnp.int32)
        alive = jnp.ones(shape, bool)
        specular = jnp.ones(shape, bool)  # first hit counts as "specular"
        nrays = jnp.zeros((), jnp.int32)

        # one fori_loop iteration per depth: bsdf_sample/estimate_direct
        # instantiate ONCE (a Python depth loop re-instantiates them per
        # depth and XLA's compile time is superlinear in module size —
        # measured: the unrolled md=3 camera pass alone took >10 min to
        # compile on CPU, the rolled one seconds)
        from tpu_pbrt.integrators.common import Interaction

        def body(depth, carry):
            (o, d, beta, alive, specular, ld_acc, vp_p, vp_wo, vp_ns, vp_ss,
             vp_ts, vp_beta, vp_uv, vp_mat, nrays) = carry
            salt = _SALT_CAM + depth * DIMS_PER_BOUNCE
            t_max = jnp.where(alive, jnp.inf, -1.0)
            hit = scene_intersect(dev, o, d, t_max)
            nrays = nrays + jnp.sum(alive.astype(jnp.int32))
            it = make_interaction(dev, hit, o, d)
            found = alive & it.valid
            # escaped rays: env radiance (specular/first only, as in path)
            if "envmap" in dev:
                miss = alive & (hit.prim < 0) & specular
                ld_acc = ld_acc + jnp.where(
                    miss[..., None], beta * ld.env_lookup(dev, d), 0.0
                )
            # emitted at the hit (specular chains / first hit)
            le = ld.emitted_radiance(dev, jnp.where(found, it.light, -1), it.wo, it.ng)
            ld_acc = ld_acc + jnp.where(
                (found & specular)[..., None], beta * le, 0.0
            )
            mp = self.mat_at(
                dev, it, u_mix=uniform_float(px, py, s, salt + DIM_MIX)
            )
            # direct lighting at every real vertex (sppm.cpp accumulates
            # UniformSampleOneLight into pixel.Ld)
            it_masked = Interaction(
                it.p, it.ng, it.ns, it.ss, it.ts, it.uv, it.mat, it.light,
                it.wo, found,
            )
            ld_acc = ld_acc + beta * estimate_direct(
                dev,
                self.light_distr,
                it_masked,
                mp,
                px,
                py,
                s,
                depth,
                salt_extra=_SALT_CAM + 500,
                vis_segments=self.vis_segments,
                # the sample index here is it_idx in [0, n_iterations), NOT
                # a [0, spp) sampler index: the stratification domain must
                # cover the iteration count or later iterations replay the
                # same permuted NEE samples and direct light never converges
                sampler=(self.skind, self.n_iterations),
            )
            nrays = nrays + 2 * jnp.sum(found.astype(jnp.int32))
            has_diffuse, has_glossy, is_spec = bxdf._lobe_flags(mp)
            store = found & (has_diffuse | (has_glossy & (depth == self.max_depth - 1)))
            vp_p = jnp.where(store[..., None], it.p, vp_p)
            vp_wo = jnp.where(store[..., None], it.wo, vp_wo)
            vp_ns = jnp.where(store[..., None], it.ns, vp_ns)
            vp_ss = jnp.where(store[..., None], it.ss, vp_ss)
            vp_ts = jnp.where(store[..., None], it.ts, vp_ts)
            vp_beta = jnp.where(store[..., None], beta, vp_beta)
            vp_uv = jnp.where(store[..., None], it.uv, vp_uv)
            vp_mat = jnp.where(store, it.mat, vp_mat)
            alive = found & ~store
            # continue by BSDF sampling (specular/glossy chains); the last
            # depth's continuation is dead (alive is masked out below)
            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            bs = bxdf.bsdf_sample(
                mp,
                wo_l,
                uniform_float(px, py, s, salt + 7),
                uniform_float(px, py, s, salt + 8),
                uniform_float(px, py, s, salt + 9),
            )
            wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            cont = alive & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
            thr = bs.f * (jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None]
            beta = jnp.where(cont[..., None], beta * thr, beta)
            specular = bs.is_specular
            o = jnp.where(cont[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
            d = jnp.where(cont[..., None], wi_w, d)
            alive = cont & (depth < self.max_depth - 1)
            return (o, d, beta, alive, specular, ld_acc, vp_p, vp_wo, vp_ns,
                    vp_ss, vp_ts, vp_beta, vp_uv, vp_mat, nrays)

        carry = (o, d, beta, alive, specular, ld_acc, vp_p, vp_wo, vp_ns,
                 vp_ss, vp_ts, vp_beta, vp_uv, vp_mat, nrays)
        (o, d, beta, alive, specular, ld_acc, vp_p, vp_wo, vp_ns, vp_ss,
         vp_ts, vp_beta, vp_uv, vp_mat, nrays) = jax.lax.fori_loop(
            0, self.max_depth, body, carry
        )
        return (
            _VisiblePoints(
                vp_p, vp_wo, vp_ns, vp_ss, vp_ts, vp_beta, vp_uv, vp_mat, ld_acc
            ),
            nrays,
        )

    # ------------------------------------------------------------------
    # photon pass (sppm.cpp "Trace photons and accumulate contributions")
    # ------------------------------------------------------------------
    def _photon_pass(self, dev, n_photons, it_idx, pid0=0):
        """Trace n_photons light subpaths; return deposit SoA of shape
        (n_photons, max_depth): position, incident direction (the photon's
        travel direction), beta, valid. Deposits skip depth 0 (direct
        lighting is the camera pass's NEE, as in the reference). pid0
        offsets the photon RNG stream ids — the mesh path gives each
        device a disjoint global id range so the union of shards is
        EXACTLY the single-device photon set."""
        pid = pid0 + jnp.arange(n_photons, dtype=jnp.int32)
        py = jnp.full((n_photons,), 0x5995, jnp.int32) + it_idx
        s = jnp.full((n_photons,), it_idx, jnp.int32)

        def u(salt):
            return uniform_float(pid, py, s, _SALT_PHOTON + salt)

        les = ld.sample_le(dev, self.scene.light_distr, u(0), u(1), u(2), u(3), u(4))
        cos0 = jnp.where(les.is_delta, 1.0, jnp.abs(dot(les.n, les.d)))
        denom = jnp.maximum(les.pmf * les.pdf_pos * les.pdf_dir, 1e-20)
        beta = les.le * (cos0 / denom)[..., None]
        alive = les.supported & (jnp.max(beta, axis=-1) > 0.0)
        o = offset_ray_origin(les.p, les.n, les.d)
        o = jnp.where(les.is_delta[..., None], les.p, o)
        d = les.d

        D = self.max_depth
        dep_p = jnp.zeros((n_photons, D, 3), jnp.float32)
        dep_d = jnp.zeros((n_photons, D, 3), jnp.float32)
        dep_beta = jnp.zeros((n_photons, D, 3), jnp.float32)
        dep_valid = jnp.zeros((n_photons, D), bool)
        nrays = jnp.zeros((), jnp.int32)

        # rolled loop (fori_loop) for the same compile-size reason as the
        # camera pass: one bsdf_sample instantiation for all depths
        def body(depth, carry):
            o, d, beta, alive, dep_p, dep_d, dep_beta, dep_valid, nrays = carry
            salt = 100 + depth * DIMS_PER_BOUNCE
            t_max = jnp.where(alive, jnp.inf, -1.0)
            hit = scene_intersect(dev, o, d, t_max)
            nrays = nrays + jnp.sum(alive.astype(jnp.int32))
            it = make_interaction(dev, hit, o, d)
            found = alive & it.valid
            dep_found = found & (depth > 0)  # depth 0 = direct (NEE covers it)
            dep_p = jax.lax.dynamic_update_index_in_dim(
                dep_p, jnp.where(dep_found[..., None], it.p, 0.0), depth, 1
            )
            dep_d = jax.lax.dynamic_update_index_in_dim(dep_d, d, depth, 1)
            dep_beta = jax.lax.dynamic_update_index_in_dim(
                dep_beta, jnp.where(dep_found[..., None], beta, 0.0), depth, 1
            )
            dep_valid = jax.lax.dynamic_update_index_in_dim(
                dep_valid, dep_found, depth, 1
            )
            mp = self.mat_at(dev, it, u_mix=u(salt + DIM_MIX))
            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            bs = bxdf.bsdf_sample(mp, wo_l, u(salt + 7), u(salt + 8), u(salt + 9))
            wi_w = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            cont = found & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
            # importance transport: shading-normal correction (bdpt.cpp
            # CorrectShadingNormals)
            num = jnp.abs(dot(it.wo, it.ns)) * jnp.abs(dot(wi_w, it.ng))
            den = jnp.maximum(jnp.abs(dot(it.wo, it.ng)) * jnp.abs(dot(wi_w, it.ns)), 1e-9)
            thr = bs.f * (jnp.abs(dot(wi_w, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None]
            beta_new = beta * thr * (num / den)[..., None]
            # Russian roulette on the throughput ratio (sppm.cpp photon RR)
            by = jnp.max(beta, axis=-1)
            bny = jnp.max(beta_new, axis=-1)
            q = jnp.maximum(0.0, 1.0 - bny / jnp.maximum(by, 1e-20))
            u_rr = u(salt + 10)
            survive = u_rr >= q
            beta = jnp.where(
                (cont & survive)[..., None],
                beta_new / jnp.maximum(1.0 - q, 1e-6)[..., None],
                beta_new,
            )
            alive = cont & survive
            o = jnp.where(alive[..., None], offset_ray_origin(it.p, it.ng, wi_w), o)
            d = jnp.where(alive[..., None], wi_w, d)
            return o, d, beta, alive, dep_p, dep_d, dep_beta, dep_valid, nrays

        carry = (o, d, beta, alive, dep_p, dep_d, dep_beta, dep_valid, nrays)
        _, _, _, _, dep_p, dep_d, dep_beta, dep_valid, nrays = jax.lax.fori_loop(
            0, D, body, carry
        )
        return (
            dep_p.reshape(-1, 3),
            dep_d.reshape(-1, 3),
            dep_beta.reshape(-1, 3),
            dep_valid.reshape(-1),
            nrays,
        )

    # ------------------------------------------------------------------
    # gather: sort deposits by cell, VPs scan their 8 overlapped cells
    # ------------------------------------------------------------------
    def _gather(self, dev, vps: _VisiblePoints, dep_p, dep_d, dep_beta,
                dep_valid, r2, lo, cs, gres):
        """Returns (phi (P,3), m (P,), dropped ()). lo/cs/gres define the
        grid: cell = floor((p - lo)/cs), linear id = x + gx*(y + gy*z)."""
        K = self.scan_cap
        P = vps.p.shape[0]
        n_dep = dep_p.shape[0]
        gx, gy, gz = gres

        def cell_of(p):
            c = jnp.floor((p - lo) / cs).astype(jnp.int32)
            c = jnp.clip(c, 0, jnp.asarray([gx - 1, gy - 1, gz - 1], jnp.int32))
            return c[..., 0] + gx * (c[..., 1] + gy * c[..., 2])

        n_cells = gx * gy * gz
        dcell = jnp.where(dep_valid, cell_of(dep_p), n_cells)
        dcell_s, order = jax.lax.sort(
            [dcell, jax.lax.iota(jnp.int32, n_dep)], num_keys=1
        )
        dp_s = dep_p[order]
        dd_s = dep_d[order]
        db_s = dep_beta[order]

        has_vp = vps.mat >= 0
        r = jnp.sqrt(r2)
        base = jnp.floor((vps.p - lo - r[..., None]) / cs).astype(jnp.int32)
        from tpu_pbrt.integrators.common import textured_mat

        mp_vp = textured_mat(
            dev, jnp.maximum(vps.mat, 0), vps.uv, vps.p, self.tex_eval, self.tex_used
        )
        wo_l = to_local(vps.wo, vps.ss, vps.ts, vps.ns)

        # collect the 8 overlapped cells' run windows first (cheap index
        # math): starts/ends (P, 8)
        starts = []
        ends = []
        for ox in (0, 1):
            for oy in (0, 1):
                for oz in (0, 1):
                    c = base + jnp.asarray([ox, oy, oz], jnp.int32)
                    inb = (
                        (c[..., 0] >= 0) & (c[..., 0] < gx)
                        & (c[..., 1] >= 0) & (c[..., 1] < gy)
                        & (c[..., 2] >= 0) & (c[..., 2] < gz)
                    )
                    use = has_vp & inb
                    cid = jnp.where(
                        use, c[..., 0] + gx * (c[..., 1] + gy * c[..., 2]), n_cells
                    )
                    st = jnp.searchsorted(dcell_s, cid, side="left").astype(jnp.int32)
                    en = jnp.searchsorted(dcell_s, cid, side="right").astype(jnp.int32)
                    # lanes with no VP / out-of-grid cell scan nothing (the
                    # n_cells sentinel's run is the invalid-deposit tail)
                    starts.append(st)
                    ends.append(jnp.where(use, en, st))
        start8 = jnp.stack(starts, axis=1)  # (P, 8)
        end8 = jnp.stack(ends, axis=1)

        # scan each run in K-photon chunks inside ONE while_loop (a single
        # bsdf_eval instantiation, like the fori-rolled passes): every run
        # is scanned to EXHAUSTION — pbrt's unbounded linked lists drop
        # nothing, and neither does this. The loop runs until the wave's
        # longest remaining run is done; early iterations (radius spanning
        # few coarse cells) simply take more chunks.
        mp_b = jax.tree.map(
            lambda a: a[:, None] if a.ndim == 1 else a[:, None, :], mp_vp
        )
        wo_b = wo_l[:, None, :]
        koff = jnp.arange(K, dtype=jnp.int32)

        def cond(carry):
            j, phi, m = carry
            return jnp.any(start8 + j * K < end8)

        def body(carry):
            j, phi, m = carry
            # (P, 8, K) slots for this chunk of every cell's run
            slot = start8[..., None] + j * K + koff[None, None, :]
            ok = slot < end8[..., None]
            slot = jnp.minimum(slot, n_dep - 1).reshape(P, 8 * K)
            ok = ok.reshape(P, 8 * K)
            ppos = dp_s[slot]  # (P,8K,3)
            diff = ppos - vps.p[:, None, :]
            d2 = jnp.sum(diff * diff, axis=-1)
            within = ok & (d2 <= r2[:, None])
            wi_w = -dd_s[slot]
            wi_l = to_local(
                wi_w, vps.ss[:, None, :], vps.ts[:, None, :], vps.ns[:, None, :]
            )
            f, _ = bxdf.bsdf_eval(mp_b, wo_b, wi_l)
            contrib = jnp.where(within[..., None], f * db_s[slot], 0.0)
            return (
                j + 1,
                phi + jnp.sum(contrib, axis=1),
                m + jnp.sum(within, axis=1).astype(jnp.float32),
            )

        _, phi, m = jax.lax.while_loop(
            cond, body,
            (jnp.int32(0), jnp.zeros((P, 3), jnp.float32), jnp.zeros((P,), jnp.float32)),
        )
        return phi, m, jnp.zeros((), jnp.int32)

    # ------------------------------------------------------------------
    def _mesh_iteration(self, dev, mesh, state, px, py, P, n_photons):
        """Build the sharded per-iteration step (see module doc): pixels
        and photons shard over the mesh axis; photon deposits all_gather
        over ICI; per-pixel state stays sharded. Returns (iteration_fn,
        possibly padded state, total photon count)."""
        from functools import partial

        from tpu_pbrt.parallel.mesh import (
            SHARD_MAP_NOCHECK,
            TILE_AXIS,
            shard_map,
        )
        from jax.sharding import NamedSharding, PartitionSpec as PS

        n_dev = int(mesh.devices.size)
        pad = (-P) % n_dev
        if pad:
            # padded lanes duplicate pixel 0; their state rows are
            # dropped at develop time (render slices [:P])
            px = jnp.concatenate([px, jnp.repeat(px[:1], pad)])
            py = jnp.concatenate([py, jnp.repeat(py[:1], pad)])
            state = _SPPMState(
                r2=jnp.concatenate([state.r2, jnp.repeat(state.r2[:1], pad)]),
                n=jnp.concatenate([state.n, jnp.zeros((pad,), jnp.float32)]),
                tau=jnp.concatenate([state.tau, jnp.zeros((pad, 3), jnp.float32)]),
                ld=jnp.concatenate([state.ld, jnp.zeros((pad, 3), jnp.float32)]),
                dropped=state.dropped,
            )
        npd = -(-n_photons // n_dev)  # photons per device
        n_total = npd * n_dev

        shard = NamedSharding(mesh, PS(TILE_AXIS))
        state = _SPPMState(
            r2=jax.device_put(state.r2, shard),
            n=jax.device_put(state.n, shard),
            tau=jax.device_put(state.tau, shard),
            ld=jax.device_put(state.ld, shard),
            dropped=state.dropped,
        )
        px = jax.device_put(px, shard)
        py = jax.device_put(py, shard)

        # THREE separate shard_map jits, mirroring the single-device
        # cam/photon/gather split: XLA:CPU compile time is superlinear in
        # module size and one fused sharded module takes tens of minutes
        # to build (the split compiles like the single-device modules)
        sm = partial(shard_map, mesh=mesh, **SHARD_MAP_NOCHECK)

        @jax.jit
        @partial(
            sm,
            in_specs=(PS(), PS(TILE_AXIS), PS(TILE_AXIS), PS()),
            out_specs=(PS(TILE_AXIS), PS()),
        )
        def cam_shard(dev_, px_s, py_s, it_idx):
            vps, nrays = self._camera_pass(dev_, px_s, py_s, it_idx)
            return vps, jax.lax.psum(nrays, TILE_AXIS)

        @jax.jit
        @partial(sm, in_specs=(PS(), PS()), out_specs=(PS(TILE_AXIS), PS()))
        def photon_shard(dev_, it_idx):
            didx = jax.lax.axis_index(TILE_AXIS)
            dep_p, dep_d, dep_beta, dep_valid, nrays = self._photon_pass(
                dev_, npd, it_idx, pid0=didx * npd
            )
            return (dep_p, dep_d, dep_beta, dep_valid), jax.lax.psum(
                nrays, TILE_AXIS
            )

        @jax.jit
        @partial(
            sm,
            in_specs=(
                PS(),
                (PS(TILE_AXIS),) * 4,
                PS(TILE_AXIS),
                (PS(TILE_AXIS),) * 4,
            ),
            out_specs=((PS(TILE_AXIS),) * 4, PS()),
        )
        def gather_shard(dev_, state_tup, vps, deps):
            r2_s, n_s, tau_s, ld_s = state_tup
            # ICI photon exchange: every device sees the full deposit set
            dep_p, dep_d, dep_beta, dep_valid = (
                jax.lax.all_gather(x, TILE_AXIS, tiled=True) for x in deps
            )
            # grid cell size from the GLOBAL max radius so every shard
            # bins photons identically
            r_max = jax.lax.pmax(jnp.sqrt(jnp.max(r2_s)), TILE_AXIS)
            verts_lo = dev_["world_center"] - dev_["world_radius"]
            verts_hi = dev_["world_center"] + dev_["world_radius"]
            glo = verts_lo - r_max
            ext = (verts_hi + r_max) - glo
            cs = jnp.maximum(2.0 * r_max, jnp.max(ext) / 64.0)
            gres = (64, 64, 64)
            phi, m, dropped = self._gather(
                dev_, vps, dep_p, dep_d, dep_beta, dep_valid, r2_s, glo,
                cs, gres,
            )
            has = m > 0.0
            n_new = n_s + _GAMMA * m
            denom = jnp.maximum(n_s + m, 1e-20)
            r2_new = r2_s * n_new / denom
            tau_new = (tau_s + vps.beta * phi) * (
                r2_new / jnp.maximum(r2_s, 1e-30)
            )[..., None]
            out = (
                jnp.where(has, r2_new, r2_s),
                jnp.where(has, n_new, n_s),
                jnp.where(has[..., None], tau_new, tau_s),
                ld_s + vps.ld,
            )
            return out, jax.lax.psum(dropped, TILE_AXIS)

        def iteration(state: _SPPMState, it_idx):
            vps, nr_c = cam_shard(dev, px, py, it_idx)
            deps, nr_p = photon_shard(dev, it_idx)
            tup = (state.r2, state.n, state.tau, state.ld)
            (r2, n, tau, ld_), dropped = gather_shard(dev, tup, vps, deps)
            return (
                _SPPMState(r2=r2, n=n, tau=tau, ld=ld_,
                           dropped=state.dropped + dropped),
                nr_c + nr_p,
            )

        return iteration, state, n_total

    def render(self, scene=None, mesh=None, max_seconds: float = 0.0, **kw) -> RenderResult:
        scene = scene or self.scene
        dev = scene.dev
        film = scene.film
        x0, x1, y0, y1 = film.sample_bounds()
        w = x1 - x0
        h = y1 - y0
        P = w * h
        n_photons = self.photons_per_iter if self.photons_per_iter > 0 else P
        n_iter = self.n_iterations

        pix = jnp.arange(P, dtype=jnp.int32)
        px = x0 + pix % w
        py = y0 + pix // w

        # initial radius: pbrt's initialSearchRadius param; scale-free
        # default = 2 x pixel footprint estimate from the scene diagonal
        verts = np.asarray(dev["tri_verts"]).reshape(-1, 3)
        s_lo = verts.min(0)
        s_hi = verts.max(0)
        diag = float(np.linalg.norm(s_hi - s_lo))
        r0 = self.initial_radius
        if r0 <= 0.0:
            r0 = 2.0 * diag / max(w, h)

        state = _SPPMState(
            r2=jnp.full((P,), r0 * r0, jnp.float32),
            n=jnp.zeros((P,), jnp.float32),
            tau=jnp.zeros((P, 3), jnp.float32),
            ld=jnp.zeros((P, 3), jnp.float32),
            dropped=jnp.zeros((), jnp.int32),
        )

        # three separate jits instead of one fused `iteration`: XLA:CPU
        # compile time is strongly superlinear in module size (LLVM on the
        # giant fused loops), so splitting the phases compiles ~an order of
        # magnitude faster for identical runtime work
        cam_j = jax.jit(self._camera_pass)
        ph_j = jax.jit(self._photon_pass, static_argnums=(1,))

        @jax.jit
        def gather_update(state: _SPPMState, vps, dep_p, dep_d, dep_beta, dep_valid):
            # grid for THIS iteration: cell size from the current max radius
            r_max = jnp.sqrt(jnp.max(state.r2))
            glo = jnp.asarray(s_lo, jnp.float32) - r_max
            ghi = jnp.asarray(s_hi, jnp.float32) + r_max
            ext = ghi - glo
            # static grid resolution bound (64^3 < 2^31 linear ids); the
            # dynamic cell size still adapts to the shrinking radius
            cs = jnp.maximum(2.0 * r_max, jnp.max(ext) / 64.0)
            gres = (64, 64, 64)
            phi, m, dropped = self._gather(
                dev, vps, dep_p, dep_d, dep_beta, dep_valid, state.r2, glo, cs, gres
            )
            # progressive update (sppm.cpp "Update pixel values from this
            # pass's photons")
            has = m > 0.0
            n_new = state.n + _GAMMA * m
            denom = jnp.maximum(state.n + m, 1e-20)
            r2_new = state.r2 * n_new / denom
            tau_new = (state.tau + vps.beta * phi) * (r2_new / jnp.maximum(state.r2, 1e-30))[..., None]
            return _SPPMState(
                r2=jnp.where(has, r2_new, state.r2),
                n=jnp.where(has, n_new, state.n),
                tau=jnp.where(has[..., None], tau_new, state.tau),
                ld=state.ld + vps.ld,
                dropped=state.dropped + dropped,
            )

        def iteration(state: _SPPMState, it_idx):
            vps, nrays_c = cam_j(dev, px, py, it_idx)
            dep_p, dep_d, dep_beta, dep_valid, nrays_p = ph_j(dev, n_photons, it_idx)
            state = gather_update(state, vps, dep_p, dep_d, dep_beta, dep_valid)
            return state, nrays_c + nrays_p

        if mesh is not None and mesh.devices.size > 1:
            iteration, state, n_photons = self._mesh_iteration(
                dev, mesh, state, px, py, P, n_photons
            )

        t0 = time.time()
        rays = 0
        iters_done = 0
        from tpu_pbrt.utils.stats import STATS, ProgressReporter

        progress = ProgressReporter(
            n_iter, "SPPM", quiet=bool(getattr(self.options, "quiet", False))
        )
        with STATS.phase("Integrator/SPPM render"):
            for i in range(n_iter):
                state, nr = iteration(state, jnp.int32(i))
                rays += int(nr)
                iters_done = i + 1
                progress.update()
                if max_seconds > 0 and time.time() - t0 > max_seconds:
                    break
        progress.done()
        secs = time.time() - t0

        STATS.counter("SPPM/Photons dropped (scan cap)", int(state.dropped))
        STATS.counter("Integrator/Rays traced", rays)

        ni = max(iters_done, 1)
        ld_img = np.asarray(state.ld)[:P].reshape(h, w, 3) / ni
        tau = np.asarray(state.tau)[:P].reshape(h, w, 3)
        r2 = np.asarray(state.r2)[:P].reshape(h, w, 1)
        img = ld_img + tau / (ni * n_photons * np.pi * r2)
        img = np.ascontiguousarray(img, np.float32)
        if film.filename:
            try:
                from tpu_pbrt.utils.imageio import write_image as _wi

                _wi(film.filename, img)
            except Exception as e:  # noqa: BLE001
                from tpu_pbrt.utils.error import Warning as _W

                _W(f"could not write image {film.filename}: {e}")
        return RenderResult(
            image=img,
            film_state=None,
            seconds=secs,
            rays_traced=rays,
            mray_per_sec=rays / max(secs, 1e-9) / 1e6,
            spp=ni,
            completed_fraction=iters_done / max(n_iter, 1),
            stats={"photons_dropped": int(state.dropped)},
        )
