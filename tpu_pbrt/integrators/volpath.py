"""VolPathIntegrator — path tracing with participating media.

Capability match for pbrt-v3 src/integrators/volpath.{h,cpp} (the cloud
config, SURVEY.md §2c): every ray segment runs Medium::Sample against the
ray's current medium; medium interactions scatter by the Henyey-Greenstein
phase function with NEE (shadow rays carry transmittance), surface
interactions shade as in the path integrator; null-BSDF (medium-transition)
surfaces pass through and flip the ray's medium per the MediumInterface.

Wavefront redesign notes (vs the reference's recursive Li):
- the per-ray "current medium" pointer becomes an int32 medium id in the
  ray state, switched on interface crossings via tri_med_in/out;
- VisibilityTester::Tr's interface walk is approximated by the current
  medium's transmittance over the shadow segment (exact for the target
  cloud.pbrt topology: camera and lights outside one medium region);
- pbrt doesn't count null-interface crossings as bounces (bounces--);
  here the loop runs PASSTHROUGH_MARGIN extra iterations instead, which
  bounds compile-time unrolling while matching typical interface depth.
"""

from __future__ import annotations

import jax.numpy as jnp

from tpu_pbrt.core import bxdf
from tpu_pbrt.core import lights_dev as ld
from tpu_pbrt.core import media as md
from tpu_pbrt.core.sampling import power_heuristic, uniform_float
from tpu_pbrt.core.vecmath import dot, normalize, offset_ray_origin, to_local, to_world
from tpu_pbrt.integrators.common import (
    scene_intersect,
    scene_intersect_p,
    unoccluded_tr,
    DIM_BSDF_LOBE,
    DIM_BSDF_UV,
    DIM_MIX,
    DIM_LIGHT_PICK,
    DIM_LIGHT_UV,
    DIM_RR,
    DIMS_PER_BOUNCE,
    WavefrontIntegrator,
    make_interaction,
)
from tpu_pbrt.scene.compiler import MAT_NONE

PASSTHROUGH_MARGIN = 4
_DIM_MEDIUM = 12
_DIM_PHASE = 14


class VolPathIntegrator(WavefrontIntegrator):
    name = "volpath"

    def __init__(self, params, scene, options):
        super().__init__(params, scene, options)
        self.max_depth = params.find_one_int("maxdepth", 5)
        self.rr_threshold = params.find_one_float("rrthreshold", 1.0)
        self.camera_medium = scene.camera_medium_id
        self.margin = PASSTHROUGH_MARGIN if scene.has_null_materials else 0

    def li(self, dev, o, d, px, py, s):
        shape = o.shape[:-1]
        mt: md.MediumTable = dev["media"]
        L = jnp.zeros(shape + (3,), jnp.float32)
        beta = jnp.ones(shape + (3,), jnp.float32)
        alive = jnp.ones(shape, bool)
        nrays = jnp.zeros(shape, jnp.int32)
        prev_pdf = jnp.zeros(shape, jnp.float32)
        specular = jnp.ones(shape, bool)
        eta_scale = jnp.ones(shape, jnp.float32)
        prev_p = o
        cur_med = jnp.full(shape, self.camera_medium, jnp.int32)
        depth = jnp.zeros(shape, jnp.int32)  # real (non-null) bounces taken

        for bounce in range(self.max_depth + 1 + self.margin):
            salt = bounce * DIMS_PER_BOUNCE
            hit = scene_intersect(dev, o, d, jnp.inf)
            nrays = nrays + alive.astype(jnp.int32)
            it = make_interaction(dev, hit, o, d)
            it.valid = it.valid & alive
            miss = alive & (hit.prim < 0)

            # ---- medium sampling over the segment -----------------------
            t_seg = jnp.where(hit.prim >= 0, hit.t, jnp.full_like(hit.t, jnp.inf))
            ms = md.medium_sample(mt, jnp.where(alive, cur_med, -1), o, d, t_seg, px, py, s, salt + _DIM_MEDIUM)
            beta = beta * jnp.where(alive[..., None], ms.weight, 1.0)
            in_medium = alive & ms.sampled_medium
            at_surface = alive & (hit.prim >= 0) & ~in_medium
            escaped = miss & ~in_medium

            # ---- emitted radiance (surface / env) with forward MIS ------
            if "envmap" in dev:
                le_env = ld.env_lookup(dev, d)
                pdf_env = ld.infinite_pdf(dev, self.light_distr, d, ref_p=prev_p)
                w_env = jnp.where(specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_env))
                L = L + jnp.where(escaped[..., None], beta * le_env * w_env[..., None], 0.0)
            hit_light = jnp.where(at_surface, it.light, -1)
            le = ld.emitted_radiance(dev, hit_light, it.wo, it.ng)
            pdf_light = ld.emitted_pdf(dev, self.light_distr, prev_p, it.p, hit_light, it.ng)
            w_emit = jnp.where(specular, 1.0, power_heuristic(1.0, prev_pdf, 1.0, pdf_light))
            L = L + beta * le * w_emit[..., None]

            alive = in_medium | at_surface
            if bounce >= self.max_depth + self.margin:
                break

            # ---- null material passthrough (medium transition) ----------
            mp = self.mat_at(
                dev, it,
                u_mix=self.u1d(px, py, s, salt + DIM_MIX),
            )
            is_null = at_surface & (mp.mtype == MAT_NONE)
            going_in_null = dot(d, it.ng) < 0.0
            med_in = dev["tri_med_in"][jnp.maximum(hit.prim, 0)]
            med_out = dev["tri_med_out"][jnp.maximum(hit.prim, 0)]
            new_med_null = jnp.where(going_in_null, med_in, med_out)
            at_surface = at_surface & ~is_null

            # ---- NEE ----------------------------------------------------
            p_medium = o + ms.t[..., None] * d
            ref_p = jnp.where(in_medium[..., None], p_medium, it.p)
            u_pick = self.u1d(px, py, s, salt + DIM_LIGHT_PICK)
            u1, u2 = self.u2d(px, py, s, salt + DIM_LIGHT_UV)
            ls = ld.sample_one_light(dev, self.light_distr, ref_p, u_pick, u1, u2)
            # scatter function value and pdf toward the light
            wo_l = to_local(it.wo, it.ss, it.ts, it.ns)
            wi_l = to_local(ls.wi, it.ss, it.ts, it.ns)
            f_surf, pdf_surf = bxdf.bsdf_eval(mp, wo_l, wi_l)
            f_surf = f_surf * jnp.abs(dot(ls.wi, it.ns))[..., None]
            g_hg = mt.g[jnp.maximum(cur_med, 0)]
            p_phase = md.hg_p(dot(-d, ls.wi), g_hg)
            f_nee = jnp.where(in_medium[..., None], p_phase[..., None].repeat(3, -1), f_surf)
            pdf_nee_fwd = jnp.where(in_medium, p_phase, pdf_surf)
            # pbrt breaks before light sampling once bounces reach maxDepth:
            # the final vertex emits but gets no NEE estimate
            can_scatter = depth < self.max_depth
            do_nee = (in_medium | at_surface) & can_scatter & (ls.pdf > 0.0) & (
                jnp.max(f_nee, axis=-1) > 0.0
            ) & (jnp.max(ls.li, axis=-1) > 0.0)
            o_sh = jnp.where(
                in_medium[..., None], p_medium, offset_ray_origin(it.p, it.ng, ls.wi)
            )
            visible, tr_sh = unoccluded_tr(
                dev,
                o_sh,
                ls.wi,
                ls.dist,
                jnp.where(do_nee, cur_med, -1),
                px,
                py,
                s,
                salt + _DIM_MEDIUM + 1,
                segments=self.vis_segments,
            )
            nrays = nrays + do_nee.astype(jnp.int32)
            w_l = jnp.where(ls.is_delta, 1.0, power_heuristic(1.0, ls.pdf, 1.0, pdf_nee_fwd))
            Ld = f_nee * ls.li * tr_sh * (w_l / jnp.maximum(ls.pdf, 1e-20))[..., None]
            L = L + jnp.where((do_nee & visible)[..., None], beta * Ld, 0.0)

            # ---- continuation -------------------------------------------
            # medium: HG sample
            up1 = uniform_float(px, py, s, salt + _DIM_PHASE)
            up2 = uniform_float(px, py, s, salt + _DIM_PHASE + 1)
            # sample around wo = -d, matching the hg_p(dot(-d, wi)) eval
            wi_m, pdf_m = md.hg_sample(-d, g_hg, up1, up2)
            wi_m = normalize(wi_m)

            # surface: BSDF sample
            ul = self.u1d(px, py, s, salt + DIM_BSDF_LOBE)
            ub1, ub2 = self.u2d(px, py, s, salt + DIM_BSDF_UV)
            bs = bxdf.bsdf_sample(mp, wo_l, ul, ub1, ub2)
            wi_surf = normalize(to_world(bs.wi, it.ss, it.ts, it.ns))
            cont_surf = at_surface & (bs.pdf > 0.0) & (jnp.max(bs.f, axis=-1) > 0.0)
            throughput = bs.f * (jnp.abs(dot(wi_surf, it.ns)) / jnp.maximum(bs.pdf, 1e-20))[..., None]

            # merge the three continuation kinds: medium / surface / null;
            # real scattering counts toward maxdepth, null crossings don't
            in_medium = in_medium & can_scatter
            cont_surf = cont_surf & can_scatter
            depth = depth + (in_medium | cont_surf).astype(jnp.int32)
            cont = in_medium | cont_surf | is_null
            beta = jnp.where(cont_surf[..., None], beta * throughput, beta)
            new_d = jnp.where(in_medium[..., None], wi_m, wi_surf)
            new_d = jnp.where(is_null[..., None], d, new_d)
            new_o = jnp.where(
                in_medium[..., None],
                p_medium,
                offset_ray_origin(it.p, it.ng, new_d),
            )
            prev_p = jnp.where(cont[..., None], jnp.where(in_medium[..., None], p_medium, it.p), prev_p)
            o = jnp.where(cont[..., None], new_o, o)
            d = jnp.where(cont[..., None], new_d, d)
            prev_pdf = jnp.where(in_medium, pdf_m, jnp.where(cont_surf, bs.pdf, prev_pdf))
            specular = jnp.where(in_medium, False, jnp.where(cont_surf, bs.is_specular, specular))
            # medium transitions: null interface or transmissive BSDF crossing
            crossing = cont_surf & bs.is_transmission
            going_in = dot(new_d, it.ng) < 0.0
            new_med_cross = jnp.where(going_in, med_in, med_out)
            cur_med = jnp.where(is_null, new_med_null, cur_med)
            cur_med = jnp.where(crossing, new_med_cross, cur_med)
            # eta tracking for RR
            eta2 = (mp.eta[..., 0]) ** 2
            scale = jnp.where(dot(it.wo, it.ns) > 0.0, eta2, 1.0 / jnp.maximum(eta2, 1e-12))
            eta_scale = jnp.where(crossing, eta_scale * scale, eta_scale)
            alive = cont

            # ---- Russian roulette (after 3 real bounces; null crossings
            # don't count, matching pbrt's bounces-- semantics) -----------
            if bounce > 3:
                rr_lane = depth > 4
                rr_beta = jnp.max(beta, axis=-1) * eta_scale
                q = jnp.maximum(0.05, 1.0 - rr_beta)
                u_rr = uniform_float(px, py, s, salt + DIM_RR)
                kill = alive & rr_lane & (rr_beta < self.rr_threshold) & (u_rr < q)
                survive = alive & rr_lane & (rr_beta < self.rr_threshold) & ~kill
                beta = beta * jnp.where(survive, 1.0 / jnp.maximum(1.0 - q, 1e-6), 1.0)[..., None]
                alive = alive & ~kill
        return L, nrays
