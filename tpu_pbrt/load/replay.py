"""Replay: drive the REAL RenderService with a generated schedule under
a VirtualClock.

The engine is an event loop over virtual time: due arrivals are
submitted (sheds caught and counted), otherwise the service takes one
scheduler step, and each dispatched chunk-slice advances the clock by
the spec's per-slice service time — the replica's device-time model.
When nothing is runnable and arrivals remain, the clock jumps to the
next arrival. The whole run is a pure function of (workload, seed):
the service samples only the injected clock (the PR 17 seam protocheck
verifies), the stub dispatches are numpy-deterministic, and every
decision appends one path-free line to the log — the byte-identity
artifact the determinism gate diffs across runs.

Stub vs real dispatches: by default jobs are submitted as precompiled
(StubScene, StubIntegrator) pairs from protocheck's harness — instant,
bit-deterministic, and exercising every service code path (residency,
WFQ, shedding, preemption, backoff, checkpoints). `scene_text` swaps in
real compiled scenes for a physically-meaningful (but slower) run.

Capture-replay: with a flight path armed, the engine writes a
``load_run`` header (the full spec) plus one ``load_submit`` heartbeat
per arrival; `workload_from_flight` reconstructs the exact Workload
from those lines — or, for a log recorded by a REAL service (no
harness lines), approximates one from the per-job ``serve_submit`` /
``serve_done`` heartbeats.

Fleet mode (``replicas > 1``): the same schedule is driven through a
``FleetRouter`` over N in-process ``LocalReplica``s, all under the one
VirtualClock — submits route by scene affinity (and may shed at the
fleet edge or at the routed replica's SLO), dispatches rotate across
the replicas, and every decision-log line names the owning replica, so
the byte-identity artifact is a pure function of (workload, seed, N).
The single-replica path is byte-for-byte what it was before fleet mode
existed — ``LOADTEST_baseline.json`` pins it.
"""

from __future__ import annotations

import glob
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpu_pbrt.load.workload import Request, Workload, WorkloadSpec

__all__ = ["ReplayResult", "replay", "workload_from_flight"]

#: hard ceiling on loop events — a wedged scheduler must terminate the
#: replay with evidence (the wedge flag), not hang CI
_MAX_EVENTS = 500_000


@dataclass
class ReplayResult:
    """Everything the gate layer consumes. Deterministic fields only —
    no wall times, no paths — so two same-seed results compare equal."""

    workload: Workload
    log: List[str] = field(default_factory=list)
    #: METRICS.snapshot() taken at drain, before teardown
    snapshot: Dict[str, Any] = field(default_factory=dict)
    #: every health condition that fired at any evaluation point
    health_flags: List[str] = field(default_factory=list)
    submitted: int = 0
    sheds: int = 0
    completed: int = 0
    failed: int = 0
    dispatches: int = 0
    steps: int = 0
    #: virtual clock at drain
    virtual_seconds: float = 0.0
    #: residency.stats() minus the per-scene detail
    compiles: int = 0
    residency_hits: int = 0
    evictions: int = 0
    preemptions: int = 0
    #: residency pin_counts() entries still nonzero at drain (leaks)
    pin_leaks: Dict[str, int] = field(default_factory=dict)
    #: job ids not terminal at drain (a wedge's evidence)
    unfinished: List[str] = field(default_factory=list)

    def log_text(self) -> str:
        return "".join(line + "\n" for line in self.log)


def _stub_pair(chunks: int, depth: int):
    """A fresh (scene, integrator) stub pair — protocheck's harness
    classes, so the replay exercises the identical submit path the
    protocol explorer verified."""
    from tpu_pbrt.analysis.protocheck import _harness

    h = _harness()
    return (h["StubScene"](), h["StubIntegrator"](chunks, depth))


def replay(
    workload: Workload,
    *,
    replicas: int = 1,
    flight_path: Optional[str] = None,
    trace_path: Optional[str] = None,
    health_every: int = 1,
) -> ReplayResult:
    """Execute the schedule against a fresh RenderService — or, with
    ``replicas > 1``, against a FleetRouter over N of them. Arms the
    global recorders (FLIGHT/TRACE/METRICS/CHAOS) for the run and
    restores them exactly — the protocheck ProtocolModel contract."""
    if replicas > 1:
        return _replay_fleet(
            workload, replicas, flight_path=flight_path,
            trace_path=trace_path, health_every=health_every,
        )
    from tpu_pbrt.chaos import CHAOS
    from tpu_pbrt.obs import health
    from tpu_pbrt.obs.flight import FLIGHT
    from tpu_pbrt.obs.metrics import METRICS
    from tpu_pbrt.obs.trace import TRACE
    from tpu_pbrt.serve.queue import SloPolicy, parse_slo_spec
    from tpu_pbrt.serve.service import (
        DONE,
        FAILED,
        RenderService,
        ShedError,
        _TERMINAL,
    )
    from tpu_pbrt.utils.clock import VirtualClock

    spec = workload.spec
    clock = VirtualClock(start=0.0, tick=1e-6)
    tmpdir = tempfile.mkdtemp(prefix="tpu_load_")
    res = ReplayResult(workload=workload)

    # arm: virtual clock on every recorder, fresh registry (forced on —
    # the gates NEED the snapshot even under TPU_PBRT_METRICS=0), the
    # scenario's fault plan, optional flight/trace sinks
    METRICS.reset()
    prev_force = METRICS._force
    METRICS._force = True
    flight_prev = (FLIGHT._clock, FLIGHT._t0, FLIGHT._path)
    FLIGHT.set_clock(clock)
    if flight_path:
        FLIGHT.configure(flight_path)
    trace_prev = (TRACE._clock, TRACE._t0, TRACE._path)
    TRACE.set_clock(clock)
    if trace_path:
        TRACE.configure(trace_path)
        TRACE.reset()
        TRACE.set_clock(clock)

    svc = RenderService(
        seed=workload.seed, spool_dir=tmpdir, clock=clock,
        max_active=spec.max_active,
        slo=SloPolicy(
            depth=parse_slo_spec(spec.slo_depth, int),
            wait_s=parse_slo_spec(spec.slo_wait_s, float),
        ),
    )
    CHAOS.install(spec.fault, workload.seed)
    flags: set = set()
    try:
        if flight_path:
            FLIGHT.heartbeat(
                "load_run", scenario=spec.name, seed=workload.seed,
                requests=len(workload.requests), spec=spec.to_json(),
            )
        pending = sorted(workload.requests, key=lambda r: (r.t, r.rid))
        i = 0
        events = 0
        while events < _MAX_EVENTS:
            events += 1
            now = clock.peek()
            if i < len(pending) and pending[i].t <= now:
                r = pending[i]
                i += 1
                try:
                    svc.submit(
                        compiled=_stub_pair(r.chunks, r.depth),
                        resident_key=r.scene, job_id=r.rid,
                        tenant=r.tenant, priority=r.priority,
                        checkpoint_every=r.checkpoint_every,
                    )
                    res.submitted += 1
                    outcome = "ok"
                except ShedError as e:
                    res.sheds += 1
                    outcome = f"shed:{e.reason}"
                if flight_path:
                    FLIGHT.heartbeat(
                        "load_submit", rid=r.rid, at=r.t,
                        tenant=r.tenant, prio=r.priority, scene=r.scene,
                        chunks=r.chunks, depth=r.depth,
                        ckpt=r.checkpoint_every, kind=r.kind,
                        outcome=outcome,
                    )
                res.log.append(
                    f"@{now:012.6f} submit {r.rid} tenant={r.tenant} "
                    f"prio={r.priority} scene={r.scene} -> {outcome}"
                )
            else:
                rid = svc.step()
                res.steps += 1
                if rid is None:
                    if i < len(pending):
                        clock.advance_to(pending[i].t)
                        res.log.append(
                            f"@{clock.peek():012.6f} advance"
                        )
                    elif svc.idle():
                        break
                    else:
                        # runnable work, no dispatch, nothing to wait
                        # for: a WEDGE. Keep stepping just long enough
                        # for the watchdog's gap counter to cross its
                        # threshold — the harness's job is to FLAG the
                        # wedge, not hang on it.
                        th = health.Thresholds()
                        for _ in range(th.resolved_wedge_steps() + 2):
                            svc.step()
                            flags |= set(
                                health.evaluate(svc, METRICS).firing()
                            )
                        res.log.append(
                            f"@{clock.peek():012.6f} wedge"
                        )
                        break
                else:
                    res.dispatches += 1
                    cur = svc.jobs[rid].cursor
                    res.log.append(
                        f"@{clock.peek():012.6f} step -> {rid}:c{cur}"
                    )
                    # the slice's device time: the replica is busy for
                    # this long in virtual time
                    clock.advance(spec.service_time_s)
            if events % max(1, health_every) == 0:
                flags |= set(health.evaluate(svc, METRICS).firing())
        flags |= set(health.evaluate(svc, METRICS).firing())

        res.health_flags = sorted(flags)
        res.virtual_seconds = round(clock.peek(), 6)
        res.completed = sum(
            1 for j in svc.jobs.values() if j.status == DONE
        )
        res.failed = sum(
            1 for j in svc.jobs.values() if j.status == FAILED
        )
        res.unfinished = sorted(
            j.job_id for j in svc.jobs.values()
            if j.status not in _TERMINAL
        )
        res.pin_leaks = {
            k: n for k, n in svc.residency.pin_counts().items() if n
        }
        res.compiles = svc.residency.scene_compiles
        res.residency_hits = svc.residency.hits
        res.evictions = svc.residency.evictions
        res.snapshot = METRICS.snapshot()
        res.preemptions = int(sum(
            s["value"] for s in res.snapshot["metrics"].get(
                "tpu_pbrt_serve_preemptions_total", {},
            ).get("series", ())
        ))
        if trace_path:
            # export INSIDE the armed window: the clock is still
            # virtual, so otherData.clock stamps "virtual" and scope's
            # --check exercises the non-wall path
            TRACE.export(trace_path)
        return res
    finally:
        CHAOS.clear()
        FLIGHT._clock, FLIGHT._t0, FLIGHT._path = flight_prev
        TRACE._clock, TRACE._t0, TRACE._path = trace_prev
        if trace_path:
            TRACE.reset()
        METRICS._force = prev_force


def _replay_fleet(
    workload: Workload,
    n_replicas: int,
    *,
    flight_path: Optional[str] = None,
    trace_path: Optional[str] = None,
    health_every: int = 1,
) -> ReplayResult:
    """The fleet engine: one VirtualClock, N LocalReplicas (each a real
    RenderService with the scenario's SLO), one FleetRouter in front.
    Same loop shape as the single-replica engine — due arrivals submit
    (through the router: affinity routing + fleet-edge shedding +
    per-replica SLO), otherwise `router.step()` dispatches one slice
    somewhere in the fleet — and the same aggregate ReplayResult, with
    the per-replica facts summed fleet-wide (pin leaks keyed by
    replica, health flags unioned over every replica's watchdog)."""
    from tpu_pbrt.chaos import CHAOS
    from tpu_pbrt.fleet.router import FleetRouter, LocalReplica
    from tpu_pbrt.obs import health
    from tpu_pbrt.obs.flight import FLIGHT
    from tpu_pbrt.obs.metrics import METRICS
    from tpu_pbrt.obs.trace import TRACE
    from tpu_pbrt.serve.queue import SloPolicy, parse_slo_spec
    from tpu_pbrt.serve.service import DONE, FAILED, ShedError, _TERMINAL
    from tpu_pbrt.utils.clock import VirtualClock

    spec = workload.spec
    clock = VirtualClock(start=0.0, tick=1e-6)
    tmpdir = tempfile.mkdtemp(prefix="tpu_load_fleet_")
    res = ReplayResult(workload=workload)

    METRICS.reset()
    prev_force = METRICS._force
    METRICS._force = True
    flight_prev = (FLIGHT._clock, FLIGHT._t0, FLIGHT._path)
    FLIGHT.set_clock(clock)
    if flight_path:
        FLIGHT.configure(flight_path)
    trace_prev = (TRACE._clock, TRACE._t0, TRACE._path)
    TRACE.set_clock(clock)
    if trace_path:
        TRACE.configure(trace_path)
        TRACE.reset()
        TRACE.set_clock(clock)

    fleet = [
        LocalReplica(
            f"r{k}", clock=clock, seed=workload.seed,
            spool_dir=os.path.join(tmpdir, f"r{k}"),
            max_active=spec.max_active,
            slo=SloPolicy(
                depth=parse_slo_spec(spec.slo_depth, int),
                wait_s=parse_slo_spec(spec.slo_wait_s, float),
            ),
        )
        for k in range(int(n_replicas))
    ]
    router = FleetRouter(
        fleet, clock=clock, spool_dir=os.path.join(tmpdir, "fleet"),
    )
    CHAOS.install(spec.fault, workload.seed)

    def _fleet_health() -> set:
        out: set = set()
        for rep in fleet:
            out |= set(health.evaluate(rep.service, METRICS).firing())
        return out

    flags: set = set()
    try:
        if flight_path:
            FLIGHT.heartbeat(
                "load_run", scenario=spec.name, seed=workload.seed,
                requests=len(workload.requests), spec=spec.to_json(),
                replicas=n_replicas,
            )
        pending = sorted(workload.requests, key=lambda r: (r.t, r.rid))
        i = 0
        events = 0
        while events < _MAX_EVENTS:
            events += 1
            now = clock.peek()
            if i < len(pending) and pending[i].t <= now:
                r = pending[i]
                i += 1
                try:
                    router.submit(
                        compiled=_stub_pair(r.chunks, r.depth),
                        resident_key=r.scene, job_id=r.rid,
                        tenant=r.tenant, priority=r.priority,
                        checkpoint_every=r.checkpoint_every,
                    )
                    res.submitted += 1
                    outcome = f"ok@{router.owner(r.rid)}"
                except ShedError as e:
                    res.sheds += 1
                    outcome = f"shed:{e.reason}"
                if flight_path:
                    FLIGHT.heartbeat(
                        "load_submit", rid=r.rid, at=r.t,
                        tenant=r.tenant, prio=r.priority, scene=r.scene,
                        chunks=r.chunks, depth=r.depth,
                        ckpt=r.checkpoint_every, kind=r.kind,
                        outcome=outcome,
                    )
                res.log.append(
                    f"@{now:012.6f} submit {r.rid} tenant={r.tenant} "
                    f"prio={r.priority} scene={r.scene} -> {outcome}"
                )
            else:
                got = router.step()
                res.steps += 1
                if got is None:
                    if i < len(pending):
                        clock.advance_to(pending[i].t)
                        res.log.append(
                            f"@{clock.peek():012.6f} advance"
                        )
                    elif all(rep.service.idle() for rep in fleet):
                        break
                    else:
                        # fleet wedge: step every replica's service
                        # directly (router.step() short-circuits when
                        # nothing is dispatchable, so the per-replica
                        # watchdog gap counters only advance on direct
                        # steps) until the wedge threshold crosses,
                        # then stop with the flag as evidence
                        th = health.Thresholds()
                        for _ in range(th.resolved_wedge_steps() + 2):
                            for rep in fleet:
                                rep.service.step()
                            flags |= _fleet_health()
                        res.log.append(
                            f"@{clock.peek():012.6f} wedge"
                        )
                        break
                else:
                    rid, job = got
                    res.dispatches += 1
                    cur = router.replicas[rid].service.jobs[job].cursor
                    res.log.append(
                        f"@{clock.peek():012.6f} step -> {rid}/{job}:c{cur}"
                    )
                    clock.advance(spec.service_time_s)
            if events % max(1, health_every) == 0:
                flags |= _fleet_health()
        flags |= _fleet_health()

        res.health_flags = sorted(flags)
        res.virtual_seconds = round(clock.peek(), 6)
        statuses: Dict[str, str] = {}
        for job_id, rec in router.jobs.items():
            st = rec.terminal
            if not st:
                rep = router.replicas.get(rec.rid)
                st = (
                    rep.status(job_id)
                    if rep is not None and rep.alive else None
                )
            statuses[job_id] = st or ""
        res.completed = sum(1 for s in statuses.values() if s == DONE)
        res.failed = sum(1 for s in statuses.values() if s == FAILED)
        res.unfinished = sorted(
            j for j, s in statuses.items() if s not in _TERMINAL
        )
        res.pin_leaks = {
            f"{rep.rid}:{k}": n
            for rep in fleet
            for k, n in rep.service.residency.pin_counts().items() if n
        }
        res.compiles = sum(
            rep.service.residency.scene_compiles for rep in fleet
        )
        res.residency_hits = sum(
            rep.service.residency.hits for rep in fleet
        )
        res.evictions = sum(
            rep.service.residency.evictions for rep in fleet
        )
        res.snapshot = METRICS.snapshot()
        res.preemptions = int(sum(
            s["value"] for s in res.snapshot["metrics"].get(
                "tpu_pbrt_serve_preemptions_total", {},
            ).get("series", ())
        ))
        if trace_path:
            TRACE.export(trace_path)
        return res
    finally:
        CHAOS.clear()
        FLIGHT._clock, FLIGHT._t0, FLIGHT._path = flight_prev
        TRACE._clock, TRACE._t0, TRACE._path = trace_prev
        if trace_path:
            TRACE.reset()
        METRICS._force = prev_force


# --------------------------------------------------------------------------
# Capture-replay
# --------------------------------------------------------------------------


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line: crash-safe format
    except OSError:
        pass
    return out


def workload_from_flight(path: str) -> Workload:
    """Reconstruct a Workload from a recorded flight log.

    Preferred source: the ``load_run`` header + ``load_submit`` lines a
    harness replay wrote — reconstruction is EXACT (same spec, same
    requests, so a re-replay produces a byte-identical decision log).

    Fallback (a log from a real serve daemon): scavenge the per-job
    ``serve_submit`` heartbeats (arrival stamp, tenant, priority, key)
    and ``serve_done`` (chunk count) from the per-job flight files next
    to `path`. Approximate — arrival stamps are the recorder's 3-dp
    rounding, un-completed jobs fall back to one chunk — but it turns
    any production incident log into a replayable schedule."""
    lines = _read_jsonl(path)
    spec: Optional[WorkloadSpec] = None
    seed = 0
    requests: List[Request] = []
    for ln in lines:
        phase = ln.get("phase")
        if phase == "load_run" and "spec" in ln:
            spec = WorkloadSpec.from_json(ln["spec"])
            seed = int(ln.get("seed", 0))
        elif phase == "load_submit":
            requests.append(Request(
                rid=str(ln["rid"]), t=float(ln["at"]),
                tenant=str(ln["tenant"]), priority=int(ln["prio"]),
                scene=str(ln["scene"]), chunks=int(ln["chunks"]),
                depth=int(ln.get("depth", 1)),
                checkpoint_every=int(ln.get("ckpt", 0)),
                kind=str(ln.get("kind", "fresh")),
            ))
    if spec is not None and requests:
        requests.sort(key=lambda r: (r.t, r.rid))
        return Workload(spec=spec, seed=seed, requests=requests)

    # -- fallback: per-job serve_* heartbeats ------------------------------
    root, ext = os.path.splitext(path)
    submits: Dict[str, Dict[str, Any]] = {}
    chunks: Dict[str, int] = {}
    for jf in sorted(glob.glob(f"{root}.*{ext}")):
        for ln in _read_jsonl(jf):
            phase = ln.get("phase")
            job = ln.get("job")
            if job is None:
                # per-job files name the job in the filename only when
                # the service's _flight attaches it as a field; skip
                # lines without one
                continue
            if phase == "serve_submit":
                submits[job] = ln
            elif phase == "serve_done":
                if "chunks" in ln:
                    chunks[job] = int(ln["chunks"])
    requests = []
    for job, ln in submits.items():
        requests.append(Request(
            rid=str(job), t=float(ln.get("t", 0.0)),
            tenant=str(ln.get("tenant", "default")),
            priority=int(ln.get("priority", 0)),
            scene=str(ln.get("key", f"captured:{job}")),
            chunks=chunks.get(job, 1), kind="fresh",
        ))
    if not requests:
        raise ValueError(
            f"no load_submit or serve_submit heartbeats found under "
            f"{path!r} — nothing to reconstruct"
        )
    requests.sort(key=lambda r: (r.t, r.rid))
    duration = max(r.t for r in requests) + 1e-6
    spec = WorkloadSpec(
        name="captured", duration_s=round(duration, 6),
        rate=round(len(requests) / duration, 6),
    )
    return Workload(spec=spec, seed=seed, requests=requests)
