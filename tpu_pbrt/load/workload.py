"""Seeded workload generation: traffic as a pure function of (seed, spec).

A `WorkloadSpec` names the statistical shape of the traffic — arrival
process, tenant mix, scene-size distribution, resubmit/edit behavior —
and `generate(spec, seed)` expands it into a concrete `Workload`: a
time-sorted list of `Request`s. Everything is drawn from ONE
`random.Random` instance seeded from (spec.name, seed), and every float
is quantized, so the same inputs produce a byte-identical schedule on
every run and platform (the determinism gate diffs the rendered lines).

The distributions model what a render fleet actually sees:

- **power-law tenants** — request share ~ 1/(rank+1)^alpha: a few hot
  studios, a long tail of occasional users (drives WFQ fairness);
- **bursty arrivals** — Poisson inter-arrivals whose rate is modulated
  by a square-wave burst window (drives SLO shedding);
- **heavy-tail scene shapes** — per-scene chunk counts from a clipped
  discrete Pareto: most scenes small, a few huge (drives preemption
  and the slice scheduler's fairness under size skew);
- **edit-storm** — a request re-submits a previously seen scene with a
  bumped revision: a NEW residency key, so it pays a recompile (drives
  residency churn and eviction);
- **resubmit** — a request re-submits an existing key verbatim: a warm
  residency hit (drives the zero-recompile contract).
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field, replace
from typing import Dict, List, Optional, Tuple

__all__ = [
    "WorkloadSpec",
    "Request",
    "Workload",
    "GateTargets",
    "LoadScenario",
    "SCENARIOS",
    "CI_SCENARIOS",
    "generate",
]


# --------------------------------------------------------------------------
# Spec / request / workload
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class WorkloadSpec:
    """The statistical shape of one traffic scenario. Frozen: a spec is
    a value — hash it, embed it in reports, reconstruct it from a
    capture header."""

    name: str
    #: virtual seconds during which requests arrive (service continues
    #: past this until drained)
    duration_s: float = 2.0
    #: mean off-burst arrival rate, requests per virtual second
    rate: float = 40.0
    #: arrival-rate multiplier inside a burst window (1.0 = flat Poisson)
    burst_factor: float = 1.0
    #: square-wave burst period; the FIRST half of each period bursts.
    #: 0 disables modulation.
    burst_period_s: float = 0.0
    #: tenant population; request share is power-law over rank
    tenants: int = 4
    tenant_alpha: float = 1.2
    #: priority classes and their draw weights (parallel tuples)
    priorities: Tuple[int, ...] = (0,)
    priority_weights: Tuple[float, ...] = (1.0,)
    #: per-scene chunk counts: clipped discrete Pareto on [min, max]
    chunks_min: int = 1
    chunks_max: int = 6
    chunks_tail: float = 1.5
    #: distinct base scenes in the pool (0 -> same as `tenants`)
    scene_pool: int = 0
    #: fraction of requests that re-submit an already-seen key verbatim
    resubmit_fraction: float = 0.0
    #: fraction that re-submit a seen scene with a bumped revision (a
    #: new key: the edit invalidates the compiled scene)
    edit_fraction: float = 0.0
    #: pipeline depth and checkpoint cadence passed through to submit
    depth: int = 1
    checkpoint_every: int = 0
    #: virtual seconds of device time one chunk-slice costs the replica
    #: (the service-time model replay advances the clock by per slice)
    service_time_s: float = 0.004
    #: SLO admission policy for the run (queue.parse_slo_spec grammar;
    #: "" disables that half)
    slo_depth: str = ""
    slo_wait_s: str = ""
    #: CHAOS fault plan installed for the run ("" = clean)
    fault: str = ""
    #: film-state slots (None = unbounded; small values drive preemption)
    max_active: Optional[int] = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "WorkloadSpec":
        d = json.loads(text)
        for k in ("priorities", "priority_weights"):
            if k in d:
                d[k] = tuple(d[k])
        return cls(**d)


@dataclass(frozen=True)
class Request:
    """One generated submit decision."""

    rid: str  #: deterministic request id (also the job id at replay)
    t: float  #: virtual arrival time, quantized to 1e-6 s
    tenant: str
    priority: int
    scene: str  #: residency key ("<base>@r<rev>")
    chunks: int
    depth: int = 1
    checkpoint_every: int = 0
    kind: str = "fresh"  #: fresh | resubmit | edit

    def line(self) -> str:
        """The schedule-artifact rendering — fixed-width, path-free;
        byte-compared by the determinism gate."""
        return (
            f"@{self.t:012.6f} {self.kind:<8s} {self.rid} "
            f"tenant={self.tenant} prio={self.priority} "
            f"scene={self.scene} chunks={self.chunks} depth={self.depth}"
        )


@dataclass
class Workload:
    """A concrete schedule: the spec that shaped it, the seed that drew
    it, and the time-sorted requests."""

    spec: WorkloadSpec
    seed: int
    requests: List[Request] = field(default_factory=list)

    def schedule_text(self) -> str:
        """The byte-identity artifact: same (spec, seed) => identical."""
        head = f"# tpu-load schedule {self.spec.name} seed={self.seed}\n"
        return head + "".join(r.line() + "\n" for r in self.requests)


# --------------------------------------------------------------------------
# Generation
# --------------------------------------------------------------------------


def _pareto_int(rng: random.Random, lo: int, hi: int, tail: float) -> int:
    """Clipped discrete Pareto: heavy-tail sizes in [lo, hi]. Smaller
    `tail` = heavier tail (more mass at hi)."""
    if hi <= lo:
        return lo
    u = max(rng.random(), 1e-12)
    v = lo * u ** (-1.0 / tail)
    return min(hi, max(lo, int(v)))


def _pick_weighted(rng: random.Random, cum: List[float]) -> int:
    """Index drawn by a pre-normalized cumulative weight table."""
    u = rng.random()
    for i, c in enumerate(cum):
        if u <= c:
            return i
    return len(cum) - 1


def _cumulative(weights: List[float]) -> List[float]:
    total = sum(weights)
    cum, acc = [], 0.0
    for w in weights:
        acc += w / total
        cum.append(acc)
    cum[-1] = 1.0
    return cum


def _in_burst(t: float, spec: WorkloadSpec) -> bool:
    if spec.burst_period_s <= 0 or spec.burst_factor == 1.0:
        return False
    return (t % spec.burst_period_s) < spec.burst_period_s / 2.0


def generate(spec: WorkloadSpec, seed: int) -> Workload:
    """Expand a spec into a concrete schedule — pure in (spec, seed)."""
    rng = random.Random(f"tpu-load:{spec.name}:{int(seed)}")

    # scene pool: each base scene draws its shape ONCE — a scene's
    # chunk count is a property of the scene, so every resubmit of the
    # same key replays the same shape (the residency cache returns the
    # first-compiled integrator anyway; divergence here would lie)
    n_scenes = spec.scene_pool or max(spec.tenants, 1)
    scene_chunks: Dict[str, int] = {
        f"s{i:02d}": _pareto_int(
            rng, spec.chunks_min, spec.chunks_max, spec.chunks_tail
        )
        for i in range(n_scenes)
    }
    bases = sorted(scene_chunks)

    tenant_cum = _cumulative(
        [(i + 1) ** -spec.tenant_alpha for i in range(spec.tenants)]
    )
    prio_cum = _cumulative(list(spec.priority_weights))

    requests: List[Request] = []
    seen_keys: List[str] = []  # insertion-ordered, deterministic
    revs: Dict[str, int] = dict.fromkeys(bases, 0)
    t = 0.0
    while True:
        rate = spec.rate * (
            spec.burst_factor if _in_burst(t, spec) else 1.0
        )
        t += rng.expovariate(rate)
        if t >= spec.duration_s:
            break
        tq = round(t, 6)
        tenant = f"t{_pick_weighted(rng, tenant_cum)}"
        prio = spec.priorities[_pick_weighted(rng, prio_cum)]
        u = rng.random()
        if seen_keys and u < spec.resubmit_fraction:
            kind = "resubmit"
            key = seen_keys[rng.randrange(len(seen_keys))]
            base = key.split("@", 1)[0]
        elif seen_keys and u < spec.resubmit_fraction + spec.edit_fraction:
            kind = "edit"
            prev = seen_keys[rng.randrange(len(seen_keys))]
            base = prev.split("@", 1)[0]
            revs[base] += 1
            key = f"{base}@r{revs[base]}"
        else:
            kind = "fresh"
            base = bases[rng.randrange(len(bases))]
            key = f"{base}@r{revs[base]}"
        if key not in seen_keys:
            seen_keys.append(key)
        requests.append(Request(
            rid=f"r{len(requests):04d}", t=tq, tenant=tenant,
            priority=int(prio), scene=key, chunks=scene_chunks[base],
            depth=spec.depth, checkpoint_every=spec.checkpoint_every,
            kind=kind,
        ))
    return Workload(spec=spec, seed=int(seed), requests=requests)


# --------------------------------------------------------------------------
# Scenario registry: spec + the gate targets that make it a TEST
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class GateTargets:
    """Pass/fail thresholds for one scenario (gates.py evaluates)."""

    #: inclusive (lo, hi) bounds on sheds/(sheds+submits); None = must
    #: shed nothing
    shed_frac: Optional[Tuple[float, float]] = None
    #: ((priority, max p99 queue wait in virtual seconds), ...)
    p99_wait_s: Tuple[Tuple[int, float], ...] = ()
    #: clean scenario: the health watchdog must NEVER fire during replay
    health_clean: bool = True
    #: storm scenario: these conditions MUST fire at least once
    health_must_flag: Tuple[str, ...] = ()
    #: every admitted job must reach DONE at drain
    complete_all: bool = True


@dataclass(frozen=True)
class LoadScenario:
    spec: WorkloadSpec
    gates: GateTargets
    #: include in the `--ci` smoke set
    ci: bool = True


def _scenarios() -> Dict[str, LoadScenario]:
    out: Dict[str, LoadScenario] = {}

    # steady: flat Poisson at ~40% utilization, power-law tenants.
    # The false-positive baseline: no sheds, no health flags, bounded
    # waits.
    out["steady"] = LoadScenario(
        spec=WorkloadSpec(
            name="steady", duration_s=2.0, rate=40.0, tenants=4,
        ),
        gates=GateTargets(
            shed_frac=None,
            p99_wait_s=((0, 0.5),),
        ),
    )

    # burst: 8x arrival spikes against a depth SLO — shedding must
    # engage, deterministically, and keep admitted-work p99 bounded,
    # WITHOUT burning past the slo_burn alarm (shedding that trips its
    # own pager is mistuned).
    out["burst"] = LoadScenario(
        spec=WorkloadSpec(
            name="burst", duration_s=2.0, rate=25.0, burst_factor=8.0,
            burst_period_s=1.0, tenants=4, slo_depth="8",
        ),
        gates=GateTargets(
            shed_frac=(0.01, 0.45),
            p99_wait_s=((0, 0.5),),
        ),
    )

    # heavy: heavy-tail scene sizes + two priority classes + two
    # film-state slots — preemption and size skew; the high class must
    # keep a tighter p99 than the default class.
    out["heavy"] = LoadScenario(
        spec=WorkloadSpec(
            name="heavy", duration_s=2.0, rate=20.0, tenants=3,
            priorities=(0, 5), priority_weights=(0.65, 0.35),
            chunks_max=16, chunks_tail=1.1, max_active=2,
            service_time_s=0.003,
        ),
        gates=GateTargets(
            shed_frac=None,
            p99_wait_s=((0, 1.5), (5, 1.5)),
        ),
    )

    # editstorm: half the traffic edits scenes (new keys = recompiles),
    # a third resubmits warm keys — residency churn under load.
    out["editstorm"] = LoadScenario(
        spec=WorkloadSpec(
            name="editstorm", duration_s=1.5, rate=30.0, tenants=2,
            scene_pool=3, edit_fraction=0.5, resubmit_fraction=0.3,
        ),
        gates=GateTargets(
            shed_frac=None,
            p99_wait_s=((0, 1.0),),
        ),
    )

    # shedstorm: a deliberately over-tight depth SLO under sustained
    # overload — the slo_burn health condition MUST fire (a storm the
    # watchdog sleeps through is the false-negative bug).
    out["shedstorm"] = LoadScenario(
        spec=WorkloadSpec(
            name="shedstorm", duration_s=1.0, rate=200.0, tenants=2,
            slo_depth="1", chunks_min=3, chunks_max=8,
            service_time_s=0.01,
        ),
        gates=GateTargets(
            shed_frac=(0.5, 1.0),
            health_clean=False,
            health_must_flag=("slo_burn",),
        ),
    )

    # retrystorm: CHAOS fails the first 6 chunk-0 dispatches — some
    # job's attempt counter must climb past the storm threshold and the
    # backoff_storm condition must fire; retry_max (8) still recovers
    # every job, so completion holds.
    out["retrystorm"] = LoadScenario(
        spec=WorkloadSpec(
            name="retrystorm", duration_s=2.0, rate=2.0, tenants=1,
            fault="dispatch:fail@chunk=0&times=6",
        ),
        gates=GateTargets(
            shed_frac=None,
            health_clean=False,
            health_must_flag=("backoff_storm",),
        ),
    )
    return out


SCENARIOS: Dict[str, LoadScenario] = _scenarios()
CI_SCENARIOS: Tuple[str, ...] = tuple(
    name for name, s in SCENARIOS.items() if s.ci
)


def scaled(scn: LoadScenario, rate: float) -> LoadScenario:
    """The capacity sweep's knob: the same scenario at a different
    offered rate (name suffixed so generation reseeds per rung)."""
    spec = replace(
        scn.spec, rate=float(rate), name=f"{scn.spec.name}+r{rate:g}"
    )
    return replace(scn, spec=spec)
