"""``python -m tpu_pbrt.load`` — the load-harness CLI.

Modes:

- default / ``--scenario NAME`` — run named scenarios (or all) with
  their gates and print a pass/fail table;
- ``--ci`` — the CI smoke: every CI scenario at a fixed seed plus a
  small capacity sweep, under a wall-seconds budget, exiting nonzero
  on any gate failure or budget overrun;
- ``--capacity NAME`` — the arrival-rate sweep: report the knee (max
  sustainable req/s per replica at the SLO);
- ``--replicas N`` — replay through the fleet router over N replicas
  under one VirtualClock (decision logs stay byte-identical per
  (spec, seed, N); the gates read fleet-wide aggregates);
- ``--list`` — the scenario registry with specs.

``--report`` writes the deterministic JSON report (no wall times, no
paths) that LOADTEST_baseline.json pins; ``--trace-out`` exports the
first scenario replay's tpu-scope trace in virtual time (the smoke
feeds it to ``tools/scope.py --check``); ``--flight-out`` arms the
flight recorder (the capture-replay source).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional

from tpu_pbrt.load.gates import capacity_sweep, evaluate_scenario
from tpu_pbrt.load.workload import CI_SCENARIOS, SCENARIOS

#: wall-seconds the --ci smoke may spend before failing (the whole
#: point is hours of virtual traffic in seconds of wall time — a smoke
#: that crawls has lost the accelerated-replay property)
CI_BUDGET_S = 240.0

#: the --ci capacity sweep: scenario, ladder, SLO target
CI_CAPACITY_SCENARIO = "steady"
CI_CAPACITY_MULTIPLIERS = (0.5, 1.0, 2.0, 4.0, 8.0)
CI_CAPACITY_P99_S = 0.5


def _print_report(rep) -> None:
    mark = "ok " if rep.ok else "FAIL"
    print(f"[{mark}] {rep.scenario} (seed {rep.seed}): "
          f"{len(rep.result.workload.requests)} requests, "
          f"{rep.result.submitted} admitted, {rep.result.sheds} shed, "
          f"{rep.result.completed} done in "
          f"{rep.result.virtual_seconds:.3f} virtual s")
    for g in rep.gates:
        gm = "ok " if g.ok else "FAIL"
        print(f"    [{gm}] {g.name}: value={g.value} target={g.target}"
              + (f" ({g.detail})" if g.detail and not g.ok else ""))


def _print_capacity(cap: Dict[str, Any]) -> None:
    knee = cap["knee_req_s"]
    print(f"capacity[{cap['scenario']}] seed {cap['seed']} "
          f"p99_target={cap['p99_target_s']}s -> knee="
          + (f"{knee:g} req/s" if knee is not None else "NONE"))
    for rung in cap["ladder"]:
        mark = "sustainable" if rung["sustainable"] else "over"
        print(f"    x{rung['rate_multiplier']:g}: "
              f"{rung['offered_req_s']:g} req/s offered, "
              f"{rung['sheds']} shed, p99={rung['p99_wait_s']} "
              f"-> {mark}")


def run(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_pbrt.load",
        description="deterministic traffic-replay load harness",
    )
    ap.add_argument("--scenario", action="append", default=None,
                    help="scenario name (repeatable; default: all)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--replicas", type=int, default=1,
                    help="fleet width: replay through the router over "
                         "N serve replicas (default 1 = no router)")
    ap.add_argument("--ci", action="store_true",
                    help="CI smoke: gate every CI scenario + capacity "
                         "sweep under a wall budget")
    ap.add_argument("--capacity", metavar="NAME", default=None,
                    help="sweep arrival rate on NAME and report the "
                         "sustainable-req/s knee")
    ap.add_argument("--budget-s", type=float, default=None,
                    help=f"wall-seconds budget (default {CI_BUDGET_S:g} "
                         "with --ci, unlimited otherwise)")
    ap.add_argument("--report", metavar="PATH", default=None,
                    help="write the deterministic JSON report "
                         "('-' = stdout)")
    ap.add_argument("--trace-out", metavar="PATH", default=None,
                    help="export the first scenario's virtual-time "
                         "tpu-scope trace")
    ap.add_argument("--flight-out", metavar="PATH", default=None,
                    help="arm the flight recorder for the first "
                         "scenario (capture-replay source)")
    ap.add_argument("--list", action="store_true",
                    help="list the scenario registry")
    args = ap.parse_args(argv)

    if args.list:
        for name, scn in SCENARIOS.items():
            tags = []
            if scn.ci:
                tags.append("ci")
            if scn.gates.health_must_flag:
                tags.append(
                    "must-flag:" + ",".join(scn.gates.health_must_flag)
                )
            print(f"{name:<12s} rate={scn.spec.rate:g}/s "
                  f"dur={scn.spec.duration_s:g}s "
                  f"tenants={scn.spec.tenants}"
                  + (f" slo_depth={scn.spec.slo_depth}"
                     if scn.spec.slo_depth else "")
                  + (f" fault={scn.spec.fault}" if scn.spec.fault else "")
                  + (f"  [{' '.join(tags)}]" if tags else ""))
        return 0

    t_wall = time.perf_counter()
    budget = args.budget_s
    if budget is None and args.ci:
        budget = CI_BUDGET_S

    names: List[str]
    if args.ci:
        names = list(CI_SCENARIOS)
    elif args.scenario:
        names = list(args.scenario)
    elif args.capacity:
        names = []
    else:
        names = list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown or (args.capacity and args.capacity not in SCENARIOS):
        bad = unknown or [args.capacity]
        print(f"unknown scenario(s): {', '.join(bad)} "
              f"(--list shows the registry)", file=sys.stderr)
        return 2

    report: Dict[str, Any] = {
        "schema": "tpu-pbrt-loadtest-v1",
        "seed": args.seed,
        "scenarios": {},
        "capacity": {},
    }
    if args.replicas != 1:
        report["replicas"] = args.replicas
    failed = False
    for i, name in enumerate(names):
        rep = evaluate_scenario(
            SCENARIOS[name], args.seed,
            replicas=args.replicas,
            flight_path=args.flight_out if i == 0 else None,
            trace_path=args.trace_out if i == 0 else None,
        )
        _print_report(rep)
        report["scenarios"][name] = rep.to_dict()
        failed = failed or not rep.ok

    cap_name = args.capacity or (CI_CAPACITY_SCENARIO if args.ci else None)
    if cap_name:
        cap = capacity_sweep(
            SCENARIOS[cap_name], args.seed,
            multipliers=CI_CAPACITY_MULTIPLIERS,
            p99_target_s=CI_CAPACITY_P99_S,
        )
        _print_capacity(cap)
        report["capacity"][cap_name] = cap
        if cap["knee_req_s"] is None:
            # the sweep exists to EMIT a capacity number; a ladder with
            # no sustainable rung means the scenario/SLO pairing is
            # mistuned, and the capacity-planning consumer gets nothing
            print("capacity sweep found no sustainable rung",
                  file=sys.stderr)
            failed = True

    wall = time.perf_counter() - t_wall
    print(f"wall: {wall:.1f}s"
          + (f" (budget {budget:g}s)" if budget is not None else ""))
    if budget is not None and wall > budget:
        print(f"FAIL: wall budget exceeded ({wall:.1f}s > {budget:g}s)",
              file=sys.stderr)
        failed = True

    if args.report:
        text = json.dumps(report, indent=1, sort_keys=True)
        if args.report == "-":
            print(text)
        else:
            with open(args.report, "w") as f:
                f.write(text + "\n")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(run())
