"""tpu-load: deterministic traffic-replay load harness (ISSUE 19).

The serve stack's policies — WFQ, SLO shedding, preemption, backoff,
health verdicts — were each tuned against hand-written selftests. This
package proves them against TRAFFIC: a seeded workload generator
(`workload.py`) emits a timestamped request schedule that is a pure
function of (seed, spec); a replay engine (`replay.py`) drives the REAL
`RenderService` with that schedule under a `VirtualClock`, so hours of
simulated multi-tenant traffic run in seconds of wall time with a
byte-reproducible decision log; and a gate layer (`gates.py`) asserts
fleet invariants over the run's metrics-registry snapshot — shed
fraction under burst, per-class p99 queue wait, zero health-watchdog
false positives on clean scenarios, pin balance at drain.

Entry point: ``python -m tpu_pbrt.load`` (see ``__main__.py``) — the
``--ci`` smoke the CI pipeline runs, and the ``--capacity`` sweep that
reports the max sustainable req/s knee the fleet-router direction
needs.

Determinism contract (the whole point): same (scenario, seed) =>
byte-identical schedule AND byte-identical service decision log. The
generator draws only from `random.Random(...)` seeded from (name,
seed); the replay clock is virtual; every log line is path-free.
"""

from tpu_pbrt.load.workload import (  # noqa: F401
    Request,
    SCENARIOS,
    Workload,
    WorkloadSpec,
    generate,
)
