"""Gates: fleet invariants asserted over a replay's results.

Each gate is a pure function of `ReplayResult` (and the scenario's
`GateTargets`) returning a `GateResult` — named, pass/fail, with the
observed value and the target it was held to. The p99 gate reads the
METRICS REGISTRY SNAPSHOT the replay captured at drain (not private
service state): the same surface a production monitor scrapes, so a
gate passing here means the alert built on the exported metric would
have stayed quiet too.

`evaluate_scenario` is the one-stop runner the CLI and tests share:
generate, replay TWICE (the determinism gate byte-compares schedule
and decision log), then apply the scenario's targets.

`capacity_sweep` re-runs one scenario across an arrival-rate ladder
and reports the KNEE — the highest offered req/s the replica sustains
with zero sheds, p99 within target, and a quiet watchdog. That number
(per replica, at the SLO) is the capacity-planning input the
fleet-router direction needs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpu_pbrt.load.replay import ReplayResult, replay
from tpu_pbrt.load.workload import (
    GateTargets,
    LoadScenario,
    generate,
    scaled,
)

__all__ = [
    "GateResult",
    "ScenarioReport",
    "snapshot_wait_p99",
    "evaluate_gates",
    "evaluate_scenario",
    "capacity_sweep",
]

_WAIT_METRIC = "tpu_pbrt_serve_queue_wait_seconds"


@dataclass
class GateResult:
    name: str
    ok: bool
    value: Any
    target: Any
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "ok": self.ok,
            "value": self.value, "target": self.target,
            "detail": self.detail,
        }


@dataclass
class ScenarioReport:
    """One scenario's full outcome: the gates plus the replay facts a
    future PR diffs against LOADTEST_baseline.json."""

    scenario: str
    seed: int
    gates: List[GateResult]
    result: ReplayResult
    #: fleet width the replay ran at (1 = the classic single-replica
    #: engine; >1 = routed through the FleetRouter)
    replicas: int = 1

    @property
    def ok(self) -> bool:
        return all(g.ok for g in self.gates)

    def to_dict(self) -> Dict[str, Any]:
        r = self.result
        extra: Dict[str, Any] = (
            {"replicas": self.replicas} if self.replicas != 1 else {}
        )
        return {
            **extra,
            "scenario": self.scenario,
            "seed": self.seed,
            "ok": self.ok,
            "gates": [g.to_dict() for g in self.gates],
            "requests": len(r.workload.requests),
            "submitted": r.submitted,
            "sheds": r.sheds,
            "completed": r.completed,
            "failed": r.failed,
            "dispatches": r.dispatches,
            "compiles": r.compiles,
            "residency_hits": r.residency_hits,
            "evictions": r.evictions,
            "preemptions": r.preemptions,
            "health_flags": r.health_flags,
            "virtual_seconds": r.virtual_seconds,
        }


# --------------------------------------------------------------------------
# Snapshot readers
# --------------------------------------------------------------------------


def snapshot_wait_p99(
    snapshot: Dict[str, Any], priority: int,
) -> Optional[float]:
    """Per-priority-class p99 queue wait from a registry snapshot:
    aggregate the histogram's bucket counts across every tenant series
    of the class, then interpolate — the exact arithmetic a recording
    rule on the exported metric would do."""
    from tpu_pbrt.obs.metrics import percentile_from_buckets

    metric = snapshot.get("metrics", {}).get(_WAIT_METRIC)
    if not metric:
        return None
    agg: Optional[List[int]] = None
    edges: Tuple[float, ...] = ()
    for series in metric["series"]:
        if series["labels"].get("priority") != str(int(priority)):
            continue
        counts = series["counts"]
        if agg is None:
            agg = [0] * len(counts)
            edges = tuple(
                float(b) for b in series["buckets"] if b != "+Inf"
            )
        for i, c in enumerate(counts):
            agg[i] += c
    if agg is None:
        return None
    return percentile_from_buckets(edges, agg, 0.99)


def _shed_fraction(result: ReplayResult) -> float:
    total = result.sheds + result.submitted
    return result.sheds / total if total else 0.0


# --------------------------------------------------------------------------
# Gates
# --------------------------------------------------------------------------


def gate_determinism(
    a: ReplayResult, b: ReplayResult,
) -> GateResult:
    """Same seed, two independent replays: the schedules are identical
    by construction, so the byte-compare is over the DECISION LOGS —
    every submit/shed/dispatch the service made, in order."""
    same = a.log == b.log
    detail = ""
    if not same:
        for i, (la, lb) in enumerate(zip(a.log, b.log)):
            if la != lb:
                detail = f"first divergence at line {i}: {la!r} != {lb!r}"
                break
        else:
            detail = f"length mismatch: {len(a.log)} vs {len(b.log)}"
    return GateResult(
        "determinism", same, len(a.log), len(b.log), detail,
    )


def gate_shed_fraction(
    result: ReplayResult, bounds: Optional[Tuple[float, float]],
) -> GateResult:
    frac = round(_shed_fraction(result), 6)
    if bounds is None:
        return GateResult(
            "shed_fraction", result.sheds == 0, frac, 0.0,
            f"{result.sheds} shed(s) on a scenario that must shed none",
        )
    lo, hi = bounds
    return GateResult(
        "shed_fraction", lo <= frac <= hi, frac, list(bounds),
        f"{result.sheds} of {result.sheds + result.submitted} submits shed",
    )


def gate_p99_wait(
    result: ReplayResult, priority: int, target_s: float,
) -> GateResult:
    p99 = snapshot_wait_p99(result.snapshot, priority)
    name = f"p99_wait[{priority}]"
    if p99 is None:
        # a class with NO dispatches observed no waits — that is a
        # scenario-shape problem, not a latency pass
        return GateResult(
            name, False, None, target_s,
            f"no queue-wait samples for priority class {priority}",
        )
    return GateResult(
        name, p99 <= target_s, round(p99, 6), target_s,
        "virtual-seconds p99 from the registry snapshot",
    )


def gate_health(
    result: ReplayResult, targets: GateTargets,
) -> List[GateResult]:
    out: List[GateResult] = []
    if targets.health_clean:
        out.append(GateResult(
            "health_clean", not result.health_flags,
            result.health_flags, [],
            "watchdog conditions that fired during a clean scenario",
        ))
    missing = [
        f for f in targets.health_must_flag
        if f not in result.health_flags
    ]
    if targets.health_must_flag:
        out.append(GateResult(
            "health_must_flag", not missing,
            result.health_flags, list(targets.health_must_flag),
            f"missing: {missing}" if missing else "",
        ))
    return out


def gate_pin_balance(result: ReplayResult) -> GateResult:
    """PROTO-PIN at drain: every residency pin released once all jobs
    are terminal (a leak is a scene the LRU can never evict)."""
    return GateResult(
        "pin_balance", not result.pin_leaks, result.pin_leaks, {},
        "residency keys with live pins after drain",
    )


def gate_completion(result: ReplayResult) -> GateResult:
    bad = result.failed + len(result.unfinished)
    return GateResult(
        "completion", bad == 0,
        {"failed": result.failed, "unfinished": result.unfinished},
        {"failed": 0, "unfinished": []},
        "every admitted job must reach DONE at drain",
    )


def evaluate_gates(
    result: ReplayResult, targets: GateTargets,
) -> List[GateResult]:
    """Apply a scenario's targets to one replay result."""
    out = [gate_shed_fraction(result, targets.shed_frac)]
    for prio, tgt in targets.p99_wait_s:
        out.append(gate_p99_wait(result, prio, tgt))
    out.extend(gate_health(result, targets))
    out.append(gate_pin_balance(result))
    if targets.complete_all:
        out.append(gate_completion(result))
    return out


# --------------------------------------------------------------------------
# Runners
# --------------------------------------------------------------------------


def evaluate_scenario(
    scn: LoadScenario, seed: int,
    *,
    replicas: int = 1,
    flight_path: Optional[str] = None,
    trace_path: Optional[str] = None,
) -> ScenarioReport:
    """Generate + double-replay + gate one scenario (``replicas > 1``
    routes both replays through the fleet engine — the determinism
    gate then byte-compares routed decision logs, and the other gates
    read fleet-wide aggregates). The second replay exists only to feed
    the determinism gate; its recorders stay unarmed so the
    flight/trace sinks hold exactly one run."""
    wl = generate(scn.spec, seed)
    first = replay(
        wl, replicas=replicas,
        flight_path=flight_path, trace_path=trace_path,
    )
    second = replay(wl, replicas=replicas)
    gates = [gate_determinism(first, second)]
    gates.extend(evaluate_gates(first, scn.gates))
    return ScenarioReport(
        scenario=scn.spec.name, seed=seed, gates=gates, result=first,
        replicas=replicas,
    )


def capacity_sweep(
    scn: LoadScenario, seed: int,
    *,
    multipliers: Sequence[float] = (0.5, 1.0, 2.0, 4.0, 8.0),
    p99_target_s: float = 0.5,
) -> Dict[str, Any]:
    """Sweep offered arrival rate across `multipliers` x the scenario's
    base rate; a rung is SUSTAINABLE when the replica finished it with
    zero sheds, every class's p99 wait within `p99_target_s`, a quiet
    watchdog, and full completion. Returns the ladder and the knee:
    the highest sustainable OFFERED rate in requests per virtual
    second (per replica, at this SLO)."""
    ladder: List[Dict[str, Any]] = []
    knee: Optional[float] = None
    for m in multipliers:
        rung_scn = scaled(scn, scn.spec.rate * m)
        wl = generate(rung_scn.spec, seed)
        result = replay(wl)
        prios = sorted({r.priority for r in wl.requests}) or [0]
        p99s = {
            p: snapshot_wait_p99(result.snapshot, p) for p in prios
        }
        offered = (
            len(wl.requests) / rung_scn.spec.duration_s
            if rung_scn.spec.duration_s else 0.0
        )
        sustainable = (
            result.sheds == 0
            and not result.health_flags
            and result.failed == 0
            and not result.unfinished
            and all(
                v is not None and v <= p99_target_s
                for v in p99s.values()
            )
        )
        ladder.append({
            "rate_multiplier": m,
            "offered_req_s": round(offered, 6),
            "requests": len(wl.requests),
            "sheds": result.sheds,
            "p99_wait_s": {
                str(p): (None if v is None else round(v, 6))
                for p, v in p99s.items()
            },
            "health_flags": result.health_flags,
            "sustainable": sustainable,
        })
        if sustainable and (knee is None or offered > knee):
            knee = offered
    return {
        "scenario": scn.spec.name,
        "seed": seed,
        "p99_target_s": p99_target_s,
        "knee_req_s": None if knee is None else round(knee, 6),
        "ladder": ladder,
    }
