"""Queue policy for the render service: priority classes + weighted
fair sharing across tenants, deterministic given a seed.

Two-level decision, evaluated at every scheduler step over the runnable
job set:

1. **Strict priority classes.** A higher `priority` int always schedules
   before a lower one (and, through the service's `max_active` knob, can
   PREEMPT a lower class's film residency — see
   `preemption_victim`). Classes are for urgency tiers (interactive
   preview vs batch final-frame), not for shares.
2. **Weighted fair sharing across tenants** within a class: each tenant
   carries a virtual service time (`vtime`) advanced by
   `slice_cost / weight` per dispatched chunk-slice; the runnable job
   whose tenant has the SMALLEST vtime runs next. A tenant with weight 2
   therefore gets ~2x the slices of a weight-1 tenant under contention,
   and an idle tenant re-enters at the current minimum among busy
   tenants (no banked credit, the classic start-time fairness rule —
   new tenants via `tenant()`, returning ones via `reenter()`, which
   the service calls on every submit).
3. FIFO within a tenant (submit sequence number).

Determinism contract: `pick` consults nothing but (priority, vtime,
seeded tenant hash, submit seq) — no wall clock, no dict order, no
Python `hash` (PYTHONHASHSEED-dependent). Two services fed the same
submit/charge sequence with the same seed produce the same interleaving,
which is what lets tests assert interleaving-independence of the
rendered films and replay a production schedule from its log.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple


@dataclass
class TenantShare:
    """Per-tenant fair-share accounting."""

    weight: float = 1.0
    vtime: float = 0.0  # virtual service time (slice cost / weight)
    slices: int = 0  # total chunk-slices charged (stats only)


class FairScheduler:
    """Deterministic priority + weighted-fair-queueing policy."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._tenants: Dict[str, TenantShare] = {}

    # -- tenants -----------------------------------------------------------
    def _set_vtime(self, ts: TenantShare, vtime: float) -> None:
        """The ONLY sanctioned vtime writer. The fairness invariants
        (no banked credit, vtime monotone per tenant under charge) live
        in the three callers — tenant()'s floor init, reenter()'s busy
        clamp, charge()'s weighted advance; a vtime write anywhere else
        is a policy bypass, and the SV-VTIME lint rule (analysis layer
        6, protocheck) rejects it."""
        ts.vtime = float(vtime)

    def tenant(self, name: str) -> TenantShare:
        ts = self._tenants.get(name)
        if ts is None:
            # a new (or returning-idle) tenant starts at the current
            # minimum vtime: it competes fairly from NOW instead of
            # replaying every slice it never asked for
            floor = min(
                (t.vtime for t in self._tenants.values()), default=0.0
            )
            ts = self._tenants[name] = TenantShare()
            self._set_vtime(ts, floor)
        return ts

    def set_weight(self, name: str, weight: float) -> None:
        self.tenant(name).weight = max(float(weight), 1e-9)

    def reenter(self, name: str, busy_tenants=()) -> None:
        """Start-time fairness for a RETURNING tenant: clamp its vtime
        up to the minimum among `busy_tenants` (the tenants that
        currently have schedulable work — the caller knows the job
        table, this policy object does not). Without the clamp an
        existing tenant that went idle keeps its stale low vtime and
        re-enters with banked credit, monopolizing the mesh until the
        backlog 'catches up' — the exact opposite of the no-banked-
        credit rule. Deterministic: a pure function of recorded
        vtimes."""
        ts = self.tenant(name)
        floor = [
            self._tenants[t].vtime
            for t in busy_tenants
            if t != name and t in self._tenants
        ]
        if floor:
            self._set_vtime(ts, max(ts.vtime, min(floor)))

    def _tiebreak(self, tenant: str) -> int:
        return zlib.crc32(f"{self.seed}:{tenant}".encode())

    # -- policy ------------------------------------------------------------
    def sort_key(self, job):
        """Total order over runnable jobs: smaller runs first. `job`
        needs .priority (int, higher = more urgent), .tenant (str) and
        .seq (int submit sequence)."""
        ts = self.tenant(job.tenant)
        return (-job.priority, ts.vtime, self._tiebreak(job.tenant), job.seq)

    def pick(self, jobs: Iterable, record: bool = True):
        """The runnable job to dispatch next, or None. `record` marks
        the decision on the trace timeline (an instant event carrying
        the chosen job's trace id) — peek passes False, keeping the
        lookahead contract that it leaves no mark anywhere."""
        best = None
        best_key = None
        for j in jobs:
            k = self.sort_key(j)
            if best is None or k < best_key:
                best, best_key = j, k
        if best is not None and record:
            from tpu_pbrt.obs.trace import TRACE

            TRACE.instant(
                "sched/pick",
                job=getattr(best, "job_id", ""),
                tenant=best.tenant, priority=best.priority,
                trace_id=getattr(best, "trace_id", ""),
            )
        return best

    def peek(self, jobs: Iterable):
        """Read-only lookahead: which job WOULD dispatch next — the
        service's prefetch path (ISSUE 13) uses this to pre-activate
        the next scheduled job under in-flight compute. Identical
        ordering to `pick` (neither charges vtime; accounting happens
        separately via `charge`) — the distinct name documents the
        prefetch contract that peeking must never perturb the recorded
        schedule (or the trace: record=False), and gives the policy
        room to diverge later (e.g. a pick that reserves) without
        breaking lookahead callers."""
        return self.pick(jobs, record=False)

    def charge(self, tenant: str, cost: float = 1.0) -> None:
        """Account one dispatched chunk-slice to `tenant`."""
        ts = self.tenant(tenant)
        self._set_vtime(ts, ts.vtime + cost / ts.weight)
        ts.slices += 1
        from tpu_pbrt.obs.trace import TRACE

        # a counter track per tenant: Perfetto plots the fair-share
        # vtime race the schedule decisions above are explained by
        TRACE.counter("sched/vtime", **{tenant: round(ts.vtime, 6)})

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "weight": ts.weight,
                "vtime": round(ts.vtime, 6),
                "slices": ts.slices,
            }
            for name, ts in sorted(self._tenants.items())
        }


# --------------------------------------------------------------------------
# SLO admission control (ISSUE 10: ROADMAP #2's load-shedding item)
# --------------------------------------------------------------------------


def parse_slo_spec(spec: str, cast) -> Dict[Optional[int], float]:
    """`TPU_PBRT_SERVE_SLO_*` spec grammar -> {priority class: target}.
    A bare value ("8") or `default=8` sets the every-class default (the
    None key); `0=4,5=32` sets per-class targets. Raises on anything
    else — a silently ignored SLO knob is the worst failure mode an
    admission-control config can have."""
    out: Dict[Optional[int], float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        if not eq:
            out[None] = cast(k)
        elif k.strip().lower() in ("default", "*"):
            out[None] = cast(v)
        else:
            out[int(k)] = cast(v)
    return out


@dataclass
class SloPolicy:
    """Per-priority-class admission targets. The shed decision is a PURE
    function of (class, queued depth, observed wait p90) — no wall
    clock, no randomness — so an over-SLO submit burst sheds the same
    requests every run (the determinism contract the scheduler already
    keeps, extended to admission)."""

    #: class -> max runnable jobs before a submit sheds (None key = default)
    depth: Dict[Optional[int], float] = field(default_factory=dict)
    #: class -> max observed p90 queue wait (seconds) before a submit sheds
    wait_s: Dict[Optional[int], float] = field(default_factory=dict)

    @classmethod
    def from_cfg(cls) -> "SloPolicy":
        from tpu_pbrt.config import cfg

        return cls(
            depth=parse_slo_spec(cfg.serve_slo_depth, int),
            wait_s=parse_slo_spec(cfg.serve_slo_wait_s, float),
        )

    def enabled(self) -> bool:
        return bool(self.depth or self.wait_s)

    def depth_target(self, priority: int) -> Optional[int]:
        t = self.depth.get(int(priority), self.depth.get(None))
        return None if t is None else int(t)

    def wait_target(self, priority: int) -> Optional[float]:
        t = self.wait_s.get(int(priority), self.wait_s.get(None))
        return None if t is None else float(t)

    def admit(
        self, priority: int, queued_depth: int,
        wait_p90: Optional[float] = None,
    ) -> Tuple[bool, str]:
        """(admit?, shed reason). queued_depth counts the class's
        runnable jobs BEFORE this submit; wait_p90 is the class's
        observed p90 queue wait (None = no observations yet — never a
        shed reason on its own: an idle service must accept work)."""
        d = self.depth_target(priority)
        if d is not None and queued_depth >= d:
            return False, (
                f"queue depth {queued_depth} at class-{priority} "
                f"target {d}"
            )
        w = self.wait_target(priority)
        if w is not None and wait_p90 is not None and wait_p90 > w:
            return False, (
                f"queue-wait p90 {wait_p90:.3f}s over class-{priority} "
                f"target {w:g}s"
            )
        return True, ""


def preemption_victim(active_jobs: Iterable, candidate) -> Optional[object]:
    """Which film-resident job to preempt (emergency-checkpoint to disk,
    PR 5's path) so `candidate` can activate: the LOWEST-priority active
    job strictly below the candidate's class — ties broken by largest
    submit seq (newest first, oldest work is closest to done). None when
    no active job is outranked (the candidate waits its fair turn
    instead)."""
    victim = None
    v_key = None
    for j in active_jobs:
        if j.priority >= candidate.priority:
            continue
        k = (j.priority, -j.seq)
        if victim is None or k < v_key:
            victim, v_key = j, k
    return victim
