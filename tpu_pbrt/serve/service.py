"""tpu-serve session core: resumable render jobs multiplexed on one mesh.

The paper's fork turns pbrt into a master/worker service — a master that
owns a tile queue, workers that render on demand. PRs 1-5 reproduced the
renderer as a batch CLI: compile one scene, drain one pool, exit. This
module is the serving layer on top of the same machinery:

- A **RenderJob** owns exactly the checkpoint-v4 tuple — film state,
  chunk cursor, ray count, telemetry counter snapshot — plus a
  `ChunkPlan` (integrators/common.py): the chunk decomposition and the
  jitted dispatch closure the run-to-completion loop was refactored
  around. Because every chunk is an idempotent pure function of
  (scene, work range) and film accumulation is associative, a job can
  be stopped between any two chunk-slices and resumed (same process or
  another) with a film BIT-identical to an uninterrupted render.
- The **scheduler loop** (`step`) dispatches ONE chunk-slice of one job
  at a time. A slice is a bounded number of pool waves (the preemption
  quantum): any job can be preempted at wave granularity with no lost
  work, because the slice either completed (its deposits are in the
  job's own film accumulator) or never ran.
- **Preemption** parks a job through PR 5's emergency-checkpoint path:
  the tuple is written durably (checkpoint v4 — CRC, fsync-before-
  rename, `.prev` rotation), the in-memory film state is dropped (HBM
  freed for higher-priority work), and a later activation reloads it.
- **Residency** (serve/residency.py): compiled scenes + their jit
  closures stay cached across jobs, so a warm resubmit pays zero scene
  compiles and zero jit retraces (the PR 2 `_cache_size` audit is the
  enforcement tool).
- **Policy** (serve/queue.py): strict priority classes, weighted fair
  sharing across tenants, deterministic given a seed — the recorded
  `schedule` is replayable and tests assert films are independent of
  the interleaving.
- **Previews**: at a client-requested cadence the live film state is
  developed (`film.develop` of the partial accumulator — radiance
  planes self-normalize by the weight sum, so a partial render is a
  noisier image, not a darker one) and written to PNG/EXR/PFM.

Frontends: the library API here, `python -m tpu_pbrt.serve` (stdin/JSONL
daemon + --selftest), and `tpu-pbrt --serve` (main.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from tpu_pbrt.config import cfg
from tpu_pbrt.core.film import FilmState
from tpu_pbrt.integrators.common import (
    ChunkDispatchError,
    ChunkPlan,
    DispatchWindow,
    NonFiniteRadianceError,
    NonFiniteWaveError,
    RenderResult,
    redispatch_backoff,
)
from tpu_pbrt.parallel.checkpoint import (
    checkpoint_exists,
    delete_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from tpu_pbrt.obs.metrics import METRICS, phase_histogram
from tpu_pbrt.serve.queue import FairScheduler, SloPolicy, preemption_victim
from tpu_pbrt.serve.residency import (
    ResidencyCache,
    scene_source_key,
)
from tpu_pbrt.utils.clock import WALL

# job lifecycle. queued: never dispatched. active: film state in memory.
# parked: progress on disk (policy preemption), schedulable. paused:
# explicitly preempted, needs resume(). done/cancelled/failed: terminal.
QUEUED = "queued"
ACTIVE = "active"
PARKED = "parked"
PAUSED = "paused"
DONE = "done"
CANCELLED = "cancelled"
FAILED = "failed"
_TERMINAL = (DONE, CANCELLED, FAILED)
_RUNNABLE = (QUEUED, ACTIVE, PARKED)


class ShedError(RuntimeError):
    """A submit was load-shed by the SLO admission policy (ISSUE 10 /
    ROADMAP #2): the priority class's queue-depth or queue-wait target
    was already breached, so queuing more work would only deepen the
    breach. The request was NOT queued — the caller should retry later
    or against another service. Deterministic: the same submit burst
    against the same service state sheds the same requests."""

    def __init__(self, msg: str, *, tenant: str, priority: int, reason: str):
        super().__init__(msg)
        self.tenant = tenant
        self.priority = priority
        self.reason = reason


# NOTE on labels: tenant/priority only — never job ids. A long-lived
# daemon processes unbounded jobs, and histogram series are permanent;
# per-job detail belongs to the per-job flight files, not the registry.
def _queue_wait_hist():
    return METRICS.histogram(
        "serve_queue_wait_seconds",
        "seconds a runnable job waited for its next chunk-slice dispatch "
        "(labels: tenant, priority)",
    )


def _slice_hist():
    return METRICS.histogram(
        "serve_slice_seconds",
        "chunk-slice service time: dispatch through bookkeeping "
        "(labels: tenant)",
    )


#: recent queue waits kept per priority class for the wait-SLO signal
_WAIT_WINDOW = 32


def _window_p90(window) -> Optional[float]:
    """Nearest-rank p90 over the bounded recent-wait window — exact and
    deterministic given the recorded waits (no buckets needed at n<=32).
    Nearest-rank: the ceil(0.9*n)-th smallest (1-based), so at n=20 the
    18th sample decides — not the 19th, which would let 2 outliers in a
    window of 20 shed a class whose p90 is actually under target."""
    if not window:
        return None
    import math

    w = sorted(window)
    return w[max(math.ceil(0.9 * len(w)) - 1, 0)]


@dataclass
class RenderJob:
    """One submitted render: identity, policy inputs, and the resumable
    state tuple (exactly what checkpoint v4 persists)."""

    job_id: str
    tenant: str
    priority: int
    seq: int  # submit sequence (FIFO within a tenant; the LRU tiebreak)
    resident_key: str
    chunk: Optional[int]  # slice width override (None = service default)
    checkpoint_path: str
    spool_ckpt: bool  # service-managed checkpoint (delete on terminal)
    checkpoint_every: int
    preview_every: int
    preview_path: str
    outfile: str
    status: str = QUEUED
    plan: Optional[ChunkPlan] = None
    state: Optional[FilmState] = None
    cursor: int = 0
    prev_rays: int = 0
    prev_ctr: Dict[str, Any] = field(default_factory=dict)
    ray_counts: List[Any] = field(default_factory=list)
    occ_counts: List[Any] = field(default_factory=list)
    ctr_counts: List[Any] = field(default_factory=list)
    nf_counts: List[Any] = field(default_factory=list)
    attempt: int = 0
    redispatches: int = 0
    #: redispatches already folded into prev_ctr (by a park/checkpoint
    #: write): snapshot_counters adds only the unbaked delta, or every
    #: park would re-merge the cumulative count (render()'s prior_rec
    #: double-count guard, ported)
    baked_redispatches: int = 0
    #: wall-clock deadline before which this job must not re-dispatch
    #: (the capped-backoff window; other tenants schedule meanwhile)
    not_before: float = 0.0
    #: in-flight dispatch window (ISSUE 13): per-slice sync handles +
    #: deferred checkpoint writes, created lazily at the first dispatch
    #: and torn down at every park/recover/cancel/finalize boundary
    window: Optional[DispatchWindow] = None
    rollbacks: int = 0
    restarts: int = 0
    preemptions: int = 0
    previews: int = 0
    #: wall clock at which the job last became dispatchable (submit,
    #: slice completion, resume, recovery) — queue wait is measured from
    #: here to the next dispatch, per slice
    ready_t: float = 0.0
    active_seconds: float = 0.0
    error: str = ""
    result: Optional[RenderResult] = None
    #: plan.n_chunks stashed at activation — survives the terminal-path
    #: plan release (a DONE/FAILED job drops its jit closures, which pin
    #: scene HBM past eviction, but poll()/progress() still need totals)
    chunks_total: int = 0
    # -- tpu-scope trace context (minted at submit) ------------------------
    #: deterministic request trace id ("t:<job_id>") every span, flight
    #: line, and histogram exemplar this job produces carries
    trace_id: str = ""
    #: this service minted the trace id and owns the root span's
    #: begin/end pair. False when a caller (the fleet router) supplied
    #: the trace context: the job's slices/waits still carry the id,
    #: but the root span opens and closes exactly once AT THE CALLER —
    #: a failover re-submit on another replica must not re-open it
    trace_owned: bool = True
    #: queue-wait episodes opened so far (the per-episode async-span id
    #: suffix: "<trace_id>/q<epoch>")
    wait_epoch: int = 0
    #: a queue-wait async span is currently open
    wait_open: bool = False
    #: the job's root async span has been closed (terminal outcome)
    trace_done: bool = False
    #: nonfinite deposits already reported to the registry counter (the
    #: drain-boundary delta guard, like baked_redispatches)
    nf_reported: int = 0

    # -- derived -----------------------------------------------------------
    def progress(self) -> float:
        total = (
            self.plan.n_chunks if self.plan is not None else self.chunks_total
        )
        if total <= 0:
            return 0.0
        return self.cursor / total

    def rays_so_far(self) -> int:
        return self.prev_rays + sum(
            int(r) for r in jax.device_get(self.ray_counts)
        )

    def snapshot_counters(self, n_ctr=None, n_nf=None) -> Dict[str, Any]:
        """Cumulative telemetry counter dict — the checkpoint payload.
        The device_get inside to_host is this job's drain-boundary
        fetch (park/finalize ARE drain boundaries). n_ctr/n_nf restrict
        the fetch to a list prefix: a deferred (pipelined) cadence
        checkpoint must persist counters for exactly the slices its
        cursor covers, not the ones dispatched ahead of it."""
        from tpu_pbrt.obs import counters as obs_counters

        snap = obs_counters.merge_host(
            self.prev_ctr, obs_counters.to_host(self.ctr_counts[:n_ctr])
        )
        nf = self.nf_counts[:n_nf]
        if nf:
            snap = obs_counters.merge_host(
                snap,
                {
                    "nonfinite_deposits": sum(
                        int(v) for v in jax.device_get(nf)
                    )
                },
            )
        unbaked = self.redispatches - self.baked_redispatches
        if unbaked > 0:
            snap = obs_counters.merge_host(
                snap, {"chunks_redispatched": unbaked}
            )
        return snap


class RenderService:
    """Multi-tenant render service over one device mesh.

    Cooperative scheduler: `step()` dispatches exactly one chunk-slice
    of the policy-selected job; `drain()` steps until every schedulable
    job reaches a terminal state. All submits share `mesh` (None =
    single device) — concurrency is wave-granular interleaving on the
    shared mesh, not parallel processes, which is exactly the TPU
    inference-stack shape (continuous batching on one resident model).

    `max_active` bounds how many jobs may hold live film state (HBM) at
    once; a higher-priority submit preempts the lowest outranked active
    job through the emergency-checkpoint path when the bound is hit.
    """

    def __init__(
        self,
        mesh=None,
        *,
        chunk: Optional[int] = None,
        max_resident_bytes: Optional[int] = None,
        max_active: Optional[int] = None,
        seed: int = 0,
        spool_dir: Optional[str] = None,
        quiet: bool = True,
        slo: Optional[SloPolicy] = None,
        clock=None,
    ):
        self.mesh = mesh
        # the protocol's only time source (utils/clock.py): every
        # scheduling decision, backoff deadline and wait measurement
        # samples THIS object, so a VirtualClock makes a whole service
        # run a pure function of the decision sequence (protocheck's
        # model-extraction seam). Default WALL = pre-seam behavior.
        self.clock = clock if clock is not None else WALL
        if chunk is None:
            chunk = cfg.serve_chunk
        self.chunk = chunk
        if max_resident_bytes is None and cfg.serve_resident_mb is not None:
            max_resident_bytes = int(cfg.serve_resident_mb * 1e6)
        self.residency = ResidencyCache(
            max_bytes=max_resident_bytes, clock=self.clock
        )
        self.scheduler = FairScheduler(seed=seed)
        self.max_active = max_active
        self.quiet = quiet
        if spool_dir is None:
            import tempfile

            spool_dir = tempfile.mkdtemp(prefix="tpu_pbrt_serve_")
        self.spool_dir = spool_dir
        self.jobs: Dict[str, RenderJob] = {}
        self._seq = 0
        # strict non-finite firewall modes read the scrub COUNT, which
        # rides the telemetry counters: refuse the combination here like
        # render() does, instead of silently degrading every job to
        # scrub mode (the exact contamination raise/retry exist to stop)
        from tpu_pbrt.obs import counters as obs_counters

        if cfg.nonfinite != "scrub" and not obs_counters.enabled():
            raise ValueError(
                f"TPU_PBRT_NONFINITE={cfg.nonfinite} needs the telemetry "
                "counters (the firewall's scrub count), but "
                "TPU_PBRT_TELEMETRY=0 disabled them; re-enable telemetry "
                "or use the default scrub mode"
            )
        # SLO admission control (ISSUE 10): per-class depth/wait targets
        # from TPU_PBRT_SERVE_SLO_* (or injected). The wait signal is a
        # BOUNDED in-service window of recent per-class queue waits —
        # not the registry's lifetime-cumulative histogram, whose p90
        # can never recover once elevated (shed submits produce no new
        # samples: a permanent lockout); the registry histogram remains
        # the exported observability surface. Works with
        # TPU_PBRT_METRICS=0 too (the window is service state).
        self.slo = slo if slo is not None else SloPolicy.from_cfg()
        self._recent_waits: Dict[int, Any] = {}
        #: submits answered with a shed (the deterministic count the
        #: selftest pins; the labeled breakdown lives in the registry)
        self.sheds = 0
        #: drain handoff (fleet router): a draining service sheds every
        #: new submit and parks its runnable jobs so the durable spool
        #: can be re-routed to another replica (begin_drain())
        self.draining = False
        #: the dispatch record [(job_id, chunk_index), ...] — the
        #: deterministic-interleaving evidence tests assert on
        self.schedule: List[tuple] = []
        # health-watchdog inputs (obs/health.py): step() calls made, and
        # the step at which a chunk cursor last advanced — their gap is
        # the wedge signal (runnable work, no progress)
        self.health_steps = 0
        self.last_progress_step = 0

    def _now(self) -> float:
        """One DECISION sample of the injected clock. SV-CLOCK contract:
        a function that reasons about runnability or backoff deadlines
        calls this at most once and threads the value through."""
        return self.clock.now()

    # -- submit ------------------------------------------------------------
    def submit(
        self,
        path: Optional[str] = None,
        *,
        text: Optional[str] = None,
        compiled=None,
        resident_key: Optional[str] = None,
        options=None,
        job_id: Optional[str] = None,
        tenant: str = "default",
        priority: int = 0,
        weight: Optional[float] = None,
        chunk: Optional[int] = None,
        checkpoint_path: str = "",
        checkpoint_every: int = 0,
        preview_every: int = 0,
        preview_path: str = "",
        outfile: str = "",
        trace_id: Optional[str] = None,
    ) -> str:
        """Submit a render: a .pbrt file `path`, inline scene `text`, or
        a precompiled (scene, integrator) pair. Returns the job id.
        Scene compilation happens HERE (once per resident key — a warm
        key is a cache hit); no rendering happens until `step`.

        `trace_id` is the caller-supplied trace context (the fleet
        router's hop): when set, the job's spans carry that id but the
        ROOT async span is owned by the caller — this service neither
        opens nor closes it, so a failover re-submit on another replica
        continues the same request timeline without a duplicate root.

        Raises ShedError WITHOUT compiling or queuing anything when the
        SLO admission policy says the request's priority class is
        already over its queue-depth or queue-wait target — shedding
        after the compile would spend the exact resources shedding
        exists to protect. A draining service (begin_drain()) sheds
        every submit the same way: nothing is compiled or queued."""
        from tpu_pbrt.obs.trace import TRACE

        if self.draining:
            self._shed(tenant, int(priority),
                       "draining: service is handing off its spool")
        if self.slo.enabled():
            self._admit_or_shed(tenant, int(priority))
        if options is None:
            from tpu_pbrt.scene.api import Options

            options = Options(quiet=self.quiet)
        opt_extra = (
            getattr(options, "crop_window", None),
            getattr(options, "quick_render", False),
            getattr(options, "image_file", ""),
        )
        if compiled is not None:
            scene_obj = compiled[0]
            key = resident_key or f"obj:{id(scene_obj):x}"
            builder = lambda: compiled  # noqa: E731
        elif path is not None:
            key = resident_key or scene_source_key(path=path, extra=opt_extra)

            def builder():
                from tpu_pbrt.scene.api import compile_file

                return compile_file(path, options)

        elif text is not None:
            key = resident_key or scene_source_key(text=text, extra=opt_extra)

            def builder():
                from tpu_pbrt.scene.api import compile_string

                return compile_string(text, options)

        else:
            raise ValueError("submit needs a path, text, or compiled pair")

        with TRACE.span("serve/submit", key=key):
            ent = self.residency.get_or_compile(key, builder)
        from tpu_pbrt.integrators.common import WavefrontIntegrator

        if type(ent.integrator).render is not WavefrontIntegrator.render:
            # SPPM/MLT own their render loops (camera/photon passes,
            # bootstrap chains) — they have no chunk-plan seam yet, so a
            # sliced submit would trace li() that does not exist. Refuse
            # at submit time with a clear error instead of failing the
            # first dispatch.
            name = getattr(ent.integrator, "name", type(ent.integrator).__name__)
            raise ValueError(
                f"integrator {name!r} overrides the chunked render loop "
                "and cannot be served slice-wise; render it with the "
                "batch CLI"
            )
        self.residency.pin(key)

        self._seq += 1
        if job_id is None:
            job_id = f"j{self._seq}"
        if job_id in self.jobs:
            self.residency.unpin(key)
            raise ValueError(f"job id {job_id!r} already exists")
        spool_ckpt = not checkpoint_path
        if spool_ckpt:
            checkpoint_path = os.path.join(
                self.spool_dir, f"{job_id}.ckpt.npz"
            )
        job = RenderJob(
            job_id=job_id, tenant=tenant, priority=int(priority),
            seq=self._seq, resident_key=key,
            chunk=chunk if chunk is not None else self.chunk,
            checkpoint_path=checkpoint_path, spool_ckpt=spool_ckpt,
            checkpoint_every=int(checkpoint_every),
            preview_every=int(preview_every), preview_path=preview_path,
            outfile=outfile,
        )
        if weight is not None:
            self.scheduler.set_weight(tenant, weight)
        # start-time fairness: a tenant returning from idle re-enters at
        # the busy tenants' vtime floor instead of spending banked credit
        self.scheduler.reenter(
            tenant,
            busy_tenants={
                j.tenant for j in self.jobs.values()
                if j.status in _RUNNABLE
            },
        )
        job.ready_t = self._now()
        self.jobs[job_id] = job
        # tpu-scope: the job's trace context. With no caller-supplied
        # id the root async span opens here and closes at the terminal
        # outcome; a router-minted id means the root pair lives at the
        # router and every span here just carries the id in its args
        job.trace_owned = trace_id is None
        job.trace_id = trace_id if trace_id else TRACE.trace_id(job_id)
        if job.trace_owned:
            TRACE.async_begin(
                "serve/job", id=job.trace_id, cat="job", job=job_id,
                tenant=tenant, priority=job.priority,
                trace_id=job.trace_id,
            )
        self._trace_ready(job)
        METRICS.counter(
            "serve_submits_total", "jobs admitted by submit"
        ).inc(tenant=tenant)
        self._update_depth_gauge()
        self._flight(job, "serve_submit", key=key, tenant=tenant,
                     priority=job.priority)
        return job_id

    def _admit_or_shed(self, tenant: str, priority: int) -> None:
        """The SLO admission decision — a pure function of the current
        job table (class queue depth) and the registry's observed
        queue-wait p90 for the class. Breach -> counted + flight-logged
        ShedError; the request never touches the compiler or the
        queue."""
        depth = sum(
            1 for j in self.jobs.values()
            if j.status in _RUNNABLE and j.priority == priority
        )
        # the wait signal is consulted only while the class actually has
        # queued work: with an empty queue the recorded waits are stale
        # congestion, and admitting is what produces the fresh samples
        # that let the signal recover (no-lockout property, pinned by
        # tests/test_serve.py)
        wait_p90 = None
        if depth > 0 and self.slo.wait_target(priority) is not None:
            wait_p90 = _window_p90(self._recent_waits.get(priority))
        ok, reason = self.slo.admit(priority, depth, wait_p90)
        if ok:
            return
        self._shed(tenant, priority, reason)

    def _shed(self, tenant: str, priority: int, reason: str) -> None:
        """Count + flight-log + raise one shed answer (SLO admission
        breaches and the drain handoff share the same refusal path)."""
        self.sheds += 1
        METRICS.counter(
            "serve_shed_total",
            "submits answered with a shed by SLO admission control",
        ).inc(tenant=tenant, priority=priority)
        from tpu_pbrt.obs.flight import FLIGHT
        from tpu_pbrt.obs.trace import TRACE

        # a shed request never gets a job id, but its refusal is part of
        # the service timeline: a zero-length pseudo-trace records who
        # was turned away and why
        shed_tid = TRACE.trace_id(f"shed{self.sheds}")
        TRACE.async_begin(
            "serve/job", id=shed_tid, cat="job", outcome="shed",
            tenant=tenant, priority=priority, reason=reason,
            trace_id=shed_tid,
        )
        TRACE.async_end("serve/job", id=shed_tid, cat="job", outcome="shed")
        FLIGHT.heartbeat(
            "serve_shed", tenant=tenant, priority=priority, reason=reason,
            trace_id=shed_tid,
        )
        raise ShedError(
            f"submit shed: {reason}", tenant=tenant, priority=priority,
            reason=reason,
        )

    def _update_depth_gauge(self) -> None:
        """Per-priority-class runnable-job depth — the gauge a monitor
        alarms on before the shed counter starts climbing."""
        if not METRICS.enabled:
            return
        g = METRICS.gauge(
            "serve_queue_depth",
            "runnable jobs per priority class (labels: priority)",
        )
        depths: Dict[int, int] = {}
        for j in self.jobs.values():
            if j.status in _RUNNABLE:
                depths[j.priority] = depths.get(j.priority, 0) + 1
        seen = {ls.get("priority") for ls in g.labelsets()}
        for prio, n in depths.items():
            g.set(n, priority=prio)
        for prio in seen - {str(p) for p in depths}:
            if prio is not None:
                g.set(0, priority=prio)

    # -- the scheduler step -------------------------------------------------
    def _runnable(self, now: Optional[float] = None) -> List[RenderJob]:
        """Runnable jobs as of `now`. Callers that also reason about
        backoff windows (step's min-not_before wait) MUST pass the same
        `now` they use there: sampling the clock twice lets a job fall
        between the samples — excluded from the runnable set yet also
        past its not_before — and step() would return None with work
        still pending (nondeterministic under test clocks)."""
        active = [j for j in self.jobs.values() if j.state is not None]
        out = []
        if now is None:
            now = self._now()
        for j in self.jobs.values():
            if j.status not in _RUNNABLE:
                continue
            if j.not_before > now:
                continue  # inside its re-dispatch backoff window
            if j.state is None and self.max_active is not None and len(
                active
            ) >= self.max_active:
                # activating this job needs a film-state slot: runnable
                # only if it outranks someone it could preempt
                if preemption_victim(active, j) is None:
                    continue
            out.append(j)
        return out

    def step(self) -> Optional[str]:
        """Dispatch ONE chunk-slice of the policy-selected job. Returns
        that job's id, or None when nothing is schedulable (all jobs
        terminal, paused, or blocked on residency)."""
        # `now` is sampled ONCE per step: the runnable filter and the
        # backoff-wait computation below must see the SAME clock, or a
        # job whose not_before falls between two samples is excluded
        # from both — step() would answer None with work still pending
        self.health_steps += 1
        now = self._now()
        job = self.scheduler.pick(self._runnable(now))
        if job is None:
            job = self._await_backoff(now)
            if job is None:
                return None
        return self._step_job(job)

    def _await_backoff(self, now: float) -> Optional[RenderJob]:
        """Nothing was dispatchable at `now` — but a job whose backoff
        window is still open is WORK, not idleness: wait out the
        earliest deadline so drain() doesn't return with jobs
        unfinished. `now` is step's single decision sample; the one
        fresh sample after the sleep is this function's own (SV-CLOCK:
        one per deadline-reasoning scope)."""
        waiting = [
            j.not_before for j in self.jobs.values()
            if j.status in _RUNNABLE and j.not_before > now
        ]
        if not waiting:
            return None
        self.clock.sleep(max(min(waiting) - now, 0.0))
        return self.scheduler.pick(self._runnable(self._now()))

    def _release_device(self, job: RenderJob) -> None:
        """Drop EVERY device reference a job holds: the film carry, the
        in-flight window's un-donated slices, and the per-slice counter
        scalars. The one release point the terminal paths (cancel, fail,
        give-up, finalize) all call — hbmcheck's HC-LEAK rule checks
        statically that no terminal-status write ships without it, and
        protocheck's PROTO-HBM watches the live watermark return to
        baseline. Leaves `plan` to the caller: a parked job keeps its
        plan for resume; a terminal one must also null it (the jit
        closures pin scene HBM past LRU eviction)."""
        if job.window is not None:
            job.window.flush(discard=True)  # closes in-flight spans
            job.window = None
        job.state = None
        job.ray_counts.clear()
        job.occ_counts.clear()
        job.ctr_counts.clear()
        job.nf_counts.clear()

    def _step_job(self, job: RenderJob) -> str:
        """Run the selected job's slice: activation, dispatch with the
        recovery ladder, prefetch overlap, and the job-level failure
        firewall. Split from step() so the selection logic above stays
        a pure clock/deadline function (the piece protocheck's mutation
        corpus perturbs) while this body owns the side effects."""
        try:
            self._activate(job)
            self._dispatch_slice(job)
            if cfg.serve_prefetch:
                # dispatch lookahead (ISSUE 13): the slice just launched
                # is in flight — use its device time to pre-activate the
                # NEXT scheduled job (plan build + checkpoint film load
                # host->HBM + residency LRU touch) so the following
                # step's dispatch is not serialized behind activation
                self._prefetch_next(job)
        except Exception as e:  # noqa: BLE001
            # an unexpected error (trace failure, OOM, corrupt resume)
            # fails THE JOB, not the service — other tenants keep
            # rendering. The dispatch-level recovery ladder inside
            # _dispatch_slice already handled the expected failures.
            if job.status not in _TERMINAL:
                job.status = FAILED
                job.error = job.error or f"{type(e).__name__}: {e}"
            self._release_device(job)
            job.plan = None
            self.residency.unpin(job.resident_key)
            self._update_depth_gauge()
            self._trace_job_end(job, "failed")
            self._flight(job, "serve_failed", error=str(job.error)[:200])
        return job.job_id

    def _prefetch_next(self, current: RenderJob) -> None:
        """Pre-activate the job the policy would schedule next, under
        the device compute of `current`'s in-flight slice: build its
        ChunkPlan (the residency lookup inside _activate also touches
        the scene's LRU slot) and load its film state host->HBM from
        its checkpoint. Pure overlap: it only runs when a film-state
        slot is free (a prefetch must never preempt), and it never
        perturbs the schedule — the peek is re-made, unchanged, by the
        next step. Self-contained error handling: a broken prefetch
        fails THAT job, never the one that just dispatched."""
        cand = [
            j for j in self._runnable()
            if j is not current and j.state is None
        ]
        nxt = self.scheduler.peek(cand)
        if nxt is None:
            return
        if self.max_active is not None:
            active = [j for j in self.jobs.values() if j.state is not None]
            if len(active) >= self.max_active:
                return
        from tpu_pbrt.obs.trace import TRACE

        try:
            with TRACE.span(
                "serve/prefetch", job=nxt.job_id, trace_id=nxt.trace_id,
            ):
                self._activate(nxt)
            METRICS.counter(
                "serve_prefetches_total",
                "next-job activations overlapped under in-flight dispatch",
            ).inc(tenant=nxt.tenant)
            self._flight(nxt, "serve_prefetch", chunk=nxt.cursor)
        except Exception as e:  # noqa: BLE001 — a broken prefetch fails
            # the prefetched job exactly like its own step() would have
            if nxt.status not in _TERMINAL:
                nxt.status = FAILED
                nxt.error = f"{type(e).__name__}: {e}"
            self._release_device(nxt)
            nxt.plan = None
            self.residency.unpin(nxt.resident_key)
            self._update_depth_gauge()
            self._trace_job_end(nxt, "failed")
            self._flight(nxt, "serve_failed", error=str(nxt.error)[:200])

    def drain(self, max_steps: int = 1_000_000) -> None:
        """Step until no job is schedulable (paused jobs stay parked)."""
        for _ in range(max_steps):
            if self.step() is None:
                return
        raise RuntimeError("drain exceeded max_steps — scheduler wedged?")

    def idle(self) -> bool:
        return all(
            j.status in _TERMINAL or j.status == PAUSED
            for j in self.jobs.values()
        )

    # -- lifecycle verbs -----------------------------------------------------
    def preempt(self, job_id: str) -> None:
        """Explicit wave-granular preemption: emergency-checkpoint the
        job's tuple (PR 5's durable write path), free its film state,
        and PARK it until resume(). A job between slices loses nothing
        — the checkpoint is the exact (state, cursor, rays, counters)
        the next activation reloads."""
        from tpu_pbrt.obs.trace import TRACE

        job = self._job(job_id)
        if job.status in _TERMINAL:
            raise ValueError(f"job {job_id} is {job.status}")
        if job.state is not None:
            self._park(job)
        job.status = PAUSED
        # a paused job is not waiting for the scheduler: close the open
        # queue-wait episode (resume opens a fresh one)
        self._trace_wait_end(job)
        TRACE.instant(
            "serve/preempt", job=job.job_id, chunk=job.cursor,
            trace_id=job.trace_id,
        )
        self._update_depth_gauge()  # PAUSED is not runnable
        self._flight(job, "serve_preempt", chunk=job.cursor)

    def resume(self, job_id: str) -> None:
        job = self._job(job_id)
        if job.status != PAUSED:
            raise ValueError(f"job {job_id} is {job.status}, not paused")
        job.status = PARKED if job.cursor else QUEUED
        job.ready_t = self._now()
        self._trace_ready(job)
        METRICS.counter(
            "serve_resumes_total", "paused jobs resumed"
        ).inc(tenant=job.tenant)
        self._update_depth_gauge()
        self._flight(job, "serve_resume", chunk=job.cursor)

    def begin_drain(self) -> Dict[str, Any]:
        """Quiesce for handoff (the daemon's `drain` verb and the fleet
        router's graceful-failover primitive): stop admitting — every
        later submit is answered with a deterministic shed — and park
        every runnable job through the emergency-checkpoint path, so
        each one's durable spool entry holds the exact resumable tuple
        another replica can adopt. Returns the spool manifest:
        quiescent means every job is terminal or parked with its
        checkpoint state reported (the "spool quiescent" signal the
        verb's caller polls for). Idempotent."""
        self.draining = True
        parked: List[str] = []
        for j in list(self.jobs.values()):
            if j.status in _RUNNABLE:
                self.preempt(j.job_id)
                parked.append(j.job_id)
        spool: Dict[str, Any] = {}
        for j in self.jobs.values():
            if j.status == PAUSED:
                spool[j.job_id] = {
                    "checkpoint": j.checkpoint_path,
                    "cursor": j.cursor,
                    "durable": checkpoint_exists(j.checkpoint_path),
                }
        return {
            "draining": True,
            "quiescent": self.idle(),
            "parked": parked,
            "spool": spool,
        }

    def cancel(self, job_id: str) -> None:
        """Terminal cancel: frees the film state, releases the residency
        pin (an unpinned scene is evictable), and removes the
        service-managed checkpoint spool."""
        job = self._job(job_id)
        if job.status in _TERMINAL:
            return
        job.status = CANCELLED
        self._release_device(job)
        job.plan = None
        self.residency.unpin(job.resident_key)
        self.residency.evict_over_budget()
        if job.spool_ckpt:
            delete_checkpoint(job.checkpoint_path)
        self._update_depth_gauge()
        self._trace_job_end(job, "cancelled")
        self._flight(job, "serve_cancel", chunk=job.cursor)

    def poll(self, job_id: str) -> Dict[str, Any]:
        job = self._job(job_id)
        out = {
            "job": job.job_id,
            "status": job.status,
            "tenant": job.tenant,
            "priority": job.priority,
            "progress": round(job.progress(), 6),
            "chunks_done": job.cursor,
            "chunks_total": (
                job.plan.n_chunks if job.plan
                else (job.chunks_total or None)
            ),
            "scene": job.resident_key,
            "preemptions": job.preemptions,
            "redispatches": job.redispatches,
            "previews": job.previews,
        }
        if job.error:
            out["error"] = job.error
        return out

    def result(self, job_id: str) -> RenderResult:
        job = self._job(job_id)
        if job.status != DONE or job.result is None:
            raise ValueError(
                f"job {job_id} has no result (status {job.status}"
                + (f": {job.error}" if job.error else "") + ")"
            )
        return job.result

    def preview(self, job_id: str) -> np.ndarray:
        """Develop the job's LIVE film state to an image right now (the
        streaming-preview primitive; the cadence path calls this too)."""
        job = self._job(job_id)
        if job.result is not None:
            return job.result.image
        plan, state = job.plan, job.state
        if plan is None or state is None:
            raise ValueError(f"job {job_id} has no live film state")
        frac = max(job.progress(), 1e-9)
        return plan.film.develop(state, splat_scale=1.0 / (plan.spp * frac))

    def stats(self) -> Dict[str, Any]:
        return {
            "jobs": {j.job_id: self.poll(j.job_id) for j in self.jobs.values()},
            "residency": self.residency.stats(),
            "tenants": self.scheduler.stats(),
            "schedule_len": len(self.schedule),
            "sheds": self.sheds,
        }

    def metrics_exposition(self) -> str:
        """The registry's Prometheus text page — what the daemon's
        `metrics` verb and `--metrics-path` snapshots serve. Empty when
        TPU_PBRT_METRICS=0 (the kill switch leaves responses with
        nothing to report, not stale data)."""
        return METRICS.exposition() if METRICS.enabled else ""

    # -- internals -----------------------------------------------------------
    def _job(self, job_id: str) -> RenderJob:
        job = self.jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return job

    def _flight(self, job: RenderJob, phase: str, **fields) -> None:
        """Heartbeat into the job's PER-JOB flight file (the recorder's
        first-class `job_heartbeat` seam — concurrent jobs never
        interleave into one stream, and the per-job file sits behind the
        same TPU_PBRT_FLIGHT_MAX_MB rotation cap as the main one). Every
        line carries the job's trace id: the join key from a flight
        post-mortem back into the trace timeline."""
        from tpu_pbrt.obs.flight import FLIGHT

        FLIGHT.job_heartbeat(
            job.job_id, phase, job=job.job_id, trace_id=job.trace_id,
            **fields,
        )

    # -- tpu-scope span threading -------------------------------------------
    def _trace_ready(self, job: RenderJob) -> None:
        """Open a queue-wait async span: the job just became
        dispatchable (submit, slice completion, resume, recovery) and
        waits for the scheduler to pick it again. One span per episode,
        id "<trace_id>/q<epoch>" — closed by the next dispatch."""
        from tpu_pbrt.obs.trace import TRACE

        if job.trace_done or job.wait_open or not job.trace_id:
            return
        job.wait_epoch += 1
        job.wait_open = True
        TRACE.async_begin(
            "serve/queue_wait", id=f"{job.trace_id}/q{job.wait_epoch}",
            cat="queue", job=job.job_id, chunk=job.cursor,
            trace_id=job.trace_id,
        )

    def _trace_wait_end(self, job: RenderJob, wait=None) -> None:
        from tpu_pbrt.obs.trace import TRACE

        if not job.wait_open:
            return
        job.wait_open = False
        kw = {} if wait is None else {"wait_s": round(wait, 6)}
        TRACE.async_end(
            "serve/queue_wait", id=f"{job.trace_id}/q{job.wait_epoch}",
            cat="queue", **kw,
        )

    def _trace_job_end(self, job: RenderJob, outcome: str) -> None:
        """Close the job's root async span with its terminal outcome
        (done/failed/cancelled) — idempotent, and closes any queue-wait
        episode still open so the trace's pairing invariant holds on
        every terminal path."""
        from tpu_pbrt.obs.trace import TRACE

        if job.trace_done or not job.trace_id:
            return
        job.trace_done = True
        self._trace_wait_end(job)
        if not job.trace_owned:
            # router-supplied context: the caller owns the root pair —
            # it closes the span once the JOB (not this instance of it)
            # reaches its fleet-wide terminal outcome
            return
        TRACE.async_end(
            "serve/job", id=job.trace_id, cat="job", outcome=outcome,
            chunks=job.cursor,
        )

    def _report_nonfinite(self, job: RenderJob, snap: Dict[str, Any]) -> None:
        """Fold the job's firewall scrub count into the registry at its
        drain boundaries (park/finalize — the places the device count is
        already fetched), as a DELTA so repeated parks never
        double-count. The watchdog's nonfinite-spike condition reads
        this counter."""
        total = int(snap.get("nonfinite_deposits", 0) or 0)
        delta = total - job.nf_reported
        if delta > 0:
            METRICS.counter(
                "render_nonfinite_total",
                "non-finite radiance deposits scrubbed by the firewall",
            ).inc(delta, tenant=job.tenant)
            job.nf_reported = total

    def _activate(self, job: RenderJob) -> None:
        """Make the job dispatchable: build (or re-use) its ChunkPlan,
        then load its film state — fresh, or from its checkpoint when a
        preemption parked it. Evicts/preempts per policy first."""
        if job.state is not None:
            job.status = ACTIVE
            return
        if self.max_active is not None:
            active = [j for j in self.jobs.values() if j.state is not None]
            while len(active) >= self.max_active:
                victim = preemption_victim(active, job)
                if victim is None:
                    break
                self._park(victim)
                victim.status = PARKED
                active = [
                    j for j in self.jobs.values() if j.state is not None
                ]
        ent = self.residency.get(job.resident_key)
        if ent is None:  # evicted while queued (unpinned by a bug) —
            raise RuntimeError(
                f"resident scene for job {job.job_id} was evicted while "
                "the job still held a pin"
            )
        if job.plan is None:
            job.plan = ent.integrator.prepare_chunks(
                ent.scene, self.mesh, chunk=job.chunk
            )
            ent.fingerprints.add(job.plan.fingerprint)
            job.plan.capacity_audit()
        job.chunks_total = job.plan.n_chunks
        if checkpoint_exists(job.checkpoint_path):
            state, cursor, rays, ctr = load_checkpoint(
                job.checkpoint_path, job.plan.fingerprint
            )
            job.state, job.cursor, job.prev_rays, job.prev_ctr = (
                state, cursor, rays, ctr
            )
            job.ray_counts.clear()
            job.occ_counts.clear()
            job.ctr_counts.clear()
            job.nf_counts.clear()
        else:
            job.state = job.plan.film.init_state()
        job.status = ACTIVE

    def _park(self, job: RenderJob) -> None:
        """Emergency-checkpoint the tuple and drop the film state (the
        preemption write — PR 5's durable path: CRC + fsync + .prev)."""
        from tpu_pbrt.obs.trace import TRACE

        if job.window is not None:
            # drop still-deferred cadence writes: the park write below
            # supersedes them at the SAME path with a newer cursor, so
            # draining them here would pay redundant npz+CRC+fsync per
            # preemption. The in-flight slices need no explicit sync —
            # save_checkpoint's host fetch of the newest state blocks
            # on them (and surfaces any latent async failure). Their
            # deposits ARE in the saved cursor's coverage, so their
            # spans close ok (the causal timeline has no gap here)
            job.window.close_spans(ok=True)
            job.window.flush(discard=True)
            job.window = None
        with TRACE.span(
            "serve/park", job=job.job_id, chunk=job.cursor,
            trace_id=job.trace_id,
        ):
            save_checkpoint(
                job.checkpoint_path, job.state, job.cursor,
                job.rays_so_far(), fingerprint=job.plan.fingerprint,
                counters=job.snapshot_counters(),
            )
        job.prev_rays = job.rays_so_far()
        job.prev_ctr = job.snapshot_counters()
        job.baked_redispatches = job.redispatches
        self._report_nonfinite(job, job.prev_ctr)
        job.ray_counts.clear()
        job.occ_counts.clear()
        job.ctr_counts.clear()
        job.nf_counts.clear()
        job.state = None
        job.preemptions += 1
        METRICS.counter(
            "serve_preemptions_total",
            "jobs parked via the emergency-checkpoint path",
        ).inc(tenant=job.tenant)
        self._flight(job, "serve_park", chunk=job.cursor)

    def _queue_checkpoint(self, job: RenderJob) -> None:
        """Cadence checkpoint for a job. With slices in flight the
        durable write is deferred to the slice's retirement, so the npz
        compression + CRC + fsync run under in-flight compute; the
        carry is never donated at depth > 1 (plan.pipeline_depth
        compiled donation out), so the deferred write holds the live
        accumulator reference directly and starts its device->host
        copy early. With an empty window, write immediately (the exact
        pre-pipeline path)."""
        from tpu_pbrt.obs.trace import TRACE
        from tpu_pbrt.parallel.checkpoint import begin_host_copy

        plan = job.plan
        cursor = job.cursor
        if job.window is None or not len(job.window):
            with TRACE.span(
                "serve/checkpoint_write", job=job.job_id, chunk=cursor,
                trace_id=job.trace_id, deferred=False,
            ):
                save_checkpoint(
                    job.checkpoint_path, job.state, cursor,
                    job.rays_so_far(), fingerprint=plan.fingerprint,
                    counters=job.snapshot_counters(),
                )
            return
        snap = job.state
        begin_host_copy(snap)
        n_ray = len(job.ray_counts)
        n_ctr = len(job.ctr_counts)
        n_nf = len(job.nf_counts)

        def write():
            # the deferred durable write runs at its cursor's retirement
            # — under newer slices' compute — but belongs to THIS job's
            # trace, which the span args record
            with TRACE.span(
                "serve/checkpoint_write", job=job.job_id, chunk=cursor,
                trace_id=job.trace_id, deferred=True,
            ):
                save_checkpoint(
                    job.checkpoint_path, snap, cursor,
                    job.prev_rays + sum(
                        int(r)
                        for r in jax.device_get(job.ray_counts[:n_ray])
                    ),
                    fingerprint=plan.fingerprint,
                    counters=job.snapshot_counters(n_ctr, n_nf),
                )

        job.window.defer(cursor, write)

    def _dispatch_slice(self, job: RenderJob) -> None:
        """One chunk-slice with the recovery ladder (capped-backoff
        re-dispatch; poisoning failures roll back to the job's last
        checkpoint or restart the job). Pipelined (ISSUE 13): the
        dispatch is an async enqueue into the job's in-flight window —
        the bookkeeping below, the next step's scheduling decision and
        the next-job prefetch all run under its device compute; the
        window's oldest slice is retired (one bounded sync) only when
        the window is full."""
        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.obs.trace import TRACE

        plan = job.plan
        c = job.cursor
        t0 = self._now()
        if job.window is None:
            tracer = plan.tracer

            def on_wait(dt, _tracer=tracer):
                if METRICS.enabled:
                    phase_histogram().observe(
                        dt, phase="device_wait", tracer=_tracer
                    )

            # the depth comes from the PLAN: donation is compiled into
            # the chunk closure, and holding job.state for deferred
            # checkpoint writes is only legal at the depth it was
            # built for
            job.window = DispatchWindow(
                plan.pipeline_depth,
                on_wait=on_wait,
                span_name="serve/slice_retire",
                clock=self.clock,
            )
        sid = f"{job.trace_id}/c{c}"
        if job.ready_t:
            # queue wait: became-dispatchable -> this dispatch (includes
            # scheduler contention and any backoff window — the latency
            # the tenant actually observes, which is what the SLO wait
            # target bounds)
            wait = t0 - job.ready_t
            self._trace_wait_end(job, wait)
            _queue_wait_hist().observe(
                wait, tenant=job.tenant, priority=job.priority,
                exemplar={
                    "trace_id": job.trace_id,
                    "span_id": f"{job.trace_id}/q{job.wait_epoch}",
                    "job": job.job_id, "chunk": c,
                },
            )
            win = self._recent_waits.get(job.priority)
            if win is None:
                from collections import deque

                win = self._recent_waits[job.priority] = deque(
                    maxlen=_WAIT_WINDOW
                )
            win.append(wait)
        try:
            CHAOS.dispatch(c, job.attempt, mesh=self.mesh is not None)
            try:
                # a slice launched with older ones still in flight has
                # its host cost hidden under their compute — attributed
                # separately (dispatch_ahead), like the render loop
                with TRACE.span(
                    "serve/slice_ahead" if len(job.window) else "serve/slice",
                    job=job.job_id, chunk=c, trace_id=job.trace_id,
                    span_id=sid,
                ):
                    state, aux = plan.dispatch(job.state, c)
            except jax.errors.JaxRuntimeError as e:
                job.state = None  # the donated accumulator is untrusted
                raise ChunkDispatchError(
                    f"device dispatch failed: {e}", poisons_state=True
                ) from e
            if cfg.nonfinite != "scrub":
                # (resolve_pipeline_depth forces the window to depth 1
                # in the strict modes — this is a per-chunk device sync)
                nrays, occ, ctr, _, nf = plan.aux_parts(aux)
                nf_dev = ctr.nonfinite if ctr is not None else nf
                nf_ct = 0 if nf_dev is None else int(jax.device_get(nf_dev))
                if nf_ct:
                    if cfg.nonfinite == "raise":
                        # only the message here: _step_job's firewall
                        # sets FAILED and releases the device buffers
                        # (HC-LEAK wants status+release in ONE scope)
                        job.error = (
                            f"chunk {c} deposited {nf_ct} non-finite "
                            "sample(s) (TPU_PBRT_NONFINITE=raise)"
                        )
                        raise NonFiniteRadianceError(job.error)
                    job.state = state  # retry: treat as poisoned
                    raise NonFiniteWaveError(
                        f"non-finite firewall: chunk {c} scrubbed "
                        f"{nf_ct} deposit(s)"
                    )
        except ChunkDispatchError as e:
            try:
                job.window.flush(discard=e.poisons_state)
            except ChunkDispatchError as e2:
                e = e2  # the flush itself found a poisoned device
                job.window.flush(discard=True)
                job.state = None
            self._recover(job, e)
            return
        job.attempt = 0
        job.state = state
        job.cursor = c + 1
        self.last_progress_step = self.health_steps
        self.schedule.append((job.job_id, c))
        self.scheduler.charge(job.tenant)
        nrays, occ, ctr, spread, nf = plan.aux_parts(aux)
        job.ray_counts.append(nrays)
        if occ is not None:
            job.occ_counts.append(occ)
        if ctr is not None:
            job.ctr_counts.append(ctr)
        if nf is not None:
            job.nf_counts.append(nf)
        if job.checkpoint_every and job.cursor % job.checkpoint_every == 0:
            self._queue_checkpoint(job)
        # retire the oldest in-flight slice(s) only once the window is
        # full — everything above (and the caller's prefetch + the next
        # step's scheduling) ran under their device compute. The slice's
        # in-flight lifetime (enqueue -> retire sync) is an async span
        # under the job's trace, causally bound by a flow event, so a
        # depth-N window renders as N overlapping attributed tracks
        TRACE.async_begin(
            "serve/slice_inflight", id=sid, cat="slice", job=job.job_id,
            chunk=c, trace_id=job.trace_id, span_id=sid,
        )
        TRACE.flow_start("slice_flow", id=sid)
        job.window.push(c, nrays, span={
            "name": "serve/slice_inflight", "id": sid, "cat": "slice",
            "flow": sid, "trace_id": job.trace_id, "span_id": sid,
        })
        try:
            while job.window.full():
                job.window.retire_one()
        except ChunkDispatchError as e:
            job.state = None  # mid-flight device failure: untrusted
            job.window.flush(discard=True)
            self._recover(job, e)
            return
        # service time closes AFTER the retire: it must cover the
        # bounded device sync (at depth 1 that is the whole chunk
        # compute — the pre-pipeline meaning), not just the async
        # enqueue + bookkeeping
        now = self._now()
        job.active_seconds += now - t0
        _slice_hist().observe(
            now - t0, tenant=job.tenant,
            exemplar={
                "trace_id": job.trace_id, "span_id": sid,
                "job": job.job_id, "chunk": c,
            },
        )
        job.ready_t = now
        if job.cursor < plan.n_chunks:
            self._trace_ready(job)
        if (
            job.preview_every
            and job.preview_path
            and job.cursor % job.preview_every == 0
            and job.cursor < plan.n_chunks
        ):
            self._write_preview(job)
        if job.cursor >= plan.n_chunks:
            self._finalize(job)

    def _recover(self, job: RenderJob, e: ChunkDispatchError) -> None:
        job.window = None  # flushed by the caller; rebuilt lazily
        job.attempt += 1
        job.redispatches += 1
        if job.attempt > int(cfg.retry_max):
            if job.state is not None and not e.poisons_state:
                self._park(job)  # completed work survives the failure
            job.status = FAILED
            job.error = f"chunk {job.cursor} failed {job.attempt} times: {e}"
            self._release_device(job)
            job.plan = None
            self.residency.unpin(job.resident_key)
            self._update_depth_gauge()
            self._trace_job_end(job, "failed")
            self._flight(job, "serve_failed", error=job.error[:200])
            return
        if e.poisons_state:
            job.state = None
            if checkpoint_exists(job.checkpoint_path):
                job.rollbacks += 1
            else:
                # no durable progress: restart this job from chunk 0
                job.cursor = 0
                job.prev_rays = 0
                job.prev_ctr = {}
                job.baked_redispatches = 0
                job.restarts += 1
            job.ray_counts.clear()
            job.occ_counts.clear()
            job.ctr_counts.clear()
            job.nf_counts.clear()
            job.status = PARKED  # re-activation reloads/re-inits state
        backoff = redispatch_backoff(job.cursor, job.attempt)
        METRICS.counter(
            "serve_redispatches_total", "chunk-slice re-dispatches"
        ).inc(tenant=job.tenant)
        METRICS.counter(
            "serve_redispatch_backoff_seconds_total",
            "seconds of re-dispatch backoff accrued",
        ).inc(backoff, tenant=job.tenant)
        # one decision sample covers both the ready time and the backoff
        # deadline (SV-CLOCK: recovery reasons about not_before, so it
        # samples the clock exactly once)
        now = self._now()
        job.ready_t = now
        self._trace_ready(job)
        self._flight(
            job, "serve_redispatch", chunk=job.cursor,
            attempt=job.attempt, poisoned=e.poisons_state,
            backoff_s=round(backoff, 3), error=str(e)[:200],
        )
        # the backoff is a per-job NOT-BEFORE deadline, never a sleep on
        # the scheduler thread: other tenants' healthy jobs keep
        # dispatching through one job's retry streak (step() only waits
        # when EVERY runnable job is inside its backoff window)
        if backoff > 0:
            from tpu_pbrt.obs.trace import TRACE

            # the backoff window's extent is known the moment it opens:
            # an explicit-duration span shows WHY the job's timeline has
            # a hole between this recovery and its next dispatch
            TRACE.complete(
                "serve/backoff", backoff * 1e6, job=job.job_id,
                chunk=job.cursor, attempt=job.attempt,
                trace_id=job.trace_id,
            )
            job.not_before = now + backoff

    def _write_preview(self, job: RenderJob) -> None:
        from tpu_pbrt.obs.trace import TRACE
        from tpu_pbrt.utils import imageio

        t0 = self.clock.monotonic()
        with TRACE.span(
            "serve/preview", job=job.job_id, chunk=job.cursor,
            trace_id=job.trace_id,
        ):
            img = self.preview(job.job_id)
            try:
                imageio.write_image(job.preview_path, img)
                job.previews += 1
            except Exception as ex:  # noqa: BLE001
                from tpu_pbrt.utils.error import Warning as _W

                _W(f"preview write failed for {job.job_id}: {ex}")
        METRICS.histogram(
            "serve_preview_seconds",
            "preview latency: live-film develop + image write",
        ).observe(self.clock.monotonic() - t0, tenant=job.tenant)
        self._flight(job, "serve_preview", chunk=job.cursor)

    def _finalize(self, job: RenderJob) -> None:
        from tpu_pbrt.obs import counters as obs_counters
        from tpu_pbrt.obs.trace import TRACE

        plan = job.plan
        # still-deferred cadence writes are superseded by the terminal
        # state below (spool checkpoints are deleted outright); the
        # block on job.state is the job's full drain either way
        window, job.window = job.window, None
        with TRACE.span(
            "serve/finalize", job=job.job_id, trace_id=job.trace_id,
        ):
            jax.block_until_ready(job.state)
            if window is not None:
                # the block above IS the tail slices' sync: their spans
                # close complete, not aborted — the reconstructed
                # timeline covers every chunk through the final cursor
                window.close_spans(ok=True)
            rays = job.rays_so_far()
            ctr_total = job.snapshot_counters()
            stats: Dict[str, Any] = {
                "job_id": job.job_id,
                "tenant": job.tenant,
                "preemptions": job.preemptions,
            }
            if job.redispatches:
                stats["recovery"] = {
                    "redispatches": job.redispatches,
                    "rollbacks": job.rollbacks,
                    "restarts": job.restarts,
                }
            if plan.use_regen and job.occ_counts:
                occ_host = jax.device_get(job.occ_counts)
                lv = sum(int(a) for a, _, _ in occ_host)
                wv = sum(int(b) for _, b, _ in occ_host)
                tr = sum(int(t) for _, _, t in occ_host)
                if tr:
                    from tpu_pbrt.utils.error import Warning as _W

                    _W(
                        f"job {job.job_id}: pool drain truncated {tr} "
                        "chunk(s) at the max_waves bound — the image is "
                        "missing samples"
                    )
                    stats["truncated_chunks"] = tr
                stats |= {
                    "mean_wave_occupancy": lv / max(wv * plan.pool, 1),
                    "n_waves": wv,
                    "pool": plan.pool,
                    "regen": True,
                }
            if obs_counters.enabled() and ctr_total:
                stats["telemetry"] = {"counters": ctr_total}
            img = plan.film.develop(job.state, splat_scale=1.0 / plan.spp)
            if job.outfile:
                from tpu_pbrt.utils import imageio

                try:
                    imageio.write_image(job.outfile, img)
                except Exception as ex:  # noqa: BLE001
                    from tpu_pbrt.utils.error import Warning as _W

                    _W(f"could not write {job.outfile}: {ex}")
        job.result = RenderResult(
            image=img,
            film_state=job.state,
            seconds=job.active_seconds,
            rays_traced=rays,
            mray_per_sec=rays / max(job.active_seconds, 1e-9) / 1e6,
            spp=plan.spp,
            completed_fraction=1.0,
            stats=stats,
        )
        job.status = DONE
        # the film lives on in result.film_state; everything else —
        # counter scalars, the (already-None) window — drops here, and
        # the plan with it: its jit closures pin scene HBM past eviction
        self._release_device(job)
        job.plan = None
        self._report_nonfinite(job, ctr_total)
        self.residency.unpin(job.resident_key)
        self.residency.evict_over_budget()
        if job.spool_ckpt:
            delete_checkpoint(job.checkpoint_path)
        self._update_depth_gauge()
        self._trace_job_end(job, "done")
        self._flight(job, "serve_done", rays=rays, chunks=job.cursor,
                     seconds=round(job.active_seconds, 3))
