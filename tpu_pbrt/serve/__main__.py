"""`python -m tpu_pbrt.serve` — the render-service frontends.

Default mode: a stdin/JSONL daemon. One JSON object per line in, one
JSON object per line out (responses carry {"ok": ...}; asynchronous job
completions are emitted as {"event": "done"/"failed", ...} lines).

Ops:
  {"op": "submit", "scene": "path.pbrt" | "text": "<inline scene>",
   "job": "id?", "tenant": "t?", "priority": 0, "weight": 1.0,
   "chunk": 0, "checkpoint": "path?", "checkpoint_every": 0,
   "preview_every": 0, "preview": "out.png?", "outfile": "img.exr?",
   "crop": [x0, x1, y0, y1]?, "quick": false}
  {"op": "poll",    "job": "j1"}
  {"op": "preempt", "job": "j1"}      # emergency checkpoint + park
  {"op": "resume",  "job": "j1"}
  {"op": "cancel",  "job": "j1"}      # releases residency
  {"op": "preview", "job": "j1", "out": "live.png"}
  {"op": "result",  "job": "j1", "out": "final.exr?"}
  {"op": "stats"}
  {"op": "metrics", "out": "metrics.prom?"}   # Prometheus text exposition
  {"op": "health"}                    # watchdog verdict (obs/health.py)
  {"op": "drain"}                     # stop admitting; park active jobs;
                                      # reports when the spool is quiescent
  {"op": "shutdown", "drain": true}

A submit may carry {"trace": "t:<id>"} — a caller-supplied trace
context (the fleet router's hop): the job's spans carry that id, but
the root serve/job span is owned by the caller, so a failover
re-submit on another daemon continues one end-to-end timeline.

`drain` (ISSUE 20) is the router's graceful-failover primitive, which
`shutdown` cannot provide: the daemon STAYS UP — answering polls,
stats, results — while every new submit is deterministically shed and
the runnable jobs park through the emergency-checkpoint path. The
response carries {"quiescent": true/false, "parked": [...], "spool":
{job: {checkpoint, cursor, durable}}}; once quiescent, every parked
job's durable spool entry holds the exact resumable tuple another
replica can adopt.

A submit rejected by SLO admission control (TPU_PBRT_SERVE_SLO_DEPTH /
_WAIT_S, or --slo-depth/--slo-wait-s) answers {"ok": false, "shed":
true, "reason": ...} — deterministic, counted in the shed metrics and
the flight log; nothing was compiled or queued.

Between commands the daemon steps the service (one chunk-slice per
step, policy-scheduled), so renders progress while the client is idle.
EOF on stdin drains the remaining jobs and exits.

`--selftest` runs the CI smoke (no stdin): submit two cropped-cornell
jobs on one mesh, preempt/resume one mid-render, and assert both films
are finite AND bit-identical to a solo run-to-completion render, the
warm resubmit paid 0 scene compiles and 0 jit recompiles, and the
preview stream wrote frames. Exit 0 = pass.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_pbrt.serve",
        description="tpu-pbrt multi-tenant render service",
    )
    p.add_argument(
        "--selftest", action="store_true",
        help="run the service smoke (2 cropped cornell jobs, one "
        "preempt/resume, bit-identity vs solo, residency warm-hit) and exit",
    )
    p.add_argument("--mesh", default="", help="device mesh shape, e.g. '4'")
    p.add_argument(
        "--chunk", type=int, default=0,
        help="slice width in camera rays (preemption quantum; 0 = platform default)",
    )
    p.add_argument("--seed", type=int, default=0, help="scheduler seed")
    p.add_argument(
        "--max-resident-mb", type=float, default=0.0,
        help="resident-scene HBM budget in MB (0 = unbounded)",
    )
    p.add_argument(
        "--max-active", type=int, default=0,
        help="max jobs holding live film state (0 = unbounded)",
    )
    p.add_argument("--spool", default="", help="checkpoint spool directory")
    p.add_argument(
        "--slo-depth", default="",
        help="per-priority-class queue-depth SLO spec ('8' or '0=4,5=32'; "
        "overrides TPU_PBRT_SERVE_SLO_DEPTH) — over-target submits shed",
    )
    p.add_argument(
        "--slo-wait-s", default="",
        help="per-class p90 queue-wait SLO spec in seconds (overrides "
        "TPU_PBRT_SERVE_SLO_WAIT_S); evaluated over recent waits while "
        "the class has queued work",
    )
    p.add_argument(
        "--metrics-path", default="",
        help="write the Prometheus metrics snapshot here on shutdown "
        "(also settable via TPU_PBRT_METRICS_PATH)",
    )
    p.add_argument("--quiet", action="store_true")
    return p


def _make_service(args):
    from tpu_pbrt.parallel.mesh import resolve_mesh
    from tpu_pbrt.serve import RenderService, SloPolicy, parse_slo_spec

    mesh_shape = (
        tuple(int(x) for x in args.mesh.split(",")) if args.mesh else None
    )
    slo = None
    if getattr(args, "slo_depth", "") or getattr(args, "slo_wait_s", ""):
        base = SloPolicy.from_cfg()
        slo = SloPolicy(
            depth=parse_slo_spec(args.slo_depth, int) or base.depth,
            wait_s=parse_slo_spec(args.slo_wait_s, float) or base.wait_s,
        )
    if getattr(args, "metrics_path", ""):
        from tpu_pbrt.obs.metrics import METRICS

        METRICS.configure(args.metrics_path)
    return RenderService(
        mesh=resolve_mesh(mesh_shape),
        chunk=args.chunk or None,
        max_resident_bytes=(
            int(args.max_resident_mb * 1e6) if args.max_resident_mb else None
        ),
        max_active=args.max_active or None,
        seed=args.seed,
        spool_dir=args.spool or None,
        quiet=True,
        slo=slo,
    )


# --------------------------------------------------------------------------
# JSONL daemon
# --------------------------------------------------------------------------


def _emit(out, payload):
    out.write(json.dumps(payload) + "\n")
    out.flush()


def _handle(service, req, out):
    from tpu_pbrt.serve import ShedError

    op = req.get("op")
    try:
        if op == "submit":
            from tpu_pbrt.scene.api import Options

            opts = Options(
                quiet=True,
                quick_render=bool(req.get("quick", False)),
                crop_window=(
                    tuple(req["crop"]) if req.get("crop") else None
                ),
                image_file=req.get("outfile", ""),
            )
            try:
                job = service.submit(
                    req.get("scene"),
                    text=req.get("text"),
                    options=opts,
                    job_id=req.get("job"),
                    tenant=req.get("tenant", "default"),
                    priority=int(req.get("priority", 0)),
                    weight=req.get("weight"),
                    chunk=int(req["chunk"]) if req.get("chunk") else None,
                    checkpoint_path=req.get("checkpoint", ""),
                    checkpoint_every=int(req.get("checkpoint_every", 0)),
                    preview_every=int(req.get("preview_every", 0)),
                    preview_path=req.get("preview", ""),
                    outfile=req.get("outfile", ""),
                    trace_id=req.get("trace"),
                )
            except ShedError as e:
                # SLO load shedding: a first-class protocol answer, not
                # an error string — clients branch on "shed" to retry
                # elsewhere/later (nothing was compiled or queued)
                _emit(out, {
                    "ok": False, "op": op, "shed": True,
                    "tenant": e.tenant, "priority": e.priority,
                    "reason": e.reason,
                })
                return None
            _emit(out, {"ok": True, "op": op, "job": job})
        elif op == "poll":
            _emit(out, {"ok": True, "op": op, **service.poll(req["job"])})
        elif op == "preempt":
            service.preempt(req["job"])
            _emit(out, {"ok": True, "op": op, "job": req["job"]})
        elif op == "resume":
            service.resume(req["job"])
            _emit(out, {"ok": True, "op": op, "job": req["job"]})
        elif op == "cancel":
            service.cancel(req["job"])
            _emit(out, {"ok": True, "op": op, "job": req["job"]})
        elif op == "preview":
            img = service.preview(req["job"])
            path = req.get("out", "")
            if path:
                from tpu_pbrt.utils import imageio

                imageio.write_image(path, img)
            _emit(out, {
                "ok": True, "op": op, "job": req["job"],
                "mean": float(img.mean()), "out": path or None,
            })
        elif op == "result":
            r = service.result(req["job"])
            path = req.get("out", "")
            if path:
                from tpu_pbrt.utils import imageio

                imageio.write_image(path, r.image)
            _emit(out, {
                "ok": True, "op": op, "job": req["job"],
                "rays": r.rays_traced,
                "seconds": round(r.seconds, 3),
                "mean": float(r.image.mean()),
                "stats": _json_safe(r.stats), "out": path or None,
            })
        elif op == "stats":
            _emit(out, {"ok": True, "op": op, **_json_safe(service.stats())})
        elif op == "metrics":
            # Prometheus text exposition of the process registry — the
            # scrape endpoint, JSONL-framed. "out" additionally writes
            # the page to a file (the --metrics-path snapshot shape).
            text = service.metrics_exposition()
            path = req.get("out", "")
            written = None
            if path and text:
                from tpu_pbrt.obs.metrics import METRICS

                written = METRICS.export(path)
            # "out" reports what was actually WRITTEN — an empty page
            # (kill switch / nothing recorded) skips the export, and the
            # client must not be told a snapshot file exists
            _emit(out, {
                "ok": True, "op": op, "exposition": text,
                "lines": len(text.splitlines()), "out": written,
            })
        elif op == "health":
            # the watchdog verdict (obs/health.py): deterministic over
            # the service's own state + the metrics registry — what a
            # monitor polls instead of waiting for client timeouts
            from tpu_pbrt.obs.health import evaluate

            _emit(out, {"ok": True, "op": op, **evaluate(service).to_dict()})
        elif op == "drain":
            # graceful handoff: shed new submits, park runnable jobs,
            # report the spool manifest — the daemon keeps serving
            # polls/results so a router can adopt the spool elsewhere
            _emit(out, {"ok": True, "op": op, **service.begin_drain()})
        elif op == "shutdown":
            return "drain" if req.get("drain", True) else "now"
        else:
            _emit(out, {"ok": False, "error": f"unknown op {op!r}"})
    except Exception as e:  # noqa: BLE001 — a bad request must not kill the daemon
        _emit(out, {"ok": False, "op": op, "error": f"{type(e).__name__}: {e}"})
    return None


def _json_safe(obj):
    """Counters and stats may carry numpy scalars; JSON needs ints."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if hasattr(obj, "item"):
        return obj.item()
    return obj


def run_daemon(service, in_stream=None, out=None) -> int:
    import queue as _q
    import threading

    in_stream = in_stream if in_stream is not None else sys.stdin
    out = out if out is not None else sys.stdout
    cmds: "_q.Queue" = _q.Queue()
    eof = threading.Event()

    def reader():
        for line in in_stream:
            cmds.put(line)
        eof.set()

    threading.Thread(target=reader, daemon=True).start()

    done_emitted = set()
    shutdown = None

    def process_line(raw):
        raw = raw.strip()
        if not raw:
            return None
        try:
            req = json.loads(raw)
        except ValueError as e:
            _emit(out, {"ok": False, "error": f"bad JSON: {e}"})
            return None
        if not isinstance(req, dict):
            # a bare string/number IS valid JSON — it must still be
            # rejected cleanly, not crash the daemon on req.get
            _emit(out, {"ok": False, "error": "request must be a JSON object"})
            return None
        return _handle(service, req, out)

    while True:
        # drain every pending command first (submits/cancels reshape the
        # very next scheduling decision)
        while shutdown is None:
            try:
                line = cmds.get_nowait()
            except _q.Empty:
                break
            shutdown = process_line(line)
        if shutdown == "now":
            break
        try:
            worked = service.step()
        except Exception as e:  # noqa: BLE001 — one job's crash must not kill the daemon
            _emit(out, {
                "event": "error", "error": f"{type(e).__name__}: {e}",
            })
            worked = None
        for job in service.jobs.values():
            if job.status in ("done", "failed") and job.job_id not in done_emitted:
                done_emitted.add(job.job_id)
                ev = {"event": job.status, "job": job.job_id}
                if job.status == "done":
                    r = job.result
                    ev.update(rays=r.rays_traced,
                              seconds=round(r.seconds, 3))
                else:
                    ev["error"] = job.error
                _emit(out, ev)
        if worked is None:
            if shutdown == "drain" or eof.is_set():
                break
            # idle: block briefly for the next command and process it
            # IN ORDER (re-queueing would reorder a burst of commands)
            try:
                shutdown = process_line(cmds.get(timeout=0.05))
            except _q.Empty:
                pass
    return 0


# --------------------------------------------------------------------------
# --selftest: the CI smoke
# --------------------------------------------------------------------------


def selftest(args) -> int:
    import os
    import tempfile

    import numpy as np

    from tpu_pbrt.scene.api import Options, compile_string
    from tpu_pbrt.scenes import cornell_box_text

    def say(msg):
        print(f"serve-selftest: {msg}", file=sys.stderr)

    text = cornell_box_text(res=64, spp=1, integrator="path", maxdepth=3)
    crop = (0.0, 0.5, 0.0, 0.5)

    # solo run-to-completion reference (its own compile + integrator —
    # the service must reproduce it bit-for-bit through sliced,
    # interleaved, preempted scheduling)
    say("rendering solo reference")
    scene, integ = compile_string(text, Options(quiet=True, crop_window=crop))
    ref = np.asarray(integ.render(scene).image, np.float32)

    args.chunk = args.chunk or 256
    service = _make_service(args)
    tmp = tempfile.mkdtemp(prefix="tpu_pbrt_selftest_")
    preview_path = os.path.join(tmp, "preview.pfm")
    opts = Options(quiet=True, crop_window=crop)
    j1 = service.submit(text=text, options=opts, tenant="alice",
                        preview_every=2, preview_path=preview_path)
    j2 = service.submit(text=text, options=opts, tenant="bob")
    say(f"submitted {j1} + {j2} (chunk={args.chunk})")

    fails = []
    res_stats = service.residency.stats()
    if res_stats["scene_compiles"] != 1:
        fails.append(
            f"expected 1 scene compile for 2 same-scene submits, got "
            f"{res_stats['scene_compiles']}"
        )

    # interleave a few slices, then preempt j2 mid-render
    for _ in range(3):
        service.step()
    p2 = service.poll(j2)
    service.preempt(j2)
    say(f"preempted {j2} at chunk {service.poll(j2)['chunks_done']}")
    if not (0 < p2["chunks_done"]):
        fails.append(f"{j2} had no progress before preempt: {p2}")
    for _ in range(2):
        service.step()
    service.resume(j2)
    service.drain()

    for j in (j1, j2):
        r = service.result(j)
        img = np.asarray(r.image, np.float32)
        if not np.isfinite(img).all():
            fails.append(f"{j}: non-finite pixels")
        if img.shape != ref.shape or not np.array_equal(img, ref):
            diff = (
                float(np.max(np.abs(img - ref)))
                if img.shape == ref.shape else "shape"
            )
            fails.append(f"{j}: film differs from solo (max diff {diff})")
    if service.poll(j2)["preemptions"] < 1:
        fails.append(f"{j2} records no preemption")
    if service.poll(j1)["previews"] < 1 or not os.path.exists(preview_path):
        fails.append("preview stream wrote no frames")

    # warm resubmit: same scene again — zero scene compiles, zero jit
    # recompiles (the _cache_size audit, PR 2)
    ent = service.residency.get(
        service.jobs[j1].resident_key
    )
    jfn = ent.integrator._jit_cache[1]
    size_before = jfn._cache_size()
    j3 = service.submit(text=text, options=opts, tenant="alice")
    service.drain()
    res_stats = service.residency.stats()
    if res_stats["scene_compiles"] != 1:
        fails.append(
            f"warm resubmit recompiled the scene "
            f"({res_stats['scene_compiles']} compiles)"
        )
    jfn2 = ent.integrator._jit_cache[1]
    if jfn2 is not jfn or jfn2._cache_size() != size_before:
        fails.append(
            f"warm resubmit retraced the chunk closure "
            f"({size_before} -> {jfn2._cache_size()})"
        )
    img3 = np.asarray(service.result(j3).image, np.float32)
    if not np.array_equal(img3, ref):
        fails.append("warm resubmit film differs from solo")

    # cancel releases residency: a fresh job's pin, cancelled, unpins
    j4 = service.submit(text=text, options=opts)
    service.cancel(j4)
    if service.residency.get(service.jobs[j4].resident_key).pins != 0:
        fails.append("cancel left the residency pin held")

    # SLO load shedding (ISSUE 10): with a class queue-depth target of 1,
    # an over-SLO submit burst is answered with deterministic sheds —
    # counted, before any compile or queue mutation. After the admitted
    # job leaves the queue, admission opens again.
    from tpu_pbrt.serve import ShedError, SloPolicy, parse_slo_spec

    say("slo shed burst (depth target 1)")
    service.slo = SloPolicy(depth=parse_slo_spec("1", int))
    burst_ok, burst_shed = [], 0
    for _ in range(4):
        try:
            burst_ok.append(
                service.submit(text=text, options=opts, tenant="burst")
            )
        except ShedError:
            burst_shed += 1
    if len(burst_ok) != 1 or burst_shed != 3 or service.sheds != 3:
        fails.append(
            f"shed burst not deterministic: {len(burst_ok)} admitted, "
            f"{burst_shed} shed (counted {service.sheds})"
        )
    service.cancel(burst_ok[0])
    try:
        service.cancel(service.submit(text=text, options=opts,
                                      tenant="burst"))
    except ShedError:
        fails.append("submit still shed after the queue drained")
    service.slo = SloPolicy()

    # drain verb (ISSUE 20): the fleet router's graceful-failover
    # primitive — the service stops admitting, parks its runnable jobs
    # through the emergency-checkpoint path, and reports the spool
    # manifest another replica could adopt; the daemon stays up
    import io

    say("drain handoff (park + shed + spool manifest)")
    j5 = service.submit(text=text, options=opts, tenant="alice",
                        checkpoint_every=1)
    service.step()
    buf = io.StringIO()
    _handle(service, {"op": "drain"}, buf)
    ans = json.loads(buf.getvalue())
    if not (ans.get("ok") and ans.get("draining")):
        fails.append(f"drain verb answered {ans}")
    if j5 not in ans.get("parked", []) or j5 not in ans.get("spool", {}):
        fails.append(f"drain did not park+spool {j5}: {ans}")
    elif not ans["spool"][j5]["durable"]:
        fails.append(f"drain left {j5} without a durable spool entry")
    if not ans.get("quiescent"):
        fails.append(f"drain reports non-quiescent after parking: {ans}")
    try:
        service.submit(text=text, options=opts, tenant="alice")
        fails.append("draining service admitted a submit")
    except ShedError as e:
        if "draining" not in e.reason:
            fails.append(f"draining shed carries wrong reason: {e.reason}")
    buf = io.StringIO()
    _handle(service, {"op": "submit", "text": text}, buf)
    shed_ans = json.loads(buf.getvalue())
    if not shed_ans.get("shed"):
        fails.append(
            f"daemon answered a draining submit without shed: {shed_ans}"
        )
    # the handoff is reversible: lift the drain, resume the parked job
    # from its durable checkpoint, and the film is still bit-identical
    service.draining = False
    service.resume(j5)
    service.drain()
    if not np.array_equal(
        np.asarray(service.result(j5).image, np.float32), ref
    ):
        fails.append("film resumed after a drain differs from solo")

    # metrics exposition (ISSUE 10): the scrape page must lint clean and
    # carry the per-tenant queue-wait/service-time histograms + the shed
    # counter the burst above just incremented
    from tpu_pbrt.obs.metrics import METRICS, validate_exposition

    if METRICS.enabled:
        exp = service.metrics_exposition()
        errs = validate_exposition(exp)
        fails += [f"exposition: {e}" for e in errs]
        for needle in (
            "tpu_pbrt_serve_queue_wait_seconds_bucket",
            "tpu_pbrt_serve_slice_seconds_count",
            'tenant="alice"',
            "tpu_pbrt_serve_shed_total",
            "tpu_pbrt_residency_hits_total",
        ):
            if needle not in exp:
                fails.append(f"exposition missing {needle}")
        # tpu-scope exemplars: the slice histogram's retained tail must
        # carry trace ids — the join key back into the trace timeline
        from tpu_pbrt.config import cfg as _cfg

        if _cfg.metrics_exemplars > 0:
            ser = (
                METRICS.snapshot()["metrics"]
                .get("tpu_pbrt_serve_slice_seconds", {})
                .get("series", [])
            )
            if not any(
                e.get("trace_id")
                for s in ser for e in s.get("exemplars", [])
            ):
                fails.append("slice histogram has no trace-id exemplars")

    # tpu-scope health: a clean selftest must not trip the watchdog
    from tpu_pbrt.obs.health import evaluate

    rep = evaluate(service)
    if not rep.ok:
        fails.append(
            f"health watchdog fired on a clean selftest: {rep.firing()}"
        )

    # when tracing is armed (TPU_PBRT_TRACE_PATH / --trace), export the
    # trace so CI's scope stage can reconstruct the job timelines from
    # this very run
    from tpu_pbrt.obs.trace import TRACE

    traced = TRACE.maybe_export()
    if traced:
        say(f"trace exported to {traced}")

    line = {
        "selftest": "tpu_pbrt.serve",
        "ok": not fails,
        "jobs": len(service.jobs),
        "schedule_len": len(service.schedule),
        "scene_compiles": res_stats["scene_compiles"],
        "residency_hits": res_stats["hits"],
        "preemptions": service.poll(j2)["preemptions"],
        "previews": service.poll(j1)["previews"],
        "sheds": service.sheds,
    }
    if fails:
        line["failures"] = fails
        for f in fails:
            say(f"FAIL: {f}")
    print(json.dumps(line))
    return 0 if not fails else 1


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.selftest:
        return selftest(args)
    try:
        return run_daemon(_make_service(args))
    finally:
        from tpu_pbrt.obs.metrics import METRICS

        # --metrics-path / TPU_PBRT_METRICS_PATH: the final scrape
        # snapshot survives the daemon exiting
        METRICS.maybe_export()


if __name__ == "__main__":
    sys.exit(main())
