"""tpu-serve: multi-tenant render service (ISSUE 6 tentpole).

The serving layer the paper's master/worker fork implies but the batch
CLI reproduction lacked: resident compiled scenes (serve/residency.py),
a priority + weighted-fair queue with deterministic scheduling
(serve/queue.py), and resumable render jobs preempted at wave
granularity through the checkpoint-v4 emergency path
(serve/service.py). Frontends: this library API, the stdin/JSONL
daemon (`python -m tpu_pbrt.serve`, `--selftest` for the CI smoke), and
`tpu-pbrt --serve` in main.py.
"""

from tpu_pbrt.serve.queue import (
    FairScheduler,
    SloPolicy,
    parse_slo_spec,
    preemption_victim,
)
from tpu_pbrt.serve.residency import (
    ResidencyCache,
    ResidentScene,
    scene_hbm_bytes,
    scene_source_key,
)
from tpu_pbrt.serve.service import (
    ACTIVE,
    CANCELLED,
    DONE,
    FAILED,
    PARKED,
    PAUSED,
    QUEUED,
    RenderJob,
    RenderService,
    ShedError,
)

__all__ = [
    "ACTIVE", "CANCELLED", "DONE", "FAILED", "PARKED", "PAUSED", "QUEUED",
    "FairScheduler", "SloPolicy", "parse_slo_spec", "preemption_victim",
    "ResidencyCache", "ResidentScene", "scene_hbm_bytes",
    "scene_source_key",
    "RenderJob", "RenderService", "ShedError",
]
